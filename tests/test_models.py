"""Model tests: shapes, forward modes, freeze_feature stop-gradient,
parameter-count parity with torchvision topology."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from active_learning_tpu.models.factory import get_network
from active_learning_tpu.models.resnet import resnet18, resnet50


def init_model(model, shape):
    x = jnp.zeros(shape)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    return variables


def test_resnet18_cifar_shapes():
    model = resnet18(num_classes=10, cifar_stem=True)
    variables = init_model(model, (2, 32, 32, 3))
    x = jnp.ones((2, 32, 32, 3))
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 10)
    logits, emb = model.apply(variables, x, train=False, return_features=True)
    assert emb.shape == (2, 512)
    head_logits = model.apply(variables, emb, method="head")
    np.testing.assert_allclose(np.asarray(head_logits), np.asarray(logits),
                               rtol=1e-5, atol=1e-5)


def test_resnet50_embedding_dim():
    model = resnet50(num_classes=10, cifar_stem=True)
    variables = init_model(model, (1, 16, 16, 3))
    _, emb = model.apply(variables, jnp.ones((1, 16, 16, 3)), train=False,
                         return_features=True)
    assert emb.shape == (1, 2048)
    assert model.embed_dim == 2048


def test_imagenet_stem_downsamples():
    # Fully-convolutional + global pool: a small input exercises the same
    # 7x7/s2 + maxpool stem path as 224x224 without the CPU compile cost.
    model = resnet18(num_classes=1000, cifar_stem=False)
    variables = init_model(model, (1, 64, 64, 3))
    logits = model.apply(variables, jnp.ones((1, 64, 64, 3)), train=False)
    assert logits.shape == (1, 1000)


def test_param_count_matches_torchvision():
    # torchvision resnet18 (1000 classes) has 11,689,512 params; ours splits
    # fc into a separate head but the total must match.
    model = resnet18(num_classes=1000, cifar_stem=False)
    variables = init_model(model, (1, 64, 64, 3))
    n = sum(np.prod(p.shape) for p in jax.tree.leaves(variables["params"]))
    assert n == 11_689_512
    # resnet50: 25,557,032.
    model50 = resnet50(num_classes=1000, cifar_stem=False)
    variables50 = init_model(model50, (1, 32, 32, 3))
    n50 = sum(np.prod(p.shape) for p in jax.tree.leaves(variables50["params"]))
    assert n50 == 25_557_032


def test_freeze_feature_stops_gradient():
    model = resnet18(num_classes=10, cifar_stem=True, freeze_feature=True)
    variables = init_model(model, (2, 8, 8, 3))
    x = jnp.ones((2, 8, 8, 3))

    def loss_fn(params):
        logits = model.apply(
            {"params": params, "batch_stats": variables["batch_stats"]},
            x, train=False)
        return logits.sum()

    grads = jax.grad(loss_fn)(variables["params"])
    # Head gets gradient; encoder gets exactly zero.
    head_norm = np.abs(np.asarray(grads["linear"]["kernel"])).sum()
    enc_norm = sum(
        np.abs(np.asarray(g)).sum()
        for g in jax.tree.leaves(grads["encoder"]))
    assert head_norm > 0
    assert enc_norm == 0


def test_train_mode_updates_batch_stats():
    model = resnet18(num_classes=10, cifar_stem=True)
    variables = init_model(model, (4, 8, 8, 3))
    x = jnp.linspace(0, 1, 4 * 8 * 8 * 3).reshape(4, 8, 8, 3)
    _, updates = model.apply(variables, x, train=True,
                             mutable=["batch_stats"])
    before = variables["batch_stats"]["encoder"]["bn_stem"]["mean"]
    after = updates["batch_stats"]["encoder"]["bn_stem"]["mean"]
    assert not np.allclose(np.asarray(before), np.asarray(after))


def test_factory_cifar_stem_rule():
    m = get_network("cifar10", "SSLResNet18")
    assert m.cifar_stem
    m = get_network("imagenet", "SSLResNet18")
    assert not m.cifar_stem
    with pytest.raises(KeyError):
        get_network("nope", "SSLResNet18")
    with pytest.raises(KeyError):
        get_network("cifar10", "NoSuchModel")


class TestDtypeResolution:
    """The production precision path (VERDICT r3 #2): configs name a dtype,
    the factory resolves it against the live backend, and bf16 models keep
    params/BN/embeddings float32 (models/resnet.py docstring)."""

    def test_resolve_names_and_auto(self):
        from active_learning_tpu.models.factory import resolve_dtype
        assert resolve_dtype("bfloat16") == jnp.bfloat16
        assert resolve_dtype("bf16") == jnp.bfloat16
        assert resolve_dtype("float32") == jnp.float32
        assert resolve_dtype(jnp.bfloat16) == jnp.bfloat16
        # The test backend is CPU (conftest), so auto must land on f32.
        assert resolve_dtype("auto") == jnp.float32
        assert resolve_dtype(None) == jnp.float32
        with pytest.raises(ValueError):
            resolve_dtype("float16")

    def test_factory_threads_dtype(self):
        m = get_network("cifar10", "SSLResNet18", dtype="bfloat16")
        assert m.dtype == jnp.bfloat16
        assert get_network("cifar10", "SSLResNet18").dtype == jnp.float32

    def test_cli_dtype_reaches_the_model(self, tmp_path):
        """--dtype must govern the model the driver actually builds."""
        from active_learning_tpu.experiment import cli
        from active_learning_tpu.experiment.driver import build_experiment

        ns = cli.get_parser().parse_args(
            ["--dataset", "synthetic", "--arg_pool", "synthetic",
             "--debug_mode", "--dtype", "bfloat16",
             "--ckpt_path", str(tmp_path), "--log_dir", str(tmp_path)])
        cfg = cli.args_to_config(ns)
        assert cfg.dtype == "bfloat16"
        strategy = build_experiment(cfg)
        assert strategy.model.dtype == jnp.bfloat16

    def test_bf16_model_keeps_params_and_outputs_f32(self):
        """bf16 selects compute precision only: params stay f32 and the
        embedding/logits surface stays f32 for acquisition math."""
        model = resnet18(num_classes=10, cifar_stem=True,
                         dtype=jnp.bfloat16)
        variables = init_model(model, (2, 8, 8, 3))
        for leaf in jax.tree.leaves(variables["params"]):
            assert leaf.dtype == jnp.float32
        for leaf in jax.tree.leaves(variables["batch_stats"]):
            assert leaf.dtype == jnp.float32
        logits, emb = model.apply(variables, jnp.ones((2, 8, 8, 3)),
                                  train=False, return_features=True)
        assert logits.dtype == jnp.float32
        assert emb.dtype == jnp.float32
