"""Test configuration: run JAX on a virtual 8-device CPU mesh.

This is the TPU answer to "test distributed code without a cluster"
(SURVEY.md §4): XLA fakes 8 host devices, so every sharding/collective code
path compiles and executes exactly as it would on an 8-chip slice.
Must run before anything imports jax.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# A sitecustomize hook may have imported jax and pinned a hardware platform
# before this file ran (making the env vars above too late); the config
# update wins as long as no backend has been initialized yet.
jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: repeated test runs skip XLA recompiles.
_cache_dir = os.path.join(os.path.dirname(__file__), ".jax_cache")
jax.config.update("jax_compilation_cache_dir", _cache_dir)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)
