"""Test configuration: run JAX on a virtual 8-device CPU mesh.

This is the TPU answer to "test distributed code without a cluster"
(SURVEY.md §4): XLA fakes 8 host devices, so every sharding/collective code
path compiles and executes exactly as it would on an 8-chip slice.
Must run before anything imports jax.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# A sitecustomize hook may have imported jax and pinned a hardware platform
# before this file ran (making the env vars above too late); the config
# update wins as long as no backend has been initialized yet.
jax.config.update("jax_platforms", "cpu")

# NO persistent compilation cache in tests.  jax 0.4.37's CPU backend
# corrupts donated buffers when an executable is DESERIALIZED from the
# persistent cache (minimal repro: a donate_argnums jit over a replicated
# sharding, compiled once then re-jitted in the same process, dies with
# `free(): corrupted unsorted chunks` — or silently trains on garbage).
# This was the root cause of the "flaky" mid-round-resume failures: the
# resumed fit's freshly-jitted train step got a cache hit and its donated
# state buffers were reused while still referenced.  The production
# driver gates the cache off on CPU for the same reason
# (experiment/driver.enable_compilation_cache).

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)
