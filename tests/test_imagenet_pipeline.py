"""Disk-dataset decode path + threaded pipeline tests.

Covers the properties the reference's DataLoader stack gets from torch and
we must guarantee ourselves: N-worker gather with ORDERED reassembly, and
crop randomness that is a pure function of (seed, epoch, index) — identical
whatever the gather order or thread interleaving.
"""

import os

import numpy as np
import pytest

from active_learning_tpu.data.core import IMAGENET_NORM, ViewSpec
from active_learning_tpu.data.imagenet import ImageFolderDataset
from active_learning_tpu.data.pipeline import iterate_batches
from active_learning_tpu.data.synthetic import get_data_synthetic


@pytest.fixture(scope="module")
def jpeg_tree(tmp_path_factory):
    pytest.importorskip("PIL.Image")
    from helpers import build_jpeg_tree
    return build_jpeg_tree(str(tmp_path_factory.mktemp("imgs") / "tree"))


def make_ds(jpeg_tree, train=True, seed=0):
    view = ViewSpec(IMAGENET_NORM, augment=train, pad=0)
    return ImageFolderDataset(jpeg_tree, view, train, num_classes=3,
                              seed=seed)


class TestDecodeRNG:
    def test_crops_pure_function_of_seed_epoch_index(self, jpeg_tree):
        ds = make_ds(jpeg_tree)
        a = ds.gather(np.asarray([3, 7, 11]))
        # Different order, interleaved with other decodes: same result.
        ds.gather(np.asarray([0, 1, 2]))
        b = ds.gather(np.asarray([11, 7, 3]))
        np.testing.assert_array_equal(a, b[::-1])

    def test_epoch_advances_crops(self, jpeg_tree):
        ds = make_ds(jpeg_tree)
        a = ds.gather(np.asarray([3]))
        ds.set_epoch(1)
        b = ds.gather(np.asarray([3]))
        assert not np.array_equal(a, b)

    def test_val_transform_deterministic(self, jpeg_tree):
        ds = make_ds(jpeg_tree, train=False)
        a = ds.gather(np.asarray([5]))
        ds.set_epoch(3)
        b = ds.gather(np.asarray([5]))
        np.testing.assert_array_equal(a, b)
        assert a.shape == (1, 224, 224, 3)


class TestEvalDecodeCache:
    def test_cached_rows_exact_and_decode_once(self, jpeg_tree):
        from active_learning_tpu.data.cache import CachedEvalRows
        ds = make_ds(jpeg_tree, train=False)
        calls = {"n": 0}
        orig = ds.gather

        def counting(idxs):
            calls["n"] += len(idxs)
            return orig(idxs)

        ds.gather = counting
        cache = CachedEvalRows(ds)
        idxs = np.asarray([5, 2, 9, 2])
        a = cache.gather(idxs)
        np.testing.assert_array_equal(a, orig(idxs))
        assert calls["n"] == 3  # unique rows only
        b = cache.gather(idxs)
        np.testing.assert_array_equal(a, b)
        assert calls["n"] == 3  # second pass: zero decodes

    def test_empty_gather_preserves_shape_contract(self, jpeg_tree):
        """A multi-host last batch can leave a process zero real rows; the
        cache must pass the empty gather through, not np.stack([])."""
        from active_learning_tpu.data.cache import CachedEvalRows
        ds = make_ds(jpeg_tree, train=False)
        cache = CachedEvalRows(ds)
        empty = cache.gather(np.zeros(0, dtype=np.int64))
        assert empty.shape == ds.gather(np.zeros(0, dtype=np.int64)).shape
        assert empty.shape[0] == 0

    def test_concurrent_gathers_consistent_and_within_budget(self,
                                                             jpeg_tree):
        """The eval pipeline gathers from num_workers threads; hammering
        the cache concurrently must stay exact and never admit past the
        byte budget."""
        from concurrent.futures import ThreadPoolExecutor

        from active_learning_tpu.data.cache import CachedEvalRows
        ds = make_ds(jpeg_tree, train=False)
        want = ds.gather(np.arange(18))
        row_bytes = want[0].nbytes
        cache = CachedEvalRows(ds, max_bytes=10 * row_bytes)
        batches = [np.asarray(b) for b in
                   (range(0, 6), range(6, 12), range(12, 18),
                    range(3, 9), range(9, 15), range(0, 18))] * 4
        with ThreadPoolExecutor(max_workers=6) as ex:
            results = list(ex.map(cache.gather, batches))
        for idxs, got in zip(batches, results):
            np.testing.assert_array_equal(got, want[idxs])
        assert cache._bytes <= 10 * row_bytes
        assert len(cache._rows) <= 10

    def test_budget_overflow_falls_through_exactly(self, jpeg_tree):
        from active_learning_tpu.data.cache import CachedEvalRows
        ds = make_ds(jpeg_tree, train=False)
        cache = CachedEvalRows(ds, max_bytes=1)
        idxs = np.asarray([1, 4])
        a = cache.gather(idxs)
        b = cache.gather(idxs)
        np.testing.assert_array_equal(a, ds.gather(idxs))
        np.testing.assert_array_equal(a, b)

    def test_fit_decodes_eval_rows_once_per_round(self, jpeg_tree):
        """Through Trainer.fit: a 3-epoch fit over a disk dataset decodes
        each eval row ONCE, not once per epoch (and the padding row reuse
        comes along for free)."""
        import jax

        from active_learning_tpu.parallel import mesh as mesh_lib
        from active_learning_tpu.train.trainer import Trainer
        from helpers import TinyClassifier, tiny_train_config

        train_ds = make_ds(jpeg_tree, train=True)
        al_ds = make_ds(jpeg_tree, train=False)
        calls = {"n": 0}
        orig = al_ds.gather

        def counting(idxs):
            calls["n"] += len(idxs)
            return orig(idxs)

        al_ds.gather = counting
        trainer = Trainer(TinyClassifier(num_classes=3),
                          tiny_train_config(batch_size=8),
                          mesh_lib.make_mesh(), num_classes=3)
        state = trainer.init_state(jax.random.PRNGKey(0),
                                   train_ds.gather(np.arange(2)))
        trainer.fit(state, train_ds, np.arange(12), al_ds,
                    np.arange(12, 18), n_epoch=3, es_patience=5,
                    rng=np.random.default_rng(0))
        assert calls["n"] == 6, calls["n"]  # 6 eval rows, 3 epochs


class TestThreadedPipeline:
    def test_threaded_matches_sync_in_order(self, jpeg_tree):
        ds = make_ds(jpeg_tree)
        idxs = np.arange(len(ds))
        sync = list(iterate_batches(ds, idxs, 4, num_threads=0))
        threaded = list(iterate_batches(ds, idxs, 4, num_threads=4,
                                        prefetch=2))
        assert len(sync) == len(threaded)
        for s, t in zip(sync, threaded):
            for k in s:
                np.testing.assert_array_equal(s[k], t[k])

    def test_threaded_matches_sync_in_memory_dataset(self):
        train_set, _, _ = get_data_synthetic(n_train=50, n_test=8)
        idxs = np.arange(50)
        sync = list(iterate_batches(train_set, idxs, 8, num_threads=0))
        threaded = list(iterate_batches(train_set, idxs, 8, num_threads=3))
        for s, t in zip(sync, threaded):
            np.testing.assert_array_equal(s["image"], t["image"])
            np.testing.assert_array_equal(s["index"], t["index"])

    def test_error_propagates_from_worker(self):
        class Boom:
            targets = np.zeros(10, dtype=np.int64)

            def gather(self, idxs):
                raise RuntimeError("decode failed")

        with pytest.raises(RuntimeError, match="decode failed"):
            list(iterate_batches(Boom(), np.arange(10), 4, num_threads=2))

    def test_early_close_does_not_hang(self, jpeg_tree):
        ds = make_ds(jpeg_tree)
        gen = iterate_batches(ds, np.arange(len(ds)), 2, num_threads=2)
        next(gen)
        gen.close()  # must not deadlock or leak


class TestNativeDecode:
    def test_identity_decode_matches_pil_exactly(self, tmp_path):
        """Whole-image rect + same-size output is a pure decode: must match
        PIL pixel-for-pixel (both are IJG-compatible JPEG decoders)."""
        PIL = pytest.importorskip("PIL.Image")
        from active_learning_tpu.data import native
        if native.load() is None:
            pytest.skip("native decode unavailable")
        rng = np.random.default_rng(1)
        # Smooth image: JPEG is lossy, but decode-vs-decode is exact.
        base = np.linspace(0, 255, 48 * 48 * 3).reshape(48, 48, 3)
        arr = (base + rng.normal(0, 4, base.shape)).clip(0, 255).astype(
            np.uint8)
        p = tmp_path / "a.jpg"
        PIL.fromarray(arr).save(p, quality=90)

        dims = native.jpeg_dims([str(p)])
        np.testing.assert_array_equal(dims, [[48, 48]])
        out, failed = native.decode_crop_resize(
            [str(p)], np.asarray([[0, 0, 48, 48]], dtype=np.int32), 48)
        assert not failed.any()
        pil = np.asarray(PIL.open(p).convert("RGB"))
        np.testing.assert_array_equal(out[0], pil)

    def test_dataset_native_and_pil_paths_agree(self, jpeg_tree):
        """Same crop rects (RNG lives in Python), near-identical pixels —
        only the resize filter differs between the two paths."""
        from active_learning_tpu.data import native
        if native.load() is None:
            pytest.skip("native decode unavailable")
        nat = make_ds(jpeg_tree, train=True, seed=3)
        pil = make_ds(jpeg_tree, train=True, seed=3)
        pil._use_native = False
        assert nat._use_native
        idxs = np.asarray([0, 5, 9])
        a = nat.gather(idxs)
        b = pil.gather(idxs)
        assert a.shape == b.shape == (3, 224, 224, 3)
        # Same crop windows: the images should be nearly identical, not
        # merely correlated.
        diff = np.abs(a.astype(np.int32) - b.astype(np.int32)).mean()
        assert diff < 12.0, f"native/PIL paths diverged: mean abs {diff}"

    def test_val_transform_native_matches_shape_and_determinism(
            self, jpeg_tree):
        from active_learning_tpu.data import native
        if native.load() is None:
            pytest.skip("native decode unavailable")
        ds = make_ds(jpeg_tree, train=False)
        a = ds.gather(np.asarray([2]))
        b = ds.gather(np.asarray([2]))
        np.testing.assert_array_equal(a, b)
        assert a.shape == (1, 224, 224, 3)

    def test_non_jpeg_falls_back_to_pil(self, tmp_path):
        PIL = pytest.importorskip("PIL.Image")
        root = tmp_path / "pngs" / "class0"
        os.makedirs(root)
        arr = np.zeros((40, 40, 3), dtype=np.uint8)
        PIL.fromarray(arr).save(root / "img.png")
        ds = ImageFolderDataset(str(tmp_path / "pngs"),
                                ViewSpec(IMAGENET_NORM, augment=False),
                                False, num_classes=1)
        out = ds.gather(np.asarray([0]))
        assert out.shape == (1, 224, 224, 3)

    def test_cmyk_jpeg_falls_back_per_file_without_disabling_native(
            self, tmp_path):
        """Real ImageNet contains a handful of CMYK JPEGs libjpeg can't
        emit as RGB; they must fall back to PIL individually while the
        rest of the batch stays on the native path."""
        PIL = pytest.importorskip("PIL.Image")
        from active_learning_tpu.data import native
        if native.load() is None:
            pytest.skip("native decode unavailable")
        root = tmp_path / "mixed" / "class0"
        os.makedirs(root)
        rng = np.random.default_rng(0)
        for i in range(3):
            arr = rng.integers(0, 256, size=(60, 60, 3), dtype=np.uint8)
            PIL.fromarray(arr).save(root / f"a{i}.jpg")
        PIL.fromarray(
            rng.integers(0, 256, size=(60, 60, 4), dtype=np.uint8),
            mode="CMYK").save(root / "cmyk.jpg")
        ds = ImageFolderDataset(str(tmp_path / "mixed"),
                                ViewSpec(IMAGENET_NORM, augment=False),
                                False, num_classes=1)
        out = ds.gather(np.arange(4))
        assert out.shape == (4, 224, 224, 3)
        assert ds._use_native  # one odd file must not kill the fast path
        # The CMYK slot decoded through PIL is not all zeros.
        assert all(out[i].any() for i in range(4))


class TestDecodedPoolCache:
    """Experiment-lifetime memmap decode cache (data/cache.DecodedPoolCache):
    exact rows, decode-once-ever semantics, persistence across instances,
    torn-write safety, and the eligibility gates of maybe_wrap_decoded."""

    def test_rows_exact_and_decoded_once_across_instances(self, jpeg_tree,
                                                          tmp_path):
        from active_learning_tpu.data.cache import (DecodedPoolCache,
                                                    maybe_wrap_decoded)
        ds = make_ds(jpeg_tree, train=False)
        want = ds.gather(np.arange(len(ds)))

        calls = []
        real_gather = ds.gather

        def counting(idxs):
            calls.append(np.asarray(idxs))
            return real_gather(idxs)

        ds.gather = counting
        cached = maybe_wrap_decoded(ds, str(tmp_path), 1 << 30)
        assert isinstance(cached, DecodedPoolCache)
        out1 = cached.gather(np.asarray([3, 1, 3]))
        np.testing.assert_array_equal(out1, want[[3, 1, 3]])
        out2 = cached.gather(np.arange(len(ds)))
        np.testing.assert_array_equal(out2, want)
        decoded = np.concatenate(calls)
        assert len(decoded) == len(np.unique(decoded)) == len(ds)

        # A second instance over the same tree (fresh process in real
        # life) must reuse the file: zero further decodes.
        calls.clear()
        cached2 = maybe_wrap_decoded(ds, str(tmp_path), 1 << 30)
        np.testing.assert_array_equal(cached2.gather(np.arange(len(ds))),
                                      want)
        assert calls == []

    def test_full_cache_promotes_to_device_residency(self, jpeg_tree,
                                                     tmp_path):
        """A fully-populated cache exposes the memmap as ``.images`` and
        thereby qualifies for the device-resident scoring path
        (parallel/resident.py:eligible) — rounds 1+ of a disk-pool
        experiment score via on-device gathers when the HBM budget
        covers the pool.  While partial it must NOT qualify: a
        half-empty memmap uploaded as real data would score zeros."""
        import jax

        from active_learning_tpu.data.cache import DecodedPoolCache
        from active_learning_tpu.parallel import mesh as mesh_lib
        from active_learning_tpu.parallel import resident as resident_lib
        from active_learning_tpu.strategies import scoring as scoring_lib

        ds = make_ds(jpeg_tree, train=False)
        cached = DecodedPoolCache(ds, str(tmp_path))
        budget = 1 << 30

        # Partial: one row decoded — no .images, not eligible.
        cached.gather(np.asarray([0]))
        assert getattr(cached, "images", None) is None
        assert not resident_lib.eligible(cached, budget)

        # Fully populated: promoted, and the resident scoring pass over
        # the cache matches the host-batched pass bit for bit.
        cached.gather(np.arange(len(cached)))
        assert isinstance(cached.images, np.ndarray)
        assert resident_lib.eligible(cached, budget)
        assert not resident_lib.eligible(cached, cached.images.nbytes - 1)

        from flax import linen as nn

        class Probe(nn.Module):
            @nn.compact
            def __call__(self, x, train=False):
                return nn.Dense(4)(x.reshape(x.shape[0], -1)
                                   .astype(np.float32))

        mesh = mesh_lib.make_mesh(1)
        model = Probe()
        variables = model.init(jax.random.PRNGKey(0),
                               cached.gather(np.arange(2)))
        step = scoring_lib.make_prob_stats_step(model, cached.view)
        idxs = np.arange(len(cached), dtype=np.int64)
        host = scoring_lib.collect_pool(cached, idxs, 8, step, variables,
                                        mesh)
        res = scoring_lib.collect_pool(cached, idxs, 8, step, variables,
                                       mesh, resident_cache={})
        for k in host:
            np.testing.assert_allclose(res[k], host[k], rtol=1e-6,
                                       atol=1e-6, err_msg=k)

    def test_torn_write_not_served(self, jpeg_tree, tmp_path):
        """A row whose bytes landed but whose valid flag did not (crash
        between the two) must be re-decoded, and vice versa a zeroed row
        with no flag never surfaces."""
        from active_learning_tpu.data.cache import DecodedPoolCache
        ds = make_ds(jpeg_tree, train=False)
        cached = DecodedPoolCache(ds, str(tmp_path))
        want = ds.gather(np.asarray([0]))[0]
        cached.gather(np.asarray([0]))
        # Simulate the torn state: flag cleared after a "crash".
        cached._valid[0] = 0
        cached._rows[0] = 0
        np.testing.assert_array_equal(cached.gather(np.asarray([0]))[0],
                                      want)

    def test_eligibility_gates(self, jpeg_tree, tmp_path):
        from active_learning_tpu.data.cache import maybe_wrap_decoded
        val_ds = make_ds(jpeg_tree, train=False)
        # Train views (non-deterministic crops) must never be wrapped.
        train_ds = make_ds(jpeg_tree, train=True)
        assert maybe_wrap_decoded(train_ds, str(tmp_path), 1 << 30) \
            is train_ds
        # A pool larger than the budget stays unwrapped (partial caches
        # thrash; the scoring pass touches every row).
        assert maybe_wrap_decoded(val_ds, str(tmp_path), 10) is val_ds
        # In-memory datasets have no paths: unwrapped.
        arr_ds = get_data_synthetic(n_train=8, n_test=4)[2]
        assert maybe_wrap_decoded(arr_ds, str(tmp_path), 1 << 30) is arr_ds
        # Disabled dir/budget: unwrapped.
        assert maybe_wrap_decoded(val_ds, None, 1 << 30) is val_ds
        assert maybe_wrap_decoded(val_ds, str(tmp_path), 0) is val_ds

    def test_driver_wraps_disk_pool_and_scoring_uses_it(self, jpeg_tree,
                                                        tmp_path):
        """build_experiment must hand the strategy a cache-wrapped al/test
        set for disk datasets, and the sampler's scoring pass must flow
        through it (attribute passthrough intact)."""
        import dataclasses

        from active_learning_tpu.config import ExperimentConfig
        from active_learning_tpu.data.cache import DecodedPoolCache
        from active_learning_tpu.experiment.driver import build_experiment
        from helpers import tiny_train_config

        train_ds = make_ds(jpeg_tree, train=True)
        al_ds = make_ds(jpeg_tree, train=False)
        test_ds = make_ds(jpeg_tree, train=False)
        train_cfg = dataclasses.replace(
            tiny_train_config(), decoded_cache_dir=str(tmp_path / "cache"))
        cfg = ExperimentConfig(
            dataset="imagenet", strategy="MarginSampler", rounds=1,
            round_budget=4, init_pool_size=4, n_epoch=1, exp_hash="t",
            enable_metrics=False,
            log_dir=str(tmp_path / "logs"), ckpt_path=str(tmp_path / "ck"))
        strategy = build_experiment(cfg, data=(train_ds, test_ds, al_ds),
                                    train_cfg=train_cfg)
        strategy.init_network_weights()
        assert isinstance(strategy.al_set, DecodedPoolCache)
        assert isinstance(strategy.test_set, DecodedPoolCache)
        assert strategy.train_set is train_ds  # train view never cached
        assert strategy.al_set.num_classes == al_ds.num_classes
        got, cost = strategy.query(4)
        assert cost == 4 and len(got) == 4
        # The query populated the cache for exactly the scored rows.
        assert int(np.count_nonzero(strategy.al_set._valid)) > 0

    def test_stale_cache_eviction(self, jpeg_tree, tmp_path):
        """Old cache triples must be LRU-evicted when a new cache would
        push the directory past its byte budget; in-use and same-
        signature files survive."""
        import time as time_mod

        from active_learning_tpu.data.cache import (DecodedPoolCache,
                                                    maybe_wrap_decoded)
        ds = make_ds(jpeg_tree, train=False)
        full = len(ds) * int(np.prod(ds.image_shape))
        # Plant a fake stale triple, old mtime, bigger than the slack.
        stale = tmp_path / "decoded_deadbeef00000000_p0"
        for ext in (".u8", ".valid", ".json"):
            with open(str(stale) + ext, "wb") as fh:
                fh.write(b"x" * 4096)
        old = time_mod.time() - 1e6
        for ext in (".u8", ".valid", ".json"):
            os.utime(str(stale) + ext, (old, old))
        DecodedPoolCache._IN_USE.clear()
        cached = maybe_wrap_decoded(ds, str(tmp_path), full + 2048)
        assert isinstance(cached, DecodedPoolCache)
        assert not os.path.exists(str(stale) + ".u8")
        # A second wrap (same signature, now in use) evicts nothing.
        assert os.path.exists(cached._data_path)
