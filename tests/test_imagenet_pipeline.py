"""Disk-dataset decode path + threaded pipeline tests.

Covers the properties the reference's DataLoader stack gets from torch and
we must guarantee ourselves: N-worker gather with ORDERED reassembly, and
crop randomness that is a pure function of (seed, epoch, index) — identical
whatever the gather order or thread interleaving.
"""

import os

import numpy as np
import pytest

from active_learning_tpu.data.core import IMAGENET_NORM, ViewSpec
from active_learning_tpu.data.imagenet import ImageFolderDataset
from active_learning_tpu.data.pipeline import iterate_batches
from active_learning_tpu.data.synthetic import get_data_synthetic


@pytest.fixture(scope="module")
def jpeg_tree(tmp_path_factory):
    PIL = pytest.importorskip("PIL.Image")
    root = tmp_path_factory.mktemp("imgs")
    rng = np.random.default_rng(0)
    for c in range(3):
        cdir = root / f"class{c}"
        os.makedirs(cdir)
        for i in range(6):
            hw = int(rng.integers(40, 80))
            arr = rng.integers(0, 256, size=(hw, hw + 10, 3), dtype=np.uint8)
            PIL.fromarray(arr).save(cdir / f"img{i}.jpg")
    return str(root)


def make_ds(jpeg_tree, train=True, seed=0):
    view = ViewSpec(IMAGENET_NORM, augment=train, pad=0)
    return ImageFolderDataset(jpeg_tree, view, train, num_classes=3,
                              seed=seed)


class TestDecodeRNG:
    def test_crops_pure_function_of_seed_epoch_index(self, jpeg_tree):
        ds = make_ds(jpeg_tree)
        a = ds.gather(np.asarray([3, 7, 11]))
        # Different order, interleaved with other decodes: same result.
        ds.gather(np.asarray([0, 1, 2]))
        b = ds.gather(np.asarray([11, 7, 3]))
        np.testing.assert_array_equal(a, b[::-1])

    def test_epoch_advances_crops(self, jpeg_tree):
        ds = make_ds(jpeg_tree)
        a = ds.gather(np.asarray([3]))
        ds.set_epoch(1)
        b = ds.gather(np.asarray([3]))
        assert not np.array_equal(a, b)

    def test_val_transform_deterministic(self, jpeg_tree):
        ds = make_ds(jpeg_tree, train=False)
        a = ds.gather(np.asarray([5]))
        ds.set_epoch(3)
        b = ds.gather(np.asarray([5]))
        np.testing.assert_array_equal(a, b)
        assert a.shape == (1, 224, 224, 3)


class TestThreadedPipeline:
    def test_threaded_matches_sync_in_order(self, jpeg_tree):
        ds = make_ds(jpeg_tree)
        idxs = np.arange(len(ds))
        sync = list(iterate_batches(ds, idxs, 4, num_threads=0))
        threaded = list(iterate_batches(ds, idxs, 4, num_threads=4,
                                        prefetch=2))
        assert len(sync) == len(threaded)
        for s, t in zip(sync, threaded):
            for k in s:
                np.testing.assert_array_equal(s[k], t[k])

    def test_threaded_matches_sync_in_memory_dataset(self):
        train_set, _, _ = get_data_synthetic(n_train=50, n_test=8)
        idxs = np.arange(50)
        sync = list(iterate_batches(train_set, idxs, 8, num_threads=0))
        threaded = list(iterate_batches(train_set, idxs, 8, num_threads=3))
        for s, t in zip(sync, threaded):
            np.testing.assert_array_equal(s["image"], t["image"])
            np.testing.assert_array_equal(s["index"], t["index"])

    def test_error_propagates_from_worker(self):
        class Boom:
            targets = np.zeros(10, dtype=np.int64)

            def gather(self, idxs):
                raise RuntimeError("decode failed")

        with pytest.raises(RuntimeError, match="decode failed"):
            list(iterate_batches(Boom(), np.arange(10), 4, num_threads=2))

    def test_early_close_does_not_hang(self, jpeg_tree):
        ds = make_ds(jpeg_tree)
        gen = iterate_batches(ds, np.arange(len(ds)), 2, num_threads=2)
        next(gen)
        gen.close()  # must not deadlock or leak
