"""Serving-subsystem tests (active_learning_tpu/serve/), tier-1.

Everything runs over loopback on the virtual 8-device CPU mesh — real
HTTP, real microbatching, the real executor thread — so the whole
online path executes exactly as it would in front of a chip.  Pinned
contracts:

  * batcher flush ordering — full-batch flushes immediately, a partial
    batch flushes at the deadline, an overflowing entry carries whole;
  * bucket-padding isolation — padded rows (whatever their content)
    never change a real row's output, checked against an unbatched
    forward;
  * served == offline — /v1/predict and /v1/score reproduce the offline
    scoring path bit-for-bit at the same batch shape;
  * zero request-path compiles after warmup (the test_compile_reuse
    counter);
  * 429 + Retry-After under queue overflow; 503/closed during drain;
  * graceful drain — in-flight requests complete, SIGTERM exits 0
    (subprocess test through the CLI's signal path).
"""

from __future__ import annotations

import asyncio
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax

from active_learning_tpu.config import ServeConfig
from active_learning_tpu.data.synthetic import get_data_synthetic
from active_learning_tpu.parallel import mesh as mesh_lib
from active_learning_tpu.serve.batcher import (BatcherClosedError,
                                               MicroBatcher,
                                               QueueFullError,
                                               serve_buckets)
from active_learning_tpu.serve.executor import DeviceExecutor
from active_learning_tpu.serve.server import ScoringServer
from active_learning_tpu.train import checkpoint as ckpt_lib

from helpers import TinyClassifier, tiny_train_config

IMG = (8, 8, 3)


# ---------------------------------------------------------------------------
# Bucket ladder
# ---------------------------------------------------------------------------

class TestServeBuckets:
    def test_ladder_covers_and_orders(self):
        b = serve_buckets(64, floor=8)
        assert b == sorted(set(b)) and b[0] == 8 and b[-1] >= 64
        for n in range(1, 65):
            assert any(x >= n for x in b)

    def test_mesh_divisibility(self):
        for nd in (1, 3, 8):
            for b in serve_buckets(64, floor=8, n_devices=nd):
                assert b % nd == 0

    def test_single_bucket_config(self):
        assert serve_buckets(8, floor=8) == [8]


# ---------------------------------------------------------------------------
# Microbatcher (pure asyncio; no device work)
# ---------------------------------------------------------------------------

def _rows(n, start=0):
    """n distinguishable uint8 rows: row i is constant-valued start+i."""
    out = np.zeros((n, *IMG), dtype=np.uint8)
    for i in range(n):
        out[i] = (start + i) % 256
    return out


class _EchoDispatch:
    """Records every flushed batch; resolves each entry with its own
    rows' first-pixel values so tests can check slicing/ordering."""

    def __init__(self, auto_resolve=True):
        self.batches = []
        self.auto_resolve = auto_resolve
        self.pending = []

    def __call__(self, host_batch, entries, want_embed):
        self.batches.append({
            "t": time.monotonic(),
            "bucket": host_batch["image"].shape[0],
            "rows": int(host_batch["mask"].sum()),
            "mask": host_batch["mask"].copy(),
        })
        if self.auto_resolve:
            self.resolve(host_batch, entries)
        else:
            self.pending.append((host_batch, entries))

    def resolve(self, host_batch, entries):
        vals = host_batch["image"][:, 0, 0, 0].astype(np.int64)
        for e in entries:
            e.future.set_result(
                {"val": vals[e.offset:e.offset + e.n], "round": 0})

    def resolve_all(self):
        for host_batch, entries in self.pending:
            self.resolve(host_batch, entries)
        self.pending.clear()


def _make_batcher(dispatch, **kw):
    kw.setdefault("max_batch", 8)
    kw.setdefault("max_latency_ms", 50.0)
    kw.setdefault("queue_depth", 64)
    kw.setdefault("bucket_floor", 4)
    b = MicroBatcher(dispatch, **kw)
    b.start()
    return b


class TestMicroBatcher:
    def test_full_batch_flushes_before_deadline(self):
        async def run():
            d = _EchoDispatch()
            b = _make_batcher(d, max_latency_ms=10_000.0)
            t0 = time.monotonic()
            r1, r2 = await asyncio.gather(b.submit(_rows(4)),
                                          b.submit(_rows(4, 100)))
            elapsed = time.monotonic() - t0
            # One coalesced full batch, dispatched WITHOUT waiting for
            # the 10-second deadline.
            assert len(d.batches) == 1
            assert d.batches[0]["rows"] == 8
            assert elapsed < 5.0
            # Ordering: each request got ITS rows, in submit order.
            assert r1["val"].tolist() == [0, 1, 2, 3]
            assert r2["val"].tolist() == [100, 101, 102, 103]
            return True

        assert asyncio.run(run())

    def test_deadline_flushes_partial_batch(self):
        async def run():
            d = _EchoDispatch()
            b = _make_batcher(d, max_latency_ms=60.0)
            t0 = time.monotonic()
            r = await b.submit(_rows(3))
            waited = time.monotonic() - t0
            assert len(d.batches) == 1
            assert d.batches[0]["rows"] == 3
            assert d.batches[0]["bucket"] == 4  # floor bucket, padded
            # Flushed BY the deadline, not before it (scheduling slack
            # allowed upward, never a full-batch-early flush).
            assert waited >= 0.05
            assert r["val"].tolist() == [0, 1, 2]
            return True

        assert asyncio.run(run())

    def test_overflowing_entry_carries_whole(self):
        async def run():
            d = _EchoDispatch()
            b = _make_batcher(d, max_latency_ms=40.0)
            r1, r2 = await asyncio.gather(b.submit(_rows(5)),
                                          b.submit(_rows(5, 50)))
            # 5 + 5 > max_batch=8: the second entry must carry into its
            # own batch — entries are never split across batches.
            assert [x["rows"] for x in d.batches] == [5, 5]
            assert r1["val"].tolist() == [0, 1, 2, 3, 4]
            assert r2["val"].tolist() == [50, 51, 52, 53, 54]
            return True

        assert asyncio.run(run())

    def test_oversized_request_chunks_and_reassembles(self):
        async def run():
            d = _EchoDispatch()
            b = _make_batcher(d, max_latency_ms=20.0)
            r = await b.submit(_rows(19))  # > 2x max_batch
            assert r["val"].tolist() == list(range(19))
            assert sum(x["rows"] for x in d.batches) == 19
            return True

        assert asyncio.run(run())

    def test_queue_full_raises_429_material(self):
        async def run():
            d = _EchoDispatch(auto_resolve=False)  # rows stay pending
            b = _make_batcher(d, queue_depth=8, max_latency_ms=5.0)
            t1 = asyncio.ensure_future(b.submit(_rows(8)))
            await asyncio.sleep(0.05)  # admitted + dispatched, unresolved
            with pytest.raises(QueueFullError):
                await b.submit(_rows(1))
            d.resolve_all()
            r = await t1
            assert len(r["val"]) == 8
            # Completion released the admission: a new request fits.
            r2 = await asyncio.wait_for(_retry_submit(b, d), timeout=2)
            assert len(r2["val"]) == 1
            return True

        assert asyncio.run(run())

    def test_drain_completes_inflight_then_rejects(self):
        async def run():
            d = _EchoDispatch(auto_resolve=False)
            b = _make_batcher(d, max_latency_ms=5.0)
            t1 = asyncio.ensure_future(b.submit(_rows(3)))
            await asyncio.sleep(0.05)
            drain = asyncio.ensure_future(b.drain(timeout_s=5))
            await asyncio.sleep(0.02)
            assert not drain.done()  # waiting on the in-flight rows
            d.resolve_all()
            await asyncio.wait_for(drain, timeout=5)
            r = await t1
            assert r["val"].tolist() == [0, 1, 2]  # completed, not dropped
            with pytest.raises(BatcherClosedError):
                await b.submit(_rows(1))
            return True

        assert asyncio.run(run())


async def _retry_submit(b, d, tries=20):
    for _ in range(tries):
        try:
            task = asyncio.ensure_future(b.submit(_rows(1)))
            await asyncio.sleep(0.03)
            d.resolve_all()
            return await task
        except QueueFullError:
            await asyncio.sleep(0.02)
    raise AssertionError("queue never freed")


# ---------------------------------------------------------------------------
# Executor-level: padding isolation + compile accounting
# ---------------------------------------------------------------------------

def _make_executor(variables=None, ckpt_dir=None, reload_every_s=5.0):
    _, _, al_set = get_data_synthetic(n_train=32, n_test=8, num_classes=4,
                                      image_size=IMG[0], seed=3)
    model = TinyClassifier(num_classes=4)
    mesh = mesh_lib.make_mesh()
    if variables is None and ckpt_dir is None:
        variables = jax.tree.map(np.asarray, model.init(
            jax.random.PRNGKey(0), np.zeros((1, *IMG), np.float32),
            train=False))
    return DeviceExecutor(model, al_set.view, mesh, image_shape=IMG,
                          variables=variables, ckpt_dir=ckpt_dir,
                          reload_every_s=reload_every_s), al_set


class TestPaddingIsolation:
    def test_padding_content_cannot_touch_real_rows(self):
        """Real rows' scores are identical whether the pad rows repeat
        row 0 (the production layout) or hold adversarial garbage — and
        both match the unbatched forward at the real rows' count."""
        ex, _ = _make_executor()
        step = ex._steps["prob_stats"]
        real = _rows(3, 7)
        mask = np.r_[np.ones(3, np.float32), np.zeros(5, np.float32)]

        def run(pad_rows):
            batch = {"image": np.concatenate([real, pad_rows]),
                     "mask": mask}
            out = step(ex._variables, mesh_lib.shard_batch(batch, ex.mesh))
            return {k: np.asarray(v)[:3] for k, v in out.items()}

        repeat = run(np.repeat(real[:1], 5, axis=0))
        garbage = run(_rows(5, 200))
        for k in repeat:
            assert np.array_equal(repeat[k], garbage[k]), k

        # Unbatched pin: the same 3 rows alone through the same step.
        alone = step(ex._variables, mesh_lib.shard_batch(
            {"image": np.concatenate([real, real[:1].repeat(5, axis=0)]),
             "mask": mask}, ex.mesh))
        for k in repeat:
            assert np.array_equal(repeat[k], np.asarray(alone[k])[:3]), k

    def test_unbatched_forward_oracle(self):
        """The served margin equals a hand-computed (no batching, no
        padding, no jit) softmax margin on the same pixels."""
        import jax.numpy as jnp
        from active_learning_tpu.data.augment import apply_view

        ex, al_set = _make_executor()
        rows = al_set.gather(np.arange(3))
        x = apply_view(jnp.asarray(rows), al_set.view, train=False)
        logits = np.asarray(ex.model.apply(
            jax.tree.map(np.asarray, ex._variables), x, train=False))
        probs = np.exp(logits - logits.max(-1, keepdims=True))
        probs /= probs.sum(-1, keepdims=True)
        srt = np.sort(probs, axis=-1)
        oracle_margin = srt[:, -1] - srt[:, -2]

        mask = np.r_[np.ones(3, np.float32), np.zeros(5, np.float32)]
        batch = {"image": np.concatenate([rows, rows[:1].repeat(5, 0)]),
                 "mask": mask}
        out = ex._steps["prob_stats"](ex._variables,
                                      mesh_lib.shard_batch(batch, ex.mesh))
        np.testing.assert_allclose(np.asarray(out["margin"])[:3],
                                   oracle_margin, rtol=0, atol=1e-6)


class TestCompileReuse:
    def test_zero_request_path_compiles_across_buckets(self):
        """Warmup compiles every ladder shape; requests of every size
        after that — including ones that land in every bucket — add
        ZERO jit-cache entries (the test_compile_reuse counter)."""
        ex, _ = _make_executor()
        buckets = serve_buckets(12, floor=4,
                                n_devices=ex.mesh.devices.size)
        ex.warmup(buckets)
        baseline = ex.compile_counts()

        for n in (1, 3, 4, 5, 9, 12):
            bucket = next(b for b in buckets if b >= n)
            mask = np.zeros(bucket, np.float32)
            mask[:n] = 1.0
            img = np.concatenate([_rows(n), _rows(bucket - n)]) \
                if bucket > n else _rows(n)
            out = ex._steps["prob_stats"](
                ex._variables,
                mesh_lib.shard_batch({"image": img, "mask": mask},
                                     ex.mesh))
            np.asarray(out["margin"])
        assert ex.compile_counts() == baseline
        assert ex.request_path_compiles() == 0


# ---------------------------------------------------------------------------
# End-to-end over loopback HTTP, from a REAL experiment dir
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def experiment_dir(tmp_path_factory):
    """A real 1-round experiment through the production driver: its
    checkpoint dir (best_rd_0.msgpack + experiment_state.json) is what
    `serve` starts from."""
    from active_learning_tpu.config import ExperimentConfig
    from active_learning_tpu.experiment.driver import run_experiment
    from active_learning_tpu.utils.metrics import NullSink

    tmp = tmp_path_factory.mktemp("serve_exp")
    data = get_data_synthetic(n_train=64, n_test=16, num_classes=4,
                              image_size=IMG[0], seed=3)
    cfg = ExperimentConfig(
        dataset="synthetic", strategy="MarginSampler", rounds=1,
        round_budget=8, n_epoch=2, early_stop_patience=0,
        exp_name="serve_e2e", exp_hash="servetest",
        ckpt_path=str(tmp / "ckpt"), log_dir=str(tmp / "logs"))
    run_experiment(cfg, sink=NullSink(), data=data,
                   train_cfg=tiny_train_config(),
                   model=TinyClassifier(num_classes=4))
    exp_dir = os.path.join(str(tmp / "ckpt"), "serve_e2e_servetest")
    assert ckpt_lib.latest_best_ckpt(exp_dir)[0] is not None
    return exp_dir


class _Stack:
    """Server + executor on a private event-loop thread, with plain
    urllib client helpers."""

    def __init__(self, executor, cfg, start_executor=True):
        self.executor = executor
        self.server = ScoringServer(executor, cfg)
        self._start_executor = start_executor
        self.loop = asyncio.new_event_loop()
        self.thread = threading.Thread(
            target=lambda: (asyncio.set_event_loop(self.loop),
                            self.loop.run_forever()), daemon=True)
        self.thread.start()
        if not start_executor:
            # Swap start() to a no-op so admitted work stays queued
            # until the test releases it.
            executor._real_start = executor.start
            executor.start = lambda: None
        self.call(self.server.start(), timeout=120)
        self.port = self.server.port

    def call(self, coro, timeout=60):
        return asyncio.run_coroutine_threadsafe(coro, self.loop).result(
            timeout)

    def url(self, path):
        return f"http://127.0.0.1:{self.port}{path}"

    def get(self, path, timeout=30):
        with urllib.request.urlopen(self.url(path), timeout=timeout) as r:
            return r.status, json.loads(r.read())

    def post(self, path, obj, timeout=60):
        req = urllib.request.Request(
            self.url(path), data=json.dumps(obj).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return r.status, json.loads(r.read()), dict(r.headers)
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read() or b"{}"), dict(e.headers)

    def close(self):
        try:
            self.call(self.server.drain(), timeout=60)
        finally:
            self.loop.call_soon_threadsafe(self.loop.stop)
            self.thread.join(timeout=10)


@pytest.fixture()
def stack(experiment_dir):
    _, _, al_set = get_data_synthetic(n_train=64, n_test=16, num_classes=4,
                                      image_size=IMG[0], seed=3)
    ex = DeviceExecutor(TinyClassifier(num_classes=4), al_set.view,
                        mesh_lib.make_mesh(), image_shape=IMG,
                        ckpt_dir=experiment_dir, reload_every_s=0.0)
    st = _Stack(ex, ServeConfig(port=0, max_batch=8, max_latency_ms=5.0,
                                queue_depth=64, bucket_floor=8))
    st.al_set = al_set
    yield st
    st.close()


class TestServeEndToEnd:
    def test_score_matches_offline_bitforbit(self, stack, experiment_dir):
        """/v1/score over HTTP == the offline scoring path (the same
        collect_pool machinery every sampler uses) at the same batch
        shape, bit for bit."""
        from active_learning_tpu.strategies import scoring

        idxs = np.arange(8)
        rows = stack.al_set.gather(idxs)
        status, resp, _ = stack.post(
            "/v1/score", {"instances": rows.tolist()})
        assert status == 200
        served = {k: np.asarray([r[k] for r in resp["scores"]],
                                np.float32)
                  for k in ("margin", "confidence", "entropy")}

        # The offline path, from the same checkpoint file: a FRESH jit
        # of the same factory over the same view + weights, through
        # collect_pool at the served bucket's batch shape.
        best, _rd = ckpt_lib.latest_best_ckpt(experiment_dir)
        variables = mesh_lib.replicate(ckpt_lib.load_variables(best),
                                       stack.executor.mesh)
        step = scoring.make_prob_stats_step(stack.executor.model,
                                            stack.al_set.view)
        offline = scoring.collect_pool(
            stack.al_set, idxs, 8, step, variables, stack.executor.mesh)
        for k in served:
            assert np.array_equal(served[k],
                                  offline[k].astype(np.float32)), k
        pred_served = np.asarray([r["pred"] for r in resp["scores"]])
        assert np.array_equal(pred_served, offline["pred"])

    def test_predict_and_embedding(self, stack):
        rows = stack.al_set.gather(np.arange(3))
        status, resp, _ = stack.post("/v1/predict",
                                     {"instances": rows.tolist()})
        assert status == 200 and len(resp["predictions"]) == 3
        assert {"pred", "confidence", "margin"} <= set(
            resp["predictions"][0])
        status, resp, _ = stack.post(
            "/v1/score", {"instances": rows.tolist(), "embedding": True})
        assert status == 200
        emb = np.asarray(resp["embedding"], np.float32)
        assert emb.shape == (3, 8)  # TinyClassifier feat_dim

    def test_healthz_metrics_and_compile_counter(self, stack):
        status, h = stack.get("/healthz")
        assert status == 200 and h["ok"] and h["image_shape"] == list(IMG)
        assert h["buckets"] == stack.server.batcher.buckets
        rows = stack.al_set.gather(np.arange(2))
        stack.post("/v1/score", {"instances": rows.tolist()})
        status, m = stack.get("/metrics")
        assert status == 200
        assert m["compiles"]["request_path_compiles"] == 0
        assert m["latency_ms"]["n"] >= 1
        assert m["batch_occupancy"]  # at least one dispatched bucket
        assert m["rows_served"] >= 2

    def test_b64_wire_format(self, stack):
        rows = stack.al_set.gather(np.arange(2))
        import base64
        status, resp, _ = stack.post("/v1/score", {
            "b64": base64.b64encode(rows.tobytes()).decode(),
            "shape": list(rows.shape)})
        assert status == 200 and len(resp["scores"]) == 2
        # And a nested-list request of the same pixels matches exactly.
        _, resp2, _ = stack.post("/v1/score",
                                 {"instances": rows.tolist()})
        assert resp["scores"] == resp2["scores"]

    def test_bad_requests_rejected(self, stack):
        assert stack.post("/v1/score", {"instances": []})[0] == 400
        assert stack.post("/v1/score", {})[0] == 400
        wrong = np.zeros((1, 4, 4, 3), np.uint8)
        assert stack.post("/v1/score",
                          {"instances": wrong.tolist()})[0] == 400
        # Malformed b64 shapes are client errors (400), never a 500
        # out of reshape.
        assert stack.post("/v1/score",
                          {"b64": "AAAA", "shape": [1, 8.5, 8, 3]})[0] \
            == 400
        assert stack.post("/v1/score",
                          {"b64": "AAAA",
                           "shape": ["1", "8", "8", "3"]})[0] == 400
        status, _, _ = stack.post("/v2/unknown", {"instances": [[0]]})
        assert status == 404

    def test_malformed_content_length_gets_400(self, stack):
        """A garbage Content-Length answers 400 and closes — never an
        unhandled task exception."""
        import socket

        with socket.create_connection(("127.0.0.1", stack.port),
                                      timeout=10) as s:
            s.sendall(b"POST /v1/score HTTP/1.1\r\n"
                      b"Content-Length: abc\r\n\r\n")
            data = s.recv(4096)
        assert b"400" in data.split(b"\r\n")[0]

    def test_hot_reload_serves_new_round(self, stack, experiment_dir):
        """A new best_rd_1 appearing (a live experiment finishing its
        next round) is picked up between batches: responses flip to the
        new round's weights without a restart."""
        rows = stack.al_set.gather(np.arange(2))
        _, before, _ = stack.post("/v1/score",
                                  {"instances": rows.tolist()})
        assert before["round"] == 0
        # Perturb the head bias hard enough to change every margin.
        best, _ = ckpt_lib.latest_best_ckpt(experiment_dir)
        variables = ckpt_lib.load_variables(best)
        variables["params"]["linear"]["bias"] = (
            np.asarray(variables["params"]["linear"]["bias"])
            + np.array([5.0, -5.0, 0.0, 0.0], np.float32))
        ckpt_lib.save_variables(
            os.path.join(experiment_dir, "best_rd_1.msgpack"), variables)
        try:
            _, after, _ = stack.post("/v1/score",
                                     {"instances": rows.tolist()})
            assert after["round"] == 1
            assert after["scores"] != before["scores"]
            _, m = stack.get("/metrics")
            assert m["executor"]["reloads"] == 1
        finally:
            os.remove(os.path.join(experiment_dir, "best_rd_1.msgpack"))


class TestBackpressure:
    def test_429_with_retry_after_then_completion(self, experiment_dir):
        """With the device loop held, admission fills queue_depth and
        the NEXT request gets 429 + Retry-After; releasing the executor
        completes the admitted requests with 200 — overflow never
        cancels admitted work."""
        _, _, al_set = get_data_synthetic(n_train=64, n_test=16,
                                          num_classes=4,
                                          image_size=IMG[0], seed=3)
        ex = DeviceExecutor(TinyClassifier(num_classes=4), al_set.view,
                            mesh_lib.make_mesh(), image_shape=IMG,
                            ckpt_dir=experiment_dir)
        st = _Stack(ex, ServeConfig(port=0, max_batch=8,
                                    max_latency_ms=5.0, queue_depth=8,
                                    bucket_floor=8),
                    start_executor=False)
        try:
            rows = al_set.gather(np.arange(4)).tolist()
            results = {}

            def bg(key):
                results[key] = st.post("/v1/score", {"instances": rows},
                                       timeout=60)

            threads = [threading.Thread(target=bg, args=(i,), daemon=True)
                       for i in range(2)]
            for t in threads:
                t.start()
            deadline = time.monotonic() + 5
            while st.server.batcher.pending_rows < 8:
                assert time.monotonic() < deadline, "admission stalled"
                time.sleep(0.01)
            status, body, headers = st.post("/v1/score",
                                            {"instances": rows})
            assert status == 429
            assert headers.get("Retry-After") == "1"
            assert "error" in body
            # Release the device loop: admitted requests must complete.
            ex._real_start()
            for t in threads:
                t.join(timeout=60)
            assert {s for s, _, _ in results.values()} == {200}
        finally:
            st.close()


class TestRobustness:
    def test_oversize_request_gets_413_not_429(self, stack):
        """A request larger than queue_depth could NEVER be admitted:
        it must get a non-retryable 413 at the door, not a 429 a
        compliant client would retry forever."""
        depth = stack.server.cfg.queue_depth
        rows = np.zeros((depth + 1, *IMG), np.uint8)
        import base64
        status, body, headers = stack.post("/v1/score", {
            "b64": base64.b64encode(rows.tobytes()).decode(),
            "shape": list(rows.shape)})
        assert status == 413
        assert "queue_depth" in body["error"]
        assert "Retry-After" not in headers

    def test_failed_chunk_releases_only_its_rows(self):
        """Per-chunk admission release: when one chunk of a multi-chunk
        request fails while siblings are still pending, only the failed
        chunk's rows free up — the queued+in-flight bound holds."""
        async def run():
            d = _EchoDispatch(auto_resolve=False)
            b = _make_batcher(d, max_batch=4, queue_depth=64,
                              max_latency_ms=5.0)
            task = asyncio.ensure_future(b.submit(_rows(10)))  # 3 chunks
            await asyncio.sleep(0.05)
            assert b.pending_rows == 10
            # Fail the FIRST chunk only; the other two stay in flight.
            host, entries = d.pending.pop(0)
            for e in entries:
                e.future.set_exception(RuntimeError("boom"))
            await asyncio.sleep(0.02)
            assert b.pending_rows == 10 - entries[0].n  # partial release
            d.resolve_all()
            with pytest.raises(RuntimeError):
                await task
            await asyncio.sleep(0.02)
            assert b.pending_rows == 0  # everything released in the end
            return True

        assert asyncio.run(run())

    def test_shard_failure_fails_batch_not_executor(self, monkeypatch):
        """One transient H2D failure rejects ITS batch's futures and the
        executor keeps serving — it must never die with futures
        hanging."""
        from active_learning_tpu.serve import executor as ex_mod

        ex, _ = _make_executor()
        real_shard = ex_mod.mesh_lib.shard_batch
        boom = {"left": 1}

        def flaky(batch, mesh):
            if boom["left"]:
                boom["left"] -= 1
                raise RuntimeError("transient device_put failure")
            return real_shard(batch, mesh)

        monkeypatch.setattr(ex_mod.mesh_lib, "shard_batch", flaky)
        ex.start()
        loop = asyncio.new_event_loop()
        try:
            f1, f2 = loop.create_future(), loop.create_future()
            host = {"image": _rows(8), "mask": np.ones(8, np.float32)}

            class E:
                def __init__(self, fut):
                    self.future, self.n, self.offset = fut, 8, 0
                    self.want_embed = False

            ex.submit_batch(dict(host), [E(f1)], False)
            ex.submit_batch(dict(host), [E(f2)], False)

            async def wait_both():
                r1 = await asyncio.wait_for(
                    asyncio.shield(_swallow(f1)), 30)
                r2 = await asyncio.wait_for(
                    asyncio.shield(_swallow(f2)), 30)
                return r1, r2

            r1, r2 = loop.run_until_complete(wait_both())
            # First batch rejected with the transient error...
            assert isinstance(r1, RuntimeError)
            # ...second batch served normally by the SAME executor.
            assert isinstance(r2, dict) and "margin" in r2
        finally:
            ex.stop()
            loop.close()


async def _swallow(fut):
    try:
        return await fut
    except Exception as e:  # noqa: BLE001 - the exception IS the result
        return e


class TestGracefulDrain:
    def test_drain_completes_inflight_requests(self, experiment_dir):
        """Drain with work queued and the device loop held: the drain
        blocks, the executor release completes the request with 200,
        then the drain finishes and new connections are refused."""
        _, _, al_set = get_data_synthetic(n_train=64, n_test=16,
                                          num_classes=4,
                                          image_size=IMG[0], seed=3)
        ex = DeviceExecutor(TinyClassifier(num_classes=4), al_set.view,
                            mesh_lib.make_mesh(), image_shape=IMG,
                            ckpt_dir=experiment_dir)
        st = _Stack(ex, ServeConfig(port=0, max_batch=8,
                                    max_latency_ms=5.0, queue_depth=64,
                                    bucket_floor=8),
                    start_executor=False)
        rows = al_set.gather(np.arange(2)).tolist()
        result = {}

        def bg():
            result["r"] = st.post("/v1/score", {"instances": rows},
                                  timeout=60)

        t = threading.Thread(target=bg, daemon=True)
        t.start()
        deadline = time.monotonic() + 5
        while st.server.batcher.pending_rows < 2:
            assert time.monotonic() < deadline
            time.sleep(0.01)
        drain = asyncio.run_coroutine_threadsafe(st.server.drain(),
                                                 st.loop)
        time.sleep(0.1)
        assert not drain.done()  # waiting on the in-flight request
        ex._real_start()
        drain.result(timeout=60)
        t.join(timeout=60)
        status, resp, _ = result["r"]
        assert status == 200 and len(resp["scores"]) == 2  # never dropped
        # Post-drain: the listener is closed (refused) or answers 503.
        try:
            status, _, _ = st.post("/v1/score", {"instances": rows},
                                   timeout=5)
            assert status == 503
        except (urllib.error.URLError, ConnectionError, OSError):
            pass
        st.loop.call_soon_threadsafe(st.loop.stop)
        st.thread.join(timeout=10)


_SIGTERM_CHILD = r"""
import asyncio, os, sys, numpy as np
sys.path.insert(0, {repo!r}); sys.path.insert(0, {tests!r})
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from helpers import TinyClassifier
from active_learning_tpu.config import ServeConfig
from active_learning_tpu.data.synthetic import get_data_synthetic
from active_learning_tpu.parallel import mesh as mesh_lib
from active_learning_tpu.serve.cli import _serve_until_signal
from active_learning_tpu.serve.executor import DeviceExecutor
from active_learning_tpu.serve.server import ScoringServer

_, _, al_set = get_data_synthetic(n_train=16, n_test=8, num_classes=4,
                                  image_size=8, seed=3)
ex = DeviceExecutor(TinyClassifier(num_classes=4), al_set.view,
                    mesh_lib.make_mesh(), image_shape=(8, 8, 3),
                    ckpt_dir={exp_dir!r})
server = ScoringServer(ex, ServeConfig(port=0, max_batch=8,
                                       max_latency_ms=5.0))

async def main():
    task = asyncio.ensure_future(_serve_until_signal(server))
    while server.port is None:
        await asyncio.sleep(0.01)
    print(f"PORT={{server.port}}", flush=True)
    await task
    print("DRAINED", flush=True)

asyncio.run(main())
"""


class TestSigterm:
    def test_sigterm_drains_and_exits_zero(self, experiment_dir):
        """The CLI's signal path end to end in a real process: serve,
        answer a request, SIGTERM, drain cleanly, exit 0."""
        code = _SIGTERM_CHILD.format(
            repo=os.path.dirname(os.path.dirname(os.path.abspath(
                __file__))),
            tests=os.path.dirname(os.path.abspath(__file__)),
            exp_dir=experiment_dir)
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PALLAS_AXON_POOL_IPS="")
        proc = subprocess.Popen([sys.executable, "-c", code],
                                stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, text=True,
                                env=env)
        try:
            port = None
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                line = proc.stdout.readline()
                if line.startswith("PORT="):
                    port = int(line.strip().split("=")[1])
                    break
            assert port, "server never reported its port"
            rows = np.zeros((2, *IMG), np.uint8)
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/score",
                data=json.dumps({"instances": rows.tolist()}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=60) as r:
                assert r.status == 200
            proc.send_signal(signal.SIGTERM)
            out, err = proc.communicate(timeout=60)
            assert proc.returncode == 0, err[-2000:]
            assert "DRAINED" in out
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.communicate()


# ---------------------------------------------------------------------------
# CLI verb + experiment-dir resolution
# ---------------------------------------------------------------------------

class TestServeCli:
    def test_verb_routes_from_main_cli(self, tmp_path):
        """`python -m active_learning_tpu serve ...` reaches the serve
        CLI (and its argument errors), not the experiment parser."""
        from active_learning_tpu.experiment.cli import main

        with pytest.raises(SystemExit) as exc:
            main(["serve", "--experiment_dir", str(tmp_path / "nope"),
                  "--compilation_cache_dir", ""])
        assert "best_rd" in str(exc.value)

    def test_resolution_from_experiment_dir(self, experiment_dir):
        """Dataset/model come from the saved config echo; num_classes
        from the checkpoint's own head; image size from the dataset."""
        from active_learning_tpu.serve.cli import (get_parser,
                                                   resolve_serve_setup)

        args = get_parser().parse_args(
            ["--experiment_dir", experiment_dir, "--image_size", "8"])
        model, variables, view, image_size, exp_dir = \
            resolve_serve_setup(args)
        assert exp_dir == experiment_dir
        assert image_size == 8
        assert variables["params"]["linear"]["bias"].shape == (4,)
        assert view.augment is False

    def test_missing_dir_exits_loudly(self):
        from active_learning_tpu.serve.cli import (get_parser,
                                                   resolve_serve_setup)

        args = get_parser().parse_args([])
        with pytest.raises(SystemExit):
            resolve_serve_setup(args)

    def test_stem_resolution_follows_config_echo(self, tmp_path):
        """An experiment trained with --stem s2d saved a FOLDED stem
        kernel; the serve model must be built with the same stem (and
        the executor fed space-to-depth input) or warmup dies on the
        param-shape mismatch."""
        from active_learning_tpu.serve.cli import (get_parser,
                                                   resolve_serve_setup)

        exp = tmp_path / "exp_s2d"
        exp.mkdir()
        ckpt_lib.save_variables(
            str(exp / "best_rd_0.msgpack"),
            {"params": {"linear": {"bias": np.zeros(7, np.float32)}}})
        (exp / "experiment_state.json").write_text(json.dumps({
            "round": 0,
            "config": {"dataset": "imagenet", "model": "SSLResNet50",
                       "arg_pool": "default", "stem": "s2d"}}))
        args = get_parser().parse_args(["--experiment_dir", str(exp)])
        model, variables, _view, image_size, _ = resolve_serve_setup(args)
        assert getattr(model, "stem", None) == "s2d"
        assert image_size == 224
        assert variables["params"]["linear"]["bias"].shape == (7,)


class TestHostS2d:
    def test_executor_transforms_input_host_side(self):
        """host_s2d executors accept client-shaped (H, W, 3) rows and
        feed the step the space-to-depth layout — same transform as the
        offline pipeline (TinyClassifier flattens, so the step accepts
        either layout; what's pinned is that the transform HAPPENED and
        the scores equal a hand-applied space_to_depth forward)."""
        from active_learning_tpu.data.pipeline import space_to_depth

        ex, al_set = _make_executor()
        ex.host_s2d = True
        ex.warmup([8])
        assert ex.request_path_compiles() == 0

        rows = al_set.gather(np.arange(3))
        host = {"image": np.concatenate([rows, rows[:1].repeat(5, 0)]),
                "mask": np.r_[np.ones(3, np.float32),
                              np.zeros(5, np.float32)]}
        dev, _entries, _we, exc = ex._put((host, [], False))
        assert exc is None
        assert dev["image"].shape == (8, 4, 4, 12)  # s2d happened
        out = ex._steps["prob_stats"](ex._variables, dev)
        # Oracle: the same step over a hand-transformed batch.
        ref = ex._steps["prob_stats"](
            ex._variables,
            mesh_lib.shard_batch(
                dict(host, image=space_to_depth(host["image"])),
                ex.mesh))
        assert np.array_equal(np.asarray(out["margin"])[:3],
                              np.asarray(ref["margin"])[:3])
        # Warmup covered the s2d shape: still zero request-path compiles.
        assert ex.request_path_compiles() == 0


# ---------------------------------------------------------------------------
# Bench phase smoke (the serve_throughput capture path)
# ---------------------------------------------------------------------------

class TestBenchServePhase:
    def test_smoke_records_qps_and_zero_compiles(self, monkeypatch):
        import importlib.util

        monkeypatch.setenv("AL_BENCH_SERVE_SMOKE", "1")
        path = os.path.join(os.path.dirname(__file__), "..", "bench.py")
        spec = importlib.util.spec_from_file_location("bench_serve", path)
        bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench)
        result = bench.run_serve_phase(2, 8)
        assert result["phase"] == "serve_throughput"
        assert result["ips"] > 0 and result["qps_closed"] > 0
        assert result["p99_ms_closed"] is not None
        assert result["request_path_compiles"] == 0
        assert result["batch_occupancy"]
        assert result["n_429"] == 0 or result["qps_open"] > 0
