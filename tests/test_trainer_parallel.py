"""Multi-device trainer/eval correctness on the virtual 8-device CPU mesh.

These are the distributed-semantics tests the reference cannot have (it
needs a real multi-GPU node): the 8-way sharded train step must produce the
SAME parameters as a 1-device run of the identical global batch (gradient
psum == DDP allreduce, strategy.py:336), global-batch BN statistics must
match (SyncBatchNorm, strategy.py:292), padding rows must not leak into
gradients, and sharded eval counts must match a NumPy oracle
(gather_parallel_eval, evaluation.py:69-98).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import linen as nn

from active_learning_tpu.config import (LoaderConfig, OptimizerConfig,
                                        SchedulerConfig, TrainConfig)
from active_learning_tpu.data.core import Normalization, ViewSpec
from active_learning_tpu.data.synthetic import get_data_synthetic
from active_learning_tpu.parallel import mesh as mesh_lib
from active_learning_tpu.train.trainer import Trainer

from helpers import TinyClassifier, tiny_train_config

VIEW = ViewSpec(Normalization((0.5,) * 3, (0.25,) * 3), augment=False)


class BNClassifier(nn.Module):
    """Conv + BatchNorm + head: exercises the global-batch BN path."""

    num_classes: int = 4

    @nn.compact
    def __call__(self, x, train: bool = True, return_features: bool = False):
        x = x.astype(jnp.float32)
        x = nn.Conv(8, (3, 3), name="conv")(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         name="bn")(x)
        x = nn.relu(x)
        emb = x.mean(axis=(1, 2))
        logits = nn.Dense(self.num_classes, name="linear")(emb)
        if return_features:
            return logits, emb
        return logits


def make_batch(rng, n, hw=8, num_classes=4):
    return {
        "image": rng.integers(0, 256, size=(n, hw, hw, 3), dtype=np.uint8),
        "label": rng.integers(0, num_classes, size=n).astype(np.int32),
        "index": np.arange(n, dtype=np.int32),
        "mask": np.ones(n, dtype=np.float32),
    }


def one_step(trainer, mesh, batch, seed=0):
    state = trainer.init_state(jax.random.PRNGKey(seed),
                               batch["image"][:2])
    cw = jnp.ones(trainer.num_classes, jnp.float32)
    new_state, loss, _gnorm = trainer._train_step(
        state, mesh_lib.shard_batch(batch, mesh), jax.random.PRNGKey(7),
        jnp.float32(0.1), cw, view=VIEW)
    return jax.tree.map(np.asarray, new_state.variables), float(loss)


class TestShardedStepEqualsSingleDevice:
    def test_params_and_bn_stats_match(self):
        """8-way data-sharded step == 1-device step on the same global
        batch: gradients psum correctly and BN stats are global-batch."""
        batch = make_batch(np.random.default_rng(0), 16)
        cfg = tiny_train_config()
        model = BNClassifier()

        mesh8 = mesh_lib.make_mesh(8)
        mesh1 = mesh_lib.make_mesh(1)
        t8 = Trainer(model, cfg, mesh8, 4, train_bn=True)
        t1 = Trainer(model, cfg, mesh1, 4, train_bn=True)
        vars8, loss8 = one_step(t8, mesh8, batch)
        vars1, loss1 = one_step(t1, mesh1, batch)

        assert abs(loss8 - loss1) < 1e-5
        flat8 = jax.tree_util.tree_leaves_with_path(vars8)
        flat1 = dict(jax.tree_util.tree_leaves_with_path(vars1))
        assert len(flat8) > 0
        for path, leaf in flat8:
            np.testing.assert_allclose(
                leaf, flat1[path], rtol=1e-4, atol=1e-5,
                err_msg=f"mismatch at {jax.tree_util.keystr(path)}")

    def test_bn_stats_are_global_batch(self):
        """The updated running mean must reflect the FULL 16-row batch, not
        any single shard's 2 rows (SyncBatchNorm semantics)."""
        batch = make_batch(np.random.default_rng(1), 16)
        cfg = tiny_train_config()
        model = BNClassifier()
        mesh8 = mesh_lib.make_mesh(8)
        trainer = Trainer(model, cfg, mesh8, 4, train_bn=True)
        state = trainer.init_state(jax.random.PRNGKey(0),
                                   batch["image"][:2])
        params = jax.tree.map(np.asarray, state.params)

        new_vars, _ = one_step(trainer, mesh8, batch)
        # Oracle: batch mean of the conv output over the whole batch.
        from active_learning_tpu.data.augment import apply_view
        x = apply_view(jnp.asarray(batch["image"]), VIEW, train=False)
        conv_out = jax.lax.conv_general_dilated(
            np.asarray(x), params["conv"]["kernel"], (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        ) + params["conv"]["bias"]
        batch_mean = np.asarray(conv_out).mean(axis=(0, 1, 2))
        # momentum 0.9: new_running = 0.9 * 0 + 0.1 * batch_mean
        np.testing.assert_allclose(new_vars["batch_stats"]["bn"]["mean"],
                                   0.1 * batch_mean, rtol=1e-3, atol=1e-5)

    def test_padding_rows_do_not_affect_gradients(self):
        """A batch padded from 10 real rows to 16 must produce the same
        update as the 10 real rows alone (padding weight 0)."""
        rng = np.random.default_rng(2)
        real = make_batch(rng, 10)
        cfg = tiny_train_config()
        model = TinyClassifier()  # no BN: padding can't leak via stats

        from active_learning_tpu.data.pipeline import gather_batch

        class _DS:
            targets = real["label"].astype(np.int64)

            def gather(self, idxs):
                return real["image"][idxs]

        padded = gather_batch(_DS(), np.arange(10), 16)
        mesh8 = mesh_lib.make_mesh(8)
        mesh1 = mesh_lib.make_mesh(1)
        t8 = Trainer(model, cfg, mesh8, 4, train_bn=False)
        t1 = Trainer(model, cfg, mesh1, 4, train_bn=False)
        vars_padded, _ = one_step(t8, mesh8, padded)
        vars_real, _ = one_step(t1, mesh1, real)
        for a, b in zip(jax.tree_util.tree_leaves(vars_padded),
                        jax.tree_util.tree_leaves(vars_real)):
            np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6)


class TestFitAndEval:
    def test_fit_decreases_loss(self):
        train_set, _, al_set = get_data_synthetic(n_train=96, n_test=16,
                                                  num_classes=4,
                                                  image_size=8, seed=3)
        model = TinyClassifier()
        mesh = mesh_lib.make_mesh(8)
        trainer = Trainer(model, tiny_train_config(), mesh, 4)
        state = trainer.init_state(jax.random.PRNGKey(0),
                                   train_set.gather(np.zeros(1, np.int64)))
        labeled = np.arange(64)
        result = trainer.fit(state, train_set, labeled, al_set,
                             np.arange(64, 80), n_epoch=5, es_patience=0,
                             rng=np.random.default_rng(0))
        losses = [h["train_loss"] for h in result.history]
        assert losses[-1] < losses[0]
        assert result.epochs_run == 5
        # The returned history is plain floats: the per-epoch loss fetch
        # is deferred to the end of the fit, and a device array leaking
        # out here would mean a consumer can accidentally sync or
        # serialize live buffers.
        assert all(isinstance(v, float) for v in losses)

    def test_eval_matches_numpy_oracle(self):
        train_set, test_set, al_set = get_data_synthetic(
            n_train=64, n_test=48, num_classes=4, image_size=8, seed=4)
        model = TinyClassifier()
        mesh = mesh_lib.make_mesh(8)
        trainer = Trainer(model, tiny_train_config(), mesh, 4)
        state = trainer.init_state(jax.random.PRNGKey(1),
                                   test_set.gather(np.zeros(1, np.int64)))
        idxs = np.arange(len(test_set))
        perf = trainer.evaluate(state, test_set, idxs)

        # Oracle: direct unsharded forward.
        from active_learning_tpu.data.augment import apply_view
        x = apply_view(jnp.asarray(test_set.gather(idxs)), test_set.view,
                       train=False)
        logits = np.asarray(model.apply(state.variables, x, train=False))
        labels = test_set.targets[idxs]
        top1 = logits.argmax(1) == labels
        order = np.argsort(-logits, axis=1)[:, :4]  # top_k = num_classes
        topk = (order == labels[:, None]).any(1)
        assert perf["count"] == len(idxs)
        np.testing.assert_allclose(perf["accuracy"], top1.mean(), atol=1e-6)
        np.testing.assert_allclose(perf["top_5_accuracy"], topk.mean(),
                                   atol=1e-6)
        for c in range(4):
            sel = labels == c
            np.testing.assert_allclose(perf["accuracy_byclass"][c],
                                       top1[sel].mean(), atol=1e-6)

    def test_empty_eval_set_reports_zero(self):
        from active_learning_tpu.train.evaluation import accumulate_metrics
        out = accumulate_metrics(iter([]))
        assert out["accuracy"] == 0.0 and out["count"] == 0.0


class TestDeviceResidentEpochs:
    def _fit_pair(self, device_resident):
        import dataclasses
        train_set, _, al_set = get_data_synthetic(n_train=90, n_test=16,
                                                  num_classes=4,
                                                  image_size=8, seed=6)
        cfg = dataclasses.replace(tiny_train_config(),
                                  device_resident=device_resident)
        model = BNClassifier()
        mesh = mesh_lib.make_mesh(8)
        trainer = Trainer(model, cfg, mesh, 4, train_bn=True)
        state = trainer.init_state(jax.random.PRNGKey(0),
                                   train_set.gather(np.zeros(1, np.int64)))
        # 90 labeled, batch 16 -> 6 steps with a padded last batch: the
        # padding-row BN semantics are part of what must match.
        result = trainer.fit(state, train_set, np.arange(90), al_set,
                             np.arange(80, 90), n_epoch=3, es_patience=0,
                             rng=np.random.default_rng(42))
        return result

    def test_matches_host_batched_path_exactly(self):
        """Same rng, same key chain, same padding rows: the scanned
        device-resident epoch must reproduce the host-batched epoch."""
        dr = self._fit_pair(device_resident=True)
        host = self._fit_pair(device_resident=False)
        assert [h["train_loss"] for h in dr.history] == pytest.approx(
            [h["train_loss"] for h in host.history], rel=1e-5)
        leaves_dr = jax.tree_util.tree_leaves(
            jax.tree.map(np.asarray, dr.state.variables))
        leaves_host = jax.tree_util.tree_leaves(
            jax.tree.map(np.asarray, host.state.variables))
        for a, b in zip(leaves_dr, leaves_host):
            np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)

    def test_vaal_hook_forces_host_path(self):
        """batch_hook needs host batches -> device-resident must not
        engage (VAAL co-training)."""
        train_set, _, al_set = get_data_synthetic(n_train=32, n_test=8,
                                                  num_classes=4,
                                                  image_size=8, seed=7)
        trainer = Trainer(TinyClassifier(), tiny_train_config(),
                          mesh_lib.make_mesh(8), 4)
        state = trainer.init_state(jax.random.PRNGKey(0),
                                   train_set.gather(np.zeros(1, np.int64)))
        seen = []
        trainer.fit(state, train_set, np.arange(24), al_set,
                    np.arange(24, 32), n_epoch=1, es_patience=0,
                    rng=np.random.default_rng(0),
                    batch_hook=lambda epoch, b: seen.append(epoch))
        assert len(seen) > 0  # hook ran => host path was used


class TestResidentGatherFeed:
    """The resident-gather train feed (DESIGN.md §2a): train batches are
    on-device gathers of labeled indices from the SAME pinned pool that
    serves scoring/evaluation — zero host image copies, and a batch
    stream bit-identical to every other feed at the same seeds."""

    def _fit(self, cfg, n_labeled=83, seed=6, pool=None):
        import dataclasses as dc
        if pool is None:
            train_set, _, al_set = get_data_synthetic(
                n_train=90, n_test=16, num_classes=4, image_size=8,
                seed=seed)
        else:
            train_set, al_set = pool
        mesh = mesh_lib.make_mesh(8)
        trainer = Trainer(BNClassifier(), cfg, mesh, 4, train_bn=True)
        state = trainer.init_state(jax.random.PRNGKey(0),
                                   train_set.gather(np.zeros(1, np.int64)))
        # n_labeled=83 with batch 16: a PADDED last batch — padding
        # isolation is part of what must match bit for bit.
        result = trainer.fit(state, train_set, np.arange(n_labeled),
                             al_set, np.arange(83, 90), n_epoch=3,
                             es_patience=0, rng=np.random.default_rng(42))
        return trainer, result

    @staticmethod
    def _leaves(result):
        return jax.tree_util.tree_leaves(
            jax.tree.map(np.asarray, result.state.variables))

    def test_bitwise_identical_to_copy_scan_and_matches_host(self):
        import dataclasses as dc
        base = tiny_train_config()
        # Scan form (forced by device_resident=True): gathers from the
        # pinned pool inside the SAME scan body the legacy copy path
        # runs.  Same gathered bytes, same program => bitwise-identical
        # parameters.
        t_scan, scan = self._fit(dc.replace(base, train_feed="resident",
                                            device_resident=True))
        assert t_scan.last_feed["source"] == "resident"
        assert t_scan.last_feed["form"] == "scan"
        t_copy, copy = self._fit(dc.replace(base, device_resident=True,
                                            resident_scoring_bytes=0))
        assert t_copy.last_feed["source"] == "resident_copy"
        for a, b in zip(self._leaves(scan), self._leaves(copy)):
            np.testing.assert_array_equal(a, b)
        # Per-batch form (the CPU-mesh execution form): same batch
        # stream through a per-batch jitted gather+step.
        t_res, res = self._fit(dc.replace(base, train_feed="resident"))
        assert t_res.last_feed["source"] == "resident"
        assert t_res.last_feed["form"] == "step"
        for a, b in zip(self._leaves(res), self._leaves(scan)):
            np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
        # And the host-batched stream is the same batches through the
        # same step — numerically identical within fusion-order noise.
        t_host, host = self._fit(dc.replace(base, device_resident=False))
        assert t_host.last_feed["source"].startswith("host")
        assert [h["train_loss"] for h in res.history] == pytest.approx(
            [h["train_loss"] for h in host.history], rel=1e-5)
        for a, b in zip(self._leaves(res), self._leaves(host)):
            np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)

    def test_unlabeled_pool_rows_never_leak_into_training(self):
        """The resident feed gathers from the FULL pool array; rows
        outside the labeled set must be complete no-ops — two pools
        identical on the labeled rows but wildly different elsewhere
        must train to bitwise-identical parameters."""
        import dataclasses as dc
        from active_learning_tpu.data.core import ArrayDataset
        train_set, _, al_set = get_data_synthetic(
            n_train=90, n_test=16, num_classes=4, image_size=8, seed=6)
        cfg = dc.replace(tiny_train_config(), train_feed="resident")
        labeled = np.arange(40)
        poisoned = train_set.images.copy()
        poisoned[60:] = 255  # never-labeled rows scrambled
        pool_a = (train_set, al_set)
        ds_b = ArrayDataset(poisoned, train_set.targets, 4, train_set.view)
        pool_b = (ds_b, ds_b.with_view(al_set.view))
        _, ra = self._fit(cfg, n_labeled=40, pool=pool_a)
        _, rb = self._fit(cfg, n_labeled=40, pool=pool_b)
        for a, b in zip(self._leaves(ra), self._leaves(rb)):
            np.testing.assert_array_equal(a, b)

    def test_one_pinned_pool_serves_training_and_evaluation(self):
        """After a resident-feed fit, evaluation over the al view (shared
        storage) reuses the SAME upload — one cache entry, and the
        budget accounting sees one array's bytes."""
        import dataclasses as dc
        from active_learning_tpu.parallel import resident as resident_lib
        cfg = dc.replace(tiny_train_config(), train_feed="resident")
        trainer, result = self._fit(cfg)
        assert len(trainer.resident_pool["images"]) == 1
        pinned = resident_lib.pinned_bytes(trainer.resident_pool)
        train_set, _, al_set = get_data_synthetic(
            n_train=90, n_test=16, num_classes=4, image_size=8, seed=6)
        # (fresh dataset objects share nothing with the fit's — re-fit on
        # the trainer's own cached dataset instead)
        ds = trainer.resident_pool["images"][next(
            iter(trainer.resident_pool["images"]))][0]
        trainer.evaluate(result.state, ds, np.arange(8))
        assert len(trainer.resident_pool["images"]) == 1
        assert resident_lib.pinned_bytes(trainer.resident_pool) == pinned

    def test_feed_resolution_hierarchy(self):
        """resolve_train_feed walks resident > resident_copy >
        host_prefetch > host_serial; a pinned pool auto-selects the
        resident feed on accelerators (the acceptance invariant)."""
        import dataclasses as dc
        from active_learning_tpu.parallel import resident as resident_lib
        train_set, _, _ = get_data_synthetic(
            n_train=64, n_test=8, num_classes=4, image_size=8, seed=1)
        idxs = np.arange(64)

        def mk(**over):
            return Trainer(TinyClassifier(), dc.replace(
                tiny_train_config(), **over), mesh_lib.make_mesh(), 4,
                train_bn=False)

        class FakeDev:
            platform = "tpu"

        def on_accel(trainer):
            class FakeMesh:
                class devices:  # noqa: N801 - mimic ndarray .flat/.size
                    flat = [FakeDev()]
                    size = trainer.n_devices
            trainer.mesh = FakeMesh()
            return trainer

        # Accelerator + pool fits the budget => resident, even unpinned.
        assert on_accel(mk()).resolve_train_feed(train_set, idxs) \
            == "resident"
        # Pinned pool => resident even when the budget later reads 0
        # (its bytes are already in HBM — parallel/resident.cached).
        t = mk()
        resident_lib.pool_arrays(t.resident_pool, train_set, t.mesh)
        on_accel(t)  # pin on the REAL mesh, then resolve as-if-on-TPU
        t.resident_budget = 0
        assert t.resolve_train_feed(train_set, idxs) == "resident"
        # Budget 0 (residency disabled / mid-run demote), auto mode: the
        # resident_copy upload is HBM like any pinned array and is
        # charged against the SAME budget — the fallback must be the
        # host path, never an unaccounted re-upload.
        t2 = on_accel(mk(resident_scoring_bytes=0))
        t2.resident_budget = 0
        assert t2.resolve_train_feed(train_set, idxs) == "host_prefetch"
        # ... while an EXPLICIT device_resident=True keeps its legacy
        # force-the-scan meaning regardless of the budget.
        t2f = on_accel(mk(resident_scoring_bytes=0, device_resident=True))
        t2f.resident_budget = 0
        assert t2f.resolve_train_feed(train_set, idxs) == "resident_copy"
        # device_resident=False pins the host leg; prefetch>0 => threaded.
        assert on_accel(mk(device_resident=False)).resolve_train_feed(
            train_set, idxs) == "host_prefetch"
        import dataclasses
        serial = mk(device_resident=False,
                    loader_tr=dataclasses.replace(
                        tiny_train_config().loader_tr, prefetch=0))
        assert on_accel(serial).resolve_train_feed(train_set, idxs) \
            == "host_serial"
        # A batch_hook (VAAL) always takes the serial host leg.
        assert on_accel(mk()).resolve_train_feed(
            train_set, idxs, batch_hook=lambda e, b: None) == "host_serial"
        # CPU auto keeps small fits on the host (scan compile must
        # amortize); a disk-style dataset (no .images) can never pin.
        assert mk().resolve_train_feed(train_set, idxs).startswith("host")

    def test_host_prefetch_stream_identical_to_serial(self):
        import dataclasses as dc
        base = tiny_train_config()
        _, pre = self._fit(dc.replace(base, device_resident=False))
        _, ser = self._fit(dc.replace(
            base, device_resident=False,
            loader_tr=dc.replace(base.loader_tr, prefetch=0)))
        for a, b in zip(self._leaves(pre), self._leaves(ser)):
            np.testing.assert_array_equal(a, b)


class TestImbalancedTrainingWeights:
    """The reference's class-weighted loss (strategy.py:444-457 +
    CrossEntropyLoss(weight=w), strategy.py:352-356)."""

    def test_class_weights_reference_semantics(self):
        import dataclasses
        cfg = dataclasses.replace(tiny_train_config(),
                                  imbalanced_training=True)
        trainer = Trainer(TinyClassifier(), cfg, mesh_lib.make_mesh(), 4)
        labels = np.array([0, 0, 0, 1, 1, 2])  # class 3 unobserved
        got = trainer.class_weights(labels)
        raw = np.array([6 / 3, 6 / 2, 6 / 1, 1.0])  # total/count, else 1
        np.testing.assert_allclose(got, raw / raw.sum(), rtol=1e-6)
        assert abs(got.sum() - 1.0) < 1e-6
        # Flag off: identity weights.
        off = Trainer(TinyClassifier(), tiny_train_config(),
                      mesh_lib.make_mesh(), 4)
        assert (off.class_weights(labels) == 1.0).all()

    def test_weighted_ce_matches_torch(self):
        """weighted_cross_entropy == torch CrossEntropyLoss(weight=w,
        reduction='mean'): sum(w_y * ce) / sum(w_y)."""
        import torch

        from active_learning_tpu.train.trainer import weighted_cross_entropy
        rng = np.random.default_rng(3)
        logits = rng.normal(size=(12, 5)).astype(np.float32)
        labels = rng.integers(0, 5, size=12)
        class_w = rng.uniform(0.2, 2.0, size=5).astype(np.float32)
        ours = float(weighted_cross_entropy(
            jnp.asarray(logits), jnp.asarray(labels),
            jnp.asarray(class_w[labels])))
        ref = torch.nn.CrossEntropyLoss(weight=torch.tensor(class_w))(
            torch.tensor(logits), torch.tensor(labels))
        assert abs(ours - float(ref)) < 1e-5

    def test_zero_weight_rows_do_not_move_the_loss(self):
        """Padding rows enter with weight 0 (mask multiplied in the train
        step) and must be exact no-ops on the loss."""
        from active_learning_tpu.train.trainer import weighted_cross_entropy
        rng = np.random.default_rng(4)
        logits = rng.normal(size=(6, 4)).astype(np.float32)
        labels = rng.integers(0, 4, size=6)
        w = np.ones(6, dtype=np.float32)
        base = float(weighted_cross_entropy(
            jnp.asarray(logits), jnp.asarray(labels), jnp.asarray(w)))
        pad_logits = np.concatenate([logits, rng.normal(size=(3, 4))
                                     .astype(np.float32)])
        pad_labels = np.concatenate([labels, np.array([0, 1, 2])])
        pad_w = np.concatenate([w, np.zeros(3, np.float32)])
        padded = float(weighted_cross_entropy(
            jnp.asarray(pad_logits), jnp.asarray(pad_labels),
            jnp.asarray(pad_w)))
        assert abs(base - padded) < 1e-6


class TestResidentEvaluation:
    """In-memory eval/test rows stay device-resident across epochs and
    rounds; results must be identical to the host-batched path."""

    def test_matches_host_batched_evaluate(self):
        import dataclasses
        train_set, _, al_set = get_data_synthetic(
            n_train=100, n_test=16, num_classes=4, image_size=8, seed=9)
        mesh = mesh_lib.make_mesh()
        res = Trainer(BNClassifier(), tiny_train_config(), mesh, 4,
                      train_bn=True)
        host = Trainer(BNClassifier(),
                       dataclasses.replace(tiny_train_config(),
                                           resident_scoring_bytes=0),
                       mesh, 4, train_bn=True)
        state = res.init_state(jax.random.PRNGKey(1),
                               train_set.gather(np.arange(2)))
        idxs = np.arange(37, 100)  # padded last batch included
        a = res.evaluate(state, al_set, idxs)
        b = host.evaluate(state, al_set, idxs)
        assert len(res.resident_pool["images"]) == 1
        for k in a:
            np.testing.assert_allclose(np.asarray(a[k]), np.asarray(b[k]),
                                       rtol=1e-6, atol=1e-6, err_msg=k)

    def test_views_share_one_upload_and_no_host_gathers(self):
        """al/train views share storage -> one upload; repeated evaluate
        calls (per-epoch validation) never touch the host dataset again."""
        train_set, _, al_set = get_data_synthetic(
            n_train=64, n_test=16, num_classes=4, image_size=8, seed=9)
        mesh = mesh_lib.make_mesh()
        trainer = Trainer(BNClassifier(), tiny_train_config(), mesh, 4,
                          train_bn=True)
        state = trainer.init_state(jax.random.PRNGKey(1),
                                   train_set.gather(np.arange(2)))
        calls = {"n": 0}
        orig = al_set.gather

        def counting(idxs):
            calls["n"] += 1
            return orig(idxs)

        al_set.gather = counting
        for _ in range(3):  # three "epochs" of validation
            trainer.evaluate(state, al_set, np.arange(48, 64))
        trainer.evaluate(state, train_set.with_view(al_set.view),
                         np.arange(8))  # shares the images array
        assert calls["n"] == 0
        assert len(trainer.resident_pool["images"]) == 1  # one upload for both


def test_eval_batch_floor_cpu_keeps_reference_batch():
    """On the CPU test mesh, evaluation uses the reference's test-loader
    batch unchanged; the accelerator floor (>=128 rows/chip) applies the
    same throughput-only policy as acquisition scoring."""
    from helpers import TinyClassifier, tiny_train_config
    from active_learning_tpu.parallel import mesh as mesh_lib
    from active_learning_tpu.train.trainer import Trainer

    trainer = Trainer(TinyClassifier(num_classes=4),
                      tiny_train_config(batch_size=16),
                      mesh_lib.make_mesh(), num_classes=4)
    assert trainer.eval_batch_size() == trainer.cfg.loader_te.batch_size

    class FakeDev:
        platform = "tpu"

    class FakeMesh:
        class devices:  # noqa: N801 — mimic np.ndarray .flat/.size
            flat = [FakeDev()]
            size = trainer.n_devices

    real = trainer.mesh
    trainer.mesh = FakeMesh()
    try:
        # Unknown row shape: conservative 128/chip floor.
        assert trainer.eval_batch_size() == 128 * trainer.n_devices

        class Small:  # 32px rows: 512/chip (v5e probe: +47% over 256)
            image_shape = (32, 32, 3)

        class Large:  # ImageNet-res rows: 256/chip (+11% over 128)
            image_shape = (224, 224, 3)

        assert trainer.eval_batch_size(Small()) == 512 * trainer.n_devices
        assert trainer.eval_batch_size(Large()) == 256 * trainer.n_devices
    finally:
        trainer.mesh = real


def test_cosine_warmup_schedule():
    """warmup_epochs=0 is exactly torch CosineAnnealingLR; warmup>0 ramps
    linearly (never starting at 0) then runs the cosine over the
    remaining epochs — the re-init-every-round cold-start fix
    (SchedulerConfig.warmup_epochs)."""
    import math

    from active_learning_tpu.config import SchedulerConfig
    from active_learning_tpu.train.optim import make_lr_schedule

    plain = make_lr_schedule(SchedulerConfig(name="cosine", t_max=10), 0.1)
    for e in range(10):
        expected = 0.1 * (1 + math.cos(math.pi * e / 10)) / 2
        assert abs(plain(e) - expected) < 1e-12

    warm = make_lr_schedule(
        SchedulerConfig(name="cosine", t_max=10, warmup_epochs=3), 0.1)
    assert abs(warm(0) - 0.1 / 3) < 1e-12
    assert abs(warm(1) - 0.2 / 3) < 1e-12
    assert abs(warm(2) - 0.1) < 1e-12
    # Cosine span starts after the ramp and ends where t_max says.
    assert abs(warm(3) - 0.1) < 1e-12
    assert warm(9) < warm(3)
    assert abs(warm(9) - 0.1 * (1 + math.cos(math.pi * 6 / 7)) / 2) < 1e-12
