"""Pluggable metrics sinks (SURVEY §5): the JSONL default plus the second
backend family (csv, tensorboard) behind one registry, composable via
MultiSink — the reference offers exactly one hardwired backend (Comet,
src/main_al.py:101-114)."""

import csv
import json
import os

import pytest

from active_learning_tpu.utils.metrics import (CsvSink, JsonlSink, MultiSink,
                                               NullSink, SINK_BACKENDS,
                                               make_sink)


def test_csv_sink_roundtrip(tmp_path):
    sink = CsvSink(str(tmp_path), experiment_key="k1")
    sink.log_parameters({"strategy": "MarginSampler", "rounds": 2})
    sink.log_metric("rd_test_accuracy", 0.5, step=1)
    sink.log_metrics({"a": 1.0, "b": 2.0}, step=3)
    sink.log_asset("labeled_idxs_on_rd_0", "1,2,3")
    sink.close()

    with open(tmp_path / "metrics.csv") as fh:
        rows = list(csv.DictReader(fh))
    assert [(r["name"], float(r["value"]), r["step"]) for r in rows] == [
        ("rd_test_accuracy", 0.5, "1"), ("a", 1.0, "3"), ("b", 2.0, "3")]
    with open(tmp_path / "params.json") as fh:
        assert json.load(fh)["strategy"] == "MarginSampler"
    with open(tmp_path / "assets" / "labeled_idxs_on_rd_0.txt") as fh:
        assert fh.read() == "1,2,3"


def test_make_sink_registry(tmp_path):
    assert isinstance(make_sink(False, str(tmp_path)), NullSink)
    assert isinstance(make_sink(True, str(tmp_path)), JsonlSink)
    assert isinstance(make_sink(True, str(tmp_path), backend="csv"), CsvSink)
    multi = make_sink(True, str(tmp_path), backend="jsonl,csv",
                      experiment_key="k2")
    assert isinstance(multi, MultiSink)
    assert multi.experiment_key == "k2"
    with pytest.raises(ValueError, match="Unknown metrics backend"):
        make_sink(True, str(tmp_path), backend="comet")


def test_multi_sink_fans_out(tmp_path):
    multi = make_sink(True, str(tmp_path), backend="jsonl,csv")
    multi.log_metric("x", 1.5, step=0)
    multi.log_asset("a", "data")
    multi.close()
    assert os.path.exists(tmp_path / "metrics.jsonl")
    with open(tmp_path / "metrics.csv") as fh:
        assert len(list(csv.DictReader(fh))) == 1


def test_cli_threads_metrics_backend(tmp_path):
    from active_learning_tpu.experiment import cli

    ns = cli.get_parser().parse_args(
        ["--dataset", "synthetic", "--metrics_backend", "csv"])
    assert cli.args_to_config(ns).metrics_backend == "csv"


@pytest.mark.slow
def test_tensorboard_sink_writes_events(tmp_path):
    # The SummaryWriter import drags in TensorFlow (~80 s cold) — slow tier.
    pytest.importorskip("torch.utils.tensorboard")
    sink = make_sink(True, str(tmp_path), backend="tensorboard",
                     experiment_key="k3")
    assert "tensorboard" in SINK_BACKENDS
    sink.log_parameters({"rounds": 2})
    sink.log_metric("rd_test_accuracy", 0.25, step=1)
    sink.log_asset("idxs", "4,5")
    sink.close()
    tb_dir = tmp_path / "tb" / "k3"
    assert any(f.startswith("events.out") for f in os.listdir(tb_dir))
    with open(tmp_path / "assets" / "idxs.txt") as fh:
        assert fh.read() == "4,5"


def test_empty_backend_with_metrics_enabled_raises(tmp_path):
    with pytest.raises(ValueError, match="metrics_backend is empty"):
        make_sink(True, str(tmp_path), backend="")
    with pytest.raises(ValueError, match="metrics_backend is empty"):
        make_sink(True, str(tmp_path), backend=" , ")
