"""The device-truth layer (telemetry/profiler.py, DESIGN.md §11):
round-window selection (never round 0), the op-classification table,
the HLO collective-bytes table, capture summarisation, the merged
host+device timeline, the off-path inertness bound, the serve
``POST /v1/profile`` verb, the perf-regression gate
(scripts/perf_report.py), and the end-to-end CPU-mesh acceptance smoke
through the production CLI."""

import contextlib
import importlib.util
import json
import os
import subprocess
import sys
import time

import pytest

from active_learning_tpu.telemetry import profiler as prof
from active_learning_tpu.telemetry import spans as spans_lib

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestProfileRounds:
    def test_default_is_first_warm_round(self):
        for spec in (None, "", "  ", "warm"):
            rounds, rejected = prof.parse_profile_rounds(spec)
            assert rounds == (1,) and rejected == []

    def test_explicit_list_dedup_sorted(self):
        rounds, rejected = prof.parse_profile_rounds("3,1,3, 2")
        assert rounds == (1, 2, 3) and rejected == []

    def test_round_zero_and_junk_rejected_never_armed(self):
        rounds, rejected = prof.parse_profile_rounds("0,-2,x,1")
        assert rounds == (1,)
        assert 0 in rejected and -2 in rejected and "x" in rejected

    def test_round_profiler_never_captures_round_zero(self, tmp_path):
        # Even a RoundProfiler constructed WITH round 0 (bypassing the
        # parser) refuses it: the second lock on the same door.
        rp = prof.RoundProfiler(str(tmp_path), rounds=(0, 1))
        assert rp.should_capture(0) is False
        assert rp.should_capture(1) is True
        assert rp.should_capture(2) is False


class TestClassification:
    @pytest.mark.parametrize("name,cls", [
        ("all-reduce.1", "collective"),
        ("all-gather-start.2", "collective"),
        ("all-gather-done.2", "collective"),
        ("collective-permute.7", "collective"),
        ("reduce-scatter.3", "collective"),
        ("all-to-all", "collective"),
        ("copy.3", "transfer"),
        ("D2D Dispatch", "transfer"),
        ("infeed", "transfer"),
        ("h2d stream", "transfer"),
        ("ThreadpoolListener::Record", "infra"),
        ("ThunkExecutor::Execute (wait for completion)", "infra"),
        ("TfrtCpuBuffer::Await", "infra"),
        ("$builtins isinstance", "infra"),
        ("fusion.12", "compute"),
        ("dot.3", "compute"),
        ("reduce.8", "compute"),     # plain reduce is NOT a collective
        ("convolution.4", "compute"),
    ])
    def test_classify_table(self, name, cls):
        assert prof.classify_op(name) == cls

    def test_collective_primitive_and_async_done(self):
        assert prof.collective_primitive("all-reduce-start.17") \
            == "all-reduce"
        assert prof.collective_primitive("fusion.2") is None
        assert prof._is_async_done("all-gather-done.2") is True
        assert prof._is_async_done("all-gather-start.2") is False
        assert prof._is_async_done("all-gather.2") is False


class TestHloCollectiveBytes:
    def _write_dump(self, tmp_path, name, text):
        path = tmp_path / name
        path.write_text(text)
        return str(path)

    def test_bytes_from_after_optimizations_text(self, tmp_path):
        self._write_dump(
            tmp_path, "module_0001.jit_step.cpu_after_optimizations.txt",
            "HloModule jit_step, is_scheduled=true\n\n"
            "ENTRY %main {\n"
            "  %all-reduce.1 = f32[32,16]{1,0} all-reduce(f32[32,16]"
            "{1,0} %p), channel_id=1\n"
            "  ROOT %all-gather.3 = bf16[8,128]{1,0} all-gather(bf16"
            "[1,128]{1,0} %q), dimensions={0}\n"
            "  %all-reduce.2 = (f32[4]{0}, f32[8]{0}) all-reduce(...)\n"
            "  %reduce.9 = f32[32]{0} reduce(f32[8,32]{1,0} %r)\n"
            "}\n")
        table = prof.hlo_collective_bytes(str(tmp_path))
        assert table[("jit_step", "all-reduce.1")] == 32 * 16 * 4
        assert table[("jit_step", "all-gather.3")] == 8 * 128 * 2
        assert table[("jit_step", "all-reduce.2")] == 4 * 4 + 8 * 4
        # The plain reduce is compute, never in the byte table.
        assert not any(op == "reduce.9" for _, op in table)

    def test_async_start_collectives_attribute_bytes(self, tmp_path):
        """TPU's async lowering emits '-start'/'-done' pairs: the
        -start instruction (whose NAME the trace's hlo_op references)
        must land in the byte table, or every collective on the primary
        platform would read as unattributed."""
        self._write_dump(
            tmp_path, "module_0004.jit_tr.tpu_after_optimizations.txt",
            "HloModule jit_tr\n"
            "  %all-reduce-start.1 = f32[64]{0} all-reduce-start(f32"
            "[64]{0} %p), channel_id=5\n"
            "  %all-reduce-done.1 = f32[64]{0} all-reduce-done(%all-"
            "reduce-start.1)\n")
        table = prof.hlo_collective_bytes(str(tmp_path))
        assert table[("jit_tr", "all-reduce-start.1")] == 64 * 4
        # The -done half is a completion marker, not a second payload.
        assert ("jit_tr", "all-reduce-done.1") not in table

    def test_shape_bucket_collision_keeps_largest(self, tmp_path):
        body = ("HloModule jit_step\n"
                "  %all-reduce.1 = f32[{n},16]{{1,0}} all-reduce(%p)\n")
        self._write_dump(
            tmp_path, "module_0001.jit_step.cpu_after_optimizations.txt",
            body.format(n=8))
        self._write_dump(
            tmp_path, "module_0002.jit_step.cpu_after_optimizations.txt",
            body.format(n=64))
        table = prof.hlo_collective_bytes(str(tmp_path))
        # A bound, not a fabrication: the bucketed recompile's largest
        # shape wins the shared (module, op) key.
        assert table[("jit_step", "all-reduce.1")] == 64 * 16 * 4

    def test_missing_dir_is_empty_table(self, tmp_path):
        assert prof.hlo_collective_bytes(None) == {}
        assert prof.hlo_collective_bytes(str(tmp_path / "absent")) == {}


def _synth_trace():
    """A hand-built parsed trace: one TPU device plane (whose 'Steps'
    line must be excluded in favor of 'XLA Ops'), one CPU XLA thread,
    one python host thread (never a device track)."""
    processes = {1: "/device:TPU:0", 2: "/host:CPU"}
    threads = {(1, 10): "XLA Ops #1", (1, 11): "Steps",
               (2, 20): "tf_XLAEigen/7", (2, 21): "python"}

    def x(pid, tid, name, ts, dur, args=None):
        e = {"ph": "X", "pid": pid, "tid": tid, "name": name,
             "ts": float(ts), "dur": float(dur)}
        if args:
            e["args"] = args
        return e

    events = [
        x(1, 10, "all-reduce.1", 0, 200_000,
          {"hlo_module": "jit_step", "hlo_op": "all-reduce.1"}),
        x(1, 10, "all-reduce-done.1", 200_000, 50_000,
          {"hlo_module": "jit_step", "hlo_op": "all-reduce-done.1"}),
        x(1, 10, "fusion.2", 250_000, 250_000),
        x(2, 20, "copy.3", 100_000, 100_000),
        x(2, 20, "ThunkExecutor::Execute (wait)", 0, 900_000),  # infra
        x(1, 11, "train_step", 0, 1_000_000),  # Steps line: excluded
        x(2, 21, prof.ANCHOR_NAME, 1_000, 5),  # the re-basing anchor
        x(2, 21, "$builtins isinstance", 0, 10),
    ]
    return {"events": events, "processes": processes, "threads": threads}


class TestSummarize:
    def test_device_tracks_prefer_xla_ops_line(self):
        tracks = prof.device_tracks(_synth_trace())
        assert (1, 10) in tracks and (2, 20) in tracks
        assert (1, 11) not in tracks      # Steps double-counts XLA Ops
        assert (2, 21) not in tracks      # python is the HOST side

    def test_summary_fracs_counts_and_bytes(self):
        table = {("jit_step", "all-reduce.1"): 2048}
        s = prof.summarize_capture(_synth_trace(), window_s=1.0,
                                   byte_table=table)
        # Busy union over [0,250k],[250k,500k],[100k,200k] = 500k of 1s.
        assert s["device_busy_frac"] == pytest.approx(0.5)
        # Op time: collective 250k, compute 250k, transfer 100k.
        assert s["collective_frac"] == pytest.approx(250 / 600, abs=1e-3)
        assert s["transfer_frac"] == pytest.approx(100 / 600, abs=1e-3)
        ar = s["collectives"]["all-reduce"]
        # The -done half carries time but never a second count/payload.
        assert ar["count"] == 1
        assert ar["bytes"] == 2048
        assert s["collective_bytes_total"] == 2048
        assert s["collective_events_unattributed"] == 0

    def test_bytes_none_when_dump_absent_zero_when_no_collectives(self):
        s = prof.summarize_capture(_synth_trace(), window_s=1.0,
                                   byte_table={})
        # Collectives ran but the dump was not armed: counts measured,
        # bytes honestly unknown — never a guess.
        assert s["collectives"]["all-reduce"]["bytes"] is None
        assert s["collective_bytes_total"] is None
        assert s["collective_events_unattributed"] == 1
        quiet = {"events": [], "processes": {}, "threads": {}}
        s2 = prof.summarize_capture(quiet, window_s=1.0)
        assert s2["collective_bytes_total"] == 0


class TestMergedTimeline:
    def _handle(self):
        h = prof.CaptureHandle("/nowhere", "test")
        # Host clock: origin 0; window [2.0 s, 3.0 s]; the anchor was
        # emitted at 2.0 s and appears in the trace at ts=1000 µs.
        h.t0_pc, h.t1_pc, h.anchor_pc = 2.0, 3.0, 2.0
        return h

    def test_rebase_filter_and_metadata(self):
        events, dropped, alignment = prof.build_device_track_events(
            _synth_trace(), self._handle(), host_origin_pc=0.0)
        assert alignment == "anchor"
        xs = [e for e in events if e["ph"] == "X"]
        metas = [e for e in events if e["ph"] == "M"]
        # Infra and the excluded tracks never splice.
        assert all(e["args"]["class"] != "infra" for e in xs)
        assert {e["name"] for e in xs} == {"all-reduce.1",
                                           "all-reduce-done.1",
                                           "fusion.2", "copy.3"}
        # Exact re-base: trace ts 0 == anchor ts 1000 µs - 1000 µs ==
        # host 2.0 s - 1 ms.
        ar = next(e for e in xs if e["name"] == "all-reduce.1")
        assert ar["ts"] == pytest.approx(2.0e6 - 1000.0)
        # Every spliced op lies inside the window (± slack).
        for e in xs:
            assert 2.0e6 - 2e5 <= e["ts"] <= 3.0e6 + 2e5
        # Device tracks render under their own named processes, away
        # from any real pid.
        procs = [e for e in metas if e["name"] == "process_name"]
        assert procs and all(e["pid"] >= prof.DEVICE_PID_BASE
                             for e in procs)
        assert any("XLA device ops" in e["args"]["name"] for e in procs)
        assert dropped == 0

    def test_out_of_window_ops_drop_instead_of_ghost_tracks(self):
        trace = _synth_trace()
        trace["events"].append({"ph": "X", "pid": 2, "tid": 20,
                                "name": "dot.9", "ts": 9e7, "dur": 10.0})
        events, dropped, _ = prof.build_device_track_events(
            trace, self._handle(), host_origin_pc=0.0)
        assert dropped == 1
        assert all(e.get("name") != "dot.9" for e in events)

    def test_phase_device_attribution_intersects_host_spans(self):
        """Per-phase attribution: device ops clipped to the round's
        host phase spans — a phase with no device ops reads busy 0
        (the gap was HOST side), collective share is per-phase."""
        host = [
            {"ph": "X", "name": "train_time", "ts": 0.0,
             "dur": 1_000_000.0, "args": {"round": 1}},
            {"ph": "X", "name": "test_time", "ts": 1_000_000.0,
             "dur": 500_000.0, "args": {"round": 1}},
            # Another round's span never attributes this capture.
            {"ph": "X", "name": "train_time", "ts": 0.0,
             "dur": 9_000_000.0, "args": {"round": 0}},
        ]
        ops = [
            {"ph": "X", "name": "all-reduce.1", "ts": 100_000.0,
             "dur": 200_000.0, "args": {"class": "collective"}},
            {"ph": "X", "name": "fusion.2", "ts": 300_000.0,
             "dur": 300_000.0, "args": {"class": "compute"}},
            # Straddles the train/test boundary: split proportionally.
            {"ph": "X", "name": "copy.3", "ts": 900_000.0,
             "dur": 200_000.0, "args": {"class": "transfer"}},
        ]
        out = prof.phase_device_attribution(host, 1, ops)
        assert set(out) == {"train_time", "test_time"}
        tr = out["train_time"]
        # 200k + 300k + the copy's first 100k = 600k busy of 1s.
        assert tr["busy_frac"] == pytest.approx(0.6)
        assert tr["collective_frac"] == pytest.approx(200 / 600,
                                                      abs=1e-3)
        te = out["test_time"]
        assert te["busy_frac"] == pytest.approx(100_000 / 500_000)
        assert te["collective_frac"] == pytest.approx(0.0)

    def test_splice_into_tracer_merges_host_and_device(self, tmp_path):
        tracer = spans_lib.SpanTracer(enabled=True)
        with tracer.span("round", args={"round": 1}):
            time.sleep(0.001)
        h = prof.CaptureHandle("/nowhere", "test")
        h.t0_pc = tracer.origin + 2.0
        h.t1_pc = tracer.origin + 3.0
        h.anchor_pc = tracer.origin + 2.0
        stats, ops = prof.splice_into_tracer(tracer, _synth_trace(), h)
        assert stats["spliced_events"] > 0
        assert ops and all(e["ph"] == "X" for e in ops)
        path = str(tmp_path / "merged.json")
        tracer.export(path)
        out = json.load(open(path))
        cats = {e.get("cat") for e in out["traceEvents"]}
        assert "host" in cats and "device" in cats
        # A disabled tracer refuses the splice (recording is opt-in).
        off = spans_lib.SpanTracer(enabled=False)
        assert off.splice_events([{"ph": "M"}]) == 0


class TestOffPathInertness:
    def test_unarmed_round_scope_is_nanoseconds(self):
        """--profile_rounds unset => the driver's per-round hook is a
        None check returning a shared nullcontext: 100k rounds' worth
        of hook under 0.25 s (<2.5 µs/call — the same bound style as
        the telemetry-off and faults-disarmed paths)."""
        t0 = time.perf_counter()
        for rd in range(100_000):
            with prof.round_scope(None, rd):
                pass
        assert time.perf_counter() - t0 < 0.25

    def test_armed_profiler_round_zero_is_null_scope(self, tmp_path):
        rp = prof.RoundProfiler(str(tmp_path), rounds=(0, 1, 2))
        scope = prof.round_scope(rp, 0)
        assert isinstance(scope, contextlib.nullcontext().__class__)
        # ... and stays cheap: an armed profiler skipping a round must
        # not pay capture costs either.
        t0 = time.perf_counter()
        for _ in range(50_000):
            with prof.round_scope(rp, 0):
                pass
        assert time.perf_counter() - t0 < 0.25


class TestCaptureWindowGate:
    def test_one_window_at_a_time_and_artifacts(self, tmp_path):
        import jax
        import jax.numpy as jnp

        f = jax.jit(lambda x: (x @ x).sum())
        x = jnp.ones((64, 64))
        f(x).block_until_ready()
        out = str(tmp_path / "cap")
        with prof.capture_window(out) as handle:
            with pytest.raises(prof.CaptureBusyError):
                prof.start_capture(str(tmp_path / "other"))
            f(x).block_until_ready()
        assert handle.window_s and handle.window_s > 0
        trace_path = prof.find_trace_file(out)
        assert trace_path and trace_path.endswith(".trace.json.gz")
        trace = prof.parse_trace(trace_path)
        assert trace["events"]
        # The anchor annotation really landed (exact re-basing works).
        assert any(e.get("name") == prof.ANCHOR_NAME
                   for e in trace["events"])

    def test_window_closes_on_exception(self, tmp_path):
        with pytest.raises(RuntimeError, match="boom"):
            with prof.capture_window(str(tmp_path / "a")):
                raise RuntimeError("boom")
        # The global gate released: a fresh window opens cleanly.
        with prof.capture_window(str(tmp_path / "b")):
            pass


class TestServeProfileVerb:
    def _server(self):
        import threading

        from active_learning_tpu.config import ServeConfig
        from active_learning_tpu.serve.server import ScoringServer

        class StubExecutor:
            _lock = threading.Lock()
            stats = {"batches": 0, "rows": 0, "reloads": 0}
            served_round = 1

            def compile_counts(self):
                return {}

            def request_path_compiles(self):
                return 0

        class StubBatcher:
            pending_rows = 0
            buckets = (8,)

        server = ScoringServer(StubExecutor(), ServeConfig())
        server.batcher = StubBatcher()
        return server

    def test_profile_verb_returns_summary(self):
        import asyncio

        server = self._server()
        body = json.dumps({"seconds": 0.1}).encode()
        status, payload, _ = asyncio.run(
            server._route("POST", "/v1/profile", body))
        assert status == 200, payload
        assert payload["ok"] is True
        assert "device_busy_frac" in payload
        assert "collectives" in payload
        # Artifacts land in a SERVER-chosen dir named in the response.
        assert payload["out_dir"].startswith("/")
        assert os.path.exists(payload["summary_path"])

    def test_profile_verb_bad_requests_are_400(self):
        import asyncio

        server = self._server()
        for bad in ({"seconds": "fast"}, {"seconds": -1},
                    {"seconds": True},
                    # A client-chosen output path is refused outright:
                    # no remote filesystem-write primitive.
                    {"seconds": 0.1, "dir": "/etc/anywhere"}):
            status, payload, _ = asyncio.run(server._route(
                "POST", "/v1/profile", json.dumps(bad).encode()))
            assert status == 400, (bad, payload)

    def test_concurrent_capture_is_409(self, tmp_path):
        import asyncio

        server = self._server()
        handle = prof.start_capture(str(tmp_path / "held"))
        try:
            status, payload, _ = asyncio.run(server._route(
                "POST", "/v1/profile",
                json.dumps({"seconds": 0.05}).encode()))
            assert status == 409, payload
        finally:
            prof.finish_capture(handle)


class TestPerfReport:
    def test_real_trajectory_renders_and_exits_zero(self, capsys):
        pr = _load_script("perf_report")
        rc = pr.main([])
        out = capsys.readouterr().out
        assert rc == 0
        # Every salvageable round renders; the dead ones show as
        # explicit skips, never KeyErrors.
        assert "r05" in out and "skipped" in out
        assert "al_round_imagenet warm_s" in out

    def test_degraded_compact_line_only_json_is_salvaged(self, tmp_path):
        pr = _load_script("perf_report")
        compact = {"metric": "m", "value": 1.0, "phases": {
            "al_round_cifar": {"ips": 400.0, "warm_s": 22.0,
                               "cached": True}}}
        wrapper = {"n": 7, "rc": 0, "parsed": None,
                   "tail": "noise\n" + json.dumps(compact) + "\n"}
        path = tmp_path / "BENCH_r07.json"
        path.write_text(json.dumps(wrapper))
        series = pr.load_series([str(path)])
        assert series[0]["phases"]["al_round_cifar"]["warm_s"] == 22.0
        assert "tail" in series[0]["note"]

    def test_schema_drift_aliases_resolve(self, tmp_path):
        pr = _load_script("perf_report")
        old = {"phases": {
            # Full-evidence shape: total ips + n_chips, old warm keys.
            "imagenet_datapath": {"ips": 697.2, "ips_per_chip": 348.6,
                                  "n_chips": 2, "ips_warm": 157.7},
            "al_round_cifar": {"ips": 830.0, "n_chips": 2,
                               "round_sec_warm": 22.59,
                               "round_sec_cold": 80.47,
                               "test_accuracy_rd1": 0.6}}}
        new = {"phases": {
            "imagenet_datapath": {"ips": 350.0,
                                  "warm_memmap_ips": 160.0}}}
        a, b = tmp_path / "a.json", tmp_path / "b.json"
        a.write_text(json.dumps(old))
        b.write_text(json.dumps(new))
        series = pr.load_series([str(a), str(b)])
        dp0 = series[0]["phases"]["imagenet_datapath"]
        assert dp0["warm_ips"] == 157.7          # ips_warm alias
        assert dp0["ips_per_chip"] == 348.6
        rd0 = series[0]["phases"]["al_round_cifar"]
        assert rd0["warm_s"] == 22.59 and rd0["cold_s"] == 80.47
        assert rd0["ips_per_chip"] == pytest.approx(415.0)  # ips/n_chips
        assert series[1]["phases"]["imagenet_datapath"][
            "warm_ips"] == 160.0                 # canonical spelling

    def test_regression_gate_trips_and_passes(self, tmp_path, capsys):
        pr = _load_script("perf_report")
        base = tmp_path / "base.json"
        base.write_text(json.dumps({"phases": {
            "al_round_cifar": {"ips": 400.0, "warm_s": 20.0},
            "resnet18_cifar_train": {"ips": 20_000.0}}}))
        ok = tmp_path / "ok.json"
        ok.write_text(json.dumps({"phases": {
            "al_round_cifar": {"ips": 390.0, "warm_s": 21.0},
            "resnet18_cifar_train": {"ips": 19_000.0}}}))
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps({"phases": {
            "al_round_cifar": {"ips": 200.0, "warm_s": 30.0},
            "resnet18_cifar_train": {"ips": 12_000.0}}}))
        assert pr.main([str(base), str(ok)]) == 0
        capsys.readouterr()
        assert pr.main([str(base), str(bad)]) == 1
        err = capsys.readouterr().err
        assert "REGRESSION al_round_cifar warm_s" in err
        assert "REGRESSION resnet18_cifar_train ips_per_chip" in err
        # A phase the latest round simply did not capture is absence,
        # not regression (the flaky-tunnel rule).
        missing = tmp_path / "missing.json"
        missing.write_text(json.dumps({"phases": {
            "kcenter_select": {"ips": 500.0}}}))
        assert pr.main([str(base), str(missing)]) == 0

    def test_first_capture_is_baseline_not_regression(self, tmp_path):
        pr = _load_script("perf_report")
        only = tmp_path / "only.json"
        only.write_text(json.dumps({"phases": {
            "al_round_cifar": {"ips": 1.0, "warm_s": 9999.0}}}))
        assert pr.main([str(only)]) == 0

    def test_unusable_current_is_loud_exit_3_not_silent_ok(self,
                                                          tmp_path,
                                                          capsys):
        """The gate asked to judge THIS run must not substitute history
        as 'latest' when the current file is unreadable or carries no
        phases: distinct exit 3, never a silent ok or a history-vs-
        itself verdict."""
        pr = _load_script("perf_report")
        base = tmp_path / "base.json"
        base.write_text(json.dumps({"phases": {
            "al_round_cifar": {"ips": 400.0, "warm_s": 20.0}}}))
        empty = tmp_path / "empty_evidence.json"
        empty.write_text(json.dumps({"phases": {}}))
        assert pr.main([str(base), "--current", str(empty)]) == 3
        assert "NO-EVIDENCE" in capsys.readouterr().err
        assert pr.main([str(base), "--current",
                        str(tmp_path / "absent.json")]) == 3
        # The same file as a plain HISTORICAL entry stays a skip-with-
        # note, not an error — only the explicit --current is gated.
        assert pr.main([str(base), str(empty)]) == 0


class TestEndToEndDeviceTruth:
    """The acceptance criteria, pinned through the PRODUCTION CLI in a
    fresh subprocess (the HLO byte-table dump can only arm before
    backend init): one merged Chrome trace carrying host spans AND
    device-op events on named tracks, device_busy_frac /
    collective_bytes_total in metrics.jsonl AND the Prometheus scrape
    file for the profiled round, no capture for round 0, and the
    scrape-file completeness contract (PER_ROUND_GAUGES)."""

    @pytest.fixture(scope="class")
    def smoke(self, tmp_path_factory):
        tmp = str(tmp_path_factory.mktemp("device_truth"))
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        if "xla_force_host_platform_device_count" not in env.get(
                "XLA_FLAGS", ""):
            env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                                + " --xla_force_host_platform_device_"
                                  "count=8").strip()
        cmd = [sys.executable, "-m", "active_learning_tpu",
               "--dataset", "synthetic", "--arg_pool", "synthetic",
               "--strategy", "MarginSampler", "--rounds", "2",
               "--round_budget", "16", "--n_epoch", "2",
               "--early_stop_patience", "2", "--log_dir", tmp,
               "--ckpt_path", tmp, "--exp_hash", "devtruth",
               "--export_trace", "--profile_rounds", "1",
               "--prometheus_file", os.path.join(tmp, "run.prom")]
        proc = subprocess.run(cmd, cwd=REPO, env=env, text=True,
                              capture_output=True, timeout=540)
        assert proc.returncode == 0, proc.stderr[-4000:]
        return tmp

    def test_merged_trace_has_host_and_device_tracks(self, smoke):
        trace = json.load(open(os.path.join(smoke, "trace.json")))
        events = trace["traceEvents"]
        host = [e for e in events
                if e.get("ph") == "X" and e.get("cat") == "host"]
        device = [e for e in events
                  if e.get("ph") == "X" and e.get("cat") == "device"]
        assert host and device
        # Named device tracks, on their own synthetic pids.
        procs = {e["pid"]: e["args"]["name"] for e in events
                 if e.get("ph") == "M" and e["name"] == "process_name"}
        dev_procs = [n for n in procs.values()
                     if n.startswith("XLA device ops")]
        assert dev_procs
        # Device ops land INSIDE the profiled round's host span.
        r1 = next(e for e in host if e["name"] == "round"
                  and (e.get("args") or {}).get("round") == 1)
        slack = 2e5
        inside = [e for e in device
                  if r1["ts"] - slack <= e["ts"]
                  <= r1["ts"] + r1["dur"] + slack]
        assert len(inside) == len(device)
        # Every spliced op is classified; collectives are present (the
        # 8-device mesh psums gradients every step).
        classes = {(e.get("args") or {}).get("class") for e in device}
        assert "collective" in classes and "compute" in classes
        assert "infra" not in classes

    def test_round0_never_captures(self, smoke):
        profile_dir = os.path.join(smoke, "profile")
        assert os.path.isdir(os.path.join(profile_dir, "round_1"))
        assert not os.path.exists(os.path.join(profile_dir, "round_0"))

    def test_summary_and_measured_bytes(self, smoke):
        path = os.path.join(smoke, "profile", "round_1",
                            "device_profile_rd1.json")
        summary = json.load(open(path))
        assert summary["round"] == 1
        assert 0 < summary["device_busy_frac"] <= 1
        assert summary["collective_frac"] > 0
        # The fresh-subprocess dump armed, so the bytes are MEASURED
        # (counts from the trace x exact HLO payload shapes).
        assert summary["byte_table_entries"] > 0
        assert summary["collective_bytes_total"] > 0
        assert summary["collectives"].get("all-reduce", {}).get(
            "count", 0) > 0
        # Per-phase attribution against the round's host spans: the
        # train phase dominates a synthetic round, and it shows device
        # work (gradient psums at minimum).
        attribution = summary["phase_attribution"]
        assert "train_time" in attribution
        assert attribution["train_time"]["busy_frac"] > 0

    def test_device_metrics_in_jsonl_and_scrape(self, smoke):
        from active_learning_tpu.experiment.driver import PER_ROUND_GAUGES
        from active_learning_tpu.telemetry import prom as prom_lib

        by_name = {}
        for line in open(os.path.join(smoke, "metrics.jsonl")):
            ev = json.loads(line)
            if ev.get("kind") == "metric":
                for k, v in ev["metrics"].items():
                    by_name.setdefault(k, []).append((ev.get("step"), v))
        for name in ("device_busy_frac", "collective_frac",
                     "collective_bytes_total"):
            assert name in by_name, f"missing {name}"
            steps = [s for s, _ in by_name[name]]
            assert steps == [1], f"{name} emitted at {steps}, not the " \
                                 "profiled round only"
        assert by_name["collective_bytes_total"][0][1] > 0
        parsed = prom_lib.parse(
            open(os.path.join(smoke, "run.prom")).read())
        # The completeness contract: every per-round driver metric that
        # reached the sink also rides the scrape file.
        for name in PER_ROUND_GAUGES:
            if name in by_name:
                assert f"al_run_{name}" in parsed, \
                    f"{name} in metrics.jsonl but not the scrape file"
        for name in ("device_busy_frac", "collective_bytes_total",
                     "span_events_dropped"):
            assert f"al_run_{name}" in parsed
        assert parsed["al_run_span_events_dropped"][()] == 0

    def test_status_renders_pipeline_health_tail(self, smoke):
        """Satellite: overlap_frac / round_vs_max_phase (and
        spec_hit_frac when a speculation hit occurred) in the status
        CLI's rendered metrics tail."""
        from active_learning_tpu.telemetry import status as status_lib

        summary = status_lib.summarize(smoke)
        text = status_lib.render_text(summary)
        assert "overlap_frac" in text
        assert "round_vs_max_phase" in text
