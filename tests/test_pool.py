"""Tests for PoolState invariants (the reference's runtime asserts at
src/query_strategies/strategy.py:470 and the duplicate-query asserts become
real tests here, per SURVEY.md §4)."""

import numpy as np
import pytest

from active_learning_tpu.pool import PoolState


def make_pool(n=20, eval_idxs=(15, 16, 17)):
    return PoolState.create(n, np.array(eval_idxs))


def test_initial_state():
    p = make_pool()
    assert p.num_labeled == 0
    assert p.num_available == 17  # 20 - 3 eval
    assert p.cumulative_cost == 0


def test_update_marks_labeled_and_cost():
    p = make_pool()
    p.update([0, 1, 2], cost=3)
    assert p.num_labeled == 3
    assert p.cumulative_cost == 3
    assert set(p.recent.tolist()) == {0, 1, 2}
    assert not p.available_mask()[[0, 1, 2]].any()


def test_update_rejects_double_labeling():
    p = make_pool()
    p.update([0, 1], cost=2)
    with pytest.raises(ValueError, match="already labeled"):
        p.update([1, 2], cost=2)


def test_update_rejects_duplicates():
    p = make_pool()
    with pytest.raises(ValueError, match="duplicate"):
        p.update([3, 3], cost=2)


def test_update_rejects_out_of_range():
    p = make_pool()
    with pytest.raises(ValueError, match="out of range"):
        p.update([-5], cost=1)
    with pytest.raises(ValueError, match="out of range"):
        p.update([20], cost=1)


def test_snapshot_does_not_alias_live_state():
    p = make_pool()
    snap = PoolState.from_arrays(p.to_arrays())
    p.update([5], cost=1)
    assert not snap.labeled[5]


def test_update_rejects_eval_idxs():
    p = make_pool()
    with pytest.raises(ValueError, match="validation"):
        p.update([15], cost=1)


def test_available_excludes_eval_and_labeled():
    p = make_pool()
    p.update([0, 5], cost=2)
    avail = p.available_query_idxs(shuffle=False)
    assert 0 not in avail and 5 not in avail
    assert 15 not in avail and 16 not in avail
    assert len(avail) == 15


def test_shuffle_is_seeded():
    p = make_pool()
    a = p.available_query_idxs(shuffle=True, rng=np.random.default_rng(1))
    b = p.available_query_idxs(shuffle=True, rng=np.random.default_rng(1))
    np.testing.assert_array_equal(a, b)


def test_round_trip_serialization():
    p = make_pool()
    p.update([0, 1], cost=2)
    p.round = 3
    q = PoolState.from_arrays(p.to_arrays())
    assert q.round == 3
    assert q.cumulative_cost == 2
    np.testing.assert_array_equal(q.labeled, p.labeled)
    np.testing.assert_array_equal(q.eval_idxs, p.eval_idxs)
