"""End-to-end LEARNING check: a multi-round AL experiment must get
measurably better at the task round over round.

The mechanics suite proves the loop runs (pool grows, metrics emit,
checkpoints land); the multichip dryrun proves one fit optimizes.  This
pins the composite: query -> update -> re-init -> train -> test, three
times, must raise test accuracy well above both chance and the round-0
model — a regression anywhere in acquisition scoring, pool bookkeeping,
checkpoint reload, or the train/eval loop shows up here as a flat curve.
(The reference has no equivalent; its only end-to-end path is the
--debug_mode smoke, src/utils/parser.py:70-71.)
"""

import jax
import numpy as np
import pytest

from active_learning_tpu.config import (ExperimentConfig, LoaderConfig,
                                        OptimizerConfig, SchedulerConfig,
                                        TrainConfig)
from active_learning_tpu.data.synthetic import get_data_synthetic
from active_learning_tpu.experiment.driver import run_experiment
from active_learning_tpu.utils.metrics import NullSink

from helpers import TinyClassifier

pytestmark = pytest.mark.slow


def test_accuracy_rises_across_rounds(tmp_path):
    data = get_data_synthetic(n_train=1024, n_test=256, num_classes=4,
                              image_size=16, seed=3)
    train_cfg = TrainConfig(
        eval_split=0.05,
        loader_tr=LoaderConfig(batch_size=32),
        loader_te=LoaderConfig(batch_size=64),
        optimizer=OptimizerConfig(name="sgd", lr=0.05),
        scheduler=SchedulerConfig(name="cosine", t_max=4),
    )
    cfg = ExperimentConfig(
        dataset="synthetic", strategy="MarginSampler", rounds=3,
        round_budget=96, init_pool_size=96, model="tiny", n_epoch=4,
        early_stop_patience=0, exp_hash="curve",
        log_dir=str(tmp_path / "logs"), ckpt_path=str(tmp_path / "ckpt"))

    class CurveSink(NullSink):
        experiment_key = "curve"

        def __init__(self):
            self.acc = {}

        def log_metrics(self, metrics, step=None):
            for k, v in metrics.items():
                if k == "rd_test_accuracy":
                    self.acc[int(step)] = float(v)

    sink = CurveSink()
    run_experiment(cfg, sink=sink, data=data, train_cfg=train_cfg,
                   model=TinyClassifier(num_classes=4))
    assert sorted(sink.acc) == [0, 1, 2]
    # Labeled set triples from round 0 to round 2 (96 -> 288) on a
    # trivially separable dataset: the final model must beat chance
    # (0.25) decisively AND beat the round-0 model.
    assert sink.acc[2] > 0.5, sink.acc
    assert sink.acc[2] > sink.acc[0], sink.acc
