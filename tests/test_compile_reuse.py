"""Recompile-count regression tests for the shape-bucketing scheme.

The AL round loop's shapes drift every round — the labeled set grows, a
subset-capped selection pool shrinks — and every drifted shape is a
fresh XLA compile unless it is bucketed away (pool.bucket_size).  These
tests pin the contract: two consecutive rounds whose sizes stay inside
one bucket trigger ZERO new jit compilations, measured directly off the
jitted functions' compilation caches.
"""

import numpy as np
import pytest

import jax

from active_learning_tpu.pool import bucket_size


def _cache_size(jitted) -> int:
    return jitted._cache_size()


class TestBucketSize:
    def test_values(self):
        assert bucket_size(1, floor=16) == 16
        assert bucket_size(16, floor=16) == 16
        assert bucket_size(17, floor=16) == 32
        assert bucket_size(300) == 512
        assert bucket_size(512) == 512
        # 1/8-octave granularity, NOT pure pow2: past a boundary the
        # bucket grows by the granule (256 here), not by doubling —
        # padded rows/steps still execute, so waste must stay bounded.
        assert bucket_size(513) == 768
        assert bucket_size(130000) == 131072

    def test_monotone_and_bounded_waste(self):
        for n in (1, 7, 255, 256, 1000, 4097, 70000, 130000):
            b = bucket_size(n)
            assert b >= n and b >= 256
            if n > 256:
                # Recurring-compute waste cap: granule is 1/8 of the
                # enclosing pow2, so padding < ~14% of n.
                assert b - n < max(256, b // 4)
                assert b < 2 * max(n, 256)


class TestKCenterCompileReuse:
    def _run(self, n, n_labeled, budget, seed=0, batch_q=8):
        from active_learning_tpu.strategies.kcenter import kcenter_greedy
        rng = np.random.default_rng(seed)
        emb = rng.normal(size=(n, 24)).astype(np.float32)
        labeled = np.zeros(n, dtype=bool)
        labeled[rng.choice(n, n_labeled, replace=False)] = True
        picks = kcenter_greedy((emb,), labeled, budget,
                               rng=np.random.default_rng(1),
                               batch_q=batch_q)
        assert len(picks) == budget

    def test_grown_pool_same_bucket_zero_new_compiles(self):
        """Round N -> N+1 with a drifted pool size and a grown labeled
        set, both inside one power-of-two bucket: the selection scan AND
        the chunked initial-min pass reuse their executables."""
        from active_learning_tpu.strategies import kcenter as kc

        self._run(300, 20, 10)  # pool bucket 512, warm
        scan = _cache_size(kc._kcenter_scan_batched)
        chunk = _cache_size(kc._min_dist_chunk)
        self._run(340, 50, 10, seed=5)  # grown; same 512 bucket
        assert _cache_size(kc._kcenter_scan_batched) == scan
        assert _cache_size(kc._min_dist_chunk) == chunk

    def test_bucket_boundary_recompiles_once(self):
        from active_learning_tpu.strategies import kcenter as kc

        self._run(300, 20, 10)
        scan = _cache_size(kc._kcenter_scan_batched)
        self._run(600, 20, 10, seed=6)  # crosses into the 1024 bucket
        assert _cache_size(kc._kcenter_scan_batched) == scan + 1


class TestShardedKCenterCompileReuse:
    """The row-sharded selection backend under the same bucket contract:
    warm AL rounds (drifted pool size, grown labeled set, same bucket)
    add ZERO compiles to the per-mesh sharded executables."""

    def _run(self, mesh, n, n_labeled, budget, seed=0, batch_q=8):
        from active_learning_tpu.strategies import kcenter as kc
        rng = np.random.default_rng(seed)
        emb = rng.normal(size=(n, 24)).astype(np.float32)
        labeled = np.zeros(n, dtype=bool)
        labeled[rng.choice(n, n_labeled, replace=False)] = True
        picks = kc.kcenter_greedy((emb,), labeled, budget,
                                  rng=np.random.default_rng(1),
                                  batch_q=batch_q, mesh=mesh,
                                  pool_sharding="row")
        assert kc.LAST_SHARDING == "row"
        assert len(picks) == budget

    def test_grown_pool_same_bucket_zero_new_compiles(self):
        from active_learning_tpu.parallel import mesh as mesh_lib
        from active_learning_tpu.strategies import kcenter as kc

        mesh = mesh_lib.make_mesh()
        self._run(mesh, 300, 20, 10)  # pool bucket 512, warm
        fns = kc._SHARDED_JITS[(mesh, 1)]
        sizes = {k: _cache_size(v) for k, v in fns.items()}
        self._run(mesh, 340, 50, 10, seed=5)  # grown; same 512 bucket
        assert {k: _cache_size(v) for k, v in fns.items()} == sizes

    def test_bucket_boundary_recompiles_scan_once(self):
        from active_learning_tpu.parallel import mesh as mesh_lib
        from active_learning_tpu.strategies import kcenter as kc

        mesh = mesh_lib.make_mesh()
        self._run(mesh, 300, 20, 10)
        fns = kc._SHARDED_JITS[(mesh, 1)]
        scan = _cache_size(fns["scan_batched"])
        self._run(mesh, 600, 20, 10, seed=6)  # crosses into 1024
        assert _cache_size(fns["scan_batched"]) == scan + 1


class TestEpochScanCompileReuse:
    def test_two_rounds_grown_labeled_zero_new_compiles(self):
        """The device-resident epoch scan across two AL 'rounds' whose
        labeled sets differ but land in the same step bucket compiles
        exactly once."""
        from helpers import TinyClassifier, tiny_train_config
        from active_learning_tpu.data.synthetic import get_data_synthetic
        from active_learning_tpu.parallel import mesh as mesh_lib
        from active_learning_tpu.train.trainer import Trainer
        import dataclasses

        train_set, _, al_set = get_data_synthetic(n_train=96, n_test=16)
        cfg = dataclasses.replace(tiny_train_config(batch_size=16),
                                  device_resident=True)
        mesh = mesh_lib.make_mesh()
        trainer = Trainer(TinyClassifier(), cfg, mesh, 4)

        def fit_round(n_labeled, seed):
            # Fresh state per round, as the driver's init_network_weights
            # does (the fitted state's buffers are donated into the scan).
            state = trainer.init_state(jax.random.PRNGKey(seed),
                                       train_set.gather(np.arange(2)))
            rng = np.random.default_rng(seed)
            labeled = np.sort(rng.choice(96, n_labeled, replace=False))
            return trainer.fit(state, train_set, labeled, al_set,
                               np.arange(90, 96), n_epoch=2, es_patience=0,
                               rng=rng, round_idx=0)

        fit_round(24, 0)  # 2 steps of 16 -> the 16-step floor bucket
        assert trainer._epoch_scan is not None
        scans = _cache_size(trainer._epoch_scan)
        steps = _cache_size(trainer._train_step)
        fit_round(60, 1)  # grown labeled set, 4 steps -> same bucket
        assert _cache_size(trainer._epoch_scan) == scans
        assert _cache_size(trainer._train_step) == steps

    def test_bucket_steps_rule(self):
        from active_learning_tpu.train.trainer import Trainer

        assert Trainer.bucket_steps(1) == Trainer.STEP_BUCKET
        assert Trainer.bucket_steps(16) == 16
        assert Trainer.bucket_steps(17) == 32
        assert Trainer.bucket_steps(33) == 48
        assert Trainer.bucket_steps(64) == 64
        # The case the pure-pow2 rule got wrong: 157 steps must not pay
        # 99 masked-but-executed train steps per epoch (256), only 3.
        assert Trainer.bucket_steps(157) == 160


class TestShardedFeedCompileReuse:
    def test_warm_rounds_on_row_sharded_feed_zero_new_compiles(self):
        """Warm AL rounds under row sharding add zero XLA compiles: the
        pool entry (constant shape) and the sharded per-batch step are
        both reused round over round — the jit-cache delta invariant of
        test_telemetry, pinned directly on the executables here."""
        import dataclasses
        from helpers import TinyClassifier, tiny_train_config
        from active_learning_tpu.data.synthetic import get_data_synthetic
        from active_learning_tpu.parallel import mesh as mesh_lib
        from active_learning_tpu.parallel import resident as resident_lib
        from active_learning_tpu.train.trainer import Trainer

        train_set, _, al_set = get_data_synthetic(n_train=96, n_test=16)
        cfg = dataclasses.replace(tiny_train_config(batch_size=16),
                                  train_feed="resident",
                                  pool_sharding="row")
        mesh = mesh_lib.make_mesh()
        trainer = Trainer(TinyClassifier(), cfg, mesh, 4)
        assert trainer.pool_sharding == "row"

        def fit_round(n_labeled, seed):
            state = trainer.init_state(jax.random.PRNGKey(seed),
                                       train_set.gather(np.arange(2)))
            rng = np.random.default_rng(seed)
            labeled = np.sort(rng.choice(96, n_labeled, replace=False))
            return trainer.fit(state, train_set, labeled, al_set,
                               np.arange(90, 96), n_epoch=2,
                               es_patience=0, rng=rng)

        fit_round(24, 0)  # round N: pins the pool, compiles the step
        assert trainer.last_feed["source"] == "resident"
        assert resident_lib.pinned_bytes(trainer.resident_pool) > 0
        step = _cache_size(trainer._resident_batch_step)
        entries = len(trainer.resident_pool["images"])
        fit_round(60, 1)  # round N+1: grown labeled set, same pool
        assert trainer.last_feed["source"] == "resident"
        assert _cache_size(trainer._resident_batch_step) == step
        assert len(trainer.resident_pool["images"]) == entries


class TestResidentBudgetDemotion:
    def test_mid_run_shrink_demotes_cleanly_without_recompile(self):
        """Budget-sharing: shrinking the resident budget mid-run demotes
        the pinned pool LRU-first (parallel/resident.enforce_budget) and
        the NEXT fit falls back to the host feed — with no batch-shape
        change and ZERO new XLA compiles, because the host step was
        already compiled at the same bucketed shapes."""
        import dataclasses
        from helpers import TinyClassifier, tiny_train_config
        from active_learning_tpu.data.synthetic import get_data_synthetic
        from active_learning_tpu.parallel import mesh as mesh_lib
        from active_learning_tpu.parallel import resident as resident_lib
        from active_learning_tpu.train.trainer import Trainer

        train_set, _, al_set = get_data_synthetic(n_train=96, n_test=16)
        cfg = dataclasses.replace(tiny_train_config(batch_size=16),
                                  train_feed="auto", device_resident=None)
        mesh = mesh_lib.make_mesh()
        trainer = Trainer(TinyClassifier(), cfg, mesh, 4)

        def fit_round(seed, feed=None):
            c = cfg if feed is None else dataclasses.replace(
                cfg, train_feed=feed)
            trainer.cfg = c
            state = trainer.init_state(jax.random.PRNGKey(seed),
                                       train_set.gather(np.arange(2)))
            rng = np.random.default_rng(seed)
            labeled = np.sort(rng.choice(96, 60, replace=False))
            return trainer.fit(state, train_set, labeled, al_set,
                               np.arange(90, 96), n_epoch=2,
                               es_patience=0, rng=rng)

        fit_round(0, feed="host")      # warm the host step's executable
        fit_round(1, feed="resident")  # pin + warm the resident step
        assert trainer.last_feed["source"] == "resident"
        assert resident_lib.pinned_bytes(trainer.resident_pool) > 0
        chained = _cache_size(trainer._chained_train_step)
        resident_step = _cache_size(trainer._resident_batch_step)

        demoted = trainer.set_resident_budget(1)  # mid-run shrink
        assert demoted and not trainer.resident_pool.get("images")

        fit_round(2)  # auto now resolves down the hierarchy
        assert trainer.last_feed["source"].startswith("host")
        # No shape change, no recompile: both executables' caches are
        # exactly where the warm-up left them.
        assert _cache_size(trainer._chained_train_step) == chained
        assert _cache_size(trainer._resident_batch_step) == resident_step

    def test_shared_budget_accounting_and_lru_order(self):
        """eligible() charges the WHOLE cache against one budget, the
        al/train views' shared storage counts once, and eviction walks
        least-recently-used first."""
        from active_learning_tpu.data.synthetic import get_data_synthetic
        from active_learning_tpu.parallel import mesh as mesh_lib
        from active_learning_tpu.parallel import resident as resident_lib

        train_set, test_set, al_set = get_data_synthetic(
            n_train=64, n_test=64, num_classes=4, image_size=8)
        mesh = mesh_lib.make_mesh()
        cache = {}
        resident_lib.pool_arrays(cache, al_set, mesh)
        one = resident_lib.pinned_bytes(cache)
        assert one == al_set.images[:64].nbytes
        # The train view shares storage: same entry, same bytes.
        resident_lib.pool_arrays(cache, train_set, mesh)
        assert resident_lib.pinned_bytes(cache) == one
        # A second array is only eligible if it fits ALONGSIDE the first.
        assert resident_lib.eligible(test_set, 2 * one, cache=cache)
        assert not resident_lib.eligible(test_set, one + 1, cache=cache)
        # An already-pinned pool stays eligible under any budget.
        assert resident_lib.eligible(al_set, 1, cache=cache)
        resident_lib.pool_arrays(cache, test_set, mesh)
        # Touch the al pool so the TEST set is now least-recently-used.
        resident_lib.pool_arrays(cache, al_set, mesh)
        demoted = resident_lib.enforce_budget(cache, one)
        assert demoted == [(id(test_set.images), 64)]
        assert resident_lib.cached(cache, al_set)
        assert not resident_lib.cached(cache, test_set)

    def test_auto_budget_adds_pinned_back_as_total_cap(self):
        """A live-headroom auto budget has already-pinned pools netted
        OUT of bytes_in_use's headroom; the shared eligible() accounting
        charges them against the budget as a TOTAL cap, so the refresh
        must add them back — otherwise every pinned pool is billed
        twice and a second pool that actually fits gets rejected."""
        from active_learning_tpu.data.synthetic import get_data_synthetic
        from active_learning_tpu.parallel import mesh as mesh_lib
        from active_learning_tpu.parallel import resident as resident_lib

        _, test_set, al_set = get_data_synthetic(
            n_train=64, n_test=64, num_classes=4, image_size=8)
        cache = {}
        resident_lib.pool_arrays(cache, al_set, mesh_lib.make_mesh())
        pinned = resident_lib.pinned_bytes(cache)
        need = test_set.images[:64].nbytes
        reserve = resident_lib.AUTO_RESERVE_BYTES
        # Live stats where headroom (net of the pinned pool) covers the
        # second pool exactly: bytes_in_use INCLUDES the pinned bytes.
        stats = {"bytes_limit": reserve + pinned + need + 1024,
                 "bytes_in_use": pinned}
        budget = resident_lib.resolve_budget(None, stats=stats,
                                             cache=cache)
        # Total cap = headroom + pinned, so the second pool is eligible
        # alongside the first under the shared accounting.
        assert budget == need + 1024 + pinned
        assert resident_lib.eligible(test_set, budget, cache=cache)
        # Without the add-back the same scenario double-counts and
        # rejects it.
        assert not resident_lib.eligible(
            test_set, resident_lib.resolve_budget(None, stats=stats),
            cache=cache)


class TestPipelinedRoundCompileReuse:
    def test_warm_pipelined_rounds_zero_new_compiles(self, tmp_path):
        """The pipelined round's compile-freeness (DESIGN.md §8): the
        speculative scorer dispatches THE SAME jitted score step the
        sequential query uses (over batch-constant chunk shapes), and
        the select-time prefetch pre-builds the very execution form the
        fit would build — so warm pipelined rounds add ZERO compiles.
        3 rounds so round 1 is a fully-warm ARMING round: it consumes
        round 0's speculation, runs the scorer through its own fit, and
        prefetches round 2's feed — the whole pipeline surface, jit
        delta 0 (the same registry-counted metric the production driver
        exports)."""
        import json
        import os

        from active_learning_tpu.config import (ExperimentConfig,
                                                TelemetryConfig)
        from active_learning_tpu.data.synthetic import get_data_synthetic
        from active_learning_tpu.experiment import arg_pools  # noqa: F401
        from active_learning_tpu.experiment.driver import run_experiment
        from active_learning_tpu.utils.metrics import JsonlSink

        from helpers import TinyClassifier, tiny_train_config

        tmp = str(tmp_path)
        cfg = ExperimentConfig(
            dataset="synthetic", arg_pool="synthetic",
            strategy="MarginSampler", rounds=3, round_budget=8,
            n_epoch=2, early_stop_patience=2, log_dir=tmp, ckpt_path=tmp,
            exp_hash="pipewarm", round_pipeline="speculative",
            telemetry=TelemetryConfig(enabled=True,
                                      heartbeat_every_s=0.0))
        data = get_data_synthetic(n_train=96, n_test=32, num_classes=4,
                                  image_size=8, seed=5)
        strategy = run_experiment(
            cfg, sink=JsonlSink(tmp, experiment_key="pipewarm"),
            data=data, train_cfg=tiny_train_config(),
            model=TinyClassifier(num_classes=4))
        assert strategy.pipeline is not None
        deltas = {}
        with open(os.path.join(tmp, "metrics.jsonl")) as fh:
            for line in fh:
                ev = json.loads(line)
                if (ev.get("kind") == "metric"
                        and "jit_cache_miss_delta" in ev.get("metrics",
                                                             {})):
                    deltas[ev.get("step")] = \
                        ev["metrics"]["jit_cache_miss_delta"]
        assert set(deltas) == {0, 1, 2}
        assert deltas[0] > 0  # round 0 pays the cold compiles ...
        for rd in (1, 2):  # ... and warm pipelined rounds pay none.
            assert deltas[rd] == 0, (
                f"warm pipelined round {rd} compiled: "
                f"{deltas[rd]} jit cache misses")


class TestGradPathCompileReuse:
    def test_warm_rounds_zero_new_compiles_under_all_new_flags(
            self, tmp_path):
        """ISSUE 10's compile-freeness acceptance: the fused donated
        optimizer (bf16 momentum), the donated round-boundary reinit,
        AND the int8 quantized gradient sync together — 3 driver rounds
        on the multi-device CPU mesh, rounds 1-2 at jit delta 0 (the
        same registry-counted metric the production driver exports).
        The int8 learning probe runs inside round 0's cold window, so
        its compiles land in the cold tax, never the warm rounds."""
        import json
        import os

        from active_learning_tpu.config import (ExperimentConfig,
                                                TelemetryConfig)
        from active_learning_tpu.data.synthetic import get_data_synthetic
        from active_learning_tpu.experiment import arg_pools  # noqa: F401
        from active_learning_tpu.experiment.driver import run_experiment
        from active_learning_tpu.utils.metrics import JsonlSink

        from helpers import TinyClassifier, tiny_train_config

        tmp = str(tmp_path)
        cfg = ExperimentConfig(
            dataset="synthetic", arg_pool="synthetic",
            strategy="MarginSampler", rounds=3, round_budget=8,
            n_epoch=2, early_stop_patience=2, log_dir=tmp, ckpt_path=tmp,
            exp_hash="gradwarm", round_pipeline="off",
            fused_optimizer="on", optim_state_dtype="bf16",
            grad_allreduce="int8",
            telemetry=TelemetryConfig(enabled=True,
                                      heartbeat_every_s=0.0))
        data = get_data_synthetic(n_train=96, n_test=32, num_classes=4,
                                  image_size=8, seed=5)
        strategy = run_experiment(
            cfg, sink=JsonlSink(tmp, experiment_key="gradwarm"),
            data=data, train_cfg=tiny_train_config(),
            model=TinyClassifier(num_classes=4))
        assert strategy.trainer.fused_tx is not None
        assert strategy.trainer.grad_allreduce == "int8"
        assert not strategy.trainer.grad_allreduce_degraded
        deltas = {}
        with open(os.path.join(tmp, "metrics.jsonl")) as fh:
            for line in fh:
                ev = json.loads(line)
                if (ev.get("kind") == "metric"
                        and "jit_cache_miss_delta" in ev.get("metrics",
                                                             {})):
                    deltas[ev.get("step")] = \
                        ev["metrics"]["jit_cache_miss_delta"]
        assert set(deltas) == {0, 1, 2}
        assert deltas[0] > 0  # cold round pays the compiles ...
        for rd in (1, 2):  # ... warm rounds pay none, under every flag.
            assert deltas[rd] == 0, (
                f"warm round {rd} compiled under the gradient-path "
                f"flags: {deltas[rd]} jit cache misses")


class TestStreamExtentCompileReuse:
    def test_appended_extents_keep_warm_round_delta_zero(self, tmp_path):
        """ISSUE 14's zero-new-compiles acceptance: a streaming run that
        ingests rows BETWEEN rounds recompiles at most once per extent
        boundary, never once per append.  Round 1 may pay the growth
        tax (the pool crosses from its base length onto the extent
        ladder, plus the first drift probe and first query); rounds 2-3
        ingest MORE rows inside the same extent and must land at jit
        cache-miss delta 0 — the same registry-counted metric the
        production driver exports."""
        import base64
        import http.client
        import json
        import os
        import signal
        import threading
        import time

        from helpers import TinyClassifier, tiny_train_config
        from active_learning_tpu.config import (ExperimentConfig,
                                                StreamConfig,
                                                TelemetryConfig)
        from active_learning_tpu.data.synthetic import get_data_synthetic
        from active_learning_tpu.faults import preempt as preempt_lib
        from active_learning_tpu.stream.service import StreamService
        from active_learning_tpu.utils.metrics import JsonlSink

        tmp = str(tmp_path)
        cfg = ExperimentConfig(
            dataset="synthetic", arg_pool="synthetic",
            strategy="MarginSampler", rounds=4, round_budget=8,
            n_epoch=2, early_stop_patience=2, log_dir=tmp, ckpt_path=tmp,
            exp_hash="streamwarm", round_pipeline="off",
            telemetry=TelemetryConfig(enabled=True,
                                      heartbeat_every_s=0.0))
        # Floor 64: the first 8-row append grows the 96-row base onto
        # the 128-slot extent; the next two appends stay INSIDE it.
        scfg = StreamConfig(port=0, max_rounds=4, watermark_rows=8,
                            drift_psi=0.0, max_interval_s=0.0,
                            poll_s=0.02, extent_floor=64)
        data = get_data_synthetic(n_train=96, n_test=32, num_classes=4,
                                  image_size=8, seed=5)
        svc = StreamService(cfg, scfg,
                            sink=JsonlSink(tmp,
                                           experiment_key="streamwarm"),
                            data=data, train_cfg=tiny_train_config(),
                            model=TinyClassifier(num_classes=4))
        box = {}

        def run():
            try:
                box["strategy"] = svc.run()
            except BaseException as e:  # noqa: BLE001 - re-raised below
                box["err"] = e

        t = threading.Thread(target=run, daemon=True)
        t.start()
        try:
            assert svc.ready.wait(240)

            def post_rows(n, seed):
                rng = np.random.default_rng(seed)
                rows = rng.integers(0, 256, size=(n, 8, 8, 3),
                                    dtype=np.uint8)
                body = json.dumps({
                    "rows_b64":
                        base64.b64encode(rows.tobytes()).decode(),
                    "shape": [n, 8, 8, 3],
                    "labels": [int(i) % 4 for i in range(n)]}).encode()
                conn = http.client.HTTPConnection("127.0.0.1", svc.port,
                                                  timeout=30)
                try:
                    conn.request("POST", "/v1/pool", body=body)
                    assert conn.getresponse().status == 200
                finally:
                    conn.close()

            # One 8-row append between every pair of rounds: each lands
            # in its own drain (watermark 8 fires the next round).
            for prev_rounds, seed in ((1, 20), (2, 21), (3, 22)):
                deadline = time.monotonic() + 240
                while svc.rounds_run < prev_rounds and t.is_alive() \
                        and time.monotonic() < deadline:
                    time.sleep(0.02)
                assert svc.rounds_run >= prev_rounds, (
                    f"round {prev_rounds - 1} never completed")
                post_rows(8, seed)
            t.join(timeout=300)
            assert not t.is_alive()
            if "err" in box:
                raise box["err"]
        finally:
            if t.is_alive():
                preempt_lib._handler(signal.SIGTERM, None)
                t.join(timeout=60)
        strategy = box["strategy"]
        assert svc.store.n_rows == 96 + 24
        assert strategy.pool.n_pool == 128  # ONE extent, three appends
        # The streaming-aware run report (ISSUE 15 satellite): every
        # round left a row joined by its stream block — trigger cause,
        # ingest totals — renderable by the `report` verb.
        with open(os.path.join(tmp, "run_report.json")) as fh:
            report = json.load(fh)
        assert report.get("stream") is True
        rows = report["rounds"]
        assert [r["round"] for r in rows] == [0, 1, 2, 3]
        causes = [r["stream"]["trigger_cause"] for r in rows]
        assert causes[0] == "bootstrap"
        assert all(c == "watermark" for c in causes[1:])
        assert rows[-1]["stream"]["ingest_rows_total"] == 24
        deltas = {}
        with open(os.path.join(tmp, "metrics.jsonl")) as fh:
            for line in fh:
                ev = json.loads(line)
                if (ev.get("kind") == "metric"
                        and "jit_cache_miss_delta" in ev.get("metrics",
                                                             {})):
                    deltas[ev.get("step")] = \
                        ev["metrics"]["jit_cache_miss_delta"]
        assert set(deltas) == {0, 1, 2, 3}
        assert deltas[0] > 0  # the cold round pays the compiles ...
        # Round 1 crosses the extent boundary (96 -> 128): at most one
        # retrace per grown executable, tolerated once per boundary.
        for rd in (2, 3):  # ... appends INSIDE the extent pay nothing.
            assert deltas[rd] == 0, (
                f"round {rd} compiled after an in-extent append: "
                f"{deltas[rd]} jit cache misses")


class TestCompilationCacheConfig:
    def test_driver_enables_persistent_cache(self, tmp_path, monkeypatch):
        from active_learning_tpu.experiment import driver

        target = str(tmp_path / "xla_cache")
        old = jax.config.jax_compilation_cache_dir
        try:
            got = driver.enable_compilation_cache(target)
            assert got == target
            assert jax.config.jax_compilation_cache_dir == target
        finally:
            # Undo the process-wide config leak: the rest of the session
            # must keep running cache-less — jax 0.4.37's CPU backend
            # corrupts donated buffers in cache-DESERIALIZED executables
            # (see conftest.py), so a leaked cache dir here could make
            # any later donating jit nondeterministic.
            jax.config.update("jax_compilation_cache_dir", old)

    def test_empty_string_disables(self):
        from active_learning_tpu.experiment import driver

        assert driver.enable_compilation_cache("") is None
