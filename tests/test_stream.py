"""The streaming subsystem (active_learning_tpu/stream/, DESIGN.md §14).

Pinned here:
  * WAL durability: fsync'd append, torn-tail drop (never corruption),
    seq continuity across segments/restarts, rotation sealing, the
    wal_write fault site's torn injection;
  * the growable pool: bucket-aligned extent growth, PoolState
    grow/valid/invalid semantics and their (de)serialization;
  * ingest handlers: 400/413/429 admission semantics, WAL-before-ack
    behaviorally (seq advanced before the ack exists);
  * the trigger policy's decision table;
  * the HTTP service end to end (POST /v1/pool + /v1/label over a live
    loopback listener, driven by the loadgen's ingest mode);
  * THE equivalence pins: a zero-ingest stream run is bit-identical to
    the batch driver; ingest chunking (one big request vs many small)
    cannot change picks; chunked-incremental scoring over appended
    rows equals the monolithic pass bit for bit;
  * THE chaos pin: preemption mid-triggered-round -> resume completes
    with zero accepted-row loss and experiment_state bit-identical to
    the uninterrupted run;
  * stream gauges reach BOTH channels (metrics.jsonl + the Prometheus
    scrape, labeled trigger-cause samples included) and `status` grows
    the stream tail + the --strict exit-5 ingest-starved contract.
"""

import base64
import glob
import http.client
import json
import os
import signal
import threading
import time

import numpy as np
import pytest

from helpers import TinyClassifier, tiny_train_config

from active_learning_tpu import faults
from active_learning_tpu.config import (ExperimentConfig, StreamConfig,
                                        TelemetryConfig)
from active_learning_tpu.data.synthetic import get_data_synthetic
from active_learning_tpu.experiment.driver import (STREAM_GAUGES,
                                                   run_experiment)
from active_learning_tpu.faults import preempt as preempt_lib
from active_learning_tpu.pool import PoolState, bucket_size
from active_learning_tpu.stream import ingest as ingest_lib
from active_learning_tpu.stream import store as store_lib
from active_learning_tpu.stream.scheduler import TriggerPolicy
from active_learning_tpu.stream.service import StreamService
from active_learning_tpu.stream.wal import (IngestWAL, iter_payloads,
                                            replay_wal)
from active_learning_tpu.telemetry import prom as prom_lib
from active_learning_tpu.telemetry import status as status_lib
from active_learning_tpu.utils.metrics import JsonlSink, NullSink

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _rows(n, px=8, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, size=(n, px, px, 3), dtype=np.uint8)


def _pool_record(rows, labels=None):
    rec = {"kind": "pool",
           "shape": [int(d) for d in rows.shape],
           "rows_b64": base64.b64encode(rows.tobytes()).decode(),
           "labels": labels}
    return rec


# ---------------------------------------------------------------------------
# WAL
# ---------------------------------------------------------------------------

class TestWAL:
    def test_append_replay_roundtrip_and_seq(self, tmp_path):
        d = str(tmp_path)
        wal = IngestWAL(d)
        rows = _rows(4)
        assert wal.append(_pool_record(rows, [0, 1, 2, 3])) == 1
        assert wal.append({"kind": "label", "ids": [1], "labels": [2]}) == 2
        wal.close()
        records, dropped = replay_wal(d)
        assert dropped == 0
        payloads = list(iter_payloads(records))
        assert [r["seq"] for r in payloads] == [1, 2]
        got, labels = store_lib.decode_pool_payload(payloads[0], (8, 8, 3))
        assert np.array_equal(got, rows) and labels == [0, 1, 2, 3]
        # Seq continues across restarts.
        wal2 = IngestWAL(d)
        assert wal2.append({"kind": "label", "ids": [0],
                            "labels": [1]}) == 3
        wal2.close()

    def test_torn_tail_dropped_never_served(self, tmp_path):
        d = str(tmp_path)
        wal = IngestWAL(d)
        wal.append(_pool_record(_rows(2), [0, 1]))
        wal.close()
        # Simulate a kill mid-append: a half-written (newline-less) line.
        with open(os.path.join(d, "wal.jsonl"), "ab") as fh:
            fh.write(b'{"seq": 2, "kind": "label", "ids"')
        records, dropped = replay_wal(d)
        assert dropped == 1
        assert [r["seq"] for r in records] == [1]
        # Reopening truncates the fragment; the next record is clean.
        wal = IngestWAL(d)
        assert wal.append({"kind": "label", "ids": [0],
                           "labels": [1]}) == 2
        wal.close()
        records, dropped = replay_wal(d)
        assert dropped == 0 and [r["seq"] for r in records] == [1, 2]

    def test_mid_file_corruption_raises(self, tmp_path):
        d = str(tmp_path)
        wal = IngestWAL(d)
        wal.append({"kind": "label", "ids": [0], "labels": [1]})
        wal.append({"kind": "label", "ids": [1], "labels": [1]})
        wal.close()
        path = os.path.join(d, "wal.jsonl")
        lines = open(path, "rb").read().splitlines(keepends=True)
        with open(path, "wb") as fh:
            fh.write(b"garbage\n" + lines[1])
        with pytest.raises(ValueError, match="corrupt WAL record"):
            replay_wal(d)

    def test_rotation_seals_segments_in_replay_order(self, tmp_path):
        d = str(tmp_path)
        wal = IngestWAL(d, rotate_bytes=200)
        for i in range(6):
            wal.append({"kind": "label", "ids": [i], "labels": [0]})
        wal.close()
        sealed = glob.glob(os.path.join(d, "wal_*.jsonl"))
        assert sealed, "no sealed segments despite the tiny rotate bound"
        records, dropped = replay_wal(d)
        assert dropped == 0
        assert [r["seq"] for r in records] == list(range(1, 7))

    def test_crc_guards_tampered_records(self, tmp_path):
        d = str(tmp_path)
        wal = IngestWAL(d)
        wal.append({"kind": "label", "ids": [0], "labels": [1]})
        wal.append({"kind": "label", "ids": [1], "labels": [1]})
        wal.close()
        path = os.path.join(d, "wal.jsonl")
        text = open(path).read().replace('"ids": [0]', '"ids": [9]', 1)
        open(path, "w").write(text)
        with pytest.raises(ValueError, match="crc mismatch"):
            replay_wal(d)

    def test_torn_fault_site_loses_only_the_unacked_record(self, tmp_path):
        d = str(tmp_path)
        wal = IngestWAL(d)
        wal.append({"kind": "label", "ids": [0], "labels": [1]})
        faults.configure("wal_write:torn@1", seed=0)
        try:
            with pytest.raises(faults.InjectedFault):
                wal.append({"kind": "label", "ids": [1], "labels": [1]})
        finally:
            faults.configure(None)
        wal.close()
        records, dropped = replay_wal(d)
        # The interrupted record was never acked: dropping it is the
        # contract, corruption would be the bug.
        assert [r["seq"] for r in records] == [1]
        assert dropped == 1


# ---------------------------------------------------------------------------
# PoolState growth + the growable store
# ---------------------------------------------------------------------------

class TestPoolGrowth:
    def test_grow_set_valid_and_query_masks(self):
        pool = PoolState.create(10, eval_idxs=[8, 9])
        pool.grow(16)
        assert pool.n_pool == 16
        assert pool.invalid[10:].all() and not pool.invalid[:10].any()
        # Padding slots are neither queryable nor labelable.
        assert pool.available_mask()[10:].sum() == 0
        with pytest.raises(ValueError, match="invalid"):
            pool.update([12], 1.0)
        pool.mark_valid([10, 11])
        assert pool.available_mask()[[10, 11]].all()
        with pytest.raises(ValueError, match="shrink"):
            pool.grow(8)

    def test_absorb_labels_skips_budget_and_recent(self):
        pool = PoolState.create(8, eval_idxs=[])
        pool.update([0, 1], 2.0)
        recent = pool.recent.copy()
        pool.grow(12)
        pool.absorb_labels([9, 10])
        assert pool.labeled[[9, 10]].all()
        assert not pool.invalid[[9, 10]].any()
        assert pool.cumulative_cost == 2.0  # no budget charged
        assert np.array_equal(pool.recent, recent)
        with pytest.raises(ValueError, match="already labeled"):
            pool.absorb_labels([9])

    def test_serialization_roundtrip_with_invalid(self):
        pool = PoolState.create(6, eval_idxs=[5])
        pool.grow(8)
        pool.update([0], 1.0)
        back = PoolState.from_arrays(pool.to_arrays())
        assert np.array_equal(back.invalid, pool.invalid)
        assert np.array_equal(back.labeled, pool.labeled)
        # Pre-stream saves (no invalid key) load as all-real slots.
        arrs = pool.to_arrays()
        del arrs["invalid"]
        legacy = PoolState.from_arrays(arrs)
        assert not legacy.invalid.any()

    def test_store_grows_by_bucket_extents(self, tmp_path):
        st = store_lib.PoolStore(str(tmp_path), (8, 8, 3), 4,
                                 base_images=_rows(20),
                                 base_targets=np.arange(20) % 4,
                                 extent_floor=16)
        assert st.capacity == bucket_size(20, floor=16)
        ids = st.apply_pool_record(_pool_record(_rows(30, seed=1),
                                                list(range(30))))
        assert np.array_equal(ids, np.arange(20, 50))
        assert st.capacity == bucket_size(50, floor=16)
        assert st.n_rows == 50
        # Targets of padding slots read UNKNOWN, never class 0.
        assert (st.snapshot()[1][50:] == store_lib.UNKNOWN_LABEL).all()


# ---------------------------------------------------------------------------
# Ingest handlers: admission + WAL-before-ack, behaviorally
# ---------------------------------------------------------------------------

class TestIngestHandlers:
    def _stack(self, tmp_path, max_backlog=64):
        wal = IngestWAL(str(tmp_path))
        queue = ingest_lib.PendingQueue(max_backlog)
        ids = ingest_lib.IdSpace(10)
        return wal, queue, ids

    def _pool_req(self, n, labels=False):
        rows = _rows(n)
        return {"rows_b64": base64.b64encode(rows.tobytes()).decode(),
                "shape": [n, 8, 8, 3],
                "labels": list(range(n)) if labels else None}

    def test_pool_append_durable_before_ack(self, tmp_path):
        wal, queue, ids = self._stack(tmp_path)
        out = ingest_lib.handle_pool_append(wal, queue, ids,
                                            self._pool_req(4), (8, 8, 3),
                                            max_request_rows=8)
        assert out["ok"] and out["ids"] == [10, 11, 12, 13]
        # The ack's seq IS on disk: the WAL already holds it.
        records, _ = replay_wal(str(tmp_path))
        assert records[-1]["seq"] == out["seq"] == 1
        assert queue.counters()["pending_rows"] == 4
        wal.close()

    def test_oversize_is_413_backlog_is_429(self, tmp_path):
        wal, queue, ids = self._stack(tmp_path, max_backlog=6)
        with pytest.raises(ingest_lib.IngestError) as e:
            ingest_lib.handle_pool_append(wal, queue, ids,
                                          self._pool_req(9), (8, 8, 3),
                                          max_request_rows=8)
        assert e.value.status == 413
        ingest_lib.handle_pool_append(wal, queue, ids, self._pool_req(4),
                                      (8, 8, 3), max_request_rows=8)
        with pytest.raises(ingest_lib.IngestError) as e:
            ingest_lib.handle_pool_append(wal, queue, ids,
                                          self._pool_req(4), (8, 8, 3),
                                          max_request_rows=8)
        assert e.value.status == 429 and e.value.retry_after is not None
        # The refused request left NOTHING durable: no seq consumed.
        assert wal.last_seq == 1
        wal.close()

    def test_label_validates_against_acked_id_space(self, tmp_path):
        wal, queue, ids = self._stack(tmp_path)
        with pytest.raises(ingest_lib.IngestError) as e:
            ingest_lib.handle_label_attach(
                wal, queue, ids, {"ids": [10], "labels": [1]})
        assert e.value.status == 400  # id 10 was never acked
        # Eval-split rows are REJECTED before the WAL write: a durable
        # label record the drain could never absorb would replay into
        # the same failure on every restart — a poison pill.
        ids_eval = ingest_lib.IdSpace(10, unlabelable=[3])
        with pytest.raises(ingest_lib.IngestError) as e:
            ingest_lib.handle_label_attach(
                wal, queue, ids_eval, {"ids": [3], "labels": [1]})
        assert e.value.status == 400
        assert "validation rows" in e.value.message
        assert wal.last_seq == 0  # nothing rejected became durable
        out = ingest_lib.handle_label_attach(
            wal, queue, ids, {"ids": [3, 4], "labels": [1, 2]})
        assert out["ok"] and wal.last_seq == 1
        for bad in ({"ids": [1], "labels": [1, 2]},
                    {"ids": [1, 1], "labels": [0, 0]},
                    {"ids": [], "labels": []},
                    {"ids": [0], "labels": [-1]}):
            with pytest.raises(ingest_lib.IngestError):
                ingest_lib.handle_label_attach(wal, queue, ids, bad)
        wal.close()

    def test_malformed_pool_payload_is_400(self, tmp_path):
        wal, queue, ids = self._stack(tmp_path)
        for req in ({"shape": [2, 8, 8, 3]},                 # no rows
                    {"rows_b64": "aaaa", "shape": [1, 4, 4, 3]},  # shape
                    {"rows_b64": "!!", "shape": [1, 8, 8, 3]}):  # b64
            with pytest.raises(ingest_lib.IngestError) as e:
                ingest_lib.handle_pool_append(wal, queue, ids, req,
                                              (8, 8, 3),
                                              max_request_rows=8)
            assert e.value.status == 400
        assert wal.last_seq == 0  # nothing malformed became durable
        wal.close()


# ---------------------------------------------------------------------------
# Trigger policy
# ---------------------------------------------------------------------------

class TestTriggerPolicy:
    def test_decision_table(self):
        p = TriggerPolicy(watermark_rows=100, drift_psi=0.25,
                          max_interval_s=60.0)
        dec = p.decide
        assert dec(100, 0, None, 0.0, 50) == "watermark"
        assert dec(99, 0, None, 0.0, 50) is None
        assert dec(0, 0, 0.25, 0.0, 50) == "drift"
        assert dec(0, 0, 0.24, 0.0, 50) is None
        assert dec(0, 0, None, 61.0, 50) == "interval"
        # Interval never fires an empty loop: no pending work, no
        # queryable rows -> idle, not a round that re-picks nothing.
        assert dec(0, 0, None, 61.0, 0) is None
        assert dec(0, 1, None, 61.0, 0) == "interval"
        # Disabled conditions never fire.
        off = TriggerPolicy(watermark_rows=0, drift_psi=0.0,
                            max_interval_s=0.0)
        assert off.decide(10**6, 10**6, 9.9, 10**6, 10**6) is None

    def test_watermark_wins_attribution(self):
        p = TriggerPolicy(watermark_rows=1, drift_psi=0.01,
                          max_interval_s=0.01)
        assert p.decide(5, 0, 1.0, 100.0, 5) == "watermark"


# ---------------------------------------------------------------------------
# Service end to end (shared fixtures)
# ---------------------------------------------------------------------------

N_EPOCH = 2


def _cfg(tag, root, *, resume=False, rounds=2, pipeline="off"):
    return ExperimentConfig(
        dataset="synthetic", arg_pool="synthetic",
        strategy="MarginSampler", rounds=rounds, round_budget=8,
        n_epoch=N_EPOCH, early_stop_patience=N_EPOCH, run_seed=7,
        exp_hash=tag, exp_name="stream", resume_training=resume,
        ckpt_path=os.path.join(root, "ckpt"),
        log_dir=os.path.join(root, "logs"), round_pipeline=pipeline,
        telemetry=TelemetryConfig(enabled=True, heartbeat_every_s=0.0))


def _scfg(**over):
    base = dict(port=0, max_rounds=2, watermark_rows=0, drift_psi=0.0,
                max_interval_s=0.01, poll_s=0.02, extent_floor=16)
    base.update(over)
    return StreamConfig(**base)


@pytest.fixture(scope="module")
def stream_data():
    return get_data_synthetic(n_train=96, n_test=32, num_classes=4,
                              image_size=8, seed=5)


def _state_of(cfg):
    path = glob.glob(os.path.join(cfg.ckpt_path, "*",
                                  "experiment_state.npz"))[0]
    return dict(np.load(path))


def _run_service(cfg, scfg, data, sink=None):
    svc = StreamService(cfg, scfg, sink=sink or NullSink(), data=data,
                        train_cfg=tiny_train_config(),
                        model=TinyClassifier(num_classes=4))
    svc.run()
    return svc


def _prefill_wal(log_dir, records):
    wal = IngestWAL(os.path.join(log_dir, "ingest_wal"))
    for rec in records:
        wal.append(rec)
    wal.close()


class TestEquivalencePins:
    def test_zero_ingest_stream_matches_batch_driver(self, stream_data,
                                                     tmp_path):
        """A stream run that never ingests IS the batch driver: same
        seeds, same data -> experiment_state bit-identical.  Every
        batch-mode guarantee (resume, ladder, pipelining) transfers to
        the streaming loop through this pin."""
        a = _cfg("batch", str(tmp_path / "a"))
        run_experiment(a, sink=NullSink(), data=stream_data,
                       train_cfg=tiny_train_config(),
                       model=TinyClassifier(num_classes=4))
        base = _state_of(a)
        b = _cfg("streamed", str(tmp_path / "b"))
        _run_service(b, _scfg(), stream_data)
        state = _state_of(b)
        assert set(state) == set(base)
        for k in base:
            assert np.array_equal(base[k], state[k]), (
                f"experiment_state[{k!r}] diverged between the batch "
                "driver and the zero-ingest stream loop")

    def test_ingest_chunking_cannot_change_picks(self, stream_data,
                                                 tmp_path):
        """The equivalence pin: the SAME appended rows presented as one
        big request vs many small ones -> identical pool, scores, and
        picks (chunked-incremental == monolithic, extended to appended
        extents)."""
        rows = _rows(24, seed=3)
        labels = [int(v) % 4 for v in range(24)]
        runs = {}
        for tag, chunks in (("mono", [rows]),
                            ("chunked", [rows[:8], rows[8:16],
                                         rows[16:]])):
            cfg = _cfg(tag, str(tmp_path / tag))
            os.makedirs(cfg.log_dir, exist_ok=True)
            off = 0
            recs = []
            for c in chunks:
                recs.append(_pool_record(c, labels[off:off + len(c)]))
                off += len(c)
            _prefill_wal(cfg.log_dir, recs)
            _run_service(cfg, _scfg(), stream_data)
            runs[tag] = _state_of(cfg)
        for k in runs["mono"]:
            assert np.array_equal(runs["mono"][k], runs["chunked"][k]), (
                f"experiment_state[{k!r}] depends on ingest chunking")
        # The grown pool really was in play: extents + labeled picks.
        assert int(runs["mono"]["n_pool"]) == bucket_size(120, floor=16)

    def test_incremental_chunk_scores_match_monolithic(self, stream_data,
                                                       tmp_path):
        """Scoring only the appended row range in chunk_row_slices plans
        and splicing == scoring the grown pool monolithically, bit for
        bit (the PR 7 contract over appended extents)."""
        import jax
        from active_learning_tpu.parallel import mesh as mesh_lib
        from active_learning_tpu.strategies import scoring

        st = store_lib.PoolStore(str(tmp_path), (8, 8, 3), 4,
                                 base_images=_rows(40, seed=1),
                                 base_targets=np.arange(40) % 4,
                                 extent_floor=16)
        st.apply_pool_record(_pool_record(_rows(33, seed=2),
                                          [0] * 33))
        train_sd, al_sd = st.make_datasets(
            stream_data[0].view, stream_data[2].view)
        al_sd.refresh()  # full capacity view
        model = TinyClassifier(num_classes=4)
        variables = model.init(jax.random.PRNGKey(0),
                               np.zeros((1, 8, 8, 3), np.float32),
                               train=False)
        mesh = mesh_lib.make_mesh()
        step = scoring.make_prob_stats_step(model, al_sd.view)
        idxs = np.arange(40, 73, dtype=np.int64)  # the appended range
        bs = 16
        mono = scoring.collect_pool(al_sd, idxs, bs, step, variables,
                                    mesh, keys=("margin", "entropy"))
        chunks = [scoring.collect_pool(al_sd, idxs[sl], bs, step,
                                       variables, mesh,
                                       keys=("margin", "entropy"))
                  for sl in scoring.chunk_row_slices(len(idxs), bs, 1)]
        spliced = scoring.splice_chunks(chunks)
        for k in mono:
            assert np.array_equal(mono[k], spliced[k]), k


class TestHTTPServiceEndToEnd:
    def _spawn(self, cfg, scfg, data, sink=None):
        svc = StreamService(cfg, scfg, sink=sink or NullSink(),
                            data=data, train_cfg=tiny_train_config(),
                            model=TinyClassifier(num_classes=4))
        box = {}

        def run():
            try:
                box["strategy"] = svc.run()
            except BaseException as e:  # noqa: BLE001 - re-raised below
                box["err"] = e

        t = threading.Thread(target=run, daemon=True)
        t.start()
        assert svc.ready.wait(240), "service never became ready"
        return svc, t, box

    def _post(self, port, path, payload):
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        try:
            conn.request("POST", path, body=json.dumps(payload).encode(),
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            return resp.status, json.loads(resp.read().decode())
        finally:
            conn.close()

    def test_ingest_trigger_round_metrics_and_status(self, stream_data,
                                                     tmp_path):
        """One live service: HTTP ingest (pool + label), watermark
        trigger, a completed round over the grown pool, stream gauges
        in BOTH channels, and the status verb's stream tail."""
        cfg = _cfg("http", str(tmp_path))
        cfg.telemetry = TelemetryConfig(
            enabled=True, heartbeat_every_s=0.0,
            prometheus_file=os.path.join(cfg.log_dir, "run.prom"))
        sink = JsonlSink(cfg.log_dir, experiment_key="http")
        scfg = _scfg(max_rounds=2, watermark_rows=24, max_interval_s=0.0)
        svc, t, box = self._spawn(cfg, scfg, stream_data, sink=sink)
        try:
            # Let the bootstrap round finish first so all 24 posted
            # rows land in ONE drain window and the watermark trigger
            # (24) is what fires round 1.
            deadline = time.monotonic() + 240
            while svc.rounds_run < 1 and t.is_alive() \
                    and time.monotonic() < deadline:
                time.sleep(0.02)
            assert svc.rounds_run >= 1, "bootstrap round never completed"
            rows = _rows(16, seed=11)
            status, out = self._post(svc.port, "/v1/pool", {
                "rows_b64": base64.b64encode(rows.tobytes()).decode(),
                "shape": [16, 8, 8, 3]})
            assert status == 200 and out["accepted"] == 16
            no_oracle_ids = out["ids"]
            # Attach labels to half the oracle-less rows.
            status, _ = self._post(svc.port, "/v1/label", {
                "ids": no_oracle_ids[:8],
                "labels": [i % 4 for i in range(8)]})
            assert status == 200
            rows2 = _rows(8, seed=12)
            status, out2 = self._post(svc.port, "/v1/pool", {
                "rows_b64": base64.b64encode(rows2.tobytes()).decode(),
                "shape": [8, 8, 8, 3],
                "labels": [i % 4 for i in range(8)]})
            assert status == 200
            t.join(timeout=300)
            assert not t.is_alive(), "service never finished"
            if "err" in box:
                raise box["err"]
        finally:
            if t.is_alive():
                preempt_lib._handler(signal.SIGTERM, None)
                t.join(timeout=60)
        strategy = box["strategy"]
        # The pool grew by one 16-aligned extent; the 8 labeled-by-
        # /v1/label rows joined the labeled set without budget.
        assert svc.store.n_rows == 96 + 24
        assert strategy.pool.n_pool == bucket_size(120, floor=16)
        assert strategy.pool.labeled[no_oracle_ids[:8]].all()
        # Oracle-less, unlabeled rows stay out of the queryable set.
        assert strategy.pool.invalid[no_oracle_ids[8:]].all()
        assert svc.rounds_run == 2
        assert svc.last_trigger["cause"] == "watermark"

        # Gauges: every stream gauge that reached metrics.jsonl also
        # rides the scrape (the PER_ROUND_GAUGES completeness rule),
        # and the per-cause trigger counter carries its label.
        sink.close()
        names = set()
        for line in open(os.path.join(cfg.log_dir, "metrics.jsonl")):
            ev = json.loads(line)
            if ev.get("kind") == "metric":
                names.update(ev["metrics"])
        parsed = prom_lib.parse(
            open(os.path.join(cfg.log_dir, "run.prom")).read())
        for name in STREAM_GAUGES:
            if name in names:
                assert f"al_run_{name}" in parsed, name
        assert "ingest_rows_total" in names
        assert parsed["al_run_ingest_rows_total"][()] == 24.0
        assert any(lbl == (("cause", "watermark"),)
                   for lbl in parsed.get("al_run_rounds_triggered", {}))

        # The status verb's stream tail + healthy strict exit.
        summary = status_lib.summarize(cfg.log_dir)
        assert summary["stream"]["pool_rows_total"] == 120
        assert summary["stream"]["last_trigger_cause"] == "watermark"
        text = status_lib.render_text(summary)
        assert "stream:" in text and "wal_backlog" in text

    def test_loadgen_ingest_mode_drives_both_endpoints(self, stream_data,
                                                       tmp_path):
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "serve_loadgen",
            os.path.join(REPO, "scripts", "serve_loadgen.py"))
        loadgen = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(loadgen)

        cfg = _cfg("loadgen", str(tmp_path))
        # Run-forever: the test stops the service itself.
        scfg = _scfg(max_rounds=0, watermark_rows=10**9,
                     max_interval_s=0.0, max_backlog_rows=10**6)
        svc, t, box = self._spawn(cfg, scfg, stream_data)
        try:
            out = loadgen.run_ingest_closed(
                f"http://127.0.0.1:{svc.port}", duration_s=1.0,
                workers=2, rows=4, label_frac=0.5, image_shape=(8, 8, 3))
            assert out["mode"] == "ingest_closed"
            assert out["n_ok"] > 0 and out["n_err"] == 0
            assert out["p50_ms"] is not None
            health = loadgen.fetch_health(f"http://127.0.0.1:{svc.port}")
            assert health["image_shape"] == [8, 8, 3]
            assert health["pool_rows"] > 96
        finally:
            preempt_lib._handler(signal.SIGTERM, None)
            t.join(timeout=120)
        assert isinstance(box.get("err"),
                          preempt_lib.PreemptionRequested)


# ---------------------------------------------------------------------------
# THE chaos pin: kill mid-round -> resume, zero loss, bit-identical
# ---------------------------------------------------------------------------

class _PreemptAtEpochSink(NullSink):
    """Records a preemption request (what the real SIGTERM handler
    does) when round ``rd``'s fit reaches ``epoch`` — the deterministic
    in-process kill of tests/test_faults.py, reused for the stream
    loop."""

    def __init__(self, rd, epoch):
        self.name = f"rd_{rd}_validation_accuracy"
        self.epoch = epoch
        self.fired = False

    def log_metric(self, name, value, step=None):
        if not self.fired and step == self.epoch and name == self.name:
            self.fired = True
            preempt_lib._handler(signal.SIGTERM, None)


class TestChaosPin:
    WAL_ROWS = 24

    def _records(self):
        rows = _rows(self.WAL_ROWS, seed=9)
        return [_pool_record(rows[:16],
                             [i % 4 for i in range(16)]),
                _pool_record(rows[16:], None),
                {"kind": "label", "ids": [96 + 16, 96 + 17],
                 "labels": [1, 2]}]

    def _launch(self, tag, root, data, sink=None, resume=False,
                prefill=True):
        cfg = _cfg(tag, root, resume=resume)
        if prefill and not resume:
            os.makedirs(cfg.log_dir, exist_ok=True)
            _prefill_wal(cfg.log_dir, self._records())
        svc = StreamService(cfg, _scfg(), sink=sink or NullSink(),
                            data=data, train_cfg=tiny_train_config(),
                            model=TinyClassifier(num_classes=4))
        return cfg, svc

    def test_preempt_mid_triggered_round_resumes_bit_identical(
            self, stream_data, tmp_path):
        """Ingest (via a pre-accepted WAL) -> bootstrap -> kill DURING
        the triggered round's fit -> resume completes: zero accepted-row
        loss, experiment_state bit-identical to the uninterrupted
        twin."""
        # The uninterrupted twin.
        cfg_a, svc_a = self._launch("uninter", str(tmp_path / "a"),
                                    stream_data)
        svc_a.run()
        baseline = _state_of(cfg_a)
        assert svc_a.store.n_rows == 96 + self.WAL_ROWS

        # The killed run: preempted at round 1, epoch 1 (mid-fit).
        sink = _PreemptAtEpochSink(rd=1, epoch=1)
        cfg_b, svc_b = self._launch("killed", str(tmp_path / "b"),
                                    stream_data, sink=sink)
        with pytest.raises(preempt_lib.PreemptionRequested):
            svc_b.run()
        assert sink.fired
        jr = faults.read_journal(
            os.path.join(cfg_b.log_dir, faults.JOURNAL_FILE))
        assert jr["status"] == "preempted"

        # Resume: same dirs, --resume_training.
        cfg_c, svc_c = self._launch("killed", str(tmp_path / "b"),
                                    stream_data, resume=True)
        svc_c.run()
        # Zero accepted-row loss: every WAL row is back in the pool.
        assert svc_c.store.n_rows == 96 + self.WAL_ROWS
        state = _state_of(cfg_c)
        assert set(state) == set(baseline)
        for k in baseline:
            assert np.array_equal(baseline[k], state[k]), (
                f"experiment_state[{k!r}] diverged after mid-round "
                "preemption resume")

    def test_preempt_mid_round0_resumes_bit_identical(self, stream_data,
                                                      tmp_path):
        """Preempted DURING the bootstrap round's fit — before any
        save_experiment exists — the journal's round-0 preemption
        record (which the resume path must read BEFORE this run's
        journal writes anything) unlocks the replay, and the result is
        bit-identical to the uninterrupted twin."""
        cfg_a, svc_a = self._launch("uninter0", str(tmp_path / "a"),
                                    stream_data)
        svc_a.run()
        baseline = _state_of(cfg_a)

        sink = _PreemptAtEpochSink(rd=0, epoch=1)
        cfg_b, svc_b = self._launch("killed0", str(tmp_path / "b"),
                                    stream_data, sink=sink)
        with pytest.raises(preempt_lib.PreemptionRequested):
            svc_b.run()
        assert sink.fired
        assert not glob.glob(os.path.join(cfg_b.ckpt_path, "*",
                                          "experiment_state.npz"))
        cfg_c, svc_c = self._launch("killed0", str(tmp_path / "b"),
                                    stream_data, resume=True)
        svc_c.run()
        assert svc_c.store.n_rows == 96 + self.WAL_ROWS
        state = _state_of(cfg_c)
        for k in baseline:
            assert np.array_equal(baseline[k], state[k]), (
                f"experiment_state[{k!r}] diverged after round-0 "
                "preemption resume")

    def test_drain_fault_crashes_clean_and_restart_loses_nothing(
            self, stream_data, tmp_path):
        """An injected stream_drain failure crashes the service BEFORE
        any round consumes a half-applied pool (the site's contract) —
        rows stay durable in the WAL, and a restart over the same
        log_dir replays them all."""
        cfg, svc = self._launch("drainfault", str(tmp_path),
                                stream_data)
        faults.configure("stream_drain:raise@1", seed=0)
        try:
            with pytest.raises(faults.InjectedFault):
                svc.run()
        finally:
            faults.configure(None)
        # Restart over the SAME dirs (no resume flag: round 0 never
        # completed): the WAL replay rebuilds the queue and the run
        # completes with every accepted row present.
        cfg2, svc2 = self._launch("drainfault", str(tmp_path),
                                  stream_data, prefill=False)
        svc2.run()
        assert svc2.store.n_rows == 96 + self.WAL_ROWS


# ---------------------------------------------------------------------------
# status --strict: the ingest-starved exit-5 contract
# ---------------------------------------------------------------------------

class TestStatusIngestStarved:
    def _dir(self, tmp_path, *, backlog, trigger_age_s, status="running"):
        from active_learning_tpu.faults.journal import RoundJournal
        from active_learning_tpu.telemetry import heartbeat as hb_lib
        d = str(tmp_path)
        os.makedirs(d, exist_ok=True)
        hb = hb_lib.HeartbeatWriter(os.path.join(d, "heartbeat.json"),
                                    every_s=0.0, stall_deadline_s=600.0)
        hb.tick(round=1, phase="stream_wait", status="running")
        j = RoundJournal(os.path.join(d, faults.JOURNAL_FILE))
        j.write(status=status, stream=True, stream_pool_rows=128,
                stream_wal_backlog=backlog, stream_rounds_run=2,
                stream_last_trigger_cause="watermark",
                stream_last_trigger_ts=time.time() - trigger_age_s)
        return d

    def test_backlog_past_deadline_is_5_only_under_strict(self, tmp_path):
        d = self._dir(tmp_path, backlog=500, trigger_age_s=10_000)
        assert status_lib.main(["--log_dir", d]) == 0
        assert status_lib.main(["--log_dir", d, "--strict"]) == 5
        text = status_lib.render_text(status_lib.summarize(d))
        assert "INGEST-STARVED" in text

    def test_recent_trigger_or_empty_backlog_is_healthy(self, tmp_path):
        d = self._dir(tmp_path / "a", backlog=500, trigger_age_s=1.0)
        assert status_lib.main(["--log_dir", d, "--strict"]) == 0
        d = self._dir(tmp_path / "b", backlog=0, trigger_age_s=10_000)
        assert status_lib.main(["--log_dir", d, "--strict"]) == 0

    def test_terminal_status_is_never_starved(self, tmp_path):
        d = self._dir(tmp_path, backlog=500, trigger_age_s=10_000,
                      status="preempted")
        assert status_lib.main(["--log_dir", d, "--strict"]) == 0


# ---------------------------------------------------------------------------
# Labeled-gauge convention (telemetry/prom)
# ---------------------------------------------------------------------------

class TestLabeledGauges:
    def test_bracketed_key_renders_with_label(self):
        samples = prom_lib.gauge_samples(
            {"rounds_triggered{cause=drift}": 2, "plain": 1.5},
            prefix="al_run_")
        text = prom_lib.render(samples)
        parsed = prom_lib.parse(text)
        assert parsed["al_run_rounds_triggered"][(("cause", "drift"),)] \
            == 2.0
        assert parsed["al_run_plain"][()] == 1.5


# ---------------------------------------------------------------------------
# Incremental resident row update (ISSUE 15 satellite: the drain stops
# re-uploading the pinned extent)
# ---------------------------------------------------------------------------

class TestIncrementalResidentUpdate:
    """parallel/resident.update_rows: an in-extent streaming drain
    refreshes a PINNED pool entry by dynamic_update_slice of ONLY the
    new rows (plus a tiny whole-labels device_put) — never a full
    re-upload of the pinned extent, never a compile once prewarmed."""

    def _pin(self, sharding):
        from active_learning_tpu.parallel import mesh as mesh_lib
        from active_learning_tpu.parallel import resident as resident_lib
        _, _, al_set = get_data_synthetic(n_train=96, n_test=16,
                                          num_classes=4, image_size=8,
                                          seed=9)
        # A writable copy: the synthetic arrays may be shared across
        # tests and the point here is to mutate rows in place.
        al_set.images = al_set.images.copy()
        al_set.targets = al_set.targets.copy()
        mesh = mesh_lib.make_mesh()
        cache = {}
        resident_lib.pool_arrays(cache, al_set, mesh, sharding=sharding)
        return cache, al_set, mesh, resident_lib, mesh_lib

    @pytest.mark.parametrize("sharding", ["replicated", "row"])
    def test_update_refreshes_rows_and_labels_in_place(self, sharding):
        cache, ds, mesh, resident_lib, mesh_lib = self._pin(sharding)
        rng = np.random.default_rng(0)
        ds.images[80:96] = rng.integers(0, 255, ds.images[80:96].shape,
                                        dtype=np.uint8)
        ds.targets[80:96] = (ds.targets[80:96] + 1) % 4
        assert resident_lib.update_rows(cache, ds, mesh, 80, 96)
        key = (id(ds.images), 96)
        _, images_dev, labels_dev = cache["images"][key]
        got = np.asarray(images_dev)[:96]
        np.testing.assert_array_equal(got, ds.images[:96])
        np.testing.assert_array_equal(
            np.asarray(labels_dev)[:96],
            ds.targets[:96].astype(np.int32))
        assert mesh_lib.is_row_sharded(images_dev) == (sharding == "row")

    @pytest.mark.parametrize("sharding", ["replicated", "row"])
    def test_no_full_image_reupload(self, sharding, monkeypatch):
        """THE satellite pin: during an in-extent update no image array
        crosses the host->device boundary through the upload primitives
        — only the [capacity]-labels vector does (1-D).  A regression
        back to release + re-upload would ship the whole pinned extent
        again and fail here."""
        cache, ds, mesh, resident_lib, mesh_lib = self._pin(sharding)
        uploads = []

        real_shard_rows = mesh_lib.shard_rows
        real_replicate = mesh_lib.replicate

        def spy_shard_rows(array, *a, **k):
            uploads.append(np.asarray(array).ndim)
            return real_shard_rows(array, *a, **k)

        def spy_replicate(tree, *a, **k):
            for leaf in np.asarray(tree, dtype=object).reshape(-1) \
                    if isinstance(tree, (list, tuple)) else [tree]:
                uploads.append(np.asarray(leaf).ndim)
            return real_replicate(tree, *a, **k)

        monkeypatch.setattr(mesh_lib, "shard_rows", spy_shard_rows)
        monkeypatch.setattr(mesh_lib, "replicate", spy_replicate)
        ds.images[90:96] ^= 1
        assert resident_lib.update_rows(cache, ds, mesh, 90, 96)
        assert uploads and all(nd == 1 for nd in uploads), uploads

    def test_unpinned_entry_returns_false(self):
        from active_learning_tpu.parallel import mesh as mesh_lib
        from active_learning_tpu.parallel import resident as resident_lib
        _, _, al_set = get_data_synthetic(n_train=96, n_test=16,
                                          num_classes=4, image_size=8)
        assert not resident_lib.update_rows({}, al_set,
                                            mesh_lib.make_mesh(), 0, 8)

    def test_pool_smaller_than_one_window_falls_back(self):
        """A pool the fixed window cannot express (fewer rows than
        UPDATE_BLOCK_FLOOR) refuses — the caller's release + re-upload
        path owns it (re-uploading a tiny pool is trivially cheap)."""
        from active_learning_tpu.data.core import ArrayDataset
        from active_learning_tpu.parallel import mesh as mesh_lib
        from active_learning_tpu.parallel import resident as resident_lib
        rng = np.random.default_rng(2)
        tiny = ArrayDataset(
            rng.integers(0, 255, (32, 8, 8, 3), dtype=np.uint8),
            np.zeros(32, dtype=np.int64), 4,
            get_data_synthetic(n_train=8, n_test=8)[2].view)
        mesh = mesh_lib.make_mesh()
        cache = {}
        resident_lib.pool_arrays(cache, tiny, mesh)
        assert not resident_lib.update_rows(cache, tiny, mesh, 0, 8)
        assert not resident_lib.prewarm_update(cache, tiny, mesh)

    @pytest.mark.parametrize("sharding", ["replicated", "row"])
    def test_prewarmed_update_adds_zero_compiles(self, sharding):
        """The delta-0 contract: prewarm_update builds + warms the ONE
        fixed-width updater; every real in-extent drain after it —
        narrow OR wider than the window (drains chunk into fixed-width
        blocks) — dispatches the SAME executable, zero new compiles
        (the in-extent rounds of TestStreamExtentCompileReuse rest on
        this)."""
        cache, ds, mesh, resident_lib, _ = self._pin(sharding)
        assert resident_lib.prewarm_update(cache, ds, mesh)
        runners = {k: v for k, v in cache["steps"].items()
                   if isinstance(k, tuple) and k and k[0] == "update_rows"}
        assert runners
        sizes = {k: v._cache_size() for k, v in runners.items()}
        ds.images[88:96] ^= 1
        assert resident_lib.update_rows(cache, ds, mesh, 88, 96)
        # A drain WIDER than the window must reuse the same executable
        # too (the review finding: a watermark > window once compiled a
        # fresh width inside a warm round).
        ds.images[0:96] ^= 2
        assert resident_lib.update_rows(cache, ds, mesh, 0, 96)
        assert {k: v._cache_size() for k, v in runners.items()} == sizes
        np.testing.assert_array_equal(
            np.asarray(cache["images"][(id(ds.images), 96)][1])[:96],
            ds.images[:96])

    def test_prewarm_is_noop_once_warm(self, monkeypatch):
        """Once the (layout, shape) pair is warmed, prewarm_update does
        NOTHING — no label re-upload, no identity dispatch — so the
        per-round service call stays free on drainless rounds."""
        cache, ds, mesh, resident_lib, mesh_lib = self._pin("replicated")
        assert resident_lib.prewarm_update(cache, ds, mesh)
        calls = []
        monkeypatch.setattr(
            mesh_lib, "replicate",
            lambda *a, **k: calls.append(1) or (_ for _ in ()).throw(
                AssertionError("prewarm re-uploaded after warm")))
        assert resident_lib.prewarm_update(cache, ds, mesh)
        assert not calls

    def test_label_upload_failure_leaves_entry_intact(self, monkeypatch):
        """Labels upload BEFORE the donating image dispatch (and under
        the upload RetryPolicy): a label-upload failure propagates with
        the pinned entry untouched and still valid."""
        cache, ds, mesh, resident_lib, mesh_lib = self._pin("replicated")

        def boom(*a, **k):
            raise RuntimeError("injected label-upload failure")

        monkeypatch.setattr(mesh_lib, "replicate", boom)
        with pytest.raises(RuntimeError, match="label-upload"):
            resident_lib.update_rows(cache, ds, mesh, 80, 96)
        monkeypatch.undo()
        assert resident_lib.cached(cache, ds)
        # The untouched entry still serves reads.
        key = (id(ds.images), 96)
        np.testing.assert_array_equal(
            np.asarray(cache["images"][key][1])[:96], ds.images[:96])

    def test_failed_donating_update_drops_entry(self, monkeypatch):
        """A failure inside the donating image dispatch may have
        consumed the old buffer: the entry must be DROPPED before the
        exception propagates — a cache entry pointing at a deleted
        array would poison every retry (the review finding).  The next
        access re-uploads cleanly."""
        cache, ds, mesh, resident_lib, _ = self._pin("replicated")

        def boom(*a, **k):
            def run(*aa, **kk):
                raise RuntimeError("injected dispatch failure")
            return run

        monkeypatch.setattr(resident_lib, "_update_runner", boom)
        with pytest.raises(RuntimeError, match="injected"):
            resident_lib.update_rows(cache, ds, mesh, 80, 96)
        assert not resident_lib.cached(cache, ds)
        monkeypatch.undo()
        # Recovery: the next pool_arrays call re-pins from host.
        resident_lib.pool_arrays(cache, ds, mesh)
        assert resident_lib.cached(cache, ds)
