"""The row-sharded resident pool (ISSUE 6, DESIGN.md §2b), pinned.

Three claims make the sharded pool safe to default on:

  1. PICK IDENTITY — row-sharded k-center selection (collective backend,
     strategies/kcenter._build_sharded_fns) produces the IDENTICAL pick
     sequence to the replicated scans at the same seeds, for the
     deterministic (batched and q=1), randomized (D^2), and
     empty-labeled (minimax seed) modes, single- and two-factor.
  2. BIT IDENTITY — sharded collect_pool scores and resident-gather
     train batches are bit-for-bit the replicated (and host) results:
     the layout is a throughput/HBM choice, never a numerics one.
  3. THE HBM MATH — per-device resident bytes for a row-sharded pool
     are <= replicated bytes / num_devices + one row of pad slack, and
     the shared budget accounting (eligible's shard_ways) admits pools
     ~ndev x larger.

Everything runs on the conftest 8-device CPU mesh — the same virtual
mesh the sharding/collective code paths compile for on real chips.
"""

import dataclasses

import jax
import numpy as np
import pytest

from active_learning_tpu.parallel import mesh as mesh_lib
from active_learning_tpu.parallel import resident as resident_lib
from active_learning_tpu.strategies import kcenter as kc
from active_learning_tpu.strategies import scoring
from active_learning_tpu.data.synthetic import get_data_synthetic
from active_learning_tpu.train.trainer import Trainer

from helpers import TinyClassifier, tiny_train_config


def oracle_kcenter(emb, labeled_mask, budget):
    """The reference greedy loop (also in test_kcenter.py)."""
    d = ((emb[:, None, :] - emb[None, :, :]) ** 2).sum(-1)
    lab = labeled_mask.copy()
    picks = []
    for _ in range(budget):
        if lab.sum() > 0:
            q = int(d[:, lab].min(axis=1).argmax())
        else:
            q = int(d.max(axis=1).argmin())
        picks.append(q)
        lab[q] = True
    return np.asarray(picks)


class TestPickIdentity:
    """Acceptance: on a multi-device CPU mesh, row-sharded k-center
    produces the identical pick sequence to the replicated backend."""

    def _both(self, emb, labeled, budget, q, randomize=False, seed=1):
        factors = emb if isinstance(emb, tuple) else (emb,)
        rep = kc.kcenter_greedy(factors, labeled, budget,
                                randomize=randomize,
                                rng=np.random.default_rng(seed),
                                batch_q=q, pool_sharding="replicated")
        assert kc.LAST_SHARDING == "replicated"
        row = kc.kcenter_greedy(factors, labeled, budget,
                                randomize=randomize,
                                rng=np.random.default_rng(seed),
                                batch_q=q, mesh=mesh_lib.make_mesh(),
                                pool_sharding="row")
        assert kc.LAST_SHARDING == "row"
        return rep, row

    @pytest.mark.parametrize("q", [1, 3, 8])
    def test_deterministic_matches_replicated_and_oracle(self, q):
        rng = np.random.default_rng(11)
        emb = rng.normal(size=(70, 6)).astype(np.float32)
        labeled = np.zeros(70, dtype=bool)
        labeled[rng.choice(70, 9, replace=False)] = True
        rep, row = self._both(emb, labeled, 13, q)
        np.testing.assert_array_equal(row, rep)
        np.testing.assert_array_equal(row, oracle_kcenter(emb, labeled, 13))

    def test_empty_labeled_minimax_seed(self):
        """Nothing labeled: the sharded minimax seed (host column blocks
        folded into a sharded row-max, pad rows masked from the argmin)
        replays the replicated seed and the oracle."""
        rng = np.random.default_rng(12)
        emb = rng.normal(size=(40, 4)).astype(np.float32)
        labeled = np.zeros(40, dtype=bool)
        rep, row = self._both(emb, labeled, 9, 4)
        np.testing.assert_array_equal(row, rep)
        np.testing.assert_array_equal(row, oracle_kcenter(emb, labeled, 9))

    def test_randomized_d2_identical_draws(self):
        """BADGE mode: the sharded D^2 draw all_gathers the O(N) weight
        vector and consumes the SAME key chain — identical picks, not
        merely identically-distributed ones."""
        rng = np.random.default_rng(13)
        emb = rng.normal(size=(60, 6)).astype(np.float32)
        labeled = np.zeros(60, dtype=bool)
        labeled[:10] = True
        rep, row = self._both(emb, labeled, 15, 1, randomize=True, seed=5)
        np.testing.assert_array_equal(row, rep)

    def test_two_factor_badge_layout(self):
        rng = np.random.default_rng(14)
        a = rng.normal(size=(30, 5)).astype(np.float32)
        e = rng.normal(size=(30, 7)).astype(np.float32)
        g = np.einsum("nc,nd->ncd", a, e).reshape(30, -1)
        labeled = np.zeros(30, dtype=bool)
        labeled[[2, 17]] = True
        rep, row = self._both((a, e), labeled, 7, 4)
        np.testing.assert_array_equal(row, rep)
        np.testing.assert_array_equal(row, oracle_kcenter(g, labeled, 7))

    def test_single_device_mesh_falls_back_to_replicated(self):
        rng = np.random.default_rng(15)
        emb = rng.normal(size=(32, 4)).astype(np.float32)
        labeled = np.zeros(32, dtype=bool)
        labeled[:4] = True
        kc.kcenter_greedy((emb,), labeled, 5,
                          rng=np.random.default_rng(1),
                          mesh=mesh_lib.make_mesh(1), pool_sharding="row")
        assert kc.LAST_SHARDING == "replicated"


class TestShardedScoring:
    """collect_pool over a row-sharded resident pool returns bit-for-bit
    the replicated-resident and host-streamed scores."""

    def _setup(self):
        _, _, al_set = get_data_synthetic(n_train=96, n_test=16,
                                          num_classes=4, image_size=8,
                                          seed=3)
        mesh = mesh_lib.make_mesh()
        model = TinyClassifier(num_classes=4)
        variables = model.init(jax.random.PRNGKey(0),
                               al_set.gather(np.zeros(1, np.int64)),
                               train=False)
        variables = mesh_lib.replicate(variables, mesh)
        step = scoring.make_prob_stats_step(model, al_set.view)
        return al_set, mesh, variables, step

    def test_scores_bit_identical_across_layouts(self):
        al_set, mesh, variables, step = self._setup()
        idxs = np.arange(len(al_set))
        kwargs = dict(batch_size=16, step_fn=step, variables=variables,
                      mesh=mesh)
        host = scoring.collect_pool(al_set, idxs, **kwargs)
        rep_cache, row_cache = {}, {}
        rep = scoring.collect_pool(al_set, idxs, resident_cache=rep_cache,
                                   resident_max_bytes=2 ** 31,
                                   pool_sharding="replicated", **kwargs)
        row = scoring.collect_pool(al_set, idxs, resident_cache=row_cache,
                                   resident_max_bytes=2 ** 31,
                                   pool_sharding="row", **kwargs)
        images_dev = row_cache["images"][next(
            iter(row_cache["images"]))][1]
        assert mesh_lib.is_row_sharded(images_dev)
        assert not mesh_lib.is_row_sharded(
            rep_cache["images"][next(iter(rep_cache["images"]))][1])
        for k in ("confidence", "margin", "entropy", "pred"):
            np.testing.assert_array_equal(row[k], rep[k])
            np.testing.assert_array_equal(row[k], host[k])

    def test_row_entry_reused_zero_new_compiles_on_second_pass(self):
        """Warm-round regression for sharded scoring: a second pass over
        the same row-sharded pool reuses the entry AND the runner
        executable — zero new compiles."""
        al_set, mesh, variables, step = self._setup()
        idxs = np.arange(len(al_set))
        cache = {}
        kwargs = dict(batch_size=16, step_fn=step, variables=variables,
                      mesh=mesh, resident_cache=cache,
                      resident_max_bytes=2 ** 31, pool_sharding="row")
        first = scoring.collect_pool(al_set, idxs, **kwargs)
        assert len(cache["images"]) == 1 and len(cache["steps"]) == 1
        runner = next(iter(cache["steps"].values()))
        compiles = runner._cache_size()
        second = scoring.collect_pool(al_set, idxs, **kwargs)
        assert len(cache["images"]) == 1 and len(cache["steps"]) == 1
        assert runner._cache_size() == compiles
        for k in first:
            np.testing.assert_array_equal(first[k], second[k])


class TestShardedTrainFeed:
    """The resident-gather train feed over a row-sharded pool trains to
    BITWISE-identical parameters vs the replicated layout (same seeds,
    same batch stream, same sharded step program)."""

    def _fit(self, pool_sharding):
        train_set, _, al_set = get_data_synthetic(
            n_train=90, n_test=16, num_classes=4, image_size=8, seed=6)
        cfg = dataclasses.replace(tiny_train_config(),
                                  train_feed="resident",
                                  pool_sharding=pool_sharding)
        mesh = mesh_lib.make_mesh(8)
        trainer = Trainer(TinyClassifier(), cfg, mesh, 4)
        state = trainer.init_state(jax.random.PRNGKey(0),
                                   train_set.gather(np.zeros(1, np.int64)))
        # 83 labeled with batch 16: a PADDED last batch — padding
        # isolation must survive the sharded gather too.
        result = trainer.fit(state, train_set, np.arange(83), al_set,
                             np.arange(83, 90), n_epoch=3,
                             es_patience=0, rng=np.random.default_rng(42))
        return trainer, result

    @staticmethod
    def _leaves(result):
        return jax.tree_util.tree_leaves(
            jax.tree.map(np.asarray, result.state.variables))

    def test_row_fit_bitwise_identical_to_replicated(self):
        t_row, row = self._fit("row")
        assert t_row.last_feed["source"] == "resident"
        assert t_row.pool_sharding == "row"
        images_dev = t_row.resident_pool["images"][next(
            iter(t_row.resident_pool["images"]))][1]
        assert mesh_lib.is_row_sharded(images_dev)
        t_rep, rep = self._fit("replicated")
        assert t_rep.last_feed["source"] == "resident"
        assert t_rep.pool_sharding == "replicated"
        for a, b in zip(self._leaves(row), self._leaves(rep)):
            np.testing.assert_array_equal(a, b)

    def test_auto_resolves_row_on_multi_device_mesh(self):
        t, _ = self._fit("auto")
        assert t.pool_sharding == "row"
        assert t._shard_ways == 8

    def test_sharded_eval_counts_match_replicated(self):
        t_row, row = self._fit("row")
        t_rep, rep = self._fit("replicated")
        _, _, al_set = get_data_synthetic(
            n_train=90, n_test=16, num_classes=4, image_size=8, seed=6)
        # Evaluate over each trainer's own cached dataset object so the
        # resident entries (one row-sharded, one replicated) are reused.
        def ev(trainer, result):
            ds = trainer.resident_pool["images"][next(
                iter(trainer.resident_pool["images"]))][0]
            return trainer.evaluate(result.state, ds, np.arange(24))
        pr, pp = ev(t_row, row), ev(t_rep, rep)
        assert float(pr["accuracy"]) == float(pp["accuracy"])
        np.testing.assert_array_equal(np.asarray(pr["accuracy_byclass"]),
                                      np.asarray(pp["accuracy_byclass"]))


class TestResidentBytesAndBudget:
    """The HBM math: per-device bytes, eligibility scaling, and the
    resolve_sharding gates."""

    def test_per_device_bytes_scale_with_devices(self):
        """Acceptance: per-device resident bytes for the same pool are
        <= replicated bytes / num_devices + one row of pad slack."""
        _, _, al_set = get_data_synthetic(n_train=96, n_test=16,
                                          num_classes=4, image_size=8)
        mesh = mesh_lib.make_mesh()
        ndev = mesh.devices.size
        rep_cache, row_cache = {}, {}
        resident_lib.pool_arrays(rep_cache, al_set, mesh,
                                 sharding="replicated")
        resident_lib.pool_arrays(row_cache, al_set, mesh, sharding="row")
        rep_bytes = resident_lib.pinned_bytes(rep_cache)
        row_bytes = resident_lib.pinned_bytes(row_cache)
        assert rep_bytes == al_set.images[:96].nbytes
        per_row = int(np.prod(al_set.images.shape[1:])) \
            * al_set.images.itemsize
        assert row_bytes <= rep_bytes / ndev + per_row
        assert row_bytes == -(-96 // ndev) * per_row

    def test_sharded_gather_returns_exact_rows(self):
        _, _, al_set = get_data_synthetic(n_train=96, n_test=16,
                                          num_classes=4, image_size=8)
        mesh = mesh_lib.make_mesh()
        cache = {}
        images_dev, labels_dev = resident_lib.pool_arrays(
            cache, al_set, mesh, sharding="row")
        ids = np.asarray([3, 50, 95, 0, 17, 88, 41, 2], np.int32)
        img, lab = jax.jit(
            lambda im, lb, i: resident_lib.sharded_pool_gather(
                im, i, mesh, labels=lb))(images_dev, labels_dev,
                                         jax.numpy.asarray(ids))
        np.testing.assert_array_equal(np.asarray(img),
                                      al_set.images[ids])
        np.testing.assert_array_equal(
            np.asarray(lab), al_set.targets[ids].astype(np.int32))

    def test_eligible_shard_ways_scales_the_budget(self):
        _, _, al_set = get_data_synthetic(n_train=96, n_test=16,
                                          num_classes=4, image_size=8)
        full = al_set.images[:96].nbytes
        # Replicated: the pool must fit whole.
        assert resident_lib.eligible(al_set, full, cache={})
        assert not resident_lib.eligible(al_set, full - 1, cache={})
        # Row-sharded over 8: an eighth (rounded up to whole rows) fits.
        per_row = int(np.prod(al_set.images.shape[1:])) \
            * al_set.images.itemsize
        need = -(-96 // 8) * per_row
        assert resident_lib.eligible(al_set, need, cache={},
                                     shard_ways=8)
        assert not resident_lib.eligible(al_set, need - 1, cache={},
                                         shard_ways=8)

    def test_resolve_sharding_rules(self):
        mesh8 = mesh_lib.make_mesh()
        mesh1 = mesh_lib.make_mesh(1)
        assert resident_lib.resolve_sharding("auto", mesh8) == "row"
        assert resident_lib.resolve_sharding(None, mesh8) == "row"
        assert resident_lib.resolve_sharding("replicated", mesh8) \
            == "replicated"
        assert resident_lib.resolve_sharding("auto", mesh1) == "replicated"
        assert resident_lib.resolve_sharding("row", mesh1) == "replicated"
        with pytest.raises(ValueError):
            resident_lib.resolve_sharding("diagonal", mesh8)


class TestRowCapableGate:
    """kcenter.row_capable IS kcenter_greedy's layout gate, exported so
    callers that must know the layout BEFORE paying for a selection (the
    kcenter_select_maxn bench climbs ndev-times-larger pools on the row
    rungs) can refuse an attempt instead of discovering a silent
    replicated fallback — at ndev times the per-chip bytes — after the
    run."""

    def test_capable_on_the_divisible_mesh(self):
        assert kc.row_capable(4096, 64, mesh_lib.make_mesh())

    def test_not_capable_when_bucket_does_not_split(self):
        # 3 of the 8 CPU devices: bucket_size(4096) = 4096 rows never
        # split 3 ways...
        mesh3 = mesh_lib.make_mesh(3)
        assert not kc.row_capable(4096, 64, mesh3)
        # ...but a bucket that happens to (3072 = 6 * 512) does.
        assert kc.row_capable(3072, 64, mesh3)

    def test_not_capable_when_shards_smaller_than_q(self):
        # bucket_size(64) = 256 rows over 8 devices = 32 per shard,
        # fewer than a q=512 candidate batch.
        mesh = mesh_lib.make_mesh()
        assert not kc.row_capable(64, 512, mesh, batch_q=512)
        assert kc.row_capable(64, 512, mesh, batch_q=8)

    def test_never_capable_without_a_mesh_or_alone(self):
        assert not kc.row_capable(4096, 64, None)
        assert not kc.row_capable(4096, 64, mesh_lib.make_mesh(1))

    def test_greedy_fallback_agrees_with_the_gate(self):
        """Row requested on a mesh the gate rejects: the greedy runs
        replicated (LAST_SHARDING tells the truth) and still returns
        the replicated picks — the gate predicted the fallback."""
        mesh3 = mesh_lib.make_mesh(3)
        rng = np.random.default_rng(21)
        emb = rng.normal(size=(40, 4)).astype(np.float32)
        labeled = np.zeros(40, dtype=bool)
        labeled[:5] = True
        assert not kc.row_capable(40, 7, mesh3)
        row = kc.kcenter_greedy((emb,), labeled, 7,
                                rng=np.random.default_rng(1),
                                mesh=mesh3, pool_sharding="row")
        assert kc.LAST_SHARDING == "replicated"
        rep = kc.kcenter_greedy((emb,), labeled, 7,
                                rng=np.random.default_rng(1),
                                pool_sharding="replicated")
        np.testing.assert_array_equal(row, rep)


class TestShardRowsUpload:
    """shard_rows builds the device array PER SHARD — the pad (and the
    contiguous copy) materialize one shard at a time, never as a second
    full-size host array."""

    def test_rows_param_pads_to_target_bucket(self):
        mesh = mesh_lib.make_mesh()
        rng = np.random.default_rng(31)
        a = rng.integers(0, 255, size=(70, 3), dtype=np.uint8)
        out = mesh_lib.shard_rows(a, mesh, rows=96)
        assert out.shape == (96, 3)
        assert mesh_lib.is_row_sharded(out)
        host = np.asarray(out)
        np.testing.assert_array_equal(host[:70], a)
        assert not host[70:].any()
        assert max(s.data.shape[0]
                   for s in out.addressable_shards) == 96 // 8

    def test_default_rows_pads_to_divide_evenly(self):
        mesh = mesh_lib.make_mesh()
        a = np.arange(70 * 2, dtype=np.float32).reshape(70, 2)
        out = mesh_lib.shard_rows(a, mesh)
        assert out.shape[0] == 72  # 70 + pad to /8
        np.testing.assert_array_equal(np.asarray(out)[:70], a)

    def test_rows_below_array_length_rejected(self):
        mesh = mesh_lib.make_mesh()
        with pytest.raises(ValueError):
            mesh_lib.shard_rows(np.zeros((16, 2), np.float32), mesh,
                                rows=8)
