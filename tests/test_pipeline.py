"""The pipelined AL round (experiment/pipeline.py, DESIGN.md §8).

The pipeline's one non-negotiable claim is the correctness contract:
speculative scoring and select-time prefetch change WALL-CLOCK only —
picks, scores, and experiment_state are bit-identical to the sequential
loop at the same seeds.  Pinned here:

  * chunk-resumable scoring: collect_pool over chunk_row_slices splices
    back bit-identical to the monolithic pass (the property the
    speculative scorer leans on);
  * the best-ckpt bus: publish_best's atomic weights+tag pair and
    BestCkptWatcher's monotonic, never-torn polls, including against an
    interleaved writer hammering publishes from another thread;
  * RoundPipeline mechanics: a speculative hit serves bit-identical
    scores, a FORCED late-epoch best improvement invalidates the
    already-scored chunks and recomputes from the final checkpoint, and
    a plan miss degrades to the sequential pass (never a wrong score);
  * end-to-end: --round_pipeline speculative vs off produce
    bit-identical experiment_state across 2 rounds on the multi-device
    CPU mesh, with the overlap telemetry landing in the metrics stream;
  * the status verb renders BOTH active phases of a pipelined round.
"""

from __future__ import annotations

import glob
import json
import os
import threading
import time

import numpy as np
import pytest

from active_learning_tpu.config import ExperimentConfig, TelemetryConfig
from active_learning_tpu.data.synthetic import get_data_synthetic
from active_learning_tpu.experiment import arg_pools  # noqa: F401
from active_learning_tpu.experiment import pipeline as pipeline_lib
from active_learning_tpu.experiment.driver import run_experiment
from active_learning_tpu.strategies import scoring
from active_learning_tpu.telemetry import status as status_lib
from active_learning_tpu.train import checkpoint as ckpt_lib
from active_learning_tpu.utils.metrics import JsonlSink

from helpers import TinyClassifier, make_strategy, tiny_train_config


def _wait_for(pred, timeout_s: float = 60.0, what: str = "condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


def _wait_for_chunks(pipe, n: int, what: str) -> None:
    """Wait for ``n`` speculative chunks — or SKIP if the scorer lost a
    chunk to an environmental failure (a saturated CI box can starve or
    OOM the scorer thread mid-chunk).  The production contract holds
    either way (a failed chunk costs a sequential recompute, never a
    score — pinned by the chaos tests); only the HIT-path assertions
    below become unreachable, so a skip is the honest verdict, not a
    red."""
    _wait_for(lambda: (pipe.stats["chunks_scored"] >= n
                       or pipe.stats["chunks_failed"] > 0), what=what)
    if pipe.stats["chunks_failed"]:
        pytest.skip("speculative chunk failed in this environment; "
                    "the hit path cannot be exercised this run "
                    "(best-effort contract covered by the fallback "
                    "tests)")


# -- chunk-resumable scoring -------------------------------------------------


class TestChunkResumableScoring:
    @pytest.mark.parametrize("n_rows,bs,cb", [
        (1, 16, 8), (16, 16, 8), (100, 16, 4), (392, 16, 8), (129, 16, 1),
    ])
    def test_slices_are_batch_aligned_and_cover_all_rows(self, n_rows, bs,
                                                         cb):
        slices = scoring.chunk_row_slices(n_rows, bs, cb)
        assert slices[0].start == 0 and slices[-1].stop == n_rows
        for a, b in zip(slices, slices[1:]):
            assert a.stop == b.start
        # Every interior boundary is a batch boundary: a chunk always
        # covers WHOLE batches of the monolithic pass.
        for sl in slices[:-1]:
            assert sl.stop % bs == 0

    def test_empty_and_splice_roundtrip(self):
        assert scoring.chunk_row_slices(0, 16, 8) == []
        parts = [{"s": np.arange(3)}, {"s": np.arange(3, 7)}]
        out = scoring.splice_chunks(parts)
        assert np.array_equal(out["s"], np.arange(7))
        one = [{"s": np.arange(5)}]
        assert scoring.splice_chunks(one) is one[0]

    def test_chunked_collect_pool_bit_identical_to_monolithic(self):
        """The property the speculative scorer is built on: scoring
        batch-aligned row slices separately (out of order, even) and
        splicing produces the EXACT bits of the one-call pass."""
        strategy = make_strategy("MarginSampler", n_train=200,
                                 init_pool=8)
        idxs = strategy.pool.available_query_idxs(shuffle=False)
        bs = strategy._score_batch_size()
        step = strategy._get_score_step("prob_stats")
        loader = strategy.train_cfg.loader_te
        kwargs = dict(num_workers=loader.num_workers,
                      prefetch=loader.prefetch,
                      **strategy._resident_kwargs())
        whole = scoring.collect_pool(strategy.al_set, idxs, bs, step,
                                     strategy.state.variables,
                                     strategy.mesh, **kwargs)
        slices = scoring.chunk_row_slices(len(idxs), bs, 3)
        assert len(slices) >= 3
        chunks = [scoring.collect_pool(strategy.al_set, idxs[sl], bs, step,
                                       strategy.state.variables,
                                       strategy.mesh, **kwargs)
                  for sl in reversed(slices)]
        spliced = scoring.splice_chunks(list(reversed(chunks)))
        assert set(spliced) == set(whole)
        for k in whole:
            assert np.array_equal(spliced[k], whole[k]), k


# -- the best-ckpt bus -------------------------------------------------------


class TestBestCkptBus:
    def _vars(self, value: float, n: int = 8):
        return {"params": {"w": np.full(n, value, dtype=np.float32)}}

    def test_publish_poll_roundtrip_and_monotonic_tags(self, tmp_path):
        d = str(tmp_path)
        path = os.path.join(d, "best_rd_0.msgpack")
        watcher = ckpt_lib.BestCkptWatcher(d)
        assert watcher.poll() is None  # empty dir
        ckpt_lib.publish_best(path, self._vars(3.0), round_idx=0, epoch=3)
        variables, rd, tag = watcher.poll()
        assert rd == 0 and tag == (0, 3)
        assert np.array_equal(variables["params"]["w"],
                              self._vars(3.0)["params"]["w"])
        # Nothing new: the same publish never reports twice.
        assert watcher.poll() is None
        # A later best epoch of the same round supersedes ...
        ckpt_lib.publish_best(path, self._vars(5.0), round_idx=0, epoch=5)
        _, _, tag = watcher.poll()
        assert tag == (0, 5)
        # ... and a newer round supersedes that, even at a lower epoch.
        ckpt_lib.publish_best(os.path.join(d, "best_rd_1.msgpack"),
                              self._vars(1.0), round_idx=1, epoch=1)
        _, rd, tag = watcher.poll()
        assert rd == 1 and tag == (1, 1)

    def test_tag_sidecar_absent_reads_none_and_legacy_ckpt_polls(self,
                                                                 tmp_path):
        d = str(tmp_path)
        path = os.path.join(d, "best_rd_0.msgpack")
        assert ckpt_lib.read_best_tag(path) is None
        # A pre-tag (legacy) writer: plain save_variables, no sidecar.
        ckpt_lib.save_variables(path, self._vars(2.0))
        watcher = ckpt_lib.BestCkptWatcher(d)
        variables, rd, tag = watcher.poll()
        assert rd == 0 and tag is None
        # A tagged publish of the SAME round supersedes the untagged one
        # even within one mtime granule (the tag is the newer code).
        ckpt_lib.publish_best(path, self._vars(4.0), round_idx=0, epoch=4)
        variables, rd, tag = watcher.poll()
        assert tag == (0, 4)
        assert float(variables["params"]["w"][0]) == 4.0

    def test_prime_marks_existing_publish_seen_without_loading(
            self, tmp_path):
        """arm()'s watcher priming: the newest file on disk at round
        start is the PREVIOUS round's best — prime marks it seen so the
        first poll doesn't deserialize a checkpoint it would discard,
        while anything published afterwards still reports."""
        d = str(tmp_path)
        ckpt_lib.publish_best(os.path.join(d, "best_rd_0.msgpack"),
                              self._vars(1.0), round_idx=0, epoch=2)
        watcher = ckpt_lib.BestCkptWatcher(d)
        watcher.prime()
        assert watcher.poll() is None  # already-seen, never loaded
        ckpt_lib.publish_best(os.path.join(d, "best_rd_1.msgpack"),
                              self._vars(9.0), round_idx=1, epoch=1)
        _, rd, tag = watcher.poll()
        assert rd == 1 and tag == (1, 1)
        # Priming an empty dir is a no-op.
        ckpt_lib.BestCkptWatcher(str(tmp_path / "empty")).prime()

    def test_corrupt_tag_sidecar_reads_none(self, tmp_path):
        path = str(tmp_path / "best_rd_0.msgpack")
        with open(f"{path}.tag.json", "w") as fh:
            fh.write("{not json")
        assert ckpt_lib.read_best_tag(path) is None

    def test_interleaved_writer_never_serves_torn_or_stale_pairs(
            self, tmp_path):
        """The satellite's hard case: a writer thread hammering
        publish_best while a reader polls concurrently.  The watcher's
        contract (checkpoint.BestCkptWatcher): a poll is never TORN (the
        weights are one complete publish), tags are strictly monotonic
        across polls, and a pairing is either exact or attributes the
        weights to an OLDER tag (writer renamed weights before the tag)
        — which the pipeline's invalidation rule turns into wasted
        work, never a wrong score.  The dangerous direction — STALE
        weights under a newer tag — must never happen.  The weights
        encode their epoch, so every case is checkable bit-for-bit."""
        d = str(tmp_path)
        path = os.path.join(d, "best_rd_0.msgpack")
        n_publishes = 40
        stop = threading.Event()
        errors: list = []

        def writer():
            try:
                for e in range(1, n_publishes + 1):
                    ckpt_lib.publish_best(path, self._vars(float(e), 64),
                                          round_idx=0, epoch=e)
            except Exception as exc:  # noqa: BLE001
                errors.append(exc)
            finally:
                stop.set()

        polls = []
        watcher = ckpt_lib.BestCkptWatcher(d)
        t = threading.Thread(target=writer)
        t.start()
        try:
            while True:
                out = watcher.poll()
                if out is not None:
                    polls.append(out)
                if stop.is_set():
                    break
        finally:
            t.join(timeout=60)
        assert not errors, errors
        # The final publish always lands (the writer finished before the
        # last poll loop iteration).
        final = watcher.poll()
        if final is not None:
            polls.append(final)
        assert polls, "reader never observed a publish"
        for variables, _, tag in polls:
            w = variables["params"]["w"]
            assert w.shape == (64,)
            # Untorn: one complete publish, every element agreeing.
            assert np.all(w == w[0]), f"torn weights under tag {tag}"
            assert 1 <= float(w[0]) <= n_publishes
            # Never stale-under-newer: the weights' epoch may run AHEAD
            # of the tag (writer raced between its two renames; the
            # invalidation rule eats it) but never behind it.  A poll
            # that outran the FIRST tag rename reports tag None (the
            # legacy-writer fallback) — nothing to compare there.
            if tag is not None:
                assert float(w[0]) >= tag[1], (
                    f"stale weights of epoch {w[0]} under tag {tag}")
        tagged = [tag for _, _, tag in polls if tag is not None]
        assert tagged == sorted(set(tagged)), "polls not monotonic"
        # The writer finished before the last poll, so the settled final
        # publish is always observed, tagged, and exactly paired.
        assert tagged and tagged[-1] == (0, n_publishes)
        final_w = polls[-1][0]["params"]["w"]
        assert float(final_w[0]) == n_publishes
        assert polls[-1][2] == (0, n_publishes)


# -- RoundPipeline mechanics -------------------------------------------------


def _sequential_scores(strategy, idxs, variables, keys=("margin",)):
    loader = strategy.train_cfg.loader_te
    return scoring.collect_pool(
        strategy.al_set, idxs, strategy._score_batch_size(),
        strategy._get_score_step("prob_stats"), variables, strategy.mesh,
        num_workers=loader.num_workers, prefetch=loader.prefetch,
        keys=keys, **strategy._resident_kwargs())


@pytest.fixture
def margin_strategy():
    # 400 pool rows / batch 16 / 8-batch chunks -> 4 speculative chunks:
    # enough that a late invalidation provably kills already-done work.
    return make_strategy("MarginSampler", n_train=400, init_pool=8)


class TestRoundPipeline:
    def test_resolve_rule(self):
        import jax

        from active_learning_tpu.parallel import mesh as mesh_lib
        mesh = mesh_lib.make_mesh()
        assert pipeline_lib.resolve_round_pipeline(None, mesh) == (
            "speculative" if mesh.devices.size > 1 else "off")
        assert pipeline_lib.resolve_round_pipeline("off", mesh) == "off"
        assert pipeline_lib.resolve_round_pipeline(
            "speculative", mesh) == "speculative"
        with pytest.raises(ValueError):
            pipeline_lib.resolve_round_pipeline("always", mesh)
        del jax

    def test_speculative_hit_is_bit_identical(self, margin_strategy):
        strategy = margin_strategy
        pipe = pipeline_lib.RoundPipeline(strategy)
        strategy.pipeline = pipe
        try:
            assert pipe.arm(0)
            variables = strategy.state.variables
            pipe.publish_best(0, 1, variables)
            _wait_for_chunks(pipe, 2, what="speculative chunks")
            pipe.finalize(0, 1)
            idxs = strategy.pool.available_query_idxs(shuffle=False)
            out = pipe.consume("prob_stats", ("margin",), idxs,
                               strategy._score_batch_size(), variables)
            if out is None and pipe.stats["chunks_failed"]:
                pytest.skip("speculation lost to an environmental "
                            "chunk failure mid-consume")
            assert out is not None
            assert pipe.last_consume["hits"] >= 2
            seq = _sequential_scores(strategy, idxs, variables)
            for k in seq:
                assert np.array_equal(out[k], seq[k]), k
        finally:
            pipe.shutdown()
        # consume() released the CPU-mesh execution drain.
        assert strategy.trainer.dispatch_lock.drain_mode is False

    def test_forced_late_best_invalidates_and_recomputes(self,
                                                         margin_strategy):
        """The invalidation rule, FORCED: chunks scored under an early
        best checkpoint are dead the moment a later epoch improves best,
        and the scores consume() serves come from the FINAL checkpoint —
        bit-identical to scoring with it sequentially."""
        strategy = margin_strategy
        pipe = pipeline_lib.RoundPipeline(strategy)
        strategy.pipeline = pipe
        try:
            assert pipe.arm(0)
            early = strategy.state.variables
            pipe.publish_best(0, 1, early)
            _wait_for_chunks(pipe, 1, what="early-ckpt speculative chunks")
            # The forced late-epoch improvement: a DIFFERENT checkpoint
            # becomes best after speculation already scored chunks.
            strategy.init_network_weights()
            late = strategy.state.variables
            pipe.publish_best(0, 5, late)
            pipe.finalize(0, 5)
            idxs = strategy.pool.available_query_idxs(shuffle=False)
            out = pipe.consume("prob_stats", ("margin",), idxs,
                               strategy._score_batch_size(), late)
            if out is None and pipe.stats["chunks_failed"]:
                pytest.skip("speculation lost to an environmental "
                            "chunk failure mid-consume")
            assert out is not None
            assert pipe.stats["chunks_invalidated"] >= 1
            seq = _sequential_scores(strategy, idxs, late)
            early_seq = _sequential_scores(strategy, idxs, early)
            assert not np.array_equal(seq["margin"],
                                      early_seq["margin"]), (
                "late re-init produced identical scores; the test "
                "cannot distinguish stale from fresh")
            for k in seq:
                assert np.array_equal(out[k], seq[k]), k
        finally:
            pipe.shutdown()

    def test_plan_miss_returns_none_and_releases_drain(self,
                                                       margin_strategy):
        strategy = margin_strategy
        pipe = pipeline_lib.RoundPipeline(strategy)
        strategy.pipeline = pipe
        try:
            assert pipe.arm(0)
            variables = strategy.state.variables
            pipe.publish_best(0, 1, variables)
            pipe.finalize(0, 1)
            idxs = strategy.pool.available_query_idxs(shuffle=False)
            # An rng-shuffled request can never match the rng-free plan.
            shuffled = np.array(idxs)[::-1].copy()
            out = pipe.consume("prob_stats", ("margin",), shuffled,
                               strategy._score_batch_size(), variables)
            assert out is None
            assert pipe.stats["plan_misses"] == 1
            assert strategy.trainer.dispatch_lock.drain_mode is False
        finally:
            pipe.shutdown()

    def test_unspeculable_sampler_never_arms(self):
        strategy = make_strategy("PartitionedCoresetSampler", n_train=96,
                                 init_pool=8, partitions=2)
        pipe = pipeline_lib.RoundPipeline(strategy)
        try:
            assert strategy.speculative_scoring_plan() is None
            assert pipe.arm(0) is False
        finally:
            pipe.shutdown()

    def test_subset_caps_disable_coreset_speculation(self):
        strategy = make_strategy("CoresetSampler", n_train=96,
                                 init_pool=8, subset_unlabeled=32)
        assert strategy.speculative_scoring_plan() is None

    def test_coreset_plan_is_the_sorted_union(self):
        strategy = make_strategy("CoresetSampler", n_train=96, init_pool=8)
        plan = strategy.speculative_scoring_plan()
        assert plan["kind"] == "embed" and plan["keys"] == ("embedding",)
        expected = np.sort(np.concatenate(
            [strategy.pool.available_query_idxs(shuffle=False),
             strategy.pool.labeled_idxs()]))
        assert np.array_equal(plan["idxs"], expected)


# -- end-to-end: pipelined vs sequential bit-identity ------------------------


def _run_e2e(tmp_path, name: str, sampler: str, mode: str):
    cfg = ExperimentConfig(
        dataset="synthetic", arg_pool="synthetic", strategy=sampler,
        rounds=2, round_budget=8, n_epoch=3, early_stop_patience=3,
        run_seed=7, exp_hash=name, exp_name="pipe",
        ckpt_path=str(tmp_path / f"ckpt_{name}"),
        log_dir=str(tmp_path / f"logs_{name}"),
        round_pipeline=mode,
        telemetry=TelemetryConfig(enabled=True, heartbeat_every_s=0.0))
    data = get_data_synthetic(n_train=96, n_test=32, num_classes=4,
                              image_size=8, seed=5)
    sink = JsonlSink(cfg.log_dir, experiment_key=name)
    strategy = run_experiment(cfg, sink=sink, data=data,
                              train_cfg=tiny_train_config(),
                              model=TinyClassifier(num_classes=4))
    state_path = glob.glob(os.path.join(cfg.ckpt_path, "*",
                                        "experiment_state.npz"))[0]
    metrics = []
    with open(os.path.join(cfg.log_dir, "metrics.jsonl")) as fh:
        for line in fh:
            metrics.append(json.loads(line))
    return strategy, dict(np.load(state_path)), metrics


class TestPipelinedExperimentBitIdentity:
    @pytest.mark.parametrize("sampler", ["MarginSampler", "CoresetSampler"])
    def test_experiment_state_bit_identical_to_sequential(self, tmp_path,
                                                          sampler):
        """The acceptance pin: the FULL driver, 2 rounds on the
        multi-device CPU mesh, --round_pipeline speculative vs off —
        every experiment_state array (labeled mask, recent picks, eval
        idxs, rng chain) identical to the bit, plus identical per-round
        test metrics."""
        seq, seq_state, seq_metrics = _run_e2e(
            tmp_path, f"seq_{sampler}", sampler, "off")
        pip, pip_state, pip_metrics = _run_e2e(
            tmp_path, f"pip_{sampler}", sampler, "speculative")
        assert seq.pipeline is None
        assert pip.pipeline is not None

        assert set(seq_state) == set(pip_state)
        for k in seq_state:
            assert np.array_equal(seq_state[k], pip_state[k]), (
                f"experiment_state[{k!r}] diverged under the pipelined "
                "round")

        def metric_series(events, name):
            return [(ev.get("step"), ev["metrics"][name])
                    for ev in events
                    if ev.get("kind") == "metric"
                    and name in ev.get("metrics", {})]

        for name in ("rd_test_accuracy", "rd_test_loss"):
            s, p = (metric_series(seq_metrics, name),
                    metric_series(pip_metrics, name))
            if s or p:
                assert s == p, name

        # The speculative run actually speculated: round 1's query was
        # served by consume() (hits + inline == all chunks) ...
        assert pip.pipeline.last_consume.get("chunks", 0) >= 1
        stats = pip.pipeline.stats
        assert stats["chunks_hit"] + stats["chunks_inline"] >= 1
        # ... and the overlap accounting landed in the metrics stream
        # from the driver's own telemetry (what bench reads back).
        for name in ("overlap_frac", "round_vs_max_phase",
                     "rd_round_time"):
            assert metric_series(pip_metrics, name), name
        # A sequential round reports ~zero overlap; never negative.
        for _, v in metric_series(seq_metrics, "overlap_frac"):
            assert 0.0 <= v <= 0.2

    def test_auto_resolves_speculative_on_test_mesh(self, tmp_path):
        """--round_pipeline auto (the config default) arms on the
        multi-device CPU mesh — the default path IS the pipelined one,
        so every other driver test in the suite exercises it too."""
        strategy, _, _ = _run_e2e(tmp_path, "auto", "MarginSampler",
                                  "auto")
        assert strategy.pipeline is not None


# -- status: both active phases ---------------------------------------------


class TestStatusShowsBothPhases:
    def _summary(self, **hb_extra):
        hb = {"path": "hb.json", "age_s": 1.0, "stale": False,
              "status": "running", "round": 1, "phase": "train_time",
              "epoch": 2, "step": 7, "process_index": 0, **hb_extra}
        return {"state": "ok", "exp": "x", "log_dir": "/tmp/x",
                "heartbeats": [hb], "metrics": {}}

    def test_active_scorer_renders_as_second_phase(self):
        text = status_lib.render_text(
            self._summary(spec_phase="score", spec_chunk=3))
        assert "spec_phase=score" in text
        assert "spec_chunk=3" in text

    def test_idle_or_absent_scorer_is_omitted(self):
        assert "spec_phase" not in status_lib.render_text(
            self._summary(spec_phase="idle"))
        assert "spec_phase" not in status_lib.render_text(self._summary())
