"""The driver-parseable guarantee, pinned end to end.

Everything that consumes ``bench.py`` keeps only a short tail of its
stdout and strict-JSON-parses the last line.  The harness promises that
line appears — parseable, bounded, with the headline schema — even when
the accelerator backend is degraded or there is no fresh capture at all.
Until now that guarantee was asserted piecemeal (helper unit tests);
this runs the REAL parent orchestration in a subprocess with the
wall-clock budget already exhausted (so no phase attempts launch) and a
redirected state dir (AL_BENCH_STATE_DIR — the repo's captured evidence
files must never be clobbered by a test), and checks the contract.
"""

import json
import os
import subprocess
import sys

import pytest

BENCH = os.path.join(os.path.dirname(__file__), "..", "bench.py")

REQUIRED_KEYS = ("metric", "value", "unit", "vs_baseline", "phases",
                 "evidence")


def _run_bench(tmp_path, extra_env=None, timeout=240):
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        AL_BENCH_STATE_DIR=str(tmp_path),
        # Budget pre-exhausted: the probe still runs (cheap on CPU) but
        # every phase degrades to "wall-clock budget exhausted" — the
        # exact shape of a dead/slow backend run.
        AL_BENCH_BUDGET_S="0",
    )
    # The conftest's virtual 8-device mesh must not leak into the bench
    # subprocess: cached entries carry real hardware (n_chips) and the
    # probe's device count has to describe the actual backend.
    env.pop("XLA_FLAGS", None)
    env.update(extra_env or {})
    return subprocess.run([sys.executable, os.path.abspath(BENCH)],
                          capture_output=True, text=True, timeout=timeout,
                          env=env)


class TestDegradedModeLine:
    def test_final_line_parseable_with_required_keys(self, tmp_path):
        proc = _run_bench(tmp_path)
        assert proc.returncode == 0, proc.stderr[-2000:]
        lines = [ln for ln in proc.stdout.strip().splitlines() if ln]
        assert lines, "bench printed nothing to stdout"
        line = lines[-1]
        # The harness-tail bound: ~2000 bytes of stdout tail, nothing on
        # stdout but this line — 1680 leaves 320 bytes of slop margin
        # (raised with the ISSUE 6 riders; margin math at
        # bench.MAX_LINE_BYTES).
        assert len(line.encode()) <= 1680
        out = json.loads(line)  # strict: NaN/Inf tokens would raise
        for key in REQUIRED_KEYS:
            assert key in out, f"missing {key!r} in {sorted(out)}"
        # No fresh capture and no matching cache: value is null, every
        # phase shows up as an explicit failure, never silently absent.
        assert out["value"] is None
        assert out.get("failed")
        # The serving phase rides the same guarantee: with no live
        # backend it appears as an explicit failure on the degraded
        # line, exactly like every offline phase.
        assert "serve_throughput" in out["failed"]
        # ... and so does the train-feed comparison phase: the feed
        # hierarchy's numbers must never silently vanish from the line.
        assert "imagenet_train_feed" in out["failed"]
        # ... and the streaming loop (ISSUE 14): the 14th phase rides
        # the same degraded-line guarantee as the other 13.
        assert "stream_round" in out["failed"]
        # ... and the disk tier (ISSUE 16): the 15th phase too.
        assert "disk_pool_feed" in out["failed"]
        # The full evidence file landed in the REDIRECTED dir and is
        # itself strict-parseable.
        assert out["evidence"] == str(tmp_path / "bench_evidence.json")
        with open(out["evidence"]) as fh:
            evidence = json.load(fh)
        assert evidence["phases"] == {}
        assert evidence["failed_phases"]

    def test_matching_cache_entry_rides_the_line(self, tmp_path):
        """A cached capture whose hardware matches the live backend must
        surface on the degraded line (the round-3 failure mode: rc=124
        with a full cache on disk and parsed=null)."""
        cache = {
            "resnet50_imagenet_train": {
                "phase": "resnet50_imagenet_train",
                "ips": 2655.3, "ips_per_chip": 2655.3, "mfu": 0.322,
                "n_chips": 1, "device_kind": "cpu", "platform": "cpu",
                "batch_per_chip": 128,
                # The telemetry-era per-phase step-time percentiles
                # (bench._step_percentiles / the driver's per-epoch
                # telemetry for al_round phases) must ride the compact
                # line under their canonical names.
                "step_time_ms_p50": 48.2, "step_time_ms_p99": 61.7,
                "step_time_source": "host-cadence",
                # The gradient-path riders (ISSUE 10): the backward's
                # share of the step and the sync precision ride the
                # line on train phases; opt_update_ms stays in the
                # evidence file.
                "bwd_frac": 0.581, "opt_update_ms": 3.4,
                "grad_allreduce": "f32", "optim_state_dtype": "f32",
                "captured_utc": "2026-01-01T00:00:00Z",
            }
        }
        (tmp_path / "bench_cache.json").write_text(json.dumps(cache))
        proc = _run_bench(tmp_path)
        assert proc.returncode == 0, proc.stderr[-2000:]
        out = json.loads(proc.stdout.strip().splitlines()[-1])
        assert out["value"] == pytest.approx(2655.3)
        assert out["metric"].startswith("resnet50_imagenet_train")
        assert out.get("headline_cached") is True
        phase = out["phases"]["resnet50_imagenet_train"]
        assert phase["cached"] is True and phase["ips"] == \
            pytest.approx(2655.3)
        # The degraded-mode line carries the step-time percentiles.
        assert phase["step_time_ms_p50"] == pytest.approx(48.2)
        assert phase["step_time_ms_p99"] == pytest.approx(61.7)
        # ... and the gradient-path riders, under their line spellings.
        assert phase["bwd_frac"] == pytest.approx(0.581)
        assert phase["grad_ar"] == "f32"
        # The finer figures stay in the evidence file, off the line.
        assert "opt_update_ms" not in phase
        assert "optim_state_dtype" not in phase

    def test_feed_fields_and_datapath_rename_ride_the_line(self, tmp_path):
        """The feed-hierarchy numbers (imagenet_train_feed, feed_source/
        feed_stall_frac on train + al_round phases), the datapath's
        canonical warm field (warm_memmap_ips — its deprecated ips_warm
        alias and the deprecated_keys shim are GONE after their one
        release), and the selection probe's pool_sharding layout tag
        must all surface on the compact line."""
        base = {"n_chips": 1, "device_kind": "cpu", "platform": "cpu",
                "captured_utc": "2026-01-01T00:00:00Z"}
        cache = {
            "imagenet_train_feed": dict(
                base, phase="imagenet_train_feed", ips=5000.0,
                ips_per_chip=5000.0, batch_per_chip=64,
                feed_source="resident", feed_stall_frac=0.02,
                ips_resident=5000.0, ips_host_prefetch=900.0,
                ips_host_serial=400.0),
            "imagenet_datapath": dict(
                base, phase="imagenet_datapath", ips=348.6,
                ips_per_chip=348.6, batch_per_chip=128,
                # Canonical name ONLY: no shim exists anymore, and a
                # stale legacy-only spelling must NOT ride (below).
                cold_populate_ips=348.6, warm_memmap_ips=157.7,
                ips_warm=999.9),
            "al_round_cifar": dict(
                base, phase="al_round_cifar", ips=400.0,
                ips_per_chip=400.0, batch_per_chip=128,
                round_sec_warm=22.0, round_sec_cold=80.0,
                feed_source="resident", feed_stall_frac=0.01,
                round_pipeline="speculative", overlap_frac=0.31,
                round_vs_max_phase=1.18, spec_hit_frac=1.0,
                fault_retries_total=2, degrade_events=1,
                ring_feed=True),
            # n_chips stays 1 (the cache rides only when the entry's
            # hardware matches the live 1-device CPU probe); the layout
            # tag is what's being plumbed here.
            "kcenter_select_maxn": dict(
                base, phase="kcenter_select_maxn", ips=120.0,
                ips_per_chip=120.0, unit="picks/sec",
                pool_sharding="row", max_n=2_560_000,
                replicated_max_n=1_280_000, row_scale_x=2.0,
                ring_feed=True),
            # The pod-tier gradient-sync riders (ISSUE 15): a train
            # capture under the quantized reduce-scatter wire rides
            # its form on the line (short spelling); the wire-model MB
            # stays in the evidence file with the other finer figures.
            "resnet50_imagenet_train": dict(
                base, phase="resnet50_imagenet_train", ips=2700.0,
                ips_per_chip=2700.0, batch_per_chip=128,
                bwd_frac=0.55, grad_allreduce="int8",
                grad_sync="reduce_scatter", grad_wire_mb=51.2),
        }
        (tmp_path / "bench_cache.json").write_text(json.dumps(cache))
        proc = _run_bench(tmp_path)
        assert proc.returncode == 0, proc.stderr[-2000:]
        out = json.loads(proc.stdout.strip().splitlines()[-1])
        feed = out["phases"]["imagenet_train_feed"]
        assert feed["feed"] == "resident"
        assert feed["stall"] == pytest.approx(0.02)
        # The hierarchy comparison, positionally: [resident,
        # host_prefetch, host_serial] img/s.
        assert feed["legs"] == [pytest.approx(5000.0),
                                pytest.approx(900.0),
                                pytest.approx(400.0)]
        dp = out["phases"]["imagenet_datapath"]
        # The canonical spelling rides; the legacy alias in the cache
        # entry above is ignored — not renamed, not forwarded.
        assert dp["warm_ips"] == pytest.approx(157.7)
        rd = out["phases"]["al_round_cifar"]
        assert rd["feed"] == "resident"
        assert rd["stall"] == pytest.approx(0.01)
        # The pipelined round's mode + warm overlap (ISSUE 7): a round
        # wall-clock claim is ambiguous without knowing whether the
        # phases were overlapped, so both ride the end-to-end phases.
        assert rd["pipeline"] == "speculative"
        assert rd["overlap"] == pytest.approx(0.31)
        # ... but the finer breakdown (round_vs_max_phase, spec_hit_
        # frac) stays in the evidence file, off the bounded line.
        assert "round_vs_max_phase" not in rd
        assert "spec_hit_frac" not in rd
        # The failure model's counters (ISSUE 8): how much self-healing
        # the measured rounds absorbed rides the degraded-mode line too.
        assert rd["retries"] == 2
        assert rd["degraded"] == 1
        # The pod-tier column-feed rider (ISSUE 15): the measured
        # rounds' k-center scans fed over the ring-permute feed.
        assert rd["ring"] is True
        # The sharded-pool probe's layout attribution (ISSUE 6): a
        # row-sharded max-N claim is meaningless without the layout tag.
        assert out["phases"]["kcenter_select_maxn"][
            "pool_sharding"] == "row"
        assert out["phases"]["kcenter_select_maxn"]["ring"] is True
        # The quantized-wire riders (ISSUE 15): the form rides in its
        # short line spelling; the wire-model MB stays in the evidence
        # file.
        tr = out["phases"]["resnet50_imagenet_train"]
        assert tr["grad_ar"] == "int8"
        assert tr["grad_sync"] == "rs"
        assert "grad_wire_mb" not in tr

    def test_stream_round_riders_on_the_line(self, tmp_path):
        """The streaming phase's compact-line riders (ISSUE 14): the
        ack tail latency and the trigger cause ride the line (an ingest
        rate is ambiguous without them); the finer figures (qps,
        labels, pool growth) stay in the evidence file.  The
        MAX_LINE_BYTES margin math at bench.MAX_LINE_BYTES accounts for
        ~70 bytes of phase entry + riders."""
        cache = {
            "stream_round": {
                "phase": "stream_round", "ips": 4002.2,
                "ips_per_chip": 4002.2,
                "unit": "ingested rows/sec (acked)",
                "n_chips": 1, "device_kind": "cpu", "platform": "cpu",
                "batch_per_chip": 64, "rounds_run": 2,
                "trigger_cause": "watermark", "ingest_qps": 250.1,
                "ack_p50_ms": 2.8, "ack_p99_ms": 42.4, "n_429": 0,
                "pool_rows_final": 6304, "pool_capacity_final": 7168,
                "captured_utc": "2026-01-01T00:00:00Z",
            }
        }
        (tmp_path / "bench_cache.json").write_text(json.dumps(cache))
        proc = _run_bench(tmp_path)
        assert proc.returncode == 0, proc.stderr[-2000:]
        out = json.loads(proc.stdout.strip().splitlines()[-1])
        sr = out["phases"]["stream_round"]
        assert sr["ips"] == pytest.approx(4002.2)
        assert sr["unit"] == "ingested rows/sec (acked)"
        assert sr["ack_p99"] == pytest.approx(42.4)
        assert sr["trigger"] == "watermark"
        # Off the bounded line, in the evidence file only.
        for key in ("ingest_qps", "ack_p50_ms", "pool_rows_final"):
            assert key not in sr
        # A streamed-ingest rate must never be billed as the training
        # headline.
        assert not out["metric"].startswith("stream_round")

    def test_disk_pool_feed_riders_on_the_line(self, tmp_path):
        """The disk tier's compact-line riders (ISSUE 16): the warm
        block-cache hit fraction and the page-in stall tail ride the
        line (a disk-backed train rate is ambiguous without them); the
        finer paging figures (page-in rate, p50, the memory-leg
        comparison) stay in the evidence file.  The MAX_LINE_BYTES
        margin math at bench.MAX_LINE_BYTES accounts for ~60 bytes of
        phase entry + riders."""
        cache = {
            "disk_pool_feed": {
                "phase": "disk_pool_feed", "ips": 3120.4,
                "ips_per_chip": 3120.4,
                "unit": "train images/sec (disk-backed pool)",
                "n_chips": 1, "device_kind": "cpu", "platform": "cpu",
                "batch_per_chip": 64, "pool_n": 50000,
                "pool_over_budget_x": 4.0,
                "cache_hit_frac": 0.982, "page_stall_ms_p99": 41.75,
                "page_stall_ms_p50": 3.2,
                "page_in_rows_per_sec": 51200.5,
                "pool_disk_rows": 50000, "ips_memory": 3600.0,
                "disk_vs_memory": 0.867, "picks_identical": True,
                "captured_utc": "2026-01-01T00:00:00Z",
            }
        }
        (tmp_path / "bench_cache.json").write_text(json.dumps(cache))
        proc = _run_bench(tmp_path)
        assert proc.returncode == 0, proc.stderr[-2000:]
        out = json.loads(proc.stdout.strip().splitlines()[-1])
        dp = out["phases"]["disk_pool_feed"]
        assert dp["ips"] == pytest.approx(3120.4)
        assert dp["hit"] == pytest.approx(0.982)
        assert dp["stall_ms"] == pytest.approx(41.75)
        # Off the bounded line, in the evidence file only.
        for key in ("page_in_rows_per_sec", "page_stall_ms_p50",
                    "ips_memory", "disk_vs_memory", "pool_disk_rows"):
            assert key not in dp
        # A disk-backed feed rate must never be billed as the training
        # headline.
        assert not out["metric"].startswith("disk_pool_feed")

    def test_legacy_ips_warm_alias_no_longer_rides(self, tmp_path):
        """A pre-rename cache entry carrying ONLY the deprecated
        ips_warm spelling gets no warm_ips on the line: the one-release
        compatibility shim is removed, so stale captures surface their
        headline ips but not a silently-renamed warm figure."""
        cache = {
            "imagenet_datapath": {
                "phase": "imagenet_datapath", "ips": 348.6,
                "ips_per_chip": 348.6, "batch_per_chip": 128,
                "n_chips": 1, "device_kind": "cpu", "platform": "cpu",
                "ips_warm": 157.7,
                "captured_utc": "2026-01-01T00:00:00Z",
            }
        }
        (tmp_path / "bench_cache.json").write_text(json.dumps(cache))
        proc = _run_bench(tmp_path)
        assert proc.returncode == 0, proc.stderr[-2000:]
        out = json.loads(proc.stdout.strip().splitlines()[-1])
        dp = out["phases"]["imagenet_datapath"]
        assert dp["ips"] == pytest.approx(348.6)
        assert "warm_ips" not in dp

    def test_state_dir_redirect_leaves_repo_files_alone(self, tmp_path):
        """The redirect itself: nothing in the repo root may be touched
        when AL_BENCH_STATE_DIR points elsewhere."""
        repo = os.path.dirname(os.path.abspath(BENCH))
        before = {
            name: os.path.getmtime(os.path.join(repo, name))
            for name in ("bench_cache.json", "bench_evidence.json")
            if os.path.exists(os.path.join(repo, name))
        }
        _run_bench(tmp_path)
        for name, mtime in before.items():
            assert os.path.getmtime(os.path.join(repo, name)) == mtime
        assert (tmp_path / "bench_partial.json").exists() or \
            (tmp_path / "bench_evidence.json").exists()


class TestCacheKeyMigration:
    def test_pre_rename_warm_resident_key_migrates_on_load(
            self, tmp_path, monkeypatch):
        """A <= PR 5 cache spelling the resident warm rate
        ips_warm_resident loads under the canonical warm_resident_ips —
        the datum survives the rename without an alias riding the
        evidence (the same one-spelling rule as warm_memmap_ips)."""
        sys.path.insert(0, os.path.dirname(os.path.abspath(BENCH)))
        try:
            import bench as bench_mod
        finally:
            sys.path.pop(0)
        cache = {"resnet18_cifar_score": {
            "phase": "resnet18_cifar_score", "ips": 1000.0,
            "ips_warm_resident": 4242.0}}
        path = tmp_path / "bench_cache.json"
        path.write_text(json.dumps(cache))
        monkeypatch.setattr(bench_mod, "CACHE_PATH", str(path))
        entry = bench_mod._load_cache()["resnet18_cifar_score"]
        assert entry["warm_resident_ips"] == pytest.approx(4242.0)
        assert "ips_warm_resident" not in entry
        # The canonical spelling, already present, is never clobbered.
        path.write_text(json.dumps({"resnet18_cifar_score": {
            "warm_resident_ips": 1.0, "ips_warm_resident": 2.0}}))
        entry = bench_mod._load_cache()["resnet18_cifar_score"]
        assert entry["warm_resident_ips"] == pytest.approx(1.0)


class TestMaxnHeadlineFallback:
    def test_row_climb_with_no_surviving_rung_keeps_replicated_headline(
            self, monkeypatch):
        """A mesh geometry every row rung is refused on (the gate says
        the bucketed pool can't split) must not null the headline: the
        completed replicated climb's ceiling and picks/sec ride the
        line, tagged with the layout they actually describe, and the
        refusals are recorded as failed attempts before any compute."""
        sys.path.insert(0, os.path.dirname(os.path.abspath(BENCH)))
        try:
            import bench as bench_mod
        finally:
            sys.path.pop(0)
        from active_learning_tpu.strategies import kcenter as kc
        monkeypatch.setattr(kc, "row_capable", lambda *a, **k: False)
        out = list(bench_mod.run_kcenter_maxn_phase(8, dim=4))[-1]
        assert out["replicated_max_n"] > 0
        assert out["max_n"] == out["replicated_max_n"]
        assert out["pool_sharding"] == "replicated"
        assert out["ips"] is not None
        assert "row_scale_x" not in out
        rows = [a for a in out["attempts"]
                if a["pool_sharding"] == "row"]
        assert rows and not any(a["ok"] for a in rows)
        assert "row layout unavailable" in rows[0]["error"]
