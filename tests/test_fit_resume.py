"""Epoch-level mid-round resume.

The reference writes rd_{n}.pth every epoch but its resume path never
reads it (strategy.py:440, resume_training.py:8-52) — a mid-round crash
loses the whole round.  Here Trainer.fit periodically writes a full
fit-state checkpoint (variables + optimizer state + early-stop counters +
both RNG streams) and automatically continues from the last completed
saved epoch, so a killed fit resumes bit-for-bit instead of restarting.
"""

import os

import jax
import numpy as np
import pytest

from active_learning_tpu.data.synthetic import get_data_synthetic
from active_learning_tpu.parallel import mesh as mesh_lib
from active_learning_tpu.train import checkpoint as ckpt_lib
from active_learning_tpu.train.trainer import Trainer

from helpers import tiny_train_config
from test_trainer_parallel import BNClassifier  # BN: batch_stats restore
                                                # is exercised for real

N_EPOCH = 6
CADENCE = 2  # fit-state written after epochs 2 and 4


class Boom(Exception):
    pass


def _flat(tree):
    leaves = [np.asarray(x).ravel() for x in jax.tree_util.tree_leaves(tree)]
    return np.concatenate(leaves) if leaves else np.zeros(0)


@pytest.mark.parametrize("device_resident", [False, True])
class TestMidRoundResume:
    def _fit(self, tmp_path, tag, device_resident, metric_cb=None,
             resume_fit_state=True):
        """One fit run from identical initial conditions."""
        import dataclasses
        train_set, _, al_set = get_data_synthetic(
            n_train=64, n_test=16, num_classes=4, image_size=8, seed=11)
        cfg = dataclasses.replace(tiny_train_config(batch_size=16),
                                  device_resident=device_resident)
        mesh = mesh_lib.make_mesh()
        trainer = Trainer(BNClassifier(), cfg, mesh, num_classes=4,
                          train_bn=True, current_ckpt_every=CADENCE)
        state = trainer.init_state(jax.random.PRNGKey(0),
                                   train_set.gather(np.arange(2)))
        paths = ckpt_lib.weight_paths(str(tmp_path), "t", tag, round_idx=1)
        result = trainer.fit(
            state, train_set, np.arange(48), al_set, np.arange(48, 64),
            n_epoch=N_EPOCH, es_patience=10,
            rng=np.random.default_rng(7), round_idx=1, weight_paths=paths,
            metric_cb=metric_cb, resume_fit_state=resume_fit_state)
        return result, paths

    def test_resume_matches_uninterrupted_run(self, tmp_path,
                                              device_resident):
        ref, ref_paths = self._fit(tmp_path / "a", "a", device_resident)
        # A completed round must leave no fit state behind — a restart
        # re-runs the round from scratch under the experiment-level resume.
        assert ckpt_lib.load_fit_state(ref_paths["fit_state"], 1) is None

        def boom(name, value, step):
            if step == 5 and name.endswith("validation_accuracy"):
                raise Boom()

        with pytest.raises(Boom):
            self._fit(tmp_path / "b", "b", device_resident, metric_cb=boom)
        # The crash (mid-epoch-5) left the epoch-4 fit state on disk.
        saved = ckpt_lib.load_fit_state(
            str(tmp_path / "b" / "t_b" / "fit_state_rd_1"), 1)
        assert saved is not None and saved["epoch"] == 4

        resumed, res_paths = self._fit(tmp_path / "b", "b", device_resident)
        # Continued from epoch 5, not from scratch.
        assert resumed.history[0]["epoch"] == 5
        assert resumed.epochs_run == ref.epochs_run
        assert resumed.best_epoch == ref.best_epoch
        assert resumed.best_perf == ref.best_perf
        # Bit-for-bit identical trained state.
        np.testing.assert_array_equal(_flat(resumed.state.params),
                                      _flat(ref.state.params))
        np.testing.assert_array_equal(_flat(resumed.state.batch_stats),
                                      _flat(ref.state.batch_stats))
        # And the resumed round also cleans up after itself.
        assert ckpt_lib.load_fit_state(res_paths["fit_state"], 1) is None

    def test_torn_fit_state_save_is_rejected(self, tmp_path,
                                             device_resident):
        """A crash BETWEEN the msgpack and json os.replace calls leaves the
        new weight trees paired with the old counters.  The shared epoch
        stamp in both files must make load_fit_state treat that torn pair
        as nothing-to-resume rather than silently mixing epochs."""
        import json
        _, paths = self._fit(tmp_path, "d", device_resident)
        fs = paths["fit_state"]
        ckpt_lib.save_fit_state(
            fs, variables={"params": {"w": np.ones(2)}}, opt_state={},
            step=np.int32(4), epoch=2, round_idx=1, best_perf=0.5,
            best_epoch=2, es_count=0, key=np.zeros(2, np.uint32),
            rng=np.random.default_rng(0))
        with open(fs + ".json") as fh:
            old_meta = fh.read()
        ckpt_lib.save_fit_state(
            fs, variables={"params": {"w": np.full(2, 9.0)}}, opt_state={},
            step=np.int32(8), epoch=4, round_idx=1, best_perf=0.7,
            best_epoch=4, es_count=0, key=np.zeros(2, np.uint32),
            rng=np.random.default_rng(0))
        # Simulate the torn save: epoch-4 msgpack on disk, epoch-2 json.
        with open(fs + ".json", "w") as fh:
            fh.write(old_meta)
        assert ckpt_lib.load_fit_state(fs, 1) is None
        assert json.loads(old_meta)["epoch"] == 2  # the tear was real

    def test_no_fit_state_saved_past_early_stop(self, tmp_path,
                                                device_resident):
        """A fit state whose es_count already exceeds patience must never
        be written: resuming from it would train PAST the point where the
        uninterrupted run stopped."""
        import dataclasses
        train_set, _, al_set = get_data_synthetic(
            n_train=64, n_test=16, num_classes=4, image_size=8, seed=11)
        cfg = dataclasses.replace(tiny_train_config(batch_size=16),
                                  device_resident=device_resident)
        trainer = Trainer(BNClassifier(), cfg, mesh_lib.make_mesh(),
                          num_classes=4, train_bn=True,
                          current_ckpt_every=1)  # save cadence every epoch
        state = trainer.init_state(jax.random.PRNGKey(0),
                                   train_set.gather(np.arange(2)))
        paths = ckpt_lib.weight_paths(str(tmp_path), "t", "es",
                                      round_idx=1)
        # Scripted validation curve: strictly declining after epoch 1, so
        # with patience 1 the stop fires at epoch 3 (es_count 2) — exactly
        # a save-cadence epoch.
        accs = iter([0.9, 0.8, 0.7, 0.6, 0.5, 0.4])
        trainer.evaluate = lambda s, d, i: {"accuracy": next(accs),
                                            "top_5_accuracy": 1.0}
        saved_counts = []
        orig = ckpt_lib.save_fit_state

        def recording(path, **kw):
            saved_counts.append(kw["es_count"])
            return orig(path, **kw)

        ckpt_lib.save_fit_state = recording
        try:
            result = trainer.fit(state, train_set, np.arange(48), al_set,
                                 np.arange(48, 64), n_epoch=6, es_patience=1,
                                 rng=np.random.default_rng(7), round_idx=1,
                                 weight_paths=paths)
        finally:
            ckpt_lib.save_fit_state = orig
        assert result.epochs_run == 3  # the stop really fired at epoch 3
        assert saved_counts, "cadence-1 fit never saved a fit state"
        assert all(c <= 1 for c in saved_counts), saved_counts

    def test_resume_with_missing_best_ckpt_restarts_best_tracking(
            self, tmp_path, device_resident):
        """fit-state says best_epoch=4/best_perf=0.99 but best_ckpt is
        gone: the resume must NOT report the stale best_perf over weights
        it no longer has — best tracking restarts and the reported best is
        re-earned by the resumed epochs."""
        import json

        def boom(name, value, step):
            if step == 5 and name.endswith("validation_accuracy"):
                raise Boom()

        with pytest.raises(Boom):
            self._fit(tmp_path, "e", device_resident, metric_cb=boom)
        fs = str(tmp_path / "t_e" / "fit_state_rd_1")
        with open(fs + ".json") as fh:
            meta = json.load(fh)
        meta["best_perf"], meta["best_epoch"] = 0.99, 4  # unbeatable
        with open(fs + ".json", "w") as fh:
            json.dump(meta, fh)
        os.remove(str(tmp_path / "t_e" / "best_rd_1.msgpack"))

        resumed, paths = self._fit(tmp_path, "e", device_resident)
        assert resumed.history[0]["epoch"] == 5  # really resumed
        vals = [r["val_accuracy"] for r in resumed.history]
        assert resumed.best_perf == max(vals)  # re-earned, not the stale .99
        assert os.path.exists(paths["best_ckpt"])

    def test_fresh_run_discards_stale_fit_state(self, tmp_path,
                                                device_resident):
        """``resume_fit_state=False`` (a fresh, non-resumed experiment over
        an existing checkpoint dir): a fit state left by an older dead run
        must be discarded, not consumed — otherwise the 'from scratch' run
        silently splices in the dead run's weights."""
        def boom(name, value, step):
            if step == 5 and name.endswith("validation_accuracy"):
                raise Boom()

        with pytest.raises(Boom):
            self._fit(tmp_path, "f", device_resident, metric_cb=boom)
        fs = str(tmp_path / "t_f" / "fit_state_rd_1")
        assert ckpt_lib.load_fit_state(fs, 1) is not None  # stale state

        ref, _ = self._fit(tmp_path / "clean", "f", device_resident)
        fresh, _ = self._fit(tmp_path, "f", device_resident,
                             resume_fit_state=False)
        # Started from epoch 1 (not 5) and matches a truly clean run.
        assert fresh.history[0]["epoch"] == 1
        assert fresh.epochs_run == ref.epochs_run
        np.testing.assert_array_equal(_flat(fresh.state.params),
                                      _flat(ref.state.params))
        # And the stale state is gone from disk.
        assert ckpt_lib.load_fit_state(fs, 1) is None

    def test_stale_state_from_other_round_is_ignored(self, tmp_path,
                                                     device_resident):
        _, paths = self._fit(tmp_path, "c", device_resident)
        # Fabricate a leftover state tagged round 3 at the round-1 path:
        # must be ignored, not resumed.
        ckpt_lib.save_fit_state(
            paths["fit_state"], variables={"params": {}}, opt_state={},
            step=np.int32(0), epoch=4, round_idx=3, best_perf=0.0,
            best_epoch=0, es_count=0, key=np.zeros(2, np.uint32),
            rng=np.random.default_rng(0))
        assert ckpt_lib.load_fit_state(paths["fit_state"], 1) is None
        assert ckpt_lib.load_fit_state(paths["fit_state"], 3) is not None


def test_fit_state_from_other_model_format_is_discarded(tmp_path):
    """A mid-round fit state written by a code version with different
    weight alignment (model_format mismatch) is treated as nothing-to-
    resume: the round restarts from scratch instead of silently
    continuing with incompatible weights."""
    import json

    import numpy as np

    from active_learning_tpu.train import checkpoint as ckpt_lib

    path = str(tmp_path / "fit_state_rd_0")
    ckpt_lib.save_fit_state(
        path, variables={"params": {"w": np.zeros(2)}},
        opt_state={}, step=np.int32(1), epoch=3, round_idx=0,
        best_perf=0.5, best_epoch=2, es_count=0,
        key=np.zeros(2, np.uint32), rng=np.random.default_rng(0))
    assert ckpt_lib.load_fit_state(path, 0) is not None

    meta = json.loads(open(path + ".json").read())
    meta["model_format"] = 1
    open(path + ".json", "w").write(json.dumps(meta))
    assert ckpt_lib.load_fit_state(path, 0) is None


class TestHookedFitRestartSemantics:
    """Fits driven through a batch_hook (VAAL's co-training seam) have
    RESTART-the-round semantics, not epoch resume: the hook's state
    (VAALState, the unlabeled-batch cursor) is outside the trainer's
    fit-state schema, so a partial fit state must be neither written by
    nor consumed into a hooked fit — recovery for those lives at the
    round level (experiment resume + Strategy.aux_state_bytes)."""

    def _fit(self, tmp_path, batch_hook, n_epoch=4):
        train_set, _, al_set = get_data_synthetic(
            n_train=64, n_test=16, num_classes=4, image_size=8, seed=11)
        mesh = mesh_lib.make_mesh()
        trainer = Trainer(BNClassifier(), tiny_train_config(batch_size=16),
                          mesh, num_classes=4, train_bn=True,
                          current_ckpt_every=1)
        state = trainer.init_state(jax.random.PRNGKey(0),
                                   train_set.gather(np.arange(2)))
        paths = ckpt_lib.weight_paths(str(tmp_path), "t", "h", round_idx=1)
        result = trainer.fit(
            state, train_set, np.arange(48), al_set, np.arange(48, 64),
            n_epoch=n_epoch, es_patience=10, rng=np.random.default_rng(7),
            round_idx=1, weight_paths=paths, batch_hook=batch_hook)
        return result, paths

    def test_hooked_fit_writes_no_fit_state_and_ignores_one(self, tmp_path):
        hook_calls = []

        def hook(epoch, batch):
            hook_calls.append(epoch)

        # An unhooked crashed fit leaves an epoch-level state behind ...
        plain, paths = self._fit(tmp_path, None, n_epoch=4)
        ckpt_lib.save_fit_state(
            paths["fit_state"], variables=plain.state.variables,
            opt_state=plain.state.opt_state, step=plain.state.step,
            epoch=3, round_idx=1, best_perf=plain.best_perf,
            best_epoch=plain.best_epoch, es_count=0,
            key=jax.random.PRNGKey(1), rng=np.random.default_rng(7))
        assert ckpt_lib.load_fit_state(paths["fit_state"], 1) is not None

        # ... but the hooked fit must start at epoch 1 (full restart, NOT
        # epoch resume), run every epoch's hooks, and — having completed
        # its round — clear the now-stale state like any finished fit.
        hooked, _ = self._fit(tmp_path, hook, n_epoch=2)
        assert hooked.epochs_run == 2
        assert min(hook_calls) == 1  # restarted from the first epoch
        assert len(hook_calls) == 2 * 3  # every epoch x 3 batches of 16/48
        assert ckpt_lib.load_fit_state(paths["fit_state"], 1) is None
