"""Epoch-level mid-round resume.

The reference writes rd_{n}.pth every epoch but its resume path never
reads it (strategy.py:440, resume_training.py:8-52) — a mid-round crash
loses the whole round.  Here Trainer.fit periodically writes a full
fit-state checkpoint (variables + optimizer state + early-stop counters +
both RNG streams) and automatically continues from the last completed
saved epoch, so a killed fit resumes bit-for-bit instead of restarting.
"""

import os

import jax
import numpy as np
import pytest

from active_learning_tpu.data.synthetic import get_data_synthetic
from active_learning_tpu.parallel import mesh as mesh_lib
from active_learning_tpu.train import checkpoint as ckpt_lib
from active_learning_tpu.train.trainer import Trainer

from helpers import tiny_train_config
from test_trainer_parallel import BNClassifier  # BN: batch_stats restore
                                                # is exercised for real

N_EPOCH = 6
CADENCE = 2  # fit-state written after epochs 2 and 4


class Boom(Exception):
    pass


def _flat(tree):
    leaves = [np.asarray(x).ravel() for x in jax.tree_util.tree_leaves(tree)]
    return np.concatenate(leaves) if leaves else np.zeros(0)


@pytest.mark.parametrize("device_resident", [False, True])
class TestMidRoundResume:
    def _fit(self, tmp_path, tag, device_resident, metric_cb=None):
        """One fit run from identical initial conditions."""
        import dataclasses
        train_set, _, al_set = get_data_synthetic(
            n_train=64, n_test=16, num_classes=4, image_size=8, seed=11)
        cfg = dataclasses.replace(tiny_train_config(batch_size=16),
                                  device_resident=device_resident)
        mesh = mesh_lib.make_mesh()
        trainer = Trainer(BNClassifier(), cfg, mesh, num_classes=4,
                          train_bn=True, current_ckpt_every=CADENCE)
        state = trainer.init_state(jax.random.PRNGKey(0),
                                   train_set.gather(np.arange(2)))
        paths = ckpt_lib.weight_paths(str(tmp_path), "t", tag, round_idx=1)
        result = trainer.fit(
            state, train_set, np.arange(48), al_set, np.arange(48, 64),
            n_epoch=N_EPOCH, es_patience=10,
            rng=np.random.default_rng(7), round_idx=1, weight_paths=paths,
            metric_cb=metric_cb)
        return result, paths

    def test_resume_matches_uninterrupted_run(self, tmp_path,
                                              device_resident):
        ref, ref_paths = self._fit(tmp_path / "a", "a", device_resident)
        # A completed round must leave no fit state behind — a restart
        # re-runs the round from scratch under the experiment-level resume.
        assert ckpt_lib.load_fit_state(ref_paths["fit_state"], 1) is None

        def boom(name, value, step):
            if step == 5 and name.endswith("validation_accuracy"):
                raise Boom()

        with pytest.raises(Boom):
            self._fit(tmp_path / "b", "b", device_resident, metric_cb=boom)
        # The crash (mid-epoch-5) left the epoch-4 fit state on disk.
        saved = ckpt_lib.load_fit_state(
            str(tmp_path / "b" / "t_b" / "fit_state_rd_1"), 1)
        assert saved is not None and saved["epoch"] == 4

        resumed, res_paths = self._fit(tmp_path / "b", "b", device_resident)
        # Continued from epoch 5, not from scratch.
        assert resumed.history[0]["epoch"] == 5
        assert resumed.epochs_run == ref.epochs_run
        assert resumed.best_epoch == ref.best_epoch
        assert resumed.best_perf == ref.best_perf
        # Bit-for-bit identical trained state.
        np.testing.assert_array_equal(_flat(resumed.state.params),
                                      _flat(ref.state.params))
        np.testing.assert_array_equal(_flat(resumed.state.batch_stats),
                                      _flat(ref.state.batch_stats))
        # And the resumed round also cleans up after itself.
        assert ckpt_lib.load_fit_state(res_paths["fit_state"], 1) is None

    def test_stale_state_from_other_round_is_ignored(self, tmp_path,
                                                     device_resident):
        _, paths = self._fit(tmp_path, "c", device_resident)
        # Fabricate a leftover state tagged round 3 at the round-1 path:
        # must be ignored, not resumed.
        ckpt_lib.save_fit_state(
            paths["fit_state"], variables={"params": {}}, opt_state={},
            step=np.int32(0), epoch=4, round_idx=3, best_perf=0.0,
            best_epoch=0, es_count=0, key=np.zeros(2, np.uint32),
            rng=np.random.default_rng(0))
        assert ckpt_lib.load_fit_state(paths["fit_state"], 1) is None
        assert ckpt_lib.load_fit_state(paths["fit_state"], 3) is not None
