"""The disk tier (data/diskpool.py, DESIGN.md §16).

The disk tier's one non-negotiable claim is bit-identity: a pool paged
off disk through the bounded block cache serves EXACTLY the bytes the
in-memory array held, so picks, metrics, and experiment_state match the
memory backend to the bit.  Pinned here:

  * ``_DiskPoolCore.gather`` bit-identity against the spilled array for
    every access shape (random, repeated, cross-block, partial tail
    block, empty);
  * the LRU block cache honors its byte budget (evictions, recency,
    ``peak_cache_bytes`` bounded) and ``take_round_stats`` drains and
    resets per round;
  * the spy contract — no paging path ever materializes the pool on one
    host (``max_read_rows`` stays one block, ``peak_cache_bytes`` stays
    far under the pool) and ``.images`` raises so every
    ``getattr(ds, "images", None)`` gate routes to streaming paths;
  * ``resolve_pool_backend``'s ONE rule and ``page_rows_for``'s bucket
    alignment;
  * page_read chaos: raise / torn / delay through the ONE RetryPolicy —
    a mid-read fault retries to a bit-identical block, a torn read can
    never serve rows (the fault fires BEFORE the cache insert);
  * the acceptance e2e: the FULL driver, 2 rounds on the multi-device
    CPU mesh, a pool 4x the residency budget, memory vs disk backend
    bit-identical for Margin AND Coreset — with the paging gauges in
    the metrics stream, zero warm-round jit misses, and a mid-round
    page-read fault that completes bit-identical.
"""

from __future__ import annotations

import dataclasses
import glob
import json
import os

import numpy as np
import pytest

from active_learning_tpu import faults
from active_learning_tpu.config import ExperimentConfig, TelemetryConfig
from active_learning_tpu.data import diskpool
from active_learning_tpu.data.diskpool import (DiskPool, _DiskPoolCore,
                                               page_rows_for,
                                               resolve_pool_backend,
                                               spill_rows, wrap_pool)
from active_learning_tpu.data.synthetic import get_data_synthetic
from active_learning_tpu.experiment import arg_pools  # noqa: F401
from active_learning_tpu.experiment.driver import run_experiment
from active_learning_tpu.pool import bucket_size
from active_learning_tpu.utils.metrics import JsonlSink

from helpers import TinyClassifier, tiny_train_config

SHAPE = (8, 8, 3)
ROW_BYTES = int(np.prod(SHAPE))  # uint8
BLOCK_ROWS = 64  # page_rows_for(64) == 64: the extent-ladder floor
BLOCK_BYTES = BLOCK_ROWS * ROW_BYTES


@pytest.fixture(autouse=True)
def _disarm_faults():
    yield
    faults.configure(None)


def _make_core(tmp_path, n_rows=300, page_rows=BLOCK_ROWS,
               host_cache_bytes=1 << 30, local_rows=None, seed=3):
    rng = np.random.default_rng(seed)
    arr = rng.integers(0, 256, size=(n_rows, *SHAPE), dtype=np.uint8)
    core = _DiskPoolCore(str(tmp_path / "pool_rows.u8"), n_rows, SHAPE,
                         page_rows=page_rows,
                         host_cache_bytes=host_cache_bytes,
                         local_rows=local_rows)
    core.create(arr)
    return core, arr


class TestGatherBitIdentity:
    def test_every_access_shape_matches_the_source(self, tmp_path):
        core, arr = _make_core(tmp_path)  # 300 rows: block 4 is partial
        rng = np.random.default_rng(0)
        for idxs in (
            np.arange(300),                         # full scan in order
            rng.permutation(300),                   # full shuffle
            rng.integers(0, 300, size=97),          # repeats, cross-block
            np.array([0, 63, 64, 255, 256, 299]),   # block boundaries
            np.array([7]),                          # single row
            np.array([], dtype=np.int64),           # empty
        ):
            assert np.array_equal(core.gather(idxs), arr[idxs])

    def test_partial_tail_block_rows(self, tmp_path):
        core, arr = _make_core(tmp_path)
        # Rows 256..299 live in a 44-row tail block — bounded to the
        # store's end, never padded or over-read.
        out = core.gather(np.arange(256, 300))
        assert out.shape == (44, *SHAPE)
        assert np.array_equal(out, arr[256:300])
        assert core.spy_counters()["max_read_rows"] == 44

    def test_local_rows_out_of_span_raises(self, tmp_path):
        core, arr = _make_core(tmp_path, local_rows=slice(64, 128))
        idxs = np.arange(64, 128)
        assert np.array_equal(core.gather(idxs), arr[idxs])
        with pytest.raises(IndexError, match="process-local"):
            core.gather(np.array([10]))
        with pytest.raises(IndexError, match="process-local"):
            core.gather(np.array([70, 128]))


class TestBlockCache:
    def test_budget_bounds_and_lru_recency(self, tmp_path):
        core, arr = _make_core(tmp_path, host_cache_bytes=2 * BLOCK_BYTES)
        for b in (0, 1, 2):  # fill past the 2-block budget
            core.gather(np.array([b * BLOCK_ROWS]))
        assert set(core._blocks) == {1, 2}
        core.gather(np.array([BLOCK_ROWS]))      # touch 1 -> MRU
        core.gather(np.array([3 * BLOCK_ROWS]))  # page 3 -> evict 2
        assert set(core._blocks) == {1, 3}
        assert core._cache_bytes <= 2 * BLOCK_BYTES
        # Evicted block 2 pages back in bit-identical.
        assert np.array_equal(core.gather(np.arange(128, 192)),
                              arr[128:192])
        assert core.spy_counters()["peak_cache_bytes"] <= 2 * BLOCK_BYTES

    def test_single_block_cache_never_empties(self, tmp_path):
        # A budget smaller than one block still caches exactly one
        # block (the len > 1 eviction guard) — thrashing, not breaking.
        core, arr = _make_core(tmp_path,
                               host_cache_bytes=BLOCK_BYTES // 2)
        idxs = np.concatenate([np.arange(0, 64), np.arange(64, 128),
                               np.arange(0, 64)])
        assert np.array_equal(core.gather(idxs), arr[idxs])
        assert len(core._blocks) == 1

    def test_round_stats_drain_and_reset(self, tmp_path):
        core, _ = _make_core(tmp_path)
        rng = np.random.default_rng(1)
        core.gather(rng.integers(0, 300, size=200))
        core.gather(np.arange(0, 64))  # guaranteed hits
        stats = core.take_round_stats()
        assert stats["pool_disk_rows"] == 300.0
        assert 0.0 < stats["pool_cache_hit_frac"] <= 1.0
        assert stats["page_in_rows_per_sec"] > 0
        assert stats["page_in_stall_ms_p99"] >= stats["page_in_stall_ms_p50"]
        # Drained: the next round reports its OWN window — None gauges
        # (retracted at the sinks), absolute disk rows unchanged.
        stats2 = core.take_round_stats()
        assert stats2["pool_disk_rows"] == 300.0
        for k in ("pool_cache_hit_frac", "page_in_rows_per_sec",
                  "page_in_stall_ms_p50", "page_in_stall_ms_p99"):
            assert stats2[k] is None


class TestSpyNoFullMaterialization:
    def test_full_shuffled_scan_stays_block_bounded(self, tmp_path):
        core, arr = _make_core(tmp_path, n_rows=1024,
                               host_cache_bytes=4 * BLOCK_BYTES)
        rng = np.random.default_rng(2)
        order = rng.permutation(1024)
        for c in range(0, 1024, 96):  # epoch-style chunked scan
            chunk = order[c:c + 96]
            assert np.array_equal(core.gather(chunk), arr[chunk])
        spy = core.spy_counters()
        assert spy["max_read_rows"] <= BLOCK_ROWS
        assert spy["peak_cache_bytes"] <= 4 * BLOCK_BYTES
        assert spy["peak_cache_bytes"] < 1024 * ROW_BYTES // 2

    def test_images_raises_and_gates_route_away(self, tmp_path):
        train_set, _, al_set = get_data_synthetic(
            n_train=96, n_test=16, num_classes=4, image_size=8, seed=5)
        train_dp, al_dp = wrap_pool(train_set, al_set,
                                    str(tmp_path / "dp"))
        assert train_dp._core is al_dp._core  # ONE extent, ONE cache
        with pytest.raises(AttributeError, match="gather"):
            _ = train_dp.images
        # The exact gate expression every residency/feed consumer uses.
        assert getattr(train_dp, "images", None) is None
        assert train_dp.paged_backend is True
        assert len(train_dp) == 96
        view_dp = train_dp.with_view(al_set.view)
        assert view_dp._core is train_dp._core
        idxs = np.arange(0, 96, 7)
        assert np.array_equal(train_dp.gather(idxs),
                              train_set.images[idxs])

    def test_wrap_pool_needs_an_in_memory_source(self, tmp_path):
        class NoImages:
            pass

        with pytest.raises(ValueError, match="in-memory"):
            wrap_pool(NoImages(), NoImages(), str(tmp_path / "dp"))


class TestBackendRule:
    def test_explicit_backends_win(self):
        assert resolve_pool_backend("memory", 1 << 60) == "memory"
        assert resolve_pool_backend("disk", 1) == "disk"

    def test_auto_crosses_the_watermark(self, monkeypatch):
        monkeypatch.setattr(diskpool, "host_ram_bytes", lambda: 1000)
        assert resolve_pool_backend("auto", 499) == "memory"
        assert resolve_pool_backend("auto", 501) == "disk"
        assert resolve_pool_backend("auto", 200,
                                    watermark_frac=0.1) == "disk"
        # Unknown RAM -> never auto-select the disk tier.
        monkeypatch.setattr(diskpool, "host_ram_bytes", lambda: 0)
        assert resolve_pool_backend("auto", 1 << 60) == "memory"

    def test_bad_backend_rejected(self):
        with pytest.raises(ValueError, match="auto/memory/disk"):
            resolve_pool_backend("ramdisk", 1)

    def test_page_rows_snap_to_the_extent_ladder(self):
        for req in (1, 17, 64, 65, 300, 2048, 5000):
            assert page_rows_for(req) == bucket_size(max(req, 1),
                                                     floor=64)

    def test_spill_rows_blocked_writes(self, tmp_path):
        rng = np.random.default_rng(4)
        arr = rng.integers(0, 256, size=(150, *SHAPE), dtype=np.uint8)
        path = str(tmp_path / "spill.u8")
        with open(path, "wb") as fh:
            fh.truncate(arr.nbytes)
        mm = np.memmap(path, dtype=np.uint8, mode="r+",
                       shape=arr.shape)
        spill_rows(mm, arr, 0, 150, block_rows=64)  # partial last block
        assert np.array_equal(np.asarray(mm), arr)

        class Gatherable:  # the non-ndarray source arm
            def gather(self, idxs):
                return arr[idxs]

        mm2 = np.memmap(str(tmp_path / "spill2.u8"), dtype=np.uint8,
                        mode="w+", shape=arr.shape)
        spill_rows(mm2, Gatherable(), 0, 150, block_rows=64)
        assert np.array_equal(np.asarray(mm2), arr)


class TestPageReadChaos:
    def test_raise_mid_round_retries_bit_identical(self, tmp_path):
        core, arr = _make_core(tmp_path)
        before = faults.retry_counters()["by_site"].get("page_read", 0)
        faults.configure("page_read:raise@2", seed=7)
        idxs = np.arange(0, 192)  # 3 block reads; the 2nd one faults
        assert np.array_equal(core.gather(idxs), arr[idxs])
        assert faults.fault_counters()["page_read"]["fires"] == 1
        after = faults.retry_counters()["by_site"].get("page_read", 0)
        assert after == before + 1

    def test_torn_read_never_serves_rows(self, tmp_path):
        core, arr = _make_core(tmp_path)
        faults.configure("page_read:torn@1", seed=7)
        # The torn point fires BETWEEN the block's two half-reads —
        # before the cache insert, so the retried read (and everything
        # after) is bit-identical and no partial block is ever cached.
        idxs = np.arange(0, 64)
        assert np.array_equal(core.gather(idxs), arr[idxs])
        assert faults.fault_counters()["page_read"]["fires"] == 1
        for blk_id, blk in core._blocks.items():
            assert blk.shape[0] == 64, "a torn block entered the cache"
        assert np.array_equal(core.gather(idxs), arr[idxs])  # cache hit

    def test_delay_lands_in_the_stall_percentiles(self, tmp_path):
        core, arr = _make_core(tmp_path)
        faults.configure("page_read:delay@0.01", seed=7)
        idxs = np.arange(0, 128)
        assert np.array_equal(core.gather(idxs), arr[idxs])
        stats = core.take_round_stats()
        assert stats["page_in_stall_ms_p50"] >= 10.0


# -- end-to-end: memory vs disk backend bit-identity -------------------------

POOL_N = 256
POOL_BYTES = POOL_N * ROW_BYTES                  # 49152
RESIDENT_BUDGET = POOL_BYTES // 4                # pool is 4x the budget


def _run_e2e(tmp_path, name: str, sampler: str, backend: str,
             fault_spec=None):
    cfg = ExperimentConfig(
        dataset="synthetic", arg_pool="synthetic", strategy=sampler,
        rounds=2, round_budget=8, n_epoch=3, early_stop_patience=3,
        run_seed=7, exp_hash=name, exp_name="disk",
        ckpt_path=str(tmp_path / f"ckpt_{name}"),
        log_dir=str(tmp_path / f"logs_{name}"),
        pool_backend=backend, fault_spec=fault_spec,
        resident_scoring_bytes=RESIDENT_BUDGET,
        telemetry=TelemetryConfig(enabled=True, heartbeat_every_s=0.0))
    data = get_data_synthetic(n_train=POOL_N, n_test=32, num_classes=4,
                              image_size=8, seed=5)
    train_cfg = dataclasses.replace(
        tiny_train_config(), pool_page_rows=BLOCK_ROWS,
        pool_host_cache_bytes=RESIDENT_BUDGET)
    sink = JsonlSink(cfg.log_dir, experiment_key=name)
    strategy = run_experiment(cfg, sink=sink, data=data,
                              train_cfg=train_cfg,
                              model=TinyClassifier(num_classes=4))
    state_path = glob.glob(os.path.join(cfg.ckpt_path, "*",
                                        "experiment_state.npz"))[0]
    metrics = []
    with open(os.path.join(cfg.log_dir, "metrics.jsonl")) as fh:
        for line in fh:
            metrics.append(json.loads(line))
    return strategy, dict(np.load(state_path)), metrics


def _metric_series(events, name):
    return [(ev.get("step"), ev["metrics"][name]) for ev in events
            if ev.get("kind") == "metric"
            and ev.get("metrics", {}).get(name) is not None]


class TestDiskBackendBitIdentity:
    @pytest.mark.parametrize("sampler", ["MarginSampler", "CoresetSampler"])
    def test_disk_pool_bit_identical_to_memory(self, tmp_path, sampler):
        """The acceptance pin: the FULL driver, 2 rounds on the
        multi-device CPU mesh, a pool exactly 4x both residency budgets
        (HBM scoring + host block cache) — every experiment_state array
        and per-round test metric identical to the bit across backends,
        with the spy counters proving the disk leg never materialized
        the pool and the warm round compiling nothing new."""
        mem, mem_state, mem_metrics = _run_e2e(
            tmp_path, f"mem_{sampler}", sampler, "memory")
        disk, disk_state, disk_metrics = _run_e2e(
            tmp_path, f"disk_{sampler}", sampler, "disk")
        assert type(mem.al_set).__name__ != "DiskPool"
        assert type(disk.al_set).__name__ == "DiskPool"

        assert set(mem_state) == set(disk_state)
        for k in mem_state:
            assert np.array_equal(mem_state[k], disk_state[k]), (
                f"experiment_state[{k!r}] diverged on the disk tier")
        assert _metric_series(mem_metrics, "rd_test_accuracy")
        for metric in ("rd_test_accuracy", "rd_test_loss"):
            m = _metric_series(mem_metrics, metric)
            d = _metric_series(disk_metrics, metric)
            if m or d:
                assert m == d, metric

        # The spy contract, on the production run: reads stayed one
        # block, the cache stayed within budget, nothing approached the
        # pool's footprint.
        spy = disk.al_set.spy_counters()
        assert 0 < spy["max_read_rows"] <= BLOCK_ROWS
        assert spy["peak_cache_bytes"] <= RESIDENT_BUDGET + BLOCK_BYTES
        assert spy["peak_cache_bytes"] < POOL_BYTES // 2

        # The paging gauges landed in the metrics stream ...
        disk_rows = _metric_series(disk_metrics, "pool_disk_rows")
        assert disk_rows and all(v == POOL_N for _, v in disk_rows)
        assert _metric_series(disk_metrics, "pool_cache_hit_frac")
        # ... and never in the memory run's.
        assert not _metric_series(mem_metrics, "pool_disk_rows")

        # Warm rounds must not compile: paging changed storage, not
        # shapes — the round-1 jit miss delta is 0, as on memory.
        deltas = dict(_metric_series(disk_metrics,
                                     "jit_cache_miss_delta"))
        assert deltas[1] == 0, f"round-1 jit cache misses: {deltas[1]}"

    def test_mid_round_page_fault_completes_bit_identical(self, tmp_path):
        """The satellite chaos case: a page-read fault in the middle of
        a live round goes through the ONE RetryPolicy and the run
        completes with experiment_state bit-identical to the unfaulted
        disk run — the fault is visible only in fault_retries_total."""
        clean, clean_state, _ = _run_e2e(
            tmp_path, "chaos_clean", "MarginSampler", "disk")
        before = faults.retry_counters()["by_site"].get("page_read", 0)
        faulted, faulted_state, faulted_metrics = _run_e2e(
            tmp_path, "chaos_fault", "MarginSampler", "disk",
            fault_spec="page_read:raise@2")
        after = faults.retry_counters()["by_site"].get("page_read", 0)
        assert after == before + 1, "the injected fault never fired"
        assert type(faulted.al_set).__name__ == "DiskPool"
        assert set(clean_state) == set(faulted_state)
        for k in clean_state:
            assert np.array_equal(clean_state[k], faulted_state[k]), (
                f"experiment_state[{k!r}] diverged under the fault")
        retries = _metric_series(faulted_metrics, "fault_retries_total")
        assert retries and max(v for _, v in retries) >= 1
