"""The static-analysis engine (DESIGN.md §12) — tier-1 fail-fast.

This file sorts FIRST in the suite (test_analysis < test_backward), so
a lint violation anywhere in the package reds out in ~2 s before any
slow jax suite spins up — and the red NAMES its check id instead of
"trace_lint failed".

Pinned here:
  * the whole 18-check run over the live tree is CLEAN (unsuppressed),
    completes under the 5 s budget, and parses each file at most once
    (the shared-AST-cache contract — the reason the engine exists);
  * every checker in the registry has a golden negative-case fixture
    under tests/fixtures/analysis/<check-id>.py, and flags it — one
    parametrized test per check id;
  * the 10 ported legacy checks produce IDENTICAL verdicts through the
    engine and through the scripts/trace_lint.py shim, live tree and
    fixtures both;
  * suppression semantics: ``# al-lint: <token> <reason>`` suppresses
    with a reason (counted in --json), converts to its own finding
    without one, and the legacy checks accept no suppressions;
  * the al_lint CLI: --list names every check, --json emits the
    machine-readable report, --check selects subsets, unknown ids exit 2.

No jax import anywhere on these paths — the lint must work against a
wedged tree, and this suite must stay cheap.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "analysis")

sys.path.insert(0, REPO) if REPO not in sys.path else None

from active_learning_tpu.analysis import (  # noqa: E402
    Engine, run_package_analysis)
from active_learning_tpu.analysis.checks import (  # noqa: E402
    CHECK_IDS, CHECKERS)
from active_learning_tpu.analysis.checks import legacy  # noqa: E402

LEGACY_IDS = tuple(c.id for c in legacy.LEGACY_CHECKERS)
DEEP_IDS = tuple(i for i in CHECK_IDS if i not in LEGACY_IDS)


def fixture(check_id: str) -> str:
    return os.path.join(FIXTURES, f"{check_id}.py")


def checker_by_id(check_id: str):
    return next(c for c in CHECKERS if c.id == check_id)


# How each check runs against its single-file fixture.  Fixed-path
# checks take the fixture as their target module; package-scan checks
# take it as the file set; the deep checkers run through a real Engine
# so suppression handling is exercised on the same path production uses.
def run_fixture(check_id: str):
    path = fixture(check_id)
    if check_id == "phase-timer-span":
        return legacy.check_phase_timer_span(tracing_path=path)
    if check_id == "resident-feed":
        return legacy.check_resident_feed(trainer_path=path)
    if check_id == "sharded-selection":
        return legacy.check_sharded_selection(kcenter_path=path)
    if check_id == "pipeline-coordinator":
        return legacy.check_pipeline_coordinator(pipeline_path=path)
    if check_id in LEGACY_IDS:
        checker_fn = {
            "phase-timer-fork": legacy.check_phase_timer_fork,
            "phase-timer-import": legacy.check_phase_timer_import,
            "trace-annotation": legacy.check_trace_annotation,
            "fault-sites": legacy.check_fault_sites,
            "backward-registry": legacy.check_backward_registry,
            "profiler-confinement": legacy.check_profiler_confinement,
        }[check_id]
        return checker_fn(files=[path])
    return Engine(files=[path]).run([checker_by_id(check_id)]).findings


class TestPackageClean:
    def test_full_run_clean_fast_single_parse(self):
        """THE tier-1 gate: 18 checks over the whole package — zero
        unsuppressed findings, every suppression carries a reason, the
        run fits the 5 s budget, and no file parses twice."""
        report = run_package_analysis()
        assert sorted(report.checks_run) == sorted(CHECK_IDS)
        bad = [f.render() for f in report.unsuppressed]
        assert not bad, "al_lint findings on the tree:\n" + "\n".join(bad)
        for f in report.suppressed:
            assert f.suppress_reason.strip(), f.render()
        assert report.elapsed_s < 5.0, (
            f"whole-package analysis took {report.elapsed_s:.2f}s — the "
            "shared-parse budget is 5s")
        assert report.files_scanned > 50
        assert report.parse_counts, "cache recorded no parses"
        worst = max(report.parse_counts.values())
        assert worst <= 1, (
            "a file was parsed more than once — the single-parse AST "
            f"cache contract broke (max={worst})")

    def test_shim_matches_engine_on_live_tree(self):
        """The 10 legacy checks produce identical verdicts through the
        shim and through the engine registry (both clean here; fixture
        parity is pinned per-check below)."""
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "trace_lint", os.path.join(REPO, "scripts", "trace_lint.py"))
        shim = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(shim)
        shim_problems = shim.check()
        engine_report = Engine().run(legacy.LEGACY_CHECKERS)
        engine_problems = [f.render() for f in engine_report.findings]
        assert shim_problems == engine_problems == []


class TestFixtures:
    def test_every_checker_has_a_fixture(self):
        """A new checker cannot land without its golden negative case."""
        missing = [cid for cid in CHECK_IDS
                   if not os.path.exists(fixture(cid))]
        assert not missing, (
            f"checkers without a fixture under tests/fixtures/analysis/: "
            f"{missing}")
        stray = sorted(
            f for f in os.listdir(FIXTURES)
            if f.endswith(".py") and f[:-3] not in CHECK_IDS)
        assert not stray, f"fixtures naming no registered check: {stray}"

    @pytest.mark.parametrize("check_id", CHECK_IDS)
    def test_fixture_flags_its_check(self, check_id):
        """Each golden fixture is flagged BY ITS OWN check — a red here
        names the broken checker instead of 'trace_lint failed'."""
        findings = run_fixture(check_id)
        assert findings, f"{check_id}: fixture produced no findings"
        assert all(f.check == check_id for f in findings), (
            f"{check_id}: findings carry foreign check ids: "
            f"{[f.check for f in findings]}")
        assert all(not f.suppressed for f in findings)

    # phase-timer-span targets the fixed utils/tracing.py path in the
    # shim (exactly as the monolith did — check() has no tracing_path
    # parameter), so its fixture parity is the engine-side test above.
    @pytest.mark.parametrize(
        "check_id",
        sorted(i for i in LEGACY_IDS if i != "phase-timer-span"))
    def test_legacy_fixture_verdicts_match_shim(self, check_id):
        """Identical verdicts, engine vs shim, on the negative fixtures
        (message strings included — the shim renders the same
        Findings)."""
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "trace_lint", os.path.join(REPO, "scripts", "trace_lint.py"))
        shim = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(shim)
        path = fixture(check_id)
        engine_msgs = [f.render() for f in run_fixture(check_id)]
        shim_fn = {
            "phase-timer-span": None,  # shim exposes it only via check()
            "phase-timer-fork": None,
            "phase-timer-import": None,
            "trace-annotation": None,
            "resident-feed": lambda: shim.check_resident_feed(path),
            "sharded-selection": lambda: shim.check_sharded_selection(
                path),
            "pipeline-coordinator":
                lambda: shim.check_pipeline_coordinator(path),
            "fault-sites": lambda: shim.check_fault_sites([path]),
            "backward-registry":
                lambda: shim.check_backward_registry([path]),
            "profiler-confinement":
                lambda: shim.check_profiler_confinement([path]),
        }[check_id]
        if shim_fn is None:
            # The whole-tree checks ride shim.check() with a
            # monkeypatched walk.
            orig = shim._py_files
            try:
                shim._py_files = lambda: [path]
                shim_msgs = [p for p in shim.check()
                             if any(m in p for m in engine_msgs)
                             or p in engine_msgs]
            finally:
                shim._py_files = orig
            assert set(engine_msgs) <= set(shim_msgs), (
                engine_msgs, shim_msgs)
        else:
            assert shim_fn() == engine_msgs

    def test_lock_fixture_names_field_and_lock(self):
        msgs = [f.message for f in run_fixture("lock-discipline")]
        assert any("'_queue'" in m and "'_lock'" in m for m in msgs)

    def test_donation_fixture_names_path_and_line(self):
        f = run_fixture("donation-safety")[0]
        assert "state" in f.message and "donated" in f.message
        assert "use-after-donate" in f.message

    def test_recompile_fixture_flags_both_rules(self):
        msgs = [f.message for f in run_fixture("recompile-hazard")]
        assert any("outside the registered step-builders" in m
                   for m in msgs)
        assert any("f-string" in m and "static operand" in m
                   for m in msgs)

    def test_collective_fixture_flags_both_rules(self):
        msgs = [f.message for f in run_fixture("collective-axis")]
        assert any("unregistered/unresolvable axis" in m and "'rows'" in m
                   for m in msgs)
        assert any("owner-gather idiom" in m for m in msgs)

    def test_collective_fixture_flags_pod_tier_spellings(self):
        """ISSUE 15's new idioms: a masked psum_scatter outside
        owner_rows_scattered, and a hand-rolled ring ppermute outside
        mesh_lib.ring_shift, are both findings with home-naming hints."""
        msgs = [f.message for f in run_fixture("collective-axis")]
        assert any("masked-psum_scatter" in m
                   and "owner_rows_scattered" in m for m in msgs)
        assert any("ring-permute feed spelled by hand" in m
                   and "ring_shift" in m for m in msgs)


class TestSuppressions:
    def _one_violation(self, tmp_path, annotation=""):
        src = (
            "import functools\n"
            "import jax\n"
            "@functools.partial(jax.jit, donate_argnums=(0,))\n"
            "def step(state):\n"
            "    return state\n"
            "def train(state):\n"
            f"    out = step(state){annotation}\n"
            "    return out + state\n")
        p = tmp_path / "frag.py"
        p.write_text(src)
        checker = checker_by_id("donation-safety")
        return Engine(files=[str(p)]).run([checker])

    def test_reasoned_suppression_counts_but_passes(self, tmp_path):
        report = self._one_violation(
            tmp_path, "  # al-lint: donated-ok buffers are host copies")
        assert not report.unsuppressed
        assert len(report.suppressed) == 1
        assert report.suppressed[0].suppress_reason == \
            "buffers are host copies"
        j = report.to_json()
        assert j["total_suppressed"] == 1
        assert j["counts"]["donation-safety"]["suppressed"] == 1

    def test_reasonless_suppression_is_itself_a_finding(self, tmp_path):
        report = self._one_violation(tmp_path, "  # al-lint: donated-ok")
        assert len(report.unsuppressed) == 1
        assert "without a reason" in report.unsuppressed[0].message

    def test_unannotated_violation_fails(self, tmp_path):
        report = self._one_violation(tmp_path)
        assert len(report.unsuppressed) == 1
        assert "use-after-donate" in report.unsuppressed[0].message

    def test_wrong_token_does_not_suppress(self, tmp_path):
        report = self._one_violation(
            tmp_path, "  # al-lint: lock-ok not the right token")
        assert len(report.unsuppressed) == 1

    def test_donates_registry_is_package_global(self, tmp_path):
        """The trainer's donating steps are called through attributes
        from bench.py and the strategies — a _DONATES declared in one
        module must cover call sites in every other."""
        a = tmp_path / "a.py"
        a.write_text("_DONATES = {'_train_step': (0,)}\n"
                     "class T:\n"
                     "    def __init__(self):\n"
                     "        self._train_step = None\n")
        b = tmp_path / "b.py"
        b.write_text("def bench(trainer, state, batch):\n"
                     "    out = trainer._train_step(state, batch)\n"
                     "    return out, state\n")
        checker = checker_by_id("donation-safety")
        report = Engine(files=[str(a), str(b)]).run([checker])
        assert len(report.unsuppressed) == 1
        assert report.unsuppressed[0].path.endswith("b.py")
        # Rebinding in the same statement clears it.
        b.write_text("def bench(trainer, state, batch):\n"
                     "    state, loss = trainer._train_step(state, batch)\n"
                     "    return state, loss\n")
        report = Engine(files=[str(a), str(b)]).run([checker])
        assert not report.unsuppressed

    def test_rebind_rhs_is_still_a_use_after_donate(self, tmp_path):
        """``state = state.replace(...)`` after donating ``state`` reads
        the dead buffer on its right-hand side — the rebind must not
        launder it (code-review regression pin)."""
        p = tmp_path / "frag.py"
        p.write_text(
            "import functools\n"
            "import jax\n"
            "@functools.partial(jax.jit, donate_argnums=(0,))\n"
            "def step(state):\n"
            "    return state\n"
            "def train(state):\n"
            "    out = step(state)\n"
            "    state = state.replace(n=1)\n"
            "    return out, state\n")
        checker = checker_by_id("donation-safety")
        report = Engine(files=[str(p)]).run([checker])
        assert len(report.unsuppressed) == 1
        assert "rebinds it" in report.unsuppressed[0].message
        # A rebind from a FRESH value genuinely clears the taint.
        p.write_text(
            "import functools\n"
            "import jax\n"
            "@functools.partial(jax.jit, donate_argnums=(0,))\n"
            "def step(state):\n"
            "    return state\n"
            "def train(state, fresh):\n"
            "    out = step(state)\n"
            "    state = fresh()\n"
            "    return out, state\n")
        report = Engine(files=[str(p)]).run([checker])
        assert not report.unsuppressed

    def test_legacy_checks_accept_no_suppressions(self, tmp_path):
        """The ported checks must keep identical verdicts — an
        annotation cannot silence them."""
        p = tmp_path / "rogue.py"
        p.write_text("def phase_timer(name):  # al-lint: lock-ok nope\n"
                     "    return name\n")
        checker = checker_by_id("phase-timer-fork")
        assert checker.suppress_token is None
        report = Engine(files=[str(p)]).run([checker])
        assert len(report.unsuppressed) == 1


class TestFullTreeSemantics:
    def test_fault_sites_plugin_runs_registry_sub_checks(self, tmp_path):
        """The engine path must pass full_tree=True: the unwired-site
        sub-check lives only in whole-tree mode, and a file set that
        wires one site must report the rest of the REAL registry as
        unwired (code-review regression pin — without the flag the
        al_lint path silently skipped this, while the shim caught it)."""
        p = tmp_path / "one_site.py"
        p.write_text("from active_learning_tpu import faults\n"
                     "def up():\n"
                     "    faults.site('h2d_upload')\n")
        checker = checker_by_id("fault-sites")
        report = Engine(files=[str(p)]).run([checker])
        msgs = [f.message for f in report.unsuppressed]
        assert any("wired at no call site" in m for m in msgs), msgs

    def test_bare_jit_alias_is_confined_too(self, tmp_path):
        """``from jax import jit; step = jit(fn)`` is the cheapest
        evasion of the step-builder discipline — the bare-name spelling
        must be confined like jax.jit (code-review regression pin)."""
        p = tmp_path / "frag.py"
        p.write_text("from jax import jit\n"
                     "_STEP_BUILDERS = ('build',)\n"
                     "def build(fn):\n"
                     "    return jit(fn)\n"
                     "def rogue(fn):\n"
                     "    return jit(fn)\n")
        checker = checker_by_id("recompile-hazard")
        report = Engine(files=[str(p)]).run([checker])
        assert len(report.unsuppressed) == 1
        assert report.unsuppressed[0].line == 6


class TestCLI:
    def _run(self, *args):
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "al_lint.py"),
             *args],
            capture_output=True, text=True, timeout=120, cwd=REPO)

    def test_json_report_shape(self):
        proc = self._run("--json")
        assert proc.returncode == 0, proc.stderr[-2000:]
        out = json.loads(proc.stdout)
        assert sorted(out["checks_run"]) == sorted(CHECK_IDS)
        assert out["max_parses_per_file"] <= 1
        assert out["total_findings"] == 0
        # Every suppression in the report carries its reason string.
        for f in out["findings"]:
            if f["suppressed"]:
                assert f["suppress_reason"].strip()

    def test_list_names_every_check(self):
        proc = self._run("--list")
        assert proc.returncode == 0
        for cid in CHECK_IDS:
            assert cid in proc.stdout

    def test_check_subset_and_unknown_id(self):
        proc = self._run("--check", "lock-discipline", "--json")
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert json.loads(proc.stdout)["checks_run"] == \
            ["lock-discipline"]
        proc = self._run("--check", "no-such-check")
        assert proc.returncode == 2
        assert "no-such-check" in proc.stderr

    def test_plain_run_green(self):
        proc = self._run()
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert "al_lint: ok" in proc.stdout
