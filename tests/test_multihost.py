"""Multi-host data parallelism: per-process batch slicing + a real
2-process CPU smoke run.

The reference is single-node only (MASTER_ADDR hardcoded to 127.0.0.1,
strategy.py:288); its per-rank data split is DistributedSampler
(strategy.py:312-314).  Here the per-host split is ``process_local_rows``
(read off the sharding itself) feeding ``gather_batch(..., local=...)``,
and the cross-host pieces (batch assembly, gradient reduction, score
gather) are exercised for real by spawning two coordinated JAX processes
over localhost — the CPU stand-in for a pod slice.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

# Spawns real 2-process jax.distributed runs (fresh interpreters, fresh
# XLA compiles per process).
pytestmark = pytest.mark.slow

from active_learning_tpu.data.pipeline import gather_batch, padded_batch_layout
from active_learning_tpu.data.synthetic import get_data_synthetic
from active_learning_tpu.parallel import mesh as mesh_lib

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestLocalSliceMath:
    def test_single_process_owns_everything(self):
        mesh = mesh_lib.make_mesh(8)
        assert mesh_lib.process_local_rows(mesh, 16) == slice(0, 16)
        assert not mesh_lib.is_multiprocess(mesh)

    def test_local_gather_matches_rows_of_full_gather(self):
        """gather_batch(local=s) must equal rows s of the full batch for
        every field, including padding rows of a partial batch."""
        train_set, _, _ = get_data_synthetic(n_train=32, n_test=8,
                                             num_classes=4, image_size=8,
                                             seed=0)
        idxs = np.array([5, 9, 2, 17, 11])  # partial batch of 8 -> 3 pad
        full = gather_batch(train_set, idxs, 8)
        for s in (slice(0, 4), slice(4, 8), slice(2, 6)):
            part = gather_batch(train_set, idxs, 8, local=s)
            for k in full:
                np.testing.assert_array_equal(part[k], full[k][s], err_msg=k)

    def test_padded_layout_is_deterministic(self):
        idxs = np.array([3, 1, 4])
        padded, mask = padded_batch_layout(idxs, 8)
        np.testing.assert_array_equal(padded, [3, 1, 4, 3, 3, 3, 3, 3])
        np.testing.assert_array_equal(mask, [1, 1, 1, 0, 0, 0, 0, 0])
        # Full batch: untouched.
        padded, mask = padded_batch_layout(np.arange(8), 8)
        np.testing.assert_array_equal(padded, np.arange(8))
        assert mask.min() == 1.0


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _single_process_oracle():
    """The worker's computation on a 4-device single-process mesh."""
    import jax

    from active_learning_tpu.strategies import scoring
    from active_learning_tpu.train.trainer import Trainer
    from helpers import TinyClassifier, tiny_train_config

    mesh = mesh_lib.make_mesh(4)
    train_set, _, al_set = get_data_synthetic(
        n_train=64, n_test=16, num_classes=4, image_size=8, seed=3)
    model = TinyClassifier()
    trainer = Trainer(model, tiny_train_config(batch_size=8), mesh,
                      num_classes=4)
    state = trainer.init_state(jax.random.PRNGKey(0),
                               train_set.gather(np.arange(2)))
    result = trainer.fit(state, train_set, np.arange(32), al_set,
                         np.arange(32, 48), n_epoch=2, es_patience=2,
                         rng=np.random.default_rng(0))
    leaves = jax.tree_util.tree_leaves(
        jax.tree.map(np.asarray, result.state.params))
    flat = np.concatenate([p.ravel() for p in leaves])
    step = scoring.make_prob_stats_step(model, al_set.view)
    scores = scoring.collect_pool(al_set, np.arange(48, 64), 8, step,
                                  result.state.variables, mesh)
    return float(flat.sum()), np.asarray(scores["margin"], np.float64)


class TestTwoProcessSmoke:
    def test_two_processes_match_single_process(self, tmp_path):
        """2 processes x 2 CPU devices == 1 process x 4 CPU devices:
        same trained parameters, same pool scores, and each process
        gathered only its half of every batch."""
        port = _free_port()
        env = dict(os.environ,
                   PALLAS_AXON_POOL_IPS="", JAX_PLATFORMS="cpu",
                   XLA_FLAGS="--xla_force_host_platform_device_count=2")
        # The workers must not inherit pytest's 8-device flag.
        procs, outs = [], []
        for pid in range(2):
            out = tmp_path / f"worker_{pid}.json"
            outs.append(out)
            procs.append(subprocess.Popen(
                [sys.executable, os.path.join(REPO, "tests",
                                              "multihost_worker.py"),
                 f"127.0.0.1:{port}", "2", str(pid), str(out)],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True))
        results = []
        for p in procs:
            try:
                stdout, stderr = p.communicate(timeout=420)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                pytest.fail("multi-host worker timed out")
            assert p.returncode == 0, f"worker failed:\n{stderr[-3000:]}"
        for out in outs:
            results.append(json.loads(out.read_text()))

        by_pid = {r["process_index"]: r for r in results}
        assert set(by_pid) == {0, 1}
        for r in results:
            assert r["process_count"] == 2
            assert r["n_devices_global"] == 4
        # Each process owns one contiguous half of every global batch.
        assert by_pid[0]["local_rows"] == [0, 4]
        assert by_pid[1]["local_rows"] == [4, 8]
        # Both processes agree bit-for-bit (replicated state, gathered
        # scores are global).
        assert by_pid[0]["param_sum"] == by_pid[1]["param_sum"]
        assert by_pid[0]["margin"] == by_pid[1]["margin"]
        # Decoded-pool disk cache under jax.distributed: both processes
        # scored through their own per-process cache files and the warm
        # margins agreed with the raw dataset (asserted in-worker) AND
        # across processes here.  A missing margin is only acceptable
        # with an explicit skip reason (PIL absent) — any other failure
        # already crashed the worker above.
        if by_pid[0]["decoded_cache_margin"] is None:
            assert by_pid[0]["decoded_cache_skip"], by_pid[0]
        else:
            assert by_pid[0]["decoded_cache_margin"] == \
                by_pid[1]["decoded_cache_margin"]

        oracle_sum, oracle_margin = _single_process_oracle()
        assert by_pid[0]["param_sum"] == pytest.approx(oracle_sum, rel=1e-5)
        np.testing.assert_allclose(np.array(by_pid[0]["margin"]),
                                   oracle_margin, rtol=1e-5, atol=1e-6)

        # BalancingSampler's cross-process pick loop: both processes agree
        # and match the host-NumPy selection over the same seeded inputs.
        assert by_pid[0]["balancing_picks"] == by_pid[1]["balancing_picks"]
        assert by_pid[0]["balancing_picks"] == _balancing_picks_oracle()


def _balancing_picks_oracle():
    """Host-NumPy replay of the worker's 4 seeded balancing picks."""
    brng = np.random.default_rng(5)
    emb = brng.normal(size=(37, 6)).astype(np.float32)
    eligible = np.ones(37, bool)
    eligible[::7] = False
    centers = brng.normal(size=(4, 6)).astype(np.float32)
    maj = np.array([True, True, False, False])
    rarest = 2
    picks = []
    for _ in range(4):
        d_rare = ((emb - centers[rarest]) ** 2).sum(1)
        a2 = (emb ** 2).sum(1, keepdims=True)
        b2 = (centers ** 2).sum(1)[None, :]
        d_all = a2 + b2 - 2.0 * emb @ centers.T
        norm = np.where(maj[None, :], d_all, -np.inf).max(1)
        score = np.where(eligible, d_rare / norm, np.inf)
        q = int(np.argmin(score))
        eligible[q] = False
        picks.append(q)
    return picks
