"""Worker for the 2-process CPU multi-host smoke test.

Each process owns 2 virtual CPU devices; together they form one 4-device
global mesh, the CPU stand-in for a 2-host TPU pod slice over DCN.  The
worker runs a real multi-epoch ``Trainer.fit`` (per-process batch slicing,
cross-process gradient reduction, global-batch BN-free tiny model,
sharded validation) plus a ``collect_pool`` scoring pass with the
cross-host result gather, then writes one JSON summary.

Manual smoke recipe (also driven by tests/test_multihost.py):

    PORT=$(python -c "import socket; s=socket.socket(); \
           s.bind(('127.0.0.1', 0)); print(s.getsockname()[1])")
    for P in 0 1; do
      PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
      XLA_FLAGS=--xla_force_host_platform_device_count=2 \
      python tests/multihost_worker.py 127.0.0.1:$PORT 2 $P /tmp/mh_$P.json &
    done; wait; cat /tmp/mh_*.json

The same flags reach the real CLI as --coordinator_address /
--num_processes / --process_id (experiment/cli.py).
"""

from __future__ import annotations

import json
import os
import sys


def main() -> None:
    coordinator, nprocs, pid, out_path = (
        sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4])
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    jax.config.update("jax_platforms", "cpu")

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, repo)
    sys.path.insert(0, os.path.join(repo, "tests"))
    # Through the production rendezvous, not a bare
    # jax.distributed.initialize: initialize_distributed arms the gloo
    # CPU collectives a cross-process CPU mesh needs — without them
    # XLA:CPU refuses multiprocess computations outright (the reason
    # this smoke was red before the pod tier, ISSUE 15).
    from active_learning_tpu.parallel import mesh as _mesh_boot
    _mesh_boot.initialize_distributed(coordinator_address=coordinator,
                                      num_processes=nprocs,
                                      process_id=pid)
    import numpy as np

    from active_learning_tpu.data.synthetic import get_data_synthetic
    from active_learning_tpu.parallel import mesh as mesh_lib
    from active_learning_tpu.strategies import scoring
    from active_learning_tpu.train.trainer import Trainer
    from helpers import TinyClassifier, tiny_train_config

    mesh = mesh_lib.make_mesh()
    bs = 8
    local = mesh_lib.process_local_rows(mesh, bs)

    train_set, _, al_set = get_data_synthetic(
        n_train=64, n_test=16, num_classes=4, image_size=8, seed=3)
    model = TinyClassifier()
    trainer = Trainer(model, tiny_train_config(batch_size=bs), mesh,
                      num_classes=4)
    state = trainer.init_state(jax.random.PRNGKey(0),
                               train_set.gather(np.arange(2)))
    result = trainer.fit(state, train_set, np.arange(32), al_set,
                         np.arange(32, 48), n_epoch=2, es_patience=2,
                         rng=np.random.default_rng(0))
    leaves = jax.tree_util.tree_leaves(
        jax.tree.map(np.asarray, result.state.params))
    flat = np.concatenate([p.ravel() for p in leaves])

    step = scoring.make_prob_stats_step(model, al_set.view)
    scores = scoring.collect_pool(al_set, np.arange(48, 64), bs, step,
                                  result.state.variables, mesh)
    # The device-resident path on a multi-process mesh (what a pod run
    # with an in-memory pool uses): pool upload via the replicated
    # make_array_from_callback branch, per-batch on-device gathers, one
    # cross-host fetch — must agree with the host-batched scores above.
    res_scores = scoring.collect_pool(al_set, np.arange(48, 64), bs, step,
                                      result.state.variables, mesh,
                                      resident_cache={})
    np.testing.assert_allclose(
        np.asarray(res_scores["margin"]), np.asarray(scores["margin"]),
        rtol=1e-6, atol=1e-6)

    # BalancingSampler's device pick loop across processes: the sharded
    # pool upload takes the make_array_from_process_local_data branch, and
    # the argmin + eligibility scatter run as cross-process SPMD.  Inputs
    # are seeded so every process (and the single-process oracle in
    # test_multihost.py) computes from identical data; 37 rows on 4
    # devices also exercises the pad-row ineligibility.
    from active_learning_tpu.strategies.balancing import (
        _balancing_pick, _mark_taken, device_pool_state)
    brng = np.random.default_rng(5)
    emb = brng.normal(size=(37, 6)).astype(np.float32)
    eligible = np.ones(37, bool)
    eligible[::7] = False
    centers = brng.normal(size=(4, 6)).astype(np.float32)
    maj = np.array([True, True, False, False])
    emb_dev, elig_dev = device_pool_state(mesh, emb, eligible)
    picks = []
    for _ in range(4):
        small = mesh_lib.replicate(
            (centers, maj, np.int32(2), np.bool_(False)), mesh)
        q = int(_balancing_pick(emb_dev, elig_dev, *small))
        elig_dev = _mark_taken(elig_dev,
                               mesh_lib.replicate(np.int32(q), mesh))
        picks.append(q)

    # Decoded-pool disk cache across processes: cache files are
    # process-suffixed (no cross-process locking), each process decodes
    # only its local rows, and scoring THROUGH the cache must equal
    # scoring the raw disk dataset.  Only PIL's availability is optional
    # (recorded as a skip reason); any other failure in this block is a
    # real bug and must crash the worker loudly.
    decoded_margin = None
    decoded_skip = None
    try:
        from PIL import Image  # noqa: F401 — availability probe only
    except ImportError:
        decoded_skip = "PIL unavailable"
    if decoded_skip is None:
        from active_learning_tpu.data.cache import (DecodedPoolCache,
                                                    maybe_wrap_decoded)
        from active_learning_tpu.data.core import IMAGENET_NORM, ViewSpec
        from active_learning_tpu.data.imagenet import ImageFolderDataset
        from helpers import build_jpeg_tree
        from jax.experimental import multihost_utils

        # SHARED scratch (both workers' out paths live in one directory):
        # process 0 writes the tree (atomic rename inside the builder —
        # an interrupted manual run never leaves a reusable partial
        # tree), the barrier publishes it to all.
        scratch = os.path.join(os.path.dirname(os.path.abspath(out_path)),
                               "mh_scratch")
        tree = os.path.join(scratch, "tree")
        if jax.process_index() == 0:
            os.makedirs(scratch, exist_ok=True)
            build_jpeg_tree(tree, n_classes=3, n_per_class=4, seed=9,
                            min_hw=48, max_hw=56)
        multihost_utils.sync_global_devices("jpeg_tree_built")
        view = ViewSpec(IMAGENET_NORM, augment=False)
        ds = ImageFolderDataset(tree, view, False, num_classes=3)
        cached = maybe_wrap_decoded(ds, os.path.join(scratch, "dcache"),
                                    1 << 30)
        assert isinstance(cached, DecodedPoolCache)
        assert cached._data_path.endswith(f"_p{jax.process_index()}.u8")
        dmodel = TinyClassifier(num_classes=3)
        dvars = dmodel.init(jax.random.PRNGKey(1),
                            ds.gather(np.zeros(1, np.int64)), train=False)
        dstep = scoring.make_prob_stats_step(dmodel, view)
        raw = scoring.collect_pool(ds, np.arange(len(ds)), 4, dstep, dvars,
                                   mesh)
        warm = scoring.collect_pool(cached, np.arange(len(ds)), 4, dstep,
                                    dvars, mesh)
        np.testing.assert_allclose(np.asarray(warm["margin"]),
                                   np.asarray(raw["margin"]),
                                   rtol=1e-6, atol=1e-6)
        decoded_margin = np.asarray(warm["margin"], np.float64).tolist()

    out = {
        "balancing_picks": picks,
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "n_devices_global": int(mesh.devices.size),
        "local_rows": [local.start, local.stop],
        "best_perf": float(result.best_perf),
        "param_sum": float(flat.sum()),
        "margin": np.asarray(scores["margin"], np.float64).tolist(),
        "decoded_cache_margin": decoded_margin,
        "decoded_cache_skip": decoded_skip,
    }
    with open(out_path, "w") as fh:
        json.dump(out, fh)
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
