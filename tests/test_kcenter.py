"""k-center engine + Coreset/BADGE sampler tests.

The device scan (strategies/kcenter.py) is checked against a NumPy oracle
that re-implements the reference's greedy loop verbatim
(coreset_sampler.py:66-105): full N x N squared-L2 matrix, min over labeled
columns, argmax per step.  The factorized BADGE distances are checked
against materialized outer products, and the pooling matrices against
torch's adaptive_avg_pool2d.
"""

import numpy as np
import pytest

from active_learning_tpu.strategies.kcenter import (
    adaptive_avg_pool_matrix, kcenter_greedy, min_sq_dist_to, self_sq_norms)

from helpers import make_strategy


def oracle_kcenter(emb, labeled_mask, budget):
    """The reference's greedy loop (coreset_sampler.py:75-105),
    deterministic mode."""
    d = ((emb[:, None, :] - emb[None, :, :]) ** 2).sum(-1)
    lab = labeled_mask.copy()
    picks = []
    for _ in range(budget):
        if lab.sum() > 0:
            q = int(d[:, lab].min(axis=1).argmax())
        else:
            q = int(d.max(axis=1).argmin())
        picks.append(q)
        lab[q] = True
    return np.asarray(picks)


class TestKCenterGreedy:
    def test_matches_reference_loop(self):
        rng = np.random.default_rng(0)
        emb = rng.normal(size=(40, 5)).astype(np.float32)
        labeled = np.zeros(40, dtype=bool)
        labeled[rng.choice(40, 6, replace=False)] = True
        got = kcenter_greedy((emb,), labeled, budget=8, randomize=False,
                             rng=np.random.default_rng(1))
        np.testing.assert_array_equal(got, oracle_kcenter(emb, labeled, 8))

    def test_empty_labeled_seed_is_minimax_row(self):
        rng = np.random.default_rng(2)
        emb = rng.normal(size=(25, 4)).astype(np.float32)
        labeled = np.zeros(25, dtype=bool)
        got = kcenter_greedy((emb,), labeled, budget=5, randomize=False,
                             rng=np.random.default_rng(3))
        np.testing.assert_array_equal(got, oracle_kcenter(emb, labeled, 5))

    def test_randomized_structural(self):
        rng = np.random.default_rng(4)
        emb = rng.normal(size=(60, 6)).astype(np.float32)
        labeled = np.zeros(60, dtype=bool)
        labeled[:10] = True
        got = kcenter_greedy((emb,), labeled, budget=15, randomize=True,
                             rng=np.random.default_rng(5))
        assert len(got) == 15
        assert np.unique(got).size == 15
        assert not labeled[got].any()
        # Same host rng seed -> same JAX key -> same draws.
        again = kcenter_greedy((emb,), labeled, budget=15, randomize=True,
                               rng=np.random.default_rng(5))
        np.testing.assert_array_equal(got, again)

    def test_randomized_prefers_far_points(self):
        # One far cluster: D^2 weights should select from it first.
        emb = np.zeros((32, 2), dtype=np.float32)
        emb[16:] += 100.0
        labeled = np.zeros(32, dtype=bool)
        labeled[0] = True
        got = kcenter_greedy((emb,), labeled, budget=1, randomize=True,
                             rng=np.random.default_rng(6))
        assert got[0] >= 16

    def test_blocked_min_dist_matches_dense(self):
        rng = np.random.default_rng(7)
        emb = rng.normal(size=(50, 3)).astype(np.float32)
        labeled_idxs = rng.choice(50, 20, replace=False)
        import jax.numpy as jnp
        factors = (jnp.asarray(emb),)
        got = np.asarray(min_sq_dist_to(factors, self_sq_norms(factors),
                                        labeled_idxs, chunk_size=7))
        d = ((emb[:, None, :] - emb[None, :, :]) ** 2).sum(-1)
        np.testing.assert_allclose(got, d[:, labeled_idxs].min(axis=1),
                                   rtol=1e-4, atol=1e-4)


class TestBatchedGreedy:
    """Batched farthest-first (q picks per pool pass with the exact
    in-batch re-check) must be pick-for-pick identical to q=1 greedy —
    the correctness claim that makes cutting scan steps ~q x free."""

    @pytest.mark.parametrize("q", [2, 3, 8])
    def test_batched_matches_q1_and_oracle(self, q):
        rng = np.random.default_rng(11)
        emb = rng.normal(size=(70, 6)).astype(np.float32)
        labeled = np.zeros(70, dtype=bool)
        labeled[rng.choice(70, 9, replace=False)] = True
        budget = 13  # not a multiple of any q above
        want = oracle_kcenter(emb, labeled, budget)
        q1 = kcenter_greedy((emb,), labeled, budget, randomize=False,
                            rng=np.random.default_rng(1), batch_q=1)
        np.testing.assert_array_equal(q1, want)
        got = kcenter_greedy((emb,), labeled, budget, randomize=False,
                             rng=np.random.default_rng(1), batch_q=q)
        np.testing.assert_array_equal(got, want)

    def test_batched_from_empty_labeled_seed(self):
        rng = np.random.default_rng(12)
        emb = rng.normal(size=(40, 4)).astype(np.float32)
        labeled = np.zeros(40, dtype=bool)
        want = oracle_kcenter(emb, labeled, 9)
        got = kcenter_greedy((emb,), labeled, 9, randomize=False,
                             rng=np.random.default_rng(2), batch_q=4)
        np.testing.assert_array_equal(got, want)

    def test_batched_two_factor(self):
        rng = np.random.default_rng(13)
        a = rng.normal(size=(30, 5)).astype(np.float32)
        e = rng.normal(size=(30, 7)).astype(np.float32)
        g = np.einsum("nc,nd->ncd", a, e).reshape(30, -1)
        labeled = np.zeros(30, dtype=bool)
        labeled[[2, 17]] = True
        got = kcenter_greedy((a, e), labeled, 7, randomize=False,
                             rng=np.random.default_rng(3), batch_q=4)
        np.testing.assert_array_equal(got, oracle_kcenter(g, labeled, 7))

    def test_budget_exhausts_pool(self):
        # budget == every unlabeled point: the re-check's stop-early and
        # the while loop's budget clamp must still deliver them all.
        rng = np.random.default_rng(14)
        emb = rng.normal(size=(20, 3)).astype(np.float32)
        labeled = np.zeros(20, dtype=bool)
        labeled[:5] = True
        got = kcenter_greedy((emb,), labeled, 15, randomize=False,
                             rng=np.random.default_rng(4), batch_q=8)
        assert np.unique(got).size == 15
        assert not labeled[got].any()
        np.testing.assert_array_equal(got, oracle_kcenter(emb, labeled, 15))


class TestFactorizedDistances:
    def test_two_factor_dots_equal_outer_product_dots(self):
        rng = np.random.default_rng(8)
        a = rng.normal(size=(12, 5)).astype(np.float32)
        e = rng.normal(size=(12, 7)).astype(np.float32)
        g = np.einsum("nc,nd->ncd", a, e).reshape(12, -1)
        import jax.numpy as jnp
        factors = (jnp.asarray(a), jnp.asarray(e))
        np.testing.assert_allclose(np.asarray(self_sq_norms(factors)),
                                   (g ** 2).sum(1), rtol=1e-4)
        labeled = np.zeros(12, dtype=bool)
        labeled[[1, 4]] = True
        got = kcenter_greedy(factors, labeled, budget=4, randomize=False,
                             rng=np.random.default_rng(9))
        np.testing.assert_array_equal(got, oracle_kcenter(g, labeled, 4))

    def test_pool_matrix_matches_torch_adaptive_pool(self):
        torch = pytest.importorskip("torch")
        import torch.nn.functional as F
        rng = np.random.default_rng(10)
        for c, d, ph in [(10, 64, 10), (20, 48, 16)]:
            pw = int(512 / ph)
            a = rng.normal(size=(c,)).astype(np.float32)
            e = rng.normal(size=(d,)).astype(np.float32)
            g = np.outer(a, e)
            ref = F.adaptive_avg_pool2d(
                torch.from_numpy(g)[None], (min(ph, c), min(pw, d)))[0].numpy()
            pa = a @ adaptive_avg_pool_matrix(c, min(ph, c))
            pe = e @ adaptive_avg_pool_matrix(d, min(pw, d))
            np.testing.assert_allclose(np.outer(pa, pe), ref, rtol=1e-5,
                                       atol=1e-6)


def direct_embeddings(strategy, idxs):
    import jax.numpy as jnp
    from active_learning_tpu.data.augment import apply_view
    images = strategy.al_set.gather(idxs)
    x = apply_view(jnp.asarray(images), strategy.al_set.view, train=False)
    _, emb = strategy.model.apply(strategy.state.variables, x, train=False,
                                  return_features=True)
    return np.asarray(emb)


class TestCoresetSampler:
    def test_matches_oracle_end_to_end(self):
        s = make_strategy("CoresetSampler", n_train=96)
        idxs_for_coreset = s.get_idxs_for_coreset()
        emb = direct_embeddings(s, idxs_for_coreset)
        labeled = s.already_labeled_mask()[idxs_for_coreset]
        budget = 7
        expected = idxs_for_coreset[oracle_kcenter(emb, labeled, budget)]
        got, cost = s.query(budget)
        assert cost == budget
        np.testing.assert_array_equal(got, expected)
        assert not s.pool.labeled[got].any()
        assert not np.isin(got, s.pool.eval_idxs).any()

    def test_subset_caps(self):
        s = make_strategy("CoresetSampler", n_train=96,
                          subset_labeled=4, subset_unlabeled=20)
        full, lab, unlab = s.get_idxs_for_coreset(return_sep_idxs=True)
        assert len(lab) == 4
        # Unused labeled quota rolls into the unlabeled cap
        # (coreset_sampler.py:28-34): here both caps bind exactly.
        assert len(unlab) == 20
        assert len(full) == 24
        # query() draws its own (shuffled) subset internally; check the
        # selection is valid rather than matching the draw above.
        got, cost = s.query(5)
        assert cost == 5 and np.unique(got).size == 5
        assert not s.pool.labeled[got].any()
        assert not np.isin(got, s.pool.eval_idxs).any()

    def test_freeze_feature_caches_embeddings(self):
        s = make_strategy("CoresetSampler", freeze_feature=True)
        calls = {"n": 0}
        orig = s.get_factors

        def counting(idxs):
            calls["n"] += 1
            return orig(idxs)

        s.get_factors = counting
        s.query(4)
        s.query(4)
        assert calls["n"] == 1  # second query served from the cache

    def test_no_cache_without_freeze(self):
        s = make_strategy("CoresetSampler")
        s.query(4)
        assert s._saved_factors is None


class TestBADGESampler:
    def test_grad_factors_match_closed_form(self):
        import jax
        import jax.numpy as jnp
        from active_learning_tpu.data.augment import apply_view
        s = make_strategy("BADGESampler")
        avail = s.available_query_idxs(shuffle=False)[:16]
        out = s.collect_scores(avail, "badge", keys=("grad_a", "grad_e"))
        images = s.al_set.gather(avail)
        x = apply_view(jnp.asarray(images), s.al_set.view, train=False)
        logits, emb = s.model.apply(s.state.variables, x, train=False,
                                    return_features=True)
        probs = np.asarray(jax.nn.softmax(logits.astype(jnp.float32), -1))
        onehot = np.eye(probs.shape[1])[probs.argmax(1)]
        np.testing.assert_allclose(out["grad_a"], probs - onehot, rtol=1e-4,
                                   atol=1e-5)
        np.testing.assert_allclose(out["grad_e"], np.asarray(emb), rtol=1e-4,
                                   atol=1e-5)

    def test_query_structural(self):
        s = make_strategy("BADGESampler", n_train=96)
        got, cost = s.query(9)
        assert cost == 9 and np.unique(got).size == 9
        assert not s.pool.labeled[got].any()
        assert s._saved_factors is None  # BADGE never caches


class TestPartitionedSamplers:
    @pytest.mark.parametrize("name", ["PartitionedCoresetSampler",
                                      "PartitionedBADGESampler"])
    def test_query_structural(self, name):
        s = make_strategy(name, n_train=96, partitions=3)
        got, cost = s.query(10)
        assert cost == 10 and np.unique(got).size == 10
        assert not s.pool.labeled[got].any()
        assert not np.isin(got, s.pool.eval_idxs).any()
        np.testing.assert_array_equal(got, np.sort(got))

    def test_partition_split_rule(self):
        s = make_strategy("PartitionedCoresetSampler", partitions=3)
        parts = s.generate_partition_idxs_list(np.arange(11))
        assert [len(p) for p in parts] == [4, 4, 3]
        assert np.array_equal(np.sort(np.concatenate(parts)), np.arange(11))

    def test_partitioned_matches_plain_when_one_partition(self):
        a = make_strategy("PartitionedCoresetSampler", n_train=96,
                          partitions=1)
        got_a, _ = a.query(6)
        b = make_strategy("CoresetSampler", n_train=96)
        got_b, _ = b.query(6)
        np.testing.assert_array_equal(np.sort(got_a), np.sort(got_b))
