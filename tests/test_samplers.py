"""Sampler unit tests on the virtual 8-device CPU mesh.

Each sampler's selection is checked against a NumPy oracle computed from a
direct (unsharded) forward pass, so these tests validate both the sampler
logic AND the mesh-sharded scoring path (strategies/scoring.py).  The MASE
boundary self-check (reference runtime assert, mase_sampler.py:85-90) is a
real test here.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from active_learning_tpu.data.augment import apply_view
from active_learning_tpu.initial_pool import balanced_allocation
from active_learning_tpu.strategies import scoring

from helpers import make_strategy


def direct_probs(strategy, idxs):
    """Oracle: unsharded forward pass over al_set[idxs] -> softmax probs."""
    images = strategy.al_set.gather(idxs)
    x = apply_view(jnp.asarray(images), strategy.al_set.view, train=False)
    logits = strategy.model.apply(strategy.state.variables, x, train=False)
    return np.asarray(jax.nn.softmax(logits.astype(jnp.float32), axis=-1))


class TestRandomSampler:
    def test_query_disjoint_and_sized(self):
        s = make_strategy("RandomSampler")
        idxs, cost = s.query(12)
        assert cost == 12 and len(idxs) == 12
        assert np.unique(idxs).size == 12
        assert not s.pool.labeled[idxs].any()
        assert not np.isin(idxs, s.pool.eval_idxs).any()
        s.update(idxs, cost)  # invariants enforced in PoolState.update
        assert s.pool.num_labeled == 8 + 12

    def test_budget_clamped_to_pool(self):
        s = make_strategy("RandomSampler", n_train=32, init_pool=8,
                          eval_count=8)
        idxs, cost = s.query(10_000)
        assert cost == 32 - 8 - 8 == len(idxs)

    def test_reproducible_given_seed(self):
        a = make_strategy("RandomSampler").query(8)[0]
        b = make_strategy("RandomSampler").query(8)[0]
        np.testing.assert_array_equal(a, b)


class TestBalancedRandomSampler:
    def test_quota_matches_water_filling(self):
        s = make_strategy("BalancedRandomSampler", n_train=128, init_pool=0)
        budget = 16
        idxs, cost = s.query(budget)
        assert cost == budget
        targets = s.al_set.targets[idxs]
        counts = np.bincount(
            s.al_set.targets[s.available_query_mask()],
            minlength=s.num_classes)
        expected = balanced_allocation(counts, budget)
        np.testing.assert_array_equal(
            np.bincount(targets, minlength=s.num_classes), expected)

    def test_scarce_class_exhausted_first(self):
        # With one class nearly exhausted the water-filling hands its
        # remaining examples out and tops up from the rich classes.
        s = make_strategy("BalancedRandomSampler", n_train=128, init_pool=0)
        targets = s.al_set.targets
        avail = s.available_query_mask()
        scarce = 0
        scarce_idxs = np.flatnonzero((targets == scarce) & avail)
        # Label all but 1 example of the scarce class out-of-band.
        s.update(scarce_idxs[:-1], len(scarce_idxs) - 1)
        idxs, cost = s.query(12)
        got = np.bincount(targets[idxs], minlength=s.num_classes)
        assert got[scarce] == 1
        assert got.sum() == 12


class TestUncertaintySamplers:
    @pytest.mark.parametrize("name,score", [
        ("ConfidenceSampler", lambda p: p.max(axis=1)),
        ("MarginSampler",
         lambda p: np.sort(p, axis=1)[:, -1] - np.sort(p, axis=1)[:, -2]),
    ])
    def test_matches_numpy_oracle(self, name, score):
        s = make_strategy(name)
        avail = s.available_query_idxs(shuffle=False)
        probs = direct_probs(s, avail)
        expected_scores = score(probs)
        budget = 10
        got, cost = s.query(budget)
        assert cost == budget
        expected = avail[np.argsort(expected_scores, kind="stable")[:budget]]
        np.testing.assert_array_equal(np.sort(got), np.sort(expected))
        # Selected scores must be the bottom-k scores exactly.
        pos = {int(v): i for i, v in enumerate(avail)}
        got_scores = expected_scores[[pos[int(g)] for g in got]]
        assert got_scores.max() <= np.partition(
            expected_scores, budget - 1)[budget - 1] + 1e-7


class TestMASE:
    def test_boundary_self_check(self):
        """Perturbing an embedding by radius * unit-normal of its nearest
        boundary must land it ON the boundary: equal top-2 logits
        (reference assert, mase_sampler.py:85-90)."""
        rng = np.random.default_rng(1)
        d, c, b = 6, 5, 32
        emb = jnp.asarray(rng.normal(size=(b, d)).astype(np.float32))
        kernel = jnp.asarray(rng.normal(size=(d, c)).astype(np.float32))
        bias = jnp.asarray(rng.normal(size=(c,)).astype(np.float32))
        out = scoring.boundary_radii(emb, kernel, bias)
        radii, preds = np.asarray(out["radii"]), np.asarray(out["pred"])
        j_star = np.argmin(radii, axis=1)
        w = np.asarray(kernel).T
        delta_w = w[preds] - w[j_star]
        unit = delta_w / np.linalg.norm(delta_w, axis=1, keepdims=True)
        emb_new = np.asarray(emb) - radii[np.arange(b), j_star][:, None] * unit
        logits_adv = emb_new @ np.asarray(kernel) + np.asarray(bias)
        top2 = np.sort(logits_adv, axis=1)[:, -2:]
        assert np.abs(top2[:, 1] - top2[:, 0]).mean() < 1e-4

    def test_radii_against_oracle(self):
        rng = np.random.default_rng(2)
        d, c, b = 4, 3, 16
        emb = rng.normal(size=(b, d)).astype(np.float32)
        kernel = rng.normal(size=(d, c)).astype(np.float32)
        bias = rng.normal(size=(c,)).astype(np.float32)
        out = scoring.boundary_radii(jnp.asarray(emb), jnp.asarray(kernel),
                                     jnp.asarray(bias))
        radii = np.asarray(out["radii"])
        logits = emb @ kernel + bias
        preds = logits.argmax(axis=1)
        for i in range(b):
            for j in range(c):
                if j == preds[i]:
                    assert np.isinf(radii[i, j])
                    continue
                dw = kernel[:, preds[i]] - kernel[:, j]
                db = bias[preds[i]] - bias[j]
                expected = (emb[i] @ dw + db) / np.linalg.norm(dw)
                np.testing.assert_allclose(radii[i, j], expected, rtol=1e-4)

    def test_head_pair_norms_matches_naive(self):
        """The hoisted [C, C] table equals element-wise ||w_c - w_j||,
        including exact zeros on the diagonal (those become the j == c
        +inf radii downstream)."""
        rng = np.random.default_rng(3)
        kernel = rng.normal(size=(8, 5)).astype(np.float32)
        got = np.asarray(scoring.head_pair_norms(jnp.asarray(kernel)))
        w = kernel.T
        naive = np.linalg.norm(w[:, None, :] - w[None, :, :], axis=-1)
        np.testing.assert_allclose(got, naive, rtol=1e-6)
        assert (np.diag(got) == 0.0).all()

    def test_near_duplicate_head_columns_match_float64_oracle(self):
        """Nearly-identical head columns are the catastrophic-cancellation
        case: a Gram-identity denominator would report +inf, and a
        logit-difference numerator would quantize the tiny margins to
        float32 ulp noise.  Both the value AND finiteness must match a
        float64 naive oracle."""
        rng = np.random.default_rng(4)
        d, c = 64, 6
        kernel = rng.normal(size=(d, c)).astype(np.float32) * 10.0
        kernel[:, 1] = kernel[:, 0]
        kernel[0, 1] += 1e-3  # ||w_0 - w_1|| = 1e-3, tiny vs ||w|| ~ 80
        bias = np.zeros(c, dtype=np.float32)
        emb = rng.normal(size=(4, d)).astype(np.float32)
        out = scoring.boundary_radii(jnp.asarray(emb), jnp.asarray(kernel),
                                     jnp.asarray(bias))
        radii = np.asarray(out["radii"])
        k64, e64 = kernel.astype(np.float64), emb.astype(np.float64)
        logits = e64 @ k64
        preds = logits.argmax(axis=1)
        for i in range(4):
            for j in range(c):
                if j == preds[i]:
                    assert np.isinf(radii[i, j])
                    continue
                dw = k64[:, preds[i]] - k64[:, j]
                expected = (e64[i] @ dw) / np.linalg.norm(dw)
                np.testing.assert_allclose(radii[i, j], expected, rtol=1e-3,
                                           err_msg=f"row {i} class {j}")

    def test_query_selects_smallest_margins(self):
        s = make_strategy("MASESampler")
        avail = s.available_query_idxs(shuffle=False)
        min_margins, _, _ = s.compute_margins(avail)
        budget = 6
        got, cost = s.query(budget)
        expected = avail[np.argsort(min_margins, kind="stable")[:budget]]
        np.testing.assert_array_equal(got, expected)


class TestBASE:
    def test_matches_numpy_oracle(self):
        """Re-run the per-class slot-filling (base_sampler.py:22-35) as a
        plain NumPy oracle over the same margins and compare selections."""
        s = make_strategy("BASESampler", n_train=128)
        budget = 10  # 4 classes -> per-class slots 3,3,2,2
        avail = s.available_query_idxs(shuffle=False)
        min_margins, radii, preds = s.compute_margins(avail)

        taken = np.zeros(len(avail), dtype=bool)
        expected = []
        for c in range(s.num_classes):
            quota = budget // s.num_classes + int(c < budget % s.num_classes)
            dist = np.where(preds == c, min_margins, radii[:, c])
            dist = np.where(taken, np.inf, dist)
            picks = np.argsort(dist, kind="stable")[:quota]
            taken[picks] = True
            expected.extend(avail[picks].tolist())

        got, cost = s.query(budget)
        assert cost == budget and np.unique(got).size == budget
        np.testing.assert_array_equal(got, np.asarray(expected))


class TestScoreBatchSize:
    """Acquisition-scoring batch policy (TrainConfig.score_batch_size):
    the reference's test-loader batch (100) starves an accelerator mesh
    at ~12 rows/chip, so auto raises it per chip off-CPU; scores are
    per-example so only throughput can change."""

    def test_auto_keeps_reference_batch_on_cpu(self):
        s = make_strategy("MarginSampler")
        want = s.trainer.padded_batch_size(s.train_cfg.loader_te.batch_size)
        assert s._score_batch_size() == want

    def test_explicit_override_wins(self):
        import dataclasses
        s = make_strategy("MarginSampler")
        s.train_cfg = dataclasses.replace(s.train_cfg, score_batch_size=512)
        assert s._score_batch_size() == s.trainer.padded_batch_size(512)

    def test_accelerator_auto_floor_is_per_chip(self):
        class FakeDev:
            platform = "tpu"

        class FakeMesh:
            class devices:  # noqa: N801 — mimic np.ndarray .flat
                flat = [FakeDev()]

        s = make_strategy("MarginSampler")
        # The auto branch delegates to Trainer.eval_batch_size (one
        # policy for scoring and evaluation), which reads trainer.mesh.
        real_mesh = s.trainer.mesh
        s.trainer.mesh = FakeMesh()
        try:
            # 32px synthetic pool -> the small-row 512/chip floor.
            floor = 512 * s.trainer.n_devices
            assert s._score_batch_size() == \
                s.trainer.padded_batch_size(floor)
        finally:
            s.trainer.mesh = real_mesh
