"""224px learn-smoke: first end-to-end LEARNING signal for the
ImageNet-shape path (VERDICT r5 Missing #2).

Everything before this pinned numerics (s2d equivalence, feed
bit-identity, served==offline) but never that the 224px configuration —
space-to-depth stem, flip-only device augment, resident-gather train
feed — actually LEARNS through the production driver.  This drives
``run_experiment`` itself (no harness shortcuts) over a tiny in-memory
224px facsimile (4 coarse-template classes, low noise — the Bayes
boundary is nearly linear, so a from-scratch ResNet-18 must clear chance
within a handful of updates if and only if the path is wired right) and
asserts above-chance round-1 test accuracy.

Slow-marked (ResNet-18 at 224px costs ~6 s/step on one CPU core);
excluded from tier-1.
"""

import numpy as np
import pytest

from active_learning_tpu.config import (ExperimentConfig, LoaderConfig,
                                        OptimizerConfig, SchedulerConfig,
                                        TrainConfig)
from active_learning_tpu.data.core import (ArrayDataset, IMAGENET_NORM,
                                           ViewSpec)
from active_learning_tpu.data.synthetic import (_class_templates,
                                                _make_images)
from active_learning_tpu.experiment.driver import run_experiment
from active_learning_tpu.utils.metrics import MetricsSink


class CaptureSink(MetricsSink):
    def __init__(self):
        self.metrics = []  # (name, value, step)

    def log_parameters(self, params):
        pass

    def log_metrics(self, metrics, step=None):
        for k, v in metrics.items():
            try:
                self.metrics.append((k, float(v), step))
            except (TypeError, ValueError):
                pass

    def log_asset(self, name, data):
        pass

    def get(self, name, step):
        for k, v, s in self.metrics:
            if k == name and s == step:
                return v
        return None


def _facsimile_224(n_train=240, n_test=64, num_classes=4, seed=11,
                   noise_sigma=12.0):
    """In-memory 224px facsimile with the ImageNet-shape view contract:
    crop-at-source semantics (fixed rows), flip-only augmented train
    view (pad=0 — the s2d path's supported augmentation), deterministic
    al/test views."""
    rng = np.random.default_rng(seed)
    templates = _class_templates(num_classes, 224, rng)
    tr_images, tr_targets = _make_images(n_train, templates, rng,
                                         noise_sigma=noise_sigma)
    te_images, te_targets = _make_images(n_test, templates, rng,
                                         noise_sigma=noise_sigma)
    train_view = ViewSpec(IMAGENET_NORM, augment=True, pad=0)
    val_view = ViewSpec(IMAGENET_NORM, augment=False)
    train_set = ArrayDataset(tr_images, tr_targets, num_classes, train_view)
    al_set = train_set.with_view(val_view)
    test_set = ArrayDataset(te_images, te_targets, num_classes, val_view)
    return train_set, test_set, al_set


@pytest.mark.slow
def test_224px_round1_learns_above_chance(tmp_path):
    # On the CPU mesh the resident feed runs its per-batch execution
    # form (DESIGN.md §2a): no epoch-scan compile, no step-bucket
    # padding — the fit executes exactly the real steps, which is what
    # makes a 224px ResNet smoke tractable on CPU at ~6 s/step.
    data = _facsimile_224()
    train_cfg = TrainConfig(
        eval_split=0.05,
        dtype="float32",  # CPU smoke; production "auto" = bf16 on TPU
        loader_tr=LoaderConfig(batch_size=16),
        loader_te=LoaderConfig(batch_size=32),
        optimizer=OptimizerConfig(name="sgd", lr=0.02, weight_decay=5e-4,
                                  momentum=0.9),
        scheduler=SchedulerConfig(name="cosine", t_max=3,
                                  warmup_epochs=1),
        train_feed="resident",
    )
    cfg = ExperimentConfig(
        dataset="imagenet",  # the ImageNet-shape model/stem path
        strategy="MarginSampler",
        model="SSLResNet18",
        stem="s2d",
        rounds=2,
        round_budget=48,
        init_pool_size=48,
        n_epoch=3,
        early_stop_patience=0,
        enable_metrics=True,
        log_dir=str(tmp_path), ckpt_path=str(tmp_path),
        exp_hash="smoke224",
        compilation_cache_dir="",  # CPU: no persistent-cache interference
        # ONE device: the conftest's virtual 8-device mesh serializes
        # 8 replicas of every 224px op onto the host cores (the
        # parallel/resident.py virtual-CPU-mesh caveat) — this smoke is
        # a LEARNING check; distributed equality is pinned by
        # test_trainer_parallel/test_multihost.
        num_devices=1,
    )
    sink = CaptureSink()
    strategy = run_experiment(cfg, sink=sink, data=data,
                              train_cfg=train_cfg)

    # The configuration under test actually engaged: s2d stem on the
    # 224px model, resident-gather train feed.
    assert getattr(strategy.model, "stem", None) == "s2d"
    assert strategy.trainer.last_feed["source"] == "resident"
    assert len(strategy.trainer.resident_pool["images"]) >= 1

    acc_rd1 = sink.get("rd_test_accuracy", 1)
    assert acc_rd1 is not None
    # 4 classes -> chance 0.25; the facsimile is nearly linearly
    # separable, so a correctly wired path clears chance with margin
    # even at ~18 updates (seeded: deterministic on the CPU mesh).
    assert acc_rd1 > 0.34, (
        f"round-1 test accuracy {acc_rd1:.3f} is not above chance — the "
        "224px s2d + resident-feed path is not learning")
    # Round 1 (twice the labels) must not be WORSE than round 0 beyond
    # small-eval-set noise — a collapsing second round is exactly the
    # degradation a learn-smoke exists to catch.  (Seeded reference run:
    # rd0 20.3%, rd1 51.6%.)
    acc_rd0 = sink.get("rd_test_accuracy", 0)
    assert acc_rd0 is not None
    assert acc_rd1 >= acc_rd0 - 0.10, (
        f"round-1 accuracy {acc_rd1:.3f} collapsed below round-0 "
        f"{acc_rd0:.3f}")
