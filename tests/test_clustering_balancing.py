"""MarginClustering + Balancing sampler tests (8-device CPU mesh)."""

import copy

import jax
import numpy as np

from helpers import make_strategy


def _balancing_oracle(emb, ys, avail, labeled, budget, rng, n_classes):
    """The reference's host-NumPy selection loop, verbatim semantics
    (balancing_sampler.py:59-128): full centroid recompute and a fresh
    O(N x C x D) distance pass per pick."""
    avail = avail.copy()
    labeled = labeled.copy()
    sel = []
    for qc in range(budget):
        ys_l = ys[labeled]
        counts = np.bincount(ys_l, minlength=n_classes)
        maj = counts > counts.mean()
        minor = ~maj
        avg_maj = counts[maj].sum() / max(maj.sum(), 1)
        avg_minor = counts[minor].sum() / max(minor.sum(), 1)
        if budget - qc <= minor.sum() * (avg_maj - avg_minor):
            centers = np.zeros((n_classes, emb.shape[1]), np.float32)
            np.add.at(centers, ys_l, emb[labeled])
            centers = centers / (counts[:, None] + 1e-5)
            rarest = int(np.argmin(counts))
            eu = emb[avail]
            d_rare = ((eu - centers[rarest]) ** 2).sum(1)
            if counts[rarest] == 0:
                d_rare = np.ones_like(d_rare)
            cm = centers[maj]
            d_maj = ((eu ** 2).sum(1, keepdims=True)
                     + (cm ** 2).sum(1)[None, :] - 2.0 * eu @ cm.T)
            score = d_rare / d_maj.max(1)
            q = int(np.flatnonzero(avail)[int(np.argmin(score))])
        else:
            q = int(rng.choice(np.flatnonzero(avail)))
        avail[q] = False
        labeled[q] = True
        sel.append(q)
    return np.asarray(sel, dtype=np.int64)


class TestMarginClustering:
    def test_round_robin_covers_small_clusters_first(self):
        s = make_strategy("MarginClusteringSampler", n_train=128)
        got, cost = s.query(10)
        assert cost == 10 and np.unique(got).size == 10
        assert not s.pool.labeled[got].any()
        assert not np.isin(got, s.pool.eval_idxs).any()
        # Cache carries forward the unqueried assignments.
        n_avail = len(s.available_query_idxs(shuffle=False))
        assert s.cluster_assignment is not None
        assert len(s.cluster_assignment) == n_avail - 10

    def test_cluster_cache_reused_across_rounds(self):
        s = make_strategy("MarginClusteringSampler", n_train=128)
        got, cost = s.query(8)
        s.update(got, cost)
        cached = s.cluster_assignment
        calls = {"n": 0}
        import sklearn.cluster

        orig = sklearn.cluster.AgglomerativeClustering.fit

        def counting_fit(self_, X):
            calls["n"] += 1
            return orig(self_, X)

        sklearn.cluster.AgglomerativeClustering.fit = counting_fit
        try:
            got2, cost2 = s.query(8)
        finally:
            sklearn.cluster.AgglomerativeClustering.fit = orig
        assert calls["n"] == 0  # second round reuses the assignment
        assert cost2 == 8 and not np.isin(got2, got).any()
        assert len(s.cluster_assignment) == len(cached) - 8

    def test_selects_min_margin_within_cluster(self):
        """The first pick must be the min-margin member of the smallest
        cluster (margin_clustering_sampler.py:71-79)."""
        from sklearn.cluster import AgglomerativeClustering
        s = make_strategy("MarginClusteringSampler", n_train=128)
        idxs = s.available_query_idxs(shuffle=False)
        emb, margins = s.get_embeddings_and_margins(idxs)
        labels = AgglomerativeClustering(n_clusters=20).fit(emb).labels_
        ids, counts = np.unique(labels, return_counts=True)
        smallest = sorted(zip(counts.tolist(), ids.tolist()))[0][1]
        members = np.flatnonzero(labels == smallest)
        expected_first = idxs[members[np.argmin(margins[members])]]
        got, _ = s.query(5)
        assert got[0] == expected_first

    def test_subset_reclusters_every_round(self):
        s = make_strategy("MarginClusteringSampler", n_train=128,
                          subset_unlabeled=40)
        got, cost = s.query(6)
        assert cost == 6
        s.update(got, cost)
        got2, cost2 = s.query(6)
        assert cost2 == 6 and not np.isin(got2, got).any()


class TestBalancingSampler:
    def test_balanced_pool_random_path(self):
        """With a balanced labeled set and a large remaining budget the
        condition at balancing_sampler.py:83-84 routes to random picks."""
        s = make_strategy("BalancingSampler", n_train=128, init_pool=0)
        got, cost = s.query(12)
        assert cost == 12 and np.unique(got).size == 12
        assert not np.isin(got, s.pool.eval_idxs).any()

    def test_imbalanced_pool_targets_rare_class(self):
        """Labeled set heavily skewed away from class 0: the balancing
        branch should pull picks toward class 0 (nearest-to-rarest-centroid
        with class-template synthetic data ~= true class).

        seed=7 is pinned as a draw whose class templates are mutually far
        under the untrained random-projection embedding: the heuristic's
        "farthest from majority centroids" rule is geometry-dependent, and
        with the spatially-coarse templates some draws put two classes
        close enough that noise outliers win — exact pick-rule behavior
        (any geometry) is pinned separately by the host-loop oracle test
        below.  (Re-pinned from seed=4: earlier rounds' model/init-chain
        changes shifted the embedding geometry and seed 4 became one of
        the close-template draws — 5 of 12 scanned seeds now pick the
        rare class on every draw, seed 7 among them; the pick rule itself
        is unchanged, as the oracle test proves.)"""
        s = make_strategy("BalancingSampler", n_train=256, init_pool=0,
                          seed=7)
        targets = s.al_set.targets
        avail = s.available_query_mask()
        # Label many examples of classes 1..3, none of class 0.
        skew = np.concatenate([
            np.flatnonzero((targets == c) & avail)[:12]
            for c in range(1, s.num_classes)])
        s.update(skew, len(skew))
        got, cost = s.query(4)
        assert cost == 4
        got_classes = targets[got]
        # Synthetic classes are template-separated, so nearest-to-rarest
        # centroid reliably lands in the rare class.
        assert (got_classes == 0).mean() >= 0.75

    def test_device_loop_matches_host_numpy_oracle(self):
        """The sharded on-device pick loop must select exactly what the
        reference's host loop selects, through BOTH branches (random while
        the remaining budget dwarfs the imbalance, balancing once
        remaining <= minor * (avg_maj - avg_minor))."""
        s = make_strategy("BalancingSampler", n_train=192, init_pool=0)
        targets = s.al_set.targets
        avail = s.available_query_mask()
        skew = np.concatenate([
            np.flatnonzero((targets == c) & avail)[:12]
            for c in range(1, s.num_classes)])
        s.update(skew, len(skew))

        emb = s._all_embeddings()
        expected = _balancing_oracle(
            emb, targets[: len(s.al_set)], s.available_query_mask(),
            s.already_labeled_mask(), 16, copy.deepcopy(s.rng),
            s.num_classes)
        # With counts [0,12,12,12] the threshold is 12, so picks 1-4 are
        # random and picks 5-16 take the balancing branch.
        got, cost = s.query(16)
        assert cost == 16
        np.testing.assert_array_equal(got, expected)

    def test_per_pick_traffic_independent_of_pool_size(self):
        """The scale property of the device-resident design: after the
        one-time pool upload, every pick moves only the O(C*D) centroids
        down and one scalar back — all via EXPLICIT transfers.  Running the
        whole pick loop under transfer_guard_host_to_device('disallow')
        proves no per-pick implicit host->device copy (i.e. nothing
        proportional to the pool) sneaks into the loop."""
        s = make_strategy("BalancingSampler", n_train=256, init_pool=0,
                          freeze_feature=True)
        targets = s.al_set.targets
        avail = s.available_query_mask()
        skew = np.concatenate([
            np.flatnonzero((targets == c) & avail)[:12]
            for c in range(1, s.num_classes)])
        s.update(skew, len(skew))
        s.query(2)  # warm-up: compiles the scoring + pick kernels,
        # caches the frozen-feature embeddings
        with jax.transfer_guard_host_to_device("disallow"):
            got, cost = s.query(8)
        assert cost == 8 and np.unique(got).size == 8

    def test_freeze_feature_caches_embeddings(self):
        s = make_strategy("BalancingSampler", freeze_feature=True)
        calls = {"n": 0}
        orig = s.collect_scores

        def counting(*a, **k):
            calls["n"] += 1
            return orig(*a, **k)

        s.collect_scores = counting
        s.query(4)
        s.query(4)
        assert calls["n"] == 1
