"""VAAL stack tests (8-device CPU mesh): VAE shapes/losses, co-training
dynamics, discriminator-score acquisition."""

import jax
import jax.numpy as jnp
import pytest

# ~200 s of XLA compiles (jitted VAE+discriminator co-step at several
# shapes): the single biggest line in the suite's wall-clock.
pytestmark = pytest.mark.slow
import numpy as np

from active_learning_tpu.models.vaal import (VAE, Discriminator,
                                             crop_size_for, random_crop)

from helpers import make_strategy


def make_vaal_strategy(**kw):
    # image_size=16 keeps the VAE valid (4 stride-2 convs need crop % 16
    # == 0) and the test fast.
    kw.setdefault("n_train", 96)
    kw.setdefault("image_size", 16)
    return make_strategy("VAALSampler", **kw)


class TestVAEModel:
    def test_shapes_roundtrip(self):
        for crop in (16, 32):
            vae = VAE(z_dim=8, crop=crop)
            x = jnp.zeros((4, crop, crop, 3))
            variables = vae.init(jax.random.PRNGKey(0), x, train=False)
            (recon, z, mu, logvar), _ = vae.apply(
                variables, x, jax.random.PRNGKey(1), train=True,
                mutable=["batch_stats"])
            assert recon.shape == x.shape
            assert z.shape == mu.shape == logvar.shape == (4, 8)

    def test_reparameterize_none_key_returns_mu(self):
        vae = VAE(z_dim=8, crop=16)
        x = jnp.ones((2, 16, 16, 3))
        variables = vae.init(jax.random.PRNGKey(0), x, train=False)
        _, z, mu, _ = vae.apply(variables, x, None, train=False)
        np.testing.assert_array_equal(np.asarray(z), np.asarray(mu))

    def test_discriminator_outputs_probabilities(self):
        disc = Discriminator(z_dim=8)
        z = jnp.asarray(np.random.default_rng(0).normal(size=(6, 8)),
                        dtype=jnp.float32)
        params = disc.init(jax.random.PRNGKey(0), z)
        p = np.asarray(disc.apply(params, z))
        assert p.shape == (6, 1)
        assert (p > 0).all() and (p < 1).all()

    def test_crop_rules(self):
        assert crop_size_for(224) == 64
        assert crop_size_for(64) == 64
        assert crop_size_for(32) == 32
        x = jnp.arange(2 * 8 * 8 * 3, dtype=jnp.float32).reshape(2, 8, 8, 3)
        # Small inputs pass through whole.
        np.testing.assert_array_equal(
            np.asarray(random_crop(x, 16, jax.random.PRNGKey(0))),
            np.asarray(x))
        # Large inputs: one shared window, correct size.
        big = jnp.arange(2 * 12 * 12 * 3, dtype=jnp.float32
                         ).reshape(2, 12, 12, 3)
        out = np.asarray(random_crop(big, 8, jax.random.PRNGKey(0)))
        assert out.shape == (2, 8, 8, 3)


class TestVAALTraining:
    def test_cotrain_updates_all_three_models(self):
        s = make_vaal_strategy(n_epoch=1)
        before_cls = jax.tree.map(np.asarray, s.state.params)
        before_vae = jax.tree.map(np.asarray, s.vaal_state.vae_params)
        before_d = jax.tree.map(np.asarray, s.vaal_state.d_params)
        s.train()

        def changed(a, b):
            return any(not np.allclose(x, y) for x, y in zip(
                jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)))

        assert changed(before_cls, jax.tree.map(np.asarray, s.state.params))
        assert changed(before_vae,
                       jax.tree.map(np.asarray, s.vaal_state.vae_params))
        assert changed(before_d,
                       jax.tree.map(np.asarray, s.vaal_state.d_params))
        # Everything stayed finite through the 3-step updates.
        for leaf in jax.tree_util.tree_leaves(s.vaal_state.vae_params):
            assert np.isfinite(np.asarray(leaf)).all()

    def test_query_returns_lowest_discriminator_scores(self):
        s = make_vaal_strategy(n_epoch=1)
        s.train()
        idxs = s.available_query_idxs(shuffle=False)
        variables = {"vae_params": s.vaal_state.vae_params,
                     "vae_stats": s.vaal_state.vae_stats,
                     "d_params": s.vaal_state.d_params}
        from active_learning_tpu.strategies import scoring
        out = scoring.collect_pool(
            s.al_set, idxs, s._score_batch_size(), s._score_step,
            variables, s.mesh)
        expected = idxs[np.argsort(out["d_score"], kind="stable")[:6]]
        got, cost = s.query(6)
        assert cost == 6
        np.testing.assert_array_equal(got, expected)
        assert not s.pool.labeled[got].any()

    def test_round_reinit_resets_vaal_state(self):
        s = make_vaal_strategy()
        first = jax.tree.map(np.asarray, s.vaal_state.vae_params)
        s.init_network_weights()
        second = jax.tree.map(np.asarray, s.vaal_state.vae_params)
        leaves1 = jax.tree_util.tree_leaves(first)
        leaves2 = jax.tree_util.tree_leaves(second)
        assert any(not np.allclose(a, b)
                   for a, b in zip(leaves1, leaves2))

    def test_e2e_two_rounds(self):
        s = make_vaal_strategy(n_epoch=1)
        s.train()
        got, cost = s.query(8)
        s.update(got, cost)
        assert s.pool.num_labeled == 8 + 8
        s.init_network_weights()
        s.train()
        got2, cost2 = s.query(8)
        assert not np.isin(got2, got).any()


class TestVAALResume:
    def test_round_resume_restores_adversary(self, tmp_path):
        """Round-level resume must bring back the trained
        VAE/discriminator (VERDICT r3 #7): the reference kept it for free
        by pickling the whole strategy (resume_training.py:38-52); here
        the explicit aux-state seam carries it, and a resumed experiment
        must produce IDENTICAL discriminator scores to the interrupted
        one."""
        from active_learning_tpu.experiment import resume as resume_lib
        from active_learning_tpu.strategies import scoring

        s = make_vaal_strategy(n_epoch=1, ckpt_path=str(tmp_path))
        s.train()
        resume_lib.save_experiment(s, s.cfg)

        def d_scores(strategy, idxs):
            variables = {"vae_params": strategy.vaal_state.vae_params,
                         "vae_stats": strategy.vaal_state.vae_stats,
                         "d_params": strategy.vaal_state.d_params}
            out = scoring.collect_pool(
                strategy.al_set, idxs, strategy._score_batch_size(),
                strategy._score_step, variables, strategy.mesh)
            return np.asarray(out["d_score"])

        idxs = s.available_query_idxs(shuffle=False)
        want = d_scores(s, idxs)

        # Fresh build = new process; its randomly-initialized adversary
        # must NOT score like the trained one (the test must bite) ...
        s2 = make_vaal_strategy(n_epoch=1, ckpt_path=str(tmp_path))
        assert not np.allclose(d_scores(s2, idxs), want)

        # ... and after load_experiment it must match bit for bit.
        resume_lib.load_experiment(s2, s2.cfg)
        for a, b in zip(jax.tree_util.tree_leaves(s.vaal_state),
                        jax.tree_util.tree_leaves(s2.vaal_state)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_array_equal(d_scores(s2, idxs), want)

    def test_save_without_aux_state_leaves_no_file(self, tmp_path):
        """Non-VAAL samplers persist no aux blob, and a stale one from an
        earlier sampler is removed rather than resurrected."""
        import os

        from active_learning_tpu.experiment import resume as resume_lib

        s = make_strategy("RandomSampler", ckpt_path=str(tmp_path))
        d = resume_lib.save_experiment(s, s.cfg)
        assert not os.path.exists(os.path.join(d, resume_lib.AUX_FILE))
        # Plant a stale blob; the next save must delete it.
        with open(os.path.join(d, resume_lib.AUX_FILE), "wb") as fh:
            fh.write(b"stale")
        resume_lib.save_experiment(s, s.cfg)
        assert not os.path.exists(os.path.join(d, resume_lib.AUX_FILE))
