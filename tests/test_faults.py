"""Chaos tests for the failure model (DESIGN.md §10): the deterministic
fault-injection registry, the one RetryPolicy, the atomic round journal,
the degradation ladder, driver preemption — and the acceptance pins:

  * the CHAOS MATRIX: with a fault armed at every registered site (one
    at a time — raise, torn-write, thread-death), a 2-round CPU-mesh
    experiment either completes or resumes to experiment_state
    BIT-IDENTICAL to the fault-free run, the fault verifiably FIRED,
    and zero threads are orphaned;
  * real-SIGTERM subprocess kill mid-pipelined-round -> --resume_training
    reproduces the uninterrupted run's picks bit-exactly;
  * disarmed fault sites add no measurable hot-path overhead (pinned
    like the telemetry-off <50µs/step bound).
"""

from __future__ import annotations

import dataclasses
import glob
import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from helpers import TinyClassifier, tiny_train_config

from active_learning_tpu import faults
from active_learning_tpu.config import ExperimentConfig, TelemetryConfig
from active_learning_tpu.data.synthetic import get_data_synthetic
from active_learning_tpu.experiment.driver import run_experiment
from active_learning_tpu.faults import journal as journal_lib
from active_learning_tpu.faults import ladder as ladder_lib
from active_learning_tpu.faults import preempt as preempt_lib
from active_learning_tpu.faults.registry import _SiteState
from active_learning_tpu.telemetry import heartbeat as hb_lib
from active_learning_tpu.telemetry import status as status_lib
from active_learning_tpu.utils.metrics import NullSink

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _disarmed():
    """Every test starts and ends disarmed (and with no recorded
    preemption) — an armed registry leaking across tests would make
    unrelated failures look like chaos."""
    faults.configure(None)
    preempt_lib.reset()
    yield
    faults.configure(None)
    preempt_lib.reset()


# ---------------------------------------------------------------------------
# Spec grammar
# ---------------------------------------------------------------------------

class TestSpecGrammar:
    def test_full_spec_parses(self):
        parsed = faults.parse_spec(
            "h2d_upload:raise@3,ckpt_write:torn@1,spec_scorer:die@0.5,"
            "dispatch:delay@0.05,feed_worker:oom")
        assert parsed == {
            "h2d_upload": ("raise", 3),
            "ckpt_write": ("torn", 1),
            "spec_scorer": ("die", 0.5),
            "dispatch": ("delay", 0.05),
            "feed_worker": ("oom", None),
        }

    @pytest.mark.parametrize("bad,msg", [
        ("bogus_site:raise", "unknown site"),
        ("h2d_upload:explode", "not one of"),
        ("h2d_upload:raise@zero", "neither an int"),
        ("h2d_upload:raise@0", "probability"),        # Nth-hit is 1-based
        ("h2d_upload:raise@1.5", "probability"),      # probs live in (0,1)
        ("h2d_upload", "expected site:action"),
        ("h2d_upload:raise,h2d_upload:die", "twice"),
    ])
    def test_malformed_specs_fail_fast(self, bad, msg):
        with pytest.raises(ValueError, match=msg):
            faults.parse_spec(bad)

    def test_every_registered_site_has_a_wired_home(self):
        # The registry is CLOSED and fully wired — enforced statically
        # by trace_lint check 8; this pins the registry contents so a
        # rename shows up here too.
        assert faults.SITES == ("h2d_upload", "ckpt_write", "spec_scorer",
                                "feed_worker", "shard_upload", "dispatch",
                                "grad_probe", "wal_write", "stream_drain",
                                "page_read", "fleet_journal")


# ---------------------------------------------------------------------------
# Registry semantics
# ---------------------------------------------------------------------------

class TestRegistry:
    def test_disarmed_site_is_a_noop(self):
        faults.site("h2d_upload")                    # nothing raises
        assert faults.fault_counters() == {}
        assert faults.active_spec() is None

    def test_nth_hit_fires_exactly_once(self):
        faults.configure("h2d_upload:raise@3")
        faults.site("h2d_upload")
        faults.site("h2d_upload")
        with pytest.raises(faults.InjectedFault) as exc:
            faults.site("h2d_upload")
        assert exc.value.site == "h2d_upload"
        for _ in range(5):                           # never again
            faults.site("h2d_upload")
        c = faults.fault_counters()["h2d_upload"]
        assert c == {"hits": 8, "fires": 1}

    def test_oom_carries_the_resource_exhausted_marker(self):
        faults.configure("feed_worker:oom@1")
        with pytest.raises(faults.InjectedOOM) as exc:
            faults.site("feed_worker")
        assert "RESOURCE_EXHAUSTED" in str(exc.value)

    def test_die_is_a_base_exception(self):
        faults.configure("spec_scorer:die@1")
        with pytest.raises(faults.ThreadDeath):
            try:
                faults.site("spec_scorer")
            except Exception:  # noqa: BLE001 - the point: this MUST NOT catch
                pytest.fail("ThreadDeath was caught by `except Exception`")

    def test_torn_fires_only_at_the_torn_point(self):
        faults.configure("ckpt_write:torn@1")
        faults.site("ckpt_write")                    # enter: no fire
        faults.site("ckpt_write")
        with pytest.raises(faults.InjectedFault):
            faults.site("ckpt_write", point="torn")
        # ... and enter-actions never fire at the torn point.
        faults.configure("ckpt_write:raise@1")
        faults.site("ckpt_write", point="torn")      # no fire

    def test_probability_is_seed_replayable(self):
        def pattern(seed):
            st = _SiteState("spec_scorer", "die", 0.5, seed)
            fired = []
            for _ in range(64):
                try:
                    st.hit("enter")
                    fired.append(False)
                except faults.ThreadDeath:
                    fired.append(True)
            return fired

        assert pattern(7) == pattern(7)              # replayable
        assert any(pattern(7)) and not all(pattern(7))

    def test_unarmed_sites_stay_silent_beside_armed_ones(self):
        faults.configure("dispatch:delay@0.0")
        faults.site("h2d_upload")                    # armed spec, other site
        assert faults.fault_counters()["dispatch"]["hits"] == 0


# ---------------------------------------------------------------------------
# RetryPolicy + classification
# ---------------------------------------------------------------------------

class TestRetryPolicy:
    def test_classification_table(self):
        cls = faults.classify_exception
        assert cls(faults.InjectedOOM("x")) == faults.OOM
        assert cls(RuntimeError("RESOURCE_EXHAUSTED: out of memory")) \
            == faults.OOM
        assert cls(faults.InjectedFault("x")) == faults.TRANSIENT
        assert cls(faults.ThreadDeath("x")) == faults.TRANSIENT
        assert cls(OSError("disk full")) == faults.TRANSIENT
        assert cls(ValueError("a bug")) == faults.FATAL

    def test_transient_retries_then_succeeds(self):
        calls = []
        before = faults.retry_counters()["total"]

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("transient")
            return "ok"

        policy = faults.RetryPolicy(site="t1", max_attempts=5,
                                    base_delay_s=0.001,
                                    classify=faults.classify_exception)
        assert policy.call(flaky) == "ok"
        assert len(calls) == 3
        counters = faults.retry_counters()
        assert counters["total"] - before == 2
        assert counters["last_site"] == "t1"
        assert counters["by_site"]["t1"] >= 2

    def test_fatal_and_oom_never_retry(self):
        for exc in (ValueError("bug"), faults.InjectedOOM("h2d_upload")):
            calls = []

            def once(exc=exc):
                calls.append(1)
                raise exc

            policy = faults.RetryPolicy(site="t2", max_attempts=5,
                                        base_delay_s=0.001,
                                        classify=faults.classify_exception)
            with pytest.raises(type(exc)):
                policy.call(once)
            assert len(calls) == 1

    def test_attempt_budget_reraises_the_last_failure(self):
        policy = faults.RetryPolicy(site="t3", max_attempts=3,
                                    base_delay_s=0.001,
                                    classify=faults.classify_exception)
        calls = []

        def always():
            calls.append(1)
            raise OSError(f"attempt {len(calls)}")

        with pytest.raises(OSError, match="attempt 3"):
            policy.call(always)
        assert len(calls) == 3

    def test_wall_budget_bounds_the_retry_loop(self):
        policy = faults.RetryPolicy(site="t4", max_attempts=10 ** 6,
                                    base_delay_s=0.02, max_delay_s=0.02,
                                    wall_budget_s=0.1,
                                    classify=faults.classify_exception)
        def always():
            raise OSError("x")

        t0 = time.monotonic()
        with pytest.raises(OSError):
            policy.call(always)
        assert time.monotonic() - t0 < 5.0

    def test_classify_is_required(self):
        with pytest.raises(ValueError, match="classify is required"):
            faults.RetryPolicy(site="t5", classify=None)


# ---------------------------------------------------------------------------
# Round journal
# ---------------------------------------------------------------------------

class TestRoundJournal:
    def test_merge_write_and_read(self, tmp_path):
        path = str(tmp_path / "round_journal.json")
        j = journal_lib.RoundJournal(path)
        j.write(status="running", round=0, phase="train", degrade=[])
        j.write(phase="test")                        # merges over retained
        got = journal_lib.read_journal(path)
        assert got["status"] == "running" and got["round"] == 0
        assert got["phase"] == "test"
        assert got["seq"] == 2 and got["ts"] > 0

    def test_none_deletes_a_field(self, tmp_path):
        path = str(tmp_path / "round_journal.json")
        j = journal_lib.RoundJournal(path)
        j.write(stalled_s=12.0, status="stalled")
        j.write(stalled_s=None, status="running")
        got = journal_lib.read_journal(path)
        assert "stalled_s" not in got and got["status"] == "running"

    def test_seq_continues_across_instances(self, tmp_path):
        path = str(tmp_path / "round_journal.json")
        journal_lib.RoundJournal(path).write(round=0)
        j2 = journal_lib.RoundJournal(path)
        payload = j2.write(round=1)
        assert payload["seq"] == 2                   # monotonic across restarts

    def test_disabled_writes_nothing(self, tmp_path):
        path = str(tmp_path / "round_journal.json")
        assert journal_lib.RoundJournal(path, enabled=False).write(x=1) is None
        assert not os.path.exists(path)

    def test_unparseable_reads_as_none(self, tmp_path):
        path = str(tmp_path / "round_journal.json")
        path2 = str(tmp_path / "garbage.json")
        open(path2, "w").write("{not json")
        assert journal_lib.read_journal(path) is None      # missing
        assert journal_lib.read_journal(path2) is None     # torn/garbage

    def test_no_tmp_residue(self, tmp_path):
        path = str(tmp_path / "round_journal.json")
        journal_lib.RoundJournal(path).write(round=0)
        assert os.listdir(tmp_path) == ["round_journal.json"]


# ---------------------------------------------------------------------------
# Preemption plumbing
# ---------------------------------------------------------------------------

class TestPreempt:
    def test_record_check_reset(self):
        preempt_lib.reset()
        assert preempt_lib.requested() is None
        preempt_lib.check()                          # no-op when clear
        preempt_lib._handler(signal.SIGTERM, None)
        assert preempt_lib.requested() == signal.SIGTERM
        with pytest.raises(preempt_lib.PreemptionRequested) as exc:
            preempt_lib.check()
        assert exc.value.signum == signal.SIGTERM
        assert "SIGTERM" in str(exc.value)
        preempt_lib.reset()
        preempt_lib.check()

    def test_install_restores_previous_handlers(self):
        before = signal.getsignal(signal.SIGTERM)
        previous = preempt_lib.install()
        assert signal.getsignal(signal.SIGTERM) is preempt_lib._handler
        preempt_lib.uninstall(previous)
        assert signal.getsignal(signal.SIGTERM) is before

    def test_install_off_main_thread_is_refused(self):
        out = {}
        t = threading.Thread(target=lambda: out.update(
            r=preempt_lib.install()))
        t.start()
        t.join()
        assert out["r"] is None


# ---------------------------------------------------------------------------
# Degradation ladder
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def _module_strategy():
    from helpers import make_strategy
    return make_strategy("MarginSampler", n_train=64, init_pool=8)


class TestDegradationLadder:
    @pytest.fixture
    def ladder(self, _module_strategy):
        lad = ladder_lib.DegradationLadder(_module_strategy)
        yield lad
        lad.relax()

    def test_generic_failures_walk_the_rungs_in_order(self, ladder,
                                                      _module_strategy):
        strategy = _module_strategy
        pipe_before = strategy.pipeline
        budget_before = strategy.trainer.resident_budget
        assert ladder.escalate(RuntimeError("x"), 0) == "pipeline_off"
        assert strategy.pipeline is None
        assert ladder.escalate(RuntimeError("x"), 0) == "pool_replicated"
        assert strategy.trainer.pool_sharding == "replicated"
        assert ladder.escalate(RuntimeError("x"), 0) == "feed_host"
        assert strategy.trainer.resident_budget == 0
        # batch_half is reserved for OOM: the generic walk ends here.
        assert ladder.escalate(RuntimeError("x"), 0) is None
        assert ladder.events == 3
        # relax reverts everything at the round boundary.
        assert set(ladder.relax(1)) == {"pipeline_off", "pool_replicated",
                                        "feed_host"}
        assert ladder.active == []
        assert strategy.pipeline is pipe_before
        assert strategy.trainer.resident_budget == budget_before

    def test_oom_jumps_to_batch_half_and_reverts(self, ladder,
                                                 _module_strategy):
        strategy = _module_strategy
        bs = strategy.train_cfg.loader_tr.batch_size
        assert ladder.escalate(faults.InjectedOOM("h2d_upload"), 0) \
            == "batch_half"
        assert strategy.trainer.cfg.loader_tr.batch_size == bs // 2
        ladder.relax(1)
        assert strategy.trainer.cfg.loader_tr.batch_size == bs

    def test_oom_at_the_batch_floor_falls_through_to_hbm_rungs(
            self, ladder, _module_strategy):
        """An OOM with the batch already at the device floor must not
        dead-end the ladder: the HBM-freeing rungs (feed_host, then
        pipeline_off — never pool_replicated, which costs MORE per
        chip) still get their shot before the run crashes."""
        strategy = _module_strategy
        floor = strategy.trainer.n_devices
        saved = strategy.trainer.cfg
        try:
            strategy.trainer.cfg = dataclasses.replace(
                saved, loader_tr=dataclasses.replace(
                    saved.loader_tr, batch_size=floor))
            oom = faults.InjectedOOM("h2d_upload")
            assert ladder.escalate(oom, 0) == "feed_host"
            assert ladder.escalate(oom, 0) == "pipeline_off"
            assert ladder.escalate(oom, 0) is None  # exhausted, no repl.
            assert "pool_replicated" not in ladder.active
        finally:
            strategy.trainer.cfg = saved

    def test_site_provenance_picks_the_matching_rung(self, ladder):
        exc = faults.InjectedFault("feed_worker")
        assert ladder.escalate(exc, 0) == "feed_host"
        ladder.relax(1)
        exc = faults.InjectedFault("shard_upload")
        assert ladder.escalate(exc, 0) == "pool_replicated"

    def test_traceback_provenance_routes_real_failures(self, ladder):
        """A REAL failure (no injected .site) is attributed by its
        deepest in-subsystem traceback frame: a crash inside
        parallel/mesh must reach pool_replicated first, not waste a
        round attempt on pipeline_off."""
        from active_learning_tpu.parallel import mesh as mesh_lib
        try:
            mesh_lib.shard_rows(None, None)     # raises inside mesh.py
        except Exception as exc:
            assert ladder_lib._provenance_rung(exc) == "pool_replicated"
            assert ladder.escalate(exc, 0) == "pool_replicated"

    def test_feed_host_rung_survives_the_auto_budget_refresh(
            self, ladder, _module_strategy):
        """The feed_host rung must actually run degraded: with the
        default AUTO budget, the retried attempt's round-start refresh
        must not quietly re-admit the resident path; relax unpins."""
        trainer = _module_strategy.trainer
        assert trainer.cfg.resident_scoring_bytes is None  # auto mode
        assert ladder.escalate(faults.InjectedFault("feed_worker"), 0) \
            == "feed_host"
        assert trainer.refresh_resident_budget() == 0
        ladder.relax(1)
        assert trainer.refresh_resident_budget() > 0

    def test_stall_request_raises_at_the_safe_point(self, ladder):
        ladder.check_stall()                         # clear: no-op
        ladder.request_stall()
        with pytest.raises(ladder_lib.DegradeRequested):
            ladder.check_stall()
        ladder.check_stall()                         # consumed

    def test_max_attempts_covers_every_rung(self, ladder):
        assert ladder.max_attempts() == len(ladder_lib.RUNGS) + 1


# ---------------------------------------------------------------------------
# Torn-write semantics at the checkpoint layer
# ---------------------------------------------------------------------------

class TestTornWrites:
    def test_torn_publish_leaves_a_readable_pair_after_retry(self,
                                                             tmp_path):
        from active_learning_tpu.train import checkpoint as ckpt_lib

        path = str(tmp_path / "best_rd_0.msgpack")
        variables = {"params": {"w": np.ones((2, 2), np.float32)}}
        faults.configure("ckpt_write:torn@1")
        with pytest.raises(faults.InjectedFault):
            ckpt_lib.publish_best(path, variables, round_idx=0, epoch=3)
        # Weights landed, tag did not: the reader sees the legacy
        # untagged form (absorbed by the watcher's rules), never a torn
        # JSON.
        assert os.path.exists(path)
        assert ckpt_lib.read_best_tag(path) is None
        # The retried publish (what _CKPT_RETRY does) lands the pair.
        ckpt_lib.publish_best(path, variables, round_idx=0, epoch=3)
        assert ckpt_lib.read_best_tag(path) == (0, 3)

    def test_torn_save_experiment_reads_as_nothing_to_resume(
            self, tmp_path, _module_strategy):
        from active_learning_tpu.experiment import resume as resume_lib

        strategy = _module_strategy
        cfg = dataclasses.replace(
            strategy.cfg, ckpt_path=str(tmp_path), exp_hash="torn")
        faults.configure("ckpt_write:torn@1")
        with pytest.raises(faults.InjectedFault):
            resume_lib.save_experiment(strategy, cfg)
        # State npz written, meta json not: meta-last ordering means the
        # torn pair reads as NO saved experiment.
        assert not resume_lib.has_saved_experiment(cfg)
        faults.configure(None)
        resume_lib.save_experiment(strategy, cfg)
        assert resume_lib.has_saved_experiment(cfg)


# ---------------------------------------------------------------------------
# Disarmed overhead (the hot-path bound)
# ---------------------------------------------------------------------------

class TestDisarmedOverhead:
    def test_disarmed_site_cost_is_negligible(self):
        """Disarmed = one module-global read + identity compare.  Pinned
        like the telemetry-off <50µs/step bound: 100k calls in well
        under a second even on a loaded CI box (~2.5µs/call allowed;
        the real cost is ~50ns)."""
        n = 100_000
        site = faults.site
        t0 = time.perf_counter()
        for _ in range(n):
            site("dispatch")
        elapsed = time.perf_counter() - t0
        assert elapsed < 0.25, f"{elapsed / n * 1e6:.2f}µs per disarmed site"


# ---------------------------------------------------------------------------
# The chaos matrix (e2e, the acceptance pin)
# ---------------------------------------------------------------------------

N_EPOCH = 3


def _e2e_cfg(tag: str, root: str, *, resume: bool = False,
             n_epoch: int = N_EPOCH, fault_spec=None) -> ExperimentConfig:
    return ExperimentConfig(
        dataset="synthetic", arg_pool="synthetic", strategy="MarginSampler",
        rounds=2, round_budget=8, n_epoch=n_epoch,
        early_stop_patience=n_epoch, run_seed=7, exp_hash=tag,
        exp_name="faults", ckpt_path=os.path.join(root, "ckpt"),
        log_dir=os.path.join(root, "logs"), round_pipeline="speculative",
        resume_training=resume, fault_spec=fault_spec,
        telemetry=TelemetryConfig(enabled=True, heartbeat_every_s=0.0))


def _run_e2e(cfg: ExperimentConfig, data, host_feed: bool = False,
             real_sink: bool = False):
    train_cfg = tiny_train_config()
    if host_feed:
        # Force the host-streamed feed hierarchy: device_prefetch (the
        # feed_worker site) only runs when the pool is NOT resident.
        train_cfg = dataclasses.replace(train_cfg, resident_scoring_bytes=0)
    run_experiment(cfg, sink=None if real_sink else NullSink(), data=data,
                   train_cfg=train_cfg, model=TinyClassifier(num_classes=4))
    state_path = glob.glob(os.path.join(
        cfg.ckpt_path, "*", "experiment_state.npz"))[0]
    return dict(np.load(state_path))


def _metric_max(log_dir: str, name: str):
    """Largest value of ``name`` in the run's metrics.jsonl (None when
    never emitted)."""
    best = None
    path = os.path.join(log_dir, "metrics.jsonl")
    if not os.path.exists(path):
        return None
    with open(path) as fh:
        for line in fh:
            ev = json.loads(line)
            if ev.get("kind") == "metric" and name in ev.get("metrics", {}):
                v = ev["metrics"][name]
                best = v if best is None else max(best, v)
    return best


@pytest.fixture(scope="module")
def chaos_data():
    return get_data_synthetic(n_train=96, n_test=32, num_classes=4,
                              image_size=8, seed=5)


@pytest.fixture(scope="module")
def baseline(chaos_data, tmp_path_factory):
    """The fault-free reference run every chaos scenario must reproduce
    bit for bit."""
    root = str(tmp_path_factory.mktemp("chaos_base"))
    return _run_e2e(_e2e_cfg("base", root), chaos_data)


@pytest.fixture(scope="module")
def baseline_host_feed(chaos_data, tmp_path_factory):
    """Fault-free reference over the host-streamed feed (the
    feed_worker scenarios run there; same-config comparison isolates
    the recovery claim from the PR 5 feed-equality contract)."""
    root = str(tmp_path_factory.mktemp("chaos_base_host"))
    return _run_e2e(_e2e_cfg("basehost", root), chaos_data,
                    host_feed=True)


# (spec, host_feed, signal): the matrix covers every registered site,
# each action class at least once — raise (injected transient),
# torn-write (both torn points), thread-death (scorer AND feeder
# threads), plus a driver-thread failure deep enough to need the
# round-attempt rollback (dispatch).  ``signal`` is how the recovery
# must surface in the driver's OWN metrics stream: "retry" = a
# site-level RetryPolicy absorbed it (fault_retries_total grew),
# "heal" = retry OR a degradation-ladder round attempt (degrade_events),
# None = the recovery is invisible to both counters by design (a failed
# speculative chunk just costs a sequential recompute).
CHAOS = [
    ("h2d_upload:raise@1", False, "retry"),
    ("h2d_upload:die@1", False, "retry"),     # ThreadDeath on the driver path
    ("shard_upload:raise@2", False, "retry"), # per-shard torn point
    ("ckpt_write:raise@2", False, "retry"),
    ("ckpt_write:torn@1", False, "retry"),    # publish_best's torn pair
    ("ckpt_write:torn@3", False, "retry"),
    ("spec_scorer:raise@1", False, None),     # chunk fails -> sequential
    ("spec_scorer:die@1", False, None),       # thread death harness
    ("feed_worker:raise@1", True, "heal"),    # score retry or ladder round
    ("feed_worker:die@1", True, "heal"),      # dead feeder thread
    # Which consumer takes the Nth gate entry is thread-timing-
    # dependent (trainer -> ladder round, collect_pool -> score retry,
    # scorer chunk -> silent sequential fallback), so the dispatch
    # scenario pins only the recovery, not which counter it rode.
    ("dispatch:raise@5", False, None),
]


class TestChaosMatrix:
    @pytest.mark.parametrize("spec,host_feed,signal", CHAOS,
                             ids=[c[0] for c in CHAOS])
    def test_run_completes_or_resumes_bit_identical(
            self, spec, host_feed, signal, chaos_data, baseline,
            baseline_host_feed, tmp_path):
        reference = baseline_host_feed if host_feed else baseline
        threads_before = set(threading.enumerate())
        retries_before = faults.retry_counters()["total"]
        tag = spec.replace(":", "_").replace("@", "_").replace(".", "p")
        cfg = _e2e_cfg(tag, str(tmp_path))

        faults.configure(spec, seed=cfg.run_seed)
        try:
            try:
                state = _run_e2e(cfg, chaos_data, host_feed=host_feed,
                                 real_sink=True)
                mode = "completed"
            except (Exception, faults.ThreadDeath):
                # The armed run crashed (ladder exhausted or the fault
                # outran every guard): resume fault-free — the durable
                # state must carry the round.
                fired = faults.fault_counters()[spec.split(":")[0]]["fires"]
                assert fired >= 1
                faults.configure(None)
                state = _run_e2e(
                    _e2e_cfg(tag, str(tmp_path), resume=True), chaos_data,
                    host_feed=host_feed, real_sink=True)
                mode = "resumed"
            if mode == "completed":
                fired = faults.fault_counters()[spec.split(":")[0]]["fires"]
                assert fired >= 1, (
                    f"{spec}: site never fired — the scenario is vacuous")
        finally:
            faults.configure(None)

        # The recovery claim: bit-identical experiment_state.
        assert set(state) == set(reference)
        for k in reference:
            assert np.array_equal(reference[k], state[k]), (
                f"{spec} ({mode}): experiment_state[{k!r}] diverged")

        # The recovery surfaces in the driver's own telemetry stream
        # (what bench rides on the al_round phases).  fault_retries_
        # total is emitted PER RUN (the driver subtracts its run-start
        # baseline from the process counter), so >= 1 means a retry
        # happened HERE — and the process counter must agree.
        retried = (_metric_max(cfg.log_dir, "fault_retries_total")
                   or 0) >= 1
        degraded = (_metric_max(cfg.log_dir, "degrade_events") or 0) >= 1
        if retried:
            assert faults.retry_counters()["total"] > retries_before
        if signal == "retry":
            assert retried, f"{spec}: recovered without a recorded retry"
        elif signal == "heal":
            assert retried or degraded, (
                f"{spec}: recovered with neither a retry nor a ladder "
                "escalation on record")

        # The journal records a clean finish.
        jr = journal_lib.read_journal(
            os.path.join(cfg.log_dir, faults.JOURNAL_FILE))
        assert jr and jr["status"] == "finished"

        # Zero orphaned threads (grace for daemon joins in flight).
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            extra = set(threading.enumerate()) - threads_before
            if not extra:
                break
            time.sleep(0.05)
        assert not extra, f"{spec}: orphaned threads {extra}"

    def test_driver_arms_from_config_and_env_then_disarms(
            self, chaos_data, tmp_path, monkeypatch):
        """--fault_spec and $AL_FAULT_SPEC both reach the registry via
        the driver (the CLI plumbs --fault_spec into the config), the
        injected fault observably fires (per-run retry metric), and the
        driver disarms ITS OWN arming on exit — a spec must never leak
        into the next in-process run (bench phases, pytest)."""
        cfg = _e2e_cfg("armcfg", str(tmp_path / "a"),
                       fault_spec="ckpt_write:raise@2")
        _run_e2e(cfg, chaos_data, real_sink=True)
        assert (_metric_max(cfg.log_dir, "fault_retries_total") or 0) >= 1
        assert faults.active_spec() is None  # disarmed at run exit
        log = glob.glob(os.path.join(cfg.log_dir, "*.log"))[0]
        assert "fault injection ARMED: ckpt_write:raise@2" in \
            open(log).read()

        monkeypatch.setenv("AL_FAULT_SPEC", "ckpt_write:raise@2")
        cfg2 = _e2e_cfg("armenv", str(tmp_path / "b"))
        _run_e2e(cfg2, chaos_data, real_sink=True)
        assert (_metric_max(cfg2.log_dir, "fault_retries_total") or 0) >= 1
        assert faults.active_spec() is None

    def test_cli_plumbs_fault_flags(self):
        from active_learning_tpu.experiment.cli import (args_to_config,
                                                        get_parser)
        args = get_parser().parse_args(
            ["--dataset", "synthetic", "--strategy", "MarginSampler",
             "--fault_spec", "h2d_upload:raise@3",
             "--watchdog_action", "degrade"])
        cfg = args_to_config(args)
        assert cfg.fault_spec == "h2d_upload:raise@3"
        assert cfg.telemetry.watchdog_action == "degrade"


class TestGradPathFaults:
    """ISSUE 10's fault-site coverage: the fused optimizer update and
    the int8 gradient sync are reachable from the PR 8 ladder."""

    @pytest.mark.parametrize("action", ["raise", "die"])
    def test_grad_probe_failure_degrades_int8_to_f32_loudly(
            self, chaos_data, baseline, tmp_path, action):
        """--grad_allreduce int8 with a broken learning probe (injected
        grad_probe fault): the run must complete on the bit-exact f32
        sync — bit-identical to the fault-free baseline, since f32 IS
        the baseline's path — with the degrade journaled and metric'd,
        never silent and never fatal.  ``die`` (ThreadDeath) included:
        the probe runs on the MAIN thread, where an uncaught injected
        death would kill the run instead of degrading it."""
        cfg = dataclasses.replace(
            _e2e_cfg(f"gradprobe_{action}", str(tmp_path)),
            grad_allreduce="int8", round_pipeline="off",
            fault_spec=f"grad_probe:{action}@1")
        run_experiment(cfg, sink=None, data=chaos_data,
                       train_cfg=tiny_train_config(),
                       model=TinyClassifier(num_classes=4))
        state = dict(np.load(glob.glob(os.path.join(
            cfg.ckpt_path, "*", "experiment_state.npz"))[0]))
        # Degraded = trained on f32 = the baseline's exact math.
        for k in baseline:
            assert np.array_equal(baseline[k], state[k]), (
                f"experiment_state[{k!r}] diverged under the probe-"
                "degraded f32 fallback")
        jr = journal_lib.read_journal(
            os.path.join(cfg.log_dir, faults.JOURNAL_FILE))
        assert jr["status"] == "finished"
        assert jr["grad_allreduce"] == "f32_degraded"
        assert (_metric_max(cfg.log_dir, "degrade_events") or 0) >= 1
        assert (_metric_max(cfg.log_dir,
                            "grad_allreduce_degraded") or 0) >= 1
        log = glob.glob(os.path.join(cfg.log_dir, "*.log"))[0]
        assert "FAILED the multichip learning probe" in open(log).read()

    def test_probe_degrade_is_sticky_across_resume(
            self, chaos_data, tmp_path):
        """A run whose probe failed (journaled f32_degraded) must STAY
        on f32 when resumed — re-probing on resume and flipping to
        int8 would splice bounded-delta rounds onto bit-exact ones
        under a journal that still says degraded."""
        cfg = dataclasses.replace(
            _e2e_cfg("stickyar", str(tmp_path)),
            grad_allreduce="int8", round_pipeline="off",
            fault_spec="grad_probe:raise@1")
        run_experiment(cfg, sink=NullSink(), data=chaos_data,
                       train_cfg=tiny_train_config(),
                       model=TinyClassifier(num_classes=4))
        # Resume (fault-free, more rounds): the probe would PASS now —
        # the sticky rule must keep f32 and skip it.
        cfg2 = dataclasses.replace(
            _e2e_cfg("stickyar", str(tmp_path), resume=True),
            grad_allreduce="int8", round_pipeline="off", rounds=3)
        strategy = run_experiment(cfg2, sink=NullSink(), data=chaos_data,
                                  train_cfg=tiny_train_config(),
                                  model=TinyClassifier(num_classes=4))
        assert strategy.trainer.grad_allreduce == "f32"
        jr = journal_lib.read_journal(
            os.path.join(cfg2.log_dir, faults.JOURNAL_FILE))
        assert jr["grad_allreduce"] == "f32_degraded"
        log = glob.glob(os.path.join(cfg2.log_dir, "*.log"))[0]
        assert "keeping f32 for the resumed segment" in open(log).read()

    def test_fused_update_oom_routes_to_batch_half(
            self, chaos_data, tmp_path):
        """An OOM surfacing from the fused-optimizer train-step
        dispatch (the dispatch site wraps every jitted train dispatch;
        the fused update executes inside it) costs a round ATTEMPT and
        lands on the ladder's batch_half rung — the run completes."""
        cfg = dataclasses.replace(
            _e2e_cfg("fusedoom", str(tmp_path)),
            round_pipeline="off", fault_spec="dispatch:oom@3")
        strategy = run_experiment(cfg, sink=None, data=chaos_data,
                                  train_cfg=tiny_train_config(),
                                  model=TinyClassifier(num_classes=4))
        assert strategy.trainer.fused_tx is not None  # the fused path ran
        assert (_metric_max(cfg.log_dir, "degrade_events") or 0) >= 1
        jr = journal_lib.read_journal(
            os.path.join(cfg.log_dir, faults.JOURNAL_FILE))
        assert jr["status"] == "finished"
        log = glob.glob(os.path.join(cfg.log_dir, "*.log"))[0]
        assert "engaging rung 'batch_half'" in open(log).read()


# ---------------------------------------------------------------------------
# Preemption: checkpoint-and-exit, resume bit-identical
# ---------------------------------------------------------------------------

class _PreemptAtEpochSink(NullSink):
    """Records a preemption request (exactly what the real signal
    handler does) when round ``rd``'s fit reaches the given epoch — a
    deterministic in-process stand-in for SIGTERM."""

    def __init__(self, rd: int, epoch: int):
        self.name = f"rd_{rd}_validation_accuracy"
        self.epoch = epoch
        self.fired = False

    def log_metric(self, name, value, step=None):
        if not self.fired and step == self.epoch and name == self.name:
            self.fired = True
            preempt_lib._handler(signal.SIGTERM, None)


class TestPreemptionResume:
    def test_round0_preemption_resumes_bit_identical(self, chaos_data,
                                                     baseline, tmp_path):
        """Preempted DURING round 0's fit (before any save_experiment):
        the trainer saves the mid-fit state at the epoch boundary, the
        journal records the preemption, and --resume_training replays
        round 0 consuming that state — experiment_state bit-identical
        to the uninterrupted run."""
        cfg = _e2e_cfg("preempt0", str(tmp_path))
        sink = _PreemptAtEpochSink(rd=0, epoch=1)
        with pytest.raises(preempt_lib.PreemptionRequested):
            run_experiment(cfg, sink=sink, data=chaos_data,
                           train_cfg=tiny_train_config(),
                           model=TinyClassifier(num_classes=4))
        assert sink.fired
        jr = journal_lib.read_journal(
            os.path.join(cfg.log_dir, faults.JOURNAL_FILE))
        assert jr["status"] == "preempted"
        assert jr["signal"] == int(signal.SIGTERM)
        # No experiment-level state yet — the journal is what makes
        # this resumable.
        assert not glob.glob(os.path.join(cfg.ckpt_path, "*",
                                          "experiment_state.npz"))
        state = _run_e2e(_e2e_cfg("preempt0", str(tmp_path), resume=True),
                         chaos_data)
        for k in baseline:
            assert np.array_equal(baseline[k], state[k]), (
                f"experiment_state[{k!r}] diverged after round-0 "
                "preemption resume")

    def test_round1_preemption_resumes_bit_identical(self, chaos_data,
                                                     baseline, tmp_path):
        """Preempted during round 1's fit: round 0's completed state
        loads, round 1's mid-fit state is consumed."""
        cfg = _e2e_cfg("preempt1", str(tmp_path))
        sink = _PreemptAtEpochSink(rd=1, epoch=1)
        with pytest.raises(preempt_lib.PreemptionRequested):
            run_experiment(cfg, sink=sink, data=chaos_data,
                           train_cfg=tiny_train_config(),
                           model=TinyClassifier(num_classes=4))
        assert sink.fired
        state = _run_e2e(_e2e_cfg("preempt1", str(tmp_path), resume=True),
                         chaos_data)
        for k in baseline:
            assert np.array_equal(baseline[k], state[k]), (
                f"experiment_state[{k!r}] diverged after round-1 "
                "preemption resume")

    def test_resume_without_state_or_preemption_still_refuses(
            self, chaos_data, tmp_path):
        """The never-silently-restart contract survives: no saved
        experiment AND no preemption journal -> explicit error."""
        cfg = _e2e_cfg("norestart", str(tmp_path), resume=True)
        with pytest.raises(FileNotFoundError, match="no saved experiment"):
            run_experiment(cfg, sink=NullSink(), data=chaos_data,
                           train_cfg=tiny_train_config(),
                           model=TinyClassifier(num_classes=4))

    def test_round0_resume_requires_matching_identity(self, chaos_data,
                                                      tmp_path):
        """The journal is keyed by log_dir, not experiment: a round-0
        preemption must only unlock the resume for the SAME exp_name/
        exp_hash — a forgotten --exp_hash (fresh uuid) or a wrong
        --ckpt_path preempted at a later round still hits the explicit
        error, never a silent restart."""
        cfg = _e2e_cfg("ident0", str(tmp_path))
        sink = _PreemptAtEpochSink(rd=0, epoch=1)
        with pytest.raises(preempt_lib.PreemptionRequested):
            run_experiment(cfg, sink=sink, data=chaos_data,
                           train_cfg=tiny_train_config(),
                           model=TinyClassifier(num_classes=4))
        # Same dirs, DIFFERENT exp_hash (the forgotten-flag shape).
        wrong = dataclasses.replace(
            _e2e_cfg("ident0", str(tmp_path), resume=True),
            exp_hash="other")
        with pytest.raises(FileNotFoundError, match="no saved experiment"):
            run_experiment(wrong, sink=NullSink(), data=chaos_data,
                           train_cfg=tiny_train_config(),
                           model=TinyClassifier(num_classes=4))
        # A journal preempted at a LATER round never unlocks the
        # round-0 path either, even with matching identity (wrong
        # --ckpt_path shape: the completed rounds live elsewhere).
        journal_lib.RoundJournal(
            os.path.join(cfg.log_dir, faults.JOURNAL_FILE)).write(
                exp_name="faults", exp_hash="ident0",
                round=1, status="preempted")
        with pytest.raises(FileNotFoundError, match="no saved experiment"):
            run_experiment(
                _e2e_cfg("ident0", str(tmp_path), resume=True),
                sink=NullSink(), data=chaos_data,
                train_cfg=tiny_train_config(),
                model=TinyClassifier(num_classes=4))


# ---------------------------------------------------------------------------
# Real SIGTERM, real subprocess, mid-pipelined-round
# ---------------------------------------------------------------------------

_CHILD = r"""
import os, sys
sys.path.insert(0, {repo!r}); sys.path.insert(0, {tests!r})
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
from helpers import TinyClassifier, tiny_train_config
from active_learning_tpu.config import ExperimentConfig, TelemetryConfig
from active_learning_tpu.data.synthetic import get_data_synthetic
from active_learning_tpu.experiment.driver import run_experiment
from active_learning_tpu.faults.preempt import PreemptionRequested
from active_learning_tpu.utils.metrics import NullSink

cfg = ExperimentConfig(
    dataset="synthetic", arg_pool="synthetic", strategy="MarginSampler",
    rounds=2, round_budget=8, n_epoch={n_epoch}, early_stop_patience={n_epoch},
    run_seed=7, exp_hash="sigterm", exp_name="faults",
    ckpt_path={ckpt!r}, log_dir={log!r}, round_pipeline="speculative",
    resume_training={resume}, fault_spec={fault_spec!r},
    telemetry=TelemetryConfig(enabled=True, heartbeat_every_s=0.0))
data = get_data_synthetic(n_train=96, n_test=32, num_classes=4,
                          image_size=8, seed=5)
print("CHILD_READY", flush=True)
try:
    run_experiment(cfg, sink=NullSink(), data=data,
                   train_cfg=tiny_train_config(),
                   model=TinyClassifier(num_classes=4))
except PreemptionRequested:
    # The CLI's mapping: graceful preemption exits 0.
    print("CHILD_PREEMPTED", flush=True)
    sys.exit(0)
print("CHILD_FINISHED", flush=True)
"""

SIG_EPOCHS = 6


def _spawn_child(ckpt: str, log: str, *, resume: bool = False,
                 fault_spec=None):
    code = _CHILD.format(repo=REPO,
                         tests=os.path.join(REPO, "tests"),
                         n_epoch=SIG_EPOCHS, ckpt=ckpt, log=log,
                         resume=resume, fault_spec=fault_spec)
    return subprocess.Popen([sys.executable, "-c", code],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True)


class TestSigtermSubprocess:
    @pytest.fixture(scope="class")
    def uninterrupted(self, chaos_data, tmp_path_factory):
        """The SIGTERM comparison baseline at the subprocess config
        (more epochs: the kill needs a fit long enough to land in)."""
        root = str(tmp_path_factory.mktemp("sig_base"))
        return _run_e2e(_e2e_cfg("sigbase", root, n_epoch=SIG_EPOCHS),
                        chaos_data)

    def test_sigterm_mid_round_resumes_bit_exact(self, uninterrupted,
                                                 tmp_path):
        """The acceptance pin, end to end in real processes: a driver
        child (pipelined round armed, every dispatch stretched by the
        delay fault so the kill window is wide) takes a REAL SIGTERM
        mid-round-0-fit, exits 0 with everything checkpointed; a second
        child resumes and finishes; the picks are bit-exact vs the
        uninterrupted run."""
        ckpt = str(tmp_path / "ckpt")
        log = str(tmp_path / "logs")
        proc = _spawn_child(ckpt, log, fault_spec="dispatch:delay@0.05")
        try:
            hb_path = os.path.join(log, "heartbeat.json")
            deadline = time.monotonic() + 300
            in_fit = False
            while time.monotonic() < deadline:
                if proc.poll() is not None:
                    pytest.fail("child exited before the kill: "
                                + proc.communicate()[1][-2000:])
                hb = hb_lib.read_heartbeat(hb_path) or {}
                if (hb.get("round") == 0 and (hb.get("epoch") or 0) >= 1
                        and hb.get("status") == "running"):
                    in_fit = True
                    break
                time.sleep(0.02)
            assert in_fit, "child never reached round 0's fit"
            proc.send_signal(signal.SIGTERM)
            out, err = proc.communicate(timeout=120)
            assert proc.returncode == 0, err[-2000:]
            assert "CHILD_PREEMPTED" in out
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.communicate()

        jr = journal_lib.read_journal(
            os.path.join(log, faults.JOURNAL_FILE))
        assert jr["status"] == "preempted"
        assert jr["signal"] == int(signal.SIGTERM)

        resumed = _spawn_child(ckpt, log, resume=True)
        try:
            out, err = resumed.communicate(timeout=600)
            assert resumed.returncode == 0, err[-2000:]
            assert "CHILD_FINISHED" in out
        finally:
            if resumed.poll() is None:
                resumed.kill()
                resumed.communicate()

        state_path = glob.glob(os.path.join(ckpt, "*",
                                            "experiment_state.npz"))[0]
        state = dict(np.load(state_path))
        assert set(state) == set(uninterrupted)
        for k in uninterrupted:
            assert np.array_equal(uninterrupted[k], state[k]), (
                f"experiment_state[{k!r}] diverged after SIGTERM resume")


# ---------------------------------------------------------------------------
# status --strict: the orchestrator exit-code contract
# ---------------------------------------------------------------------------

class TestStatusStrict:
    def _fresh_dir(self, tmp_path, *, degrade=None, status="running"):
        d = str(tmp_path)
        os.makedirs(d, exist_ok=True)
        hb = hb_lib.HeartbeatWriter(os.path.join(d, "heartbeat.json"),
                                    every_s=0.0, stall_deadline_s=600.0)
        hb.tick(round=1, phase="train", status="running")
        j = journal_lib.RoundJournal(os.path.join(d, faults.JOURNAL_FILE))
        j.write(status=status, round=1, phase="train",
                degrade=degrade or [])
        return d

    def test_healthy_is_zero_with_and_without_strict(self, tmp_path):
        d = self._fresh_dir(tmp_path)
        assert status_lib.main(["--log_dir", d]) == 0
        assert status_lib.main(["--log_dir", d, "--strict"]) == 0

    def test_degraded_is_4_only_under_strict(self, tmp_path):
        d = self._fresh_dir(tmp_path, degrade=["pool_replicated"])
        assert status_lib.main(["--log_dir", d]) == 0
        assert status_lib.main(["--log_dir", d, "--strict"]) == 4
        text = status_lib.render_text(status_lib.summarize(d))
        assert "DEGRADED" in text and "pool_replicated" in text

    def test_stale_beats_degraded(self, tmp_path):
        d = self._fresh_dir(tmp_path, degrade=["feed_host"])
        hb_path = os.path.join(d, "heartbeat.json")
        old = time.time() - 10_000.0
        os.utime(hb_path, (old, old))
        assert status_lib.main(["--log_dir", d, "--strict"]) == 3

    def test_terminal_status_with_leftover_degrade_is_healthy(
            self, tmp_path):
        # A run that ended ON a rung — finished, or CLEANLY PREEMPTED
        # mid-degraded-round — is done self-healing: exit 4 is for live
        # capacity loss, not history (a false 4 after preemption would
        # block resume automation).
        for i, status in enumerate(("finished", "preempted")):
            d = self._fresh_dir(tmp_path / str(i), degrade=["feed_host"],
                                status=status)
            assert status_lib.main(["--log_dir", d, "--strict"]) == 0, \
                status

    def test_no_heartbeat_is_2(self, tmp_path):
        assert status_lib.main(["--log_dir", str(tmp_path),
                                "--strict"]) == 2
