"""Differential torch-vs-Flax forward parity.

The reference runs torchvision ResNets (resnet_simclr.py:8-22) and the
SSL-checkpoint workflow ports torch weights into this repo's Flax models
(utils/pretrained.py).  Parameter-count and key-mapping tests cannot catch
topology/numerics drift — stride placement, padding alignment, BN
epsilon — so this module builds the same networks in raw torch (CPU,
torchvision is not installed here), pushes their weights through the real
converter, and requires the two frameworks to produce the SAME logits.

This is the test that catches the SAME-vs-torch padding shift on strided
3x3 convs (flax SAME pads (0, 1) on even inputs; torch padding=1 pads
(1, 1)) — a silent one-pixel window misalignment that would degrade every
converted checkpoint.
"""

import numpy as np
import pytest

# Differential torch-vs-Flax forwards compile both stacks at multiple
# input shapes.
pytestmark = pytest.mark.slow

torch = pytest.importorskip("torch")
import torch.nn as nn  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from active_learning_tpu.models.resnet import resnet18, resnet50  # noqa: E402
from active_learning_tpu.utils.pretrained import overlay_torch_state  # noqa: E402


class TorchBasicBlock(nn.Module):
    def __init__(self, cin, cout, stride=1):
        super().__init__()
        self.conv1 = nn.Conv2d(cin, cout, 3, stride, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(cout)
        self.conv2 = nn.Conv2d(cout, cout, 3, 1, 1, bias=False)
        self.bn2 = nn.BatchNorm2d(cout)
        self.downsample = None
        if stride != 1 or cin != cout:
            self.downsample = nn.Sequential(
                nn.Conv2d(cin, cout, 1, stride, bias=False),
                nn.BatchNorm2d(cout))

    def forward(self, x):
        idn = x if self.downsample is None else self.downsample(x)
        out = torch.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        return torch.relu(out + idn)


class TorchBottleneck(nn.Module):
    """v1.5: the stride lives on the 3x3 conv."""

    def __init__(self, cin, width, stride=1):
        super().__init__()
        cout = width * 4
        self.conv1 = nn.Conv2d(cin, width, 1, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(width)
        self.conv2 = nn.Conv2d(width, width, 3, stride, 1, bias=False)
        self.bn2 = nn.BatchNorm2d(width)
        self.conv3 = nn.Conv2d(width, cout, 1, 1, bias=False)
        self.bn3 = nn.BatchNorm2d(cout)
        self.downsample = None
        if stride != 1 or cin != cout:
            self.downsample = nn.Sequential(
                nn.Conv2d(cin, cout, 1, stride, bias=False),
                nn.BatchNorm2d(cout))

    def forward(self, x):
        idn = x if self.downsample is None else self.downsample(x)
        out = torch.relu(self.bn1(self.conv1(x)))
        out = torch.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        return torch.relu(out + idn)


class TorchEncoder(nn.Module):
    """ResNet encoder with torchvision's attribute names, so its
    state_dict keys are exactly what the converter maps.  ``cifar_stem``
    selects the SimCLR 3x3 stem (resnet_hacks.py:31-35) vs the standard
    7x7 stride-2 stem + 3x3 stride-2 max pool."""

    def __init__(self, block, layers, widths=(64, 128, 256, 512),
                 cifar_stem=True):
        super().__init__()
        self.cifar_stem = cifar_stem
        if cifar_stem:
            self.conv1 = nn.Conv2d(3, 64, 3, 1, 1, bias=False)
        else:
            self.conv1 = nn.Conv2d(3, 64, 7, 2, 3, bias=False)
            self.maxpool = nn.MaxPool2d(3, 2, 1)
        self.bn1 = nn.BatchNorm2d(64)
        cin = 64
        for i, (n, w) in enumerate(zip(layers, widths)):
            blocks = []
            for j in range(n):
                stride = 2 if i > 0 and j == 0 else 1
                blocks.append(block(cin, w, stride))
                cin = w * (4 if block is TorchBottleneck else 1)
            setattr(self, f"layer{i + 1}", nn.Sequential(*blocks))
        self.out_dim = cin

    def forward(self, x):
        x = torch.relu(self.bn1(self.conv1(x)))
        if not self.cifar_stem:
            x = self.maxpool(x)
        for i in range(4):
            x = getattr(self, f"layer{i + 1}")(x)
        return x.mean(dim=(2, 3))


class TorchSSLNet(nn.Module):
    def __init__(self, block, layers, num_classes=10, cifar_stem=True):
        super().__init__()
        self.encoder = TorchEncoder(block, layers, cifar_stem=cifar_stem)
        self.linear = nn.Linear(self.encoder.out_dim, num_classes)

    def forward(self, x):
        return self.linear(self.encoder(x))


def _randomized_state(tnet, seed):
    """Non-trivial weights AND running stats: a few train-mode batches
    populate BN running mean/var with real values, so the stats mapping
    (running_* -> batch_stats) is exercised with distinguishable numbers."""
    g = torch.Generator().manual_seed(seed)
    with torch.no_grad():
        for p in tnet.parameters():
            p.copy_(torch.randn(p.shape, generator=g) * 0.05)
    tnet.train()
    with torch.no_grad():
        for _ in range(3):
            tnet(torch.randn(8, 3, 32, 32, generator=g))
    tnet.eval()
    return {k: v.numpy().copy() for k, v in tnet.state_dict().items()}


@pytest.mark.parametrize("name",
                         ["resnet18", "resnet50", "resnet18_imagenet"])
def test_forward_logits_match_torch(name):
    px = 32
    if name == "resnet18":
        tnet = TorchSSLNet(TorchBasicBlock, [2, 2, 2, 2])
        model = resnet18(num_classes=10, cifar_stem=True)
        tol = 2e-4
    elif name == "resnet50":
        tnet = TorchSSLNet(TorchBottleneck, [3, 4, 6, 3])
        model = resnet50(num_classes=10, cifar_stem=True)
        tol = 5e-4
    else:
        # The ImageNet stem: 7x7 stride-2 conv + 3x3 stride-2 max pool —
        # covers the stem/pool padding alignment the CIFAR stem skips.
        tnet = TorchSSLNet(TorchBasicBlock, [2, 2, 2, 2], cifar_stem=False)
        model = resnet18(num_classes=10, cifar_stem=False)
        tol, px = 2e-4, 64
    state = _randomized_state(tnet, seed=0)

    x = np.random.default_rng(1).normal(size=(4, 3, px, px)
                                        ).astype(np.float32)
    with torch.no_grad():
        want = tnet(torch.from_numpy(x)).numpy()

    variables = model.init(jax.random.PRNGKey(0),
                           jnp.asarray(x.transpose(0, 2, 3, 1)),
                           train=False)
    variables = overlay_torch_state(
        jax.tree.map(np.asarray, dict(variables)), state)
    got = np.asarray(model.apply(variables,
                                 jnp.asarray(x.transpose(0, 2, 3, 1)),
                                 train=False))
    np.testing.assert_allclose(got, want, rtol=tol, atol=tol)


def test_moco_checkpoint_full_pipeline(tmp_path):
    """The complete SSL ingestion path on a MoCo-v2-style torch.save file:
    {'state_dict': ...} wrapper, 'module.' DataParallel prefix,
    'encoder_q' -> 'encoder' rename, 'fc' projection head skipped
    (arg_pools ssp_finetuning semantics, reference
    ssp_finetuning.py:34-37).  The converted encoder must reproduce the
    torch encoder's embeddings; the linear head must keep its random
    init (the reference's partial-update semantics)."""
    from active_learning_tpu.config import PretrainedConfig
    from active_learning_tpu.utils.pretrained import apply_pretrained

    tnet = TorchSSLNet(TorchBasicBlock, [2, 2, 2, 2])
    _randomized_state(tnet, seed=3)
    enc_state = tnet.encoder.state_dict()
    ckpt = {f"module.encoder_q.{k}": torch.as_tensor(v)
            for k, v in enc_state.items()}
    # MoCo's projection head and queue — must be filtered out.
    ckpt["module.encoder_q.fc.0.weight"] = torch.zeros(64, 512)
    ckpt["module.encoder_k.conv1.weight"] = torch.zeros_like(
        ckpt["module.encoder_q.conv1.weight"])
    ckpt["module.queue"] = torch.zeros(128, 100)
    path = str(tmp_path / "moco.pth")
    torch.save({"state_dict": ckpt, "epoch": 7}, path)

    model = resnet18(num_classes=10, cifar_stem=True)
    x = np.random.default_rng(2).normal(size=(4, 3, 32, 32)
                                        ).astype(np.float32)
    variables = jax.tree.map(
        np.asarray,
        dict(model.init(jax.random.PRNGKey(0),
                        jnp.asarray(x.transpose(0, 2, 3, 1)),
                        train=False)))
    cfg = PretrainedConfig(path=path, required_key=("encoder_q",),
                           skip_key=("fc", "queue"),
                           replace_key=(("encoder_q", "encoder"),))
    loaded = apply_pretrained(variables, cfg)

    tnet.eval()
    with torch.no_grad():
        want_emb = tnet.encoder(torch.from_numpy(x)).numpy()
    _, got_emb = model.apply(loaded, jnp.asarray(x.transpose(0, 2, 3, 1)),
                             train=False, return_features=True)
    np.testing.assert_allclose(np.asarray(got_emb), want_emb,
                               rtol=2e-4, atol=2e-4)
    # Partial update: the head was not in the checkpoint and keeps its
    # random init bit-for-bit.
    np.testing.assert_array_equal(
        loaded["params"]["linear"]["kernel"],
        variables["params"]["linear"]["kernel"])


def test_moco_v2_real_checkpoint_layout(tmp_path):
    """Faithful facsimile of the ACTUAL paper input — MoCo-v2's published
    ``moco_v2_800ep_pretrain.pth.tar`` (the file every ImageNet arg pool
    names, reference ssp_finetuning.py:34 / ssp_linear_evaluation.py:21)
    — pushed through ``apply_pretrained`` with the reference's EXACT key
    filters.  The real file is the full training state main_moco.py
    saves: ``{"epoch", "arch", "state_dict", "optimizer"}`` where
    state_dict holds DistributedDataParallel-prefixed
    ``module.encoder_q.*`` (ResNet-50, ImageNet stem, v2 MLP projection
    head ``fc.0``/``fc.2``), a full momentum copy ``module.encoder_k.*``,
    and the contrastive ``module.queue``/``queue_ptr`` buffers.

    Asserts FULL overlay coverage: every key that survives the
    reference's surgery must map into the Flax model (strict mode) and
    every encoder leaf must actually be overwritten — a wrapper/naming
    quirk that silently drops tensors is exactly what this test exists
    to catch before paper-run time."""
    from flax.traverse_util import flatten_dict

    from active_learning_tpu.config import PretrainedConfig
    from active_learning_tpu.utils.pretrained import (apply_pretrained,
                                                      surgery,
                                                      torch_key_to_flax)

    tenc = TorchEncoder(TorchBottleneck, [3, 4, 6, 3], cifar_stem=False)
    g = torch.Generator().manual_seed(11)
    with torch.no_grad():
        for p in tenc.parameters():
            p.copy_(torch.randn(p.shape, generator=g) * 0.05)
    tenc.train()
    with torch.no_grad():
        for _ in range(2):
            tenc(torch.randn(4, 3, 64, 64, generator=g))
    tenc.eval()

    def moco_module(enc):
        # One encoder as MoCo-v2 stores it: backbone + MLP head replacing
        # torchvision's fc (main_moco.py builds fc = Sequential(Linear,
        # ReLU, Linear); its state_dict keys are fc.0.* / fc.2.*).
        sd = {k: v.clone() for k, v in enc.state_dict().items()}
        sd["fc.0.weight"] = torch.randn(2048, 2048, generator=g)
        sd["fc.0.bias"] = torch.randn(2048, generator=g)
        sd["fc.2.weight"] = torch.randn(128, 2048, generator=g)
        sd["fc.2.bias"] = torch.randn(128, generator=g)
        return sd

    state_dict = {}
    for k, v in moco_module(tenc).items():
        state_dict[f"module.encoder_q.{k}"] = v
    for k, v in moco_module(tenc).items():
        state_dict[f"module.encoder_k.{k}"] = v * 0.5
    state_dict["module.queue"] = torch.randn(128, 65536, generator=g)
    state_dict["module.queue_ptr"] = torch.zeros(1, dtype=torch.long)
    path = str(tmp_path / "moco_v2_800ep_pretrain.pth.tar")
    torch.save({"epoch": 800, "arch": "resnet50",
                "state_dict": state_dict,
                "optimizer": {"param_groups": []}}, path)

    # The reference's exact filter config (ssp_finetuning.py:35-37).
    cfg = PretrainedConfig(path=path, required_key=("encoder_q",),
                           skip_key=("fc",),
                           replace_key=(("encoder_q", "encoder"),))

    # Coverage accounting BEFORE the overlay: after surgery, every
    # surviving key must be encoder backbone state — each either maps to
    # a Flax leaf or is a num_batches_tracked counter.  torch_key_to_flax
    # raising KeyError on ANY of them fails the test.
    survivors = surgery({k: v.numpy() for k, v in state_dict.items()},
                        required_key=cfg.required_key,
                        skip_key=cfg.skip_key, replace_map=cfg.replace_map)
    assert all(k.startswith("encoder.") for k in survivors)
    mapped = {k: torch_key_to_flax(k) for k in survivors}
    n_counters = sum(1 for v in mapped.values() if v is None)
    assert n_counters == 53  # one per BN layer in a ResNet-50
    paths = [v[0] for v in mapped.values() if v is not None]
    assert len(set(paths)) == len(paths)  # no two keys share one leaf

    model = resnet50(num_classes=1000, cifar_stem=False)
    x = np.random.default_rng(4).normal(size=(2, 3, 64, 64)
                                        ).astype(np.float32)
    variables = jax.tree.map(
        np.asarray,
        dict(model.init(jax.random.PRNGKey(0),
                        jnp.asarray(x.transpose(0, 2, 3, 1)),
                        train=False)))
    loaded = apply_pretrained(variables, cfg)

    # Full coverage, verified on the RESULT: every encoder leaf (params
    # AND batch_stats) was overwritten by a checkpoint tensor.
    flat_init = flatten_dict(variables)
    flat_loaded = flatten_dict(loaded)
    enc_leaves = [p for p in flat_init if "encoder" in p]
    assert len(enc_leaves) == len(mapped) - n_counters
    untouched = [p for p in enc_leaves
                 if np.array_equal(flat_loaded[p], flat_init[p])]
    assert not untouched, f"leaves never overlaid: {untouched[:5]}"

    # The converted encoder reproduces the torch encoder's embeddings.
    with torch.no_grad():
        want_emb = tenc(torch.from_numpy(x)).numpy()
    _, got_emb = model.apply(loaded, jnp.asarray(x.transpose(0, 2, 3, 1)),
                             train=False, return_features=True)
    np.testing.assert_allclose(np.asarray(got_emb), want_emb,
                               rtol=5e-4, atol=5e-4)
    # And the classification head kept its random init bit-for-bit (the
    # reference's partial-update semantics: fc was skipped).
    np.testing.assert_array_equal(
        loaded["params"]["linear"]["kernel"],
        variables["params"]["linear"]["kernel"])


def test_converter_strict_errors():
    """Unmappable keys and shape mismatches must raise, not silently
    skip — a wrong checkpoint going unnoticed is the failure mode the
    strict mode exists for (reference silently ignores them)."""
    model = resnet18(num_classes=10, cifar_stem=True)
    x = jnp.zeros((1, 32, 32, 3), jnp.float32)
    variables = jax.tree.map(
        np.asarray, dict(model.init(jax.random.PRNGKey(0), x,
                                    train=False)))
    with pytest.raises(KeyError):
        overlay_torch_state(variables,
                            {"encoder.not_a_layer.weight":
                             np.zeros((3, 3), np.float32)})
    with pytest.raises(ValueError, match="Shape mismatch"):
        overlay_torch_state(variables,
                            {"encoder.conv1.weight":
                             np.zeros((64, 3, 7, 7), np.float32)})
