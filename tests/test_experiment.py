"""End-to-end experiment-driver tests on the virtual 8-device mesh.

Covers the reference's round loop (src/main_al.py:145-184): pool growth,
metric emission, round-0 query with an empty initial pool, and resume
reproducing the identical next-round query (src/utils/resume_training.py).
"""

from __future__ import annotations

import json
import os

import numpy as np
import pytest

from active_learning_tpu.config import ExperimentConfig
from active_learning_tpu.data.synthetic import get_data_synthetic
from active_learning_tpu.experiment import arg_pools  # noqa: F401
from active_learning_tpu.experiment.driver import run_experiment
from active_learning_tpu.registry import STRATEGIES
from active_learning_tpu.utils.metrics import JsonlSink

from helpers import TinyClassifier, tiny_train_config


def _cfg(tmp_path, name, **overrides) -> ExperimentConfig:
    base = dict(
        dataset="synthetic", arg_pool="synthetic", strategy="MarginSampler",
        rounds=2, round_budget=8, n_epoch=2, early_stop_patience=2,
        exp_hash=name, exp_name="e2e",
        ckpt_path=str(tmp_path / f"ckpt_{name}"),
        log_dir=str(tmp_path / f"logs_{name}"),
        run_seed=7,
    )
    base.update(overrides)
    return ExperimentConfig(**base)


def _run(cfg, tmp_path, name):
    data = get_data_synthetic(n_train=96, n_test=32, num_classes=4,
                              image_size=8, seed=5)
    sink = JsonlSink(cfg.log_dir, experiment_key=name)
    model = TinyClassifier(num_classes=4)
    strategy = run_experiment(cfg, sink=sink, data=data,
                              train_cfg=tiny_train_config(), model=model)
    return strategy, sink


def test_config_driven_imbalanced_data_path(tmp_path):
    """run_experiment with data=None must build the imbalanced dataset
    from cfg.imbalance itself — the driver once downgraded the
    ImbalanceConfig to a dict, crashing every config-driven imbalanced
    run (the factories read it by attribute) while injected-data tests
    passed."""
    from active_learning_tpu.config import ImbalanceConfig

    cfg = _cfg(tmp_path, "cfgimb", dataset="imbalanced_synthetic",
               imbalance=ImbalanceConfig(imbalance_type="exp",
                                         imbalance_factor=0.1,
                                         imbalance_seed=3))
    sink = JsonlSink(cfg.log_dir, experiment_key="cfgimb")
    strategy = run_experiment(cfg, sink=sink,
                              train_cfg=tiny_train_config(),
                              model=TinyClassifier(num_classes=10))
    assert strategy.pool.num_labeled == 16


def _read_metrics(log_dir):
    events = []
    with open(os.path.join(log_dir, "metrics.jsonl")) as fh:
        for line in fh:
            events.append(json.loads(line))
    return events


def _asset(log_dir, name) -> np.ndarray:
    path = os.path.join(log_dir, "assets", f"{name}.txt")
    with open(path) as fh:
        text = fh.read().strip()
    if not text:
        return np.zeros(0, dtype=np.int64)
    return np.asarray([int(e) for e in text.split(",")], dtype=np.int64)


def test_two_round_experiment_grows_pool_and_emits_metrics(tmp_path):
    cfg = _cfg(tmp_path, "basic")
    strategy, sink = _run(cfg, tmp_path, "basic")

    # Init pool (round_budget) + one query round.
    assert strategy.pool.num_labeled == 16
    assert strategy.pool.cumulative_cost == 16
    assert strategy.round == 1

    events = _read_metrics(cfg.log_dir)
    names = set()
    for e in events:
        if e["kind"] == "metric":
            names.update(e["metrics"])
    # The reference's metric schema (main_al.py:24-40).
    assert "rd_test_accuracy" in names
    assert "budget_test_accuracy" in names
    assert "cumulative_budget" in names
    assert "rd_0_validation_accuracy" in names
    assert "rd_train_time" in names
    # Queried-idx audit assets exist for both rounds and are disjoint.
    rd0 = _asset(cfg.log_dir, "labeled_idxs_on_rd_0")
    rd1 = _asset(cfg.log_dir, "labeled_idxs_on_rd_1")
    assert len(rd0) == 8 and len(rd1) == 8
    assert np.intersect1d(rd0, rd1).size == 0
    # Eval idxs never queried (strategy.py:138-144).
    assert np.intersect1d(rd1, strategy.pool.eval_idxs).size == 0
    # Checkpoints on disk for both rounds.
    ckpt_dir = os.path.join(cfg.ckpt_path, "e2e_basic")
    assert os.path.exists(os.path.join(ckpt_dir, "best_rd_0.msgpack"))
    assert os.path.exists(os.path.join(ckpt_dir, "best_rd_1.msgpack"))


def test_round0_queries_when_init_pool_empty(tmp_path):
    # init_pool_size=0 => round 0 initializes weights and queries before
    # training (main_al.py:149-157).
    cfg = _cfg(tmp_path, "rd0", init_pool_size=0, rounds=1,
               strategy="RandomSampler")
    strategy, _ = _run(cfg, tmp_path, "rd0")
    assert strategy.pool.num_labeled == 8
    rd0 = _asset(cfg.log_dir, "labeled_idxs_on_rd_0")
    assert len(rd0) == 8


def test_resume_reproduces_identical_round2_query(tmp_path):
    # Uninterrupted 3-round run.
    cfg_full = _cfg(tmp_path, "full", rounds=3)
    _run(cfg_full, tmp_path, "full")
    want = _asset(cfg_full.log_dir, "labeled_idxs_on_rd_2")

    # Same config stopped after round 1, then resumed for round 2.
    cfg_a = _cfg(tmp_path, "part", rounds=2)
    _run(cfg_a, tmp_path, "part")
    cfg_b = _cfg(tmp_path, "part", rounds=3, resume_training=True)
    strategy_b, _ = _run(cfg_b, tmp_path, "part")

    got = _asset(cfg_b.log_dir, "labeled_idxs_on_rd_2")
    np.testing.assert_array_equal(np.sort(got), np.sort(want))
    assert strategy_b.round == 2
    # init pool (8) + queries at rounds 1 and 2 (round 0 trains only).
    assert strategy_b.pool.num_labeled == 24
    # Post-resume TRAINING must also match the uninterrupted run: the
    # restored rng + init key reproduce the identical round-2 re-init and
    # fit, so the best round-2 weights are bit-identical.
    from active_learning_tpu.train import checkpoint as ckpt_lib
    va = ckpt_lib.load_variables(
        os.path.join(cfg_full.ckpt_path, "e2e_full", "best_rd_2.msgpack"))
    vb = ckpt_lib.load_variables(
        os.path.join(cfg_b.ckpt_path, "e2e_part", "best_rd_2.msgpack"))
    import jax
    jax.tree.map(np.testing.assert_array_equal, va, vb)


def test_mid_round_crash_resumes_from_saved_epoch(tmp_path):
    """Driver-level epoch recovery: a run killed mid-fit of round 1
    relaunched with --resume_training continues that round from the last
    saved fit-state epoch (not epoch 1) and lands on the same best round-1
    weights as an uninterrupted run — the full wiring of
    strategy.resume_next_fit through Trainer.fit."""
    import dataclasses

    import jax

    from active_learning_tpu.train import checkpoint as ckpt_lib

    class Boom(Exception):
        pass

    class BoomSink(JsonlSink):
        def log_metric(self, name, value, step=None):
            if name == "rd_1_validation_accuracy" and step == 5:
                raise Boom()
            super().log_metric(name, value, step=step)

    tcfg = dataclasses.replace(tiny_train_config(), current_ckpt_every=2,
                               device_resident=False)
    data = get_data_synthetic(n_train=96, n_test=32, num_classes=4,
                              image_size=8, seed=5)

    def run(name, rounds, sink_cls, resume=False, log_name=None):
        # The resumed run gets its OWN metrics file (same ckpt_path), so
        # step assertions below can't see the crashed run's events.
        cfg = _cfg(tmp_path, name, rounds=rounds, n_epoch=6,
                   early_stop_patience=10, resume_training=resume,
                   log_dir=str(tmp_path / f"logs_{log_name or name}"))
        sink = sink_cls(cfg.log_dir, experiment_key=name)
        strategy = run_experiment(cfg, sink=sink, data=data, train_cfg=tcfg,
                                  model=TinyClassifier(num_classes=4))
        return cfg, strategy

    # Oracle: uninterrupted 2-round run.
    cfg_full, _ = run("mrfull", 2, JsonlSink)

    # Crash mid-epoch-5 of round 1 (round 0 completed and saved).
    with pytest.raises(Boom):
        run("mrcrash", 2, BoomSink)
    fs = os.path.join(tmp_path / "ckpt_mrcrash", "e2e_mrcrash",
                      "fit_state_rd_1")
    saved = ckpt_lib.load_fit_state(fs, 1)
    assert saved is not None and saved["epoch"] == 4

    # Resume: round 1 continues from epoch 5, not from scratch.
    cfg_res, strategy = run("mrcrash", 2, JsonlSink, resume=True,
                            log_name="mrres")
    steps = []
    for e in _read_metrics(cfg_res.log_dir):
        if e["kind"] == "metric" and "rd_1_validation_accuracy" in e["metrics"]:
            steps.append(e["step"])
    assert min(steps) == 5, steps
    assert strategy.round == 1
    # Completed round cleaned up its fit state.
    assert ckpt_lib.load_fit_state(fs, 1) is None
    # Bit-identical round-1 best weights vs the uninterrupted run.
    va = ckpt_lib.load_variables(os.path.join(
        cfg_full.ckpt_path, "e2e_mrfull", "best_rd_1.msgpack"))
    vb = ckpt_lib.load_variables(os.path.join(
        cfg_res.ckpt_path, "e2e_mrcrash", "best_rd_1.msgpack"))
    jax.tree.map(np.testing.assert_array_equal, va, vb)


def test_resume_skips_completed_rounds(tmp_path):
    cfg = _cfg(tmp_path, "skip", rounds=2)
    strategy_1, _ = _run(cfg, tmp_path, "skip")
    # Re-running with resume_training and the same rounds does nothing new.
    cfg2 = _cfg(tmp_path, "skip", rounds=2, resume_training=True)
    strategy_2, _ = _run(cfg2, tmp_path, "skip")
    np.testing.assert_array_equal(strategy_2.pool.labeled,
                                  strategy_1.pool.labeled)


def test_profile_dir_captures_bounded_round_window(tmp_path):
    """--profile_dir arms the device-truth layer's BOUNDED capture
    (telemetry/profiler.py, DESIGN.md §11): the default warm-round
    window (round 1) produces trace artifacts + the classification
    summary, and round 0 — the compile-tax round — never captures.
    (The pre-ISSUE-11 behavior wrapped the WHOLE run in one trace;
    that multi-hour-capture footgun is gone by design.)"""
    profile_dir = tmp_path / "trace"
    cfg = _cfg(tmp_path, "prof", rounds=2, strategy="RandomSampler",
               profile_dir=str(profile_dir))
    _run(cfg, tmp_path, "prof")
    round1 = profile_dir / "round_1"
    names = [f for _, _, fs in os.walk(round1) for f in fs]
    assert any(f.endswith(".trace.json.gz") or f.endswith(".pb")
               for f in names), names
    assert (round1 / "device_profile_rd1.json").exists()
    summary = json.loads((round1 / "device_profile_rd1.json").read_text())
    assert summary["round"] == 1
    assert summary["device_op_count"] > 0
    # Never round 0 (its trace would answer "how slow is compilation").
    assert not (profile_dir / "round_0").exists()


class TestGenJobs:
    def test_every_job_parses_and_names_registered_components(self):
        """The sweep printer must stay in sync with the CLI flag surface
        and the strategy/arg-pool registries (reference: gen_jobs.py)."""
        from active_learning_tpu.experiment import cli, gen_jobs
        from active_learning_tpu.registry import ARG_POOLS
        from active_learning_tpu.strategies import get_strategy

        jobs = gen_jobs.all_jobs("/data")
        assert len(jobs) == 38  # 9 + 9 + 10 + 10
        parser = cli.get_parser()
        for job in jobs:
            tokens = job.split()
            assert tokens[:3] == ["python", "-m", "active_learning_tpu"]
            ns = parser.parse_args(tokens[3:])
            cfg = cli.args_to_config(ns)
            get_strategy(cfg.strategy)  # raises if unregistered
            ARG_POOLS.get(cfg.arg_pool)

    def test_cli_accepts_every_reference_flag(self):
        """Published commands must translate flag-for-flag: the reference's
        30 argparse flags (src/utils/parser.py:7-92, hard-coded here as the
        stable public interface) all exist on this CLI.  The one deliberate
        exception is --enable_comet, replaced by the JSONL metrics sink
        (metrics on by default; --disable_metrics turns them off)."""
        from active_learning_tpu.experiment import cli

        reference_flags = [
            # parser.py:15-21 (comet/logging)
            "--project_name", "--exp_name", "--log_dir", "--enable_comet",
            # parser.py:24-39 (dataset + imbalance)
            "--dataset", "--dataset_dir", "--arg_pool", "--imbalance_type",
            "--imbalance_factor", "--imbalance_seed",
            # parser.py:42-54 (AL globals)
            "--strategy", "--rounds", "--round_budget", "--freeze_feature",
            "--init_pool_size", "--init_pool_type",
            # parser.py:57-67 (training)
            "--model", "--resume_training", "--exp_hash", "--ckpt_path",
            "--n_epoch", "--early_stop_patience",
            # parser.py:70-79 (debug + partitioning)
            "--debug_mode", "--subset_labeled", "--subset_unlabeled",
            "--partitions",
            # parser.py:82-90 (VAAL)
            "--vae_latent_dim", "--vaal_adversary_param", "--lr_vae",
            "--lr_discriminator",
        ]
        assert len(reference_flags) == 30
        parser = cli.get_parser()
        ours = {opt for a in parser._actions for opt in a.option_strings}
        replaced = {"--enable_comet"}  # -> --disable_metrics
        missing = [f for f in reference_flags
                   if f not in ours and f not in replaced]
        assert not missing, missing
        assert "--disable_metrics" in ours

    def test_download_data_flag_reaches_config(self):
        """--download_data (the reference's implicit torchvision
        download=True) must plumb through to ExperimentConfig."""
        from active_learning_tpu.experiment import cli

        parser = cli.get_parser()
        ns = parser.parse_args(["--dataset", "cifar10", "--download_data"])
        assert cli.args_to_config(ns).download_data is True
        ns = parser.parse_args(["--dataset", "cifar10"])
        assert cli.args_to_config(ns).download_data is False

    def test_resident_scoring_bytes_flag_reaches_trainer(self, tmp_path):
        """--resident_scoring_bytes is a per-chip HBM sizing override: it
        must land on the TrainConfig the trainer and scoring share (the
        default None defers to the arg pool's conservative budget, 0
        disables residency) — asserted on the BUILT experiment, not just
        the parsed config, so dropping the driver's override would fail
        here."""
        from active_learning_tpu.experiment import cli
        from active_learning_tpu.experiment.driver import build_experiment

        parser = cli.get_parser()
        ns = parser.parse_args(["--dataset", "cifar10",
                                "--resident_scoring_bytes", "10000000000"])
        assert cli.args_to_config(ns).resident_scoring_bytes == 10 ** 10
        ns = parser.parse_args(["--dataset", "cifar10"])
        assert cli.args_to_config(ns).resident_scoring_bytes is None
        ns = parser.parse_args(["--dataset", "cifar10",
                                "--resident_scoring_bytes", "0"])
        assert cli.args_to_config(ns).resident_scoring_bytes == 0

        import dataclasses as dc

        from active_learning_tpu.config import ExperimentConfig
        from active_learning_tpu.data.synthetic import get_data_synthetic
        from helpers import tiny_train_config

        for override, want in ((10 ** 10, 10 ** 10), (None, None)):
            cfg = ExperimentConfig(
                dataset="synthetic", strategy="MarginSampler", rounds=1,
                round_budget=4, init_pool_size=4, n_epoch=1,
                exp_hash=f"rsb{override}", enable_metrics=False,
                resident_scoring_bytes=override,
                log_dir=str(tmp_path / "logs"),
                ckpt_path=str(tmp_path / "ck"))
            base = tiny_train_config()
            strategy = build_experiment(
                cfg, data=get_data_synthetic(n_train=16, n_test=8),
                train_cfg=base)
            expect = base.resident_scoring_bytes if want is None else want
            assert strategy.train_cfg.resident_scoring_bytes == expect
            assert (strategy.trainer.cfg.resident_scoring_bytes == expect)

    def test_vaal_adversary_flag_uses_reference_spelling(self):
        """Published VAAL commands use --vaal_adversary_param
        (reference parser.py:84); both that and the short alias must
        reach VAALConfig.adversary_param."""
        from active_learning_tpu.experiment import cli

        parser = cli.get_parser()
        for flag in ("--vaal_adversary_param", "--adversary_param"):
            ns = parser.parse_args(
                ["--dataset", "synthetic", "--strategy", "VAALSampler",
                 flag, "2.5"])
            assert cli.args_to_config(ns).vaal.adversary_param == 2.5


class TestBenchHarness:
    """The benchmark harness's pure helpers (bench.py at the repo root)."""

    def _bench(self):
        import importlib.util
        import os
        path = os.path.join(os.path.dirname(__file__), "..", "bench.py")
        spec = importlib.util.spec_from_file_location("bench", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_parse_child_json_requires_keys(self):
        bench = self._bench()
        out = ('{"note": "stray library json"}\n'
               '{"phase": "p", "ips": 1.0, "ips_per_chip": 1.0}\n'
               '{"also": "stray"}\n')
        got = bench._parse_child_json(out)
        assert got == {"phase": "p", "ips": 1.0, "ips_per_chip": 1.0}
        # With a different required set the scan must skip parseable
        # lines missing the key instead of stopping at them.
        flops = bench._parse_child_json(
            '{"flops_per_image": 7.0}\n{"other": 1}\n',
            required=("flops_per_image",))
        assert flops == {"flops_per_image": 7.0}
        assert bench._parse_child_json("no json here\n{broken\n") is None

    def test_crashed_child_keeps_completed_measurement(self, monkeypatch):
        """A child that printed a complete measurement and then died in a
        later optional pass produced real evidence: the parent must keep
        it (same discipline as the timeout path) instead of burning a
        retry and reporting failure."""
        import types

        bench = self._bench()
        good = ('{"phase": "p", "ips": 5.0, "ips_per_chip": 5.0}\n')

        calls = []

        def fake_run(cmd, **kwargs):
            calls.append(cmd)
            return types.SimpleNamespace(returncode=1, stdout=good,
                                         stderr="boom in optional pass")

        monkeypatch.setattr(bench.subprocess, "run", fake_run)
        result, failure = bench.run_phase_with_retries(
            "p", iters=3, per_chip=8, timeout=30,
            deadline=bench.time.monotonic() + 300)
        assert failure is None
        assert result == {"phase": "p", "ips": 5.0, "ips_per_chip": 5.0}
        assert len(calls) == 1  # no retry burned

        # Without any parseable stdout the crash is a real failure and
        # the retry ladder proceeds.
        def fake_run_bad(cmd, **kwargs):
            calls.append(cmd)
            return types.SimpleNamespace(returncode=1, stdout="",
                                         stderr="hard crash")

        monkeypatch.setattr(bench.subprocess, "run", fake_run_bad)
        result, failure = bench.run_phase_with_retries(
            "p", iters=3, per_chip=8, timeout=30,
            deadline=bench.time.monotonic() + 300, max_attempts=2)
        assert result is None and failure.startswith("exit 1")
        assert len(calls) == 3  # both attempts of the ladder actually ran

    def test_oom_crash_stashes_snapshot_and_still_retries(self,
                                                          monkeypatch):
        """A child that OOMed (RESOURCE_EXHAUSTED) after printing a
        partial measurement must NOT end the ladder: the halved-batch
        retry can recover the measurements the crash cut short.  The
        snapshot is returned only when the retry also fails (ADVICE r5
        #3)."""
        import types

        bench = self._bench()
        partial = '{"phase": "p", "ips": 5.0, "ips_per_chip": 5.0}\n'
        full = ('{"phase": "p", "ips": 4.0, "ips_per_chip": 4.0, '
                '"ips_warm": 9.0}\n')

        calls = []

        def fake_run_retry_wins(cmd, **kwargs):
            calls.append(cmd)
            if len(calls) == 1:
                return types.SimpleNamespace(
                    returncode=1, stdout=partial,
                    stderr="RESOURCE_EXHAUSTED: out of memory")
            return types.SimpleNamespace(returncode=0, stdout=full,
                                         stderr="")

        monkeypatch.setattr(bench.subprocess, "run", fake_run_retry_wins)
        result, failure = bench.run_phase_with_retries(
            "p", iters=30, per_chip=64, timeout=30,
            deadline=bench.time.monotonic() + 300, max_attempts=2)
        assert failure is None and result["ips_warm"] == 9.0
        assert len(calls) == 2  # the retry actually ran
        # ... at half the per-chip batch.
        assert "32" in calls[1][calls[1].index("--per-chip-batch") + 1]

        calls.clear()

        def fake_run_retry_fails(cmd, **kwargs):
            calls.append(cmd)
            if len(calls) == 1:
                return types.SimpleNamespace(
                    returncode=1, stdout=partial,
                    stderr="RESOURCE_EXHAUSTED: out of memory")
            return types.SimpleNamespace(returncode=1, stdout="",
                                         stderr="hard crash")

        monkeypatch.setattr(bench.subprocess, "run", fake_run_retry_fails)
        result, failure = bench.run_phase_with_retries(
            "p", iters=30, per_chip=64, timeout=30,
            deadline=bench.time.monotonic() + 300, max_attempts=2)
        assert failure is None  # the stashed snapshot is the answer
        assert result == {"phase": "p", "ips": 5.0, "ips_per_chip": 5.0}
        assert len(calls) == 2

    @pytest.mark.slow
    def test_al_round_phase_smoke(self, monkeypatch):
        """run_al_round_phase end to end at smoke scale: the phase that
        carries BASELINE.md metric #1 must be known-working BEFORE its
        one chance at a live-TPU capture.  (The imagenet variant differs
        only in its dataset branch — JPEG tree + ImageFolderDataset —
        which test_imagenet_pipeline covers; the full variant is
        CPU-compile-bound, not CI material.)"""
        monkeypatch.setenv("AL_BENCH_ROUND_SMOKE", "1")
        bench = self._bench()
        result = bench.run_al_round_phase("cifar", epochs=2)
        assert result["phase"] == "al_round_cifar"
        assert result["ips"] is None or result["ips"] > 0
        for key in ("round_sec_warm", "round_sec_cold", "total_sec",
                    "test_accuracy_rd1"):
            assert result[key] is not None, key
        rounds = result["phases_sec"]
        for rd in ("round0", "round1"):
            for name in ("query_time", "train_time", "test_time"):
                assert rounds[rd][name] > 0, (rd, name)
        # Warm round must not include round 0's XLA compiles.
        assert result["round_sec_warm"] < result["round_sec_cold"]

    def test_kcenter_phase_tiny(self):
        bench = self._bench()
        result, picks = bench.run_kcenter_phase(8, dim=16, pool_n=128)
        assert result["ips"] > 0 and result["budget"] == 8
        assert result["unit"] == "picks/sec"
        assert result["backend"] in ("xla", "xla-batched")
        assert len(picks) == 8 and len(set(picks.tolist())) == 8


class TestCollapseGuard:
    """The evidence protocol's dead-round guard (VERDICT r5 #3,
    scripts/cifar10_evidence.py): a fit whose BEST validation accuracy
    is at chance re-initializes and retrains, bounded, with retries
    recorded — no headline curve rides through a collapsed round."""

    def _guarded(self, monkeypatch, perf_script):
        """Build a guarded RandomSampler whose base train() is scripted
        to report the next best_perf from ``perf_script`` and count
        calls — collapse behavior without real (re)training."""
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                        "scripts"))
        import cifar10_evidence as ev
        from active_learning_tpu.strategies import get_strategy
        from active_learning_tpu.strategies.base import Strategy

        from helpers import make_strategy

        calls = {"train": 0, "init": 0}
        script = list(perf_script)

        def fake_train(self):
            calls["train"] += 1
            self.best_perf = script.pop(0)

        def fake_init(self):
            calls["init"] += 1

        monkeypatch.setattr(Strategy, "train", fake_train)
        monkeypatch.setattr(Strategy, "init_network_weights", fake_init)
        name = ev._collapse_guarded("RandomSampler")
        assert get_strategy(name) is not None
        strategy = make_strategy(name, init_pool=8)
        return strategy, calls

    def test_collapsed_round_reinits_and_records(self, monkeypatch):
        # chance = 1/4 classes; 0.2 <= 0.25 * 1.25 => collapsed twice,
        # then escapes at 0.9.
        strategy, calls = self._guarded(monkeypatch, [0.2, 0.2, 0.9])
        init_before = calls["init"]
        strategy.train()
        assert calls["train"] == 3
        assert calls["init"] - init_before == 2  # one re-init per retry
        assert strategy.collapse_retries == {0: 2}
        assert strategy.best_perf == 0.9

    def test_healthy_round_untouched(self, monkeypatch):
        strategy, calls = self._guarded(monkeypatch, [0.9])
        strategy.train()
        assert calls["train"] == 1
        assert getattr(strategy, "collapse_retries", {}) == {}

    def test_es0_fit_uses_explicit_eval_not_zero(self, monkeypatch):
        """The evidence protocol runs early_stop_patience=0, which
        DISABLES per-epoch validation (trainer.fit's use_es gate) and
        leaves FitResult.best_perf at 0.0 — the guard must then
        evaluate the final weights explicitly instead of reading the
        0.0 gate value and re-training every healthy round 3x.  Pinned
        mechanically (retries bounded to 0 so a marginal tiny model
        can't make it flaky): after one REAL es=0 fit, the guard's
        best_perf equals the explicit eval-split accuracy of the
        trained state, not 0.0-by-gate."""
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                        "scripts"))
        import cifar10_evidence as ev
        from helpers import make_strategy

        monkeypatch.setattr(ev, "MAX_COLLAPSE_RETRIES", 0)
        name = ev._collapse_guarded("RandomSampler")
        strategy = make_strategy(name, init_pool=32, n_epoch=12)
        strategy.cfg.early_stop_patience = 0  # the protocol's setting
        strategy.train()
        explicit = float(strategy.trainer.evaluate(
            strategy.state, strategy.al_set,
            strategy.pool.eval_idxs)["accuracy"])
        assert strategy.best_perf == explicit
        # At this epoch count the (seeded, deterministic) fit lands
        # strictly above 0 on the eval split, so the equality above is
        # a REAL discrimination from the 0.0 gate value, not 0.0==0.0.
        assert strategy.best_perf > 0.0

    def test_retry_bound_holds(self, monkeypatch):
        # Never escapes chance: exactly MAX_COLLAPSE_RETRIES retries,
        # then give up with the retries on the record.  (3 scripted
        # perfs = 1 try + MAX_COLLAPSE_RETRIES=2 retries.)
        strategy, calls = self._guarded(monkeypatch, [0.2, 0.2, 0.2])
        import cifar10_evidence as ev

        strategy.train()
        assert calls["train"] == ev.MAX_COLLAPSE_RETRIES + 1
        assert strategy.collapse_retries == {0: ev.MAX_COLLAPSE_RETRIES}


def test_resume_refuses_other_model_format(tmp_path):
    """A saved state whose weights predate a model-format bump (e.g. the
    conv padding fix) must fail loudly on resume — shapes still match, so
    without the guard the run would silently diverge."""
    import json

    import pytest

    from active_learning_tpu.experiment import resume as resume_lib

    d = tmp_path / "exp_no_hash"
    d.mkdir(parents=True)
    np.savez(str(d / resume_lib.STATE_FILE)[: -len(".npz")],
             init_key=np.zeros(2, np.uint32))
    (d / resume_lib.META_FILE).write_text(json.dumps(
        {"round": 0, "model_format": 1, "rng_state": {}, "config": {}}))

    cfg = type("Cfg", (), {})()
    cfg.ckpt_path, cfg.exp_name, cfg.exp_hash = str(tmp_path), "exp", None
    with pytest.raises(RuntimeError, match="model format"):
        resume_lib.load_experiment(object(), cfg)


class TestEverySamplerEndToEnd:
    """Every registered strategy drives a full 2-round experiment through
    the real driver — the wiring test (registry -> config plumbing ->
    query/update/train/test) that per-sampler unit tests cannot see."""

    @pytest.mark.parametrize("name", sorted(STRATEGIES.names()))
    def test_runs_and_grows_pool(self, name, tmp_path):
        cfg = _cfg(tmp_path, f"all_{name}", strategy=name, rounds=2,
                   n_epoch=1, early_stop_patience=0, round_budget=8)
        data = get_data_synthetic(n_train=96, n_test=32, num_classes=4,
                                  image_size=16, seed=5)
        sink = JsonlSink(cfg.log_dir, experiment_key=name)
        model = TinyClassifier(num_classes=4)
        strategy = run_experiment(cfg, sink=sink, data=data,
                                  train_cfg=tiny_train_config(), model=model)
        # Init pool (8, = round_budget) + one queried round of 8.
        assert strategy.pool.num_labeled == 16
        picked = strategy.pool.labeled_idxs()
        assert len(np.unique(picked)) == 16


class TestBenchEvidence:
    """bench.py's _finalize evidence assembly — the machinery that turned
    round 3's rc=124/parsed=null into guaranteed output.  Pure-logic
    tests over the module state; no backend is touched."""

    def _bench_with_state(self, phases=None, failures=None, cache=None,
                          probe=None):
        import importlib.util
        import os as os_mod
        path = os_mod.path.join(os_mod.path.dirname(__file__), "..",
                                "bench.py")
        spec = importlib.util.spec_from_file_location("bench_ev", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        import time as time_mod
        mod._STATE.update(start=time_mod.monotonic(), phases=phases or {},
                          failures=failures or {}, cache=cache or {},
                          probe=probe, emitted=False)
        return mod

    def _entry(self, name, **extra):
        return dict({"phase": name, "ips": 100.0, "ips_per_chip": 100.0,
                     "n_chips": 1, "device_kind": "TPU v5 lite",
                     "captured_utc": "2026-01-01T00:00:00Z"}, **extra)

    def test_dead_probe_reuses_cache_unverified(self):
        bench = self._bench_with_state(
            cache={"resnet50_imagenet_train":
                   self._entry("resnet50_imagenet_train")},
            probe={"ok": False, "error": "probe timeout"})
        out = bench._finalize()
        entry = out["phases"]["resnet50_imagenet_train"]
        assert entry["cached"] and entry["device_unverified"]
        assert out["value"] == 100.0
        # Phases with no cache show up as explicit failures naming the
        # dead backend.
        assert "backend unreachable" in \
            out["failed_phases"]["kcenter_select"]

    def test_hw_mismatch_never_resurrects_cache(self):
        bench = self._bench_with_state(
            cache={"resnet50_imagenet_train":
                   self._entry("resnet50_imagenet_train")},
            probe={"ok": True, "device_kind": "TPU v4", "n_devices": 4,
                   "platform": "tpu", "seconds": 5.0})
        out = bench._finalize()
        assert "resnet50_imagenet_train" not in out["phases"]
        assert "TPU v4" in out["failed_phases"]["resnet50_imagenet_train"]
        assert out["value"] is None

    def test_profiled_and_decode_only_never_headline(self):
        bench = self._bench_with_state(phases={
            "resnet50_imagenet_train":
                self._entry("resnet50_imagenet_train", profiled=True),
            "imagenet_datapath":
                self._entry("imagenet_datapath", decode_only=True),
            "resnet18_cifar_train":
                self._entry("resnet18_cifar_train", ips_per_chip=50.0),
        })
        out = bench._finalize()
        assert out["metric"].startswith("resnet18_cifar_train")
        assert out["value"] == 50.0

    def test_emit_final_survives_malformed_cache(self, capsys, tmp_path):
        # A cache entry missing ips_per_chip must degrade the headline to
        # null, never suppress the output line.
        bench = self._bench_with_state(
            cache={"resnet50_imagenet_train": {
                "phase": "resnet50_imagenet_train",
                "device_kind": "TPU v5 lite", "n_chips": 1}},
            probe={"ok": False, "error": "dead"})
        bench.PARTIAL_PATH = str(tmp_path / "partial.json")
        bench.EVIDENCE_PATH = str(tmp_path / "evidence.json")
        bench._emit_final()
        line = capsys.readouterr().out.strip().splitlines()[-1]
        out = json.loads(line)
        assert out["value"] is None
        assert bench._STATE["emitted"]

    def test_headline_skips_rateless_entry(self):
        # ADVICE r4: a malformed entry without ips_per_chip used to win
        # the headline slot, making value None and (with a V100 baseline)
        # crashing the vs_baseline math — which degraded the output to
        # the minimal error line, dropping every phase's evidence.
        bench = self._bench_with_state(phases={
            "resnet50_imagenet_train": {
                "phase": "resnet50_imagenet_train", "n_chips": 1,
                "device_kind": "TPU v5 lite"},  # no ips_per_chip
            "resnet18_cifar_train":
                self._entry("resnet18_cifar_train", ips_per_chip=3600.0),
        })
        out = bench._finalize()
        assert out["metric"].startswith("resnet18_cifar_train")
        assert out["value"] == 3600.0
        assert out["vs_baseline"] == 2.0  # 3600 / the 1800 V100 envelope

    def test_headline_skips_nan_rate(self):
        # A stale cache file can carry a literal NaN (json.load accepts
        # the token): such an entry must not win the headline over a
        # phase holding a real number.
        bench = self._bench_with_state(phases={
            "resnet50_imagenet_train":
                self._entry("resnet50_imagenet_train",
                            ips_per_chip=float("nan")),
            "resnet18_cifar_train":
                self._entry("resnet18_cifar_train", ips_per_chip=1800.0),
        })
        out = bench._finalize()
        assert out["metric"].startswith("resnet18_cifar_train")
        assert out["value"] == 1800.0

    def _full_entry(self, name):
        # The optional fields each phase ACTUALLY produces, all at once —
        # the realistic-maximal line must keep its rich form.  mfu/flops
        # only exist on the 4 model train/score phases (cost_analysis of
        # a jitted step); claiming them on every phase made the fixture
        # ~100 bytes FATTER than any real line can be.
        extra = dict(cached=True, fresh_failure="not attempted",
                     device_unverified=True,
                     batch_per_chip=128, iters=30, platform="tpu")
        if name in ("resnet50_imagenet_train", "resnet18_cifar_train",
                    "resnet50_imagenet_score", "resnet18_cifar_score"):
            extra.update(mfu=0.321, tflops_per_sec_per_chip=77.6,
                         peak_tflops_per_chip=197.0, gflop_per_image=7.97,
                         flops_source="device-cost-analysis")
        if name.endswith("_train"):
            extra.update(feed_source="resident", feed_stall_frac=0.0)
        if name == "imagenet_datapath":
            # Canonical names only: the ips_warm alias and its
            # deprecated_keys shim are gone (kept one release, PR 5).
            extra.update(warm_memmap_ips=9000.1,
                         cold_populate_ips=100.0, decode_ips=1047.8)
        if name == "imagenet_train_feed":
            extra.update(unit="train images/sec (in-fit)",
                         feed_source="resident", feed_stall_frac=0.013,
                         ips_resident=21000.4, ips_host_prefetch=1100.2,
                         ips_host_serial=160.9, resident_x_serial=130.5)
        if name.startswith("al_round"):
            extra.update(round_sec_warm=123.45, round_sec_cold=456.78,
                         test_accuracy_rd1=0.8125,
                         feed_source="resident", feed_stall_frac=0.02,
                         # The pipelined round's riders (ISSUE 7) and
                         # the failure model's counters (ISSUE 8) both
                         # ride every end-to-end round phase.
                         round_pipeline="speculative", overlap_frac=0.389,
                         round_vs_max_phase=1.18, spec_hit_frac=0.33,
                         fault_retries_total=12, degrade_events=3,
                         phases_sec={"round0": {"train_time": 100.0}})
        if name.startswith("kcenter_select"):
            # Every selection phase now attributes its pool layout
            # alongside the scan backend (ISSUE 6).
            extra.update(unit="picks/sec", backend="xla-batched",
                         pool_sharding="row")
        if name == "kcenter_select_maxn":
            # The sharded-pool probe's extra evidence: the row-vs-
            # replicated ceiling comparison (file-only; pool_sharding
            # is the field that rides the line).
            extra.update(max_n=2_560_000, replicated_max_n=1_280_000,
                         row_scale_x=2.0)
        if name == "serve_throughput":
            extra.update(unit="scored images/sec (served)",
                         qps_closed=137.2, p99_ms_closed=25.0,
                         request_path_compiles=0,
                         batch_occupancy={"8": {"4": 64, "8": 236}})
        if name == "stream_round":
            # The streaming phase's line riders (ISSUE 14) plus its
            # file-only figures — absent from this fixture until ISSUE
            # 16 made the maximal pin actually cover the margin math.
            extra.update(unit="ingested rows/sec (acked)",
                         ack_p99_ms=142.375, trigger_cause="watermark",
                         ingest_qps=250.1, ack_p50_ms=2.8,
                         pool_rows_final=6304)
        if name == "disk_pool_feed":
            # The disk tier (ISSUE 16): hit fraction + stall tail ride
            # the line; the rest is evidence-file-only.
            extra.update(unit="train images/sec (disk-backed pool)",
                         cache_hit_frac=0.982, page_stall_ms_p99=41.75,
                         page_stall_ms_p50=3.2,
                         page_in_rows_per_sec=51200.5,
                         pool_disk_rows=50000, pool_over_budget_x=4.0,
                         ips_memory=4100.2, disk_vs_memory=0.873,
                         picks_identical=True)
        if name == "fleet_smoke":
            # The fleet tier (ISSUE 18): runs finished / resumed and
            # the fleet wall ride the line; the attempt/kill detail is
            # evidence-file-only.
            extra.update(unit="runs finished/min (2-worker localhost "
                              "fleet)",
                         runs_finished=2, runs_failed=0, runs_resumed=1,
                         attempts_total=3,
                         killed_run="MarginSampler-synthetic-8-0-abcd1234",
                         merged_prom_runs=2, comparison_rendered=True,
                         total_sec=131.5, workers=2)
        return self._entry(name, **extra)

    def test_compact_line_bounded_all_phases_full(self, capsys, tmp_path):
        """Worst realistic case — every phase present with every optional
        field it produces — must fit the driver's tail window in RICH
        form, and the full evidence must land in the file the line
        references."""
        phases = {name: self._full_entry(name)
                  for name, _, _, _ in
                  self._bench_with_state().PHASES}
        bench = self._bench_with_state(
            phases=phases,
            probe={"ok": True, "device_kind": "TPU v5 lite",
                   "n_devices": 1, "platform": "tpu", "seconds": 5.0})
        bench.PARTIAL_PATH = str(tmp_path / "partial.json")
        bench.EVIDENCE_PATH = str(tmp_path / "evidence.json")
        bench._emit_final(extra={"error": "x" * 400})
        line = capsys.readouterr().out.strip().splitlines()[-1]
        assert len(line.encode()) <= bench.MAX_LINE_BYTES
        out = json.loads(line)
        assert out["evidence"] == bench.EVIDENCE_PATH
        assert out["phases"]["resnet50_imagenet_train"]["ips"] == 100.0
        assert out["phases"]["al_round_cifar"]["warm_s"] == 123.45
        assert out["phases"]["al_round_cifar"]["retries"] == 12
        assert out["phases"]["al_round_cifar"]["degraded"] == 3
        assert out["phases"]["imagenet_datapath"]["warm_ips"] == 9000.1
        # The disk tier's riders (ISSUE 16) ride in rich form alongside
        # everything above — the 15-phase maximal line still fits.
        assert out["phases"]["disk_pool_feed"]["hit"] == 0.982
        assert out["phases"]["disk_pool_feed"]["stall_ms"] == 41.75
        assert "disk_vs_memory" not in out["phases"]["disk_pool_feed"]
        assert out["phases"]["stream_round"]["ack_p99"] == 142.375
        # The fleet tier's riders (ISSUE 18) — the 16-phase maximal
        # line still fits the tail window.
        assert out["phases"]["fleet_smoke"]["runs"] == 2
        assert out["phases"]["fleet_smoke"]["resumed"] == 1
        assert out["phases"]["fleet_smoke"]["wall_s"] == 131.5
        assert "killed_run" not in out["phases"]["fleet_smoke"]
        # The file carries what the line dropped.
        with open(bench.EVIDENCE_PATH) as fh:
            full = json.load(fh)
        assert full["phases"]["resnet50_imagenet_train"][
            "tflops_per_sec_per_chip"] == 77.6

    def test_compact_line_bounded_all_phases_failed(self, capsys, tmp_path):
        """Opposite extreme — nothing captured, every phase failing with a
        long message — must also fit and stay strictly parseable."""
        failures = {name: "e" * 500 for name, _, _, _ in
                    self._bench_with_state().PHASES}
        bench = self._bench_with_state(
            failures=failures, probe={"ok": False, "error": "p" * 300})
        bench.PARTIAL_PATH = str(tmp_path / "partial.json")
        bench.EVIDENCE_PATH = str(tmp_path / "evidence.json")
        bench._emit_final()
        line = capsys.readouterr().out.strip().splitlines()[-1]
        assert len(line.encode()) <= bench.MAX_LINE_BYTES
        out = json.loads(line)
        assert out["value"] is None and not out["probe_ok"]
        assert "al_round_cifar" in out["failed"]

    def test_compact_line_degrades_on_adversarial_bloat(self, tmp_path):
        """Even an impossible shape — every phase carrying every optional
        field at once — stays under the bound via staged truncation."""
        bench = self._bench_with_state()
        entry = self._entry(
            "x", mfu=0.3, unit="picks/sec", cached=True, ips_warm=1.0,
            round_sec_warm=1.0, round_sec_cold=2.0, test_accuracy_rd1=0.5,
            qps_closed=137.2, p99_ms_closed=25.0, request_path_compiles=0,
            backend="xla-batched")
        out = {
            "metric": "m" * 60, "value": 1.0, "unit": "u",
            "vs_baseline": 1.0, "backend_probe": {"ok": True},
            "elapsed_sec": 1.0, "error": "e" * 1000,
            "phases": {f"phase_{i:02d}_{'n' * 20}": dict(entry)
                       for i in range(12)},
            "failed_phases": {f"fail_{i:02d}": "f" * 500
                              for i in range(12)},
        }
        line = bench._compact_line(out)
        assert len(line.encode()) <= bench.MAX_LINE_BYTES
        parsed = json.loads(line)
        assert parsed["evidence"] == bench.EVIDENCE_PATH

    def test_finalize_crash_keeps_partial_and_recovers_it(self, capsys,
                                                          tmp_path):
        """A finalize crash at emit time must not clobber the last good
        per-phase snapshot — it is recovered as the evidence body with
        the error attached, and the partial mirror is left alone."""
        bench = self._bench_with_state(
            # A non-dict cache entry makes _finalize's dict(entry, ...)
            # raise — the malformed-cache crash class.
            cache={"resnet50_imagenet_train": "corrupt"},
            probe={"ok": False, "error": "dead"})
        bench.PARTIAL_PATH = str(tmp_path / "partial.json")
        bench.EVIDENCE_PATH = str(tmp_path / "evidence.json")
        bench._STATE["run_id"] = "this-run"
        good = {"phases": {"resnet18_cifar_train":
                           self._entry("resnet18_cifar_train")},
                "partial": True, "run_id": "this-run"}
        with open(bench.PARTIAL_PATH, "w") as fh:
            json.dump(good, fh)
        bench._emit_final()
        line = capsys.readouterr().out.strip().splitlines()[-1]
        out = json.loads(line)
        assert "error" in out
        # The snapshot survived in BOTH files.
        with open(bench.PARTIAL_PATH) as fh:
            assert json.load(fh) == good
        with open(bench.EVIDENCE_PATH) as fh:
            ev = json.load(fh)
        assert ev["phases"]["resnet18_cifar_train"]["ips"] == 100.0
        assert "finalize failed" in ev["error"]

    def test_finalize_crash_never_adopts_other_runs_partial(self, capsys,
                                                            tmp_path):
        """A PREVIOUS run's snapshot (different run_id) must not be
        presented as this run's evidence."""
        bench = self._bench_with_state(
            cache={"resnet50_imagenet_train": "corrupt"},
            probe={"ok": False, "error": "dead"})
        bench.PARTIAL_PATH = str(tmp_path / "partial.json")
        bench.EVIDENCE_PATH = str(tmp_path / "evidence.json")
        bench._STATE["run_id"] = "this-run"
        stale = {"phases": {"resnet18_cifar_train":
                            self._entry("resnet18_cifar_train")},
                 "partial": True, "run_id": "previous-run", "value": 100.0}
        with open(bench.PARTIAL_PATH, "w") as fh:
            json.dump(stale, fh)
        bench._emit_final()
        line = capsys.readouterr().out.strip().splitlines()[-1]
        out = json.loads(line)
        assert out["value"] is None and "error" in out
        with open(bench.EVIDENCE_PATH) as fh:
            ev = json.load(fh)
        assert "phases" not in ev  # the minimal dict, not the stale one
        with open(bench.PARTIAL_PATH) as fh:
            assert json.load(fh) == stale  # and the stale file untouched

    def test_failed_evidence_write_nulls_the_path(self, capsys, tmp_path):
        """If the evidence file cannot be written, the line must not point
        at a stale previous file."""
        bench = self._bench_with_state(
            phases={"resnet18_cifar_train":
                    self._entry("resnet18_cifar_train")})
        bench.PARTIAL_PATH = str(tmp_path / "partial.json")
        bench.EVIDENCE_PATH = str(tmp_path / "no_such_dir" / "evidence.json")
        bench._emit_final()
        line = capsys.readouterr().out.strip().splitlines()[-1]
        out = json.loads(line)
        assert out["evidence"] is None
        assert out["value"] == 100.0  # the line itself still carries data

    def test_nan_never_serialized(self, capsys, tmp_path):
        """ADVICE r4: a NaN rate must serialize as null — the bare `NaN`
        token is non-standard JSON and strict parsers reject the line."""
        bench = self._bench_with_state(phases={
            "resnet18_cifar_train":
                self._entry("resnet18_cifar_train",
                            ips=float("nan"), ips_per_chip=float("nan"),
                            mfu=float("inf"))})
        bench.PARTIAL_PATH = str(tmp_path / "partial.json")
        bench.EVIDENCE_PATH = str(tmp_path / "evidence.json")
        bench._emit_final()
        line = capsys.readouterr().out.strip().splitlines()[-1]
        assert "NaN" not in line and "Infinity" not in line

        def reject(_):
            raise AssertionError("non-standard JSON constant in line")

        out = json.loads(line, parse_constant=reject)
        assert out["phases"]["resnet18_cifar_train"]["ips"] is None
        with open(bench.EVIDENCE_PATH) as fh:
            json.load(fh, parse_constant=reject)
