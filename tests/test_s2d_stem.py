"""Space-to-depth stem: exact equivalence with the baseline 7x7/s2 stem,
weight-transform round-trip, s2d view transforms, and the resident-budget
auto-sizing that makes pool residency default behavior.

The s2d fold (models/resnet.s2d_stem_kernel) is pure re-indexing — every
product of the 7x7 convolution appears exactly once — so it is exact in
exact arithmetic.  XLA's conv lowering may SUM those products in a
different order for the two shapes, so float32 logits agree to
reduction-order rounding (pinned tight here) and a float64 run pins the
identity itself to ~1e-12.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import linen as nn
from flax.traverse_util import flatten_dict, unflatten_dict

from active_learning_tpu.data.augment import apply_view, s2d_flip
from active_learning_tpu.data.core import IMAGENET_NORM, ViewSpec
from active_learning_tpu.data import pipeline
from active_learning_tpu.models import resnet
from active_learning_tpu.models.factory import (get_network,
                                                resolve_bn_stats_dtype)
from active_learning_tpu.parallel import resident


def _s2d_variables_from_baseline(variables):
    """Copy a baseline-stem variable tree, folding conv_stem 7x7 -> 4x4."""
    flat = flatten_dict(jax.tree.map(np.asarray, variables))
    out = {}
    for path, leaf in flat.items():
        if path[-2:] == ("conv_stem", "kernel") and leaf.shape[:2] == (7, 7):
            leaf = np.asarray(resnet.s2d_stem_kernel(leaf))
        out[path] = leaf
    return unflatten_dict(out)


class TestS2DEquivalence:
    def _models(self, dtype=jnp.float32):
        base = resnet.resnet50(num_classes=12, dtype=dtype)
        s2d = resnet.resnet50(num_classes=12, dtype=dtype, stem="s2d")
        return base, s2d

    def test_logits_match_baseline_stem_f32(self):
        """Baseline-stem vs s2d-stem ResNet-50 logits on random input,
        float32, identical (transformed) weights — agreement to
        reduction-order rounding."""
        base, s2d = self._models()
        rng = np.random.default_rng(0)
        x = rng.integers(0, 256, size=(2, 64, 64, 3), dtype=np.uint8)
        xf = jnp.asarray(x, jnp.float32)
        variables = base.init(jax.random.PRNGKey(0), xf, train=False)
        variables_s2d = _s2d_variables_from_baseline(variables)
        y_base = np.asarray(base.apply(variables, xf, train=False))
        y_s2d = np.asarray(s2d.apply(variables_s2d, xf, train=False))
        np.testing.assert_allclose(y_s2d, y_base, rtol=2e-5, atol=2e-5)
        # Host-side pre-transformed input must land in the same place.
        x12 = jnp.asarray(pipeline.space_to_depth(x), jnp.float32)
        y_host = np.asarray(s2d.apply(variables_s2d, x12, train=False))
        np.testing.assert_array_equal(y_host, y_s2d)

    def test_stem_conv_identity_is_exact_in_f64(self):
        """The fold itself is exact: in float64 the two stems agree to
        accumulated-rounding noise (~1e-12), proving the f32 delta above
        is summation order, not an algebraic error."""
        with jax.experimental.enable_x64():
            rng = np.random.default_rng(1)
            x = jnp.asarray(rng.normal(size=(1, 32, 32, 3)))
            k7 = jnp.asarray(rng.normal(size=(7, 7, 3, 16)))
            y7 = nn.Conv(16, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
                         use_bias=False).apply(
                             {"params": {"kernel": k7}}, x)
            y4 = nn.Conv(16, (4, 4), (1, 1), padding=[(2, 1), (2, 1)],
                         use_bias=False).apply(
                             {"params": {"kernel": resnet.s2d_stem_kernel(
                                 k7)}}, resnet.space_to_depth(x))
            np.testing.assert_allclose(np.asarray(y4), np.asarray(y7),
                                       rtol=1e-10, atol=1e-10)

    def test_weight_transform_round_trip(self):
        rng = np.random.default_rng(2)
        k7 = rng.normal(size=(7, 7, 3, 64)).astype(np.float32)
        k4 = np.asarray(resnet.s2d_stem_kernel(k7))
        assert k4.shape == (4, 4, 12, 64)
        np.testing.assert_array_equal(
            np.asarray(resnet.stem_kernel_from_s2d(k4)), k7)
        # The pad row/col the fold introduces is structurally zero.
        assert float(np.abs(k4).sum()) == pytest.approx(
            float(np.abs(k7).sum()))

    def test_host_and_device_s2d_agree(self):
        rng = np.random.default_rng(3)
        x = rng.integers(0, 256, size=(3, 8, 8, 3), dtype=np.uint8)
        np.testing.assert_array_equal(
            pipeline.space_to_depth(x),
            np.asarray(resnet.space_to_depth(jnp.asarray(x))))

    def test_s2d_flip_commutes_with_space_to_depth(self):
        rng = np.random.default_rng(4)
        x = rng.integers(0, 256, size=(4, 8, 8, 3), dtype=np.uint8)
        flip = jnp.asarray([True, False, True, False])
        flipped = np.where(np.asarray(flip)[:, None, None, None],
                           x[:, :, ::-1, :], x)
        np.testing.assert_array_equal(
            np.asarray(s2d_flip(jnp.asarray(pipeline.space_to_depth(x)),
                                flip)),
            pipeline.space_to_depth(flipped))

    def test_apply_view_s2d_matches_baseline_view(self):
        """The full train view (flip + normalize) over an s2d batch equals
        space-to-depth of the baseline view's output, key-for-key."""
        rng = np.random.default_rng(5)
        x = rng.integers(0, 256, size=(4, 8, 8, 3), dtype=np.uint8)
        view = ViewSpec(IMAGENET_NORM, augment=True, pad=0)
        key = jax.random.PRNGKey(7)
        y_base = np.asarray(apply_view(jnp.asarray(x), view, key=key,
                                       train=True))
        y_s2d = np.asarray(apply_view(
            jnp.asarray(pipeline.space_to_depth(x)), view, key=key,
            train=True))
        b, h, w, c = y_base.shape
        y_base_s2d = y_base.reshape(b, h // 2, 2, w // 2, 2, c).transpose(
            0, 1, 3, 2, 4, 5).reshape(b, h // 2, w // 2, 4 * c)
        np.testing.assert_allclose(y_s2d, y_base_s2d, rtol=1e-6, atol=1e-6)

    def test_factory_guards(self):
        with pytest.raises(ValueError):
            resnet.resnet50(num_classes=10, cifar_stem=True, stem="s2d")
        # Factory-level: a global --stem s2d quietly keeps the CIFAR stem.
        m = get_network("cifar10", "SSLResNet18", stem="s2d")
        assert m.stem == "default"
        m = get_network("imagenet", "SSLResNet50", stem="s2d")
        assert m.stem == "s2d"


class TestFusedBatchNorm:
    def test_matches_flax_batchnorm(self):
        """Train-mode stats, running-stat EMA, and eval-mode output agree
        with nn.BatchNorm within bf16-read rounding."""
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(16, 4, 4, 8)).astype(np.float32))
        ref = nn.BatchNorm(momentum=0.9, epsilon=1e-5)
        fused = resnet.FusedBatchNorm(momentum=0.9, epsilon=1e-5)
        vr = ref.init(jax.random.PRNGKey(0), x, use_running_average=False)
        vf = fused.init(jax.random.PRNGKey(0), x,
                        use_running_average=False)
        yr, mr = ref.apply(vr, x, use_running_average=False,
                           mutable=["batch_stats"])
        yf, mf = fused.apply(vf, x, use_running_average=False,
                             mutable=["batch_stats"])
        np.testing.assert_allclose(np.asarray(yf), np.asarray(yr),
                                   rtol=1e-5, atol=1e-5)
        jax.tree.map(lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
            mf["batch_stats"], mr["batch_stats"])
        # Eval mode from the updated stats.
        ye = ref.apply({"params": vr["params"], **mr},
                       x, use_running_average=True)
        yfe = fused.apply({"params": vf["params"], **mf},
                          x, use_running_average=True)
        np.testing.assert_allclose(np.asarray(yfe), np.asarray(ye),
                                   rtol=1e-5, atol=1e-5)

    def test_resolution_follows_compute_dtype(self):
        assert resolve_bn_stats_dtype("auto", jnp.bfloat16) == jnp.bfloat16
        assert resolve_bn_stats_dtype("auto", jnp.float32) is None
        assert resolve_bn_stats_dtype("float32", jnp.bfloat16) is None
        assert resolve_bn_stats_dtype("bfloat16",
                                      jnp.bfloat16) == jnp.bfloat16

    def test_variable_tree_structure_matches_flax_path(self):
        """Checkpoints interop across stats modes: the fused-stats model
        must produce the exact variable tree of the flax-BN model (the
        FusedBatchNorm class advertises the BatchNorm auto-name)."""
        x = jnp.zeros((2, 16, 16, 3), jnp.float32)
        v_f = resnet.resnet18(num_classes=12).init(
            jax.random.PRNGKey(0), x, train=False)
        v_b = resnet.resnet18(
            num_classes=12, dtype=jnp.bfloat16,
            bn_stats_dtype=jnp.bfloat16).init(
                jax.random.PRNGKey(0), x, train=False)
        assert jax.tree_util.tree_structure(v_f) \
            == jax.tree_util.tree_structure(v_b)

    def test_bf16_model_uses_fused_stats_and_keeps_f32_state(self):
        m = resnet.resnet18(num_classes=12, dtype=jnp.bfloat16,
                            bn_stats_dtype=jnp.bfloat16)
        x = jnp.zeros((2, 16, 16, 3), jnp.float32)
        variables = m.init(jax.random.PRNGKey(0), x, train=False)
        stats = jax.tree.leaves(variables["batch_stats"])
        assert stats and all(s.dtype == jnp.float32 for s in stats)
        logits, mut = m.apply(variables, x, train=True,
                              mutable=["batch_stats"])
        assert logits.dtype == jnp.float32
        assert all(s.dtype == jnp.float32
                   for s in jax.tree.leaves(mut["batch_stats"]))


class TestDevicePrefetch:
    """The async double-buffered feed behind the residency fallback."""

    def test_order_preserved_and_put_applied(self):
        from active_learning_tpu.data.cache import device_prefetch
        got = list(device_prefetch(iter(range(20)), lambda x: x * 10,
                                   depth=2))
        assert got == [x * 10 for x in range(20)]

    def test_feeder_errors_reraise_at_consumer(self):
        from active_learning_tpu.data.cache import device_prefetch

        def batches():
            yield 1
            raise RuntimeError("decode failed")

        it = device_prefetch(batches(), lambda x: x)
        assert next(it) == 1
        with pytest.raises(RuntimeError, match="decode failed"):
            list(it)

    def test_abandoned_generator_joins_feeder(self):
        import threading

        from active_learning_tpu.data.cache import device_prefetch
        before = threading.active_count()
        it = device_prefetch(iter(range(1000)), lambda x: x, depth=2)
        assert next(it) == 0
        it.close()  # consumer walks away mid-stream
        assert threading.active_count() <= before + 1

    def test_collect_pool_host_path_uses_prefetch_and_aligns(self):
        """End to end through collect_pool's host path (resident cache
        disabled): results aligned with idxs, s2d host batches accepted."""
        from active_learning_tpu.data.synthetic import get_data_synthetic
        from active_learning_tpu.parallel import mesh as mesh_lib
        from active_learning_tpu.strategies import scoring

        _, _, al_set = get_data_synthetic(n_train=48, n_test=8,
                                          image_size=8)
        mesh = mesh_lib.make_mesh()

        def step(variables, batch):
            assert batch["image"].shape[-1] == 12  # host s2d applied
            return {"m": jnp.sum(batch["image"].astype(jnp.float32),
                                 axis=(1, 2, 3))}

        idxs = np.arange(40)
        out = scoring.collect_pool(al_set, idxs, 16, step, {}, mesh,
                                   host_s2d=True)
        expect = al_set.gather(idxs).astype(np.float32).sum(axis=(1, 2, 3))
        np.testing.assert_allclose(out["m"], expect, rtol=1e-6)


class TestResidentBudgetAutoSizing:
    """resolve_budget/auto_budget: pool residency as default behavior."""

    def test_pool_fits_headroom(self):
        stats = {"bytes_limit": 16 << 30, "bytes_in_use": 2 << 30}
        budget = resident.auto_budget(stats=stats)
        assert budget == (16 << 30) - (2 << 30) - resident.AUTO_RESERVE_BYTES
        # A 7.5 GB decoded pool fits this headroom -> resident by default.
        assert budget >= int(7.5 * 2 ** 30)

    def test_pool_does_not_fit(self):
        """Headroom minus the activation reserve can go to zero — the
        budget floors at 0 (prefetch fallback), never negative."""
        stats = {"bytes_limit": 8 << 30, "bytes_in_use": 5 << 30}
        assert resident.auto_budget(stats=stats) == 0

    def test_headroom_minus_activation_reserve(self):
        stats = {"bytes_limit": 16 << 30, "bytes_in_use": 0}
        assert resident.auto_budget(reserve_bytes=6 << 30, stats=stats) \
            == (16 << 30) - (6 << 30)

    def test_no_memory_stats_falls_back_to_static_default(self):
        from active_learning_tpu.config import RESIDENT_SCORING_BYTES_DEFAULT
        assert resident.auto_budget(stats={}) \
            == RESIDENT_SCORING_BYTES_DEFAULT

    def test_resolve_budget_explicit_and_auto(self):
        assert resident.resolve_budget(0) == 0
        assert resident.resolve_budget(123) == 123
        stats = {"bytes_limit": 16 << 30, "bytes_in_use": 2 << 30}
        assert resident.resolve_budget(None, stats=stats) \
            == resident.auto_budget(stats=stats)

    def test_cached_pool_survives_budget_shrink(self):
        """A pool uploaded under a generous budget keeps its resident
        fast path after a refresh shrinks the budget below its size."""
        from active_learning_tpu.data.synthetic import get_data_synthetic
        from active_learning_tpu.parallel import mesh as mesh_lib
        _, _, al_set = get_data_synthetic(n_train=32, n_test=8,
                                          image_size=8)
        mesh = mesh_lib.make_mesh()
        cache = {}
        assert not resident.cached(cache, al_set)
        resident.pool_arrays(cache, al_set, mesh)
        assert resident.cached(cache, al_set)
        assert not resident.eligible(al_set, 0)  # budget shrank to zero
        # collect_pool's gate is eligible(...) OR cached(...): still fast.
