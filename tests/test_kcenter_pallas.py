"""The fused Pallas k-center kernel vs the plain jnp expressions
(interpret mode — same semantics as the compiled TPU kernel), plus the
backend dispatcher's contract."""

import numpy as np
import pytest

import jax.numpy as jnp

from active_learning_tpu.ops import kcenter_pallas as kp
from active_learning_tpu.strategies import kcenter as kc


def _setup(n, d, seed=0, n_inf_min=False):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    xt = kp.pad_to_tiles(jnp.asarray(x))
    n_pad = xt.shape[1]
    sqn = np.zeros((1, n_pad), np.float32)
    sqn[0, :n] = (x * x).sum(axis=1)
    min_dist = np.zeros((1, n_pad), np.float32)
    min_dist[0, :n] = (np.full(n, np.inf, np.float32) if n_inf_min
                       else rng.uniform(0.1, 50.0, size=n).astype(np.float32))
    sel = np.zeros((1, n_pad), np.float32)
    sel[0, :n] = (rng.uniform(size=n) > 0.1).astype(np.float32)
    return x, xt, sqn, min_dist, sel


@pytest.mark.parametrize("n,d", [(512, 512), (1024, 1024), (1536, 512)])
def test_fused_update_matches_jnp(n, d):
    x, xt, sqn, min_dist, sel = _setup(n, d)
    for centers in ([0] * kp.CENTER_TILE,
                    [7, n - 1, 3, 7, 7, 7, 7, 7],
                    list(range(kp.CENTER_TILE))):
        got, _, _ = kp.fused_update_argmax(
            xt, jnp.asarray(sqn), jnp.asarray(min_dist), jnp.asarray(sel),
            jnp.asarray(centers, jnp.int32), interpret=True)
        want = min_dist[0, :n].copy()
        for c in set(centers):
            want = np.minimum(want,
                              sqn[0, :n] + sqn[0, c] - 2.0 * (x @ x[c]))
        np.testing.assert_allclose(np.asarray(got)[0, :n], want,
                                   rtol=1e-5, atol=1e-3)


def test_fused_argmax_matches_masked_argmax():
    n, d = 1536, 512
    x, xt, sqn, min_dist, sel = _setup(n, d, seed=3)
    centers = jnp.asarray([11, 400, 900, 11, 11, 11, 11, 11], jnp.int32)
    new_min, bmax, barg = kp.fused_update_argmax(
        xt, jnp.asarray(sqn), jnp.asarray(min_dist), jnp.asarray(sel),
        centers, interpret=True)
    # The scan's global reduction: first block holding the max, lowest
    # lane within it — must equal jnp.argmax over the masked row.
    pick = int(np.asarray(barg)[0, np.argmax(np.asarray(bmax)[0])])
    masked = np.where(np.asarray(sel)[0] > 0, np.asarray(new_min)[0],
                      -np.inf)
    assert pick == int(np.argmax(masked))


def test_padded_tiles_roundtrip():
    rng = np.random.default_rng(1)
    n, d = 700, 300  # neither a tile multiple
    x = rng.normal(size=(n, d)).astype(np.float32)
    xt = kp.pad_to_tiles(jnp.asarray(x))
    assert xt.shape == (512, 1024)
    sqn = np.zeros((1, 1024), np.float32)
    sqn[0, :n] = (x * x).sum(axis=1)
    min_dist = np.full((1, 1024), np.inf, np.float32)
    min_dist[0, :n] = rng.uniform(1.0, 9.0, size=n).astype(np.float32)
    sel = np.zeros((1, 1024), np.float32)
    sel[0, :n] = 1.0
    idx = 3
    got, _, _ = kp.fused_update_argmax(
        xt, jnp.asarray(sqn), jnp.asarray(min_dist), jnp.asarray(sel),
        jnp.full((kp.CENTER_TILE,), idx, jnp.int32), interpret=True)
    want = np.minimum(min_dist[0, :n],
                      sqn[0, :n] + sqn[0, idx] - 2.0 * (x @ x[idx]))
    np.testing.assert_allclose(np.asarray(got)[0, :n], want,
                               rtol=1e-5, atol=1e-3)


def test_pad_centers():
    idxs = jnp.asarray([5, 9, 2], jnp.int32)
    padded = kp.pad_centers(idxs)
    assert padded.shape[0] % kp.CENTER_TILE == 0
    np.testing.assert_array_equal(np.asarray(padded)[:3], [5, 9, 2])
    assert set(np.asarray(padded)[3:].tolist()) == {5}


@pytest.mark.parametrize("batch_q", [1, 8])
def test_kcenter_greedy_pallas_matches_xla(monkeypatch, batch_q):
    """The full greedy selection with the fused Pallas kernel (interpret
    mode) picks the same points in the same order as the XLA scan — for
    both the q=1 fused update+argmax scan and the batched path."""
    from active_learning_tpu.strategies.kcenter import kcenter_greedy

    rng = np.random.default_rng(7)
    x = rng.normal(size=(600, 96)).astype(np.float32)
    labeled = np.zeros(600, dtype=bool)
    labeled[rng.choice(600, 40, replace=False)] = True

    monkeypatch.delenv("AL_TPU_KCENTER_PALLAS", raising=False)
    want = kcenter_greedy([x], labeled, 25, rng=np.random.default_rng(0),
                          batch_q=batch_q)
    monkeypatch.setenv("AL_TPU_KCENTER_PALLAS", "interpret")
    got = kcenter_greedy([x], labeled, 25, rng=np.random.default_rng(0),
                         batch_q=batch_q)
    assert kp.LAST_BACKEND == "pallas-interpret"
    np.testing.assert_array_equal(got, want)


def test_dispatcher_contract(monkeypatch):
    """Auto dispatch must fall back to XLA everywhere the kernel has no
    measured win: off-TPU, randomized, multi-factor, small pools, q < a
    center tile.  Explicit modes override."""
    monkeypatch.delenv("AL_TPU_KCENTER_PALLAS", raising=False)
    # Off-TPU (this CI runs on CPU): always XLA, even at winning shapes.
    assert kc._select_backend(65536, 2048, 1, False, 8) == "xla"
    assert kc._select_backend(65536, 2048, 2, False, 8) == "xla"
    assert kc._select_backend(65536, 2048, 1, True, 8) == "xla"
    monkeypatch.setenv("AL_TPU_KCENTER_PALLAS", "1")
    assert kc._select_backend(65536, 2048, 1, False, 8) == "pallas"
    # Multi-factor / randomized never take the kernel, even forced.
    assert kc._select_backend(65536, 2048, 2, False, 8) == "xla"
    assert kc._select_backend(65536, 2048, 1, True, 8) == "xla"
    monkeypatch.setenv("AL_TPU_KCENTER_PALLAS", "0")
    assert kc._select_backend(65536, 2048, 1, False, 8) == "xla"
    monkeypatch.setenv("AL_TPU_KCENTER_PALLAS", "interpret")
    assert kc._select_backend(256, 96, 1, False, 8) == "pallas-interpret"
