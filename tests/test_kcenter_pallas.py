"""The Pallas k-center distance-update kernel vs the plain jnp expression
(interpret mode — same semantics as the compiled TPU kernel)."""

import numpy as np
import pytest

import jax.numpy as jnp

from active_learning_tpu.ops import kcenter_pallas as kp


@pytest.mark.parametrize("n,d", [(512, 512), (1024, 1024), (1536, 512)])
def test_matches_jnp_update(n, d):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, d)).astype(np.float32)
    xt = kp.pad_to_tiles(jnp.asarray(x))
    sqn = (x * x).sum(axis=1)[None, :]
    min_dist = rng.uniform(0.1, 50.0, size=(1, n)).astype(np.float32)
    for idx in (0, 7, n - 1):
        want = np.minimum(
            min_dist[0], sqn[0] + sqn[0, idx] - 2.0 * (x @ x[idx]))
        got = kp.min_dist_update(xt, jnp.asarray(sqn),
                                 jnp.asarray(min_dist),
                                 jnp.int32(idx), interpret=True)
        np.testing.assert_allclose(np.asarray(got)[0], want,
                                   rtol=1e-5, atol=1e-3)


def test_padded_tiles_roundtrip():
    rng = np.random.default_rng(1)
    n, d = 700, 300  # neither a tile multiple
    x = rng.normal(size=(n, d)).astype(np.float32)
    xt = kp.pad_to_tiles(jnp.asarray(x))
    assert xt.shape == (512, 1024)
    sqn_real = (x * x).sum(axis=1)
    sqn = np.zeros((1, xt.shape[1]), np.float32)
    sqn[0, :n] = sqn_real
    min_dist = np.full((1, xt.shape[1]), np.inf, np.float32)
    min_dist[0, :n] = rng.uniform(1.0, 9.0, size=n).astype(np.float32)
    idx = 3
    got = kp.min_dist_update(xt, jnp.asarray(sqn), jnp.asarray(min_dist),
                             jnp.int32(idx), interpret=True)
    want = np.minimum(min_dist[0, :n],
                      sqn_real + sqn_real[idx] - 2.0 * (x @ x[idx]))
    np.testing.assert_allclose(np.asarray(got)[0, :n], want,
                               rtol=1e-5, atol=1e-3)


def test_kcenter_greedy_pallas_matches_xla(monkeypatch):
    """The full greedy selection with the Pallas update (interpret mode)
    picks the same points in the same order as the XLA scan."""
    from active_learning_tpu.strategies.kcenter import kcenter_greedy

    rng = np.random.default_rng(7)
    x = rng.normal(size=(600, 96)).astype(np.float32)
    labeled = np.zeros(600, dtype=bool)
    labeled[rng.choice(600, 40, replace=False)] = True

    monkeypatch.delenv("AL_TPU_KCENTER_PALLAS", raising=False)
    want = kcenter_greedy([x], labeled, 25,
                          rng=np.random.default_rng(0))
    monkeypatch.setenv("AL_TPU_KCENTER_PALLAS", "interpret")
    got = kcenter_greedy([x], labeled, 25,
                         rng=np.random.default_rng(0))
    np.testing.assert_array_equal(got, want)
