"""Shared test fixtures: a tiny Flax classifier with the reference model
interface (split encoder / ``linear`` head, return_features, head-only
mode — resnet_simclr.py:29-41) and a factory that wires a full Strategy
stack (synthetic data + mesh + trainer + pool) small enough for fast CPU
tests on the virtual 8-device mesh."""

from __future__ import annotations

import tempfile

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from active_learning_tpu.config import (ExperimentConfig, LoaderConfig,
                                        OptimizerConfig, SchedulerConfig,
                                        TrainConfig)
from active_learning_tpu.data.synthetic import get_data_synthetic
from active_learning_tpu.initial_pool import (generate_eval_idxs,
                                              generate_init_lb_idxs)
from active_learning_tpu.parallel import mesh as mesh_lib
from active_learning_tpu.pool import PoolState
from active_learning_tpu.strategies import get_strategy
from active_learning_tpu.train.trainer import Trainer


class TinyClassifier(nn.Module):
    """Minimal model with the SSLClassifier interface: encoder -> embedding,
    separate ``linear`` head, three forward modes."""

    num_classes: int = 4
    feat_dim: int = 8
    freeze_feature: bool = False

    def setup(self):
        self.proj = nn.Dense(self.feat_dim, name="proj")
        self.linear = nn.Dense(self.num_classes, name="linear")

    def __call__(self, x, train: bool = True, return_features: bool = False):
        emb = x.reshape((x.shape[0], -1)).astype(jnp.float32)
        emb = nn.tanh(self.proj(emb))
        if self.freeze_feature:
            emb = jax.lax.stop_gradient(emb)
        logits = self.linear(emb)
        if return_features:
            return logits, emb
        return logits

    def head(self, embedding):
        return self.linear(embedding)


def tiny_train_config(batch_size: int = 16) -> TrainConfig:
    return TrainConfig(
        eval_split=0.1,
        loader_tr=LoaderConfig(batch_size=batch_size),
        loader_te=LoaderConfig(batch_size=batch_size),
        optimizer=OptimizerConfig(name="sgd", lr=0.05, weight_decay=0.0,
                                  momentum=0.9),
        scheduler=SchedulerConfig(name="constant"),
    )


def make_strategy(name: str = "RandomSampler", n_train: int = 64,
                  n_test: int = 32, num_classes: int = 4, image_size: int = 8,
                  seed: int = 0, init_pool: int = 8, eval_count: int = 8,
                  n_epoch: int = 2, sink=None, **cfg_overrides):
    """Build a fully wired Strategy over synthetic data on the 8-device CPU
    mesh."""
    train_set, test_set, al_set = get_data_synthetic(
        n_train=n_train, n_test=n_test, num_classes=num_classes,
        image_size=image_size, seed=seed)
    model = TinyClassifier(num_classes=num_classes)
    mesh = mesh_lib.make_mesh()
    train_cfg = tiny_train_config()
    cfg_overrides.setdefault(
        "ckpt_path", tempfile.mkdtemp(prefix="al_tpu_test_ckpt_"))
    cfg_overrides.setdefault(
        "log_dir", tempfile.mkdtemp(prefix="al_tpu_test_log_"))
    cfg = ExperimentConfig(
        dataset="synthetic", strategy=name, n_epoch=n_epoch,
        early_stop_patience=2, rounds=2, round_budget=init_pool,
        exp_hash="test", **cfg_overrides)
    trainer = Trainer(model, train_cfg, mesh, num_classes)

    targets = train_set.targets
    eval_idxs = generate_eval_idxs(targets, num_classes,
                                   ratio=eval_count / n_train,
                                   random_seed=cfg.eval_split_seed)
    pool = PoolState.create(len(al_set), eval_idxs)
    rng = np.random.default_rng(cfg.run_seed)
    strategy = get_strategy(name)(
        train_set, al_set, test_set, model, trainer, pool, cfg, train_cfg,
        sink=sink, rng=rng)
    if init_pool:
        init_idxs = generate_init_lb_idxs(
            targets, num_classes, eval_idxs, init_pool,
            random_seed=cfg.init_pool_seed)
        strategy.update(init_idxs, len(init_idxs))
    strategy.init_network_weights()
    return strategy


def build_jpeg_tree(root: str, n_classes: int = 3, n_per_class: int = 6,
                    seed: int = 0, min_hw: int = 40, max_hw: int = 80) -> str:
    """Seeded class-per-subdirectory JPEG tree, built ATOMICALLY (written
    to a sibling temp dir, then renamed into place) so an interrupted
    build can never leave a partial tree that later runs silently reuse.
    Shared by the pytest jpeg_tree fixture and the multihost worker."""
    import json
    import os
    import shutil

    from PIL import Image

    # Reuse only a tree whose manifest matches EVERY build parameter: a
    # persistent root (the worker's manual-recipe scratch lives in /tmp)
    # must never hand back a tree built by older code after a param edit.
    params = {"n_classes": n_classes, "n_per_class": n_per_class,
              "seed": seed, "min_hw": min_hw, "max_hw": max_hw}
    manifest = os.path.join(root, "manifest.json")
    if os.path.isdir(root):
        try:
            with open(manifest) as fh:
                if json.load(fh) == params:
                    return root
        except (OSError, json.JSONDecodeError):
            pass
        shutil.rmtree(root)
    tmp = root + ".building"
    shutil.rmtree(tmp, ignore_errors=True)
    rng = np.random.default_rng(seed)
    for c in range(n_classes):
        cdir = os.path.join(tmp, f"class{c}")
        os.makedirs(cdir)
        for i in range(n_per_class):
            hw = int(rng.integers(min_hw, max_hw))
            arr = rng.integers(0, 256, size=(hw, hw + 10, 3), dtype=np.uint8)
            Image.fromarray(arr).save(os.path.join(cdir, f"img{i}.jpg"))
    with open(os.path.join(tmp, "manifest.json"), "w") as fh:
        json.dump(params, fh)
    os.rename(tmp, root)
    return root
