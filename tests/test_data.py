"""Data-layer tests: views, batching/padding, on-device augmentation,
imbalance synthesis, disk datasets, prefetch."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from active_learning_tpu.config import ImbalanceConfig
from active_learning_tpu.data import get_data
from active_learning_tpu.data.augment import apply_view, random_crop_flip
from active_learning_tpu.data.core import ArrayDataset, ViewSpec, CIFAR10_NORM
from active_learning_tpu.data.imbalance import img_num_per_cls, imbalanced_indices
from active_learning_tpu.data.pipeline import (batch_index_lists, gather_batch,
                                               iterate_batches, num_batches)


def test_synthetic_triple_shares_storage():
    train, test, al = get_data("synthetic", n_train=64, n_test=16)
    assert train.images is al.images
    assert train.view.augment and not al.view.augment
    assert len(train) == 64 and len(test) == 16
    assert train.num_classes == 10


def test_debug_mode_truncates():
    train, test, al = get_data("synthetic", n_train=200, debug_mode=True)
    assert len(train) == 50 and len(al) == 50


def test_gather_batch_pads_and_masks():
    train, _, _ = get_data("synthetic", n_train=10)
    batch = gather_batch(train, np.array([1, 2, 3]), batch_size=8)
    assert batch["image"].shape == (8, 32, 32, 3)
    assert batch["mask"].tolist() == [1, 1, 1, 0, 0, 0, 0, 0]
    assert batch["index"][:3].tolist() == [1, 2, 3]


def test_iterate_batches_covers_all_once():
    train, _, _ = get_data("synthetic", n_train=50)
    seen = []
    for b in iterate_batches(train, np.arange(50), 16):
        seen.extend(b["index"][b["mask"] > 0].tolist())
    assert sorted(seen) == list(range(50))
    assert num_batches(50, 16) == 4


def test_iterate_batches_prefetch_matches_sync():
    train, _, _ = get_data("synthetic", n_train=40)
    sync = list(iterate_batches(train, np.arange(40), 16))
    pref = list(iterate_batches(train, np.arange(40), 16, num_threads=1))
    assert len(sync) == len(pref)
    for a, b in zip(sync, pref):
        np.testing.assert_array_equal(a["image"], b["image"])


def test_shuffle_requires_rng():
    train, _, _ = get_data("synthetic", n_train=10)
    with pytest.raises(ValueError):
        batch_index_lists(np.arange(10), 4, shuffle=True)


def test_apply_view_normalizes():
    view = ViewSpec(CIFAR10_NORM, augment=False)
    x = apply_view(jnp.full((2, 8, 8, 3), 128, dtype=jnp.uint8), view,
                   train=False)
    expected = (128.0 - 0.4914 * 255) / (0.2023 * 255)
    assert abs(float(x[0, 0, 0, 0]) - expected) < 1e-4


def test_random_crop_flip_shapes_and_determinism():
    x = jnp.arange(2 * 8 * 8 * 3, dtype=jnp.uint8).reshape(2, 8, 8, 3)
    key = jax.random.PRNGKey(0)
    a = random_crop_flip(x, key, pad=2)
    b = random_crop_flip(x, key, pad=2)
    assert a.shape == x.shape
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    c = random_crop_flip(x, jax.random.PRNGKey(1), pad=2)
    assert not np.array_equal(np.asarray(a), np.asarray(c))


def test_flip_only_when_pad_zero():
    x = jnp.arange(1 * 4 * 4 * 3, dtype=jnp.uint8).reshape(1, 4, 4, 3)
    out = random_crop_flip(x, jax.random.PRNGKey(0), pad=0)
    # either identical or horizontally flipped
    same = np.array_equal(np.asarray(out), np.asarray(x))
    flipped = np.array_equal(np.asarray(out), np.asarray(x[:, :, ::-1, :]))
    assert same or flipped


def test_img_num_per_cls_exp_and_step():
    counts = img_num_per_cls(1000, 10, "exp", 0.1)
    assert counts[0] == 100 and counts[-1] == 10
    assert all(a >= b for a, b in zip(counts, counts[1:]))
    counts = img_num_per_cls(1000, 10, "step", 0.1)
    assert counts[:5] == [100] * 5 and counts[5:] == [10] * 5
    with pytest.raises(ValueError):
        img_num_per_cls(1000, 10, "bogus", 0.1)


def test_imbalanced_indices_seeded():
    targets = np.repeat(np.arange(4), 25)
    a = imbalanced_indices(targets, [25, 12, 6, 3], seed=0)
    b = imbalanced_indices(targets, [25, 12, 6, 3], seed=0)
    np.testing.assert_array_equal(a, b)
    assert len(a) == 46
    counts = np.bincount(targets[a], minlength=4)
    np.testing.assert_array_equal(counts, [25, 12, 6, 3])


def test_imbalanced_synthetic_dataset():
    imb = ImbalanceConfig(imbalance_type="exp", imbalance_factor=0.1)
    train, test, al = get_data("imbalanced_synthetic", imbalance_args=imb,
                               n_train=1000)
    counts = train.class_counts()
    assert counts[0] > counts[-1]
    assert len(train) == len(al)
    assert train.images is al.images


def test_image_folder_dataset(tmp_path):
    from PIL import Image
    from active_learning_tpu.data.imagenet import ImageFolderDataset
    from active_learning_tpu.data.core import IMAGENET_NORM

    for cls in ["a", "b"]:
        os.makedirs(tmp_path / cls)
        for i in range(3):
            arr = np.full((40, 60, 3), 30 * i, dtype=np.uint8)
            Image.fromarray(arr).save(tmp_path / cls / f"{i}.jpg")
    view = ViewSpec(IMAGENET_NORM, augment=False)
    ds = ImageFolderDataset(str(tmp_path), view, train_transform=False,
                            num_classes=2, seed=0)
    assert len(ds) == 6
    np.testing.assert_array_equal(np.unique(ds.targets), [0, 1])
    batch = ds.gather(np.array([0, 3]))
    assert batch.shape == (2, 224, 224, 3)
    # train view: random-resized crop also lands at 224
    ds_tr = ImageFolderDataset(str(tmp_path), view, train_transform=True,
                               num_classes=2, seed=0)
    assert ds_tr.gather(np.array([1])).shape == (1, 224, 224, 3)


def test_file_list_dataset(tmp_path):
    from PIL import Image
    from active_learning_tpu.data.imagenet import FileListDataset
    from active_learning_tpu.data.core import IMAGENET_NORM

    os.makedirs(tmp_path / "imgs")
    lines = []
    for i in range(4):
        arr = np.full((50, 50, 3), 40 * i, dtype=np.uint8)
        Image.fromarray(arr).save(tmp_path / "imgs" / f"{i}.jpg")
        lines.append(f"imgs/{i}.jpg {i % 2}")
    list_file = tmp_path / "list.txt"
    list_file.write_text("\n".join(lines))
    view = ViewSpec(IMAGENET_NORM, augment=False)
    ds = FileListDataset(str(tmp_path), str(list_file), view,
                         train_transform=False, num_classes=2)
    assert len(ds) == 4
    assert ds.targets.tolist() == [0, 1, 0, 1]
    assert ds.gather(np.array([2])).shape == (1, 224, 224, 3)


class TestCifar10Fetch:
    """The self-provisioning CIFAR-10 path (reference custom_cifar10.py:
    30-33's torchvision download=True) against a byte-layout-faithful
    facsimile archive served over file:// — everything but the pixel
    content of the canonical tar.gz."""

    @pytest.fixture()
    def archive(self, tmp_path):
        from active_learning_tpu.data.facsimile import write_cifar10_facsimile
        path, md5 = write_cifar10_facsimile(
            str(tmp_path / "cifar-10-python.tar.gz"),
            n_train=250, n_test=50, seed=5)
        return path, md5

    def test_fetch_extract_load(self, archive, tmp_path):
        from active_learning_tpu.data.cifar10 import (fetch_cifar10,
                                                      load_cifar10_arrays)
        path, md5 = archive
        dest = str(tmp_path / "data")
        root = fetch_cifar10(dest, url=f"file://{path}", expected_md5=md5)
        assert root.endswith("cifar-10-batches-py")
        (tr_im, tr_y), (te_im, te_y) = load_cifar10_arrays(dest)
        assert tr_im.shape == (250, 32, 32, 3) and tr_im.dtype == np.uint8
        assert te_im.shape == (50, 32, 32, 3)
        assert set(np.unique(tr_y)) <= set(range(10))
        # Idempotent: a second call must not re-download (dead URL).
        assert fetch_cifar10(dest, url="file:///nonexistent") == root

    def test_facsimile_pixels_roundtrip(self, archive, tmp_path):
        """The archive's plane-major [N, 3072] rows must decode back to
        the exact HWC uint8 images that went in — a silent transpose in
        either direction would feed permuted garbage to every
        facsimile-backed run."""
        from active_learning_tpu.data.cifar10 import (fetch_cifar10,
                                                      load_cifar10_arrays)
        from active_learning_tpu.data.synthetic import (_class_templates,
                                                        _make_images)
        path, md5 = archive
        dest = str(tmp_path / "data")
        fetch_cifar10(dest, url=f"file://{path}", expected_md5=md5)
        (tr_im, tr_y), _ = load_cifar10_arrays(dest)
        # Rebuild the generator chain write_cifar10_facsimile(seed=5)
        # consumed: templates first, then batch 1 (250 rows at n_train=250
        # -> per-file cap ceil(250/5)=50, so batch 1 holds rows 0..49).
        rng = np.random.default_rng(5)
        templates = _class_templates(10, 32, rng)
        want_im, want_y = _make_images(50, templates, rng)
        np.testing.assert_array_equal(tr_im[:50], want_im)
        np.testing.assert_array_equal(tr_y[:50], want_y)

    def test_bad_md5_refuses_extraction(self, archive, tmp_path):
        from active_learning_tpu.data.cifar10 import fetch_cifar10
        path, _ = archive
        dest = str(tmp_path / "data")
        with pytest.raises(RuntimeError, match="md5"):
            fetch_cifar10(dest, url=f"file://{path}", expected_md5="0" * 32)
        assert not os.path.exists(os.path.join(dest,
                                               "cifar-10-batches-py"))

    def test_hostile_member_refused(self, tmp_path):
        import io
        import tarfile
        from active_learning_tpu.data.cifar10 import fetch_cifar10
        evil = str(tmp_path / "evil.tar.gz")
        with tarfile.open(evil, "w:gz") as tar:
            info = tarfile.TarInfo("../outside")
            info.size = 1
            tar.addfile(info, io.BytesIO(b"x"))
        with pytest.raises(RuntimeError, match="suspicious"):
            fetch_cifar10(str(tmp_path / "d"), url=f"file://{evil}",
                          expected_md5=None)
        assert not (tmp_path / "outside").exists()

    def test_get_data_dispatch_with_download(self, archive, tmp_path,
                                             monkeypatch):
        """The full production dispatch: get_data('cifar10',
        download=True) self-provisions from the (patched) canonical URL
        and returns the reference's dataset triple."""
        from active_learning_tpu.data import cifar10 as c10
        path, md5 = archive
        monkeypatch.setattr(c10, "CIFAR10_URL", f"file://{path}")
        monkeypatch.setattr(c10, "CIFAR10_TGZ_MD5", md5)
        train_set, test_set, al_set = get_data(
            "cifar10", data_path=str(tmp_path / "data"), download=True)
        assert len(train_set) == 250 and len(test_set) == 50
        assert al_set.images is train_set.images  # shared storage
        assert not al_set.view.augment and train_set.view.augment

    def test_missing_without_download_mentions_flag(self, tmp_path):
        from active_learning_tpu.data.cifar10 import find_cifar10_root
        with pytest.raises(FileNotFoundError, match="download"):
            find_cifar10_root(str(tmp_path / "nope"))
