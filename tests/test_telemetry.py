"""Run-wide telemetry (active_learning_tpu/telemetry/, DESIGN.md §7):
span nesting + Chrome-trace validity, heartbeat atomicity + staleness,
the watchdog on a frozen fake clock, Prometheus exposition, the
telemetry-off no-per-step-work contract, the status verb, trace_lint,
and the end-to-end CPU-mesh smoke run the acceptance criteria pin."""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from active_learning_tpu.telemetry import heartbeat as hb_lib
from active_learning_tpu.telemetry import prom as prom_lib
from active_learning_tpu.telemetry import runtime as rt_lib
from active_learning_tpu.telemetry import spans as spans_lib
from active_learning_tpu.telemetry import status as status_lib

REPO = os.path.join(os.path.dirname(__file__), "..")


class TestSpanTracer:
    def test_nesting_and_chrome_trace_validity(self, tmp_path):
        tracer = spans_lib.SpanTracer(enabled=True)
        with tracer.span("experiment", args={"exp": "t"}):
            assert tracer.depth() == 1
            for rd in range(2):
                with tracer.span("round", args={"round": rd}):
                    with tracer.span("train_time", args={"round": rd}):
                        with tracer.span("epoch", args={"epoch": 1}):
                            assert tracer.depth() == 4
        assert tracer.depth() == 0
        path = str(tmp_path / "trace.json")
        assert tracer.export(path) == path

        with open(path) as fh:
            trace = json.load(fh)  # strict JSON
        events = trace["traceEvents"]
        assert {e["name"] for e in events} == {"experiment", "round",
                                               "train_time", "epoch"}
        for e in events:
            assert e["ph"] == "X"
            assert isinstance(e["ts"], float) and isinstance(e["dur"],
                                                             float)
            assert e["dur"] >= 0 and "pid" in e and "tid" in e
        # Interval nesting: every child lies inside its parent's span.
        by_name = {e["name"]: e for e in events}
        exp = by_name["experiment"]
        for name in ("round", "train_time", "epoch"):
            child = by_name[name]
            assert child["ts"] >= exp["ts"] - 1e-6
            assert (child["ts"] + child["dur"]
                    <= exp["ts"] + exp["dur"] + 1e-6)

    def test_disabled_tracer_still_times_but_records_nothing(self):
        tracer = spans_lib.SpanTracer(enabled=False)
        with tracer.span("phase") as sp:
            time.sleep(0.01)
        assert sp.duration_s >= 0.01
        assert tracer.events == []

    def test_complete_and_instant_and_cap(self, tmp_path):
        tracer = spans_lib.SpanTracer(enabled=True, max_events=2)
        t0 = time.perf_counter()
        tracer.complete("chunk", t0, t0 + 0.5, args={"rows": 32})
        tracer.instant("stall_suspected", args={"stalled_s": 3.0})
        tracer.complete("chunk", t0, t0 + 1.0)  # over the cap: dropped
        assert len(tracer.events) == 2 and tracer.dropped == 1
        path = str(tmp_path / "t.json")
        tracer.export(path)
        with open(path) as fh:
            out = json.load(fh)
        assert out["otherData"]["dropped_events"] == 1

    def test_thread_safety_of_event_buffer(self):
        tracer = spans_lib.SpanTracer(enabled=True)

        def worker():
            for _ in range(200):
                with tracer.span("w"):
                    pass

        threads = [threading.Thread(target=worker) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(tracer.events) == 800


class TestHeartbeat:
    def test_tick_writes_atomic_json_and_rate_limits(self, tmp_path):
        path = str(tmp_path / "heartbeat.json")
        clock = {"t": 100.0}
        hb = hb_lib.HeartbeatWriter(path, every_s=5.0,
                                    stall_deadline_s=60.0,
                                    monotonic_fn=lambda: clock["t"])
        assert hb.tick(round=0, phase="query") is True
        first = hb_lib.read_heartbeat(path)
        assert first["round"] == 0 and first["phase"] == "query"
        assert first["progress"] == 1
        assert first["stall_deadline_s"] == 60.0
        # Within the cadence: progress advances, file does not.
        clock["t"] += 1.0
        assert hb.tick(round=0, phase="train", epoch=3) is False
        assert hb_lib.read_heartbeat(path)["phase"] == "query"
        assert hb.progress == 2
        # force=True (phase transitions) writes regardless.
        assert hb.tick(force=True, phase="test") is True
        now = hb_lib.read_heartbeat(path)
        assert now["phase"] == "test" and now["epoch"] == 3
        # No torn temp files left behind.
        assert [f for f in os.listdir(tmp_path)
                if f.startswith("heartbeat.json.tmp")] == []

    def test_staleness_from_mtime_vs_embedded_deadline(self, tmp_path):
        path = str(tmp_path / "heartbeat.json")
        hb = hb_lib.HeartbeatWriter(path, every_s=0.0,
                                    stall_deadline_s=30.0)
        hb.tick(round=1)
        assert hb_lib.is_stale(path) is False
        # Age the FILE (the mtime is the contract, not the payload ts).
        old = time.time() - 100.0
        os.utime(path, (old, old))
        assert hb_lib.is_stale(path) is True          # 100s > 30s
        assert hb_lib.is_stale(path, deadline_s=1000.0) is False
        assert hb_lib.is_stale(str(tmp_path / "absent.json")) is None
        age = hb_lib.heartbeat_age_s(path)
        assert age == pytest.approx(100.0, abs=5.0)

    def test_watchdog_fires_once_per_stall_on_fake_clock(self, tmp_path):
        clock = {"t": 0.0}
        hb = hb_lib.HeartbeatWriter(str(tmp_path / "hb.json"), every_s=0.0,
                                    monotonic_fn=lambda: clock["t"])
        stalls = []
        wd = hb_lib.StallWatchdog(hb, deadline_s=10.0,
                                  on_stall=stalls.append,
                                  monotonic_fn=lambda: clock["t"])
        hb.tick(round=0)
        clock["t"] = 5.0
        assert wd.check() is False          # under the deadline
        clock["t"] = 11.0
        assert wd.check() is False          # progress moved at t=0... still
        clock["t"] = 12.0
        hb.tick(round=0)                    # progress resumes
        assert wd.check() is False
        clock["t"] = 23.0                   # frozen 11s > 10s deadline
        assert wd.check() is True
        assert len(stalls) == 1 and stalls[0] > 10.0
        clock["t"] = 24.0                   # inside the fire's window
        assert wd.check() is False
        clock["t"] = 40.0                   # STILL stalled one more full
        assert wd.check() is True           # deadline: fires again (the
        assert stalls[1] > 25.0             # fixed re-arm edge; reports
        hb.tick(round=1)                    # the TOTAL stall), and
        clock["t"] = 41.0                   # progress still re-arms
        assert wd.check() is False
        clock["t"] = 60.0
        assert wd.check() is True           # next episode fires again
        assert wd.stalls_detected == 3


class TestPrometheus:
    def test_render_parses_and_round_trips(self):
        text = prom_lib.render([
            ("al_run_round", None, 3),
            ("al_serve_requests_total", {"endpoint": "/v1/score"}, 17),
            ("al_serve_requests_total", {"endpoint": "/v1/predict"}, 4),
            ("al_serve_request_latency_ms", {"quantile": "0.99"}, 12.75),
            ("weird-name.with dots", None, 1.5),
            ("dropped_none", None, None),
            ("bool_gauge", None, True),
        ])
        parsed = prom_lib.parse(text)
        assert parsed["al_run_round"][()] == 3
        assert parsed["al_serve_requests_total"][
            (("endpoint", "/v1/score"),)] == 17
        assert parsed["al_serve_request_latency_ms"][
            (("quantile", "0.99"),)] == 12.75
        assert parsed["weird_name_with_dots"][()] == 1.5
        assert parsed["bool_gauge"][()] == 1
        assert "dropped_none" not in parsed
        # One TYPE header per metric name, before its samples.
        assert text.count("# TYPE al_serve_requests_total gauge") == 1

    def test_label_escaping(self):
        text = prom_lib.render([("m", {"k": 'a"b\\c\nd'}, 1)])
        parsed = prom_lib.parse(text)
        assert parsed["m"][(("k", 'a"b\\c\nd'),)] == 1

    def test_serve_metrics_endpoint_prometheus_view(self):
        """GET /metrics?format=prometheus through the real router over a
        stub executor/batcher: valid exposition, text content type, and
        the serving contract (request_path_compiles) scrapable."""
        import asyncio

        from active_learning_tpu.config import ServeConfig
        from active_learning_tpu.serve.server import ScoringServer

        class StubExecutor:
            _lock = threading.Lock()
            stats = {"batches": 3, "rows": 170, "reloads": 1,
                     "warm_buckets": [8, 16]}
            served_round = 2

            def compile_counts(self):
                return {"prob_stats": 2, "embed": 2}

            def request_path_compiles(self):
                return 0

        class StubBatcher:
            pending_rows = 5
            buckets = (8, 16)

        server = ScoringServer(StubExecutor(), ServeConfig(queue_depth=64))
        server.batcher = StubBatcher()
        server.metrics.record_request("/v1/score")
        server.metrics.record_response(200, 0.012, rows=8)
        server.metrics.record_batch(8, 5)

        status, payload, headers = asyncio.run(
            server._route("GET", "/metrics?format=prometheus", b""))
        assert status == 200 and isinstance(payload, str)
        assert headers["Content-Type"].startswith("text/plain")
        parsed = prom_lib.parse(payload)
        assert parsed["al_serve_request_path_compiles"][()] == 0
        assert parsed["al_serve_served_round"][()] == 2
        assert parsed["al_serve_requests_total"][
            (("endpoint", "/v1/score"),)] == 1
        assert parsed["al_serve_batch_occupancy_total"][
            (("bucket", "8"), ("rows", "5"))] == 1
        assert parsed["al_serve_queue_pending_rows"][()] == 5
        # The JSON view is unchanged, and a junk format is a 400.
        status, payload, _ = asyncio.run(
            server._route("GET", "/metrics", b""))
        assert status == 200 and isinstance(payload, dict)
        status, _, _ = asyncio.run(
            server._route("GET", "/metrics?format=xml", b""))
        assert status == 400

    def test_scrape_file_write_is_atomic(self, tmp_path):
        path = str(tmp_path / "run.prom")
        assert prom_lib.write_textfile(path, "# TYPE a gauge\na 1\n")
        assert prom_lib.parse(open(path).read())["a"][()] == 1
        assert [f for f in os.listdir(tmp_path)
                if f.startswith("run.prom.tmp")] == []


class TestTelemetryOffPath:
    def test_default_runtime_is_inert(self, tmp_path):
        rt = rt_lib.get_run()
        assert rt.train_metrics is False
        rt.tick(round=1)                      # no heartbeat, no file
        rt.register_jit("x", lambda: None)    # no registry growth
        assert rt.jit_cache_sizes() == {}
        assert rt.export_trace() is None
        assert os.listdir(tmp_path) == []
        assert spans_lib.get_tracer().enabled is False

    def test_fit_emits_no_step_metrics_when_off(self, tmp_path):
        """With no run installed, the trainer's metric_cb sees exactly
        the pre-telemetry names — no step_time/imgs_per_sec/EMA series,
        no per-step timing work."""
        import dataclasses

        import jax

        from active_learning_tpu.data.synthetic import get_data_synthetic
        from active_learning_tpu.parallel import mesh as mesh_lib
        from active_learning_tpu.train import checkpoint as ckpt_lib
        from active_learning_tpu.train.trainer import Trainer
        from helpers import TinyClassifier, tiny_train_config

        train_set, _, al_set = get_data_synthetic(
            n_train=32, n_test=8, num_classes=4, image_size=8, seed=3)
        cfg = dataclasses.replace(tiny_train_config(batch_size=16),
                                  device_resident=False)
        trainer = Trainer(TinyClassifier(), cfg, mesh_lib.make_mesh(),
                          num_classes=4, train_bn=True)
        state = trainer.init_state(jax.random.PRNGKey(0),
                                   train_set.gather(np.arange(2)))
        names = []
        trainer.fit(state, train_set, np.arange(24), al_set,
                    np.arange(24, 32), n_epoch=2, es_patience=2,
                    rng=np.random.default_rng(0), round_idx=0,
                    weight_paths=ckpt_lib.weight_paths(
                        str(tmp_path), "t", "off", 0),
                    metric_cb=lambda n, v, s: names.append(n))
        assert not any(n.startswith(("step_time", "imgs_per_sec",
                                     "train_loss_ema", "grad_norm_ema"))
                       for n in names)
        assert any("validation_accuracy" in n for n in names)

    def test_per_step_record_cost_supports_overhead_budget(self, tmp_path):
        """The default-on per-step work is a perf_counter delta + list
        append + rate-limited heartbeat tick.  Bound it hard: 10k
        simulated steps well under 0.5 s total (<50 µs/step — noise
        against ms-scale real steps: the DESIGN §7 overhead budget)."""
        hb = hb_lib.HeartbeatWriter(str(tmp_path / "hb.json"),
                                    every_s=3600.0)
        t0 = time.perf_counter()
        times = []
        prev = time.perf_counter()
        for i in range(10_000):
            now = time.perf_counter()
            times.append(now - prev)
            prev = now
            hb.tick(epoch=1, step=i)
        assert time.perf_counter() - t0 < 0.5
        assert len(times) == 10_000


class TestRunTelemetryLifecycle:
    def test_start_finish_install_uninstall(self, tmp_path):
        from active_learning_tpu.config import TelemetryConfig

        cfg = TelemetryConfig(enabled=True, export_trace=True,
                              watchdog=True, heartbeat_every_s=0.0,
                              stall_deadline_s=60.0,
                              prometheus_file=str(tmp_path / "g.prom"))
        rt = rt_lib.start_run(cfg, log_dir=str(tmp_path))
        try:
            assert rt_lib.get_run() is rt
            assert spans_lib.get_tracer() is rt.tracer
            assert rt.train_metrics is True
            with spans_lib.get_tracer().span("experiment"):
                rt.tick(round=0, phase="query")
            rt.set_gauges(round=0, imgs_per_sec=123.4)
        finally:
            rt.finish("finished")
            rt_lib.uninstall(rt)
        hb = hb_lib.read_heartbeat(str(tmp_path / "heartbeat.json"))
        assert hb["status"] == "finished" and hb["round"] == 0
        trace = json.load(open(tmp_path / "trace.json"))
        assert trace["otherData"]["status"] == "finished"
        parsed = prom_lib.parse(open(tmp_path / "g.prom").read())
        assert parsed["al_run_imgs_per_sec"][()] == pytest.approx(123.4)
        # Uninstalled: back to the inert default.
        assert rt_lib.get_run().train_metrics is False
        assert spans_lib.get_tracer().enabled is False

    def test_disabled_config_installs_inert_runtime(self, tmp_path):
        from active_learning_tpu.config import TelemetryConfig

        rt = rt_lib.start_run(TelemetryConfig(enabled=False),
                              log_dir=str(tmp_path))
        try:
            assert rt.train_metrics is False
            assert rt.heartbeat is None
            rt.tick(round=1)
            assert os.listdir(tmp_path) == []
        finally:
            rt.finish()
            rt_lib.uninstall(rt)

    def test_multiprocess_heartbeat_filename(self):
        assert hb_lib.heartbeat_filename(0, 1) == "heartbeat.json"
        assert hb_lib.heartbeat_filename(0, 4) == "heartbeat_p0.json"
        assert hb_lib.heartbeat_filename(3, 4) == "heartbeat_p3.json"


class TestEndToEndSmoke:
    """The acceptance-criteria smoke: a CPU-mesh synthetic run with
    telemetry on produces (a) nested Chrome-trace spans, (b) a fresh
    heartbeat the status verb flags stale once its mtime ages past the
    deadline, (c) per-epoch step_time_ms_p50/p99 + imgs_per_sec in
    metrics.jsonl."""

    @pytest.fixture(scope="class")
    def smoke_run(self, tmp_path_factory):
        from active_learning_tpu.config import (ExperimentConfig,
                                                TelemetryConfig)
        from active_learning_tpu.experiment.driver import run_experiment

        tmp = str(tmp_path_factory.mktemp("tele_smoke"))
        cfg = ExperimentConfig(
            dataset="synthetic", arg_pool="synthetic",
            strategy="MarginSampler", rounds=2, round_budget=16,
            n_epoch=2, early_stop_patience=2, log_dir=tmp, ckpt_path=tmp,
            exp_hash="telesmoke",
            telemetry=TelemetryConfig(enabled=True, export_trace=True,
                                      watchdog=True,
                                      heartbeat_every_s=0.0,
                                      stall_deadline_s=120.0))
        run_experiment(cfg)
        return tmp

    def test_trace_json_is_valid_and_nested(self, smoke_run):
        trace = json.load(open(os.path.join(smoke_run, "trace.json")))
        events = trace["traceEvents"]
        names = {e["name"] for e in events}
        # The span hierarchy of DESIGN §7: experiment → round → phase →
        # epoch → collect_pool chunk.
        for expected in ("experiment", "round", "train_time", "test_time",
                         "query_time", "epoch", "collect_pool",
                         "collect_pool_chunk"):
            assert expected in names, f"missing span {expected!r}"
        spans = {e["name"]: e for e in events}
        exp = spans["experiment"]
        for e in events:
            if e.get("ph") == "M":
                # Metadata events (the pipelined round's thread_name
                # track labels) carry no timestamp by the trace-event
                # spec.
                continue
            assert e["ts"] >= exp["ts"] - 1e-6
            assert (e["ts"] + e.get("dur", 0.0)
                    <= exp["ts"] + exp["dur"] + 1e-6)
        rounds = [e for e in events if e["name"] == "round"]
        assert len(rounds) == 2
        # Every epoch span nests inside some train phase span.
        trains = [e for e in events if e["name"] == "train_time"]
        for ep in (e for e in events if e["name"] == "epoch"):
            assert any(t["ts"] <= ep["ts"]
                       and ep["ts"] + ep["dur"] <= t["ts"] + t["dur"] + 1e-6
                       for t in trains)

    def test_heartbeat_fresh_then_stale_via_status(self, smoke_run):
        hb_path = os.path.join(smoke_run, "heartbeat.json")
        hb = hb_lib.read_heartbeat(hb_path)
        assert hb["status"] == "finished"
        assert hb["round"] == 1
        summary = status_lib.summarize(smoke_run)
        assert summary["state"] == "ok"  # finished runs are never stale
        # A RUNNING heartbeat whose mtime ages past the deadline reads
        # STALE through the same summarize path the CLI verb uses.
        hb_run = hb_lib.HeartbeatWriter(hb_path, every_s=0.0,
                                        stall_deadline_s=120.0)
        hb_run.tick(round=1, phase="train", status="running")
        old = time.time() - 1000.0
        os.utime(hb_path, (old, old))
        summary = status_lib.summarize(smoke_run)
        assert summary["state"] == "stale"
        assert summary["heartbeats"][0]["stale"] is True
        assert summary["metrics"].get("rd_test_accuracy") is not None
        text = status_lib.render_text(summary)
        assert "STALE" in text and "rd_test_accuracy" in text

    def test_per_epoch_telemetry_lands_in_metrics_jsonl(self, smoke_run):
        by_name = {}
        for line in open(os.path.join(smoke_run, "metrics.jsonl")):
            ev = json.loads(line)
            if ev.get("kind") == "metric":
                for k, v in ev["metrics"].items():
                    by_name.setdefault(k, []).append((ev.get("step"), v))
        for name in ("step_time_ms_p50", "step_time_ms_p99",
                     "imgs_per_sec", "train_loss_ema", "grad_norm_ema",
                     "pool_rows_per_sec", "jit_cache_miss_delta"):
            assert name in by_name, f"missing {name}"
        # 2 rounds x 2 epochs of step-time series, positive values,
        # p99 >= p50, monotonic round-folded step axis.
        p50 = by_name["step_time_ms_p50"]
        p99 = by_name["step_time_ms_p99"]
        assert len(p50) == 4 and len(p99) == 4
        steps = [s for s, _ in p50]
        assert steps == sorted(steps) and len(set(steps)) == 4
        assert all(v > 0 for _, v in p50)
        assert all(q >= p for (_, p), (_, q) in zip(p50, p99))
        assert all(v > 0 for _, v in by_name["imgs_per_sec"])
        assert all(v > 0 for _, v in by_name["grad_norm_ema"])
        # Warm rounds must not compile: the round-1 miss delta is 0.
        deltas = dict(by_name["jit_cache_miss_delta"])
        assert deltas[1] == 0, f"round-1 jit cache misses: {deltas[1]}"

    def test_status_cli_subprocess_no_jax(self, smoke_run):
        """The status verb answers from a plain subprocess — and never
        imports jax (it must work against a wedged run)."""
        code = (
            "import sys\n"
            "from active_learning_tpu.telemetry.status import main\n"
            f"rc = main(['--log_dir', {smoke_run!r}, '--json'])\n"
            "assert 'jax' not in sys.modules, 'status imported jax'\n"
            "sys.exit(rc)\n")
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=60,
            cwd=os.path.abspath(REPO))
        assert proc.returncode in (0, 3), proc.stderr[-2000:]
        out = json.loads(proc.stdout)
        assert out["heartbeats"]


class TestTraceLint:
    def test_trace_lint_passes_from_tier1(self):
        proc = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "scripts", "trace_lint.py")],
            capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stderr

    # The negative case, without polluting the real tree:
    def test_lint_logic_flags_competing_definition(self, tmp_path,
                                                   monkeypatch):
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "trace_lint", os.path.join(REPO, "scripts", "trace_lint.py"))
        lint = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(lint)
        bad = tmp_path / "rogue.py"
        bad.write_text("def phase_timer(name):\n    return name\n")
        monkeypatch.setattr(
            lint, "_py_files",
            lambda: [str(bad)])
        problems = lint.check()
        assert any("defines its own phase_timer" in p for p in problems)

    def test_lint_flags_host_copies_on_resident_feed_path(self, tmp_path):
        """The zero-host-copy invariant (DESIGN.md §2a): a resident-feed
        function that materializes image arrays on the host (np.*, a
        .gather()/.asarray() call) must fail the lint, and deleting the
        function entirely must too — the enforcement cannot be renamed
        away."""
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "trace_lint", os.path.join(REPO, "scripts", "trace_lint.py"))
        lint = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(lint)

        bad = tmp_path / "trainer.py"
        bad.write_text(
            "import numpy as np\n"
            "def _resident_feed_arrays(self, train_set):\n"
            "    rows = np.asarray(train_set.gather(self.idxs))\n"
            "    return rows, None\n")
        problems = lint.check_resident_feed(str(bad))
        assert any("references np" in p for p in problems)
        assert any(".gather()" in p for p in problems)

        empty = tmp_path / "empty_trainer.py"
        empty.write_text("def unrelated():\n    pass\n")
        problems = lint.check_resident_feed(str(empty))
        assert any("not found" in p for p in problems)

        # The REAL trainer is clean (also covered by the subprocess run
        # above, but pinned here against the specific check).
        assert lint.check_resident_feed() == []

    def test_lint_flags_unsharding_on_sharded_selection_path(self,
                                                             tmp_path):
        """The sharded pool's scale-out invariant (check 6, DESIGN.md
        §2b): a sharded-selection function that pulls the pool to host
        (np in the device tier, jax.device_get anywhere) or replicates
        a row-sharded array must fail the lint; deleting a function
        drops to 'not found' — the enforcement cannot be renamed away."""
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "trace_lint", os.path.join(REPO, "scripts", "trace_lint.py"))
        lint = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(lint)

        bad = tmp_path / "kcenter.py"
        bad.write_text(
            "import numpy as np\n"
            "import jax\n"
            "def _build_sharded_fns(mesh, nf):\n"
            "    rows = np.asarray(jax.device_get(mesh))\n"
            "    return rows\n"
            "def _kcenter_greedy_sharded(factors, mask, budget):\n"
            "    full = jax.device_get(factors)\n"
            "    rep = mesh_lib.replicate(factors, None)\n"
            "    return full, rep\n")
        problems = lint.check_sharded_selection(str(bad))
        assert any("references np" in p for p in problems)
        assert any(".device_get()" in p or "device_get" in p
                   for p in problems)
        assert any("replicate()" in p for p in problems)

        # The orchestrator tier ALLOWS np (it owns the host factor
        # copy) — only fetches/replication are flagged there.
        ok_np = tmp_path / "kcenter_np_ok.py"
        ok_np.write_text(
            "import numpy as np\n"
            "def _build_sharded_fns(mesh, nf):\n"
            "    return mesh\n"
            "def _kcenter_greedy_sharded(factors, mask, budget):\n"
            "    return np.flatnonzero(mask)\n")
        assert lint.check_sharded_selection(str(ok_np)) == []

        empty = tmp_path / "empty_kcenter.py"
        empty.write_text("def unrelated():\n    pass\n")
        problems = lint.check_sharded_selection(str(empty))
        assert any("not found" in p for p in problems)

    def test_lint_flags_train_stream_sync_in_pipeline_coordinator(
            self, tmp_path):
        """The pipelined round's never-sync-the-train-stream invariant
        (check 7, DESIGN.md §8): a coordinator function calling
        block_until_ready or device_get must fail the lint, and deleting
        a coordinator function drops to 'not found' — the enforcement
        cannot be renamed away."""
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "trace_lint", os.path.join(REPO, "scripts", "trace_lint.py"))
        lint = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(lint)

        bad = tmp_path / "pipeline.py"
        bad.write_text(
            "import jax\n"
            "def _worker(self):\n"
            "    jax.block_until_ready(self.out)\n"
            "def _worker_loop(self):\n"
            "    pass\n"
            "def _score_slice(self, plan, sl, variables):\n"
            "    return jax.device_get(variables)\n"
            "def _score_chunk(self, plan, sl, tag, variables, i):\n"
            "    return None\n"
            "def publish_best(self, r, e, v):\n"
            "    pass\n"
            "def finalize(self, r, e):\n"
            "    pass\n"
            "def consume(self, kind, keys, idxs, bs, variables):\n"
            "    return None\n")
        problems = lint.check_pipeline_coordinator(str(bad))
        assert any("_worker" in p and "block_until_ready" in p
                   for p in problems)
        assert any("_score_slice" in p and "device_get" in p
                   for p in problems)
        assert len(problems) == 2  # the clean coordinators stay clean

        # Renaming a coordinator away is itself a finding.
        missing = tmp_path / "pipeline_missing.py"
        missing.write_text("def unrelated():\n    pass\n")
        problems = lint.check_pipeline_coordinator(str(missing))
        assert any("not found" in p for p in problems)

        # The REAL pipeline module is clean, and the lint's fn list
        # mirrors the module's own (kept in both places so the lint
        # works without importing jax).
        assert lint.check_pipeline_coordinator() == []
        from active_learning_tpu.experiment import pipeline as pipe_lib
        assert tuple(lint.PIPELINE_COORDINATOR_FNS) == tuple(
            pipe_lib.PIPELINE_COORDINATOR_FNS)

        # The REAL backend is clean, and the module's own fn list stays
        # in lockstep with the lint's mirror (renames can't silently
        # drop enforcement on either side).
        assert lint.check_sharded_selection() == []
        from active_learning_tpu.strategies import kcenter as kc
        assert set(kc.SHARDED_SELECTION_FNS) == set(
            lint.SHARDED_DEVICE_FNS + lint.SHARDED_ORCHESTRATOR_FNS)

    def test_lint_flags_fault_site_violations(self, tmp_path):
        """The failure model's closed-registry invariant (check 8,
        DESIGN.md §10): an unregistered site name, a non-literal site
        name, and a RetryPolicy without an explicit classify= must each
        fail the lint; duplicate registration and a registered-but-
        never-wired site are findings too."""
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "trace_lint", os.path.join(REPO, "scripts", "trace_lint.py"))
        lint = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(lint)

        bad = tmp_path / "bad_sites.py"
        bad.write_text(
            "from active_learning_tpu import faults\n"
            "def upload(name):\n"
            "    faults.site('h2d_uplaod')\n"          # typo'd site
            "    faults.site(name)\n"                  # non-literal
            "    faults.site('ckpt_write')\n"          # fine
            "    p = faults.RetryPolicy(site='x')\n"   # no classify=
            "    q = faults.RetryPolicy(site='y', "
            "classify=faults.classify_exception)\n")   # fine
        problems = lint.check_fault_sites([str(bad)])
        assert any("unregistered site" in p and "h2d_uplaod" in p
                   for p in problems)
        assert any("non-literal site name" in p for p in problems)
        assert any("without an explicit classify=" in p for p in problems)
        assert len(problems) == 3  # the two clean calls stay clean

        # Duplicate registration is a finding against the registry.
        dup_reg = tmp_path / "dup_registry.py"
        dup_reg.write_text("SITES = ('a', 'b', 'a')\n")
        problems = lint.check_fault_sites([str(bad)],
                                          registry_path=str(dup_reg))
        assert any("registered more than once" in p for p in problems)

        # Full-tree mode: a registered site wired at no call site makes
        # its chaos coverage vacuous.
        lone = tmp_path / "lone_registry.py"
        lone.write_text("SITES = ('never_wired',)\n")
        orig = lint._py_files
        try:
            lint._py_files = lambda: [str(bad)]
            problems = lint.check_fault_sites(
                registry_path=str(lone))
        finally:
            lint._py_files = orig
        assert any("never_wired" in p and "wired at no call site" in p
                   for p in problems)

        # The REAL tree is clean against the REAL registry, and the
        # lint's view of the registry matches the package's.
        assert lint.check_fault_sites() == []
        from active_learning_tpu import faults
        assert tuple(lint._registered_fault_sites(
            lint.FAULTS_REGISTRY, [])) == tuple(faults.SITES)

    def test_lint_flags_stray_jax_profiler_use(self, tmp_path):
        """The device-truth layer's one-gate invariant (check 10,
        DESIGN.md §11): importing jax.profiler, touching the
        jax.profiler attribute, or calling start_trace/stop_trace under
        ANY alias outside telemetry/profiler.py must each fail the
        lint — and the gate module itself must define the gated API and
        really import jax.profiler (the closed-registry handshake,
        matching check 9)."""
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "trace_lint", os.path.join(REPO, "scripts", "trace_lint.py"))
        lint = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(lint)

        bad = tmp_path / "rogue_profiler.py"
        bad.write_text(
            "import jax.profiler\n"                      # direct import
            "from jax import profiler as jp\n"           # aliased import
            "def capture(d):\n"
            "    jax.profiler.start_trace(d)\n"          # attr + call
            "    jp.stop_trace()\n"                      # aliased call
            "def fine():\n"
            "    from active_learning_tpu.telemetry import profiler\n"
            "    with profiler.capture_window('/tmp/x'):\n"
            "        pass\n")
        problems = lint.check_profiler_confinement([str(bad)])
        assert any("imports jax.profiler" in p for p in problems)
        assert any("imports jax's profiler" in p for p in problems)
        assert any("touches jax.profiler" in p for p in problems)
        assert any("start_trace()" in p for p in problems)
        assert any("stop_trace()" in p for p in problems)
        # The gated-API path is clean — exactly the rogue uses flag.
        clean = tmp_path / "clean_caller.py"
        clean.write_text(
            "from active_learning_tpu.telemetry import profiler\n"
            "def go(d):\n"
            "    with profiler.capture_window(d):\n"
            "        pass\n")
        assert lint.check_profiler_confinement([str(clean)]) == []

        # A renamed-away gate makes the check vacuous: full-tree mode
        # verifies the module defines the API and touches jax.profiler.
        hollow = tmp_path / "hollow_gate.py"
        hollow.write_text("def unrelated():\n    pass\n")
        orig = lint._py_files
        try:
            lint._py_files = lambda: [str(clean)]
            problems = lint.check_profiler_confinement(
                profiler_path=str(hollow))
        finally:
            lint._py_files = orig
        assert any("gated API function" in p and "not found" in p
                   for p in problems)
        assert any("never imports jax.profiler" in p for p in problems)

        # The REAL tree is clean against the REAL gate.
        assert lint.check_profiler_confinement() == []

    def test_lint_flags_backward_registry_violations(self, tmp_path):
        """The gradient path's proven-backward invariant (check 9,
        DESIGN.md §4): a jax.custom_vjp outside ops/backward.py, a
        registry entry with no definition, a PARITY_TESTED_VJPS drift,
        and host materialization inside a fused-update function must
        each fail the lint."""
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "trace_lint", os.path.join(REPO, "scripts", "trace_lint.py"))
        lint = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(lint)

        # a) a custom VJP dodging the registry, flagged on a fragment.
        stray = tmp_path / "stray_vjp.py"
        stray.write_text(
            "import jax\n"
            "@jax.custom_vjp\n"
            "def sneaky(x):\n"
            "    return x\n")
        problems = lint.check_backward_registry([str(stray)])
        assert any("custom_vjp outside ops/backward.py" in p
                   for p in problems)

        # b) registry drift: a registered name with no definition.
        ops_bad = tmp_path / "ops_bad.py"
        ops_bad.write_text(
            "import jax\n"
            "TRAIN_PATH_VJPS = ('ghost',)\n"
            "@jax.custom_vjp\n"
            "def real(x):\n"
            "    return x\n")
        problems = lint.check_backward_registry(
            ops_path=str(ops_bad), optim_path=lint.OPTIM,
            tests_path=lint.BACKWARD_TESTS)
        assert any("'ghost'" in p and "no such function" in p
                   for p in problems)

        # c) a custom backward without a registered parity test.
        tests_bad = tmp_path / "tests_bad.py"
        tests_bad.write_text("PARITY_TESTED_VJPS = ('stem_conv',)\n")
        problems = lint.check_backward_registry(
            ops_path=lint.OPS_BACKWARD, optim_path=lint.OPTIM,
            tests_path=str(tests_bad))
        assert any("PARITY_TESTED_VJPS" in p and "TRAIN_PATH_VJPS" in p
                   for p in problems)

        # d) host materialization inside a fused-update function.
        optim_bad = tmp_path / "optim_bad.py"
        optim_bad.write_text(
            "import numpy as np\n"
            "FUSED_UPDATE_FNS = ('fused_sgd_update',)\n"
            "def fused_sgd_update(grads, state, params, lr):\n"
            "    host = np.asarray(grads)\n"
            "    return params, state\n")
        problems = lint.check_backward_registry(
            ops_path=lint.OPS_BACKWARD, optim_path=str(optim_bad),
            tests_path=lint.BACKWARD_TESTS)
        assert any("references np" in p for p in problems)

        # The REAL tree is clean, and the registered half matches the
        # tested half (the closed-registry handshake).
        assert lint.check_backward_registry() == []
        from active_learning_tpu.ops import backward as backward_ops
        import importlib
        tb = importlib.import_module("test_backward")
        assert set(tb.PARITY_TESTED_VJPS) == \
            set(backward_ops.TRAIN_PATH_VJPS)


class TestSatelliteFixes:
    def test_setup_logging_appends_on_resume(self, tmp_path):
        """The resume log-loss fix: a second setup_logging over the same
        file (resume) must APPEND, not truncate prior rounds' lines."""
        from active_learning_tpu.utils.logging import setup_logging

        logger = setup_logging(str(tmp_path), "run.log")
        logger.info("round 0 done")
        for h in list(logger.handlers):
            h.close()
        logger = setup_logging(str(tmp_path), "run.log")  # resume
        logger.info("resumed at round 1")
        for h in list(logger.handlers):
            h.close()
            logger.removeHandler(h)
        content = open(tmp_path / "run.log").read()
        assert "round 0 done" in content        # survived the resume
        assert "resumed at round 1" in content
        # A FRESH file still starts clean (mode "w" path).
        logger = setup_logging(str(tmp_path), "fresh.log")
        logger.info("fresh line")
        for h in list(logger.handlers):
            h.close()
            logger.removeHandler(h)
        assert open(tmp_path / "fresh.log").read().count("\n") == 1

    def test_tensorboard_auto_step_is_per_name(self):
        """TensorBoardSink._auto_step satellite: call sites omitting
        ``step`` get a PER-NAME 1,2,3,... axis, not a shared counter
        scrambled across unrelated series.  (Fake writer: importing the
        real SummaryWriter drags in TensorFlow, slow-tier only.)"""
        from active_learning_tpu.utils.metrics import TensorBoardSink

        calls = []

        class FakeWriter:
            def add_scalar(self, name, value, global_step=None):
                calls.append((name, value, global_step))

            def flush(self):
                pass

        sink = TensorBoardSink.__new__(TensorBoardSink)
        sink._writer = FakeWriter()
        sink.log_metrics({"a": 1.0})
        sink.log_metrics({"b": 10.0})
        sink.log_metrics({"a": 2.0, "b": 20.0})
        sink.log_metrics({"a": 3.0}, step=99)  # explicit step untouched
        sink.log_metrics({"a": 4.0})
        assert calls == [
            ("a", 1.0, 1), ("b", 10.0, 1),
            ("a", 2.0, 2), ("b", 20.0, 2),
            ("a", 3.0, 99),
            ("a", 4.0, 3),
        ]

    def test_compilation_cache_default_off_on_cpu(self, tmp_path,
                                                  monkeypatch):
        """The donation-corruption gate: on a CPU-configured platform
        the DEFAULT persistent cache stays off; an explicit dir still
        wins (deliberate operator choice, and what the existing
        test_compile_reuse config test exercises)."""
        import jax

        from active_learning_tpu.experiment import driver

        monkeypatch.setenv("JAX_PLATFORMS", "cpu")
        monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR", raising=False)
        old = jax.config.jax_compilation_cache_dir
        try:
            assert driver.enable_compilation_cache(None) is None
            explicit = str(tmp_path / "explicit_cache")
            assert driver.enable_compilation_cache(explicit) == explicit
            # $JAX_COMPILATION_CACHE_DIR is the same explicit opt-in as
            # the flag — the CPU gate suppresses only the implicit
            # default.
            env_dir = str(tmp_path / "env_cache")
            monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", env_dir)
            assert driver.enable_compilation_cache(None) == env_dir
        finally:
            # The enable leaks process-wide jax config; the REST of the
            # session must keep running cache-less (the very corruption
            # this gate exists for).
            jax.config.update("jax_compilation_cache_dir", old)
