"""The fleet chaos tests' run child: the REAL driver behind the REAL
CLI flag surface, at test size.

tests/test_fleet.py hands the fleet controller this script as its
``base_cmd`` — the controller appends exactly the argv it would hand
``python -m active_learning_tpu``, and this harness parses it with the
production parser (experiment/cli.get_parser + args_to_config), then
runs run_experiment with the tier-1 test fixtures (TinyClassifier,
tiny_train_config, 96-row synthetic data) instead of a real dataset.
Everything the fleet layer consumes — heartbeats, the round journal,
SIGTERM checkpoint-and-exit, ``--resume_training`` bit-identical
resume, the Prometheus scrape file, run_report.json — is the driver's
own machinery, untouched.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

_TESTS = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_TESTS)
for path in (_REPO, _TESTS):
    if path not in sys.path:
        sys.path.insert(0, path)


def main(argv=None):
    from helpers import TinyClassifier, tiny_train_config

    from active_learning_tpu.data.synthetic import get_data_synthetic
    from active_learning_tpu.experiment.cli import (args_to_config,
                                                    get_parser)
    from active_learning_tpu.experiment.driver import run_experiment
    from active_learning_tpu.faults.preempt import PreemptionRequested

    cfg = args_to_config(get_parser().parse_args(argv))
    # Fixed data config: the standalone baselines in test_fleet.py build
    # the same arrays, so experiment_state comparisons are meaningful.
    data = get_data_synthetic(n_train=96, n_test=32, num_classes=4,
                              image_size=8, seed=5)
    try:
        run_experiment(cfg, data=data, train_cfg=tiny_train_config(),
                       model=TinyClassifier(num_classes=4))
    except PreemptionRequested:
        return 0  # the CLI's mapping: graceful preemption exits 0
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
