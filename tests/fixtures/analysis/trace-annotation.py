# Golden negative case for check id ``trace-annotation``: uses
# jax.profiler.TraceAnnotation directly instead of utils.tracing.annotate.
import jax


def annotate(name):
    return jax.profiler.TraceAnnotation(name)
