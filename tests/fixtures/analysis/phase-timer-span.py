# Golden negative case for check id ``phase-timer-span``: a phase_timer
# that measures with its own clock instead of opening a tracer span —
# metrics and trace would silently fork.
import contextlib
import time


@contextlib.contextmanager
def phase_timer(name, metrics=None, round_idx=None):
    t0 = time.perf_counter()
    yield
    if metrics is not None:
        metrics(f"rd_{name}", time.perf_counter() - t0, round_idx)
