"""Golden negative case for the disk-pool-paging checker: paging-path
functions (named by the closed ``_PAGED_READERS`` registry) that
materialize the whole store — the constructor, subscript, and method
spellings of the same full-pool copy — plus a registered name no code
defines (registry drift)."""

import numpy as np

_PAGED_READERS = ("rogue_gather", "rogue_spill", "rogue_block",
                  "never_defined")


class RoguePool:
    def rogue_gather(self, idxs):
        whole = np.asarray(self._mm)  # whole-store copy in one call
        return whole[idxs]

    def rogue_block(self, b):
        return self._mm[:].copy()  # full slice AND .copy() — two reds


def rogue_spill(mm, source):
    rows = mm.tolist()  # the store as a python list: RAM times four
    return rows


def bounded_is_fine(mm, lo, hi):
    # Not registered, and bounded slices never flag anyway.
    return mm[lo:hi]
