# Golden negative case for check id ``phase-timer-import``: calls
# phase_timer without importing it from utils.tracing (a local copy or
# star-import would bypass the one-measurement contract).
def run_round(metrics):
    with phase_timer("query", metrics):  # noqa: F821 - the point
        pass
