# Golden negative case for check id ``collective-axis``: a collective
# over an unregistered axis literal, and the masked-psum owner-gather
# idiom hand-rolled outside parallel/mesh.owner_rows.
import jax
import jax.numpy as jnp


def gather_rows(pool, idxs):
    rows = pool[idxs]
    # VIOLATION: "rows" is not a *_AXIS constant in parallel/mesh.py.
    return jax.lax.psum(rows, "rows")


def owner_gather(arr, mask, axis="data"):
    picked = jnp.where(mask, arr, jnp.zeros((), arr.dtype))
    # VIOLATION: psum of a where-masked operand — the one spelling of
    # the owner-gather idiom is mesh_lib.owner_rows.
    return jax.lax.psum(picked, axis)


def owner_scatter(arr, mask, axis="data"):
    picked = jnp.where(mask, arr, jnp.zeros((), arr.dtype))
    # VIOLATION: psum_scatter of a where-masked operand — the one
    # spelling of the scattered owner-gather is
    # mesh_lib.owner_rows_scattered.
    return jax.lax.psum_scatter(picked, axis, scatter_dimension=0,
                                tiled=True)


def ring_feed(block, ndev, axis="data"):
    perm = [(i, (i + 1) % ndev) for i in range(ndev)]
    # VIOLATION: hand-rolled ring ppermute — the ring-feed idiom's one
    # home is parallel/mesh.ring_shift.
    return jax.lax.ppermute(block, axis, perm=perm)
