# Golden negative case for check id ``pipeline-coordinator``: a
# coordinator function that syncs the train stream.
import jax


def _worker(self):
    jax.block_until_ready(self.out)


def _worker_loop(self):
    pass


def _score_slice(self, plan, sl, variables):
    return jax.device_get(variables)


def _score_chunk(self, plan, sl, tag, variables, i):
    return None


def publish_best(self, r, e, v):
    pass


def finalize(self, r, e):
    pass


def consume(self, kind, keys, idxs, bs, variables):
    return None
