# Golden negative case for check id ``lock-discipline``: a field the
# registry declares guarded, read outside its lock by a second method.
import threading

_GUARDED_BY = {"_queue": "_lock"}


class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self._queue = []

    def push(self, item):
        with self._lock:
            self._queue.append(item)

    def steal(self):
        # VIOLATION: bare read-modify-write of the guarded deque — the
        # exact cross-thread race the checker exists to catch.
        return self._queue.pop()
