# Golden negative case for check id ``sharded-selection``: the sharded
# backend pulling the factor matrix whole onto host / replicating it.
import jax
import numpy as np


def _build_sharded_fns(mesh, nf):
    rows = np.asarray(jax.device_get(mesh))
    return rows


def _kcenter_greedy_sharded(factors, mask, budget):
    full = jax.device_get(factors)
    rep = mesh_lib.replicate(factors, None)  # noqa: F821
    return full, rep
