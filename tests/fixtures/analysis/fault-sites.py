# Golden negative case for check id ``fault-sites``: a typo'd site name,
# a non-literal site, and a RetryPolicy without classify=.
from active_learning_tpu import faults


def upload(name):
    faults.site("h2d_uplaod")  # typo'd: not in the registry
    faults.site(name)  # non-literal
    p = faults.RetryPolicy(site="x")  # no classify=
    return p
