"""Golden negative fixture for the diagnostics-inert check: a
"host-pure" diagnostics module that imports jax and syncs the device,
plus a strategy hook that reads .diagnostics with no flag gate — each
line below is a finding the checker must produce."""

_DIAGNOSTICS_HOST_PURE = True

import jax  # host-purity violation: jax import in a host-pure module
import numpy as np


def fetch_scores(device_scores):
    # host-purity violation: a device sync inside the diagnostics layer
    # (the caller must hand host arrays in).
    return np.asarray(jax.device_get(device_scores))


class LeakyStrategy:
    def query_hot_path(self, out):
        # gated-access violation: an unconditional .diagnostics hook on
        # the hot path — no if/ternary gate anywhere in the function.
        self.diagnostics.observe_scores("margin", out["margin"])
        return out
