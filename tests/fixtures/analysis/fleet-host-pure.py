"""Golden negative fixture for the fleet-host-pure check: a "fleet"
module that imports jax, references the jax name, journals through a
bare json.dump, and ships a write_atomic_json that lost its rename —
each marked line below is a finding the checker must produce."""

_FLEET_MODULE = True

import json
import os

import jax  # host-purity violation: jax import in a fleet module


def worker_backend():
    # host-purity violation: the jax name referenced on the head node.
    return jax.devices()


def save_state(path, payload):
    with open(path, "w") as fh:
        # atomic-journal violation: json.dump outside write_atomic_json
        # — a fleet file write that can tear.
        json.dump(payload, fh)


def write_atomic_json(path, payload):
    # violation: no os.replace — the "atomic" helper writes in place.
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(payload, fh)
    os.rename(tmp, path)
