# Golden negative case for check id ``phase-timer-fork``: a competing
# phase_timer definition outside utils/tracing.py.
def phase_timer(name):
    return name
