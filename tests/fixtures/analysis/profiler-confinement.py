# Golden negative case for check id ``profiler-confinement``: touching
# jax.profiler outside the telemetry/profiler.py gate.
import jax.profiler


def capture(d):
    jax.profiler.start_trace(d)
    jax.profiler.stop_trace()
