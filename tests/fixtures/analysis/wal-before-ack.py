"""Golden negative case for the wal-before-ack checker: an ingest
handler that constructs its ack BEFORE the WAL append (the durability
promise nothing backs yet), one that never appends at all, and a jax
import inside the handler module (host-purity violation)."""

import jax  # host-purity violation: the ack path must never touch a device

_INGEST_HANDLERS = ("rogue_pool_append", "rogue_label_attach")


def make_ack(ids):
    return {"ok": True, "ids": list(ids)}


def rogue_pool_append(wal, queue, req):
    rows = req["rows"]
    response = make_ack(range(len(rows)))  # ack built before durability
    wal.append({"kind": "pool", "rows": rows})
    return response


def rogue_label_attach(wal, queue, req):
    jax.block_until_ready(req)  # device wait on the ack path
    return make_ack(req["ids"])  # acks with no WAL append at all
