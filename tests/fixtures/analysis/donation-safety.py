# Golden negative case for check id ``donation-safety``: the donated
# state is read again after the call handed its buffer to XLA.
import functools

import jax


@functools.partial(jax.jit, donate_argnums=(0,))
def step(state, batch):
    return state + batch


def train(state, batches):
    out = step(state, batches[0])
    # VIOLATION: ``state``'s buffer was donated into the call above —
    # this read touches a deleted device array.
    return out + state.sum()
