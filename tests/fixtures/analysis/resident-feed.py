# Golden negative case for check id ``resident-feed``: a resident-feed
# trainer function that materializes image rows on the host.
import numpy as np


def _resident_feed_arrays(self, train_set):
    rows = np.asarray(train_set.gather(self.idxs))
    return rows, None


def _build_resident_batch_step(self):
    return None
