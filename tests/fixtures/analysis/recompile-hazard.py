# Golden negative case for check id ``recompile-hazard``: a jit outside
# the registered step-builders, plus an f-string static operand (a fresh
# object per call = a recompile per call).
import functools

import jax

_STEP_BUILDERS = ("build_step",)


def build_step(model):
    @jax.jit
    def step(variables, batch):
        return model(variables, batch)

    return step


# VIOLATION: a jitted def not named in _STEP_BUILDERS.
@functools.partial(jax.jit, static_argnames=("mode",))
def rogue_step(x, mode):
    return x


def call_it(x):
    # VIOLATION: an f-string as a static operand — a new string value
    # per distinct x, a new executable per distinct value.
    return rogue_step(x, mode=f"mode-{x}")
