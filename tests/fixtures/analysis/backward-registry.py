# Golden negative case for check id ``backward-registry``: a custom VJP
# dodging the ops/backward.py closed registry.
import jax


@jax.custom_vjp
def sneaky(x):
    return x
