"""The pod tier (ISSUE 15, DESIGN.md §15), pinned.

Four claims make the pod tier safe to turn on:

  1. WIRE TRUTH — the quantized reduce-scatter gradient sync
     (mesh.int8_reduce_scatter) moves FEWER bytes than the all-gather
     form at ndev >= 8, in the wire-model table AND in MEASURED
     optimized-HLO collective payload bytes (the collective_bytes_total
     methodology of PR 10, applied to the compiled executables), while
     staying inside its documented error bound, deterministic and
     replicated, and poisoning non-finite blocks like the f32 path
     would surface them.
  2. RING TRUTH — ring_shift rotates blocks so every shard sees every
     block exactly once, owner_rows_scattered assembles center blocks
     exactly (zeros + owner bits), and the ring-fed k-center scans stay
     bit-identical to the replicated scans (tests/test_pool_sharding.py
     pins the picks; the primitives are pinned here).
  3. GATING TRUTH — the reduce-scatter path sits behind the SAME
     learning probe + sticky-degrade journal machinery as PR 9's int8
     path (chaos-cased), and warm rounds under it add zero compiles.
  4. POD TRUTH — a REAL 2-process mesh (jax.distributed over localhost,
     gloo CPU collectives) produces experiment_state BIT-IDENTICAL to
     the single-process run at the same seeds, for Margin AND Coreset
     (slow-marked subprocess harness, tests/pod_harness.py).
"""

import dataclasses
import glob
import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from active_learning_tpu.parallel import mesh as mesh_lib
from active_learning_tpu.strategies import kcenter as kc
from active_learning_tpu.strategies import scoring

from helpers import TinyClassifier, tiny_train_config

NDEV = 8


def _run_sync(fn, x_global):
    """Run a gradient-sync tree function over the 8-device mesh; the
    result rides out PER DEVICE (each shard returns its full replicated
    copy) so replication is assertable, not assumed."""
    mesh = mesh_lib.make_mesh()

    def body(v):
        return fn({"g": v})["g"]

    out = jax.jit(shard_map(body, mesh=mesh, in_specs=(P("data"),),
                            out_specs=P("data"), check_rep=False))(
        jnp.asarray(x_global).reshape(-1))
    return np.asarray(out).reshape(NDEV, -1)


class TestWireResolution:
    def test_resolve_grad_allreduce_modes(self):
        one = mesh_lib.make_mesh(1)
        full = mesh_lib.make_mesh()
        for mode in ("int8", "int8_rs", "auto"):
            assert mesh_lib.resolve_grad_allreduce(mode, one) == "f32"
            assert mesh_lib.resolve_grad_allreduce(mode, full) == "int8"
        assert mesh_lib.resolve_grad_allreduce("f32", full) == "f32"
        with pytest.raises(ValueError):
            mesh_lib.resolve_grad_allreduce("int4", full)

    def test_resolve_int8_wire_crossover(self):
        full = mesh_lib.make_mesh()  # 8 devices: at the crossover
        assert mesh_lib.resolve_int8_wire("int8", full) == "allgather"
        assert mesh_lib.resolve_int8_wire("auto", full) == "allgather"
        assert mesh_lib.resolve_int8_wire("int8_rs", full) \
            == "reduce_scatter"

    def test_wire_model_table(self):
        """The pod-tier wire-model table: the all-gather form's bytes
        grow linearly with ndev (inverted vs the ~8n f32 ring past ~9
        devices — the documented PR 9 blowup), the reduce-scatter form
        stays ~2n regardless, and sits BELOW the all-gather form at
        every ndev >= 8 (the acceptance row)."""
        n = 10 ** 6
        for ndev in (8, 9, 16, 64, 256):
            ag = mesh_lib.wire_model_bytes("allgather", ndev, n)
            rs = mesh_lib.wire_model_bytes("reduce_scatter", ndev, n)
            f32 = mesh_lib.wire_model_bytes("f32", ndev, n)
            assert rs < ag, (ndev, rs, ag)
            assert rs < f32
            assert rs < 2 * (n + 4 * n // 256) + 1
        # The inversion the crossover rule encodes: past ~9 devices the
        # all-gather form moves MORE than the f32 ring it was meant to
        # beat.
        assert mesh_lib.wire_model_bytes("allgather", 9, n) \
            > mesh_lib.wire_model_bytes("f32", 9, n)
        assert mesh_lib.wire_model_bytes("allgather", 4, n) \
            < mesh_lib.wire_model_bytes("f32", 4, n)
        assert mesh_lib.wire_model_bytes("f32", 1, n) == 0
        with pytest.raises(ValueError):
            mesh_lib.wire_model_bytes("int4", 8, n)


class TestMeasuredWireBytes:
    def test_reduce_scatter_measures_below_allgather(self):
        """MEASURED wire bytes, not just modeled: compile both quantized
        sync forms for the same gradient size and read the collective
        payload bytes off the optimized HLO (telemetry/profiler.
        hlo_text_collective_bytes — the exact-shape half of PR 10's
        collective_bytes_total).  At the 8-device mesh the
        reduce-scatter form's total collective payload must land BELOW
        the all-gather form's — the wire claim, proven on the
        executables that would actually run."""
        from active_learning_tpu.telemetry import profiler as prof

        mesh = mesh_lib.make_mesh()
        n = NDEV * 100_000

        def compiled(fn):
            body = lambda v: fn({"g": v})["g"]  # noqa: E731
            return jax.jit(shard_map(
                body, mesh=mesh, in_specs=(P("data"),), out_specs=P(),
                check_rep=False)).lower(
                    jnp.zeros((n,), jnp.float32)).compile()

        ag = prof.hlo_text_collective_bytes(
            compiled(lambda t: mesh_lib.int8_allreduce(
                t, "data")).as_text())
        rs = prof.hlo_text_collective_bytes(
            compiled(lambda t: mesh_lib.int8_reduce_scatter(
                t, NDEV, "data")).as_text())
        assert ag and rs, "no collectives parsed from the optimized HLO"
        ag_total, rs_total = sum(ag.values()), sum(rs.values())
        assert rs_total < ag_total, (rs, ag)
        # The dominant ag payload is the full gathered int8 matrix
        # (~n * 1 byte per shard result); rs's biggest ops are the
        # 1/ndev-shard all_to_all + all_gather.
        assert ag_total > 0.9 * (n // NDEV) * NDEV
        assert rs_total < 3 * (n // NDEV) + 8192

    def test_int8_payloads_actually_int8(self):
        """The quantized payload rides the wire as s8, not a float that
        was quantized and silently promoted back before the collective:
        the optimized HLO's biggest all-to-all/all-gather carry 1-byte
        elements."""
        from active_learning_tpu.telemetry import profiler as prof

        mesh = mesh_lib.make_mesh()
        n = NDEV * 65536
        body = lambda v: mesh_lib.int8_reduce_scatter(  # noqa: E731
            {"g": v}, NDEV, "data")["g"]
        text = jax.jit(shard_map(
            body, mesh=mesh, in_specs=(P("data"),), out_specs=P(),
            check_rep=False)).lower(
                jnp.zeros((n,), jnp.float32)).compile().as_text()
        table = prof.hlo_text_collective_bytes(text)
        per_shard = n // NDEV
        # all-to-all result: my shard's int8 blocks from every peer —
        # exactly per_shard bytes.  A f32 payload would read 4x.
        a2a = [v for k, v in table.items() if k.startswith("all-to-all")]
        assert a2a and min(a2a) <= per_shard + 1024


class TestInt8ReduceScatter:
    def _exact_and_rs(self, x):
        exact = x.reshape(NDEV, -1).sum(0)
        rs = _run_sync(lambda t: mesh_lib.int8_reduce_scatter(
            t, NDEV, "data"), x)
        return exact, rs

    def test_bounded_error_and_replicated(self):
        rng = np.random.default_rng(3)
        x = (rng.normal(size=(NDEV, 4096)) * 0.01).astype(np.float32)
        exact, rs = self._exact_and_rs(x)
        # Replicated: every device holds the SAME dequantized bytes
        # (all consume the owner's all_gathered payload).
        for d in range(1, NDEV):
            np.testing.assert_array_equal(rs[d], rs[0])
        # Documented bound: first quantization <= ndev * scale1 / 2
        # summed, requantization <= scale2 / 2 — scale2 bounded via
        # |reduced| <= |exact| + ndev * scale1 / 2.
        block = mesh_lib.INT8_BLOCK
        blocks = x.reshape(NDEV, -1, block)
        s1 = np.abs(blocks).max(axis=(0, 2)) / 127.0  # shared pmax
        sum_err = NDEV * s1 / 2.0
        eblk = np.abs(exact.reshape(-1, block)).max(axis=1)
        s2 = (eblk + sum_err) / 127.0
        bound = np.repeat(sum_err + s2 / 2.0, block)
        assert (np.abs(rs[0] - exact) <= bound * 1.0001).all()

    def test_deterministic(self):
        rng = np.random.default_rng(4)
        x = (rng.normal(size=(NDEV, 1024)) * 3.0).astype(np.float32)
        _, a = self._exact_and_rs(x)
        _, b = self._exact_and_rs(x)
        np.testing.assert_array_equal(a, b)

    def test_nonfinite_block_poisons_to_nan(self):
        rng = np.random.default_rng(5)
        x = (rng.normal(size=(NDEV, 1024)) * 0.1).astype(np.float32)
        x[3, 7] = np.inf
        _, rs = self._exact_and_rs(x)
        blk = mesh_lib.INT8_BLOCK
        assert np.isnan(rs[0][:blk]).all()
        assert np.isfinite(rs[0][blk:]).all()

    def test_non_float_leaves_psum_exactly(self):
        ints = np.arange(NDEV * 16, dtype=np.int32)
        out = _run_sync(lambda t: mesh_lib.int8_reduce_scatter(
            t, NDEV, "data"), ints)
        np.testing.assert_array_equal(out[0],
                                      ints.reshape(NDEV, -1).sum(0))

    def test_padding_preserves_shape_and_tail(self):
        """A leaf whose size doesn't divide block * ndev round-trips at
        its own shape with the tail synced correctly (the pad is
        internal)."""
        rng = np.random.default_rng(6)
        x = (rng.normal(size=(NDEV, 333)) * 0.05).astype(np.float32)
        exact, rs = self._exact_and_rs(x)
        assert rs.shape[1] == 333
        assert np.abs(rs[0] - exact).max() < 0.05


class TestRingPrimitives:
    def test_ring_shift_rotates_right_and_closes(self):
        mesh = mesh_lib.make_mesh()
        x = np.arange(NDEV * 4, dtype=np.float32)

        def body(v):
            one = mesh_lib.ring_shift(v, NDEV)
            closed = one
            for _ in range(NDEV - 1):
                closed = mesh_lib.ring_shift(closed, NDEV)
            return one, closed

        one, closed = jax.jit(shard_map(
            body, mesh=mesh, in_specs=(P("data"),),
            out_specs=(P("data"), P("data")), check_rep=False))(
                jnp.asarray(x))
        # One shift: shard i holds shard i-1's block (right rotation).
        np.testing.assert_array_equal(np.asarray(one),
                                      np.roll(x.reshape(NDEV, 4), 1,
                                              axis=0).reshape(-1))
        # ndev shifts: home again — the every-block-exactly-once closure
        # the column scans rely on.
        np.testing.assert_array_equal(np.asarray(closed), x)

    def test_owner_rows_scattered_exact_slices(self):
        """Each shard receives ITS K/ndev slice of the owner-gathered
        rows, bit-exact (zeros + the owner's value), with unowned
        (sentinel) ids coming back as zero rows."""
        mesh = mesh_lib.make_mesh()
        rng = np.random.default_rng(7)
        arr = rng.normal(size=(NDEV * 4, 3)).astype(np.float32)
        ids = np.asarray([5, 31, 0, 17, 22, 9, 30, 2,
                          11, 4, 28, 3, 19, 7, 32, 32], np.int32)

        def body(a, i):
            return mesh_lib.owner_rows_scattered(a, i, "data")

        out = jax.jit(shard_map(
            body, mesh=mesh, in_specs=(P("data", None), P()),
            out_specs=P("data", None), check_rep=False))(
                jnp.asarray(arr), jnp.asarray(ids))
        got = np.asarray(out)
        want = np.where((ids < NDEV * 4)[:, None], arr[np.minimum(
            ids, NDEV * 4 - 1)], 0.0).astype(np.float32)
        np.testing.assert_array_equal(got, want)

    def test_ring_center_layout(self):
        cidx, cvalid = scoring.ring_center_layout(
            np.asarray([3, 9, 40]), sentinel=512, ndev=8, floor=64)
        assert len(cidx) == len(cvalid) and len(cidx) % 8 == 0
        assert len(cidx) >= 64
        np.testing.assert_array_equal(cidx[:3], [3, 9, 40])
        assert (cidx[3:] == 512).all()
        np.testing.assert_array_equal(cvalid[:3], [1.0, 1.0, 1.0])
        assert (cvalid[3:] == 0).all()
        # Bucketed: two labeled counts inside one bucket share a layout
        # length (compile reuse round over round).
        a, _ = scoring.ring_center_layout(np.arange(20), 512, 8)
        b, _ = scoring.ring_center_layout(np.arange(800), 512, 8)
        assert len(a) == len(b)

    def test_ring_feed_attribution(self):
        """kcenter_greedy publishes whether the ring feed ran — the
        bench rider's source of truth."""
        rng = np.random.default_rng(8)
        emb = rng.normal(size=(64, 4)).astype(np.float32)
        labeled = np.zeros(64, dtype=bool)
        labeled[:5] = True
        kc.kcenter_greedy((emb,), labeled, 5,
                          rng=np.random.default_rng(1),
                          pool_sharding="replicated")
        assert kc.LAST_RING_FEED is False
        kc.kcenter_greedy((emb,), labeled, 5,
                          rng=np.random.default_rng(1),
                          mesh=mesh_lib.make_mesh(), pool_sharding="row")
        assert kc.LAST_RING_FEED is True


class TestBatchScaling:
    def test_identity_at_scale_one(self):
        from active_learning_tpu.train.optim import apply_batch_scaling
        cfg = tiny_train_config()
        out, changed = apply_batch_scaling(cfg, 1)
        assert out is cfg and not changed

    def test_linear_rules_at_scale_eight(self):
        from active_learning_tpu.config import SchedulerConfig
        from active_learning_tpu.train.optim import apply_batch_scaling
        cfg = dataclasses.replace(
            tiny_train_config(batch_size=32),
            scheduler=SchedulerConfig(name="cosine", t_max=40,
                                      warmup_epochs=0))
        out, changed = apply_batch_scaling(cfg, 8)
        assert changed
        assert out.loader_tr.batch_size == 256
        assert out.optimizer.lr == pytest.approx(cfg.optimizer.lr * 8)
        assert out.scheduler.warmup_epochs == 5
        # A pre-configured LONGER warmup is never shortened.
        cfg2 = dataclasses.replace(
            cfg, scheduler=SchedulerConfig(name="cosine", t_max=40,
                                           warmup_epochs=9))
        out2, _ = apply_batch_scaling(cfg2, 8)
        assert out2.scheduler.warmup_epochs == 9

    def test_warmup_clamped_below_t_max(self):
        """A short schedule must not get a warmup _cosine_lr rejects
        (warm >= t_max raises)."""
        from active_learning_tpu.config import SchedulerConfig
        from active_learning_tpu.train.optim import (apply_batch_scaling,
                                                     make_lr_schedule)
        cfg = dataclasses.replace(
            tiny_train_config(),
            scheduler=SchedulerConfig(name="cosine", t_max=3,
                                      warmup_epochs=0))
        out, _ = apply_batch_scaling(cfg, 8)
        assert out.scheduler.warmup_epochs < out.scheduler.t_max
        make_lr_schedule(out.scheduler, out.optimizer.lr)  # must not raise

    def test_step_schedule_keeps_milestones(self):
        from active_learning_tpu.config import SchedulerConfig
        from active_learning_tpu.train.optim import apply_batch_scaling
        cfg = dataclasses.replace(
            tiny_train_config(),
            scheduler=SchedulerConfig(name="step", step_size=30,
                                      gamma=0.2))
        out, changed = apply_batch_scaling(cfg, 4)
        assert changed and out.scheduler == cfg.scheduler

    def test_driver_rejects_unknown_mode(self, tmp_path):
        from active_learning_tpu.config import ExperimentConfig
        from active_learning_tpu.experiment.driver import build_experiment
        from active_learning_tpu.data.synthetic import get_data_synthetic
        from active_learning_tpu.experiment import arg_pools  # noqa: F401
        cfg = ExperimentConfig(dataset="synthetic", arg_pool="synthetic",
                               scale_batch="always",
                               log_dir=str(tmp_path),
                               ckpt_path=str(tmp_path))
        data = get_data_synthetic(n_train=32, n_test=16)
        with pytest.raises(ValueError, match="scale_batch"):
            build_experiment(cfg, data=data,
                             train_cfg=tiny_train_config(),
                             model=TinyClassifier(num_classes=4))


class TestReduceScatterGating:
    def test_probe_passes_on_reduce_scatter_form(self):
        """The learning probe actually trains through the reduce-scatter
        step when the run requests it (int8_rs forces the form on the
        8-device mesh) and lands inside the pinned accuracy bound."""
        from active_learning_tpu.experiment import driver
        ok, delta = driver.run_grad_allreduce_probe(
            mesh_lib.make_mesh(), "int8_rs")
        assert ok, f"reduce-scatter probe failed (delta {delta})"
        assert delta is not None \
            and delta <= driver.INT8_PROBE_MAX_ACC_DELTA

    def test_trainer_resolves_wire_form(self):
        mesh = mesh_lib.make_mesh()
        from active_learning_tpu.train.trainer import Trainer
        t_rs = Trainer(TinyClassifier(),
                       dataclasses.replace(tiny_train_config(),
                                           grad_allreduce="int8_rs"),
                       mesh, 4)
        assert t_rs.grad_allreduce == "int8"
        assert t_rs.grad_sync_form == "reduce_scatter"
        t_ag = Trainer(TinyClassifier(),
                       dataclasses.replace(tiny_train_config(),
                                           grad_allreduce="int8"),
                       mesh, 4)
        assert t_ag.grad_sync_form == "allgather"
        t_f32 = Trainer(TinyClassifier(), tiny_train_config(), mesh, 4)
        assert t_f32.grad_sync_form is None

    def test_probe_failure_degrades_reduce_scatter_to_f32(self, tmp_path):
        """Chaos case (the grad_probe contract, extended to the new
        path): --grad_allreduce int8_rs with a broken probe completes
        on the bit-exact f32 sync — experiment_state identical to the
        f32 baseline — with the degrade journaled (the same sticky
        record a resume honors)."""
        from active_learning_tpu import faults
        from active_learning_tpu.config import (ExperimentConfig,
                                                TelemetryConfig)
        from active_learning_tpu.data.synthetic import get_data_synthetic
        from active_learning_tpu.experiment import arg_pools  # noqa: F401
        from active_learning_tpu.experiment.driver import run_experiment

        data = get_data_synthetic(n_train=96, n_test=32, num_classes=4,
                                  image_size=8, seed=5)

        def run(sub, **over):
            d = os.path.join(str(tmp_path), sub)
            cfg = ExperimentConfig(
                dataset="synthetic", arg_pool="synthetic",
                strategy="MarginSampler", rounds=2, round_budget=8,
                n_epoch=2, early_stop_patience=2, log_dir=d,
                ckpt_path=d, exp_hash=sub, round_pipeline="off",
                telemetry=TelemetryConfig(enabled=False), **over)
            run_experiment(cfg, data=data,
                           train_cfg=tiny_train_config(),
                           model=TinyClassifier(num_classes=4))
            state = dict(np.load(glob.glob(os.path.join(
                d, "*", "experiment_state.npz"))[0]))
            return d, state

        _, baseline = run("f32base")
        d, degraded = run("rsfault", grad_allreduce="int8_rs",
                          fault_spec="grad_probe:raise@1")
        for k in baseline:
            np.testing.assert_array_equal(baseline[k], degraded[k])
        jr = faults.read_journal(os.path.join(d, faults.JOURNAL_FILE))
        assert jr["status"] == "finished"
        assert jr["grad_allreduce"] == "f32_degraded"


class TestReduceScatterCompileReuse:
    def test_warm_rounds_zero_new_compiles_under_int8_rs(self, tmp_path):
        """The acceptance's every-new-path compile-freeness, on the
        reduce-scatter wire: 3 driver rounds under grad_allreduce=
        int8_rs (+ row sharding + ring feed via the default auto
        layout), rounds 1-2 at jit cache-miss delta 0 — probe and ring
        compiles all land in round 0's cold tax."""
        from active_learning_tpu.config import (ExperimentConfig,
                                                TelemetryConfig)
        from active_learning_tpu.data.synthetic import get_data_synthetic
        from active_learning_tpu.experiment import arg_pools  # noqa: F401
        from active_learning_tpu.experiment.driver import run_experiment
        from active_learning_tpu.utils.metrics import JsonlSink

        tmp = str(tmp_path)
        cfg = ExperimentConfig(
            dataset="synthetic", arg_pool="synthetic",
            strategy="CoresetSampler", rounds=3, round_budget=8,
            n_epoch=2, early_stop_patience=2, log_dir=tmp, ckpt_path=tmp,
            exp_hash="rswarm", round_pipeline="off",
            grad_allreduce="int8_rs",
            telemetry=TelemetryConfig(enabled=True,
                                      heartbeat_every_s=0.0))
        data = get_data_synthetic(n_train=96, n_test=32, num_classes=4,
                                  image_size=8, seed=5)
        strategy = run_experiment(
            cfg, sink=JsonlSink(tmp, experiment_key="rswarm"),
            data=data, train_cfg=tiny_train_config(),
            model=TinyClassifier(num_classes=4))
        assert strategy.trainer.grad_allreduce == "int8"
        assert strategy.trainer.grad_sync_form == "reduce_scatter"
        assert not strategy.trainer.grad_allreduce_degraded
        assert kc.LAST_RING_FEED is True  # coreset ran the ring feed
        deltas = {}
        with open(os.path.join(tmp, "metrics.jsonl")) as fh:
            for line in fh:
                ev = json.loads(line)
                if (ev.get("kind") == "metric"
                        and "jit_cache_miss_delta" in ev.get("metrics",
                                                             {})):
                    deltas[ev.get("step")] = \
                        ev["metrics"]["jit_cache_miss_delta"]
        assert set(deltas) == {0, 1, 2}
        assert deltas[0] > 0
        for rd in (1, 2):
            assert deltas[rd] == 0, (
                f"warm round {rd} compiled under int8_rs + ring feed: "
                f"{deltas[rd]} jit cache misses")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


HARNESS = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "pod_harness.py")


def _spawn(cfg: dict) -> subprocess.Popen:
    env = dict(os.environ)
    # The child pins its OWN platform/device-count env before importing
    # jax; the conftest's 8-device flags must not leak in.
    env.pop("XLA_FLAGS", None)
    return subprocess.Popen(
        [sys.executable, HARNESS, json.dumps(cfg)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env)


def _state(ckpt_path: str) -> dict:
    paths = glob.glob(os.path.join(ckpt_path, "*",
                                   "experiment_state.npz"))
    assert len(paths) == 1, paths
    return dict(np.load(paths[0]))


@pytest.mark.slow
class TestTwoProcessPod:
    """The pod acceptance: a REAL 2-process mesh (2 hosts x 2 devices
    over localhost DCN, gloo CPU collectives) runs the PRODUCTION
    driver end to end — row-sharded pool with per-process shard
    assembly, collective k-center with the ring column feed, the
    full fit/eval stack — and its experiment_state is bit-identical
    to the single-process 4-device run at the same seeds."""

    @pytest.mark.parametrize("strategy", ["MarginSampler",
                                          "CoresetSampler"])
    def test_two_process_state_bit_identical(self, tmp_path, strategy):
        base = str(tmp_path)
        sp_dir = os.path.join(base, "sp")
        mp_dir = os.path.join(base, "mp")
        os.makedirs(sp_dir)
        os.makedirs(mp_dir)
        port = _free_port()
        common = {"strategy": strategy, "exp_hash": "podtier"}
        sp = _spawn(dict(common, log_dir=sp_dir, ckpt_path=sp_dir,
                         local_devices=4))
        procs = [
            _spawn(dict(common, log_dir=mp_dir, ckpt_path=mp_dir,
                        local_devices=2,
                        coordinator=f"127.0.0.1:{port}",
                        num_processes=2, process_id=pid))
            for pid in (0, 1)
        ]
        outs = []
        for p in [sp] + procs:
            out, _ = p.communicate(timeout=600)
            outs.append(out)
        for p, out in zip([sp] + procs, outs):
            assert p.returncode == 0, out[-3000:]
            assert "POD_HARNESS_OK" in out, out[-3000:]
        sp_state = _state(sp_dir)
        mp_state = _state(mp_dir)
        assert set(sp_state) == set(mp_state)
        for k in sp_state:
            np.testing.assert_array_equal(
                sp_state[k], mp_state[k],
                err_msg=f"experiment_state[{k!r}] diverged between the "
                        "2-process pod and the single-process run")
