"""Child script for the 2-process CPU-mesh pod-tier harness
(tests/test_pod_tier.py) — NOT a test module.

Runs ONE production driver experiment over synthetic data, either as a
single process (the reference run) or as one process of a
jax.distributed pod over localhost (the pod-tier run:
mesh_lib.initialize_distributed arms gloo CPU collectives, the mesh
spans both processes' devices, the pool row-shards with per-process
shard assembly, and the k-center scans run their collective backend
over DCN-shaped collectives).  The parent compares the coordinator's
experiment_state bit for bit against the single-process run.

Usage: python pod_harness.py '<json config>'
Keys: log_dir, ckpt_path, exp_hash, strategy, local_devices,
      coordinator (optional), num_processes (optional),
      process_id (optional), grad_allreduce (optional),
      scale_batch (optional).
"""

import json
import os
import sys


def main() -> int:
    cfg_in = json.loads(sys.argv[1])
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = (
        "--xla_force_host_platform_device_count="
        f"{int(cfg_in['local_devices'])}")
    tests_dir = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, tests_dir)  # helpers.py
    sys.path.insert(0, os.path.dirname(tests_dir))  # the package root

    from active_learning_tpu.config import (ExperimentConfig,
                                            TelemetryConfig)
    from active_learning_tpu.data.synthetic import get_data_synthetic
    from active_learning_tpu.experiment import arg_pools  # noqa: F401
    from active_learning_tpu.experiment.driver import run_experiment
    from helpers import TinyClassifier, tiny_train_config

    cfg = ExperimentConfig(
        dataset="synthetic", arg_pool="synthetic",
        strategy=cfg_in["strategy"], rounds=2, round_budget=8,
        n_epoch=2, early_stop_patience=2,
        log_dir=cfg_in["log_dir"], ckpt_path=cfg_in["ckpt_path"],
        exp_hash=cfg_in["exp_hash"], round_pipeline="off",
        pool_sharding="row",
        grad_allreduce=cfg_in.get("grad_allreduce"),
        scale_batch=cfg_in.get("scale_batch"),
        telemetry=TelemetryConfig(enabled=False),
        coordinator_address=cfg_in.get("coordinator"),
        num_processes=cfg_in.get("num_processes"),
        process_id=cfg_in.get("process_id"),
    )
    # The SAME seeds and data on every path: bit-identity is the claim.
    data = get_data_synthetic(n_train=96, n_test=32, num_classes=4,
                              image_size=8, seed=5)
    strategy = run_experiment(cfg, data=data,
                              train_cfg=tiny_train_config(),
                              model=TinyClassifier(num_classes=4))
    # The claim is bit-identity OF THE ROW-SHARDED PATH — a silent
    # replicated fallback on both sides would also compare equal, so
    # the layout that actually ran is asserted, not assumed.
    assert strategy.trainer.pool_sharding == "row", \
        strategy.trainer.pool_sharding
    if cfg_in["strategy"] == "CoresetSampler":
        from active_learning_tpu.strategies import kcenter as kc
        assert kc.LAST_SHARDING == "row", kc.LAST_SHARDING
        assert kc.LAST_RING_FEED is True
    print("POD_HARNESS_OK", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
