"""The gradient path, proven (ISSUE 10, DESIGN.md §4):

  * the two hand-written backwards (ops/backward.py: the s2d stem
    conv's f32-accumulated dW, FusedBatchNorm's bf16-reads/f32-
    accumulation backward) are gradient-equivalent to the flax/XLA-
    derived backward — proven the same way the s2d FORWARD was:
    rounding-order tolerance at bf16, ~1e-10 identity at f64;
  * the fused optimizer update is BIT-identical to the optax chain at
    f32 state (and at bf16 momentum still learns, bounded-delta),
    end-to-end: a 2-round driver run with the fused path on vs off
    produces bit-identical experiment_state;
  * ``Trainer.reinit_optimizer`` reuses the donated momentum buffers at
    round boundaries instead of re-allocating;
  * the int8 block-scaled gradient all-reduce stays inside its error
    bound on the multi-device CPU mesh and the driver's learning-probe
    gate passes (its accuracy-delta bound pinned here).

``PARITY_TESTED_VJPS`` is the registered half of trace_lint check 9's
closed registry: it must match ops/backward.TRAIN_PATH_VJPS exactly, so
a custom backward without a parity test here can never land.
"""

import dataclasses
import gc
import glob
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax
from jax import lax

from active_learning_tpu.ops import backward as backward_ops

# The closed-registry handshake with scripts/trace_lint.py check 9:
# every entry of ops/backward.TRAIN_PATH_VJPS must appear here, and the
# classes below must actually test each one.
PARITY_TESTED_VJPS = ("stem_conv", "fused_bn_train")

PAD = ((2, 1), (2, 1))
_DN = ("NHWC", "HWIO", "NHWC")


def test_registry_matches_ops_module():
    assert set(PARITY_TESTED_VJPS) == set(backward_ops.TRAIN_PATH_VJPS)


def _ref_stem_conv(x, k, dt):
    """The exact flax nn.Conv chain stem_conv replaces: promote both
    operands to the compute dtype, stride-1 NHWC conv."""
    return lax.conv_general_dilated(x.astype(dt), k.astype(dt), (1, 1),
                                    PAD, dimension_numbers=_DN)


class TestStemConvVJP:
    def _data(self, seed=0, b=2, hw=12, c=12, f=16):
        rng = np.random.default_rng(seed)
        x = jnp.asarray(rng.normal(size=(b, hw, hw, c)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(4, 4, c, f)), jnp.float32)
        cot = jnp.asarray(rng.normal(size=(b, hw, hw, f)), jnp.float32)
        return x, k, cot

    def test_forward_bit_identical_to_nn_conv(self):
        """The primal is the SAME conv flax emits — forward parity
        contracts (s2d logits equivalence, checkpoint trees) hold
        bit-for-bit in both compute dtypes."""
        x, k, _ = self._data()
        for dt in (jnp.float32, jnp.bfloat16):
            ref = _ref_stem_conv(x, k, dt)
            got = backward_ops.stem_conv(x, k, dtype=dt, padding=PAD)
            assert got.dtype == ref.dtype
            np.testing.assert_array_equal(np.asarray(got, np.float32),
                                          np.asarray(ref, np.float32))

    def _grads(self, fn, x, k, cot):
        def loss(x_, k_):
            return jnp.sum((fn(x_, k_) * cot.astype(fn(x_, k_).dtype))
                           .astype(jnp.float32))
        return jax.grad(loss, argnums=(0, 1))(x, k)

    def test_grads_match_xla_derived_f32(self):
        """At f32 the hand-written backward emits the same convs XLA's
        transpose rule derives — grads agree to reduction-order
        rounding (measured bit-identical on XLA:CPU; pinned to 1e-6)."""
        x, k, cot = self._data()
        gx_r, gk_r = self._grads(
            lambda a, b: _ref_stem_conv(a, b, jnp.float32), x, k, cot)
        gx_c, gk_c = self._grads(
            lambda a, b: backward_ops.stem_conv(a, b, dtype=jnp.float32,
                                                padding=PAD), x, k, cot)
        np.testing.assert_allclose(gx_c, gx_r, rtol=1e-6, atol=1e-6)
        np.testing.assert_allclose(gk_c, gk_r, rtol=1e-6, atol=1e-6)

    def test_grads_match_xla_derived_bf16_tolerance(self):
        """bf16 compute: dx identical (same transposed conv); dW agrees
        to bf16 rounding order — the f32 ACCUMULATION changes rounding,
        never the math (the f64 test below pins the identity)."""
        x, k, cot = self._data(seed=1)
        xb = x.astype(jnp.bfloat16)
        gx_r, gk_r = self._grads(
            lambda a, b: _ref_stem_conv(a, b, jnp.bfloat16), xb, k, cot)
        gx_c, gk_c = self._grads(
            lambda a, b: backward_ops.stem_conv(a, b, dtype=jnp.bfloat16,
                                                padding=PAD), xb, k, cot)
        np.testing.assert_array_equal(np.asarray(gx_c, np.float32),
                                      np.asarray(gx_r, np.float32))
        np.testing.assert_allclose(np.asarray(gk_c), np.asarray(gk_r),
                                   rtol=2e-2, atol=2e-2)

    def test_f64_identity(self):
        """The identity proof: at f64 every cast is a no-op and the
        hand-written formulas must reproduce autodiff to accumulated
        rounding noise (~1e-10) — the bf16 delta above is rounding
        order, not an algebraic error."""
        with jax.experimental.enable_x64():
            rng = np.random.default_rng(2)
            x = jnp.asarray(rng.normal(size=(2, 10, 10, 12)))
            k = jnp.asarray(rng.normal(size=(4, 4, 12, 8)))
            cot = jnp.asarray(rng.normal(size=(2, 10, 10, 8)))
            gx_r, gk_r = self._grads(
                lambda a, b: _ref_stem_conv(a, b, jnp.float64), x, k, cot)
            gx_c, gk_c = self._grads(
                lambda a, b: backward_ops.stem_conv(
                    a, b, dtype=jnp.float64, padding=PAD), x, k, cot)
            np.testing.assert_allclose(gx_c, gx_r, rtol=1e-10, atol=1e-10)
            np.testing.assert_allclose(gk_c, gk_r, rtol=1e-10, atol=1e-10)

    def test_bf16_dw_no_less_accurate_than_xla_derivation(self):
        """The point of the custom dW: f32 accumulation over bf16 reads
        is at least as close to the f64 truth as XLA's bf16-accumulate-
        then-cast derivation (strictly closer as the contraction
        grows; never worse)."""
        x, k, cot = self._data(seed=3, b=4, hw=16, c=12, f=24)
        with jax.experimental.enable_x64():
            x64 = jnp.asarray(np.asarray(x), jnp.float64)
            k64 = jnp.asarray(np.asarray(k), jnp.float64)
            cot64 = jnp.asarray(np.asarray(cot), jnp.float64)
            dw_true = np.asarray(jax.grad(
                lambda k_: jnp.sum(_ref_stem_conv(x64, k_, jnp.float64)
                                   * cot64))(k64))
        _, dw_xla = self._grads(
            lambda a, b: _ref_stem_conv(a, b, jnp.bfloat16),
            x.astype(jnp.bfloat16), k, cot)
        _, dw_cust = self._grads(
            lambda a, b: backward_ops.stem_conv(a, b, dtype=jnp.bfloat16,
                                                padding=PAD),
            x.astype(jnp.bfloat16), k, cot)
        e_xla = np.linalg.norm(np.asarray(dw_xla, np.float64) - dw_true)
        e_cust = np.linalg.norm(np.asarray(dw_cust, np.float64) - dw_true)
        assert e_cust <= e_xla * 1.05, (
            f"f32-accumulated dW err {e_cust:.3e} worse than XLA's "
            f"bf16 derivation {e_xla:.3e}")

    def test_model_level_s2d_grads_match_nn_conv_model(self):
        """Through the real module: an s2d-stem encoder's gradients
        (S2DStemConv, custom VJP) match a twin whose stem is the plain
        nn.Conv it replaced — at f32, to reduction-order rounding."""
        from flax import linen as nn

        from active_learning_tpu.models import resnet

        class _Twin(nn.Module):
            custom: bool = True

            @nn.compact
            def __call__(self, x):
                if self.custom:
                    y = resnet.S2DStemConv(8, dtype=jnp.float32,
                                           name="conv_stem")(x)
                else:
                    y = nn.Conv(8, (4, 4), (1, 1),
                                padding=[(2, 1), (2, 1)], use_bias=False,
                                dtype=jnp.float32,
                                kernel_init=resnet.conv_kernel_init,
                                name="conv_stem")(x)
                return jnp.sum(y.astype(jnp.float32) ** 2)

        rng = np.random.default_rng(4)
        x = jnp.asarray(rng.normal(size=(2, 8, 8, 12)), jnp.float32)
        v = _Twin(custom=True).init(jax.random.PRNGKey(0), x)
        g_c = jax.grad(lambda p: _Twin(custom=True).apply(p, x))(v)
        g_r = jax.grad(lambda p: _Twin(custom=False).apply(p, x))(v)
        leaves_c = jax.tree.leaves(g_c)
        leaves_r = jax.tree.leaves(g_r)
        for a, b in zip(leaves_c, leaves_r):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6, atol=1e-6)


class TestFusedBNVJP:
    def _data(self, seed=0, shape=(4, 6, 6, 16)):
        rng = np.random.default_rng(seed)
        x = jnp.asarray(rng.normal(size=shape) * 2 + 1, jnp.float32)
        scale = jnp.asarray(rng.normal(size=shape[-1:]) + 1.0, jnp.float32)
        bias = jnp.asarray(rng.normal(size=shape[-1:]), jnp.float32)
        cot = jnp.asarray(rng.normal(size=shape), jnp.float32)
        return x, scale, bias, cot

    @staticmethod
    def _ref(x, scale, bias, dt, eps=1e-5):
        """The pre-custom-VJP FusedBatchNorm train-branch math, inline
        (autodiff of THIS is the XLA-derived backward being matched)."""
        acc = jnp.promote_types(dt, jnp.float32)
        xs = x.astype(dt)
        mean = jnp.mean(xs, (0, 1, 2), dtype=acc)
        mean2 = jnp.mean(lax.square(xs.astype(acc)), (0, 1, 2))
        var = jnp.maximum(mean2 - lax.square(mean), 0.0)
        mul = (scale * lax.rsqrt(var + eps)).astype(dt)
        sub = mean.astype(dt) * mul - bias.astype(dt)
        return x.astype(dt) * mul - sub

    @staticmethod
    def _cust(x, scale, bias, dt, eps=1e-5):
        return backward_ops.fused_bn_train(x, scale, bias, dtype=dt,
                                           epsilon=eps)[0]

    def _grads(self, fn, x, scale, bias, cot, dt):
        def loss(x_, s_, b_):
            y = fn(x_, s_, b_, dt)
            return jnp.sum((y * cot.astype(y.dtype)).astype(jnp.float32))
        return jax.grad(loss, argnums=(0, 1, 2))(x, scale, bias)

    def test_forward_bit_identical(self):
        x, scale, bias, _ = self._data()
        for dt in (jnp.float32, jnp.bfloat16):
            ref = self._ref(x.astype(dt) if dt == jnp.bfloat16 else x,
                            scale, bias, dt)
            got = self._cust(x.astype(dt) if dt == jnp.bfloat16 else x,
                             scale, bias, dt)
            assert got.dtype == ref.dtype
            np.testing.assert_array_equal(np.asarray(got, np.float32),
                                          np.asarray(ref, np.float32))

    def test_grads_match_xla_derived_f32(self):
        x, scale, bias, cot = self._data(seed=1)
        g_r = self._grads(self._ref, x, scale, bias, cot, jnp.float32)
        g_c = self._grads(self._cust, x, scale, bias, cot, jnp.float32)
        for a, b in zip(g_c, g_r):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-5, atol=2e-6)

    def test_grads_match_xla_derived_bf16_tolerance(self):
        x, scale, bias, cot = self._data(seed=2)
        xb = x.astype(jnp.bfloat16)
        g_r = self._grads(self._ref, xb, scale, bias, cot, jnp.bfloat16)
        g_c = self._grads(self._cust, xb, scale, bias, cot, jnp.bfloat16)
        # dscale/dbias fold Σgy·x − Σgy·mean style cancellations whose
        # bf16 reduction-order differences reach a few percent of the
        # tensor max — rounding order, not algebra (the f64 test pins
        # the identity at 1e-10).
        for a, b, tol in zip(g_c, g_r, (3e-2, 6e-2, 6e-2)):
            a32 = np.asarray(a, np.float32)
            b32 = np.asarray(b, np.float32)
            ref_mag = float(np.max(np.abs(b32))) + 1e-12
            assert float(np.max(np.abs(a32 - b32))) <= tol * ref_mag

    def test_f64_identity(self):
        with jax.experimental.enable_x64():
            rng = np.random.default_rng(3)
            x = jnp.asarray(rng.normal(size=(3, 5, 5, 8)) + 0.5)
            scale = jnp.asarray(rng.normal(size=(8,)) + 1.0)
            bias = jnp.asarray(rng.normal(size=(8,)))
            cot = jnp.asarray(rng.normal(size=x.shape))
            g_r = self._grads(self._ref, x, scale, bias, cot, jnp.float64)
            g_c = self._grads(self._cust, x, scale, bias, cot, jnp.float64)
            for a, b in zip(g_c, g_r):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=1e-10, atol=1e-10)

    def test_f64_identity_vs_flax_batchnorm(self):
        """At f64 the fused-stats math and flax's materialize-as-f32
        BatchNorm are the SAME function — gradients through the real
        modules (custom VJP vs flax autodiff) agree to ~1e-10, tying
        the custom backward to the flax reference, not just to our own
        forward."""
        from flax import linen as nn

        from active_learning_tpu.models.resnet import FusedBatchNorm

        with jax.experimental.enable_x64():
            rng = np.random.default_rng(4)
            x = jnp.asarray(rng.normal(size=(4, 5, 5, 6)) + 1.0)
            cot = jnp.asarray(rng.normal(size=x.shape))
            fused = FusedBatchNorm(use_running_average=False,
                                   dtype=jnp.float64)
            ref = nn.BatchNorm(use_running_average=False, momentum=0.9,
                               epsilon=1e-5, dtype=jnp.float64)
            v = fused.init(jax.random.PRNGKey(0), x)
            v = jax.tree.map(
                lambda l: l + 0.1 * np.arange(l.size).reshape(l.shape)
                if l.ndim else l, v)

            def loss(module):
                def inner(params):
                    y, _ = module.apply(
                        {"params": params,
                         "batch_stats": v["batch_stats"]},
                        x, mutable=["batch_stats"])
                    return jnp.sum(y * cot)
                return inner

            g_f = jax.grad(loss(fused))(v["params"])
            g_r = jax.grad(loss(ref))(v["params"])
            for a, b in zip(jax.tree.leaves(g_f), jax.tree.leaves(g_r)):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=1e-10, atol=1e-10)

    def test_running_stats_update_unchanged(self):
        """The EMA update rides the custom VJP's returned mean/var —
        batch_stats after one train-mode apply are bit-identical to the
        inline-math module the custom replaced."""
        from active_learning_tpu.models.resnet import FusedBatchNorm

        x, scale, bias, _ = self._data(seed=5)
        xb = x.astype(jnp.bfloat16)
        mod = FusedBatchNorm(use_running_average=False,
                             dtype=jnp.bfloat16)
        v = mod.init(jax.random.PRNGKey(0), xb)
        _, mut = mod.apply(v, xb, mutable=["batch_stats"])
        # Reference EMA from the same forward math.
        acc = jnp.float32
        mean = jnp.mean(xb, (0, 1, 2), dtype=acc)
        mean2 = jnp.mean(lax.square(xb.astype(acc)), (0, 1, 2))
        var = jnp.maximum(mean2 - lax.square(mean), 0.0)
        np.testing.assert_array_equal(
            np.asarray(mut["batch_stats"]["mean"]),
            np.asarray(0.9 * v["batch_stats"]["mean"] + 0.1 * mean))
        np.testing.assert_array_equal(
            np.asarray(mut["batch_stats"]["var"]),
            np.asarray(0.9 * v["batch_stats"]["var"] + 0.1 * var))


class TestFusedOptimizerParity:
    def _trees(self, seed=0):
        rng = np.random.default_rng(seed)
        params = {"a": jnp.asarray(rng.normal(size=(33, 7)), jnp.float32),
                  "b": {"w": jnp.asarray(rng.normal(size=(130,)),
                                         jnp.float32)}}
        return params

    @pytest.mark.parametrize("wd", [0.0, 5e-4])
    def test_bit_parity_vs_optax_chain(self, wd):
        """The fused leaf expression is the optax chain's scalar op
        sequence exactly: several steps of both paths stay bit-equal,
        with and without weight decay."""
        from active_learning_tpu.config import OptimizerConfig, TrainConfig
        from active_learning_tpu.train import optim as optim_lib

        cfg = TrainConfig(optimizer=OptimizerConfig(
            name="sgd", lr=0.1, momentum=0.9, weight_decay=wd))
        fused = optim_lib.make_fused_optimizer(cfg)
        assert fused is not None
        tx = optim_lib.make_optimizer(cfg.optimizer)

        params_f = self._trees()
        params_o = jax.tree.map(jnp.copy, params_f)
        state_f = fused.init(params_f)
        state_o = tx.init(params_o)
        rng = np.random.default_rng(1)
        for step in range(5):
            grads = jax.tree.map(
                lambda p: jnp.asarray(rng.normal(size=p.shape),
                                      jnp.float32), params_f)
            lr = jnp.float32(0.1 * (0.9 ** step))
            params_f, state_f = fused.update(grads, state_f, params_f, lr)
            updates, state_o = tx.update(grads, state_o, params_o)
            updates = jax.tree.map(lambda u: -lr * u, updates)
            params_o = optax.apply_updates(params_o, updates)
            for a, b in zip(jax.tree.leaves(params_f),
                            jax.tree.leaves(params_o)):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_fused_on_rule(self):
        from active_learning_tpu.config import OptimizerConfig, TrainConfig
        from active_learning_tpu.train import optim as optim_lib

        sgd = TrainConfig(optimizer=OptimizerConfig(name="sgd"))
        adam = TrainConfig(optimizer=OptimizerConfig(name="adam"))
        assert optim_lib.make_fused_optimizer(sgd) is not None
        assert optim_lib.make_fused_optimizer(
            dataclasses.replace(sgd, fused_optimizer="off")) is None
        assert optim_lib.make_fused_optimizer(adam) is None
        with pytest.raises(ValueError):
            optim_lib.make_fused_optimizer(
                dataclasses.replace(adam, fused_optimizer="on"))

    def test_bf16_state_halves_bytes_and_learns(self):
        """bf16 momentum: half the optimizer HBM, and the bounded-delta
        learn contract — the probe fit reaches the f32 twin's accuracy
        within 0.1 on the deterministic synthetic task."""
        from active_learning_tpu.config import (LoaderConfig,
                                                OptimizerConfig,
                                                SchedulerConfig,
                                                TrainConfig)
        from active_learning_tpu.data.synthetic import get_data_synthetic
        from active_learning_tpu.parallel import mesh as mesh_lib
        from active_learning_tpu.train.trainer import Trainer

        from helpers import TinyClassifier

        data = get_data_synthetic(n_train=96, n_test=128, num_classes=4,
                                  image_size=16, seed=7)
        mesh = mesh_lib.make_mesh()
        base = TrainConfig(
            loader_tr=LoaderConfig(batch_size=16),
            loader_te=LoaderConfig(batch_size=16),
            optimizer=OptimizerConfig(name="sgd", lr=0.3,
                                      weight_decay=5e-4),
            scheduler=SchedulerConfig(name="cosine", t_max=8),
            resident_scoring_bytes=0)

        def fit_acc(state_dtype):
            cfg = dataclasses.replace(base,
                                      optim_state_dtype=state_dtype)
            tr = Trainer(TinyClassifier(), cfg, mesh, 4)
            st = tr.init_state(jax.random.PRNGKey(1),
                               data[2].gather(np.zeros(1, np.int64)))
            if state_dtype == "bf16":
                trace = jax.tree.leaves(st.opt_state)
                assert all(t.dtype == jnp.bfloat16 for t in trace)
                f32_bytes = sum(p.nbytes for p in
                                jax.tree.leaves(st.params))
                assert sum(t.nbytes for t in trace) == f32_bytes // 2
            res = tr.fit(st, data[2], np.arange(len(data[2])), data[2],
                         np.array([], np.int64), n_epoch=8,
                         es_patience=0, rng=np.random.default_rng(1))
            m = tr.evaluate(res.state, data[1],
                            np.arange(len(data[1])))
            return float(m["accuracy"])

        acc_f32 = fit_acc("f32")
        acc_bf16 = fit_acc("bf16")
        assert acc_f32 >= 0.9  # the task saturates; a broken path won't
        assert abs(acc_f32 - acc_bf16) <= 0.1, (
            f"bf16 momentum delta too large: {acc_f32} vs {acc_bf16}")


class TestReinitOptimizerReuse:
    def _trainer_and_state(self):
        from active_learning_tpu.data.synthetic import get_data_synthetic
        from active_learning_tpu.parallel import mesh as mesh_lib
        from active_learning_tpu.train.trainer import Trainer

        from helpers import TinyClassifier, tiny_train_config

        train_set, _, al_set = get_data_synthetic(n_train=64, n_test=16)
        mesh = mesh_lib.make_mesh()
        trainer = Trainer(TinyClassifier(), tiny_train_config(), mesh, 4)
        state = trainer.init_state(jax.random.PRNGKey(0),
                                   train_set.gather(np.arange(2)))
        return trainer, state, train_set, al_set

    def test_round_boundary_reuses_buffers_without_reallocation(self):
        """The satellite pin: reinit zeroes the donated momentum tree
        through ONE jitted executable (no per-round host re-build +
        re-upload), keeps shapes/dtypes/sharding, and no extra device
        allocation survives the round boundary (live-array census flat
        across repeated reinits; on TPU the donation also reuses the
        buffers in place — CPU lacks aliasing, so the census is the
        portable assertion)."""
        trainer, state, train_set, al_set = self._trainer_and_state()
        assert trainer.fused_tx is not None
        # Make the momentum non-zero so zeroing is observable.
        res = trainer.fit(state, train_set, np.arange(32), al_set,
                          np.arange(56, 64), n_epoch=1, es_patience=0,
                          rng=np.random.default_rng(0))
        state = res.state
        shapes = jax.tree.map(lambda l: (l.shape, str(l.dtype)),
                              state.opt_state)
        state = trainer.reinit_optimizer(state)
        assert trainer._reinit_opt is not None
        assert trainer._reinit_opt._cache_size() == 1
        assert jax.tree.map(lambda l: (l.shape, str(l.dtype)),
                            state.opt_state) == shapes
        assert all(float(jnp.max(jnp.abs(l))) == 0.0
                   for l in jax.tree.leaves(state.opt_state))
        gc.collect()
        census = len(jax.live_arrays())
        for _ in range(3):
            state = trainer.reinit_optimizer(state)
        gc.collect()
        assert len(jax.live_arrays()) <= census
        # ... and still exactly one compiled executable (warm rounds
        # add zero compiles).
        assert trainer._reinit_opt._cache_size() == 1

    def test_stale_optax_fit_state_discarded_not_crashed(self, tmp_path):
        """A mid-round fit state written by the OPTAX path (pre-fused
        checkpoint, or a --fused_optimizer flip between launch and
        resume) has a different opt_state pytree layout: the fused
        trainer must discard it and restart the round from scratch —
        never crash the resume on the layout mismatch."""
        from active_learning_tpu.train import checkpoint as ckpt_lib

        trainer, state, train_set, al_set = self._trainer_and_state()
        assert trainer.fused_tx is not None
        # An optax-layout opt_state, serialized the way save_fit_state
        # would have under fused_optimizer=off.
        optax_state = trainer.tx.init(
            jax.tree.map(np.asarray, state.params))
        paths = ckpt_lib.weight_paths(str(tmp_path), "fusedmig", "t", 0)
        ckpt_lib.save_fit_state(
            paths["fit_state"], variables=state.variables,
            opt_state=optax_state, step=jnp.int32(4), epoch=1,
            round_idx=0, best_perf=0.5, best_epoch=1, es_count=0,
            key=jax.random.PRNGKey(3), rng=np.random.default_rng(3))
        res = trainer.fit(state, train_set, np.arange(32), al_set,
                          np.arange(56, 64), n_epoch=2, es_patience=2,
                          rng=np.random.default_rng(0), round_idx=0,
                          weight_paths=paths, resume_fit_state=True)
        # The round ran FROM SCRATCH (both epochs), and the stale state
        # is gone so a later resume can't trip over it either.
        assert res.epochs_run == 2
        assert ckpt_lib.load_fit_state(paths["fit_state"], 0) is None

    def test_reinit_falls_back_on_dead_buffers(self):
        """A failed round attempt's restore leaves the donated
        opt_state of the crashed fit behind — reinit must detect the
        dead buffers and re-init fresh instead of reading them."""
        trainer, state, _, _ = self._trainer_and_state()
        # Simulate the donated-away state: delete the buffers.
        for leaf in jax.tree.leaves(state.opt_state):
            leaf.delete()
        state2 = trainer.reinit_optimizer(state)
        assert all(float(jnp.max(jnp.abs(l))) == 0.0
                   for l in jax.tree.leaves(state2.opt_state))


class TestInt8Allreduce:
    def test_matches_exact_psum_within_bound(self):
        """The unit contract on the multi-device CPU mesh: the
        block-scaled int8 sum lands within ndev * scale / 2 of the
        exact f32 psum per element, and is identical across devices."""
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        from active_learning_tpu.parallel import mesh as mesh_lib

        mesh = mesh_lib.make_mesh()
        ndev = mesh.devices.size
        assert ndev > 1
        rng = np.random.default_rng(0)
        # Per-device distinct values, including a >1e3 outlier block to
        # exercise the per-block scales.
        local = rng.normal(size=(ndev, 1000)).astype(np.float32)
        local[:, :8] *= 1e3
        full = jnp.asarray(local.reshape(-1))

        def body(x):
            return mesh_lib.int8_allreduce({"g": x}, "data")["g"]

        got = shard_map(body, mesh=mesh, in_specs=P("data"),
                        out_specs=P("data"), check_rep=False)(full)
        got = np.asarray(got).reshape(ndev, -1)
        # Replicated result: every device's copy identical.
        assert all(np.array_equal(got[0], got[i]) for i in range(ndev))
        exact = local.sum(axis=0)
        block = mesh_lib.INT8_BLOCK
        padded = np.zeros(((local.shape[1] + block - 1) // block * block,),
                          np.float32)
        bound = np.zeros_like(padded)
        for d in range(ndev):
            padded[:local.shape[1]] = np.abs(local[d])
            bound = np.maximum(bound, padded)
        scales = bound.reshape(-1, block).max(axis=1) / 127.0
        per_elem = np.repeat(scales, block)[:local.shape[1]]
        err = np.abs(got[0] - exact)
        assert np.all(err <= ndev * per_elem / 2 + 1e-6), (
            f"int8 allreduce outside its error bound: "
            f"max excess {np.max(err - ndev * per_elem / 2)}")
        # And it is genuinely close: quantization, not garbage.
        assert np.linalg.norm(got[0] - exact) <= \
            0.05 * np.linalg.norm(exact) + 1e-6

    def test_nonfinite_blocks_poison_to_nan(self):
        """A loss spike must stay VISIBLE: an inf/NaN gradient block
        comes back NaN (like the f32 psum would surface it), never
        quantized to silent zeros."""
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        from active_learning_tpu.parallel import mesh as mesh_lib

        mesh = mesh_lib.make_mesh()
        ndev = mesh.devices.size
        block = mesh_lib.INT8_BLOCK
        local = np.ones((ndev, 2 * block), np.float32)
        local[0, 0] = np.inf  # one bad element on one device

        def body(x):
            return mesh_lib.int8_allreduce({"g": x}, "data")["g"]

        got = np.asarray(shard_map(
            body, mesh=mesh, in_specs=P("data"), out_specs=P("data"),
            check_rep=False)(jnp.asarray(local.reshape(-1))))
        got = got.reshape(ndev, -1)
        # The poisoned BLOCK is all-NaN; the clean block sums exactly.
        assert np.all(np.isnan(got[0][:block]))
        np.testing.assert_array_equal(got[0][block:],
                                      np.full(block, float(ndev)))

    def test_int8_refuses_unsyncable_bn_model(self):
        """A train-mode-BN model with no axis_name field cannot sync
        its statistics inside the shard_map step — fit must refuse
        loudly instead of training divergent per-shard BN."""
        from flax import linen as nn

        from active_learning_tpu.config import (LoaderConfig,
                                                OptimizerConfig,
                                                TrainConfig)
        from active_learning_tpu.data.synthetic import get_data_synthetic
        from active_learning_tpu.parallel import mesh as mesh_lib
        from active_learning_tpu.train.trainer import Trainer

        class _BnNoAxis(nn.Module):
            num_classes: int = 4
            freeze_feature: bool = False

            @nn.compact
            def __call__(self, x, train: bool = True,
                         return_features: bool = False):
                emb = x.reshape((x.shape[0], -1)).astype(jnp.float32)
                emb = nn.BatchNorm(use_running_average=not train)(emb)
                logits = nn.Dense(self.num_classes, name="linear")(emb)
                return (logits, emb) if return_features else logits

        data = get_data_synthetic(n_train=64, n_test=16)
        cfg = TrainConfig(loader_tr=LoaderConfig(batch_size=16),
                          loader_te=LoaderConfig(batch_size=16),
                          optimizer=OptimizerConfig(name="sgd", lr=0.05),
                          grad_allreduce="int8",
                          resident_scoring_bytes=0)
        tr = Trainer(_BnNoAxis(), cfg, mesh_lib.make_mesh(), 4)
        st = tr.init_state(jax.random.PRNGKey(0),
                           data[0].gather(np.arange(2)))
        with pytest.raises(ValueError, match="no axis_name"):
            tr.fit(st, data[0], np.arange(32), data[2],
                   np.array([], np.int64), n_epoch=1, es_patience=0,
                   rng=np.random.default_rng(0))

    def test_int_leaves_psum_exactly(self):
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        from active_learning_tpu.parallel import mesh as mesh_lib

        mesh = mesh_lib.make_mesh()
        ndev = mesh.devices.size
        x = jnp.arange(ndev * 4, dtype=jnp.int32)

        def body(v):
            return mesh_lib.int8_allreduce({"c": v}, "data")["c"]

        got = shard_map(body, mesh=mesh, in_specs=P("data"),
                        out_specs=P("data"), check_rep=False)(x)
        exact = np.asarray(x).reshape(ndev, -1).sum(axis=0)
        assert np.array_equal(np.asarray(got).reshape(ndev, -1)[0], exact)

    def test_resolve_rule_off_on_single_device(self):
        from active_learning_tpu.parallel import mesh as mesh_lib

        one = mesh_lib.make_mesh(1)
        full = mesh_lib.make_mesh()
        assert mesh_lib.resolve_grad_allreduce("int8", one) == "f32"
        assert mesh_lib.resolve_grad_allreduce("int8", full) == "int8"
        assert mesh_lib.resolve_grad_allreduce("f32", full) == "f32"
        with pytest.raises(ValueError):
            mesh_lib.resolve_grad_allreduce("int4", full)

    def test_learning_probe_passes_and_bound_pinned(self):
        """The driver gate: on the healthy 8-device CPU mesh the probe
        must PASS (delta within the pinned 0.05 bound) — and the bound
        itself is pinned so a silent loosening shows up here."""
        from active_learning_tpu.experiment import driver
        from active_learning_tpu.parallel import mesh as mesh_lib

        assert driver.INT8_PROBE_MAX_ACC_DELTA == 0.05
        ok, delta = driver.run_grad_allreduce_probe(mesh_lib.make_mesh())
        assert ok, f"int8 learning probe failed: delta={delta}"
        assert delta is not None and delta <= 0.05


class TestFusedE2EBitIdentity:
    def _run(self, tmp_path, name, fused_mode):
        from active_learning_tpu.config import (ExperimentConfig,
                                                TelemetryConfig)
        from active_learning_tpu.data.synthetic import get_data_synthetic
        from active_learning_tpu.experiment import arg_pools  # noqa: F401
        from active_learning_tpu.experiment.driver import run_experiment
        from active_learning_tpu.utils.metrics import JsonlSink

        from helpers import TinyClassifier, tiny_train_config

        cfg = ExperimentConfig(
            dataset="synthetic", arg_pool="synthetic",
            strategy="MarginSampler", rounds=2, round_budget=8,
            n_epoch=3, early_stop_patience=3, run_seed=7,
            exp_hash=name, exp_name="fusedab",
            ckpt_path=str(tmp_path / f"ckpt_{name}"),
            log_dir=str(tmp_path / f"logs_{name}"),
            fused_optimizer=fused_mode,
            telemetry=TelemetryConfig(enabled=False))
        data = get_data_synthetic(n_train=96, n_test=32, num_classes=4,
                                  image_size=8, seed=5)
        sink = JsonlSink(cfg.log_dir, experiment_key=name)
        strategy = run_experiment(cfg, sink=sink, data=data,
                                  train_cfg=tiny_train_config(),
                                  model=TinyClassifier(num_classes=4))
        state_path = glob.glob(os.path.join(
            cfg.ckpt_path, "*", "experiment_state.npz"))[0]
        return strategy, dict(np.load(state_path))

    def test_two_round_experiment_state_bit_identical(self, tmp_path):
        """The acceptance pin: the FULL driver, 2 rounds on the
        multi-device CPU mesh, fused path on vs off at f32 — every
        experiment_state array identical to the bit."""
        on, on_state = self._run(tmp_path, "fon", "on")
        off, off_state = self._run(tmp_path, "foff", "off")
        assert on.trainer.fused_tx is not None
        assert off.trainer.fused_tx is None
        assert set(on_state) == set(off_state)
        for k in on_state:
            assert np.array_equal(on_state[k], off_state[k]), (
                f"experiment_state[{k!r}] diverged between the fused "
                "and optax optimizer paths at f32")
