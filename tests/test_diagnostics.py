"""The experiment-truth observability layer (DESIGN.md §13), pinned.

  * the mergeable fixed-bin histogram: chunked / sharded / monolithic
    accumulation bit-equal; spec mismatches raise; PSI/JS honesty rules
    (None below MIN_DRIFT_N, 0 on identical, positive on shift);
  * ECE from the eval step's additive calibration counts;
  * k-center pick distances ride out of the selection scans with picks
    unchanged — batched == q=1 == row-sharded, monotone non-increasing
    for deterministic greedy, NaN on the seed;
  * the off-path contract: diagnostics disabled is one None check per
    hook site (<2.5µs/call, same bound as disarmed fault sites);
  * JsonlSink size rotation: atomic, lock-held, no lost lines;
  * serve-side drift: live histogram, checkpoint-time rebaseline, and
    the Prometheus exposition of the histogram + drift gauges;
  * e2e through the production CLI: a 2-round run with diagnostics on
    vs off produces BIT-IDENTICAL experiment state (margin family AND
    k-center family, 8-device CPU mesh), the on-run emits
    rd_score_drift_* through sink + scrape, run_report.json renders a
    two-run strategy comparison, and `status` shows the drift tail.
"""

import json
import os
import time

import numpy as np
import pytest

from active_learning_tpu.telemetry import diagnostics as diag_lib

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# The histogram
# ---------------------------------------------------------------------------

class TestScoreHistogram:
    def test_chunked_and_sharded_merges_bit_equal_monolithic(self, rng):
        values = rng.random(5000).astype(np.float32)
        mono = diag_lib.histogram_for("margin").add(values)
        # Chunked (the speculative consume path: per-chunk partials
        # summed at consume) — uneven chunk sizes on purpose.
        chunked = diag_lib.histogram_from_chunks(
            "margin", np.array_split(values, 13))
        # Sharded (row-sharded pools: per-shard partial counts are
        # psum-able because bin counts are pure integer sums).
        shards = [diag_lib.histogram_for("margin").add(part)
                  for part in np.array_split(values, 8)]
        sharded = diag_lib.histogram_from_chunks("margin", shards)
        for other in (chunked, sharded):
            assert (mono.counts == other.counts).all()
            assert mono.n == other.n
            assert mono.summary() == other.summary()

    def test_out_of_range_clamps_and_nan_drops(self):
        h = diag_lib.histogram_for("margin")
        h.add(np.array([-1.0, 2.0, np.nan, 0.5]))
        assert h.n == 3 and h.n_nan == 1
        assert h.counts[0] == 1 and h.counts[-1] == 1

    def test_spec_mismatch_raises(self):
        a = diag_lib.histogram_for("margin")
        b = diag_lib.histogram_for("entropy")
        with pytest.raises(ValueError, match="specs"):
            a.merge(b)
        with pytest.raises(ValueError, match="undefined"):
            diag_lib.psi(a, b)

    def test_round_trip_dict(self, rng):
        h = diag_lib.histogram_for("kcenter_dist").add(rng.random(100) * 50)
        h2 = diag_lib.ScoreHistogram.from_dict(h.to_dict())
        assert h.same_spec(h2) and (h.counts == h2.counts).all()
        assert h.summary() == h2.summary()


class TestDrift:
    def test_identical_zero_shifted_positive(self, rng):
        a = diag_lib.histogram_for("margin").add(rng.random(2000))
        b = diag_lib.histogram_for("margin").add(rng.random(2000) * 0.3)
        assert diag_lib.psi(a, a) == 0.0
        assert diag_lib.js_divergence(a, a) == 0.0
        assert diag_lib.psi(a, b) > 0.1
        js = diag_lib.js_divergence(a, b)
        assert 0.0 < js <= np.log(2) + 1e-9

    def test_below_min_n_is_none_not_a_number(self):
        a = diag_lib.histogram_for("margin").add(
            np.full(diag_lib.MIN_DRIFT_N - 1, 0.5))
        b = diag_lib.histogram_for("margin").add(np.full(100, 0.9))
        assert diag_lib.psi(a, b) is None
        assert diag_lib.js_divergence(a, b) is None


class TestCalibrationAndComposition:
    def test_ece_perfect_and_known_gap(self):
        nb = diag_lib.NUM_CAL_BINS
        count = np.zeros(nb)
        correct = np.zeros(nb)
        conf = np.zeros(nb)
        # One populated bin: 100 rows at confidence 0.75, 75 correct —
        # perfectly calibrated.
        count[7], correct[7], conf[7] = 100, 75, 75.0
        assert diag_lib.ece_from_counts(count, correct, conf) == \
            pytest.approx(0.0)
        # Same confidence, 50 correct: gap 0.25.
        correct[7] = 50
        assert diag_lib.ece_from_counts(count, correct, conf) == \
            pytest.approx(0.25)
        assert diag_lib.ece_from_counts(np.zeros(nb), np.zeros(nb),
                                        np.zeros(nb)) is None

    def test_eval_step_counts_feed_ece(self):
        """The eval-batch piggyback: batch_metric_counts' calibration
        bins are additive and ece_from_counts consumes them."""
        import jax.numpy as jnp
        from active_learning_tpu.train.evaluation import (
            accumulate_metrics, batch_metric_counts)

        logits = jnp.asarray([[4.0, 0.0, 0.0], [0.0, 3.0, 0.0],
                              [0.0, 0.0, 2.0], [1.0, 0.9, 0.0]])
        labels = jnp.asarray([0, 1, 0, 1])
        mask = jnp.ones(4)
        counts = batch_metric_counts(logits, labels, mask, 3)
        out = accumulate_metrics(iter([counts]))
        assert float(np.sum(out["cal_count"])) == 4.0
        ece = diag_lib.ece_from_counts(out["cal_count"],
                                       out["cal_correct"],
                                       out["cal_conf_sum"])
        assert ece is not None and 0.0 <= ece <= 1.0

    def test_pick_composition_balance_and_novelty(self):
        targets = np.array([0, 0, 1, 1, 2, 2, 3, 3])
        labeled_before = np.zeros(8, dtype=bool)
        labeled_before[0] = True  # class 0 already seen
        comp = diag_lib.pick_composition(
            np.array([1, 2, 4, 6]), targets, labeled_before, 4)
        # 4 picks over 4 distinct classes: perfectly balanced.
        assert comp["class_balance"] == pytest.approx(1.0)
        # Classes 1/2/3 are novel, class 0 is not: 3/4.
        assert comp["novelty"] == pytest.approx(0.75)
        empty = diag_lib.pick_composition(np.zeros(0, np.int64),
                                          targets, labeled_before, 4)
        assert empty["class_balance"] is None


# ---------------------------------------------------------------------------
# k-center pick distances out of the selection scans
# ---------------------------------------------------------------------------

class TestKcenterPickDists:
    def _dists(self, emb, labeled, budget, **kw):
        from active_learning_tpu.strategies import kcenter
        picks = kcenter.kcenter_greedy((emb,), labeled, budget, **kw)
        dists = kcenter.LAST_PICK_DISTS
        assert dists is not None and len(dists) == len(picks)
        return picks, dists

    def test_deterministic_dists_exact_and_monotone(self, rng):
        emb = rng.normal(size=(64, 6)).astype(np.float32)
        labeled = np.zeros(64, dtype=bool)
        labeled[:4] = True
        picks, dists = self._dists(emb, labeled, 10, randomize=False,
                                   rng=rng, batch_q=1)
        assert np.isfinite(dists).all()
        # Greedy farthest-first distances never increase, and each
        # equals the exact min squared distance to labeled ∪ earlier
        # picks — recomputed here the slow way.
        assert (np.diff(dists) <= 1e-4).all()
        chosen = list(np.flatnonzero(labeled))
        for pick, d in zip(picks, dists):
            ref = min(float(np.sum((emb[pick] - emb[j]) ** 2))
                      for j in chosen)
            assert d == pytest.approx(ref, rel=1e-3, abs=1e-3)
            chosen.append(int(pick))

    def test_batched_matches_q1_and_sharded_matches_replicated(self, rng):
        from active_learning_tpu.parallel import mesh as mesh_lib
        emb = rng.normal(size=(128, 8)).astype(np.float32)
        labeled = np.zeros(128, dtype=bool)
        labeled[:8] = True
        _, d_q1 = self._dists(emb, labeled, 16, randomize=False,
                              rng=np.random.default_rng(0), batch_q=1)
        p8, d_q8 = self._dists(emb, labeled, 16, randomize=False,
                               rng=np.random.default_rng(0), batch_q=8)
        np.testing.assert_allclose(d_q1, d_q8, rtol=1e-5, atol=1e-5)
        mesh = mesh_lib.make_mesh()
        p_row, d_row = self._dists(emb, labeled, 16, randomize=False,
                                   rng=np.random.default_rng(0),
                                   batch_q=8, mesh=mesh,
                                   pool_sharding="row")
        np.testing.assert_array_equal(p8, p_row)
        np.testing.assert_allclose(d_q8, d_row, rtol=1e-5, atol=1e-5)

    def test_seed_pick_is_nan(self, rng):
        emb = rng.normal(size=(32, 4)).astype(np.float32)
        labeled = np.zeros(32, dtype=bool)  # nothing labeled: seed first
        picks, dists = self._dists(emb, labeled, 5, randomize=False,
                                   rng=rng, batch_q=1)
        assert np.isnan(dists[0]) and np.isfinite(dists[1:]).all()


# ---------------------------------------------------------------------------
# Off-path cost + hook inertness
# ---------------------------------------------------------------------------

class TestOffPathCost:
    def test_disabled_hooks_under_microsecond_budget(self):
        """Diagnostics off = one None check per site: 100k calls per
        hook in well under a second even on a loaded CI box (~2.5µs/
        call allowed — the same bound as disarmed fault sites)."""
        from active_learning_tpu.strategies.base import Strategy

        s = object.__new__(Strategy)
        s.diagnostics = None
        out = {"margin": np.zeros(4, np.float32)}
        n = 100_000
        t0 = time.perf_counter()
        for _ in range(n):
            s._record_score_diagnostics(out)
            s._record_pick_dist_diagnostics(None)
        elapsed = time.perf_counter() - t0
        assert elapsed < 0.5, (
            f"{elapsed / (2 * n) * 1e6:.2f}µs per disabled hook")

    def test_gauges_in_per_round_registry(self):
        from active_learning_tpu.experiment.driver import (
            DIAGNOSTICS_GAUGES, PER_ROUND_GAUGES)
        for name in ("rd_score_drift_psi", "rd_score_drift_js",
                     "rd_score_mean", "rd_pick_class_balance",
                     "rd_pick_novelty", "rd_pick_min_dist",
                     "rd_pick_mean_dist", "rd_ece"):
            assert name in DIAGNOSTICS_GAUGES
            assert name in PER_ROUND_GAUGES

    def test_stale_drift_gauge_retracted_from_scrape_set(self):
        """A round whose diagnostics produced no drift must POP the
        previous round's gauge from the scrape (the honesty rule's
        scrape-side half): finish_round reports the key as None, and
        the driver's retraction feeds those Nones to set_gauges, which
        drops them."""
        from active_learning_tpu.experiment.driver import (
            DIAGNOSTICS_GAUGES)
        from active_learning_tpu.telemetry.runtime import RunTelemetry

        diag = diag_lib.RoundDiagnostics(num_classes=4)
        rt = RunTelemetry()
        rng = np.random.default_rng(0)
        # Round 1/2 score enough: drift lands in the gauges.
        diag.observe_scores("margin", rng.random(100))
        diag.finish_round(1)
        diag.observe_scores("margin", rng.random(100) * 0.3)
        g2 = diag.finish_round(2)
        assert g2["rd_score_drift_psi"] is not None
        rt.set_gauges(**{k: v for k, v in g2.items() if v is not None})
        assert "rd_score_drift_psi" in rt.gauges()
        # Round 3 scores below MIN_DRIFT_N: drift is honesty-None, and
        # the retraction must clear the stale value.
        diag.observe_scores("margin", rng.random(4))
        g3 = diag.finish_round(3)
        assert g3.get("rd_score_drift_psi") is None
        rt.set_gauges(**{k: v for k, v in g3.items() if v is not None})
        rt.set_gauges(**{k: None for k in DIAGNOSTICS_GAUGES
                         if g3.get(k) is None})
        assert "rd_score_drift_psi" not in rt.gauges()
        assert "rd_score_drift_js" not in rt.gauges()


# ---------------------------------------------------------------------------
# JsonlSink rotation
# ---------------------------------------------------------------------------

class TestJsonlRotation:
    def test_rotation_atomic_no_lost_lines(self, tmp_path):
        from active_learning_tpu.utils.metrics import JsonlSink
        sink = JsonlSink(str(tmp_path), experiment_key="k",
                         rotate_bytes=2048)
        n = 300
        for i in range(n):
            sink.log_metric("m", float(i), step=i)
        sink.close()
        live = os.path.join(tmp_path, "metrics.jsonl")
        rotated = live + ".1"
        assert os.path.exists(rotated), "cap never triggered a rotation"
        assert os.path.getsize(live) < 2048 + 256
        seen = []
        for path in (rotated, live):
            with open(path) as fh:
                for line in fh:
                    ev = json.loads(line)  # every line whole + parseable
                    if ev.get("kind") == "metric":
                        seen.append(ev["step"])
        # The .1 file only holds the LAST generation before the live
        # file; earlier generations age out.  Within what survives,
        # steps are contiguous through the boundary and end at n-1 —
        # no event was lost or torn AT a rotation.
        assert seen == list(range(seen[0], n))

    def test_make_sink_threads_rotate_bytes(self, tmp_path):
        from active_learning_tpu.utils.metrics import make_sink
        sink = make_sink(True, str(tmp_path), backend="jsonl",
                         rotate_bytes=4096)
        assert sink.rotate_bytes == 4096
        sink.close()

    def test_cli_threads_rotation_and_diagnostics_flags(self):
        from active_learning_tpu.experiment import cli
        ns = cli.get_parser().parse_args(
            ["--dataset", "synthetic", "--metrics_rotate_bytes", "9000",
             "--disable_diagnostics"])
        cfg = cli.args_to_config(ns)
        assert cfg.metrics_rotate_bytes == 9000
        assert cfg.telemetry.diagnostics is False
        cfg2 = cli.args_to_config(cli.get_parser().parse_args(
            ["--dataset", "synthetic"]))
        assert cfg2.telemetry.diagnostics is True


# ---------------------------------------------------------------------------
# Serve-side drift
# ---------------------------------------------------------------------------

class TestServeScoreDrift:
    def test_observe_rebaseline_snapshot(self, rng):
        d = diag_lib.ServeScoreDrift(key="margin")
        d.observe(rng.random(500))
        snap = d.snapshot()
        assert snap["psi"] is None and snap["baseline_round"] is None
        d.rebaseline(served_round=3)
        d.observe(rng.random(500) * 0.2)  # the new model scores low
        snap = d.snapshot()
        assert snap["baseline_round"] == 3
        assert snap["psi"] is not None and snap["psi"] > 0.1
        assert snap["live"]["n"] == 500

    def test_prometheus_exposition_of_hist_and_drift(self, rng):
        from active_learning_tpu.serve.metrics import prometheus_samples
        from active_learning_tpu.telemetry import prom as prom_lib
        d = diag_lib.ServeScoreDrift(key="margin")
        d.observe(rng.random(300))
        d.rebaseline(served_round=1)
        d.observe(rng.random(300) * 0.5)
        snap = {"score_drift": d.snapshot()}
        text = prom_lib.render(prometheus_samples(snap))
        parsed = prom_lib.parse(text)
        assert "al_serve_score_drift_psi" in parsed
        assert "al_serve_score_drift_js" in parsed
        assert parsed["al_serve_score_baseline_round"][()] == 1.0
        buckets = parsed["al_serve_score_hist_bucket"]
        inf = buckets[(("key", "margin"), ("le", "+Inf"))]
        assert inf == 300.0  # the live histogram's total
        assert parsed["al_serve_score_hist_count"][
            (("key", "margin"),)] == 300.0

    def test_log1p_bucket_edges_exposed_in_score_space(self, rng):
        """A log1p-spec histogram's Prometheus `le` labels must be in
        SCORE space (expm1 of the transformed ladder), not the
        transformed coordinates the bins live in."""
        import math
        from active_learning_tpu.serve.metrics import prometheus_samples
        d = diag_lib.ServeScoreDrift(key="min_margin")  # log1p spec
        d.observe(rng.random(64) * 20.0)
        samples = prometheus_samples({"score_drift": d.snapshot()})
        edges = [float(labels["le"]) for name, labels, _ in samples
                 if name == "al_serve_score_hist_bucket"
                 and labels["le"] != "+Inf"]
        lo, hi, bins, _ = diag_lib.SCORE_SPECS["min_margin"]
        assert edges[-1] == pytest.approx(math.expm1(hi), rel=1e-4)
        assert edges[0] == pytest.approx(
            math.expm1((hi - lo) / bins), rel=1e-4)

    def test_snapshot_dict_built_under_lock(self, rng):
        """snapshot() must serialize the live histogram while holding
        the lock (a concurrent observe() otherwise exposes a
        count/bucket mismatch to a scrape) — pinned by hammering
        observe from a thread while snapshotting."""
        import threading
        d = diag_lib.ServeScoreDrift(key="margin")
        stop = threading.Event()

        def writer():
            while not stop.is_set():
                d.observe(np.full(17, 0.5))

        t = threading.Thread(target=writer)
        t.start()
        try:
            for _ in range(200):
                live = d.snapshot()["live"]
                assert sum(live["counts"]) == live["n"]
        finally:
            stop.set()
            t.join()


# ---------------------------------------------------------------------------
# e2e: production CLI, bit-neutrality, reports, status
# ---------------------------------------------------------------------------

def _cli_run(tmp, tag, strategy, extra=()):
    """One 2-round production-CLI run over synthetic data on the
    8-device CPU mesh; returns its (log_dir, state_dir)."""
    from active_learning_tpu.experiment import cli
    log_dir = os.path.join(tmp, tag)
    argv = ["--dataset", "synthetic", "--arg_pool", "synthetic",
            "--strategy", strategy, "--rounds", "2",
            "--round_budget", "24", "--init_pool_size", "0",
            "--n_epoch", "1", "--early_stop_patience", "1",
            "--log_dir", log_dir, "--ckpt_path", log_dir,
            "--exp_hash", tag, *extra]
    cli.main(argv)
    state_dir = os.path.join(log_dir, f"active_learning_{tag}")
    return log_dir, state_dir


@pytest.fixture(scope="module")
def e2e_runs(tmp_path_factory):
    """Four production-CLI runs: {margin, k-center} × {diagnostics on,
    off}, same seeds — the bit-neutrality and report corpus."""
    tmp = str(tmp_path_factory.mktemp("diag_e2e"))
    runs = {}
    for family, strategy in (("margin", "MarginSampler"),
                             ("kcenter", "CoresetSampler")):
        runs[family, "on"] = _cli_run(tmp, f"{family}on", strategy)
        runs[family, "off"] = _cli_run(
            tmp, f"{family}off", strategy,
            extra=("--disable_diagnostics",))
    return runs


def _metric_events(log_dir):
    by = {}
    with open(os.path.join(log_dir, "metrics.jsonl")) as fh:
        for line in fh:
            ev = json.loads(line)
            if ev.get("kind") == "metric":
                for k, v in ev["metrics"].items():
                    by.setdefault(k, []).append((ev.get("step"), v))
    return by


class TestEndToEnd:
    @pytest.mark.parametrize("family", ["margin", "kcenter"])
    def test_bit_identical_experiment_state_on_vs_off(self, e2e_runs,
                                                      family):
        """THE acceptance pin: diagnostics on vs off, same seeds, same
        2-round production run — labeled/recent/eval idxs, cost, round,
        init key, and the host rng chain all bit-identical."""
        state = {}
        for mode in ("on", "off"):
            _, state_dir = e2e_runs[family, mode]
            state[mode] = dict(np.load(os.path.join(
                state_dir, "experiment_state.npz")))
        assert sorted(state["on"]) == sorted(state["off"])
        for key in state["on"]:
            np.testing.assert_array_equal(
                state["on"][key], state["off"][key], err_msg=key)
        rngs = []
        for mode in ("on", "off"):
            _, state_dir = e2e_runs[family, mode]
            meta = json.load(open(os.path.join(
                state_dir, "experiment_state.json")))
            rngs.append(json.dumps(meta["rng_state"], sort_keys=True))
        assert rngs[0] == rngs[1]

    @pytest.mark.parametrize("family", ["margin", "kcenter"])
    def test_drift_emitted_through_sink(self, e2e_runs, family):
        """rd_score_drift_psi/js at round >= 1 in the diagnostics-on
        runs (margin family via the score histogram, k-center via pick
        distances), absent in the off runs."""
        on = _metric_events(e2e_runs[family, "on"][0])
        off = _metric_events(e2e_runs[family, "off"][0])
        for name in ("rd_score_drift_psi", "rd_score_drift_js"):
            assert name in on, f"{name} missing ({family})"
            assert all(step >= 1 for step, _ in on[name])
            assert name not in off
        assert "rd_pick_class_balance" in on
        if family == "kcenter":
            assert "rd_pick_min_dist" in on
            assert "rd_pick_mean_dist" in on

    def test_run_report_artifact_and_comparison(self, e2e_runs):
        """run_report.json per run, and the cross-run strategy
        comparison table from two REAL experiment dirs — the paper's
        headline figure as a machine artifact."""
        from active_learning_tpu.telemetry import report as report_lib
        margin_dir = e2e_runs["margin", "on"][0]
        kcenter_dir = e2e_runs["kcenter", "on"][0]
        for d in (margin_dir, kcenter_dir):
            payload = json.load(open(os.path.join(d, "run_report.json")))
            assert payload["schema"] == 1
            rounds = payload["rounds"]
            assert [r["round"] for r in rounds] == [0, 1]
            for r in rounds:
                assert r["labeled"] > 0
                assert r["test_accuracy"] is not None
                assert r["round_time_s"] > 0
        runs = [report_lib.load_run(margin_dir),
                report_lib.load_run(kcenter_dir)]
        table = report_lib.render_compare(runs)
        assert "matched" in table and "*" in table
        assert "MarginSampler" in table and "CoresetSampler" in table
        single = report_lib.render_single(runs[0])
        assert "drift_psi" in single

    def test_report_cli_verb_and_script(self, e2e_runs, capsys):
        from active_learning_tpu.experiment import cli
        margin_dir = e2e_runs["margin", "on"][0]
        kcenter_dir = e2e_runs["kcenter", "on"][0]
        assert cli.main(["report", margin_dir, kcenter_dir]) == 0
        out = capsys.readouterr().out
        assert "strategy comparison" in out
        assert cli.main(["report", margin_dir]) == 0
        assert "run report:" in capsys.readouterr().out

    def test_status_renders_drift_tail(self, e2e_runs):
        from active_learning_tpu.telemetry import status as status_lib
        summary = status_lib.summarize(e2e_runs["margin", "on"][0])
        text = status_lib.render_text(summary)
        assert "drift / acquisition:" in text
        assert "rd_score_drift_psi" in text

    def test_prometheus_scrape_completeness_for_drift(self, e2e_runs,
                                                      tmp_path):
        """The new gauges honor the one-dict-two-channels contract:
        re-running with a scrape file, every diagnostics metric that
        reached the sink also rides the al_run_ scrape."""
        from active_learning_tpu.telemetry import prom as prom_lib
        prom_file = str(tmp_path / "run.prom")
        log_dir, _ = _cli_run(str(tmp_path), "prom", "MarginSampler",
                              extra=("--prometheus_file", prom_file))
        by = _metric_events(log_dir)
        parsed = prom_lib.parse(open(prom_file).read())
        from active_learning_tpu.experiment.driver import PER_ROUND_GAUGES
        for name in PER_ROUND_GAUGES:
            if name in by:
                assert f"al_run_{name}" in parsed, name
        assert "al_run_rd_score_drift_psi" in parsed
        assert "al_run_rd_score_drift_js" in parsed
