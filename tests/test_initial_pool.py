"""Tests for seeded initial-pool / eval-split generation
(reference: src/utils/generate_initial_pool.py)."""

import numpy as np
import pytest

from active_learning_tpu.initial_pool import (
    balanced_allocation,
    generate_eval_idxs,
    generate_idxs,
    generate_init_lb_idxs,
)


def test_balanced_allocation_even():
    quota = balanced_allocation(np.array([100, 100, 100, 100]), 40)
    np.testing.assert_array_equal(quota, [10, 10, 10, 10])


def test_balanced_allocation_scarce_class():
    # Class 0 only has 3: water-filling gives it all 3, the rest split 37.
    quota = balanced_allocation(np.array([3, 100, 100, 100]), 40)
    assert quota[0] == 3
    assert quota.sum() == 40
    assert quota[1:].max() - quota[1:].min() <= 1


def test_balanced_allocation_extras_go_to_largest():
    # total=7 over counts [5,5,3]: thres=2 gives 2+2+2=6, one extra goes to
    # a largest class (matching generate_initial_pool.py:51-53).
    quota = balanced_allocation(np.array([5, 5, 3]), 7)
    assert quota.sum() == 7
    assert quota[2] == 2
    assert sorted(quota[:2].tolist()) == [2, 3]


def test_balanced_allocation_overdraw_raises():
    with pytest.raises(ValueError):
        balanced_allocation(np.array([1, 1]), 3)


def test_generate_random_is_seeded_and_avoids():
    targets = np.zeros(100, dtype=int)
    avoid = np.arange(50)
    a = generate_idxs(targets, 1, 20, "random", avoid_idxs=avoid, random_seed=7)
    b = generate_idxs(targets, 1, 20, "random", avoid_idxs=avoid, random_seed=7)
    np.testing.assert_array_equal(a, b)
    assert (a >= 50).all()
    assert len(a) == 20


def test_generate_balance_rounds_down_nondivisible():
    targets = np.repeat(np.arange(10), 50)
    out = generate_idxs(targets, 10, 57, "random_balance", random_seed=0)
    # 57 -> 50 (multiple of num_classes), 5 per class
    assert len(out) == 50
    counts = np.bincount(targets[out], minlength=10)
    np.testing.assert_array_equal(counts, [5] * 10)


def test_eval_and_init_pool_disjoint():
    targets = np.repeat(np.arange(10), 100)
    eval_idxs = generate_eval_idxs(targets, 10, ratio=0.1, random_seed=99)
    init = generate_init_lb_idxs(targets, 10, eval_idxs, 200,
                                 init_pool_type="random", random_seed=98)
    assert len(np.intersect1d(eval_idxs, init)) == 0
    assert len(init) == 200


def test_balanced_init_pool_is_balanced():
    targets = np.repeat(np.arange(10), 100)
    init = generate_init_lb_idxs(targets, 10, np.array([]), 100,
                                 init_pool_type="random_balance", random_seed=98)
    counts = np.bincount(targets[init], minlength=10)
    np.testing.assert_array_equal(counts, [10] * 10)


def test_unknown_type_raises():
    with pytest.raises(ValueError):
        generate_idxs(np.zeros(10, dtype=int), 1, 5, "bogus")
