"""Device-resident acquisition scoring (8-device CPU mesh).

In-memory pool images never change across AL rounds, so
scoring.collect_pool keeps them resident on device for the whole
experiment: one upload serves every round's every sampler, and each
scoring batch moves only a [batch]-int32 index vector to the device.
"""

import numpy as np

from active_learning_tpu.strategies import scoring

from helpers import make_strategy


class TestResidentScoring:
    def test_matches_host_batched_path_exactly(self):
        s = make_strategy("MarginSampler", n_train=96)
        idxs = np.arange(len(s.al_set), dtype=np.int64)
        step = s._get_score_step("prob_stats")
        host = scoring.collect_pool(
            s.al_set, idxs, s._score_batch_size(), step,
            s.state.variables, s.mesh)
        resident = scoring.collect_pool(
            s.al_set, idxs, s._score_batch_size(), step,
            s.state.variables, s.mesh, resident_cache={})
        assert set(host) == set(resident)
        for k in host:
            np.testing.assert_allclose(resident[k], host[k],
                                       rtol=1e-6, atol=1e-6, err_msg=k)

    def test_no_host_gathers_and_one_upload_across_rounds(self):
        """Two full query rounds: the pool's images are uploaded once and
        the dataset's host gather is never called for scoring."""
        s = make_strategy("MarginSampler", n_train=96)
        calls = {"n": 0}
        orig = s.al_set.gather

        def counting(idxs):
            calls["n"] += 1
            return orig(idxs)

        s.al_set.gather = counting
        got1, cost1 = s.query(8)
        s.update(got1, cost1)
        got2, cost2 = s.query(8)
        assert cost1 == 8 and cost2 == 8
        assert not np.isin(got2, got1).any()
        assert calls["n"] == 0  # zero host image gathers across rounds
        assert len(s._resident_pool["images"]) == 1  # one upload total

    def test_scoring_and_evaluation_share_one_upload(self):
        """The trainer's evaluation and the sampler's scoring draw from
        ONE shared cache: the pool uploads once for both consumers."""
        s = make_strategy("MarginSampler", n_train=96)
        s.query(4)  # scoring uploads the pool
        s.trainer.evaluate(s.state, s.al_set, np.arange(8))  # reuses it
        assert len(s._resident_pool["images"]) == 1
        assert s._resident_pool is s.trainer.resident_pool

    def test_zero_budget_disables_resident_path(self):
        """A zero resident budget must fall back to host-batched scoring
        (no upload, host gathers happen).  The budget is the trainer's
        RESOLVED one (config None = auto-sized; an explicit 0 disables),
        so the runtime seam is trainer.resident_budget."""
        s = make_strategy("MarginSampler", n_train=64)
        s.trainer.resident_budget = 0
        calls = {"n": 0}
        orig = s.al_set.gather

        def counting(idxs):
            calls["n"] += 1
            return orig(idxs)

        s.al_set.gather = counting
        got, cost = s.query(4)
        assert cost == 4
        assert calls["n"] > 0  # host path used
        assert "images" not in s._resident_pool  # nothing uploaded

    def test_embedding_samplers_share_the_resident_pool(self):
        """Coreset then BADGE-style scoring over the same strategy reuse
        the single uploaded pool (different step fns, same images)."""
        s = make_strategy("CoresetSampler", n_train=96)
        got, cost = s.query(6)
        assert cost == 6
        s.update(got, cost)
        # A second scoring pass of a DIFFERENT kind over the same pool.
        idxs = s.available_query_idxs(shuffle=False)
        out = scoring.collect_pool(
            s.al_set, idxs, s._score_batch_size(),
            s._get_score_step("prob_stats"), s.state.variables, s.mesh,
            resident_cache=s._resident_pool)
        assert len(out["margin"]) == len(idxs)
        assert len(s._resident_pool["images"]) == 1
        assert len(s._resident_pool["steps"]) >= 2  # embed + prob_stats

    def test_host_path_bulk_flush_preserves_order(self):
        """The host path defers fetches and flushes device results every
        32 batches; crossing several flush boundaries (and ending on a
        partial pending buffer) must keep score rows aligned with idxs."""
        s = make_strategy("MarginSampler", n_train=560)
        idxs = np.arange(len(s.al_set), dtype=np.int64)
        step = s._get_score_step("prob_stats")
        bs = s.trainer.padded_batch_size(1)  # tiny batches -> many flushes
        assert len(idxs) // bs > 2 * 32
        got = scoring.collect_pool(s.al_set, idxs, bs, step,
                                   s.state.variables, s.mesh)
        big = scoring.collect_pool(s.al_set, idxs, s._score_batch_size(),
                                   step, s.state.variables, s.mesh)
        assert len(got["margin"]) == len(idxs)
        np.testing.assert_allclose(got["margin"], big["margin"],
                                   rtol=1e-5, atol=1e-6)
