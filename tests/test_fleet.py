"""The fleet layer, failure mode by failure mode (DESIGN.md §17).

Unit tiers first — spec expansion and stable run-ids, the atomic fleet
journal (including the injected torn write), the status exit-code
contract the controller consumes, gen_jobs' fleet rendering, and the
controller's scheduling decisions exercised against fake run children
(tiny scripts that speak the round-journal protocol without paying for
jax).  Then the chaos end-to-end: a REAL 4-run sweep on two localhost
workers through ``python -m active_learning_tpu fleet run``, with a
SIGKILL'd worker mid-round AND a SIGTERM'd controller mid-schedule, a
controller restart from the journal, and a bit-identical comparison of
every finished experiment_state against the same runs executed
standalone — the fleet layer provably adds scheduling, not noise.
"""

import json
import os
import shlex
import signal
import subprocess
import sys
import time
from glob import glob

import numpy as np
import pytest

from active_learning_tpu import faults
from active_learning_tpu.experiment import gen_jobs
from active_learning_tpu.experiment.cli import get_parser as run_parser
from active_learning_tpu.faults import preempt as preempt_lib
from active_learning_tpu.fleet import (FLEET_JOURNAL_FILE, FleetController,
                                       FleetJournal, Worker,
                                       default_base_cmd, expand_spec,
                                       load_spec, read_fleet_journal,
                                       run_argv, run_id_for,
                                       write_atomic_json)
from active_learning_tpu.fleet import cli as fleet_cli
from active_learning_tpu.fleet import controller as controller_mod
from active_learning_tpu.fleet import report as fleet_report
from active_learning_tpu.fleet.spec import validate_spec
from active_learning_tpu.telemetry import heartbeat as hb_lib
from active_learning_tpu.telemetry import prom
from active_learning_tpu.telemetry import status as status_lib
from active_learning_tpu.telemetry.report import RUN_REPORT_FILE
from active_learning_tpu.telemetry.status import strict_exit_code

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHILD = os.path.join(REPO, "tests", "fleet_child.py")


@pytest.fixture(autouse=True)
def _disarmed():
    """Fault-registry hygiene (the test_faults discipline): every test
    starts and ends disarmed, with no pending preemption flag."""
    faults.configure(None)
    preempt_lib.reset()
    yield
    faults.configure(None)
    preempt_lib.reset()


# ---------------------------------------------------------------------------
# Sweep specs
# ---------------------------------------------------------------------------


class TestSweepSpec:
    SPEC = {
        "name": "demo",
        "defaults": {"dataset": "synthetic", "rounds": 2},
        "grid": {"strategy": ["MarginSampler", "RandomSampler"],
                 "run_seed": [0, 1]},
        "runs": [{"strategy": "BADGESampler", "partitions": 4}],
    }

    def test_expansion_count_and_order(self):
        recs = expand_spec(self.SPEC)
        assert len(recs) == 5
        # Grid product in declaration order, later axes fastest, then
        # the explicit runs.
        combos = [(r["args"]["strategy"], r["args"].get("run_seed"))
                  for r in recs]
        assert combos == [("MarginSampler", 0), ("MarginSampler", 1),
                          ("RandomSampler", 0), ("RandomSampler", 1),
                          ("BADGESampler", None)]
        # Defaults merge under every record.
        assert all(r["args"]["dataset"] == "synthetic" for r in recs)
        assert recs[-1]["args"]["partitions"] == 4

    def test_run_ids_stable_and_distinct(self):
        a = [r["run_id"] for r in expand_spec(self.SPEC)]
        b = [r["run_id"] for r in expand_spec(json.loads(
            json.dumps(self.SPEC)))]
        assert a == b  # same spec -> same ids, across serialization
        assert len(set(a)) == len(a)
        # The slug keeps the id readable; the hash keeps it unique.
        assert a[0].startswith("MarginSampler-synthetic")

    def test_any_differing_arg_changes_the_id(self):
        base = {"strategy": "MarginSampler", "run_seed": 0}
        assert run_id_for(base) != run_id_for({**base, "run_seed": 1})
        assert run_id_for(base) != run_id_for({**base, "n_epoch": 3})

    def test_duplicate_runs_collide_loudly(self):
        spec = {"name": "dup", "grid": {"run_seed": [0]},
                "runs": [{"run_seed": 0}]}
        with pytest.raises(ValueError, match="identical args"):
            expand_spec(spec)

    @pytest.mark.parametrize("bad, match", [
        ({"grid": {}, "runs": []}, "zero runs"),
        ({"grid": {"x": []}}, "non-empty list"),
        ({"grid": 3}, "must be an object"),
        ({"grid": {"x": 3}}, "non-empty list"),
        ({"defaults": 3, "grid": {"x": [1]}}, "'defaults'"),
        ({"grid": {"x": [1]}, "gird": {}}, "unknown top-level"),
        ({"runs": "nope"}, "'runs'"),
    ])
    def test_validation_rejects(self, bad, match):
        with pytest.raises(ValueError, match=match):
            validate_spec(bad)

    def test_run_argv_mapping(self):
        argv = run_argv({"strategy": "MarginSampler",
                         "freeze_feature": True,
                         "download_data": False,
                         "subset_labeled": None,
                         "round_budget": 8})
        assert argv == ["--strategy", "MarginSampler",
                        "--freeze_feature", "--round_budget", "8"]

    def test_spec_round_trips_through_the_real_parser(self, tmp_path):
        path = tmp_path / "spec.json"
        path.write_text(json.dumps(self.SPEC))
        for rec in expand_spec(load_spec(str(path))):
            args = run_parser().parse_args(run_argv(rec["args"]))
            assert args.dataset == "synthetic"


# ---------------------------------------------------------------------------
# The atomic fleet journal
# ---------------------------------------------------------------------------


class TestFleetJournal:
    def test_merge_semantics_and_seq(self, tmp_path):
        path = str(tmp_path / FLEET_JOURNAL_FILE)
        j = FleetJournal(path)
        j.write(a=1, b=2)
        j.write(b=None, c=3)  # None deletes
        payload = read_fleet_journal(path)
        assert payload["a"] == 1 and payload["c"] == 3
        assert "b" not in payload
        assert payload["seq"] == 2 and "ts" in payload

    def test_seq_continues_across_controller_lives(self, tmp_path):
        path = str(tmp_path / FLEET_JOURNAL_FILE)
        FleetJournal(path).write(a=1)
        second = FleetJournal(path)  # a restarted controller
        second.write(b=2)
        assert read_fleet_journal(path)["seq"] == 2

    def test_disabled_journal_writes_nothing(self, tmp_path):
        path = str(tmp_path / FLEET_JOURNAL_FILE)
        assert FleetJournal(path, enabled=False).write(a=1) is None
        assert not os.path.exists(path)

    def test_write_failure_returns_false(self):
        # /dev/null is a file, so the journal's parent "directory"
        # cannot be created: the OSError is absorbed, not raised.
        assert write_atomic_json("/dev/null/x/journal.json",
                                 {"a": 1}) is False

    def test_torn_write_leaves_previous_complete_journal(self, tmp_path):
        """The fleet_journal fault site's torn point fires between the
        tmp write and the rename: the injected crash propagates, the
        on-disk journal is still the PREVIOUS complete payload (never a
        splice), and the journal keeps working once disarmed."""
        path = str(tmp_path / FLEET_JOURNAL_FILE)
        j = FleetJournal(path)
        j.write(round=1)
        faults.configure("fleet_journal:torn@1")
        with pytest.raises(faults.InjectedFault):
            j.write(round=2)
        assert faults.fault_counters()["fleet_journal"]["fires"] == 1
        survivor = read_fleet_journal(path)
        assert survivor["round"] == 1 and survivor["seq"] == 1
        # The complete tmp file sits beside the old journal — the crash
        # happened after the write, before the publish.
        (tmp,) = glob(path + ".tmp.*")
        assert json.load(open(tmp))["round"] == 2
        faults.configure(None)
        j.write(round=3)
        final = read_fleet_journal(path)
        assert final["round"] == 3 and final["seq"] == 3


# ---------------------------------------------------------------------------
# The status contract the controller consumes
# ---------------------------------------------------------------------------


class TestStatusContract:
    @pytest.mark.parametrize("summary, code", [
        ({"state": "no-heartbeat"}, 2),
        ({"state": "stale", "degraded": True}, 3),  # staleness beats it
        ({"state": "ok", "degraded": True, "ingest_starved": True}, 4),
        ({"state": "ok", "ingest_starved": True}, 5),
        ({"state": "ok"}, 0),
    ])
    def test_strict_exit_code_pins(self, summary, code):
        assert strict_exit_code(summary) == code

    def test_json_output_carries_the_exit_code(self, tmp_path, capsys):
        rc = status_lib.main(["--log_dir", str(tmp_path),
                              "--strict", "--json"])
        payload = json.loads(capsys.readouterr().out)
        assert rc == 2
        assert payload["exit_code"] == 2
        assert payload["state"] == "no-heartbeat"

    def test_non_strict_downgrades_degraded(self, tmp_path, capsys,
                                            monkeypatch):
        monkeypatch.setattr(
            status_lib, "summarize",
            lambda *a, **k: {"state": "ok", "degraded": True})
        log = ["--log_dir", str(tmp_path), "--json"]
        assert status_lib.main(log + ["--strict"]) == 4
        assert status_lib.main(log) == 0
        # ...and the JSON payload reports the code it EXITS with.
        capsys.readouterr()
        status_lib.main(log)
        assert json.loads(capsys.readouterr().out)["exit_code"] == 0


# ---------------------------------------------------------------------------
# gen_jobs --format fleet
# ---------------------------------------------------------------------------


class TestGenJobsFleet:
    def test_fleet_spec_covers_all_38_runs(self):
        spec = gen_jobs.fleet_spec("/data")
        recs = expand_spec(validate_spec(spec))
        assert len(recs) == 38
        assert len({r["run_id"] for r in recs}) == 38
        # One grid definition, two renderings: every fleet run is one
        # of the shell commands, token for token.
        shell = set(gen_jobs.all_jobs("/data"))
        for rec in recs:
            cmd = " ".join([gen_jobs.CLI] + run_argv(rec["args"]))
            assert cmd in shell

    def test_sweep_narrowing(self):
        spec = gen_jobs.fleet_spec("/data", sweep="cifar10")
        assert spec["name"] == "cifar10"
        assert len(expand_spec(spec)) == len(
            gen_jobs.cifar10_experiments("/data"))
        with pytest.raises(ValueError, match="unknown sweep"):
            gen_jobs.fleet_spec("/data", sweep="mnist")

    def test_main_fleet_format_prints_a_loadable_spec(self, tmp_path,
                                                      capsys):
        gen_jobs.main(["/data", "--format", "fleet"])
        out = capsys.readouterr().out
        path = tmp_path / "spec.json"
        path.write_text(out)
        assert len(expand_spec(load_spec(str(path)))) == 38

    def test_every_fleet_run_parses_with_the_real_cli(self):
        for rec in expand_spec(gen_jobs.fleet_spec("/data")):
            run_parser().parse_args(run_argv(rec["args"]))


# ---------------------------------------------------------------------------
# The controller against fake run children
# ---------------------------------------------------------------------------

# A run child in ~40 lines: speaks the round-journal protocol, records
# its argv, honors FAKE_MODE — the controller cannot tell it from the
# real CLI, and the tests don't pay for jax.
_FAKE_CHILD = r"""
import json, os, sys, time

def flag(name, default=None):
    return sys.argv[sys.argv.index(name) + 1] if name in sys.argv \
        else default

log_dir = flag("--log_dir"); ckpt = flag("--ckpt_path")
exp_name = flag("--exp_name")
os.makedirs(log_dir, exist_ok=True)
with open(os.path.join(log_dir, "argv.jsonl"), "a") as fh:
    fh.write(json.dumps(sys.argv[1:]) + "\n")

def journal(status):
    with open(os.path.join(log_dir, "round_journal.json"), "w") as fh:
        json.dump({"status": status}, fh)

def save_state():
    d = os.path.join(ckpt, exp_name + "_fleet")
    os.makedirs(d, exist_ok=True)
    for name in ("experiment_state.npz", "experiment_state.json"):
        open(os.path.join(d, name), "w").close()

mode = os.environ.get("FAKE_MODE", "finish")
marker = os.path.join(log_dir, "attempted")
first = not os.path.exists(marker)
open(marker, "w").close()

if mode == "sleep":
    time.sleep(120)
if mode == "preempt_once" and "--resume_training" not in sys.argv:
    save_state(); journal("preempted"); sys.exit(0)
if mode == "crash_once" and first:
    sys.exit(3)
if mode == "crash_always":
    sys.exit(3)
journal("finished")
sys.exit(0)
"""


@pytest.fixture
def fake_child(tmp_path):
    path = tmp_path / "fake_child.py"
    path.write_text(_FAKE_CHILD)
    return str(path)


def _tiny_spec(n=2):
    return {"name": "tiny",
            "defaults": {"dataset": "synthetic", "rounds": 1},
            "grid": {"run_seed": list(range(n))}}


def _controller(tmp_path, fake_child, workers=None, spec=None, **kw):
    return FleetController(
        str(tmp_path / "fleet"), spec or _tiny_spec(),
        workers if workers is not None else [Worker("w0", 2)],
        base_cmd=[sys.executable, fake_child], **kw)


class TestControllerScheduling:
    def test_dry_run_emits_commands_and_launches_nothing(self, tmp_path):
        ctrl = FleetController(str(tmp_path / "fleet"), _tiny_spec(),
                               [], dry_run=True)
        cmds = ctrl.schedule_once()
        assert len(cmds) == 2
        for cmd in cmds:
            assert cmd[:3] == default_base_cmd()
            args = run_parser().parse_args(cmd[3:])
            assert args.exp_hash == "fleet"
            assert args.prometheus_file.endswith("run.prom")
        assert all(r["state"] == "queued" for r in ctrl.runs.values())
        # The journal and fleet gauges still record the fleet's shape.
        journal = read_fleet_journal(
            os.path.join(ctrl.fleet_dir, FLEET_JOURNAL_FILE))
        assert len(journal["runs"]) == 2

    def test_controller_flags_override_spec_redirection(self, tmp_path):
        # A spec entry trying to redirect log_dir loses: the
        # controller's flags come after, and argparse takes the last.
        spec = {"name": "sneaky",
                "runs": [{"log_dir": "/tmp/elsewhere",
                          "run_seed": 0}]}
        ctrl = FleetController(str(tmp_path / "fleet"), spec, [],
                               dry_run=True)
        (cmd,) = ctrl.schedule_once()
        args = run_parser().parse_args(cmd[3:])
        assert args.log_dir.startswith(ctrl.fleet_dir)

    def test_cli_dry_run(self, tmp_path, capsys):
        spec_path = tmp_path / "spec.json"
        spec_path.write_text(json.dumps(_tiny_spec()))
        rc = fleet_cli.main(["run", "--spec", str(spec_path),
                             "--fleet_dir", str(tmp_path / "fleet"),
                             "--dry_run"])
        assert rc == 0
        lines = [l for l in capsys.readouterr().out.splitlines() if l]
        assert len(lines) == 2
        for line in lines:
            assert shlex.split(line)[:3] == default_base_cmd()

    def test_fleet_finishes_and_journals(self, tmp_path, fake_child):
        ctrl = _controller(tmp_path, fake_child, poll_every_s=0.05)
        counts = ctrl.run()
        assert counts == {"queued": 0, "running": 0,
                          "finished": 2, "failed": 0}
        journal = read_fleet_journal(
            os.path.join(ctrl.fleet_dir, FLEET_JOURNAL_FILE))
        assert journal["controller"]["status"] == "finished"
        assert all(r["state"] == "finished"
                   for r in journal["runs"].values())
        gauges = prom.parse(open(os.path.join(
            ctrl.fleet_dir, controller_mod.FLEET_PROM_FILE)).read())
        assert next(iter(
            gauges["al_fleet_runs_finished"].values())) == 2.0

    def test_worker_env_overlay_wins(self, tmp_path, fake_child,
                                     monkeypatch):
        monkeypatch.setenv("FAKE_MODE", "crash_always")
        ctrl = _controller(
            tmp_path, fake_child,
            workers=[Worker("w0", 2, env={"FAKE_MODE": "finish"})],
            poll_every_s=0.05)
        assert ctrl.run()["finished"] == 2

    def test_clean_preemption_requeues_with_resume(self, tmp_path,
                                                   fake_child,
                                                   monkeypatch):
        monkeypatch.setenv("FAKE_MODE", "preempt_once")
        ctrl = _controller(tmp_path, fake_child, poll_every_s=0.05)
        counts = ctrl.run()
        assert counts["finished"] == 2
        for rid, run in ctrl.runs.items():
            assert run["attempts"] == 2
            assert run["preemptions"] == 1 and run["resumes"] == 1
            argvs = [json.loads(l) for l in open(os.path.join(
                ctrl.log_dir(rid), "argv.jsonl"))]
            assert "--resume_training" not in argvs[0]
            assert "--resume_training" in argvs[1]

    def test_crash_requeues_without_resume_state(self, tmp_path,
                                                 fake_child,
                                                 monkeypatch):
        # A SIGKILL'd/crashed child left no saved experiment: the rerun
        # is a cold start (no --resume_training), not a bogus resume.
        monkeypatch.setenv("FAKE_MODE", "crash_once")
        ctrl = _controller(tmp_path, fake_child, poll_every_s=0.05)
        assert ctrl.run()["finished"] == 2
        for rid, run in ctrl.runs.items():
            assert run["attempts"] == 2 and run["resumes"] == 0
            for line in open(os.path.join(ctrl.log_dir(rid),
                                          "argv.jsonl")):
                assert "--resume_training" not in json.loads(line)

    def test_max_attempts_parks_as_failed(self, tmp_path, fake_child,
                                          monkeypatch):
        monkeypatch.setenv("FAKE_MODE", "crash_always")
        ctrl = _controller(tmp_path, fake_child, max_attempts=2,
                           poll_every_s=0.05)
        counts = ctrl.run()
        assert counts["failed"] == 2
        assert all(r["attempts"] == 2 for r in ctrl.runs.values())

    def test_cli_exit_code_reflects_failures(self, tmp_path, fake_child,
                                             monkeypatch):
        monkeypatch.setenv("FAKE_MODE", "crash_always")
        spec_path = tmp_path / "spec.json"
        spec_path.write_text(json.dumps(_tiny_spec()))
        saved = {sig: signal.getsignal(sig)
                 for sig in (signal.SIGTERM, signal.SIGINT)}
        try:
            rc = fleet_cli.main(
                ["run", "--spec", str(spec_path),
                 "--fleet_dir", str(tmp_path / "fleet"),
                 "--workers", "w0=2", "--max_attempts", "1",
                 "--poll_every_s", "0.05",
                 "--base_cmd", f"{sys.executable} {fake_child}"])
        finally:
            for sig, handler in saved.items():
                signal.signal(sig, handler)
        assert rc == 1

    def test_packing_respects_worker_capacity(self, tmp_path, fake_child,
                                              monkeypatch):
        monkeypatch.setenv("FAKE_MODE", "sleep")
        ctrl = _controller(tmp_path, fake_child, spec=_tiny_spec(3),
                           workers=[Worker("w0", 2), Worker("w1", 1)])
        try:
            ctrl.schedule_once()
            placed = sorted(
                (rid, run["worker"])
                for rid, run in ctrl.runs.items()
                if run["state"] == "running")
            # Deterministic packing: sorted run-ids onto registration-
            # ordered free slots.
            assert [w for _, w in placed] == ["w0", "w0", "w1"]
        finally:
            for child in ctrl._children.values():
                child.kill()

    def test_stale_heartbeat_kills_and_requeues(self, tmp_path,
                                                fake_child, monkeypatch):
        """Failure mode 'run wedges': strict code 3 -> the child is
        killed and the reap path re-queues it like any preemption."""
        monkeypatch.setenv("FAKE_MODE", "sleep")
        ctrl = _controller(tmp_path, fake_child, spec=_tiny_spec(1))
        monkeypatch.setattr(controller_mod, "strict_exit_code",
                            lambda summary: 3)
        try:
            ctrl.schedule_once()  # launch
            (rid,) = ctrl.runs
            ctrl.schedule_once()  # health poll -> SIGKILL
            deadline = time.monotonic() + 10
            while (ctrl._children[rid].poll() is None
                   and time.monotonic() < deadline):
                time.sleep(0.02)
            ctrl.schedule_once()  # reap -> requeue
            run = ctrl.runs[rid]
            assert run["state"] == "queued" or run["attempts"] >= 1
            assert run["health"] == 3
        finally:
            for child in ctrl._children.values():
                child.kill()


class TestControllerRecovery:
    def _dead_pid(self):
        proc = subprocess.Popen([sys.executable, "-c", "pass"])
        proc.wait()
        return proc.pid

    def _record(self, state, pid=None, **extra):
        rec = {"state": state, "worker": "w0", "pid": pid, "attempts": 1,
               "resumes": 0, "preemptions": 0, "health": None,
               "rc": None, "resume": False}
        rec.update(extra)
        return rec

    def test_restart_requeues_dead_and_keeps_finished(self, tmp_path,
                                                      fake_child):
        spec = _tiny_spec()
        rid0, rid1 = (r["run_id"] for r in expand_spec(spec))
        fleet_dir = tmp_path / "fleet"
        FleetJournal(str(fleet_dir / FLEET_JOURNAL_FILE)).write(
            spec_name="tiny", runs={
                rid0: self._record("running", pid=self._dead_pid()),
                rid1: self._record("finished", rc=0),
            })
        ctrl = _controller(tmp_path, fake_child)
        assert ctrl.runs[rid0]["state"] == "queued"
        assert ctrl.runs[rid1]["state"] == "finished"
        # seq continued: the journal is one ordered history.
        ctrl.schedule_once()
        assert read_fleet_journal(
            str(fleet_dir / FLEET_JOURNAL_FILE))["seq"] >= 2

    def test_restart_adopts_live_pid_never_relaunches(self, tmp_path,
                                                      fake_child):
        spec = _tiny_spec(1)
        (rid,) = (r["run_id"] for r in expand_spec(spec))
        fleet_dir = tmp_path / "fleet"
        live = subprocess.Popen([sys.executable, "-c",
                                 "import time; time.sleep(120)"])
        try:
            FleetJournal(str(fleet_dir / FLEET_JOURNAL_FILE)).write(
                spec_name="tiny",
                runs={rid: self._record("running", pid=live.pid)})
            ctrl = _controller(tmp_path, fake_child, spec=spec,
                               workers=[Worker("w0", 1)])
            assert ctrl.runs[rid]["state"] == "running"
            assert rid in ctrl._children
            assert ctrl._children[rid].adopted()
            # No free slot is double-booked while the adoptee lives.
            assert ctrl._free_slots() == []
        finally:
            live.kill()
            live.wait()

    def test_adopted_death_judged_by_round_journal(self, tmp_path,
                                                   fake_child):
        """An adopted pid grants no wait() rights: when it dies, the
        round journal supplies the verdict — finished sticks, anything
        else re-queues."""
        spec = _tiny_spec()
        rid0, rid1 = (r["run_id"] for r in expand_spec(spec))
        fleet_dir = tmp_path / "fleet"
        p0 = subprocess.Popen([sys.executable, "-c",
                               "import time; time.sleep(120)"])
        p1 = subprocess.Popen([sys.executable, "-c",
                               "import time; time.sleep(120)"])
        try:
            FleetJournal(str(fleet_dir / FLEET_JOURNAL_FILE)).write(
                spec_name="tiny", runs={
                    rid0: self._record("running", pid=p0.pid),
                    rid1: self._record("running", pid=p1.pid)})
            # Dry-run mode still reaps adopted children but never
            # launches — the reap verdicts stand alone for inspection.
            ctrl = FleetController(str(tmp_path / "fleet"), spec, [],
                                   dry_run=True)
            assert ctrl._children[rid0].adopted()
            os.makedirs(ctrl.log_dir(rid0), exist_ok=True)
            with open(os.path.join(ctrl.log_dir(rid0),
                                   "round_journal.json"), "w") as fh:
                json.dump({"status": "finished"}, fh)
            for p in (p0, p1):
                p.kill()
                p.wait()
            ctrl.schedule_once()
            assert ctrl.runs[rid0]["state"] == "finished"
            assert ctrl.runs[rid1]["state"] == "queued"
        finally:
            for p in (p0, p1):
                if p.poll() is None:
                    p.kill()
                    p.wait()


# ---------------------------------------------------------------------------
# Fleet reporting
# ---------------------------------------------------------------------------


def _fabricate_fleet(root, runs):
    """A dead fleet directory: journal + per-run report/scrape
    artifacts, the shape report.py answers from."""
    fleet_dir = os.path.join(root, "fleet")
    records = {}
    for rid, (strategy, accs, state) in runs.items():
        log_dir = os.path.join(fleet_dir, "runs", rid, "logs")
        os.makedirs(log_dir, exist_ok=True)
        rows = [{"round": i, "labeled": 16 * (i + 1),
                 "cumulative_budget": 16 * (i + 1),
                 "test_accuracy": a, "round_time_s": 1.0,
                 "wall_clock_s": 2.0 * (i + 1)}
                for i, a in enumerate(accs)]
        with open(os.path.join(log_dir, RUN_REPORT_FILE), "w") as fh:
            json.dump({"schema": 1, "exp_name": rid,
                       "strategy": strategy, "rounds": rows}, fh)
        prom.write_textfile(
            os.path.join(fleet_dir, "runs", rid, "run.prom"),
            prom.render(prom.gauge_samples(
                {"round": float(len(accs) - 1), "fault_retries_total": 1.0,
                 "degrade_events": 0.0}, prefix="al_run_")))
        records[rid] = {"state": state, "worker": None, "pid": None,
                        "attempts": 1, "resumes": 1, "preemptions": 1,
                        "health": 0, "rc": 0, "resume": False}
    FleetJournal(os.path.join(fleet_dir, FLEET_JOURNAL_FILE)).write(
        spec_name="fab", runs=records,
        controller={"pid": 1234, "status": "finished"})
    return fleet_dir


class TestFleetReport:
    RUNS = {
        "margin-0-aaaaaaaa": ("MarginSampler", [0.30, 0.52, 0.61],
                              "finished"),
        "random-0-bbbbbbbb": ("RandomSampler", [0.28, 0.45, 0.50],
                              "finished"),
    }

    def test_payload_counts_and_progress(self, tmp_path):
        fleet_dir = _fabricate_fleet(str(tmp_path), self.RUNS)
        payload = fleet_report.fleet_payload(fleet_dir)
        assert payload["counts"] == {"finished": 2}
        assert payload["resumes_total"] == 2
        assert payload["preemptions_total"] == 2
        assert payload["comparison"] is not None
        for rec in payload["runs"]:
            assert rec["round"] == 2.0  # from the scrape file
            assert rec["fault_retries"] == 1.0

    def test_render_contains_lifecycle_and_comparison(self, tmp_path):
        fleet_dir = _fabricate_fleet(str(tmp_path), self.RUNS)
        text = fleet_report.render_fleet(
            fleet_report.fleet_payload(fleet_dir))
        assert "margin-0-aaaaaaaa" in text
        assert "strategy comparison at matched label budgets" in text
        # MarginSampler wins every matched budget in this fabrication.
        assert "*" in text

    def test_merge_prom_relabels_with_run_id(self, tmp_path):
        fleet_dir = _fabricate_fleet(str(tmp_path), self.RUNS)
        path, merged = fleet_report.merge_prom(fleet_dir)
        assert merged == 2
        gauges = prom.parse(open(path).read())
        labels = {dict(l)["run_id"]
                  for l in gauges["al_run_round"]}
        assert labels == set(self.RUNS)

    def test_as_json_is_machine_clean(self, tmp_path):
        fleet_dir = _fabricate_fleet(str(tmp_path), self.RUNS)
        payload = json.loads(fleet_report.as_json(
            fleet_report.fleet_payload(fleet_dir)))
        assert "_reports" not in payload
        assert payload["spec_name"] == "fab"
        assert payload["comparison"]["runs"][0]["curve"]

    def test_cli_status_and_report(self, tmp_path, capsys):
        fleet_dir = _fabricate_fleet(str(tmp_path), self.RUNS)
        assert fleet_cli.main(["status", "--fleet_dir", fleet_dir]) == 0
        out = capsys.readouterr().out
        assert "finished" in out
        assert fleet_cli.main(["report", "--fleet_dir", fleet_dir]) == 0
        out = capsys.readouterr().out
        assert "strategy comparison at matched label budgets" in out
        assert os.path.exists(os.path.join(
            fleet_dir, fleet_report.MERGED_PROM_FILE))
        # --json round-trips.
        fleet_cli.main(["status", "--fleet_dir", fleet_dir, "--json"])
        assert json.loads(capsys.readouterr().out)["counts"] == {
            "finished": 2}

    def test_journal_loss_falls_back_to_artifacts(self, tmp_path):
        fleet_dir = _fabricate_fleet(str(tmp_path), self.RUNS)
        os.remove(os.path.join(fleet_dir, FLEET_JOURNAL_FILE))
        payload = fleet_report.fleet_payload(fleet_dir)
        assert {r["run_id"] for r in payload["runs"]} == set(self.RUNS)
        assert payload["comparison"] is not None


# ---------------------------------------------------------------------------
# The chaos end-to-end
# ---------------------------------------------------------------------------


def _heartbeat_resumable(log_dir):
    """True once the run's heartbeat shows round >= 1.  The driver
    persists experiment_state at each round's END before ticking the
    next round_start, so a heartbeat at round 1 proves the round-0
    checkpoint is on disk — a SIGKILL now MUST reschedule with
    --resume_training."""
    hb = hb_lib.read_heartbeat(
        os.path.join(log_dir, "heartbeat.json")) or {}
    return (hb.get("round") or 0) >= 1 and hb.get("status") == "running"


def _state_arrays(ckpt_root):
    paths = glob(os.path.join(ckpt_root, "*", "experiment_state.npz"))
    assert len(paths) == 1, f"expected one state under {ckpt_root}"
    return dict(np.load(paths[0]))


@pytest.mark.slow
class TestFleetChaosE2E:
    """The acceptance scenario: 4 runs (2 strategies x 2 seeds) on two
    localhost workers; one child SIGKILL'd mid-run past its round-0
    checkpoint (so the reschedule must resume); the controller
    SIGTERM'd mid-schedule; a second controller restarts from the
    fleet journal and completes everything; every finished
    experiment_state is bit-identical to the same run executed
    standalone (no controller, no preemption).  Slow tier like the
    other multi-process spawns (pytest.ini): two controller lives plus
    eight driver children."""

    SPEC = {
        "name": "chaos",
        "defaults": {
            "dataset": "synthetic", "arg_pool": "synthetic",
            # Three rounds: the SIGKILL waits for a round-1 heartbeat
            # (checkpoint committed), and the survivor still has most
            # of its run left when the controller is SIGTERM'd — so
            # the handoff reliably catches it MID-round (preempted),
            # not between runs.
            "rounds": 3, "round_budget": 8, "n_epoch": 3,
            "early_stop_patience": 3, "round_pipeline": "speculative",
            "heartbeat_every_s": 0.0,
            # Stretch scoring dispatches so rounds are not instant.
            "fault_spec": "dispatch:delay@0.05",
        },
        "grid": {"strategy": ["MarginSampler", "RandomSampler"],
                 "run_seed": [0, 1]},
    }

    def _controller_cmd(self, spec_path, fleet_dir):
        return [sys.executable, "-m", "active_learning_tpu", "fleet",
                "run", "--spec", spec_path, "--fleet_dir", fleet_dir,
                "--workers", "w0,w1", "--poll_every_s", "0.2",
                "--base_cmd", f"{sys.executable} {CHILD}"]

    def test_preempted_fleet_matches_standalone(self, tmp_path):
        spec_path = str(tmp_path / "spec.json")
        with open(spec_path, "w") as fh:
            json.dump(self.SPEC, fh)
        fleet_dir = str(tmp_path / "fleet")
        recs = expand_spec(self.SPEC)
        assert len(recs) == 4
        env = {**os.environ, "JAX_PLATFORMS": "cpu"}

        # -- life 1: launch, SIGKILL one child mid-fit, SIGTERM the
        # controller while work remains.
        ctrl = subprocess.Popen(
            self._controller_cmd(spec_path, fleet_dir), env=env,
            cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        journal_path = os.path.join(fleet_dir, FLEET_JOURNAL_FILE)
        killed = None
        try:
            deadline = time.monotonic() + 300
            while time.monotonic() < deadline and killed is None:
                journal = read_fleet_journal(journal_path) or {}
                for rid, rec in (journal.get("runs") or {}).items():
                    if rec.get("state") != "running" or not rec.get("pid"):
                        continue
                    log_dir = os.path.join(fleet_dir, "runs", rid, "logs")
                    if not _heartbeat_resumable(log_dir):
                        continue
                    attempt0 = rec.get("attempts", 1)
                    try:
                        os.kill(rec["pid"], signal.SIGKILL)
                    except ProcessLookupError:
                        continue  # finished under us; hunt another
                    # Confirm the kill TOOK: the controller must see
                    # the death and requeue (attempts grows or state
                    # returns to queued).  A zombie killed after its
                    # natural exit lands 'finished' instead — not a
                    # victim, keep hunting.
                    sub_deadline = time.monotonic() + 60
                    while time.monotonic() < sub_deadline:
                        vrec = ((read_fleet_journal(journal_path) or {})
                                .get("runs") or {}).get(rid) or {}
                        if vrec.get("state") == "queued" or \
                                vrec.get("attempts", 0) > attempt0:
                            killed = rid
                            break
                        if vrec.get("state") in ("finished", "failed"):
                            break
                        time.sleep(0.05)
                    break  # re-read the journal either way
                if ctrl.poll() is not None:
                    pytest.fail("controller exited before the kill:\n"
                                + ctrl.communicate()[0][-2000:])
                time.sleep(0.05)
            assert killed, \
                "no running child was ever killed past its round-0 save"
            # Preempt the controller itself immediately — the handoff
            # SIGTERMs surviving children mid-round (they journal
            # 'preempted' and exit 0) and requeues them.
            ctrl.send_signal(signal.SIGTERM)
            out, _ = ctrl.communicate(timeout=120)
            assert ctrl.returncode == 0, out[-2000:]
        finally:
            if ctrl.poll() is None:
                ctrl.kill()
                ctrl.communicate()
        journal = read_fleet_journal(journal_path)
        assert journal["controller"]["status"] == "preempted"
        states = {rec["state"] for rec in journal["runs"].values()}
        assert states <= {"queued", "finished"}
        assert "queued" in states  # the preemption left real work

        # -- life 2: restart from the journal, run to completion.
        ctrl = subprocess.Popen(
            self._controller_cmd(spec_path, fleet_dir), env=env,
            cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        try:
            out, _ = ctrl.communicate(timeout=840)
            assert ctrl.returncode == 0, out[-2000:]
        finally:
            if ctrl.poll() is None:
                ctrl.kill()
                ctrl.communicate()
        journal = read_fleet_journal(journal_path)
        assert journal["controller"]["status"] == "finished"
        runs = journal["runs"]
        assert all(r["state"] == "finished" for r in runs.values())
        assert sum(r["resumes"] for r in runs.values()) >= 1
        assert sum(r["preemptions"] for r in runs.values()) >= 1

        # -- the fleet report renders the matched-budget comparison.
        report = subprocess.run(
            [sys.executable, "-m", "active_learning_tpu", "fleet",
             "report", "--fleet_dir", fleet_dir],
            env=env, cwd=REPO, capture_output=True, text=True)
        assert report.returncode == 0, report.stderr[-2000:]
        assert "strategy comparison at matched label budgets" \
            in report.stdout
        assert os.path.exists(os.path.join(
            fleet_dir, fleet_report.MERGED_PROM_FILE))

        # -- bit-identity: each run standalone (same harness, no
        # controller, no preemption) produces the same final state.
        # Sequential on purpose: the comparison needs determinism, not
        # wall-clock, and N concurrent jax children thrash small boxes.
        base_root = str(tmp_path / "standalone")
        for rec in recs:
            rid = rec["run_id"]
            argv = run_argv(rec["args"]) + [
                "--exp_name", rid, "--exp_hash", "fleet",
                "--log_dir", os.path.join(base_root, rid, "logs"),
                "--ckpt_path", os.path.join(base_root, rid, "ckpt")]
            done = subprocess.run(
                [sys.executable, CHILD] + argv, env=env, cwd=REPO,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, timeout=570)
            assert done.returncode == 0, f"{rid}:\n{done.stdout[-2000:]}"
        for rec in recs:
            rid = rec["run_id"]
            fleet_state = _state_arrays(
                os.path.join(fleet_dir, "runs", rid, "ckpt"))
            base_state = _state_arrays(
                os.path.join(base_root, rid, "ckpt"))
            assert fleet_state.keys() == base_state.keys()
            for key in fleet_state:
                assert np.array_equal(fleet_state[key],
                                      base_state[key]), \
                    f"{rid}: {key} diverged from the standalone run"
