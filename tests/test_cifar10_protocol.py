"""End-to-end CIFAR-10-path learning check (slow tier).

Pins the composite the evidence script (scripts/cifar10_evidence.py)
drives by hand: fetch (file://) -> md5 -> extract -> python-batch load ->
production driver round loop -> rising test accuracy.  The data is the
byte-layout-faithful facsimile at evidence difficulty (contrast 0.06 /
sigma 60), so a regression anywhere in the disk-dataset path — archive
parsing, plane-major decode, view plumbing, pool bookkeeping over a
disk-loaded ArrayDataset — shows up as a flat or chance-level curve.
(Reference equivalent: the real-data path of main_al.py:145-184 over
custom_cifar10.py, which has no test at all.)
"""

import os

import numpy as np
import pytest

import flax.linen as nn
import jax.numpy as jnp

from active_learning_tpu.config import (ExperimentConfig, LoaderConfig,
                                        OptimizerConfig, SchedulerConfig,
                                        TrainConfig)
from active_learning_tpu.data import get_data
from active_learning_tpu.data.facsimile import write_cifar10_facsimile
from active_learning_tpu.experiment.driver import run_experiment
from active_learning_tpu.utils.metrics import NullSink

pytestmark = pytest.mark.slow


class _Probe(nn.Module):
    num_classes: int = 10
    freeze_feature: bool = False

    @nn.compact
    def __call__(self, x, train=True, return_features=False):
        emb = x.reshape((x.shape[0], -1)).astype(jnp.float32)
        logits = nn.Dense(self.num_classes, name="linear")(emb)
        return (logits, emb) if return_features else logits


def test_facsimile_protocol_learns(tmp_path, monkeypatch):
    from active_learning_tpu.data import cifar10 as c10

    path, md5 = write_cifar10_facsimile(
        str(tmp_path / "cifar-10-python.tar.gz"), n_train=4000,
        n_test=1000, noise_sigma=60, contrast=0.06)
    monkeypatch.setattr(c10, "CIFAR10_URL", f"file://{path}")
    monkeypatch.setattr(c10, "CIFAR10_TGZ_MD5", md5)
    data_dir = str(tmp_path / "data")
    data = get_data("cifar10", data_path=data_dir, download=True)

    train_cfg = TrainConfig(
        eval_split=0.05,
        loader_tr=LoaderConfig(batch_size=128),
        loader_te=LoaderConfig(batch_size=256),
        optimizer=OptimizerConfig(name="sgd", lr=0.05, momentum=0.9,
                                  weight_decay=1e-4),
        scheduler=SchedulerConfig(name="cosine", t_max=20),
    )
    cfg = ExperimentConfig(
        dataset="cifar10", dataset_dir=data_dir, strategy="MarginSampler",
        rounds=3, round_budget=400, init_pool_size=400, n_epoch=20,
        early_stop_patience=0, exp_hash="protocol",
        log_dir=str(tmp_path / "logs"), ckpt_path=str(tmp_path / "ckpt"))

    class CurveSink(NullSink):
        experiment_key = "protocol"

        def __init__(self):
            self.acc = {}

        def log_metrics(self, metrics, step=None):
            if "rd_test_accuracy" in metrics:
                self.acc[int(step)] = float(metrics["rd_test_accuracy"])

    sink = CurveSink()
    run_experiment(cfg, sink=sink, data=data, train_cfg=train_cfg,
                   model=_Probe())
    assert sorted(sink.acc) == [0, 1, 2]
    # 400 -> 1200 labels on the calibrated facsimile: decisively above
    # chance (0.10) and rising.
    assert sink.acc[2] > 0.2, sink.acc
    assert sink.acc[2] > sink.acc[0], sink.acc
