"""Headline benchmark: jitted train-step throughput on the flagship model.

Measures images/sec/chip for the CIFAR-10 protocol model (SSLResNet18,
SimCLR CIFAR stem, 32x32 inputs, on-device augmentation fused into the
step) in bfloat16 over the full local mesh, plus mesh-parallel pool-scoring
throughput — the two hot paths of an AL round (BASELINE.md metric list).

Prints exactly ONE JSON line to stdout:
    {"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}
Diagnostics (per-chip breakdown, MFU estimate, scoring throughput) go to
stderr.

vs_baseline: the reference publishes no throughput numbers (BASELINE.md —
"not published in repo"), so the comparison point is the well-documented
envelope of its hardware: ~1,800 images/sec for ResNet-18/CIFAR-10 training
(fp32, batch 128, torch) on the 1x V100-SXM2 node the reference targets
(README.md:44-47).
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

V100_RESNET18_CIFAR_IPS = 1800.0  # estimated reference envelope, see above


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def bench_train_step(trainer, mesh, batch_size: int, view,
                     warmup: int = 10, iters: int = 200):
    import jax
    import jax.numpy as jnp
    from active_learning_tpu.parallel import mesh as mesh_lib

    rng = np.random.default_rng(0)
    host_batch = {
        "image": rng.integers(0, 256, size=(batch_size, 32, 32, 3),
                              dtype=np.uint8),
        "label": rng.integers(0, 10, size=batch_size).astype(np.int32),
        "index": np.arange(batch_size, dtype=np.int32),
        "mask": np.ones(batch_size, dtype=np.float32),
    }
    batch = mesh_lib.shard_batch(host_batch, mesh)
    state = trainer.init_state(jax.random.PRNGKey(0),
                               host_batch["image"][:8])
    class_weights = jnp.ones(trainer.num_classes, jnp.float32)
    lr = jnp.float32(0.1)
    key = jax.random.PRNGKey(1)

    for _ in range(warmup):
        key, sub = jax.random.split(key)
        state, loss = trainer._train_step(state, batch, sub, lr,
                                          class_weights, view=view)
    float(loss)  # host fetch — proves the device really finished

    t0 = time.perf_counter()
    for _ in range(iters):
        key, sub = jax.random.split(key)
        state, loss = trainer._train_step(state, batch, sub, lr,
                                          class_weights, view=view)
    # block_until_ready can return early on remote-execution backends; a
    # host fetch of a value data-dependent on every step (the step chain
    # threads the state) cannot.
    float(loss)
    dt = time.perf_counter() - t0

    try:
        lowered = trainer._train_step.lower(state, batch, key, lr,
                                            class_weights, view=view)
        cost = lowered.compile().cost_analysis()
        if isinstance(cost, list):
            cost = cost[0]
        flops = float(cost.get("flops", 0.0))
        if flops:
            log(f"train step: {flops / 1e9:.1f} GFLOP/step, "
                f"{flops * iters / dt / 1e12:.1f} TFLOP/s achieved")
    except Exception as e:
        log(f"cost analysis unavailable: {e!r}")
    return batch_size * iters / dt, state


def bench_scoring(model, state, mesh, batch_size: int, view,
                  warmup: int = 3, iters: int = 20):
    """Mesh-parallel acquisition-scoring throughput (prob-stats pass)."""
    import jax
    from active_learning_tpu.parallel import mesh as mesh_lib
    from active_learning_tpu.strategies import scoring

    rng = np.random.default_rng(1)
    host_batch = {
        "image": rng.integers(0, 256, size=(batch_size, 32, 32, 3),
                              dtype=np.uint8),
        "mask": np.ones(batch_size, dtype=np.float32),
    }
    batch = mesh_lib.shard_batch(host_batch, mesh)
    step = scoring.make_prob_stats_step(model, view)
    variables = state.variables
    out = None
    for _ in range(warmup):
        out = step(variables, batch)
    float(out["margin"][0])
    t0 = time.perf_counter()
    for _ in range(iters):
        out = step(variables, batch)
    float(out["margin"][0])  # host fetch, see bench_train_step
    return batch_size * iters / (time.perf_counter() - t0)


def main() -> None:
    import jax
    import jax.numpy as jnp
    from active_learning_tpu.config import LoaderConfig, TrainConfig
    from active_learning_tpu.data.core import CIFAR10_NORM, ViewSpec
    from active_learning_tpu.models.resnet import resnet18
    from active_learning_tpu.parallel import mesh as mesh_lib
    from active_learning_tpu.train.trainer import Trainer

    mesh = mesh_lib.make_mesh(-1)
    n_chips = mesh.devices.size
    per_chip = 256
    batch_size = per_chip * n_chips
    log(f"devices: {jax.devices()}  (batch {batch_size} = "
        f"{per_chip}/chip x {n_chips})")

    model = resnet18(num_classes=10, cifar_stem=True, dtype=jnp.bfloat16)
    cfg = TrainConfig(loader_tr=LoaderConfig(batch_size=batch_size))
    trainer = Trainer(model, cfg, mesh, num_classes=10, train_bn=True)
    train_view = ViewSpec(CIFAR10_NORM, augment=True, pad=4)
    score_view = ViewSpec(CIFAR10_NORM, augment=False)

    ips, state = bench_train_step(trainer, mesh, batch_size, train_view)
    ips_chip = ips / n_chips
    log(f"train step: {ips:,.0f} img/s total, {ips_chip:,.0f} img/s/chip")

    try:
        score_ips = bench_scoring(model, state, mesh, batch_size, score_view)
        log(f"pool scoring: {score_ips:,.0f} img/s total, "
            f"{score_ips / n_chips:,.0f} img/s/chip")
    except Exception as e:  # diagnostics only — never break the headline
        log(f"scoring bench failed: {e!r}")

    print(json.dumps({
        "metric": "resnet18_cifar_train_images_per_sec_per_chip",
        "value": round(ips_chip, 1),
        "unit": "images/sec/chip",
        "vs_baseline": round(ips_chip / V100_RESNET18_CIFAR_IPS, 3),
    }), flush=True)


if __name__ == "__main__":
    main()
