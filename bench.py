"""Headline benchmark: the framework's hot loops on real hardware.

Six phases, bfloat16 over the full local mesh:

  * resnet50_imagenet train/score — the paper's north-star protocol model
    (SSLResNet50 at 224px, reference src/gen_jobs.py:8-13, README.md:53):
    train-step images/sec/chip with achieved TFLOP/s and MFU, plus
    mesh-parallel pool-scoring throughput.
  * resnet18_cifar train/score — the CIFAR-10 protocol model
    (SSLResNet18, SimCLR CIFAR stem, 32px): same two phases.
  * imagenet_datapath — a 50k synthetic JPEG tree through the native C++
    decoder into the mesh scoring pass (per-core decode rate, h2d
    bandwidth, end-to-end images/sec).
  * kcenter_select — greedy selection at protocol scale (10k picks over a
    [50k, 2048] pool), with an A/B of the opt-in Pallas fused update.

Prints exactly ONE JSON line to stdout and always exits 0.  The headline
triple is {"metric", "value", "unit", "vs_baseline"}; per-phase numbers
(incl. resnet50 MFU/TFLOPs) ride along in "phases".  On a dead or
degraded backend the line still appears with value null and the failure
reasons recorded — a flaky remote runtime must never cost a round its
performance evidence.

Robustness: every phase runs in its own subprocess with a hard timeout
(a hung remote dispatch cannot wedge the parent), backend-init failures
retry with backoff, iteration counts shrink on retry, and batch sizes
shrink on OOM.  Timing forces a host fetch of a value data-dependent on
every step — block_until_ready can return early on remote-execution
backends, host fetches cannot.

vs_baseline: the reference publishes no throughput numbers (BASELINE.md)
so the comparison points are the documented envelope of its hardware —
the 1x V100-SXM2 node (reference README.md:44-47): ~400 images/sec for
fp32 ResNet-50/ImageNet training and ~1,800 images/sec for fp32
ResNet-18/CIFAR-10 training.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

V100_BASELINE_IPS = {
    "resnet50_imagenet_train": 400.0,
    "resnet18_cifar_train": 1800.0,
}

# Peak bf16 TFLOP/s per chip by device_kind substring, for MFU.
PEAK_TFLOPS_BF16 = [
    ("v5 lite", 197.0), ("v5e", 197.0), ("v5p", 459.0),
    ("v6", 918.0), ("v4", 275.0), ("v3", 123.0), ("v2", 45.0),
]

# Successful phase results are persisted here (with a capture timestamp)
# and reused — marked "cached": true — when a later invocation can't
# capture that phase fresh.  The tunneled TPU backend's availability is
# highly variable (whole-phase timeouts minutes apart from 3.5-minute
# successes), and a flaky tunnel at harness time must not erase real
# numbers captured hours earlier on the same hardware.
CACHE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "bench_cache.json")

PHASES = [
    # (name, iters, per-chip batch, first-attempt timeout seconds).
    # Iteration counts are sized for timing stability on a HEALTHY backend
    # while still fitting the first attempt when the tunnel runs several
    # times slower than its best observed window.
    ("resnet50_imagenet_train", 30, 128, 900),
    ("resnet18_cifar_train", 100, 256, 600),
    ("resnet50_imagenet_score", 20, 128, 600),
    # ImageNet-scale data-path rehearsal (SURVEY hard part (e)): a 50k
    # synthetic JPEG tree (1/25 of ImageNet) through ImageFolderDataset +
    # native C++ decode + the mesh-parallel scoring pass.  iters is in
    # THOUSANDS of images so the retry halving shrinks the tree.
    ("imagenet_datapath", 50, 128, 900),
    ("resnet18_cifar_score", 30, 256, 420),
    # The selection hot loop (SURVEY hard part (a)): greedy k-center over
    # a 50k-row, 2048-dim pool — the reference's paper protocol subsets
    # the pool to 50k and picks 10k per round (gen_jobs.py:8-13).  iters
    # is the budget (picks); per-chip batch is unused.
    ("kcenter_select", 10000, 128, 600),
]
TOTAL_BUDGET_S = 3000.0  # stop launching attempts past this wall-clock


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# Child: one phase, one process, own backend.
# ---------------------------------------------------------------------------

def _peak_tflops(device_kind: str):
    kind = device_kind.lower()
    for sub, peak in PEAK_TFLOPS_BF16:
        if sub in kind:
            return peak
    return None


def _model_and_views(config: str):
    import jax.numpy as jnp
    from active_learning_tpu.data.core import (CIFAR10_NORM, IMAGENET_NORM,
                                               ViewSpec)
    from active_learning_tpu.models.resnet import resnet18, resnet50

    if config == "resnet50_imagenet":
        model = resnet50(num_classes=1000, dtype=jnp.bfloat16)
        # ImageNet: crop happens at decode; the device view only flips
        # (data/imagenet.py:257).
        return (model, 224, 1000,
                ViewSpec(IMAGENET_NORM, augment=True, pad=0),
                ViewSpec(IMAGENET_NORM, augment=False))
    model = resnet18(num_classes=10, cifar_stem=True, dtype=jnp.bfloat16)
    return (model, 32, 10, ViewSpec(CIFAR10_NORM, augment=True, pad=4),
            ViewSpec(CIFAR10_NORM, augment=False))


def _ensure_jpeg_tree(root: str, n_images: int, n_classes: int = 100
                      ) -> float:
    """Synthetic ImageNet-like JPEG tree: ``n_classes`` class directories,
    variable image sizes (224-320px), seeded per index so the tree is
    reproducible and resumable.  ONE shared root that only ever grows: a
    retry with a smaller target reuses the existing files (smaller runs
    read a ``limit=`` of them), so generation cost is paid once, not per
    attempt.  Returns generation seconds (0.0 when enough images exist)."""
    import numpy as np
    from PIL import Image

    marker = os.path.join(root, ".generated")
    have = 0
    try:
        with open(marker) as fh:
            have = int(fh.read().strip() or 0)
    except (OSError, ValueError):
        pass
    if have >= n_images:
        return 0.0
    t0 = time.perf_counter()
    for c in range(n_classes):
        os.makedirs(os.path.join(root, f"cls_{c:04d}"), exist_ok=True)
    for i in range(n_images):
        path = os.path.join(root, f"cls_{i % n_classes:04d}",
                            f"img_{i:06d}.jpg")
        if os.path.exists(path):
            continue
        rng = np.random.default_rng(i)
        h = int(rng.integers(224, 321))
        w = int(rng.integers(224, 321))
        base = rng.integers(0, 256, size=(12, 16, 3), dtype=np.uint8)
        Image.fromarray(base).resize((w, h), Image.BILINEAR).save(
            path, quality=75)
    with open(marker, "w") as fh:
        fh.write(str(n_images))
    return time.perf_counter() - t0


def run_datapath_phase(n_images: int, per_chip: int) -> dict:
    """End-to-end rehearsal of the ImageNet scoring data path: disk JPEGs
    -> native C++ batch decode/crop/resize -> threaded prefetch ->
    mesh-sharded ResNet-50 scoring via collect_pool (which also enforces
    score/index alignment over the whole pass).  Reports the end-to-end
    scoring rate, the decode-only rate, and the per-core decode rate —
    the number that says how many host cores a full-size run needs to
    keep the mesh fed."""
    import tempfile

    import numpy as np

    import jax
    import jax.numpy as jnp
    from active_learning_tpu.data.core import IMAGENET_NORM, ViewSpec
    from active_learning_tpu.data.imagenet import ImageFolderDataset
    from active_learning_tpu.data.pipeline import iterate_batches
    from active_learning_tpu.parallel import mesh as mesh_lib
    from active_learning_tpu.strategies import scoring

    root = os.path.join(tempfile.gettempdir(), "al_tpu_datapath")
    gen_sec = _ensure_jpeg_tree(root, n_images)
    mesh = mesh_lib.make_mesh(-1)
    n_chips = int(mesh.devices.size)
    batch_size = per_chip * n_chips
    device_kind = jax.devices()[0].device_kind
    cores = (len(os.sched_getaffinity(0))
             if hasattr(os, "sched_getaffinity") else os.cpu_count() or 1)
    threads = max(2, min(16, 2 * cores))
    log(f"[imagenet_datapath] {n_images} JPEGs (gen {gen_sec:.0f}s), "
        f"{n_chips}x {device_kind}, batch {batch_size}, {cores} host cores")

    view = ViewSpec(IMAGENET_NORM, augment=False)
    dataset = ImageFolderDataset(root, view, train_transform=False,
                                 num_classes=1000, limit=n_images)
    dataset.gather(np.arange(8))  # warm-up: builds/loads the native lib

    # Decode-only: the host side in isolation (native decode + crop +
    # resize + batch assembly through the threaded prefetcher).
    n_decode = min(len(dataset), 5000)
    t0 = time.perf_counter()
    rows = 0
    for b in iterate_batches(dataset, np.arange(n_decode), batch_size,
                             num_threads=threads):
        rows += int(b["mask"].sum())
    decode_ips = rows / (time.perf_counter() - t0)

    result = {
        "phase": "imagenet_datapath",
        "n_chips": n_chips,
        "batch_per_chip": per_chip,
        "n_images": len(dataset),
        "decode_ips": round(decode_ips, 1),
        "host_cores": cores,
        "decode_ips_per_core": round(decode_ips / cores, 1),
        "gen_sec": round(gen_sec, 1),
        "device_kind": device_kind,
        "platform": jax.devices()[0].platform,
    }
    if jax.devices()[0].platform != "cpu":
        # Host->device bandwidth for one decoded batch: on a tunneled
        # remote backend this transfer (19 MB per 128-row 224px batch) can
        # be the end-to-end bottleneck; on a co-located TPU host it is
        # PCIe-speed noise.  Reported so a slow end-to-end rate is
        # attributable.  Skipped on the CPU-fallback backend, where a
        # device_put is a host memcpy describing no real transfer path.
        probe = np.zeros((batch_size, 224, 224, 3), dtype=np.uint8)
        jax.device_put(probe).block_until_ready()  # warm the path
        t0 = time.perf_counter()
        jax.device_put(probe).block_until_ready()
        h2d_mb_s = probe.nbytes / 1e6 / (time.perf_counter() - t0)
        result["h2d_mb_per_sec"] = round(h2d_mb_s, 1)
        result["h2d_ips_ceiling"] = round(h2d_mb_s * 1e6 / (224 * 224 * 3),
                                          1)
    if os.environ.get("AL_BENCH_DATAPATH_DECODE_ONLY") == "1":
        # Accelerator unreachable: report the host-side numbers (the
        # phase's real subject) and skip the model pass.
        result.update(ips=round(decode_ips, 1),
                      ips_per_chip=round(decode_ips / n_chips, 1),
                      decode_only=True)
        return result

    # Full scoring pass over the whole tree, decode overlapped with device
    # compute exactly as a real acquisition round runs it.
    model, _, _, _, score_view = _model_and_views("resnet50_imagenet")
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((8, 224, 224, 3), jnp.float32),
                           train=False)
    step = scoring.make_prob_stats_step(model, score_view)
    # Untimed warm-up at the real batch shape: the jitted step's XLA
    # compile (tens of seconds for ResNet-50 on TPU) must not pollute the
    # measured pass, same as every other phase's 3 warm-up iterations.
    scoring.collect_pool(dataset, np.arange(min(batch_size, len(dataset))),
                         batch_size, step, variables, mesh,
                         keys=("margin",))
    all_idxs = np.arange(len(dataset))
    t0 = time.perf_counter()
    out = scoring.collect_pool(dataset, all_idxs, batch_size, step,
                               variables, mesh, num_workers=threads,
                               prefetch=4, keys=("margin",))
    score_sec = time.perf_counter() - t0
    assert len(out["margin"]) == len(dataset)
    ips = len(dataset) / score_sec
    result.update(ips=round(ips, 1), ips_per_chip=round(ips / n_chips, 1),
                  score_sec=round(score_sec, 1))
    return result


def run_kcenter_phase(budget: int, dim: int = 2048, pool_n: int = 50000
                      ) -> dict:
    """Greedy k-center selection at the paper's protocol scale: one
    ``budget``-step lax.scan over a [50k, 2048] embedding pool (the
    reference's subset cap, gen_jobs.py:8-13; its host loop does one
    np.random.choice + full-matrix min per pick, coreset_sampler.py:66-105).
    Reports picks/sec; "ips" carries picks/sec so the parent's schema
    checks hold (unit field says which)."""
    import numpy as np

    import jax
    from active_learning_tpu.strategies.kcenter import kcenter_greedy

    device_kind = jax.devices()[0].device_kind
    log(f"[kcenter_select] pool [{pool_n}, {dim}], budget {budget} on "
        f"{device_kind}")
    host_rng = np.random.default_rng(0)
    emb = host_rng.normal(size=(pool_n, dim)).astype(np.float32)
    labeled = np.zeros(pool_n, dtype=bool)
    labeled[host_rng.choice(pool_n, min(1000, pool_n // 8),
                            replace=False)] = True

    # Warm-up at the SAME budget/shapes (budget is a static scan length):
    # the first call pays the XLA compile, the timed call does not.
    os.environ.pop("AL_TPU_KCENTER_PALLAS", None)
    kcenter_greedy((emb,), labeled, budget, rng=np.random.default_rng(1))
    t0 = time.perf_counter()
    picks = kcenter_greedy((emb,), labeled, budget,
                           rng=np.random.default_rng(2))
    dt = time.perf_counter() - t0
    assert len(picks) == budget and len(set(picks.tolist())) == budget
    rate = budget / dt
    return {
        "phase": "kcenter_select",
        "ips": round(rate, 1),
        "ips_per_chip": round(rate, 1),
        "unit": "picks/sec",
        "n_chips": 1,  # the sequential scan runs on one chip
        "pool_n": pool_n,
        "dim": dim,
        "budget": budget,
        "select_sec": round(dt, 2),
        "device_kind": device_kind,
        "platform": jax.devices()[0].platform,
    }


def run_kcenter_pallas_ab(budget: int, xla_result: dict, dim: int = 2048,
                          pool_n: int = 50000):
    """A/B the opt-in fused Pallas distance-update (ops/kcenter_pallas.py)
    against the XLA scan just measured.  TPU only; failures are recorded,
    never fatal — the XLA number is already with the parent."""
    import numpy as np

    import jax
    from active_learning_tpu.strategies.kcenter import kcenter_greedy

    if jax.devices()[0].platform != "tpu":
        return None
    host_rng = np.random.default_rng(0)
    emb = host_rng.normal(size=(pool_n, dim)).astype(np.float32)
    labeled = np.zeros(pool_n, dtype=bool)
    labeled[host_rng.choice(pool_n, min(1000, pool_n // 8),
                            replace=False)] = True
    result = dict(xla_result)
    os.environ["AL_TPU_KCENTER_PALLAS"] = "1"
    try:
        kcenter_greedy((emb,), labeled, budget,
                       rng=np.random.default_rng(1))  # compile
        t0 = time.perf_counter()
        picks = kcenter_greedy((emb,), labeled, budget,
                               rng=np.random.default_rng(2))
        dt = time.perf_counter() - t0
        assert len(set(picks.tolist())) == budget
        result["pallas_ips"] = round(budget / dt, 1)
        result["pallas_select_sec"] = round(dt, 2)
        result["pallas_speedup"] = round(
            result["pallas_ips"] / max(result["ips"], 1e-9), 2)
        log(f"[kcenter_select] pallas: {budget / dt:,.0f} picks/s "
            f"({result['pallas_speedup']}x the XLA scan)")
    except Exception as e:
        log(f"[kcenter_select] pallas path failed: {e!r}")
        result["pallas_error"] = repr(e)[:200]
    finally:
        os.environ.pop("AL_TPU_KCENTER_PALLAS", None)
    return result


def _phase_setup(config: str, batch_size: int):
    """Shared model/trainer/batch construction for the timing child and
    the CPU FLOPs child: the batch schema and step signatures live in ONE
    place so the two paths cannot drift.  ``batch_size`` is the GLOBAL
    batch over the current backend's mesh."""
    import numpy as np

    import jax
    from active_learning_tpu.config import LoaderConfig, TrainConfig
    from active_learning_tpu.parallel import mesh as mesh_lib
    from active_learning_tpu.train.trainer import Trainer

    mesh = mesh_lib.make_mesh(-1)
    model, px, n_classes, train_view, score_view = _model_and_views(config)
    cfg = TrainConfig(loader_tr=LoaderConfig(batch_size=batch_size))
    trainer = Trainer(model, cfg, mesh, num_classes=n_classes, train_bn=True)
    rng = np.random.default_rng(0)
    host_batch = {
        "image": rng.integers(0, 256, size=(batch_size, px, px, 3),
                              dtype=np.uint8),
        "label": rng.integers(0, n_classes,
                              size=batch_size).astype(np.int32),
        "index": np.arange(batch_size, dtype=np.int32),
        "mask": np.ones(batch_size, dtype=np.float32),
    }
    batch = mesh_lib.shard_batch(host_batch, mesh)
    state = trainer.init_state(jax.random.PRNGKey(0),
                               host_batch["image"][:min(8, batch_size)])
    return (mesh, model, n_classes, train_view, score_view, trainer, batch,
            state)


def run_flops_cpu(phase: str, batch_size: int) -> dict:
    """Per-image FLOPs of a phase's step, lowered on the CPU backend.

    The tunneled TPU backend does not expose ``cost_analysis`` reliably,
    but the FLOP count is a property of the computation, not the device —
    lowering the identical step on CPU (run with JAX_PLATFORMS=cpu) gives
    the same number, and the parent combines it with the TPU-measured
    images/sec to report achieved TFLOP/s and MFU."""
    import jax
    import jax.numpy as jnp

    config, kind = phase.rsplit("_", 1)
    (mesh, model, n_classes, train_view, score_view, trainer, batch,
     state) = _phase_setup(config, batch_size)
    if kind == "train":
        flops = _flops_per_step(
            trainer._train_step, phase, state, batch, jax.random.PRNGKey(1),
            jnp.float32(0.1), jnp.ones(n_classes, jnp.float32),
            view=train_view)
    else:
        from active_learning_tpu.strategies import scoring
        sstep = scoring.make_prob_stats_step(model, score_view)
        flops = _flops_per_step(sstep, phase,
                                state.variables,
                                {"image": batch["image"],
                                 "mask": batch["mask"]})
    n_local = int(mesh.devices.size)
    return {"phase": phase, "flops_source": "cpu-lowering",
            # cost_analysis reports the per-device partitioned module, so
            # divide by the rows one device saw.
            "flops_per_image": (flops * n_local / batch_size
                                if flops else None)}


def _flops_per_step(jitted, phase: str, *args, **kwargs):
    """Per-device flops of one step via AOT lower/compile.  This is a
    SECOND full XLA compile (it does not reuse the jit cache), so callers
    emit their timing result BEFORE calling this — a backend that dies or
    crawls inside the optional compile must not take a completed
    measurement down with it."""
    try:
        cost = jitted.lower(*args, **kwargs).compile().cost_analysis()
        if isinstance(cost, list):
            cost = cost[0]
        return float(cost.get("flops", 0.0)) or None
    except Exception as e:
        log(f"[{phase}] cost analysis unavailable: {e!r}")
        return None


def run_child_phase(phase: str, iters: int, per_chip: int):
    """Yields the phase result dict, then — for train/score phases — the
    same result enriched with flops/MFU.  The caller prints each as its
    own JSON line and the parent keeps the LAST parseable one, so the
    enrichment compile is strictly best-effort."""
    import jax
    import jax.numpy as jnp

    if phase == "imagenet_datapath":
        yield run_datapath_phase(iters * 1000, per_chip)
        return
    if phase == "kcenter_select":
        result = run_kcenter_phase(iters)
        yield dict(result)  # the XLA measurement is safe with the parent
        extra = run_kcenter_pallas_ab(iters, result)
        if extra is not None:
            yield extra
        return
    config, kind = phase.rsplit("_", 1)
    n_chips = len(jax.devices())
    batch_size = per_chip * n_chips
    device_kind = jax.devices()[0].device_kind
    log(f"[{phase}] {n_chips}x {device_kind}, batch {batch_size} "
        f"({per_chip}/chip), {iters} iters")

    (mesh, model, n_classes, train_view, score_view, trainer, batch,
     state) = _phase_setup(config, batch_size)

    if kind == "train":
        class_weights = jnp.ones(n_classes, jnp.float32)
        lr = jnp.float32(0.1)
        key = jax.random.PRNGKey(1)

        def step(state, key):
            key, sub = jax.random.split(key)
            state, loss = trainer._train_step(state, batch, sub, lr,
                                              class_weights, view=train_view)
            return state, key, loss

        for _ in range(3):
            state, key, loss = step(state, key)
        float(loss)  # host fetch — the device really finished warmup
        t0 = time.perf_counter()
        for _ in range(iters):
            state, key, loss = step(state, key)
        float(loss)  # data-dependent on every step via the state chain
        dt = time.perf_counter() - t0

        def flops_fn():
            return _flops_per_step(trainer._train_step, phase, state, batch,
                                   key, lr, class_weights, view=train_view)
    else:
        from active_learning_tpu.strategies import scoring

        sbatch = {"image": batch["image"], "mask": batch["mask"]}
        sstep = scoring.make_prob_stats_step(model, score_view)
        variables = state.variables

        # Chain a scalar through every iteration INSIDE one jitted call so
        # the final host fetch is data-dependent on all of them, with
        # exactly one dispatch per iteration — per-iteration eager ops
        # (indexing + add) each cost a full round-trip on a tunneled
        # remote backend and can dwarf the compute being measured.
        @jax.jit
        def chained(variables, batch, carry):
            out = sstep(variables, batch)
            return carry + out["margin"][0]

        carry = jnp.float32(0.0)
        for _ in range(3):
            carry = chained(variables, sbatch, carry)
        float(carry)
        t0 = time.perf_counter()
        for _ in range(iters):
            carry = chained(variables, sbatch, carry)
        float(carry)
        dt = time.perf_counter() - t0

        def flops_fn():
            return _flops_per_step(sstep, phase, variables, sbatch)

    ips = batch_size * iters / dt
    result = {
        "phase": phase,
        "ips": round(ips, 1),
        "ips_per_chip": round(ips / n_chips, 1),
        "n_chips": n_chips,
        "batch_per_chip": per_chip,
        "iters": iters,
        "device_kind": device_kind,
        "platform": jax.devices()[0].platform,
    }
    yield dict(result)  # the measurement is safe with the parent now
    flops_per_step = flops_fn()
    if flops_per_step:
        # cost_analysis on a jitted SPMD executable reports the PER-DEVICE
        # partitioned module's flops (verified empirically: an 8-way
        # sharded matmul reports 1/8 the single-device figure), so this is
        # per-chip achieved throughput and MFU divides by one chip's peak.
        # Same schema as the CPU-lowering back-fill: per-image flops +
        # flops_source.
        tflops_chip = flops_per_step * iters / dt / 1e12
        result["gflop_per_image"] = round(flops_per_step / per_chip / 1e9,
                                          2)
        result["tflops_per_sec_per_chip"] = round(tflops_chip, 1)
        result["flops_source"] = "device-cost-analysis"
        peak = _peak_tflops(device_kind)
        if peak:
            result["mfu"] = round(tflops_chip / peak, 3)
            result["peak_tflops_per_chip"] = peak
        yield result


# ---------------------------------------------------------------------------
# Parent: orchestrate phases in subprocesses; always print one JSON line.
# ---------------------------------------------------------------------------

def _parse_child_json(stdout: str, required=("ips", "ips_per_chip")):
    """Last stdout line that parses as a dict carrying all ``required``
    keys — stray JSON-ish lines from libraries must not masquerade as a
    phase result."""
    for line in reversed(stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                result = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(result, dict) and all(k in result
                                                for k in required):
                return result
    return None


def run_phase_with_retries(name: str, iters: int, per_chip: int,
                           timeout: float, deadline: float):
    """Up to 3 attempts; iters halve per retry, batch halves on OOM.
    The datapath phase gets a 4th attempt on the CPU backend: its
    headline metrics (decode imgs/sec, per-core rate) are host-side, so a
    dead accelerator tunnel must not erase them — the result is tagged
    with platform "cpu" by the child itself.
    Returns (result dict | None, failure string | None)."""
    failure = None
    attempts = 4 if name == "imagenet_datapath" else 3
    for attempt in range(attempts):
        cpu_fallback = name == "imagenet_datapath" and attempt == attempts - 1
        remaining = deadline - time.monotonic()
        if remaining <= 30:
            return None, failure or "wall-clock budget exhausted"
        attempt_timeout = min(timeout if attempt == 0 else timeout * 0.75,
                              remaining)
        cmd = [sys.executable, os.path.abspath(__file__), "--phase", name,
               "--iters", str(iters), "--per-chip-batch", str(per_chip)]
        env = None
        if cpu_fallback:
            # Decode-only: the ResNet-50 scoring pass is pointless on one
            # CPU core and would blow the timeout; the host-side decode
            # rate is the number this fallback exists to save.
            env = dict(os.environ, PALLAS_AXON_POOL_IPS="",
                       JAX_PLATFORMS="cpu",
                       AL_BENCH_DATAPATH_DECODE_ONLY="1")
            log(f"[parent] {name}: accelerator attempts failed; measuring "
                "the host-side data path (decode only) on the CPU backend")
        log(f"[parent] {name} attempt {attempt + 1}: iters={iters} "
            f"batch/chip={per_chip} timeout={attempt_timeout:.0f}s")
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=attempt_timeout, env=env)
        except subprocess.TimeoutExpired as e:
            partial = e.stderr or ""
            if isinstance(partial, bytes):
                partial = partial.decode(errors="replace")
            sys.stderr.write(partial[-2000:])
            # The child prints each completed measurement as its own line
            # BEFORE the optional flops-enrichment compile — a timeout
            # inside the enrichment must not discard a finished number.
            out = e.stdout or ""
            if isinstance(out, bytes):
                out = out.decode(errors="replace")
            result = _parse_child_json(out)
            if result is not None:
                log(f"[parent] {name}: timed out during enrichment; "
                    "keeping the completed measurement")
                return result, None
            failure = f"timeout after {attempt_timeout:.0f}s"
            log(f"[parent] {name}: {failure}")
            if "RESOURCE_EXHAUSTED" in partial:
                per_chip = max(16, per_chip // 2)
            iters = max(10, iters // 2)
            continue
        sys.stderr.write(proc.stderr[-4000:])
        if proc.returncode == 0:
            result = _parse_child_json(proc.stdout)
            if result is not None:
                return result, None
            failure = "child emitted no JSON"
            continue
        tail = (proc.stderr or "")[-2000:]
        failure = f"exit {proc.returncode}: {tail.strip().splitlines()[-1] if tail.strip() else 'no stderr'}"
        log(f"[parent] {name}: {failure}")
        if "RESOURCE_EXHAUSTED" in tail:
            per_chip = max(16, per_chip // 2)
        elif "UNAVAILABLE" in tail or "DEADLINE_EXCEEDED" in tail \
                or "failed to initialize" in tail.lower():
            time.sleep(15)  # transient backend trouble; let it settle
        iters = max(10, iters // 2)
    return None, failure


def main() -> None:
    try:
        _main_inner()
    except Exception as e:  # the JSON line must appear no matter what
        log(f"[parent] fatal: {e!r}")
        print(json.dumps({
            "metric": "train_images_per_sec_per_chip", "value": None,
            "unit": "images/sec/chip", "vs_baseline": None,
            "error": repr(e),
        }), flush=True)


def _probe_hardware(timeout: float = 120.0):
    """(device_kind, n_devices) of the live backend via a subprocess, or
    None when the backend is unreachable — which is exactly when the cache
    fallback is being considered."""
    code = ("import jax; d = jax.devices(); "
            "print(d[0].device_kind + '|' + str(len(d)))")
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              timeout=timeout)
        if proc.returncode == 0 and "|" in proc.stdout:
            kind, n = proc.stdout.strip().rsplit("|", 1)
            return kind, int(n)
    except (subprocess.SubprocessError, ValueError, OSError):
        pass
    return None


def _load_cache() -> dict:
    try:
        with open(CACHE_PATH) as fh:
            cache = json.load(fh)
        return cache if isinstance(cache, dict) else {}
    except (OSError, json.JSONDecodeError):
        return {}


def _save_cache(cache: dict) -> None:
    try:
        tmp = f"{CACHE_PATH}.tmp"
        with open(tmp, "w") as fh:
            json.dump(cache, fh, indent=1)
        os.replace(tmp, CACHE_PATH)
    except OSError as e:
        log(f"[parent] cache write failed: {e!r}")


def _main_inner() -> None:
    start = time.monotonic()
    deadline = start + TOTAL_BUDGET_S
    cache = _load_cache()
    phases: dict = {}
    failures: dict = {}
    for name, iters, per_chip, timeout in PHASES:
        result, failure = run_phase_with_retries(name, iters, per_chip,
                                                 timeout, deadline)
        if result is not None:
            result["captured_utc"] = time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime())
            phases[name] = result
            if not result.get("decode_only"):
                # A decode-only CPU fallback is a degraded capture; it
                # must never clobber a real accelerator entry in the
                # cache (the cache exists to preserve those).
                cache[name] = result
                _save_cache(cache)
            log(f"[parent] {name}: {result['ips']:,.0f} img/s total, "
                f"{result['ips_per_chip']:,.0f} img/s/chip")
        else:
            failures[name] = failure

    # Cache fallback for failed phases, AFTER the loop so the hardware
    # probe never contends with a running phase.  Numbers captured on
    # DIFFERENT hardware are never resurrected: reuse requires the cached
    # device_kind/chip count to match the live backend (when the backend
    # is unreachable — the usual reason for the fallback — the entry is
    # marked device_unverified instead).
    missing = [n for n in failures if n in cache]
    if missing:
        hw = _probe_hardware()
        for name in missing:
            entry = cache[name]
            if hw is not None and (entry.get("device_kind"),
                                   entry.get("n_chips")) != hw:
                log(f"[parent] {name}: cached result is from "
                    f"{entry.get('device_kind')} x{entry.get('n_chips')}, "
                    f"live backend is {hw[0]} x{hw[1]}; not reusing")
                continue
            phases[name] = dict(entry, cached=True,
                                fresh_failure=failures.pop(name))
            if hw is None:
                phases[name]["device_unverified"] = True
            log(f"[parent] {name}: fresh capture failed; using cached "
                f"result from {entry.get('captured_utc')}")

    # MFU back-fill: cost_analysis is unavailable on the tunneled TPU
    # backend, so phases that timed or errored out of the on-device flops
    # enrichment get their FLOP count from an identical CPU lowering (a
    # property of the computation, not the device) combined with the
    # TPU-measured throughput.
    for name, entry in phases.items():
        if not name.endswith(("_train", "_score")) or entry.get("mfu") \
                or not entry.get("ips_per_chip"):
            continue
        remaining = deadline - time.monotonic()
        if remaining <= 60:
            break
        # FLOPs scale linearly in batch, so lower a small batch (cheap CPU
        # compile) and let the child normalize per image.
        cmd = [sys.executable, os.path.abspath(__file__), "--phase", name,
               "--flops-cpu", "--per-chip-batch",
               str(min(32, entry.get("batch_per_chip", 128)))]
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        log(f"[parent] {name}: computing FLOPs via CPU lowering")
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=min(600, remaining), env=env)
        except subprocess.SubprocessError as e:
            log(f"[parent] {name}: flops child failed: {e!r}")
            continue
        parsed = _parse_child_json(proc.stdout,
                                   required=("flops_per_image",))
        flops = (parsed or {}).get("flops_per_image")
        if not flops:
            log(f"[parent] {name}: CPU flops lowering gave nothing "
                f"(rc={proc.returncode})")
            continue
        tflops_chip = flops * entry["ips_per_chip"] / 1e12
        entry["gflop_per_image"] = round(flops / 1e9, 2)
        entry["tflops_per_sec_per_chip"] = round(tflops_chip, 1)
        entry["flops_source"] = "cpu-lowering"
        peak = _peak_tflops(entry.get("device_kind", ""))
        if peak:
            entry["mfu"] = round(tflops_chip / peak, 3)
            entry["peak_tflops_per_chip"] = peak
        if name in cache and not entry.get("decode_only"):
            cache[name] = {k: v for k, v in entry.items()
                           if k not in ("cached", "fresh_failure",
                                        "device_unverified")}
            _save_cache(cache)

    # Headline: the north-star model if captured, else the CIFAR model.
    headline = None
    for name in ("resnet50_imagenet_train", "resnet18_cifar_train",
                 "resnet50_imagenet_score", "resnet18_cifar_score",
                 "imagenet_datapath"):
        # A decode-only datapath result is a host decode rate, not model
        # throughput — never the headline.
        if name in phases and not phases[name].get("decode_only"):
            headline = name
            break

    out = {
        "metric": (f"{headline}_images_per_sec_per_chip" if headline
                   else "train_images_per_sec_per_chip"),
        "value": phases[headline]["ips_per_chip"] if headline else None,
        "unit": "images/sec/chip",
        "vs_baseline": None,
        "phases": phases,
        "elapsed_sec": round(time.monotonic() - start, 1),
    }
    if headline:
        base = V100_BASELINE_IPS.get(headline)
        if base:
            out["vs_baseline"] = round(out["value"] / base, 3)
        if phases[headline].get("cached"):
            out["headline_cached"] = True
    if failures:
        out["failed_phases"] = failures
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--phase", default=None)
    parser.add_argument("--iters", type=int, default=50)
    parser.add_argument("--per-chip-batch", type=int, default=128)
    parser.add_argument("--flops-cpu", action="store_true")
    args = parser.parse_args()
    if args.phase and args.flops_cpu:
        print(json.dumps(run_flops_cpu(args.phase, args.per_chip_batch)),
              flush=True)
    elif args.phase:
        for result in run_child_phase(args.phase, args.iters,
                                      args.per_chip_batch):
            print(json.dumps(result), flush=True)
    else:
        main()
