"""Headline benchmark: jitted train-step + pool-scoring throughput.

Two model configs are measured, each in bfloat16 over the full local mesh:

  * resnet50_imagenet — the paper's north-star protocol model (SSLResNet50
    at 224px, reference src/gen_jobs.py:8-13, README.md:53): train-step
    images/sec/chip with achieved TFLOP/s and MFU, plus mesh-parallel
    pool-scoring throughput.
  * resnet18_cifar — the CIFAR-10 protocol model (SSLResNet18, SimCLR
    CIFAR stem, 32px): same two phases.

Prints exactly ONE JSON line to stdout and always exits 0.  The headline
triple is {"metric", "value", "unit", "vs_baseline"}; per-phase numbers
(incl. resnet50 MFU/TFLOPs) ride along in "phases".  On a dead or
degraded backend the line still appears with value null and the failure
reasons recorded — a flaky remote runtime must never cost a round its
performance evidence.

Robustness: every phase runs in its own subprocess with a hard timeout
(a hung remote dispatch cannot wedge the parent), backend-init failures
retry with backoff, iteration counts shrink on retry, and batch sizes
shrink on OOM.  Timing forces a host fetch of a value data-dependent on
every step — block_until_ready can return early on remote-execution
backends, host fetches cannot.

vs_baseline: the reference publishes no throughput numbers (BASELINE.md)
so the comparison points are the documented envelope of its hardware —
the 1x V100-SXM2 node (reference README.md:44-47): ~400 images/sec for
fp32 ResNet-50/ImageNet training and ~1,800 images/sec for fp32
ResNet-18/CIFAR-10 training.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

V100_BASELINE_IPS = {
    "resnet50_imagenet_train": 400.0,
    "resnet18_cifar_train": 1800.0,
}

# Peak bf16 TFLOP/s per chip by device_kind substring, for MFU.
PEAK_TFLOPS_BF16 = [
    ("v5 lite", 197.0), ("v5e", 197.0), ("v5p", 459.0),
    ("v6", 918.0), ("v4", 275.0), ("v3", 123.0), ("v2", 45.0),
]

PHASES = [
    # (name, iters, per-chip batch, first-attempt timeout seconds)
    ("resnet50_imagenet_train", 50, 128, 900),
    ("resnet18_cifar_train", 200, 256, 600),
    ("resnet50_imagenet_score", 30, 128, 600),
    ("resnet18_cifar_score", 50, 256, 420),
]
TOTAL_BUDGET_S = 3000.0  # stop launching attempts past this wall-clock


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# Child: one phase, one process, own backend.
# ---------------------------------------------------------------------------

def _peak_tflops(device_kind: str):
    kind = device_kind.lower()
    for sub, peak in PEAK_TFLOPS_BF16:
        if sub in kind:
            return peak
    return None


def _model_and_views(config: str):
    import jax.numpy as jnp
    from active_learning_tpu.data.core import (CIFAR10_NORM, IMAGENET_NORM,
                                               ViewSpec)
    from active_learning_tpu.models.resnet import resnet18, resnet50

    if config == "resnet50_imagenet":
        model = resnet50(num_classes=1000, dtype=jnp.bfloat16)
        # ImageNet: crop happens at decode; the device view only flips
        # (data/imagenet.py:257).
        return (model, 224, 1000,
                ViewSpec(IMAGENET_NORM, augment=True, pad=0),
                ViewSpec(IMAGENET_NORM, augment=False))
    model = resnet18(num_classes=10, cifar_stem=True, dtype=jnp.bfloat16)
    return (model, 32, 10, ViewSpec(CIFAR10_NORM, augment=True, pad=4),
            ViewSpec(CIFAR10_NORM, augment=False))


def run_child_phase(phase: str, iters: int, per_chip: int) -> dict:
    import numpy as np

    import jax
    import jax.numpy as jnp
    from active_learning_tpu.config import LoaderConfig, TrainConfig
    from active_learning_tpu.parallel import mesh as mesh_lib
    from active_learning_tpu.train.trainer import Trainer

    config, kind = phase.rsplit("_", 1)
    mesh = mesh_lib.make_mesh(-1)
    n_chips = int(mesh.devices.size)
    batch_size = per_chip * n_chips
    device_kind = jax.devices()[0].device_kind
    log(f"[{phase}] {n_chips}x {device_kind}, batch {batch_size} "
        f"({per_chip}/chip), {iters} iters")

    model, px, n_classes, train_view, score_view = _model_and_views(config)
    cfg = TrainConfig(loader_tr=LoaderConfig(batch_size=batch_size))
    trainer = Trainer(model, cfg, mesh, num_classes=n_classes, train_bn=True)

    rng = np.random.default_rng(0)
    host_batch = {
        "image": rng.integers(0, 256, size=(batch_size, px, px, 3),
                              dtype=np.uint8),
        "label": rng.integers(0, n_classes, size=batch_size).astype(np.int32),
        "index": np.arange(batch_size, dtype=np.int32),
        "mask": np.ones(batch_size, dtype=np.float32),
    }
    batch = mesh_lib.shard_batch(host_batch, mesh)
    state = trainer.init_state(jax.random.PRNGKey(0),
                               host_batch["image"][:min(8, batch_size)])

    flops_per_step = None
    if kind == "train":
        class_weights = jnp.ones(n_classes, jnp.float32)
        lr = jnp.float32(0.1)
        key = jax.random.PRNGKey(1)

        def step(state, key):
            key, sub = jax.random.split(key)
            state, loss = trainer._train_step(state, batch, sub, lr,
                                              class_weights, view=train_view)
            return state, key, loss

        for _ in range(3):
            state, key, loss = step(state, key)
        float(loss)  # host fetch — the device really finished warmup
        t0 = time.perf_counter()
        for _ in range(iters):
            state, key, loss = step(state, key)
        float(loss)  # data-dependent on every step via the state chain
        dt = time.perf_counter() - t0
        try:
            lowered = trainer._train_step.lower(
                state, batch, key, lr, class_weights, view=train_view)
            cost = lowered.compile().cost_analysis()
            if isinstance(cost, list):
                cost = cost[0]
            flops_per_step = float(cost.get("flops", 0.0)) or None
        except Exception as e:
            log(f"[{phase}] cost analysis unavailable: {e!r}")
    else:
        from active_learning_tpu.strategies import scoring

        sbatch = {"image": batch["image"], "mask": batch["mask"]}
        sstep = scoring.make_prob_stats_step(model, score_view)
        variables = state.variables
        out = None
        for _ in range(3):
            out = sstep(variables, sbatch)
        float(out["margin"][0])
        # Chain a scalar through every iteration so the final host fetch
        # is data-dependent on ALL of them (independent dead outputs could
        # otherwise be skipped/in-flight when the fetch returns).
        t0 = time.perf_counter()
        carry = jnp.float32(0.0)
        for _ in range(iters):
            out = sstep(variables, sbatch)
            carry = carry + out["margin"][0]
        float(carry)
        dt = time.perf_counter() - t0

    ips = batch_size * iters / dt
    result = {
        "phase": phase,
        "ips": round(ips, 1),
        "ips_per_chip": round(ips / n_chips, 1),
        "n_chips": n_chips,
        "batch_per_chip": per_chip,
        "iters": iters,
        "device_kind": device_kind,
        "platform": jax.devices()[0].platform,
    }
    if flops_per_step:
        # cost_analysis on a jitted SPMD executable reports the PER-DEVICE
        # partitioned module's flops (verified empirically: an 8-way
        # sharded matmul reports 1/8 the single-device figure), so this is
        # per-chip achieved throughput and MFU divides by one chip's peak.
        tflops_chip = flops_per_step * iters / dt / 1e12
        result["gflop_per_step_per_chip"] = round(flops_per_step / 1e9, 1)
        result["tflops_per_sec_per_chip"] = round(tflops_chip, 1)
        peak = _peak_tflops(device_kind)
        if peak:
            result["mfu"] = round(tflops_chip / peak, 3)
            result["peak_tflops_per_chip"] = peak
    return result


# ---------------------------------------------------------------------------
# Parent: orchestrate phases in subprocesses; always print one JSON line.
# ---------------------------------------------------------------------------

def _parse_child_json(stdout: str):
    for line in reversed(stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                result = json.loads(line)
            except json.JSONDecodeError:
                continue
            # Only accept a real phase result — stray JSON-ish lines from
            # libraries must not masquerade as one.
            if isinstance(result, dict) and "ips" in result \
                    and "ips_per_chip" in result:
                return result
    return None


def run_phase_with_retries(name: str, iters: int, per_chip: int,
                           timeout: float, deadline: float):
    """Up to 3 attempts; iters halve per retry, batch halves on OOM.
    Returns (result dict | None, failure string | None)."""
    failure = None
    for attempt in range(3):
        remaining = deadline - time.monotonic()
        if remaining <= 30:
            return None, failure or "wall-clock budget exhausted"
        attempt_timeout = min(timeout if attempt == 0 else timeout * 0.75,
                              remaining)
        cmd = [sys.executable, os.path.abspath(__file__), "--phase", name,
               "--iters", str(iters), "--per-chip-batch", str(per_chip)]
        log(f"[parent] {name} attempt {attempt + 1}: iters={iters} "
            f"batch/chip={per_chip} timeout={attempt_timeout:.0f}s")
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=attempt_timeout)
        except subprocess.TimeoutExpired as e:
            partial = e.stderr or ""
            if isinstance(partial, bytes):
                partial = partial.decode(errors="replace")
            sys.stderr.write(partial[-2000:])
            failure = f"timeout after {attempt_timeout:.0f}s"
            log(f"[parent] {name}: {failure}")
            if "RESOURCE_EXHAUSTED" in partial:
                per_chip = max(16, per_chip // 2)
            iters = max(10, iters // 2)
            continue
        sys.stderr.write(proc.stderr[-4000:])
        if proc.returncode == 0:
            result = _parse_child_json(proc.stdout)
            if result is not None:
                return result, None
            failure = "child emitted no JSON"
            continue
        tail = (proc.stderr or "")[-2000:]
        failure = f"exit {proc.returncode}: {tail.strip().splitlines()[-1] if tail.strip() else 'no stderr'}"
        log(f"[parent] {name}: {failure}")
        if "RESOURCE_EXHAUSTED" in tail:
            per_chip = max(16, per_chip // 2)
        elif "UNAVAILABLE" in tail or "DEADLINE_EXCEEDED" in tail \
                or "failed to initialize" in tail.lower():
            time.sleep(15)  # transient backend trouble; let it settle
        iters = max(10, iters // 2)
    return None, failure


def main() -> None:
    try:
        _main_inner()
    except Exception as e:  # the JSON line must appear no matter what
        log(f"[parent] fatal: {e!r}")
        print(json.dumps({
            "metric": "train_images_per_sec_per_chip", "value": None,
            "unit": "images/sec/chip", "vs_baseline": None,
            "error": repr(e),
        }), flush=True)


def _main_inner() -> None:
    start = time.monotonic()
    deadline = start + TOTAL_BUDGET_S
    phases: dict = {}
    failures: dict = {}
    for name, iters, per_chip, timeout in PHASES:
        result, failure = run_phase_with_retries(name, iters, per_chip,
                                                 timeout, deadline)
        if result is not None:
            phases[name] = result
            log(f"[parent] {name}: {result['ips']:,.0f} img/s total, "
                f"{result['ips_per_chip']:,.0f} img/s/chip")
        else:
            failures[name] = failure

    # Headline: the north-star model if captured, else the CIFAR model.
    headline = None
    for name in ("resnet50_imagenet_train", "resnet18_cifar_train",
                 "resnet50_imagenet_score", "resnet18_cifar_score"):
        if name in phases:
            headline = name
            break

    out = {
        "metric": (f"{headline}_images_per_sec_per_chip" if headline
                   else "train_images_per_sec_per_chip"),
        "value": phases[headline]["ips_per_chip"] if headline else None,
        "unit": "images/sec/chip",
        "vs_baseline": None,
        "phases": phases,
        "elapsed_sec": round(time.monotonic() - start, 1),
    }
    if headline:
        base = V100_BASELINE_IPS.get(headline)
        if base:
            out["vs_baseline"] = round(out["value"] / base, 3)
    if failures:
        out["failed_phases"] = failures
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--phase", default=None)
    parser.add_argument("--iters", type=int, default=50)
    parser.add_argument("--per-chip-batch", type=int, default=128)
    args = parser.parse_args()
    if args.phase:
        print(json.dumps(run_child_phase(args.phase, args.iters,
                                         args.per_chip_batch)), flush=True)
    else:
        main()
