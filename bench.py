"""Headline benchmark: the framework's hot loops on real hardware.

Eight phases, bfloat16 over the full local mesh:

  * resnet50_imagenet train/score — the paper's north-star protocol model
    (SSLResNet50 at 224px, reference src/gen_jobs.py:8-13, README.md:53):
    train-step images/sec/chip with achieved TFLOP/s and MFU, plus
    mesh-parallel pool-scoring throughput.
  * resnet18_cifar train/score — the CIFAR-10 protocol model
    (SSLResNet18, SimCLR CIFAR stem, 32px): same two phases.
  * imagenet_datapath — a 50k synthetic JPEG tree through the native C++
    decoder into the mesh scoring pass (per-core decode rate, h2d
    bandwidth, end-to-end images/sec).
  * kcenter_select — greedy selection at protocol scale (10k picks over a
    [50k, 2048] pool) through the production batched-greedy XLA scan
    (the Pallas kernel was deleted per the r5 verdict — DESIGN.md §5).
  * serve_throughput — the ONLINE path: a loopback scoring service
    (active_learning_tpu/serve/) under the closed+open-loop load
    generator, recording qps, p50/p99 request latency, the
    batch-occupancy histogram, and asserting zero request-path XLA
    compiles after the bucket warmup.
  * al_round_cifar / al_round_imagenet — BASELINE.md metric #1: one REAL
    end-to-end AL round (query -> train -> test) through the production
    driver (experiment/driver.py), with the per-phase wall-clock the
    driver already timers.  Two rounds run so the warm round (all XLA
    compiles cached) is reported separately from the cold one.

Prints exactly ONE COMPACT JSON line (<= MAX_LINE_BYTES, guaranteed) to
stdout and always exits 0.  The headline triple is {"metric", "value",
"unit", "vs_baseline"}; per-phase numbers ride along in "phases" as
{ips, mfu, cached} only.  The FULL evidence (every field every phase
produced, probe record, failure strings) is written to
bench_evidence.json, whose path the line carries under "evidence" — the
harness that consumes this output keeps only a ~2 KB tail of stdout, so
a fat line is truncated past parseability (round 4's parsed=null) while
a file survives at any size.  On a dead or degraded backend the line
still appears with value null and the failure reasons recorded — a
flaky remote runtime must never cost a round its performance evidence.

Robustness (the round-3 driver capture died rc=124 with a full cache on
disk; none of these may regress):
  * A <=90 s health probe (tiny jitted matmul in a subprocess) runs
    BEFORE any long phase attempt; a dead/degraded backend routes
    straight to emitting the cached numbers instead of burning the
    wall-clock budget on doomed 900-second attempts.
  * The would-be-final JSON is rewritten to bench_partial.json after
    every phase, so even a SIGKILL leaves the evidence on disk.
  * SIGTERM/SIGINT print the final JSON line immediately and exit 0 — an
    outer `timeout` on this process yields a parsed result, not rc=124.
  * Every phase runs in its own subprocess with a hard timeout (a hung
    remote dispatch cannot wedge the parent), the retry ladder is capped
    at 2 attempts, iteration counts shrink on retry, and batch sizes
    shrink on OOM.  Total fresh-capture time is bounded by
    AL_BENCH_BUDGET_S (default 1400 s) so the guaranteed line lands well
    inside a 30-minute outer timeout.
  * Timing forces a host fetch of a value data-dependent on every step —
    block_until_ready can return early on remote-execution backends,
    host fetches cannot.

vs_baseline: the reference publishes no throughput numbers (BASELINE.md)
so the comparison points are the documented envelope of its hardware —
the 1x V100-SXM2 node (reference README.md:44-47): ~400 images/sec for
fp32 ResNet-50/ImageNet training and ~1,800 images/sec for fp32
ResNet-18/CIFAR-10 training.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import signal
import subprocess
import sys
import time


def _finite(x) -> bool:
    """True for a real, finite number (bools excluded): the ONE spelling
    of 'usable rate' shared by the headline filter and the sanitizer."""
    return (isinstance(x, (int, float)) and not isinstance(x, bool)
            and math.isfinite(x))

V100_BASELINE_IPS = {
    "resnet50_imagenet_train": 400.0,
    "resnet18_cifar_train": 1800.0,
}

# Peak bf16 TFLOP/s per chip by device_kind substring, for MFU.
PEAK_TFLOPS_BF16 = [
    ("v5 lite", 197.0), ("v5e", 197.0), ("v5p", 459.0),
    ("v6", 918.0), ("v4", 275.0), ("v3", 123.0), ("v2", 45.0),
]

# Where the cache/partial/evidence files live: the repo dir by default;
# AL_BENCH_STATE_DIR redirects all three so tests (and parallel bench
# invocations) can exercise the full emit path without touching the real
# captured evidence (tests/test_bench_json.py pins the degraded-mode
# JSON-line guarantee through this).
_STATE_DIR = (os.environ.get("AL_BENCH_STATE_DIR")
              or os.path.dirname(os.path.abspath(__file__)))

# Successful phase results are persisted here (with a capture timestamp)
# and reused — marked "cached": true — when a later invocation can't
# capture that phase fresh.  The tunneled TPU backend's availability is
# highly variable (whole-phase timeouts minutes apart from 3.5-minute
# successes), and a flaky tunnel at harness time must not erase real
# numbers captured hours earlier on the same hardware.
CACHE_PATH = os.path.join(_STATE_DIR, "bench_cache.json")

PHASES = [
    # (name, iters, per-chip batch, first-attempt timeout seconds).
    # Iteration counts are sized for timing stability on a HEALTHY backend
    # while still fitting the first attempt when the tunnel runs several
    # times slower than its best observed window.
    ("resnet50_imagenet_train", 30, 128, 900),
    ("resnet18_cifar_train", 100, 256, 600),
    ("resnet50_imagenet_score", 20, 128, 600),
    # ImageNet-scale data-path rehearsal (SURVEY hard part (e)): a 50k
    # synthetic JPEG tree (1/25 of ImageNet) through ImageFolderDataset +
    # native C++ decode + the mesh-parallel scoring pass.  iters is in
    # THOUSANDS of images so the retry halving shrinks the tree.
    ("imagenet_datapath", 50, 128, 900),
    # The train-feed hierarchy, measured (DESIGN.md §2a): identical fits
    # over an in-memory 224px pool under each leg — resident-gather
    # (on-device gather + augment from the pinned pool) vs
    # prefetched-host (worker threads behind the double-buffered device
    # prefetch) vs serial-host — so the auto feed choice is justified on
    # THIS hardware.  iters is the per-leg epoch count.
    ("imagenet_train_feed", 2, 64, 900),
    # PRIMARY at the 512-rows/chip production floor (trainer.py
    # eval_batch_size: <=64px rows score at 512/chip — +47% measured over
    # 256); the automatic alt probe then covers 1024 as the beyond-floor
    # data point.  Earlier rounds captured 256 primary / 512 alt, so the
    # README's production number came from the alt probe — now it IS the
    # primary capture.
    ("resnet18_cifar_score", 30, 512, 420),
    # The disk tier (DESIGN.md §16): the same 2-round experiment under
    # the memory backend and the demand-paged disk backend with the
    # pool pinned at 4x the residency budgets — asserts bit-identical
    # picks/accuracy and records the paging tax (hit fraction, page-in
    # rate, stall percentiles).  iters is the per-round epoch count;
    # per-chip batch is unused (the production config decides).
    ("disk_pool_feed", 2, 64, 900),
    # The selection hot loop (SURVEY hard part (a)): greedy k-center over
    # a 50k-row, 2048-dim pool — the reference's paper protocol subsets
    # the pool to 50k and picks 10k per round (gen_jobs.py:8-13).  iters
    # is the budget (picks); per-chip batch is unused.  XLA scan only
    # since the r5 verdict deleted the Pallas kernel.
    ("kcenter_select", 10000, 128, 600),
    # The same selection at the PAPER'S pool size: the protocol scores a
    # 130k subset (50k labeled cap + 80k unlabeled cap, gen_jobs.py:8-13)
    # that the reference can only handle partitioned — this phase times
    # the full-pool no-partition scan and records peak HBM.
    ("kcenter_select_130k", 10000, 128, 900),
    # Where does no-partition selection actually stop?  Climb + bisect
    # toward the FULL 1.28M x 2048 f32 factor matrix, recording picks/s
    # and peak HBM at each pool size; the largest completed N is the
    # measured envelope DESIGN.md §3's analytic one must match.  iters is
    # the per-attempt pick budget (small: the question is residency, not
    # selection throughput).
    ("kcenter_select_maxn", 256, 128, 900),
    # First on-TPU VAAL execution record: one VAE+discriminator co-train
    # epoch over the synthetic in-memory pool through the production
    # VAALSampler step, with finite-loss/learning assertions.  iters is
    # the epoch count.
    ("vaal_cotrain", 1, 64, 600),
    # The ONLINE path (active_learning_tpu/serve/): a loopback scoring
    # service driven by the closed+open-loop load generator.  iters is
    # the closed-loop window in SECONDS; per-chip batch is the service's
    # max_batch.  Records qps, p50/p99 request latency, the
    # batch-occupancy histogram, and asserts ZERO request-path compiles
    # after the bucket warmup (the test_compile_reuse counter).
    ("serve_throughput", 8, 64, 600),
    # The STREAMING loop (active_learning_tpu/stream/): a real
    # StreamService on loopback — ingest N synthetic rows through
    # POST /v1/pool (+ labels through /v1/label) via the loadgen's
    # ingest mode, the watermark trigger fires, a full AL round
    # completes over the grown (extent-aligned) pool.  iters is the
    # round count (bootstrap + triggered); per-chip batch bounds
    # max_request_rows.  Records ingest rows/sec (WAL-fsync bound),
    # ack p50/p99, and the trigger cause.
    ("stream_round", 2, 64, 600),
    # The fleet tier (DESIGN.md §17): a 2-run sweep on two localhost
    # workers through the real controller, one child SIGKILL'd after
    # its round-0 checkpoint — must resume and finish with the merged
    # scrape + matched-budget comparison rendered.  iters is the
    # per-run round count (floored at 2: the kill waits for a resumable
    # checkpoint); per-chip batch is unused.  CPU-only (host-pure
    # controller + the tests/fleet_child.py harness), so it never
    # competes for the tunnel.
    ("fleet_smoke", 2, 64, 900),
    # BASELINE.md metric #1: real end-to-end AL rounds through the
    # production driver.  iters is the per-round epoch count.
    ("al_round_cifar", 4, 128, 900),
    # Cold round-0 query alone decodes the full 50k JPEG tree (~420s
    # measured through the tunnel), so the first attempt needs the
    # largest window of any phase.
    ("al_round_imagenet", 2, 128, 1800),
]
# Stop launching fresh attempts past this wall-clock: the guaranteed JSON
# line must land WELL inside the driver's outer timeout (round 3 died at
# rc=124 against a ~50-minute ladder).  Probe + phases + emit fit in this.
TOTAL_BUDGET_S = float(os.environ.get("AL_BENCH_BUDGET_S", "1400"))
# Probe slower than this => the backend is degraded; don't start fresh
# 900-second phase attempts against it.
PROBE_DEGRADED_S = 60.0
# The would-be-final JSON is rewritten here after every phase, so even a
# SIGKILL mid-run leaves complete evidence of everything captured so far.
PARTIAL_PATH = os.path.join(_STATE_DIR, "bench_partial.json")
# The FULL final evidence lands here; the stdout line only references it.
EVIDENCE_PATH = os.path.join(_STATE_DIR, "bench_evidence.json")
# Hard bound on the ONE stdout line: the consuming harness records a
# ~2,000-byte tail of stdout — which carries nothing but this line — so
# the bound needs enough margin for tail-window slop, not another whole
# line.  1950 fits the 16-phase realistic-maximal rich form (every
# phase cached with every optional
# rider: the feed-hierarchy fields, unit/backend on BOTH paper-scale
# selection phases, the sharded-ceiling probe's pool_sharding tag,
# pipeline/overlap on both end-to-end round phases — ISSUE 7, ~90
# bytes — the failure-model counters retries/degraded on both round
# phases — ISSUE 8, worst case '"retries":NN,"degraded":N,' x2 ≈ 50
# bytes — the gradient-path riders on both TRAIN phases — ISSUE 10,
# worst case '"bwd_frac":0.NNN,"grad_ar":"int8",' x2 ≈ 68 bytes — and
# now the experiment-truth drift rider on both round phases — ISSUE
# 13, worst case '"drift":0.NNNNNN,' x2 ≈ 36 bytes — and the streaming
# phase — ISSUE 14: one more phase entry (~30 bytes) plus its riders,
# worst case '"ack_p99":NNN.NNN,"trigger":"watermark",' ≈ 40 bytes —
# and the pod-tier riders — ISSUE 15: the quantized wire form on both
# train phases ('"grad_sync":"rs",' x2 ≈ 36 bytes; grad_wire_mb stays
# in the evidence file) plus the ring-feed tag on both round phases and
# the maxn probe ('"ring":true,' x3 ≈ 36 bytes) — and the disk-tier
# phase — ISSUE 16: one more phase entry (~30 bytes) plus its riders,
# worst case '"hit":0.NNN,"stall_ms":NN.NN,' ≈ 30 bytes; the finer
# paging figures (page-in rate, p50, the memory-leg comparison) stay in
# the evidence file) without truncation; staged truncation in
# _compact_line still guards the pathological cases.  NOTE the
# accounting above counts COMPACT spellings ('"ack_p99":NNN.NNN,' — no
# spaces), which json.dumps only emits under explicit
# separators=(",", ":"); the default ", "/": " separators spent one
# unbudgeted tail byte per key and comma (~150 bytes across the rich
# form) until ISSUE 16's 15th phase pushed the spaced form past the
# bound and exposed the gap — _compact_line now dumps compact.  The
# fleet tier (ISSUE 18) adds the 16th phase entry (~35 bytes) plus its
# riders, worst case '"runs":N,"resumed":N,"wall_s":NNN.N,' ≈ 37 bytes
# and its long unit string ('"unit":"runs finished/min (2-worker
# localhost fleet)",' ≈ 52 bytes) — which pushed the 15-phase 1782-byte
# maximal past 1950.  16 phases ride; the measured realistic-maximal
# rich form is 1958 bytes
# (pinned ≤ MAX_LINE_BYTES by test_compact_line_bounded_all_phases_full
# with every phase's riders present AND a pytest-length evidence path —
# ~44 bytes longer than the production ~/.cache path), 2000 leaves ~40
# bytes of tail-window slop (the tail carries nothing but this line and
# its newline), and the all-failed degraded form stays under the
# 1750-byte tail-slop pin in tests/test_bench_json.py.  Pinned by unit
# tests at both extremes.
MAX_LINE_BYTES = 2000


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# Child: one phase, one process, own backend.
# ---------------------------------------------------------------------------

def _peak_tflops(device_kind: str):
    kind = device_kind.lower()
    for sub, peak in PEAK_TFLOPS_BF16:
        if sub in kind:
            return peak
    return None


def _model_and_views(config: str):
    import jax.numpy as jnp
    from active_learning_tpu.data.core import (CIFAR10_NORM, IMAGENET_NORM,
                                               ViewSpec)
    from active_learning_tpu.models.resnet import resnet18, resnet50

    # The bench measures the production bf16 configuration: fused bf16 BN
    # statistics (TrainConfig.bn_stats_dtype "auto" on a bf16 model) and,
    # for the 224px model, the space-to-depth stem.  AL_BENCH_S2D=0 /
    # AL_BENCH_BN_STATS=f32 restore the old stem/stats for A/Bs.
    s2d = os.environ.get("AL_BENCH_S2D", "1") != "0"
    bf16_stats = os.environ.get("AL_BENCH_BN_STATS", "bf16") != "f32"
    bn_stats = jnp.bfloat16 if bf16_stats else None
    if config == "resnet50_imagenet":
        model = resnet50(num_classes=1000, dtype=jnp.bfloat16,
                         stem="s2d" if s2d else "default",
                         bn_stats_dtype=bn_stats)
        # ImageNet: crop happens at decode; the device view only flips
        # (data/imagenet.py:257).
        return (model, 224, 1000,
                ViewSpec(IMAGENET_NORM, augment=True, pad=0),
                ViewSpec(IMAGENET_NORM, augment=False))
    model = resnet18(num_classes=10, cifar_stem=True, dtype=jnp.bfloat16,
                     bn_stats_dtype=bn_stats)
    return (model, 32, 10, ViewSpec(CIFAR10_NORM, augment=True, pad=4),
            ViewSpec(CIFAR10_NORM, augment=False))


def _model_config_fields(model) -> dict:
    """The stem/BN-stats configuration a train/score phase measured —
    recorded in the phase JSON so every number is attributable to its
    compute configuration."""
    import jax.numpy as jnp
    return {
        "s2d": getattr(model, "stem", "default") == "s2d",
        "bn_stats_dtype": ("bfloat16"
                          if getattr(model, "bn_stats_dtype", None)
                          == jnp.bfloat16 else "float32"),
    }


def _ensure_jpeg_tree(root: str, n_images: int, n_classes: int = 100
                      ) -> float:
    """Synthetic ImageNet-like JPEG tree: ``n_classes`` class directories,
    variable image sizes (224-320px), seeded per index so the tree is
    reproducible and resumable.  ONE shared root that only ever grows: a
    retry with a smaller target reuses the existing files (smaller runs
    read a ``limit=`` of them), so generation cost is paid once, not per
    attempt.  Returns generation seconds (0.0 when enough images exist)."""
    import numpy as np
    from PIL import Image

    marker = os.path.join(root, ".generated")
    have = 0
    try:
        with open(marker) as fh:
            have = int(fh.read().strip() or 0)
    except (OSError, ValueError):
        pass
    if have >= n_images:
        return 0.0
    t0 = time.perf_counter()
    for c in range(n_classes):
        os.makedirs(os.path.join(root, f"cls_{c:04d}"), exist_ok=True)
    for i in range(n_images):
        path = os.path.join(root, f"cls_{i % n_classes:04d}",
                            f"img_{i:06d}.jpg")
        if os.path.exists(path):
            continue
        rng = np.random.default_rng(i)
        h = int(rng.integers(224, 321))
        w = int(rng.integers(224, 321))
        base = rng.integers(0, 256, size=(12, 16, 3), dtype=np.uint8)
        Image.fromarray(base).resize((w, h), Image.BILINEAR).save(
            path, quality=75)
    with open(marker, "w") as fh:
        fh.write(str(n_images))
    return time.perf_counter() - t0


def run_datapath_phase(n_images: int, per_chip: int):
    """End-to-end rehearsal of the ImageNet scoring data path: disk JPEGs
    -> native C++ batch decode/crop/resize -> threaded prefetch ->
    mesh-sharded ResNet-50 scoring via collect_pool (which also enforces
    score/index alignment over the whole pass).  Reports the end-to-end
    scoring rate, the decode-only rate, and the per-core decode rate —
    the number that says how many host cores a full-size run needs to
    keep the mesh fed.

    GENERATOR: yields the result after each completed measurement (cold
    scored pass, warm scored pass, warm gather decomposition) so a
    timeout mid-phase loses only the unfinished measurement — the caller
    prints each snapshot as its own JSON line and the parent keeps the
    last parseable one."""
    import tempfile

    import numpy as np

    import jax
    import jax.numpy as jnp
    from active_learning_tpu.data.core import IMAGENET_NORM, ViewSpec
    from active_learning_tpu.data.imagenet import ImageFolderDataset
    from active_learning_tpu.data.pipeline import iterate_batches
    from active_learning_tpu.parallel import mesh as mesh_lib
    from active_learning_tpu.strategies import scoring

    root = os.path.join(tempfile.gettempdir(), "al_tpu_datapath")
    gen_sec = _ensure_jpeg_tree(root, n_images)
    mesh = mesh_lib.make_mesh(-1)
    n_chips = int(mesh.devices.size)
    batch_size = per_chip * n_chips
    device_kind = jax.devices()[0].device_kind
    cores = (len(os.sched_getaffinity(0))
             if hasattr(os, "sched_getaffinity") else os.cpu_count() or 1)
    threads = max(2, min(16, 2 * cores))
    log(f"[imagenet_datapath] {n_images} JPEGs (gen {gen_sec:.0f}s), "
        f"{n_chips}x {device_kind}, batch {batch_size}, {cores} host cores")

    view = ViewSpec(IMAGENET_NORM, augment=False)
    dataset = ImageFolderDataset(root, view, train_transform=False,
                                 num_classes=1000, limit=n_images)
    dataset.gather(np.arange(8))  # warm-up: builds/loads the native lib

    # Decode-only: the host side in isolation (native decode + crop +
    # resize + batch assembly through the threaded prefetcher).
    n_decode = min(len(dataset), 5000)
    t0 = time.perf_counter()
    rows = 0
    for b in iterate_batches(dataset, np.arange(n_decode), batch_size,
                             num_threads=threads):
        rows += int(b["mask"].sum())
    decode_ips = rows / (time.perf_counter() - t0)

    result = {
        "phase": "imagenet_datapath",
        "n_chips": n_chips,
        "batch_per_chip": per_chip,
        "n_images": len(dataset),
        "decode_ips": round(decode_ips, 1),
        "host_cores": cores,
        "decode_ips_per_core": round(decode_ips / cores, 1),
        "gen_sec": round(gen_sec, 1),
        "device_kind": device_kind,
        "platform": jax.devices()[0].platform,
    }
    if jax.devices()[0].platform != "cpu":
        # Host->device bandwidth for one decoded batch: on a tunneled
        # remote backend this transfer (19 MB per 128-row 224px batch) can
        # be the end-to-end bottleneck; on a co-located TPU host it is
        # PCIe-speed noise.  Reported so a slow end-to-end rate is
        # attributable.  Skipped on the CPU-fallback backend, where a
        # device_put is a host memcpy describing no real transfer path.
        probe = np.zeros((batch_size, 224, 224, 3), dtype=np.uint8)
        jax.device_put(probe).block_until_ready()  # warm the path
        t0 = time.perf_counter()
        jax.device_put(probe).block_until_ready()
        h2d_mb_s = probe.nbytes / 1e6 / (time.perf_counter() - t0)
        result["h2d_mb_per_sec"] = round(h2d_mb_s, 1)
        result["h2d_ips_ceiling"] = round(h2d_mb_s * 1e6 / (224 * 224 * 3),
                                          1)
    if os.environ.get("AL_BENCH_DATAPATH_DECODE_ONLY") == "1":
        # Accelerator unreachable: report the host-side numbers (the
        # phase's real subject) and skip the model pass.
        result.update(ips=round(decode_ips, 1),
                      ips_per_chip=round(decode_ips / n_chips, 1),
                      decode_only=True)
        yield result
        return

    # Full scoring pass over the whole tree, decode overlapped with device
    # compute exactly as a real acquisition round runs it — INCLUDING the
    # production decoded-pool memmap cache (driver wires it the same way),
    # so this timed pass is round 0 (decode + cache write) and the second
    # pass below is every later round (pure cache read, bounded by
    # h2d/page cache instead of JPEG decode).
    import shutil

    from active_learning_tpu.data.cache import maybe_wrap_decoded
    # Same location family as the production driver (~/.cache), NOT
    # tempfile.gettempdir(): /tmp is commonly tmpfs, where a pool-sized
    # uint8 "disk" cache is actually host RAM and can OOM the bench.
    # The fixed "decoded_bench" leaf is ALWAYS appended — this dir is
    # rmtree'd below, and an env override naming a shared parent (or the
    # production cache) must never make that recursive delete eat it.
    cache_dir = os.path.join(
        os.environ.get("AL_BENCH_CACHE_DIR")
        or os.path.join(os.path.expanduser("~"), ".cache", "al_tpu"),
        "decoded_bench")
    shutil.rmtree(cache_dir, ignore_errors=True)  # measure a COLD round 0
    cached_set = maybe_wrap_decoded(dataset, cache_dir, 32 << 30)
    result["decoded_cache"] = cached_set is not dataset
    try:
        yield from _datapath_model_passes(result, dataset, cached_set,
                                          batch_size, threads, mesh)
    finally:
        # Pool-sized uint8 data must not squat in persistent ~/.cache
        # after the bench (and the next run's round 0 must start cold).
        shutil.rmtree(cache_dir, ignore_errors=True)


def _datapath_model_passes(result, dataset, cached_set, batch_size,
                           threads, mesh):
    import numpy as np

    import jax
    import jax.numpy as jnp
    from active_learning_tpu.strategies import scoring

    n_chips = result["n_chips"]
    model, _, _, _, score_view = _model_and_views("resnet50_imagenet")
    result.update(_model_config_fields(model))
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((8, 224, 224, 3), jnp.float32),
                           train=False)
    step = scoring.make_prob_stats_step(model, score_view)
    # Untimed warm-up at the real batch shape: the jitted step's XLA
    # compile (tens of seconds for ResNet-50 on TPU) must not pollute the
    # measured pass, same as every other phase's 3 warm-up iterations.
    scoring.collect_pool(dataset, np.arange(min(batch_size, len(dataset))),
                         batch_size, step, variables, mesh,
                         keys=("margin",))
    all_idxs = np.arange(len(dataset))
    t0 = time.perf_counter()
    out = scoring.collect_pool(cached_set, all_idxs, batch_size, step,
                               variables, mesh, num_workers=threads,
                               prefetch=4, keys=("margin",))
    score_sec = time.perf_counter() - t0
    assert len(out["margin"]) == len(dataset)
    ips = len(dataset) / score_sec
    # Field semantics (the r5 naming trap: "warm" 157.7 reading LOWER
    # than "cold" 348.6 looked like a regression): the COLD pass is the
    # decode-once round-0 pass that ALSO writes the memmap cache, run
    # with every decode thread busy; the WARM pass is the steady-state
    # rounds-1+ memmap feed, whose rate is bounded by page-cache/gather
    # bandwidth, not decode parallelism — on a many-core host cold decode
    # can legitimately out-rate the single-stream warm gather.  The
    # canonical names (cold_populate_ips / warm_memmap_ips) are the ONLY
    # spellings; the deprecated ips_warm alias and its deprecated_keys
    # shim served their one release (PR 5) and are gone.  ``ips`` stays
    # as the generic phase-schema throughput key every phase carries.
    result.update(
        ips=round(ips, 1), ips_per_chip=round(ips / n_chips, 1),
        cold_populate_ips=round(ips, 1),
        score_sec=round(score_sec, 1))
    yield dict(result)  # cold pass is safe with the parent
    if cached_set is not dataset:
        # Steady state: rounds 1+ re-score the pool from the warm cache.
        t0 = time.perf_counter()
        out = scoring.collect_pool(cached_set, all_idxs, batch_size, step,
                                   variables, mesh, num_workers=threads,
                                   prefetch=4, keys=("margin",))
        warm_sec = time.perf_counter() - t0
        assert len(out["margin"]) == len(dataset)
        result.update(warm_memmap_ips=round(len(dataset) / warm_sec, 1),
                      warm_score_sec=round(warm_sec, 1))
        yield dict(result)  # warm pass is safe with the parent
        # Host-side-only warm rate (cache gather + batch assembly, no
        # device work): decomposes warm_memmap_ips into host vs
        # device+h2d the way decode_ips does for the cold pass — on a
        # 1-core sandbox the warm pass is HOST-bound and this number
        # says by how much.
        t0 = time.perf_counter()
        rows = 0
        for start in range(0, len(dataset), batch_size):
            rows += len(cached_set.gather(
                all_idxs[start:start + batch_size]))
        gather_sec = time.perf_counter() - t0
        result.update(warm_gather_ips=round(rows / gather_sec, 1),
                      warm_gather_sec=round(gather_sec, 1))
        yield dict(result)
        # Device-resident warm pass: the fully-populated cache promotes
        # to .images (data/cache.py), and with the budget raised over the
        # pool (the documented --resident_scoring_bytes deployment choice
        # for 16 GB chips) rounds 1+ score via on-device gathers — no
        # per-batch image h2d at all.  Timed including the one-off pool
        # upload, reported separately so steady state is attributable.
        cache = None
        try:
            from active_learning_tpu.parallel import resident as res_lib
            pool_bytes = len(dataset) * int(np.prod(
                cached_set.image_shape))
            if res_lib.eligible(cached_set, pool_bytes + 1):
                cache = {}
                t0 = time.perf_counter()
                # block_until_ready: device_put is async, and an in-flight
                # multi-GB transfer leaking into the scoring timer would
                # defeat the point of reporting the upload separately.
                jax.block_until_ready(
                    res_lib.pool_arrays(cache, cached_set, mesh))
                upload_sec = time.perf_counter() - t0
        except Exception as e:
            # Genuinely environmental: HBM/upload failure.  Correctness
            # of the scoring pass itself is NOT handled here — see below.
            log(f"[imagenet_datapath] resident warm pass unavailable: "
                f"{e!r}")
            result["resident_warm_error"] = repr(e)[:160]
            yield dict(result)
            cache = None
        if cache is not None:
            run_kwargs = dict(keys=("margin",), resident_cache=cache,
                              resident_max_bytes=pool_bytes + 1)
            # Untimed warm-up: the resident gather runner is a fresh jit
            # that has never executed — its compile (tens of seconds on
            # TPU) must not pollute the steady-state number, same as
            # every other phase's warm-up.
            scoring.collect_pool(cached_set, all_idxs[:batch_size],
                                 batch_size, step, variables, mesh,
                                 **run_kwargs)
            t0 = time.perf_counter()
            out = scoring.collect_pool(cached_set, all_idxs, batch_size,
                                       step, variables, mesh, **run_kwargs)
            resident_sec = time.perf_counter() - t0
            if len(out["margin"]) != len(dataset):
                # A row-count mismatch is a scoring correctness bug and
                # must read as one — never as "unavailable".
                result["resident_warm_error"] = (
                    f"CORRECTNESS: resident pass returned "
                    f"{len(out['margin'])} rows for {len(dataset)}")
            else:
                result.update(
                    warm_resident_ips=round(len(dataset) / resident_sec,
                                            1),
                    warm_resident_sec=round(resident_sec, 1),
                    resident_upload_sec=round(upload_sec, 1))
            yield dict(result)


def run_train_feed_phase(epochs: int, per_chip: int):
    """The train-feed hierarchy, leg by leg: identical fits (same pool,
    same seeds, bit-identical batch streams) through the PRODUCTION
    Trainer.fit under each feed —

      * resident       on-device gather + augment from the pinned pool
                       (zero host image copies after the one upload);
      * host_prefetch  worker-threaded gather behind the double-buffered
                       device prefetch (data/pipeline.train_feed_batches);
      * host_serial    the per-batch gather -> shard -> step loop.

    The measured host feed (BENCH_r05: 157.7 warm memmap ips) against an
    8-chip device demand of ~21k ips is the ~100x host-bound gap this
    phase exists to close; feed_stall_frac on the host legs quantifies
    it directly.  GENERATOR: yields after each completed leg so a
    timeout loses only the unfinished ones."""
    import numpy as np

    import jax
    from active_learning_tpu.config import (LoaderConfig, TelemetryConfig,
                                            TrainConfig)
    from active_learning_tpu.data.core import ArrayDataset
    from active_learning_tpu.parallel import mesh as mesh_lib
    from active_learning_tpu.telemetry import runtime as tele_runtime
    from active_learning_tpu.train.trainer import Trainer

    smoke = (os.environ.get("AL_BENCH_ROUND_SMOKE") == "1"
             or jax.devices()[0].platform == "cpu")
    config = "smoke_tinyconv" if smoke else "resnet50_imagenet"
    if smoke:
        # CPU/CI smoke: a tiny conv net — ResNet steps cost ~6 s each on
        # one CPU core, and the smoke exists to exercise every feed leg
        # end-to-end, not to measure ResNet.  Tagged "smoke" so the
        # parent's cache can never bill it as a real capture's config.
        import flax.linen as nn
        import jax.numpy as jnp
        from active_learning_tpu.data.core import CIFAR10_NORM, ViewSpec

        class _SmokeNet(nn.Module):
            @nn.compact
            def __call__(self, x, train=True, return_features=False):
                x = x.astype(jnp.float32)
                x = nn.relu(nn.Conv(8, (3, 3))(x))
                emb = x.mean(axis=(1, 2))
                logits = nn.Dense(10, name="linear")(emb)
                return (logits, emb) if return_features else logits

        model, px, n_classes = _SmokeNet(), 32, 10
        train_view = ViewSpec(CIFAR10_NORM, augment=True, pad=4)
    else:
        model, px, n_classes, train_view, _score_view = _model_and_views(
            "resnet50_imagenet")
    mesh = mesh_lib.make_mesh(-1)
    n_chips = int(mesh.devices.size)
    device_kind = jax.devices()[0].device_kind
    batch_size = per_chip * n_chips
    pool_n = max(4 * batch_size, 256 if smoke else 4096)
    cores = (len(os.sched_getaffinity(0))
             if hasattr(os, "sched_getaffinity") else os.cpu_count() or 1)
    workers = max(2, min(16, 2 * cores))
    log(f"[imagenet_train_feed] {config} x{n_chips} {device_kind}, pool "
        f"{pool_n}x{px}px, batch {batch_size}, {epochs} epochs/leg")

    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, size=(pool_n, px, px, 3), dtype=np.uint8)
    targets = rng.integers(0, n_classes, size=pool_n).astype(np.int64)

    # feed_stall_frac/host_wait collection needs an ENABLED telemetry
    # runtime (the trainer's collect gate); no heartbeat/trace — just the
    # per-step collection flag.
    rt = tele_runtime.RunTelemetry(cfg=TelemetryConfig(enabled=True))
    tele_runtime.install(rt)
    result = {
        "phase": "imagenet_train_feed",
        "ips": None, "ips_per_chip": None,
        "unit": "train images/sec (in-fit)",
        "n_chips": n_chips, "batch_per_chip": per_chip,
        "pool_n": pool_n, "px": px, "epochs": epochs, "smoke": smoke,
        "model_config": config, "feed_workers": workers,
        "device_kind": device_kind,
        "platform": jax.devices()[0].platform,
        **_model_config_fields(model),
    }
    legs = (
        ("resident", dict(train_feed="resident",
                          loader=dict(num_workers=0, prefetch=2))),
        ("host_prefetch", dict(train_feed="host", feed_workers=workers,
                               loader=dict(num_workers=0, prefetch=4))),
        ("host_serial", dict(train_feed="host", feed_workers=0,
                             loader=dict(num_workers=0, prefetch=0))),
    )
    try:
        for leg, spec in legs:
            loader = spec.pop("loader")
            cfg = TrainConfig(
                loader_tr=LoaderConfig(batch_size=batch_size, **loader),
                **spec)
            train_set = ArrayDataset(images, targets, n_classes, train_view)
            trainer = Trainer(model, cfg, mesh, n_classes, train_bn=True)
            labeled = np.arange(pool_n)

            def one_fit(n_ep: int):
                state = trainer.init_state(jax.random.PRNGKey(0),
                                           images[:8])
                return trainer.fit(state, train_set, labeled, train_set,
                                   np.zeros(0, np.int64), n_epoch=n_ep,
                                   es_patience=0,
                                   rng=np.random.default_rng(1))

            one_fit(1)  # warm-up: compiles (and the resident upload)
            t0 = time.perf_counter()
            fit = one_fit(epochs)
            # fit materializes every epoch loss to host floats before
            # returning — a data-dependent fetch, so the wall is real.
            assert all(
                isinstance(h["train_loss"], float) for h in fit.history)
            dt = time.perf_counter() - t0
            got = trainer.last_feed
            ips = pool_n * epochs / dt
            if got["source"] == leg:
                result[f"ips_{leg}"] = round(ips, 1)
                result[f"stall_{leg}"] = got.get("feed_stall_frac")
            else:
                # e.g. the pool didn't fit the resident budget: the leg
                # degraded — record what actually ran under a DEGRADED
                # key, never as the leg's number (resident_x_serial and
                # the compact line's legs array derive only from true
                # per-leg captures).
                result[f"feed_degraded_{leg}"] = got["source"]
                result[f"ips_{leg}_degraded"] = round(ips, 1)
            log(f"[imagenet_train_feed] {leg}: {ips:,.1f} img/s "
                f"(feed={got['source']}, "
                f"stall={got.get('feed_stall_frac')})")
            if leg == "resident" and got["source"] == "resident":
                result["ips"] = round(ips, 1)
                result["ips_per_chip"] = round(ips / n_chips, 1)
                result["feed_source"] = got["source"]
                result["feed_stall_frac"] = got.get("feed_stall_frac")
            yield dict(result)
    finally:
        tele_runtime.uninstall(rt)
    if result.get("ips_host_serial") and result.get("ips_resident"):
        result["resident_x_serial"] = round(
            result["ips_resident"] / result["ips_host_serial"], 2)
    # An auto-resolved trainer must land on the top of the hierarchy —
    # the acceptance invariant "resident-gather is the auto-selected
    # path whenever the pool is pinned", asserted LIVE on accelerator
    # runs (the CPU smoke's auto rule deliberately keeps small fits on
    # the host leg — the scan compile doesn't amortize there).
    if not smoke:
        auto_trainer = Trainer(model, TrainConfig(
            loader_tr=LoaderConfig(batch_size=batch_size)), mesh,
            n_classes, train_bn=True)
        train_set = ArrayDataset(images, targets, n_classes, train_view)
        from active_learning_tpu.parallel import resident as resident_lib
        if resident_lib.eligible(train_set, auto_trainer.resident_budget):
            # Pinned, exactly as a round's scoring pass pins it.
            resident_lib.pool_arrays(auto_trainer.resident_pool,
                                     train_set, mesh)
            auto = auto_trainer.resolve_train_feed(train_set,
                                                   np.arange(pool_n))
            result["auto_feed_with_pinned_pool"] = auto
            if auto != "resident":
                result["auto_feed_error"] = (
                    "CORRECTNESS: pinned pool did not auto-select the "
                    f"resident feed (got {auto})")
    yield result


def run_kcenter_phase(budget: int, dim: int = 2048, pool_n: int = 50000
                      ) -> dict:
    """Greedy k-center selection at the paper's protocol scale over a
    [50k, 2048] embedding pool (the reference's subset cap,
    gen_jobs.py:8-13; its host loop does one np.random.choice +
    full-matrix min per pick, coreset_sampler.py:66-105).  Times the
    PRODUCTION path: batched farthest-first (q = DEFAULT_BATCH_Q picks
    per pool pass) on the XLA scan — since the r5 verdict deleted the
    Pallas kernel this is the only backend; the scan that answered
    still rides in "backend" for attribution.  Reports picks/sec; "ips"
    carries picks/sec so the parent's schema checks hold (unit field
    says which)."""
    import numpy as np

    import jax
    from active_learning_tpu.strategies import kcenter as kc
    from active_learning_tpu.strategies.kcenter import (DEFAULT_BATCH_Q,
                                                        kcenter_greedy)

    device_kind = jax.devices()[0].device_kind
    log(f"[kcenter_select] pool [{pool_n}, {dim}], budget {budget} on "
        f"{device_kind}")
    host_rng = np.random.default_rng(0)
    emb = host_rng.normal(size=(pool_n, dim)).astype(np.float32)
    labeled = np.zeros(pool_n, dtype=bool)
    labeled[host_rng.choice(pool_n, min(1000, pool_n // 8),
                            replace=False)] = True

    # Warm-up at the SAME budget/shapes (budget is a static scan length):
    # the first call pays the XLA compile, the timed call does not.
    kcenter_greedy((emb,), labeled, budget, rng=np.random.default_rng(1))
    t0 = time.perf_counter()
    picks = kcenter_greedy((emb,), labeled, budget,
                           rng=np.random.default_rng(2))
    dt = time.perf_counter() - t0
    assert len(picks) == budget and len(set(picks.tolist())) == budget
    rate = budget / dt
    result = {
        "phase": "kcenter_select",
        "ips": round(rate, 1),
        "ips_per_chip": round(rate, 1),
        "unit": "picks/sec",
        "n_chips": 1,  # the sequential scan runs on one chip
        "pool_n": pool_n,
        "dim": dim,
        "budget": budget,
        "batch_q": DEFAULT_BATCH_Q,
        "backend": kc.LAST_BACKEND,
        "pool_sharding": kc.LAST_SHARDING,
        "ring_feed": kc.LAST_RING_FEED,
        "select_sec": round(dt, 2),
        "device_kind": device_kind,
        "platform": jax.devices()[0].platform,
    }
    try:
        stats = jax.local_devices()[0].memory_stats() or {}
        peak = stats.get("peak_bytes_in_use")
        if peak:
            result["peak_hbm_gb"] = round(peak / 2**30, 2)
    except Exception:
        pass  # memory_stats is backend-dependent; absence is fine
    return result, picks


def run_kcenter_maxn_phase(budget: int, dim: int = 2048):
    """Climb + bisect toward the largest pool the no-partition k-center
    scan completes — now under BOTH resident layouts (ISSUE 6):

      1. REPLICATED: the single-chip ceiling, 160k -> 320k -> 640k ->
         1.28M rows of [N, 2048] f32 factors (1.28M x 2048 x 4 =
         10.5 GB — the FULL ImageNet pool), with a couple of bisection
         steps between the last success and the first failure.  This is
         the pre-sharding envelope (``replicated_max_n`` /
         ``no_partition_holds_to_n``).
      2. ROW-SHARDED (multi-device meshes): the same climb with the
         ladder scaled by the device count — each chip holds rows/ndev
         of the factor matrix (strategies/kcenter._build_sharded_fns),
         so max-N should scale ~linearly with chips.  The phase ASSERTS
         ``max_n >= 2 * replicated_max_n`` whenever both layouts
         completed a climb on a >=2-device mesh at equal per-chip HBM
         (``row_scale_x`` records the measured ratio) — the acceptance
         gate for breaking, not just finding, the ceiling.

    Each attempt records picks/s, its analytic per-chip factor bytes
    (``factor_gb_per_chip`` — the equal-per-chip-HBM evidence), and the
    measured per-chip / mesh-total peak HBM; ``peak_bytes_in_use`` is a
    process-lifetime high-water mark, so an attempt that peaked below an
    earlier one carries ``peak_hbm_carryover`` instead of claiming the
    stale figure as its own.  Row rungs whose bucketed pool cannot split
    over the mesh (``kcenter.row_capable``) are refused before any
    compute — the greedy would silently run them replicated at ndev
    times the per-chip bytes.  Failures past the envelope
    (RESOURCE_EXHAUSTED) are recorded, not fatal.  GENERATOR: yields
    after every completed attempt so a timeout loses only the unfinished
    pool size.  CPU backends climb a tiny ladder instead — the envelope
    question is an HBM question; the layout-scaling question still
    answers structurally."""
    import numpy as np

    import jax
    from active_learning_tpu.parallel import mesh as mesh_lib
    from active_learning_tpu.strategies import kcenter as kc

    platform = jax.devices()[0].platform
    device_kind = jax.devices()[0].device_kind
    n_chips = len(jax.devices())
    mesh = mesh_lib.make_mesh() if n_chips > 1 else None
    sharding = "row" if mesh is not None else "replicated"
    if platform == "cpu":
        ladder = [4096, 8192, 16384]
        budget = min(budget, 64)
    else:
        ladder = [160_000, 320_000, 640_000, 1_280_000]
    row_ladder = [n * n_chips for n in ladder]
    result = {
        "phase": "kcenter_select_maxn",
        "ips": None, "ips_per_chip": None, "unit": "picks/sec",
        "n_chips": n_chips, "dim": dim, "budget": budget,
        "pool_sharding": sharding, "max_n": 0, "replicated_max_n": 0,
        "target_n": (row_ladder if mesh is not None else ladder)[-1],
        "attempts": [],
        "device_kind": device_kind, "platform": platform,
    }

    def hbm_peaks():
        per = []
        try:
            for d in jax.local_devices():
                stats = d.memory_stats() or {}
                p = stats.get("peak_bytes_in_use")
                if p:
                    per.append(int(p))
        except Exception:
            pass  # memory_stats is backend-dependent; absence is fine
        if not per:
            return None, None
        return max(per), sum(per)

    def attempt(n: int, use_mesh):
        layout = "row" if use_mesh is not None else "replicated"
        if use_mesh is not None and not kc.row_capable(n, budget,
                                                       use_mesh):
            # The greedy's own gate would silently fall back to the
            # replicated backend (e.g. a bucketed pool that doesn't
            # divide over a non-power-of-two mesh) — which on a row
            # rung means ndev times the intended per-chip bytes and a
            # wrong-layout timing.  Refuse BEFORE any compute so the
            # climb records a layout-capability skip, never a
            # misattributed OOM.
            raise RuntimeError(
                f"row layout unavailable for n={n}: the bucketed pool "
                f"does not split over {use_mesh.devices.size} devices "
                "(kcenter.row_capable) — skipped before any compute")
        log(f"[kcenter_select_maxn] trying pool [{n}, {dim}] "
            f"({n * dim * 4 / 2**30:.1f} GB of factors, {layout})")
        pre_peak, _ = hbm_peaks()
        rng = np.random.default_rng(0)
        # Chunked generation: a 1.28M-row normal draw in one call holds
        # two 10.5 GB temporaries on the host.
        emb = np.empty((n, dim), dtype=np.float32)
        for lo in range(0, n, 131072):
            hi = min(n, lo + 131072)
            emb[lo:hi] = rng.standard_normal(
                (hi - lo, dim), dtype=np.float32)
        labeled = np.zeros(n, dtype=bool)
        labeled[rng.choice(n, min(1000, n // 8), replace=False)] = True
        kcenter_greedy = kc.kcenter_greedy
        kcenter_greedy((emb,), labeled, budget,
                       rng=np.random.default_rng(1), mesh=use_mesh,
                       pool_sharding=layout)  # compile
        t0 = time.perf_counter()
        picks = kcenter_greedy((emb,), labeled, budget,
                               rng=np.random.default_rng(2),
                               mesh=use_mesh, pool_sharding=layout)
        dt = time.perf_counter() - t0
        assert len(set(picks.tolist())) == budget
        assert kc.LAST_SHARDING == layout, (
            f"requested {layout} but selection ran {kc.LAST_SHARDING}")
        entry = {"n": n, "ok": True, "ips": round(budget / dt, 1),
                 "select_sec": round(dt, 2), "pool_sharding": layout}
        # The attempt's true per-chip factor residency, analytically —
        # the number the "equal per-chip HBM" comparison actually
        # rests on (a row rung at n = ndev*m holds the same per-chip
        # factor bytes as the replicated rung at m).
        ways = use_mesh.devices.size if use_mesh is not None else 1
        entry["factor_gb_per_chip"] = round(n * dim * 4 / ways / 2**30, 2)
        per_chip, total = hbm_peaks()
        if per_chip:
            entry["peak_hbm_gb"] = round(per_chip / 2**30, 2)
            entry["mesh_peak_hbm_gb"] = round(total / 2**30, 2)
            if pre_peak is not None and per_chip <= pre_peak:
                # peak_bytes_in_use is a PROCESS-LIFETIME high-water
                # mark: an attempt that peaked below an earlier one
                # (every row rung after the replicated climb hit the
                # single-chip ceiling) reads the old mark, not its
                # own.  Flag it — factor_gb_per_chip above carries the
                # attempt's true residency either way.
                entry["peak_hbm_carryover"] = True
        return entry

    def climb(steps, use_mesh, max_key):
        """Ladder climb + two bisection steps; updates result[max_key]
        and yields a snapshot after every attempt."""
        lo, hi = 0, None  # largest success / smallest failure

        def record(entry):
            result["attempts"].append(entry)
            if entry["ok"] and entry["n"] > result[max_key]:
                result[max_key] = entry["n"]
                # The headline follows the most capable climb that
                # actually SUCCEEDED: the replicated rungs set it, row
                # successes (climbed second, at ndev x the rows)
                # overwrite it — so a row climb with no surviving rung
                # still leaves the measured replicated ceiling on the
                # line instead of a null headline.  Per-chip rate
                # divides by the chips the entry's selection actually
                # used: a replicated attempt runs on ONE device
                # whatever the host holds.
                div = n_chips if entry["pool_sharding"] == "row" else 1
                result["ips"] = entry["ips"]
                result["ips_per_chip"] = round(entry["ips"] / div, 1)
                # The column-feed attribution (ISSUE 15): row-layout
                # headline rungs fed their initial-min/minimax columns
                # over the ring-permute feed; replicated rungs did not.
                result["ring_feed"] = kc.LAST_RING_FEED

        for n in steps:
            try:
                entry = attempt(n, use_mesh)
            except Exception as e:
                log(f"[kcenter_select_maxn] pool {n} failed: {e!r}")
                result["attempts"].append(
                    {"n": n, "ok": False, "error": repr(e)[:160],
                     "pool_sharding": ("row" if use_mesh is not None
                                       else "replicated")})
                hi = n
                yield dict(result)
                break
            record(entry)
            lo = n
            yield dict(result)
        # Two bisection steps sharpen the boundary w/o unbounded retries.
        for _ in range(2):
            if hi is None or hi - lo <= max(lo // 8, 1):
                break
            mid = (lo + hi) // 2 // 2048 * 2048
            if mid <= lo:
                break
            try:
                entry = attempt(mid, use_mesh)
            except Exception as e:
                log(f"[kcenter_select_maxn] pool {mid} failed: {e!r}")
                result["attempts"].append(
                    {"n": mid, "ok": False, "error": repr(e)[:160],
                     "pool_sharding": ("row" if use_mesh is not None
                                       else "replicated")})
                hi = mid
                yield dict(result)
                continue
            record(entry)
            lo = mid
            yield dict(result)

    # 1. The replicated (single-chip) envelope — the number DESIGN.md
    # §3's N ~ 1.8M arithmetic must reproduce on a 16 GB chip.
    yield from climb(ladder, None, "replicated_max_n")
    result["no_partition_holds_to_n"] = result["replicated_max_n"]
    if mesh is None:
        result["max_n"] = result["replicated_max_n"]
        yield dict(result)
        return
    # 2. The row-sharded climb: same per-chip rows, ndev x the pool.
    yield from climb(row_ladder, mesh, "max_n")
    if result["replicated_max_n"] > 0 and result["max_n"] > 0:
        scale = result["max_n"] / result["replicated_max_n"]
        result["row_scale_x"] = round(scale, 2)
        if n_chips >= 2:
            # The acceptance gate (ISSUE 6): row sharding must SUSTAIN
            # at least 2x the replicated ceiling at equal per-chip HBM
            # (each row attempt holds replicated-sized shards per chip).
            assert scale >= 2.0, (
                f"row-sharded max_n {result['max_n']} is only "
                f"{scale:.2f}x the replicated ceiling "
                f"{result['replicated_max_n']} on {n_chips} devices")
    elif result["max_n"] == 0:
        # No row rung survived (a gate-refused mesh geometry, or the
        # collectives' overhead pushed the first rung past the
        # envelope): the phase's honest ceiling is the replicated one —
        # emit it, tagged with the layout the headline now actually
        # describes, rather than max_n=0/ips=null discarding the
        # completed replicated climb.
        result["max_n"] = result["replicated_max_n"]
        result["pool_sharding"] = "replicated"
    yield dict(result)


def run_vaal_phase(epochs: int, per_chip: int):
    """One VAE+discriminator co-train epoch over the synthetic in-memory
    pool through the PRODUCTION VAALSampler step (strategies/vaal.py),
    asserted finite and learning (reconstruction loss falls over the
    epoch) — the first on-accelerator execution record for the VAAL path;
    until now it had only CPU-mesh unit tests (tests/test_vaal.py)."""
    import tempfile

    import numpy as np

    import jax
    import jax.numpy as jnp
    from active_learning_tpu.config import ExperimentConfig
    from active_learning_tpu.data.pipeline import iterate_batches
    from active_learning_tpu.data.synthetic import get_data_synthetic
    from active_learning_tpu.experiment.driver import build_experiment
    from active_learning_tpu.parallel import mesh as mesh_lib

    n_chips = len(jax.devices())
    device_kind = jax.devices()[0].device_kind
    smoke = os.environ.get("AL_BENCH_ROUND_SMOKE") == "1"
    pool_n = 512 if smoke else 4096
    tmp = tempfile.mkdtemp(prefix="al_bench_vaal_")
    data = get_data_synthetic(n_train=pool_n, n_test=64)
    cfg = ExperimentConfig(
        dataset="synthetic", arg_pool="synthetic", strategy="VAALSampler",
        rounds=1, round_budget=min(256, pool_n // 4), model="SSLResNet18",
        n_epoch=epochs, enable_metrics=False, log_dir=tmp, ckpt_path=tmp,
        exp_hash="bench")
    strategy = build_experiment(cfg, data=data)
    strategy.init_network_weights()
    bs = strategy.trainer.padded_batch_size(per_chip * n_chips)
    labeled = strategy.already_labeled_idxs()
    unlabeled = strategy.available_query_idxs(shuffle=False)
    log(f"[vaal_cotrain] {n_chips}x {device_kind}, pool {pool_n}, "
        f"batch {bs}, {epochs} epoch(s)")

    def epoch_batches():
        u_iter = iterate_batches(strategy.train_set, unlabeled, bs)
        for b_l in iterate_batches(strategy.train_set, labeled, bs):
            b_u = next(u_iter, None)
            if b_u is None:
                u_iter = iterate_batches(strategy.train_set, unlabeled, bs)
                b_u = next(u_iter)
            yield b_l, b_u

    key = jax.random.PRNGKey(0)
    losses = []
    steps = 0
    vs = strategy.vaal_state
    t0 = time.perf_counter()
    for _ in range(epochs):
        for b_l, b_u in epoch_batches():
            key, sub = jax.random.split(key)
            vs, step_losses = strategy._vaal_step(
                vs, mesh_lib.shard_batch(b_l, strategy.mesh),
                mesh_lib.shard_batch(b_u, strategy.mesh),
                sub, jnp.float32(cfg.vaal.lr_vae),
                jnp.float32(cfg.vaal.lr_discriminator))
            losses.append(step_losses)  # device scalars; fetched below
            steps += 1
    vae = [float(d["vae_loss"]) for d in losses]
    d_l = [float(d["d_loss"]) for d in losses]
    dt = time.perf_counter() - t0
    # The execution-record assertions: every loss finite, and the VAE
    # actually learned (mean reconstruction+KL over the last quarter of
    # the epoch below the first quarter).  A violation fails the phase.
    assert all(np.isfinite(v) for v in vae + d_l), "non-finite VAAL loss"
    q = max(1, len(vae) // 4)
    learned = float(np.mean(vae[-q:])) < float(np.mean(vae[:q]))
    assert learned, (f"VAE loss did not fall: first-quarter "
                     f"{np.mean(vae[:q]):.3f} vs last {np.mean(vae[-q:]):.3f}")
    ips = 2 * bs * steps / dt  # labeled + unlabeled rows per step
    import shutil
    shutil.rmtree(tmp, ignore_errors=True)
    return {
        "phase": "vaal_cotrain",
        "ips": round(ips, 1),
        "ips_per_chip": round(ips / n_chips, 1),
        "unit": "cotrain images/sec",
        "n_chips": n_chips,
        "batch_per_chip": per_chip,
        "pool_n": pool_n,
        "steps": steps,
        "vae_loss_first": round(vae[0], 4),
        "vae_loss_last": round(vae[-1], 4),
        "d_loss_first": round(d_l[0], 4),
        "d_loss_last": round(d_l[-1], 4),
        "finite_losses": True,
        "learned": bool(learned),
        "device_kind": device_kind,
        "platform": jax.devices()[0].platform,
    }


def run_serve_phase(duration_s: int, max_batch: int) -> dict:
    """The ONLINE path's throughput/latency record: a real loopback
    scoring service (active_learning_tpu/serve/ — asyncio HTTP server,
    microbatcher, device executor) driven by the closed+open-loop load
    generator (scripts/serve_loadgen.py).  Request latency, not round
    wall-clock, is the metric here; "ips" carries served images/sec so
    the parent's schema checks hold (the unit field says which).

    The phase also asserts the serving contract the subsystem was built
    around: after the startup bucket warmup, the request path performs
    ZERO XLA compiles (the tests/test_compile_reuse.py counter, read
    back through /metrics) — a violation fails the phase loudly.

    AL_BENCH_SERVE_SMOKE=1 shrinks to a tiny linear model at 8px for
    CI; the production capture serves SSLResNet18 at the CIFAR shape in
    bf16, the same model resnet18_cifar_score measures offline."""
    import asyncio
    import importlib.util
    import threading

    import numpy as np

    import jax
    from active_learning_tpu.config import ServeConfig
    from active_learning_tpu.parallel import mesh as mesh_lib
    from active_learning_tpu.serve.executor import DeviceExecutor
    from active_learning_tpu.serve.server import ScoringServer

    smoke = os.environ.get("AL_BENCH_SERVE_SMOKE") == "1"
    n_chips = len(jax.devices())
    device_kind = jax.devices()[0].device_kind
    if smoke:
        import flax.linen as nn
        import jax.numpy as jnp
        from active_learning_tpu.data.core import CIFAR10_NORM, ViewSpec

        class _Probe(nn.Module):
            @nn.compact
            def __call__(self, x, train=True, return_features=False):
                emb = x.reshape((x.shape[0], -1)).astype(jnp.float32)
                logits = nn.Dense(10, name="linear")(emb)
                return (logits, emb) if return_features else logits

        model, px = _Probe(), 8
        score_view = ViewSpec(CIFAR10_NORM, augment=False)
        duration_s = min(int(duration_s), 3)
        max_batch = min(int(max_batch), 16)
        workers, rows = 2, 4
    else:
        model, px, _n_classes, _tv, score_view = _model_and_views(
            "resnet18_cifar")
        workers, rows = 4, max(1, max_batch // 4)
    mesh = mesh_lib.make_mesh(-1)
    variables = jax.tree.map(np.asarray, model.init(
        jax.random.PRNGKey(0), np.zeros((2, px, px, 3), np.float32),
        train=False))
    executor = DeviceExecutor(model, score_view, mesh,
                              image_shape=(px, px, 3),
                              variables=variables)
    serve_cfg = ServeConfig(host="127.0.0.1", port=0, max_batch=max_batch,
                            max_latency_ms=5.0,
                            queue_depth=max(128, 8 * max_batch))
    server = ScoringServer(executor, serve_cfg)
    log(f"[serve_throughput] {n_chips}x {device_kind}, max_batch "
        f"{max_batch}, {duration_s}s closed window, {workers} workers x "
        f"{rows} rows")

    loop = asyncio.new_event_loop()
    thread = threading.Thread(
        target=lambda: (asyncio.set_event_loop(loop), loop.run_forever()),
        daemon=True, name="al-bench-serve-loop")
    thread.start()
    spec = importlib.util.spec_from_file_location(
        "serve_loadgen", os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "scripts", "serve_loadgen.py"))
    loadgen = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(loadgen)
    try:
        asyncio.run_coroutine_threadsafe(server.start(), loop).result(600)
        url = f"http://127.0.0.1:{server.port}"
        shape = (px, px, 3)
        closed = loadgen.run_closed(url, duration_s, workers, rows, shape)
        open_qps = max(1.0, 0.7 * closed["qps"])
        opened = loadgen.run_open(url, max(1.0, duration_s / 2),
                                  open_qps, rows, shape)
        snap = server._metrics()
    finally:
        try:
            asyncio.run_coroutine_threadsafe(server.drain(), loop).result(60)
        finally:
            loop.call_soon_threadsafe(loop.stop)
            thread.join(timeout=10)
    compiles = snap["compiles"]["request_path_compiles"]
    # THE contract: every served shape was pre-compiled at startup.
    assert compiles == 0, (
        f"request path compiled {compiles}x after warmup — a served "
        "shape escaped the bucket ladder")
    return {
        "phase": "serve_throughput",
        "ips": closed["ips"],
        "ips_per_chip": round(closed["ips"] / n_chips, 1),
        "unit": "scored images/sec (served)",
        "n_chips": n_chips,
        "batch_per_chip": max_batch,
        "qps_closed": closed["qps"],
        "p50_ms_closed": closed["p50_ms"],
        "p99_ms_closed": closed["p99_ms"],
        "qps_open_offered": opened.get("offered_qps"),
        "qps_open": opened["qps"],
        "p50_ms_open": opened["p50_ms"],
        "p99_ms_open": opened["p99_ms"],
        "n_429": closed["n_429"] + opened["n_429"],
        "workers": workers,
        "rows_per_request": rows,
        "batch_occupancy": snap["batch_occupancy"],
        "request_path_compiles": compiles,
        "buckets": list(server.batcher.buckets),
        "smoke": smoke,
        "device_kind": device_kind,
        "platform": jax.devices()[0].platform,
    }


def run_stream_phase(rounds: int, max_batch: int) -> dict:
    """The streaming-loop smoke: a real StreamService (ingest WAL +
    growable pool + trigger scheduler + driver-phase rounds,
    active_learning_tpu/stream/) on loopback, driven by the load
    generator's ingest mode — N synthetic rows through POST /v1/pool
    (+ a label fraction through /v1/label), the watermark trigger
    fires, and a full AL round completes over the grown pool.  Records
    ingest throughput (rows acked/sec — WAL-fsync bound), ack p50/p99,
    the trigger cause, and the triggered round's wall.

    AL_BENCH_STREAM_SMOKE=1 shrinks to a tiny linear model for CI; the
    production capture streams into SSLResNet18 at the CIFAR shape —
    the same model the serve phase scores."""
    import importlib.util
    import shutil
    import tempfile
    import threading

    import jax
    from active_learning_tpu.config import (ExperimentConfig,
                                            StreamConfig,
                                            TelemetryConfig)
    from active_learning_tpu.data.synthetic import get_data_synthetic
    from active_learning_tpu.faults import preempt as preempt_lib
    from active_learning_tpu.faults.preempt import PreemptionRequested
    from active_learning_tpu.stream.service import StreamService
    from active_learning_tpu.utils.metrics import NullSink

    smoke = os.environ.get("AL_BENCH_STREAM_SMOKE") == "1"
    n_chips = len(jax.devices())
    device_kind = jax.devices()[0].device_kind
    if smoke:
        import sys as _sys
        _sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "tests"))
        from helpers import TinyClassifier, tiny_train_config
        model, train_cfg = TinyClassifier(num_classes=4), \
            tiny_train_config()
        pool_n, px, n_classes, epochs, budget = 96, 8, 4, 2, 8
        ingest_rows, workers, watermark = 16, 2, 24
    else:
        model, train_cfg = None, None
        pool_n, px, n_classes, epochs, budget = 2000, 32, 10, 2, 64
        ingest_rows, workers, watermark = 64, 4, 256
    rounds = max(2, int(rounds))  # bootstrap + >=1 triggered round
    data = get_data_synthetic(n_train=pool_n, n_test=max(64, pool_n // 8),
                              num_classes=n_classes, image_size=px,
                              seed=7)
    tmp = tempfile.mkdtemp(prefix="al_bench_stream_")
    cfg = ExperimentConfig(
        dataset="synthetic", arg_pool="synthetic",
        strategy="MarginSampler", rounds=rounds, round_budget=budget,
        model="SSLResNet18", n_epoch=epochs, early_stop_patience=epochs,
        enable_metrics=False, log_dir=tmp, ckpt_path=tmp,
        exp_hash="benchstream", round_pipeline="off",
        telemetry=TelemetryConfig(enabled=True, heartbeat_every_s=0.0))
    # max_rounds=0 (run forever): the phase stops the service itself
    # once the triggered round lands, via the driver's own in-process
    # preemption flag — exercising the SIGTERM checkpoint path for free.
    scfg = StreamConfig(port=0, max_rounds=0, watermark_rows=watermark,
                        drift_psi=0.0, max_interval_s=0.0, poll_s=0.05,
                        max_request_rows=max(ingest_rows, max_batch),
                        extent_floor=64 if smoke else 256)
    service = StreamService(cfg, scfg, sink=NullSink(), data=data,
                            train_cfg=train_cfg, model=model)
    log(f"[stream_round] {n_chips}x {device_kind}, pool {pool_n}, "
        f"watermark {watermark} rows, {workers} ingest workers x "
        f"{ingest_rows} rows")
    result_box: dict = {}

    def run():
        try:
            result_box["strategy"] = service.run()
        except BaseException as e:  # noqa: BLE001 - examined below
            result_box["error"] = e

    thread = threading.Thread(target=run, daemon=True,
                              name="al-bench-stream")
    t0 = time.perf_counter()
    thread.start()
    try:
        assert service.ready.wait(300), "stream service never came up"
        spec = importlib.util.spec_from_file_location(
            "serve_loadgen", os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "scripts",
                "serve_loadgen.py"))
        loadgen = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(loadgen)
        url = f"http://127.0.0.1:{service.port}"
        ingest = loadgen.run_ingest_closed(
            url, duration_s=2.0 if smoke else 5.0, workers=workers,
            rows=ingest_rows, label_frac=0.25, image_shape=(px, px, 3))
        # Bootstrap (round 0) + at least one TRIGGERED round.
        deadline = time.monotonic() + 540
        while service.rounds_run < 2 and thread.is_alive() \
                and time.monotonic() < deadline:
            time.sleep(0.1)
        assert service.rounds_run >= 2, (
            f"no triggered round completed (rounds_run="
            f"{service.rounds_run})")
    finally:
        # Stop the run-forever loop through the preemption flag — the
        # same checkpoint-and-exit path a real SIGTERM takes.
        preempt_lib._handler(signal.SIGTERM, None)
        thread.join(timeout=120)
    total_sec = time.perf_counter() - t0
    err = result_box.get("error")
    if err is not None and not isinstance(err, PreemptionRequested):
        raise err
    shutil.rmtree(tmp, ignore_errors=True)
    snap = service.metrics.snapshot()
    lat = snap.get("latency_ms") or {}
    return {
        "phase": "stream_round",
        # Headline: acked ingest rows/sec (the WAL-fsync-bound rate).
        "ips": ingest["ips"],
        "ips_per_chip": round(ingest["ips"] / n_chips, 1),
        "unit": "ingested rows/sec (acked)",
        "n_chips": n_chips,
        "batch_per_chip": max_batch,
        "pool_n": pool_n,
        "rounds_run": service.rounds_run,  # bootstrap + triggered
        "trigger_cause": service.last_trigger.get("cause"),
        "ingest_qps": ingest["qps"],
        "ack_p50_ms": lat.get("p50"),
        "ack_p99_ms": lat.get("p99"),
        "n_429": ingest["n_429"],
        "labels_sent": ingest.get("labels_sent"),
        "pool_rows_final": service.store.n_rows,
        "pool_capacity_final": service.store.capacity,
        "total_sec": round(total_sec, 1),
        "smoke": smoke,
        "device_kind": device_kind,
        "platform": jax.devices()[0].platform,
    }


def _last_ring_feed():
    """kcenter.LAST_RING_FEED, imported lazily like every other child-
    side touch of the package (bench parents never import jax)."""
    from active_learning_tpu.strategies import kcenter as kc
    return kc.LAST_RING_FEED


def run_al_round_phase(config: str, epochs: int) -> dict:
    """One REAL end-to-end AL experiment through the production driver —
    BASELINE.md metric #1 ("AL round wall-clock"), mirroring the
    reference's per-phase prints (src/main_al.py:160-178).

    Runs TWO rounds with ``init_pool_size=0`` so round 0 exercises the
    full query -> train -> test loop cold (XLA compiles included) and
    round 1 repeats it warm: the warm round is the steady-state number an
    8/30-round protocol run amortizes to.  Configs:

      * cifar: the CIFAR-10 protocol shape (BASELINE.md config #2) —
        50k-image in-memory pool at 32px, SSLResNet18, MarginSampler,
        budget 1000, the default arg pool's hyperparameters.
      * imagenet: the ImageNet protocol scaled 1/25 (BASELINE.md #4/#5)
        — the shared 50k synthetic JPEG tree via ImageFolderDataset +
        native decode, SSLResNet50, MarginSampler, budget 2000.

    The model precision is whatever the production path resolves
    ("auto" => bf16 on TPU), NOT a bench-only override — this phase
    exists to measure the loop users actually run."""
    import shutil
    import tempfile

    import jax
    from active_learning_tpu.config import ExperimentConfig
    from active_learning_tpu.experiment.arg_pools import get_train_config
    from active_learning_tpu.experiment.driver import run_experiment
    from active_learning_tpu.utils.metrics import MetricsSink

    class CaptureSink(MetricsSink):
        def __init__(self):
            self.metrics = []  # (name, value, step)

        def log_parameters(self, params):
            pass

        def log_metrics(self, metrics, step=None):
            for k, v in metrics.items():
                self.metrics.append((k, float(v), step))

        def log_asset(self, name, data):
            pass

    # Smoke scale (CI / CPU): shrunk so the phase's full code path —
    # driver, sink capture, both dataset kinds — runs on a single CPU
    # core.  ImageNet smoke is far smaller than CIFAR smoke because every
    # forward is ResNet-50 at 224px (~3-5 img/s on one core).
    smoke = os.environ.get("AL_BENCH_ROUND_SMOKE") == "1"
    if smoke:
        pool_n, test_n = (2000, 500) if config == "cifar" else (320, 96)
    else:
        pool_n, test_n = 50000, 10000
    if config == "cifar":
        from active_learning_tpu.data.synthetic import get_data_synthetic
        data = get_data_synthetic(n_train=pool_n, n_test=test_n)
        train_cfg = get_train_config("default", "cifar10")
        dataset, model_name = "cifar10", "SSLResNet18"
        budget = 40 if smoke else 1000
    else:
        from active_learning_tpu.data.core import IMAGENET_NORM, ViewSpec
        from active_learning_tpu.data.imagenet import ImageFolderDataset
        root = os.path.join(tempfile.gettempdir(), "al_tpu_datapath")
        _ensure_jpeg_tree(root, pool_n)
        train_view = ViewSpec(IMAGENET_NORM, augment=True, pad=0)
        val_view = ViewSpec(IMAGENET_NORM, augment=False)
        train_set = ImageFolderDataset(root, train_view, True, limit=pool_n)
        al_set = ImageFolderDataset(root, val_view, False, limit=pool_n)
        test_set = ImageFolderDataset(root, val_view, False,
                                      limit=min(5000, test_n))
        data = (train_set, test_set, al_set)
        train_cfg = get_train_config("default", "imagenet")
        dataset, model_name = "imagenet", "SSLResNet50"
        budget = 16 if smoke else 2000

    tmp = tempfile.mkdtemp(prefix="al_bench_round_")
    sink = CaptureSink()
    # The decoded-pool cache lives inside this phase's tmp dir (deleted on
    # exit): round 0 must pay real JPEG decode every bench invocation —
    # the driver's persistent default dir would make later runs' "cold"
    # round silently warm.
    import dataclasses
    train_cfg = dataclasses.replace(
        train_cfg, decoded_cache_dir=os.path.join(tmp, "decoded"))
    device_kind = jax.devices()[0].device_kind
    n_chips = len(jax.devices())
    # The pipelined round (DESIGN.md §8) needs a WARM arming round to
    # measure: the last round never arms (no next query to speculate
    # for), so a 2-round run only overlaps inside the cold compile-laden
    # round 0.  Where --round_pipeline auto resolves speculative
    # (single-process multi-device), run 3 rounds: round 1 is THE warm
    # pipelined round — it consumes round 0's speculation, arms round
    # 2's, and its overlap_frac from the driver's own telemetry is the
    # phase's acceptance gate.
    pipelined = jax.process_count() == 1 and n_chips > 1
    n_rounds = 3 if pipelined else 2
    cfg = ExperimentConfig(
        dataset=dataset, strategy="MarginSampler", rounds=n_rounds,
        round_budget=budget, init_pool_size=0, model=model_name,
        n_epoch=epochs, early_stop_patience=epochs, enable_metrics=True,
        log_dir=tmp, ckpt_path=tmp, exp_hash="bench")
    # The production driver enables the persistent XLA compilation cache
    # (experiment/driver.py:enable_compilation_cache): whether its
    # default dir already holds entries decides if this run's "cold"
    # round 0 pays real compiles or warm disk hits — recorded so the
    # cold-warm compile-tax gap is attributable across bench rounds.
    # The driver gates the DEFAULT cache off on CPU (donated-buffer
    # corruption in cache-deserialized executables); mirror that gate so
    # a CPU smoke run with a leftover non-empty dir is not misreported
    # as cache-warm while the child actually ran uncached.
    from active_learning_tpu.experiment.driver import _platform_is_cpu
    xla_cache_dir = (os.environ.get("JAX_COMPILATION_CACHE_DIR")
                     or os.path.join(os.path.expanduser("~"), ".cache",
                                     "al_tpu_xla_cache"))
    cache_enabled = bool(os.environ.get("JAX_COMPILATION_CACHE_DIR")
                         or not _platform_is_cpu())
    cache_prewarmed = bool(cache_enabled and os.path.isdir(xla_cache_dir)
                           and os.listdir(xla_cache_dir))
    log(f"[al_round_{config}] {model_name} x{n_chips} {device_kind}, "
        f"budget {budget}, {epochs} epochs, 2 rounds "
        f"(compile cache {'warm' if cache_prewarmed else 'cold'})")
    t0 = time.perf_counter()
    try:
        strategy = run_experiment(cfg, sink=sink, data=data,
                                  train_cfg=train_cfg)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    total_sec = time.perf_counter() - t0
    # Residency attribution: whether the pool actually pinned in HBM
    # (auto-sized budget) or the query streamed through the async
    # double-buffered prefetch fallback — the phase's query_time is
    # meaningless without knowing which feed path produced it.
    pinned = len((strategy.trainer.resident_pool or {}).get("images", {}))
    residency = {
        "mode": "resident" if pinned else "prefetch",
        "pinned_arrays": pinned,
        "resident_budget_bytes": int(strategy.trainer.resident_budget),
        "budget_source": ("auto"
                          if train_cfg.resident_scoring_bytes is None
                          else "explicit"),
    }

    def phase_sec(name, rd):
        for k, v, step in sink.metrics:
            if k == f"rd_{name}" and step == rd:
                return round(v, 2)
        return None

    def step_pct(name):
        # The driver's per-epoch telemetry (trainer._emit_epoch_telemetry)
        # on the WARM round only: its step axis is round*(epochs+1)+epoch,
        # so round 1 is strictly past epochs+1.  Median over the round's
        # epochs — one number per phase for the bench line.
        vals = sorted(v for k, v, s in sink.metrics
                      if k == name and s is not None and s > epochs + 1)
        return round(vals[len(vals) // 2], 3) if vals else None

    names = ("query_time", "init_network_weights_time", "train_time",
             "load_best_ckpt_time", "test_time")
    rounds = {
        f"round{rd}": {n: phase_sec(n, rd) for n in names}
        for rd in range(n_rounds)
    }
    warm = sum(v for v in rounds["round1"].values() if v)
    cold = sum(v for v in rounds["round0"].values() if v)
    # Warm-round training throughput: round 1 trains on 2*budget labeled
    # rows for `epochs` epochs (init_pool_size=0: round 0 labeled the
    # first `budget`).
    # A missing round-1 train time yields ips None, never NaN: json would
    # serialize NaN as a non-standard token strict parsers reject.
    train_sec = rounds["round1"]["train_time"]
    ips = (2 * budget * epochs / train_sec) if train_sec else None
    test_acc = next((v for k, v, s in sink.metrics
                     if k == "rd_test_accuracy" and s == 1), None)

    def round_metric(name, rd):
        return next((v for k, v, s in sink.metrics
                     if k == name and s == rd), None)

    def run_total(name):
        # The driver emits the failure-model counters CUMULATIVELY at
        # each round boundary: the run total is the largest value seen.
        vals = [v for k, v, s in sink.metrics if k == name]
        return max(vals) if vals else None

    # The pipelined round's proof-of-overlap numbers, from the DRIVER'S
    # own telemetry stream (experiment/driver._emit_overlap_telemetry —
    # bench never times the loop a second time): the warm arming round's
    # overlap_frac is 1 − round_wall / (Σ phase walls + speculative-
    # scorer busy), and round_vs_max_phase is round_wall / max(stream) —
    # 1.0 would mean the round costs exactly its longest stream.
    # Keyed off the driver's ACTUAL resolution (strategy.pipeline), not
    # the n_rounds prediction above: if the auto rule ever drifts from
    # the prediction, the worst case is a missing overlap field — never
    # a spurious gate failure.
    pipeline_mode = ("speculative" if strategy.pipeline is not None
                     else "off")
    warm_rd = 1 if (pipeline_mode == "speculative"
                    and n_rounds >= 3) else None
    overlap = (round_metric("overlap_frac", warm_rd)
               if warm_rd is not None else None)
    vs_max = (round_metric("round_vs_max_phase", warm_rd)
              if warm_rd is not None else None)
    spec_hit = (round_metric("spec_hit_frac", warm_rd)
                if warm_rd is not None else None)
    if warm_rd is not None and not smoke and n_chips >= 2:
        # The acceptance gate (ISSUE 7): a warm pipelined round must
        # complete in <= 0.85x its serial-equivalent wall — which is
        # exactly overlap_frac >= 0.15.  Smoke scale is exempt (the
        # tiny fit ends before the scorer can overlap anything).
        assert overlap is not None and overlap >= 0.15, (
            f"warm pipelined round overlapped only "
            f"{overlap if overlap is not None else 'nothing'} of its "
            f"serial-equivalent work on {n_chips} devices (need >= 0.15 "
            f"== round <= 0.85x sequential)")
    return {
        "phase": f"al_round_{config}",
        "ips": round(ips, 1) if ips is not None else None,
        "ips_per_chip": (round(ips / n_chips, 1) if ips is not None
                         else None),
        "unit": "train images/sec (in-loop)",
        "n_chips": n_chips,
        "budget": budget,
        "epochs": epochs,
        "pool_n": pool_n,
        "round_sec_warm": round(warm, 2),
        "round_sec_cold": round(cold, 2),
        # The per-run compile tax: everything round 0 pays that round 1
        # does not (XLA compiles dominate it).  The persistent compile
        # cache + shape bucketing exist to shrink this gap.
        "compile_tax_sec": round(cold - warm, 2),
        "compile_cache_enabled": cache_enabled,
        "compile_cache_prewarmed": cache_prewarmed,
        # Warm-round step-time percentiles from the driver's own
        # per-epoch telemetry stream (the run-wide telemetry subsystem
        # measuring a real driver loop, not a bench-only timer).
        "step_time_ms_p50": step_pct("step_time_ms_p50"),
        "step_time_ms_p99": step_pct("step_time_ms_p99"),
        # Which leg of the train-feed hierarchy the production fit
        # resolved (trainer.last_feed), and the warm-round median
        # fraction of each epoch's train wall spent blocked on the host
        # feed — "done" for the feed work is feed_stall_frac <= 0.1 with
        # the resident feed on live hardware.
        "feed_source": strategy.trainer.last_feed.get("source"),
        "feed_stall_frac": step_pct("feed_stall_frac"),
        "host_wait_ms_p50": step_pct("host_wait_ms_p50"),
        # The pipelined round (DESIGN.md §8): which mode the driver
        # resolved, and the warm arming round's overlap evidence (None
        # when the mesh runs sequential — nothing was overlapped).
        "round_pipeline": pipeline_mode,
        "overlap_frac": overlap,
        "round_vs_max_phase": vs_max,
        "spec_hit_frac": spec_hit,
        # The experiment-truth rider (DESIGN.md §13): round 1's
        # score-distribution drift vs round 0 from the driver's own
        # diagnostics stream — an end-to-end round capture now records
        # whether the acquisition distribution moved while it was being
        # timed (None when diagnostics were off or round 0 never
        # scored).
        "rd_score_drift_psi": round_metric("rd_score_drift_psi", 1),
        "rd_score_drift_js": round_metric("rd_score_drift_js", 1),
        # The failure model's self-healing counters (DESIGN.md §10),
        # from the same driver stream: site-level retries absorbed and
        # degradation-ladder escalations taken during the measured
        # rounds — an end-to-end wall-clock claim is dishonest if the
        # run quietly self-healed mid-measurement.
        "fault_retries_total": run_total("fault_retries_total"),
        "degrade_events": run_total("degrade_events"),
        # The pod-tier column-feed rider (DESIGN.md §15): whether the
        # measured rounds' k-center scans fed their initial-min/minimax
        # columns over the ring-permute feed (the row-sharded backend's
        # only column feed) — None when the strategy never ran a
        # k-center selection.
        "ring_feed": _last_ring_feed(),
        "total_sec": round(total_sec, 1),
        "residency": residency,
        **_model_config_fields(strategy.model),
        "phases_sec": rounds,
        "test_accuracy_rd1": test_acc,
        "device_kind": device_kind,
        "platform": jax.devices()[0].platform,
    }


def run_disk_pool_feed_phase(epochs: int) -> dict:
    """The disk tier measured (DESIGN.md §16): the SAME 2-round AL
    experiment through the production driver twice — once on the
    in-memory pool backend, once on the demand-paged disk backend with
    the pool held at >= 4x both residency budgets (HBM pin AND host
    block cache) — asserting the backends pick the SAME rows and land
    the SAME accuracy (the tier's bit-identity contract), and recording
    what the paging actually cost: the disk leg's in-loop train rate,
    its warm-round block-cache hit fraction, page-in throughput, and
    the gather-observed stall percentiles, all from the driver's own
    PAGING_GAUGES telemetry stream (bench never times the pager
    itself).

    The pool is the CIFAR protocol shape (synthetic, so the phase is
    data-path-pure): 50k rows at 32px f32 = ~614 MB, budgets capped at
    a quarter of that.  Absolute RAM is modest — the phase's subject is
    the PAGING MACHINERY at a pinned pool:budget ratio, not exhausting
    this host's DIMMs."""
    import dataclasses
    import shutil
    import tempfile

    import jax
    import numpy as np
    from active_learning_tpu.config import ExperimentConfig
    from active_learning_tpu.data.synthetic import get_data_synthetic
    from active_learning_tpu.experiment.arg_pools import get_train_config
    from active_learning_tpu.experiment.driver import run_experiment
    from active_learning_tpu.utils.metrics import MetricsSink

    class CaptureSink(MetricsSink):
        def __init__(self):
            self.metrics = []  # (name, value, step)

        def log_parameters(self, params):
            pass

        def log_metrics(self, metrics, step=None):
            for k, v in metrics.items():
                self.metrics.append((k, float(v), step))

        def log_asset(self, name, data):
            pass

    smoke = os.environ.get("AL_BENCH_ROUND_SMOKE") == "1"
    if smoke:
        pool_n, test_n, budget, page_rows = 2000, 500, 40, 256
    else:
        pool_n, test_n, budget, page_rows = 50000, 10000, 1000, 2048
    pool_bytes = pool_n * 32 * 32 * 3 * 4  # f32 rows, CIFAR shape
    # BOTH residency tiers capped at a quarter of the pool: the HBM pin
    # (resident_scoring_bytes) and the host block cache — a disk leg
    # that could cache the whole pool would measure the memory backend
    # with extra steps.
    budget_bytes = pool_bytes // 4
    train_cfg = dataclasses.replace(
        get_train_config("default", "cifar10"),
        resident_scoring_bytes=budget_bytes,
        pool_host_cache_bytes=budget_bytes,
        pool_page_rows=page_rows)
    device_kind = jax.devices()[0].device_kind
    n_chips = len(jax.devices())
    log(f"[disk_pool_feed] {n_chips}x {device_kind}, pool {pool_n} rows "
        f"({pool_bytes / 1e6:.0f} MB) at 4.0x the "
        f"{budget_bytes / 1e6:.0f} MB residency budget, budget {budget}, "
        f"{epochs} epochs, 2 rounds per leg")

    def leg(backend):
        # Fresh data per leg from the SAME seed: bit-identity must hold
        # over identical inputs, and the driver absorbs labels into the
        # datasets it is handed.
        data = get_data_synthetic(n_train=pool_n, n_test=test_n)
        tmp = tempfile.mkdtemp(prefix=f"al_bench_diskfeed_{backend}_")
        sink = CaptureSink()
        cfg = ExperimentConfig(
            dataset="cifar10", strategy="MarginSampler", rounds=2,
            round_budget=budget, init_pool_size=0, model="SSLResNet18",
            n_epoch=epochs, early_stop_patience=epochs,
            enable_metrics=True, run_seed=17, pool_backend=backend,
            log_dir=tmp, ckpt_path=tmp, exp_hash="bench")
        t0 = time.perf_counter()
        try:
            strategy = run_experiment(cfg, sink=sink, data=data,
                                      train_cfg=train_cfg)
            return {
                "backend": backend,
                "labeled": np.array(strategy.pool.labeled, copy=True),
                "acc": strategy.last_test_acc,
                "sink": sink,
                "al_set_kind": type(strategy.al_set).__name__,
                "total_sec": time.perf_counter() - t0,
            }
        finally:
            shutil.rmtree(tmp, ignore_errors=True)

    mem = leg("memory")
    disk = leg("disk")

    def gauge(run, name, rd):
        return next((v for k, v, s in run["sink"].metrics
                     if k == name and s == rd), None)

    # The tier's whole contract, asserted where the numbers are minted:
    # a disk-leg rate for DIFFERENT picks would be a benchmark of a
    # different experiment.
    assert disk["al_set_kind"] == "DiskPool", (
        f"--pool_backend disk resolved to {disk['al_set_kind']} — the "
        "leg never left host memory, so there is nothing to measure")
    assert np.array_equal(mem["labeled"], disk["labeled"]), (
        "disk backend picked different rows than memory — the paging "
        "tier broke bit-identity (DESIGN.md §16)")
    assert mem["acc"] == disk["acc"], (
        f"accuracy diverged across backends: memory {mem['acc']} vs "
        f"disk {disk['acc']} over identical picks")
    disk_rows = gauge(disk, "pool_disk_rows", 1)
    assert disk_rows, ("the disk leg emitted no paging telemetry — "
                       "PAGING_GAUGES never saw a disk-backed round")

    def ips_of(run):
        # Round 1 trains on 2*budget labeled rows (init_pool_size=0).
        train_sec = gauge(run, "rd_train_time", 1)
        return (2 * budget * epochs / train_sec) if train_sec else None

    ips, ips_mem = ips_of(disk), ips_of(mem)
    return {
        "phase": "disk_pool_feed",
        "ips": round(ips, 1) if ips is not None else None,
        "ips_per_chip": (round(ips / n_chips, 1) if ips is not None
                         else None),
        "unit": "train images/sec (disk-backed pool)",
        "n_chips": n_chips,
        "pool_n": pool_n,
        "budget": budget,
        "epochs": epochs,
        "pool_bytes": pool_bytes,
        "resident_budget_bytes": budget_bytes,
        "pool_over_budget_x": round(pool_bytes / budget_bytes, 1),
        # The paging tax, directly: the same fit on the same picks under
        # the in-memory backend — vs_mem < 1 is what the disk tier costs.
        "ips_memory": (round(ips_mem, 1) if ips_mem is not None
                       else None),
        "disk_vs_memory": (round(ips / ips_mem, 3)
                           if ips and ips_mem else None),
        # Warm-round paging evidence from the driver's PAGING_GAUGES.
        "cache_hit_frac": gauge(disk, "pool_cache_hit_frac", 1),
        "page_in_rows_per_sec": gauge(disk, "page_in_rows_per_sec", 1),
        "page_stall_ms_p50": gauge(disk, "page_in_stall_ms_p50", 1),
        "page_stall_ms_p99": gauge(disk, "page_in_stall_ms_p99", 1),
        "pool_disk_rows": disk_rows,
        "picks_identical": True,  # asserted above; recorded as evidence
        "test_accuracy_rd1": gauge(disk, "rd_test_accuracy", 1),
        "total_sec": round(mem["total_sec"] + disk["total_sec"], 1),
        "device_kind": device_kind,
        "platform": jax.devices()[0].platform,
    }


def run_fleet_smoke_phase(rounds: int) -> dict:
    """The fleet tier end to end at bench scale (DESIGN.md §17): a
    2-run sweep (Margin vs Random) on two localhost worker slots
    through the REAL controller — spec expansion, journal, packing,
    health polling, the CLI child launch path — with one child
    SIGKILL'd after its round-0 checkpoint.  The controller must
    re-queue it with ``--resume_training`` and the fleet must finish
    with every run accounted; the phase records the resume/preemption
    counters and the merged-scrape coverage as evidence.  The children
    are the tests/fleet_child.py harness (the production driver behind
    the production CLI flags, at TinyClassifier/synthetic-pool size) on
    the CPU backend — the controller never touches an accelerator
    (al_lint fleet-host-pure), so the scheduling claim is
    backend-independent and this phase never competes for the tunnel."""
    import shutil
    import tempfile
    import threading

    from active_learning_tpu.fleet import (FLEET_JOURNAL_FILE,
                                           FleetController, Worker,
                                           read_fleet_journal)
    from active_learning_tpu.fleet import report as fleet_report
    from active_learning_tpu.telemetry import heartbeat as hb_lib

    child = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "tests", "fleet_child.py")
    rounds = max(2, int(rounds))  # the kill waits for a round-0 ckpt
    spec = {
        "name": "bench_fleet_smoke",
        "defaults": {
            "dataset": "synthetic", "arg_pool": "synthetic",
            "rounds": rounds, "round_budget": 8, "n_epoch": 3,
            "early_stop_patience": 3, "round_pipeline": "speculative",
            "heartbeat_every_s": 0.0, "run_seed": 0,
        },
        "grid": {"strategy": ["MarginSampler", "RandomSampler"]},
    }
    fleet_dir = tempfile.mkdtemp(prefix="al_bench_fleet_")
    cpu_env = {"JAX_PLATFORMS": "cpu"}
    ctrl = FleetController(
        fleet_dir, spec,
        [Worker("w0", env=cpu_env), Worker("w1", env=cpu_env)],
        base_cmd=[sys.executable, child], poll_every_s=0.2)
    log(f"[fleet_smoke] 2 runs x {rounds} rounds on 2 workers "
        f"(children: {os.path.basename(child)})")
    t0 = time.perf_counter()
    thread = threading.Thread(target=ctrl.run, daemon=True)
    thread.start()
    # Preempt one worker the moment its run has a checkpoint to resume
    # from: heartbeat round >= 1 means round 0 committed.
    journal_path = os.path.join(fleet_dir, FLEET_JOURNAL_FILE)
    killed = None
    deadline = time.monotonic() + 420
    while killed is None and thread.is_alive() \
            and time.monotonic() < deadline:
        journal = read_fleet_journal(journal_path) or {}
        for rid, rec in (journal.get("runs") or {}).items():
            if rec.get("state") != "running" or not rec.get("pid"):
                continue
            hb = hb_lib.read_heartbeat(os.path.join(
                fleet_dir, "runs", rid, "logs", "heartbeat.json")) or {}
            if (hb.get("round") or 0) >= 1 and hb.get("status") == "running":
                try:
                    os.kill(rec["pid"], signal.SIGKILL)
                except OSError:
                    continue
                killed = rid
                log(f"[fleet_smoke] SIGKILL'd {rid} (pid {rec['pid']}) "
                    f"at round {hb.get('round')}")
                break
        time.sleep(0.05)
    thread.join(timeout=480)
    total_sec = time.perf_counter() - t0
    if thread.is_alive():
        ctrl.stop()
        thread.join(timeout=60)
        raise RuntimeError("fleet_smoke: controller never converged")
    if killed is None:
        raise RuntimeError("fleet_smoke: no run ever reached round 1 — "
                           "the preemption was never injected")
    counts = ctrl.counts()
    resumes = sum(r["resumes"] for r in ctrl.runs.values())
    attempts = sum(r["attempts"] for r in ctrl.runs.values())
    if counts["finished"] != 2:
        raise RuntimeError(f"fleet_smoke: fleet ended {counts}")
    if resumes < 1:
        raise RuntimeError("fleet_smoke: the SIGKILL'd run was not "
                           "resumed from its checkpoint")
    _, merged = fleet_report.merge_prom(fleet_dir)
    payload = fleet_report.fleet_payload(fleet_dir)
    shutil.rmtree(fleet_dir, ignore_errors=True)
    return {
        "phase": "fleet_smoke",
        # Headline: fleet throughput (a scheduling rate, not a device
        # rate — the controller is host-pure).
        "ips": round(60.0 * counts["finished"] / total_sec, 2),
        "ips_per_chip": round(60.0 * counts["finished"] / total_sec, 2),
        "unit": "runs finished/min (2-worker localhost fleet)",
        "runs_finished": counts["finished"],
        "runs_failed": counts["failed"],
        "runs_resumed": resumes,
        "attempts_total": attempts,
        "killed_run": killed,
        "merged_prom_runs": merged,
        "comparison_rendered": payload.get("comparison") is not None,
        "total_sec": round(total_sec, 1),
        "workers": 2,
    }


def _phase_setup(config: str, batch_size: int):
    """Shared model/trainer/batch construction for the timing child and
    the CPU FLOPs child: the batch schema and step signatures live in ONE
    place so the two paths cannot drift.  ``batch_size`` is the GLOBAL
    batch over the current backend's mesh."""
    import numpy as np

    import jax
    from active_learning_tpu.config import LoaderConfig, TrainConfig
    from active_learning_tpu.parallel import mesh as mesh_lib
    from active_learning_tpu.train.trainer import Trainer

    mesh = mesh_lib.make_mesh(-1)
    model, px, n_classes, train_view, score_view = _model_and_views(config)
    cfg = TrainConfig(loader_tr=LoaderConfig(batch_size=batch_size))
    trainer = Trainer(model, cfg, mesh, num_classes=n_classes, train_bn=True)
    rng = np.random.default_rng(0)
    host_batch = {
        "image": rng.integers(0, 256, size=(batch_size, px, px, 3),
                              dtype=np.uint8),
        "label": rng.integers(0, n_classes,
                              size=batch_size).astype(np.int32),
        "index": np.arange(batch_size, dtype=np.int32),
        "mask": np.ones(batch_size, dtype=np.float32),
    }
    batch = mesh_lib.shard_batch(host_batch, mesh)
    state = trainer.init_state(jax.random.PRNGKey(0),
                               host_batch["image"][:min(8, batch_size)])
    return (mesh, model, n_classes, train_view, score_view, trainer, batch,
            state)


def run_flops_cpu(phase: str, batch_size: int) -> dict:
    """Per-image FLOPs of a phase's step, lowered on the CPU backend.

    The tunneled TPU backend does not expose ``cost_analysis`` reliably,
    but the FLOP count is a property of the computation, not the device —
    lowering the identical step on CPU (run with JAX_PLATFORMS=cpu) gives
    the same number, and the parent combines it with the TPU-measured
    images/sec to report achieved TFLOP/s and MFU."""
    import jax
    import jax.numpy as jnp

    config, kind = phase.rsplit("_", 1)
    (mesh, model, n_classes, train_view, score_view, trainer, batch,
     state) = _phase_setup(config, batch_size)
    if kind == "train":
        flops = _flops_per_step(
            trainer._train_step, phase, state, batch, jax.random.PRNGKey(1),
            jnp.float32(0.1), jnp.ones(n_classes, jnp.float32),
            view=train_view)
    else:
        from active_learning_tpu.strategies import scoring
        sstep = scoring.make_prob_stats_step(model, score_view)
        flops = _flops_per_step(sstep, phase,
                                state.variables,
                                {"image": batch["image"],
                                 "mask": batch["mask"]})
    n_local = int(mesh.devices.size)
    return {"phase": phase, "flops_source": "cpu-lowering",
            # cost_analysis reports the per-device partitioned module, so
            # divide by the rows one device saw.
            "flops_per_image": (flops * n_local / batch_size
                                if flops else None)}


def _flops_per_step(jitted, phase: str, *args, **kwargs):
    """Per-device flops of one step via AOT lower/compile.  This is a
    SECOND full XLA compile (it does not reuse the jit cache), so callers
    emit their timing result BEFORE calling this — a backend that dies or
    crawls inside the optional compile must not take a completed
    measurement down with it."""
    try:
        cost = jitted.lower(*args, **kwargs).compile().cost_analysis()
        if isinstance(cost, list):
            cost = cost[0]
        return float(cost.get("flops", 0.0)) or None
    except Exception as e:
        log(f"[{phase}] cost analysis unavailable: {e!r}")
        return None


def _time_loop(step_once, sync, iters: int, warmup: int = 3,
               step_times=None) -> float:
    """The ONE timing discipline for every measured step — primary and
    alt-batch, train and score: ``warmup`` untimed iterations, a
    data-dependent host fetch (``sync``) so the device really finished,
    then ``iters`` timed iterations closed by the same fetch
    (block_until_ready can return early on remote-execution backends;
    host fetches cannot).  ``step_times`` (a list) collects the per-
    iteration host deltas for the step-time percentiles — see
    _step_percentiles for when those deltas are trustworthy."""
    for _ in range(warmup):
        step_once()
    sync()
    t0 = time.perf_counter()
    prev = t0
    for _ in range(iters):
        step_once()
        if step_times is not None:
            now = time.perf_counter()
            step_times.append(now - prev)
            prev = now
    sync()
    return time.perf_counter() - t0


def _pctile(vals, q: float):
    """Nearest-rank percentile (the serve/metrics + telemetry
    convention, re-spelled here so the bench child stays importable
    without the package)."""
    if not vals:
        return None
    vals = sorted(vals)
    return float(vals[min(len(vals) - 1,
                          max(0, int(round(q * (len(vals) - 1)))))])


def _step_percentiles(result: dict, step_times, dt: float,
                      iters: int) -> None:
    """step_time_ms_p50/p99 onto a phase result.  Host-side per-
    iteration deltas are real step cadence only while the dispatch queue
    backpressures (donated buffers + data-dependent chaining do this in
    steady state); when the host ran far ahead (sum of deltas << the
    synced wall time — fully async backend), percentiles degrade to the
    loop average and say so in step_time_source."""
    if iters <= 0 or dt <= 0:
        return
    if step_times and sum(step_times) >= 0.8 * dt:
        result["step_time_ms_p50"] = round(
            _pctile(step_times, 0.50) * 1000, 3)
        result["step_time_ms_p99"] = round(
            _pctile(step_times, 0.99) * 1000, 3)
        result["step_time_source"] = "host-cadence"
    else:
        result["step_time_ms_p50"] = result["step_time_ms_p99"] = round(
            dt / iters * 1000, 3)
        result["step_time_source"] = "loop-average"


def _train_runner(trainer, batch, state, n_classes, view, seed: int):
    """(step_once, sync, holder) driving one train step per call with ONE
    dispatch per iteration — the PRODUCTION chained step (PRNG split
    folded into the jitted call, trainer._chained_train_step), so the
    bench measures exactly the dispatch pattern the host-batched fit
    loop runs.  The holder chains state/key so the final loss fetch is
    data-dependent on every step."""
    import jax
    import jax.numpy as jnp

    cw = jnp.ones(n_classes, jnp.float32)
    lr = jnp.float32(0.1)
    h = {"state": state, "key": jax.random.PRNGKey(seed), "loss": None}

    def step_once():
        h["state"], h["key"], h["loss"], h["gnorm"] = \
            trainer._chained_train_step(
                h["state"], batch, h["key"], lr, cw, view=view)

    return step_once, (lambda: float(h["loss"])), h


def _grad_path_fields(trainer, holder, batch, n_classes, view,
                      step_sec: float, iters: int) -> dict:
    """The backward-decomposition riders for a train phase (ISSUE 10):
    time a forward-only step and the fused optimizer update alone with
    the SAME timing discipline as the primary loop, and attribute the
    remainder of the measured step to the backward pass —
    ``bwd_frac`` — alongside ``opt_update_ms`` and the gradient-path
    flags (``optim_state_dtype``/``grad_allreduce``/``fused_optimizer``)
    so every train number is attributable to its gradient-path
    configuration.  Short loops (max(4, iters//4)): these are
    decomposition ratios, not headline rates."""
    import functools

    import jax
    import jax.numpy as jnp

    from active_learning_tpu.data.augment import apply_view
    from active_learning_tpu.train.trainer import weighted_cross_entropy

    model = trainer.model
    train_bn = trainer.train_bn
    cw = jnp.ones(n_classes, jnp.float32)
    sub_iters = max(4, iters // 4)
    variables = holder["state"].variables

    @jax.jit
    def fwd_once(variables, batch, key, carry):
        x = apply_view(batch["image"], view, key=key, train=True)
        if train_bn:
            logits, _ = model.apply(variables, x, train=True,
                                    mutable=["batch_stats"])
        else:
            logits = model.apply(variables, x, train=False)
        w = cw[batch["label"]] * batch["mask"]
        return carry + weighted_cross_entropy(logits, batch["label"], w)

    h = {"carry": jnp.float32(0.0), "k": jax.random.PRNGKey(7)}

    def fwd_step():
        h["k"], sub = jax.random.split(h["k"])
        h["carry"] = fwd_once(variables, batch, sub, h["carry"])

    fwd_dt = _time_loop(fwd_step, lambda: float(h["carry"]), sub_iters)
    fields = {
        "optim_state_dtype": getattr(trainer.cfg, "optim_state_dtype",
                                     "f32"),
        "grad_allreduce": trainer.grad_allreduce,
        "fused_optimizer": trainer.fused_tx is not None,
    }
    if trainer.grad_allreduce == "int8":
        # The pod-tier wire riders (DESIGN.md §15): WHICH quantized
        # wire the step synced over (allgather vs the reduce-scatter
        # form) and its per-device per-step wire model MB
        # (mesh_lib.wire_model_bytes — the same table the measured
        # collective_bytes_total cross-check in tests/test_pod_tier.py
        # pins against the optimized HLO).
        from active_learning_tpu.parallel import mesh as _mesh_lib
        form = getattr(trainer, "grad_sync_form", None) or "allgather"
        n_params = sum(int(p.size)
                       for p in jax.tree.leaves(variables["params"]))
        fields["grad_sync"] = form
        fields["grad_wire_mb"] = round(
            _mesh_lib.wire_model_bytes(form, trainer.n_devices,
                                       n_params) / 1e6, 2)
    # The optimizer-update loop times WHICHEVER path the measured step
    # ran — fused single-pass or the optax chain — so bwd_frac never
    # attributes optimizer time to the backward (a fused-on/off A/B
    # must show the win under opt_update_ms, not as a phantom
    # backward-pass change).
    import optax

    fused = trainer.fused_tx
    tx = trainer.tx

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def opt_once(params, opt_state, grads, lr):
        if fused is not None:
            return fused.update(grads, opt_state, params, lr)
        updates, new_state = tx.update(grads, opt_state, params)
        updates = jax.tree.map(lambda u: -lr * u, updates)
        return optax.apply_updates(params, updates), new_state

    params = jax.tree.map(jnp.copy, variables["params"])
    grads = jax.tree.map(lambda p: jnp.full(p.shape, 1e-4, p.dtype),
                         params)
    oh = {"p": params,
          "o": fused.init(params) if fused is not None
          else tx.init(params)}

    def opt_step():
        oh["p"], oh["o"] = opt_once(oh["p"], oh["o"], grads,
                                    jnp.float32(0.1))

    def opt_sync():
        return float(jax.tree.leaves(oh["p"])[0].reshape(-1)[0])

    opt_dt = _time_loop(opt_step, opt_sync, sub_iters)
    opt_sec = opt_dt / sub_iters
    fields["opt_update_ms"] = round(opt_sec * 1000.0, 3)
    fwd_sec = fwd_dt / sub_iters
    if step_sec > 0:
        fields["bwd_frac"] = round(
            max(0.0, (step_sec - fwd_sec - opt_sec) / step_sec), 3)
    return fields


def _score_runner(model, score_view, variables, batch):
    """(step_once, sync, sstep, sbatch) for the scoring pass.  A scalar is
    chained through every iteration INSIDE one jitted call so the final
    host fetch is data-dependent on all of them with exactly one dispatch
    per iteration — per-iteration eager ops (indexing + add) each cost a
    full round-trip on a tunneled remote backend and can dwarf the
    compute being measured."""
    import jax
    import jax.numpy as jnp
    from active_learning_tpu.strategies import scoring

    sbatch = {"image": batch["image"], "mask": batch["mask"]}
    sstep = scoring.make_prob_stats_step(model, score_view)

    @jax.jit
    def chained(variables, batch, carry):
        return carry + sstep(variables, batch)["margin"][0]

    h = {"carry": jnp.float32(0.0)}

    def step_once():
        h["carry"] = chained(variables, sbatch, h["carry"])

    return step_once, (lambda: float(h["carry"])), sstep, sbatch


def run_child_phase(phase: str, iters: int, per_chip: int):
    """Yields the phase result dict, then — for train/score phases — the
    same result enriched with flops/MFU.  The caller prints each as its
    own JSON line and the parent keeps the LAST parseable one, so the
    enrichment compile is strictly best-effort."""
    import jax
    import jax.numpy as jnp

    if phase == "imagenet_datapath":
        yield from run_datapath_phase(iters * 1000, per_chip)
        return
    if phase == "imagenet_train_feed":
        yield from run_train_feed_phase(iters, per_chip)
        return
    if phase.startswith("al_round_"):
        yield run_al_round_phase(phase[len("al_round_"):], iters)
        return
    if phase == "kcenter_select":
        result, _picks = run_kcenter_phase(iters)
        yield result
        return
    if phase == "kcenter_select_130k":
        # Paper scale, production path (batched greedy + auto dispatch —
        # the backend chosen rides in "backend"); the forced-backend A/B
        # question is answered at 50k, so no second run here.
        result, _ = run_kcenter_phase(iters, pool_n=130000)
        result["phase"] = phase
        yield result
        return
    if phase == "kcenter_select_maxn":
        yield from run_kcenter_maxn_phase(iters)
        return
    if phase == "vaal_cotrain":
        yield run_vaal_phase(iters, per_chip)
        return
    if phase == "serve_throughput":
        yield run_serve_phase(iters, per_chip)
        return
    if phase == "stream_round":
        yield run_stream_phase(iters, per_chip)
        return
    if phase == "disk_pool_feed":
        yield run_disk_pool_feed_phase(iters)
        return
    if phase == "fleet_smoke":
        yield run_fleet_smoke_phase(iters)
        return
    config, kind = phase.rsplit("_", 1)
    n_chips = len(jax.devices())
    batch_size = per_chip * n_chips
    device_kind = jax.devices()[0].device_kind
    log(f"[{phase}] {n_chips}x {device_kind}, batch {batch_size} "
        f"({per_chip}/chip), {iters} iters")

    (mesh, model, n_classes, train_view, score_view, trainer, batch,
     state) = _phase_setup(config, batch_size)

    if kind == "train":
        step_once, sync, holder = _train_runner(trainer, batch, state,
                                                n_classes, train_view, 1)

        def flops_fn():
            return _flops_per_step(
                trainer._train_step, phase, holder["state"], batch,
                holder["key"], jnp.float32(0.1),
                jnp.ones(n_classes, jnp.float32), view=train_view)
    else:
        variables = state.variables
        step_once, sync, sstep, sbatch = _score_runner(
            model, score_view, variables, batch)

        def flops_fn():
            return _flops_per_step(sstep, phase, variables, sbatch)

    profile_dir = os.environ.get("AL_BENCH_PROFILE_DIR")
    device_truth = None
    if profile_dir:
        # XLA trace of the measured loop (VERDICT r3 #4, train AND score
        # MFU) through the gated capture API — telemetry/profiler.py is
        # the ONLY module allowed to touch jax.profiler (trace_lint
        # check 10).  Warmup runs outside the trace so the capture is
        # steady-state steps only.  Trace collection adds overhead to
        # the timed loop, so the result is tagged "profiled" and the
        # parent keeps it OUT of the cross-round cache.
        from active_learning_tpu.telemetry import profiler as prof_lib

        _time_loop(step_once, sync, 0, warmup=3)
        with prof_lib.capture_window(os.path.join(profile_dir, phase),
                                     label=phase) as cap:
            step_times = []
            dt = _time_loop(step_once, sync, iters, warmup=0,
                            step_times=step_times)
        log(f"[{phase}] profiler trace written to "
            f"{os.path.join(profile_dir, phase)}")
        try:
            # Device-truth riders on the profiled result (best-effort:
            # the capture is evidence, never a phase failure): what
            # share of the window the device was actually busy, and how
            # much of its op time was collectives.
            trace_path = prof_lib.find_trace_file(cap.out_dir)
            if trace_path:
                device_truth = prof_lib.summarize_capture(
                    prof_lib.parse_trace(trace_path), cap.window_s)
        except Exception as e:  # noqa: BLE001 - riders only
            log(f"[{phase}] device-truth summary unavailable: {e!r}")
    else:
        step_times = []
        dt = _time_loop(step_once, sync, iters, step_times=step_times)

    ips = batch_size * iters / dt
    result = {
        "phase": phase,
        "ips": round(ips, 1),
        "ips_per_chip": round(ips / n_chips, 1),
        "n_chips": n_chips,
        "batch_per_chip": per_chip,
        "iters": iters,
        "device_kind": device_kind,
        "platform": jax.devices()[0].platform,
        **_model_config_fields(model),
    }
    if kind == "train":
        # Feed attribution: the timed loop steps over ONE pre-sharded
        # HBM-resident batch — the feed is device-resident by
        # construction, and zero wall-clock in the loop is host-feed
        # stall.  The imagenet_train_feed phase is where the hierarchy's
        # legs are actually compared.
        result["feed_source"] = "resident"
        result["feed_stall_frac"] = 0.0
    _step_percentiles(result, step_times, dt, iters)
    if profile_dir:
        result["profiled"] = True  # trace overhead in dt: never cached
        if device_truth:
            for key in ("device_busy_frac", "collective_frac",
                        "transfer_frac", "collective_bytes_total"):
                if device_truth.get(key) is not None:
                    result[key] = device_truth[key]
    yield dict(result)  # the measurement is safe with the parent now

    if kind == "train":
        # Backward decomposition riders (best-effort AFTER the primary
        # number is safe): bwd_frac / opt_update_ms + the gradient-path
        # flags, from short fwd-only and optimizer-only loops under the
        # same timing discipline.
        try:
            result.update(_grad_path_fields(
                trainer, holder, batch, n_classes, train_view,
                dt / iters, iters))
            log(f"[{phase}] bwd_frac={result.get('bwd_frac')} "
                f"opt_update_ms={result.get('opt_update_ms')} "
                f"grad_allreduce={result.get('grad_allreduce')}")
            yield dict(result)
        except Exception as e:
            log(f"[{phase}] backward decomposition unavailable: {e!r}")

    if jax.devices()[0].platform == "tpu":
        # Batch-size lever for the MFU question (VERDICT r3 #4: train MFU
        # 32% vs 39% scoring, CIFAR scoring 26%): measure the same step at
        # 2x per-chip batch.  Kept separate from the primary number so the
        # series stays comparable across rounds.
        try:
            alt_pc = per_chip * 2
            (_m2, model2, n_cls2, tv2, sv2, trainer2, batch2,
             state2) = _phase_setup(config, alt_pc * n_chips)
            alt_iters = max(10, iters // 2)
            if kind == "train":
                alt_once, alt_sync, _h2 = _train_runner(
                    trainer2, batch2, state2, n_cls2, tv2, 2)
            else:
                alt_once, alt_sync, _s2, _b2 = _score_runner(
                    model2, sv2, state2.variables, batch2)
            alt_dt = _time_loop(alt_once, alt_sync, alt_iters)
            result["alt_batch_per_chip"] = alt_pc
            result["alt_ips_per_chip"] = round(
                alt_pc * alt_iters / alt_dt, 1)
            log(f"[{phase}] batch {alt_pc}/chip: "
                f"{result['alt_ips_per_chip']:,.0f} img/s/chip "
                f"(vs {result['ips_per_chip']:,.0f} at {per_chip})")
            yield dict(result)
        except Exception as e:
            log(f"[{phase}] alt-batch probe failed: {e!r}")

    flops_per_step = flops_fn()
    if flops_per_step:
        # cost_analysis on a jitted SPMD executable reports the PER-DEVICE
        # partitioned module's flops (verified empirically: an 8-way
        # sharded matmul reports 1/8 the single-device figure), so this is
        # per-chip achieved throughput and MFU divides by one chip's peak.
        # Same schema as the CPU-lowering back-fill: per-image flops +
        # flops_source.
        tflops_chip = flops_per_step * iters / dt / 1e12
        result["gflop_per_image"] = round(flops_per_step / per_chip / 1e9,
                                          2)
        result["tflops_per_sec_per_chip"] = round(tflops_chip, 1)
        result["flops_source"] = "device-cost-analysis"
        peak = _peak_tflops(device_kind)
        if peak:
            result["mfu"] = round(tflops_chip / peak, 3)
            result["peak_tflops_per_chip"] = peak
        yield result


# ---------------------------------------------------------------------------
# Parent: orchestrate phases in subprocesses; always print one JSON line.
# ---------------------------------------------------------------------------

def _parse_child_json(stdout: str, required=("ips", "ips_per_chip")):
    """Last stdout line that parses as a dict carrying all ``required``
    keys — stray JSON-ish lines from libraries must not masquerade as a
    phase result."""
    for line in reversed(stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                result = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(result, dict) and all(k in result
                                                for k in required):
                return result
    return None


def _halve_iters(iters: int) -> int:
    """Retry iteration cut that can never INCREASE the work: the floor of
    10 exists for timing stability of per-step phases (iters >= 20), but
    the al_round phases count EPOCHS (2-4) — flooring those at 10 made a
    timed-out attempt's retry strictly longer than the attempt that
    already died (observed: al_round_imagenet 2 epochs -> retry at 10)."""
    return max(10, iters // 2) if iters > 10 else max(1, iters // 2)


def run_phase_with_retries(name: str, iters: int, per_chip: int,
                           timeout: float, deadline: float,
                           max_attempts: int = 2):
    """Capped retry ladder (default 2 attempts — a third attempt against a
    backend that already ate two timeouts is how round 3 burned its whole
    budget on one phase); iters halve per retry, batch halves on OOM.
    The datapath phase gets one extra attempt on the CPU backend: its
    headline metrics (decode imgs/sec, per-core rate) are host-side, so a
    dead accelerator tunnel must not erase them — the result is tagged
    with platform "cpu" by the child itself.
    Returns (result dict | None, failure string | None)."""
    failure = None
    # A partial snapshot from a child that OOM-crashed after printing a
    # completed measurement: kept as a fallback, but the halved-batch
    # retry still runs — the retry may recover the measurements the crash
    # cut short (warm/resident passes), and only if it also fails does
    # the snapshot become the answer.
    stashed = None
    attempts = max_attempts + 1 if name == "imagenet_datapath" else max_attempts
    for attempt in range(attempts):
        cpu_fallback = name == "imagenet_datapath" and attempt == attempts - 1
        remaining = deadline - time.monotonic()
        if remaining <= 30:
            if stashed is not None:
                return stashed, None
            return None, failure or "wall-clock budget exhausted"
        # Reserve ~90s of budget past any single attempt: a hung child
        # granted the full remainder would starve the cached-evidence
        # fallback, MFU back-fill, and the final emit (phase timeouts can
        # legitimately exceed the DEFAULT total budget — al_round_imagenet
        # at 1800s is sized for AL_BENCH_BUDGET_S-raised runs, and under
        # the default it degrades to whatever window this cap grants).
        attempt_timeout = min(timeout if attempt == 0 else timeout * 0.75,
                              max(60.0, remaining - 90.0), remaining)
        cmd = [sys.executable, os.path.abspath(__file__), "--phase", name,
               "--iters", str(iters), "--per-chip-batch", str(per_chip)]
        env = None
        if cpu_fallback:
            # Decode-only: the ResNet-50 scoring pass is pointless on one
            # CPU core and would blow the timeout; the host-side decode
            # rate is the number this fallback exists to save.
            env = dict(os.environ, PALLAS_AXON_POOL_IPS="",
                       JAX_PLATFORMS="cpu",
                       AL_BENCH_DATAPATH_DECODE_ONLY="1")
            log(f"[parent] {name}: accelerator attempts failed; measuring "
                "the host-side data path (decode only) on the CPU backend")
        log(f"[parent] {name} attempt {attempt + 1}: iters={iters} "
            f"batch/chip={per_chip} timeout={attempt_timeout:.0f}s")
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=attempt_timeout, env=env)
        except subprocess.TimeoutExpired as e:
            partial = e.stderr or ""
            if isinstance(partial, bytes):
                partial = partial.decode(errors="replace")
            sys.stderr.write(partial[-2000:])
            # The child prints each completed measurement as its own line
            # BEFORE the optional flops-enrichment compile — a timeout
            # inside the enrichment must not discard a finished number.
            out = e.stdout or ""
            if isinstance(out, bytes):
                out = out.decode(errors="replace")
            result = _parse_child_json(out)
            if result is not None:
                log(f"[parent] {name}: timed out during enrichment; "
                    "keeping the completed measurement")
                return result, None
            failure = f"timeout after {attempt_timeout:.0f}s"
            log(f"[parent] {name}: {failure}")
            if "RESOURCE_EXHAUSTED" in partial:
                per_chip = max(16, per_chip // 2)
            iters = _halve_iters(iters)
            continue
        sys.stderr.write(proc.stderr[-4000:])
        if proc.returncode == 0:
            result = _parse_child_json(proc.stdout)
            if result is not None:
                return result, None
            failure = "child emitted no JSON"
            continue
        # A child that printed a complete measurement and THEN died (e.g.
        # in a later optional pass) still produced evidence — same
        # discipline as the timeout path above.  Exception: an OOM death
        # (RESOURCE_EXHAUSTED) is recoverable by the batch-halving retry,
        # which may capture the measurements the crash cut short — stash
        # the snapshot and keep climbing the ladder instead of returning
        # a partial result as success.
        tail = (proc.stderr or "")[-2000:]
        result = _parse_child_json(proc.stdout)
        if result is not None:
            if "RESOURCE_EXHAUSTED" in tail and attempt < attempts - 1:
                log(f"[parent] {name}: child OOMed (exit "
                    f"{proc.returncode}) after a completed measurement; "
                    "stashing it and retrying at half batch")
                stashed = result
            else:
                log(f"[parent] {name}: child exited {proc.returncode} "
                    "after a completed measurement; keeping it")
                return result, None
        else:
            failure = f"exit {proc.returncode}: {tail.strip().splitlines()[-1] if tail.strip() else 'no stderr'}"
            log(f"[parent] {name}: {failure}")
        if "RESOURCE_EXHAUSTED" in tail:
            per_chip = max(16, per_chip // 2)
        elif "UNAVAILABLE" in tail or "DEADLINE_EXCEEDED" in tail \
                or "failed to initialize" in tail.lower():
            time.sleep(15)  # transient backend trouble; let it settle
        iters = _halve_iters(iters)
    if stashed is not None:
        log(f"[parent] {name}: retries failed; returning the stashed "
            "pre-OOM snapshot")
        return stashed, None
    return None, failure


# Mutable orchestration state shared with the signal handler: the final
# JSON can be assembled and printed at ANY moment.  ``run_id`` stamps
# this process's partial snapshots so crash recovery can never attribute
# a PREVIOUS run's numbers to this one.
_STATE: dict = {"start": None, "phases": {}, "failures": {}, "cache": {},
                "probe": None, "emitted": False, "run_id": None}


def _probe_health(timeout: float = 90.0) -> dict:
    """Health-probe the default backend in a subprocess BEFORE any long
    phase attempt: backend init + one tiny jitted matmul with a host
    fetch.  Returns {"ok", "seconds", "device_kind", "n_devices",
    "platform"} or {"ok": False, "error"}.  A dead tunnel hangs inside
    the child (possibly at interpreter start — the sitecustomize hook
    dials the relay), so the subprocess timeout IS the detection."""
    code = (
        "import time; t0 = time.time()\n"
        "import jax, jax.numpy as jnp\n"
        "d = jax.devices()\n"
        "x = jnp.ones((512, 512), jnp.bfloat16)\n"
        "float((x @ x).sum())\n"
        "print('PROBE|%s|%d|%s|%.1f'\n"
        "      % (d[0].device_kind, len(d), d[0].platform,\n"
        "         time.time() - t0), flush=True)\n")
    t0 = time.perf_counter()
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              timeout=timeout)
    except subprocess.SubprocessError as e:
        return {"ok": False,
                "error": f"probe {type(e).__name__} after {timeout:.0f}s"}
    for line in reversed(proc.stdout.strip().splitlines()):
        if line.startswith("PROBE|"):
            _, kind, n, platform, secs = line.split("|")
            return {"ok": True, "device_kind": kind, "n_devices": int(n),
                    "platform": platform, "seconds": float(secs),
                    "probe_wall_sec": round(time.perf_counter() - t0, 1)}
    tail = (proc.stderr or "").strip().splitlines()
    return {"ok": False, "error": f"probe exit {proc.returncode}: "
                                  f"{tail[-1] if tail else 'no output'}"}


def _load_cache() -> dict:
    try:
        with open(CACHE_PATH) as fh:
            cache = json.load(fh)
        if not isinstance(cache, dict):
            return {}
        for entry in cache.values():
            # Pre-rename caches (<= PR 5) spell the resident warm rate
            # ips_warm_resident; migrate on load so the canonical
            # warm_resident_ips is the only spelling downstream — the
            # same one-spelling rule as warm_memmap_ips, without an
            # alias riding the evidence.
            if isinstance(entry, dict) and "ips_warm_resident" in entry:
                entry.setdefault("warm_resident_ips",
                                 entry.pop("ips_warm_resident"))
        return cache
    except (OSError, json.JSONDecodeError):
        return {}


def _save_cache(cache: dict) -> None:
    try:
        tmp = f"{CACHE_PATH}.tmp"
        with open(tmp, "w") as fh:
            json.dump(cache, fh, indent=1)
        os.replace(tmp, CACHE_PATH)
    except OSError as e:
        log(f"[parent] cache write failed: {e!r}")


def _finalize() -> dict:
    """Assemble the final output dict from _STATE at ANY moment: phases
    not (yet) freshly captured fall back to cache entries whose hardware
    matches the probed backend (unverifiable when the probe failed —
    marked, not dropped)."""
    phases = dict(_STATE["phases"])
    failures = dict(_STATE["failures"])
    cache = _STATE["cache"]
    probe = _STATE["probe"] or {}
    hw = ((probe.get("device_kind"), probe.get("n_devices"))
          if probe.get("ok") else None)
    configured_batch = {name: per_chip for name, _, per_chip, _ in PHASES}
    for name, _, _, _ in PHASES:
        if name in phases or name not in cache:
            continue
        entry = cache[name]
        if hw is not None and (entry.get("device_kind"),
                               entry.get("n_chips")) != hw:
            failures.setdefault(
                name, f"cached result is from {entry.get('device_kind')} "
                      f"x{entry.get('n_chips')}, live is {hw[0]} x{hw[1]}")
            continue
        if (entry.get("batch_per_chip") is not None
                and entry["batch_per_chip"] != configured_batch[name]):
            # A phase whose primary batch config changed (e.g.
            # resnet18_cifar_score 256 -> 512) must not have the OLD
            # config's capture silently billed as the new primary.
            failures.setdefault(
                name, f"cached result is at batch "
                      f"{entry['batch_per_chip']}/chip; the phase now "
                      f"captures {configured_batch[name]}/chip")
            continue
        phases[name] = dict(entry, cached=True,
                            fresh_failure=failures.pop(
                                name, "not attempted"))
        if hw is None:
            phases[name]["device_unverified"] = True
    for name, _, _, _ in PHASES:
        if name not in phases:
            # No fresh capture AND no cache: the phase must show up as an
            # explicit failure, not silently vanish from the evidence.
            # The cause names the backend only when the probe actually
            # failed — mid-run partials on a healthy backend just have
            # queued phases.
            cause = ("not attempted (backend unreachable)"
                     if _STATE["probe"] is not None and not probe.get("ok")
                     else "not attempted")
            failures.setdefault(name, f"{cause}; no cached entry")

    # Headline: the north-star model if captured, else the CIFAR model.
    headline = None
    for name in ("resnet50_imagenet_train", "resnet18_cifar_train",
                 "resnet50_imagenet_score", "resnet18_cifar_score",
                 "imagenet_datapath"):
        # A decode-only datapath result is a host decode rate, a profiled
        # run's timings carry trace overhead, and a malformed entry whose
        # rate is missing or non-finite (a NaN can ride in via a stale
        # cache file: json.load accepts the token) has no number to
        # headline — none may be it.
        if name in phases and not phases[name].get("decode_only") \
                and not phases[name].get("profiled") \
                and _finite(phases[name].get("ips_per_chip")):
            headline = name
            break

    out = {
        "metric": (f"{headline}_images_per_sec_per_chip" if headline
                   else "train_images_per_sec_per_chip"),
        "value": phases[headline].get("ips_per_chip") if headline else None,
        "unit": "images/sec/chip",
        "vs_baseline": None,
        "phases": phases,
        "backend_probe": probe,
        "elapsed_sec": round(time.monotonic() - _STATE["start"], 1),
    }
    if headline:
        base = V100_BASELINE_IPS.get(headline)
        if base and out["value"] is not None:
            out["vs_baseline"] = round(out["value"] / base, 3)
        if phases[headline].get("cached"):
            out["headline_cached"] = True
    if failures:
        out["failed_phases"] = failures
    return out


def _dump_json_file(out: dict, path: str) -> bool:
    """Atomic, sanitized, never-raising evidence write: NaN/Inf become
    null (strict parsers must accept the file), and NO exception — OSError
    or a TypeError from an unserializable field — may escape to suppress
    the stdout line this write precedes.  Returns False on failure so the
    caller can avoid pointing the stdout line at a stale file."""
    try:
        tmp = f"{path}.tmp"
        with open(tmp, "w") as fh:
            json.dump(_sanitize(out), fh, indent=1, default=repr,
                      allow_nan=False)
        os.replace(tmp, path)
        return True
    except Exception as e:
        log(f"[parent] evidence write to {path} failed: {e!r}")
        return False


def _write_partial() -> None:
    """Persist the would-be-final JSON after every phase: a SIGKILL (which
    no handler can catch) still leaves the full evidence on disk."""
    try:
        out = dict(_finalize(), partial=True, run_id=_STATE["run_id"])
    except Exception as e:
        log(f"[parent] partial assembly failed: {e!r}")
        return
    _dump_json_file(out, PARTIAL_PATH)


def _sanitize(obj):
    """NaN/Inf never reach json.dumps: a missing round-1 train time once
    produced ips=NaN, whose non-standard `NaN` token strict parsers (the
    consuming harness) reject — the parsed=null failure mode again."""
    if isinstance(obj, float) and not math.isfinite(obj):
        return None
    if isinstance(obj, dict):
        return {k: _sanitize(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_sanitize(v) for v in obj]
    return obj


def _compact_line(out: dict, evidence_ok: bool = True) -> str:
    """The ONE stdout line, guaranteed <= MAX_LINE_BYTES: headline triple
    + per-phase {ips, mfu, cached} + the evidence-file path.  Staged
    truncation (shorten failures -> names only -> ips only -> headline
    only) keeps the line parseable no matter what the full evidence
    holds.  ``evidence_ok=False`` (the write failed) nulls the path so a
    STALE previous file is never attributed to this run."""
    evidence = EVIDENCE_PATH if evidence_ok else None
    phases = {}
    for name, e in (out.get("phases") or {}).items():
        c = {"ips": e.get("ips_per_chip")}
        if e.get("mfu") is not None:
            c["mfu"] = e["mfu"]
        if e.get("unit") and "images/sec" not in str(e["unit"]):
            c["unit"] = e["unit"]
        if e.get("cached"):
            c["cached"] = True
        # The warm-round / warm-cache / backend / serving / feed /
        # pool-layout numbers are round-level headline evidence — small
        # enough to ride the line.  warm_memmap_ips is the ONLY spelling
        # of the datapath's steady-state rate (the deprecated ips_warm
        # fallback is gone with its shim).
        for src, dst in (("warm_memmap_ips", "warm_ips"),
                         ("round_sec_warm", "warm_s"),
                         ("round_sec_cold", "cold_s"),
                         ("compile_tax_sec", "tax_s"),
                         ("test_accuracy_rd1", "acc"),
                         ("qps_closed", "qps"),
                         ("p99_ms_closed", "p99_ms"),
                         ("request_path_compiles", "req_compiles"),
                         ("step_time_ms_p50", "step_time_ms_p50"),
                         ("step_time_ms_p99", "step_time_ms_p99"),
                         ("backend", "be"),
                         # The streaming phase's riders: the ack tail
                         # latency (the WAL-fsync bound clients feel)
                         # and which trigger fired the measured round —
                         # an ingest-rate claim is ambiguous without
                         # them.  The rest (qps, labels, pool growth)
                         # stays in the evidence file.
                         *((("ack_p99_ms", "ack_p99"),
                            ("trigger_cause", "trigger"))
                           if name == "stream_round" else ()),
                         # The disk tier's riders (ISSUE 16): the warm
                         # block-cache hit fraction and the page-in
                         # stall tail — a disk-backed train rate is
                         # ambiguous without knowing how often the
                         # gather actually touched disk and what the
                         # misses cost.  The finer figures (page-in
                         # rate, p50, the memory-leg comparison) stay
                         # in the evidence file.
                         *((("cache_hit_frac", "hit"),
                            ("page_stall_ms_p99", "stall_ms"))
                           if name == "disk_pool_feed" else ()),
                         # The fleet tier's riders (ISSUE 18): how many
                         # runs finished, how many came back from a
                         # preemption, and the fleet's wall — a
                         # scheduling-rate headline is ambiguous
                         # without them.  The rest (attempts, merged
                         # scrape coverage, the killed run's id) stays
                         # in the evidence file.
                         *((("runs_finished", "runs"),
                            ("runs_resumed", "resumed"),
                            ("total_sec", "wall_s"))
                           if name == "fleet_smoke" else ()),
                         # The resident-pool layout rides the line only
                         # where it is the phase's SUBJECT (the
                         # sharded-ceiling probe) — a row-sharded max-N
                         # is meaningless without the layout tag, but
                         # claiming it on every selection phase pushed
                         # the realistic-maximal line past the tail
                         # bound (same rule as feed_source below; the
                         # other phases keep it in the evidence file).
                         *((("pool_sharding", "pool_sharding"),
                            # The pod-tier column feed (ISSUE 15):
                            # whether the row scans fed their columns
                            # over the ring-permute feed — a row-layout
                            # max-N is ambiguous without it.
                            ("ring_feed", "ring"))
                           if name == "kcenter_select_maxn" else ()),
                         # Feed attribution rides the line only where it
                         # is the phase's subject (the hierarchy
                         # comparison and the end-to-end rounds) — the
                         # plain train phases' feed_source lives in the
                         # evidence file; putting it on 3 more phases
                         # pushed the realistic-maximal line past the
                         # tail bound.
                         *((("feed_source", "feed"),
                            ("feed_stall_frac", "stall"))
                           if name == "imagenet_train_feed"
                           or name.startswith("al_round") else ()),
                         # The pipelined round's mode + warm overlap
                         # ride only the end-to-end round phases (their
                         # SUBJECT since ISSUE 7); the full overlap
                         # breakdown stays in the evidence file.
                         # ... plus the failure model's counters
                         # (ISSUE 8): how many site-level retries the
                         # run absorbed and how many degradation-ladder
                         # escalations it took — an end-to-end round
                         # number is dishonest without knowing it
                         # self-healed.
                         *((("round_pipeline", "pipeline"),
                            ("overlap_frac", "overlap"),
                            ("fault_retries_total", "retries"),
                            ("degrade_events", "degraded"),
                            # The experiment-truth drift rider (ISSUE
                            # 13): a timed round's score-distribution
                            # shift rides the line; the JS twin stays
                            # in the evidence file.
                            ("rd_score_drift_psi", "drift"),
                            # The pod-tier column-feed rider (ISSUE
                            # 15): did the measured rounds' k-center
                            # scans run the ring feed (absent when the
                            # strategy never ran k-center).
                            ("ring_feed", "ring"))
                           if name.startswith("al_round") else ()),
                         # The gradient-path riders (ISSUE 10 + 15)
                         # ride only the TRAIN phases (their subject):
                         # the backward's share of the step, the sync
                         # precision the number was measured under,
                         # and — when quantized — WHICH wire form
                         # synced it and its per-step wire-model MB
                         # (allgather vs the pod-tier reduce-scatter).
                         # opt_update_ms stays in the evidence file.
                         *((("bwd_frac", "bwd_frac"),
                            ("grad_allreduce", "grad_ar"),
                            ("grad_sync", "grad_sync"))
                           if name.endswith("_train") else ())):
            if e.get(src) is not None and dst not in c:
                c[dst] = e[src]
        if name == "imagenet_train_feed":
            # The hierarchy comparison, positionally: [resident,
            # host_prefetch, host_serial] img/s (full spellings in the
            # evidence file) — the array form keeps the line bounded.
            legs = [e.get("ips_resident"), e.get("ips_host_prefetch"),
                    e.get("ips_host_serial")]
            if any(v is not None for v in legs):
                c["legs"] = legs
        if c.get("grad_sync"):
            # Line spelling of the wire form: "ag"/"rs" (the full
            # spelling + grad_wire_mb stay in the evidence file — the
            # same finer-figures rule as opt_update_ms).
            c["grad_sync"] = {"allgather": "ag",
                              "reduce_scatter": "rs"}.get(
                                  c["grad_sync"], c["grad_sync"])
        if isinstance(e.get("residency"), dict) and "feed" not in c:
            # feed_source subsumes the older scoring-residency tag on
            # the line (feed == "resident" implies the pool pinned);
            # the full residency dict stays in the evidence file.
            c["resid"] = e["residency"].get("mode")
        if e.get("s2d"):
            c["s2d"] = True
        phases[name] = c
    compact = {
        "metric": out.get("metric"), "value": out.get("value"),
        "unit": out.get("unit"), "vs_baseline": out.get("vs_baseline"),
        "phases": phases,
        "probe_ok": bool((out.get("backend_probe") or {}).get("ok")),
        "elapsed_sec": out.get("elapsed_sec"),
        "evidence": evidence,
    }
    if out.get("headline_cached"):
        compact["headline_cached"] = True
    for k in ("partial", "interrupted_by_signal", "error"):
        if out.get(k) is not None:
            compact[k] = (out[k][:120] if isinstance(out[k], str)
                          else out[k])
    failed = out.get("failed_phases") or {}
    if failed:
        compact["failed"] = {n: str(m)[:40] for n, m in failed.items()}

    def dumps(o):
        # Compact separators: the margin accounting at MAX_LINE_BYTES
        # counts spellings like '"ack_p99":NNN.NNN,' — json's default
        # ", "/": " separators were silently spending one tail byte per
        # key and comma (~150 bytes across the 15-phase rich form) that
        # the accounting never budgeted.
        return json.dumps(_sanitize(o), allow_nan=False,
                          separators=(",", ":"))

    line = dumps(compact)
    if len(line) > MAX_LINE_BYTES and failed:
        compact["failed"] = sorted(failed)
        line = dumps(compact)
    if len(line) > MAX_LINE_BYTES:
        compact["phases"] = {n: c.get("ips") for n, c in phases.items()}
        line = dumps(compact)
    if len(line) > MAX_LINE_BYTES:
        line = dumps({"metric": out.get("metric"), "value": out.get("value"),
                      "unit": out.get("unit"),
                      "vs_baseline": out.get("vs_baseline"),
                      "evidence": evidence})
    return line


def _emit_final(extra: dict = None) -> None:
    """Print THE one compact JSON line (exactly once, no matter how many
    paths race to it), after writing the FULL evidence to
    bench_evidence.json (+ the bench_partial.json mirror).  SIGTERM/
    SIGINT are masked for the duration: without the mask, a signal
    landing between flag-set and print would find 'emitted' already True
    in the handler and os._exit before the main thread's print runs —
    zero output, the exact rc=124/parsed=null failure this machinery
    exists to prevent.  A _finalize crash (e.g. a malformed cache entry)
    degrades to a minimal error line rather than suppressing output
    entirely."""
    old_mask = signal.pthread_sigmask(
        signal.SIG_BLOCK, {signal.SIGTERM, signal.SIGINT})
    try:
        if _STATE["emitted"]:
            return
        finalize_error = None
        try:
            out = _finalize()
            if extra:
                out.update(extra)
        except Exception as e:
            log(f"[parent] finalize failed: {e!r}")
            # The repr is truncated: an exception quoting a malformed
            # cache entry must not push THIS line past the bound either.
            finalize_error = f"finalize failed: {e!r}"[:300]
            out = {"metric": "train_images_per_sec_per_chip", "value": None,
                   "unit": "images/sec/chip", "vs_baseline": None,
                   "error": finalize_error}
            # The per-phase snapshot rewritten after every phase is the
            # best evidence still standing — attach the error to it
            # rather than clobbering it with the minimal dict.  The
            # run_id match keeps a PREVIOUS run's snapshot from being
            # attributed to this one.
            try:
                with open(PARTIAL_PATH) as fh:
                    prev = json.load(fh)
                if isinstance(prev, dict) and prev.get("phases") \
                        and prev.get("run_id") == _STATE["run_id"]:
                    out = dict(prev, error=finalize_error)
            except Exception:
                pass
        # Evidence first, line second: the line only names the file when
        # the write actually landed.  On the finalize-error path the
        # partial mirror is left alone — it may hold the last good
        # snapshot this error path just recovered.
        evidence_ok = _dump_json_file(out, EVIDENCE_PATH)
        if finalize_error is None:
            _dump_json_file(out, PARTIAL_PATH)
        try:
            line = _compact_line(out, evidence_ok=evidence_ok)
        except Exception as e:
            log(f"[parent] compact-line failed: {e!r}")
            line = json.dumps(_sanitize(
                {"metric": out.get("metric"), "value": out.get("value"),
                 "unit": out.get("unit"), "vs_baseline": None,
                 "error": f"compact failed: {e!r}"[:300],
                 "evidence": EVIDENCE_PATH if evidence_ok else None}),
                allow_nan=False)
        print(line, flush=True)
        _STATE["emitted"] = True
    finally:
        signal.pthread_sigmask(signal.SIG_SETMASK, old_mask)


def _signal_emit(signum, frame):
    """An outer `timeout`'s SIGTERM (or a ^C) becomes a parsed result: the
    round-3 harness recorded rc=124/parsed=null while a complete cache sat
    on disk — the line must go out BEFORE the process dies."""
    log(f"[parent] caught signal {signum}; emitting evidence now")
    _emit_final(extra={"interrupted_by_signal": signum})
    os._exit(0)


def main() -> None:
    _STATE["start"] = time.monotonic()
    _STATE["run_id"] = f"{os.getpid()}-{time.time_ns()}"
    _STATE["cache"] = _load_cache()
    signal.signal(signal.SIGTERM, _signal_emit)
    signal.signal(signal.SIGINT, _signal_emit)
    try:
        _main_inner()
        _emit_final()
    except Exception as e:  # the JSON line must appear no matter what
        log(f"[parent] fatal: {e!r}")
        _emit_final(extra={"error": repr(e)})


def _main_inner() -> None:
    deadline = _STATE["start"] + TOTAL_BUDGET_S
    cache = _STATE["cache"]
    phases: dict = _STATE["phases"]
    failures: dict = _STATE["failures"]

    probe = _probe_health()
    _STATE["probe"] = probe
    if not probe.get("ok"):
        log(f"[parent] backend probe failed ({probe.get('error')}); "
            "emitting cached evidence without fresh attempts")
        return
    log(f"[parent] backend healthy: {probe['device_kind']} "
        f"x{probe['n_devices']} ({probe['platform']}), probe "
        f"{probe['seconds']:.1f}s")
    degraded = probe["seconds"] > PROBE_DEGRADED_S
    if degraded:
        log(f"[parent] probe took {probe['seconds']:.0f}s — degraded "
            "backend: single attempts, fresh-only phases first")

    # Phases with no cache entry carry the only NEW evidence this run can
    # produce — capture them first so a mid-run death costs the least.
    order = sorted(PHASES, key=lambda p: (
        p[0] in cache, cache.get(p[0], {}).get("captured_utc", "")))
    for name, iters, per_chip, timeout in order:
        result, failure = run_phase_with_retries(
            name, iters, per_chip, timeout, deadline,
            max_attempts=1 if degraded else 2)
        if result is not None:
            result["captured_utc"] = time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime())
            phases[name] = result
            if not result.get("decode_only") and not result.get("profiled"):
                # A decode-only CPU fallback is a degraded capture, and a
                # profiled run's timings carry trace overhead; neither may
                # clobber a clean accelerator entry in the cache (the
                # cache exists to preserve those).
                cache[name] = result
                _save_cache(cache)
            if isinstance(result.get("ips"), (int, float)):
                log(f"[parent] {name}: {result['ips']:,.0f} img/s total, "
                    f"{result['ips_per_chip']:,.0f} img/s/chip")
            else:
                log(f"[parent] {name}: captured without a rate "
                    "(see phase entry)")
        else:
            failures[name] = failure
        _write_partial()

    # MFU back-fill: cost_analysis is unavailable on the tunneled TPU
    # backend, so phases that timed or errored out of the on-device flops
    # enrichment get their FLOP count from an identical CPU lowering (a
    # property of the computation, not the device) combined with the
    # TPU-measured throughput.  Runs over fresh AND cache-fallback
    # entries; PALLAS_AXON_POOL_IPS is cleared so the child's interpreter
    # cannot hang dialing a dead tunnel (the hook runs at startup).
    for name, entry in list(phases.items()) + [
            (n, cache[n]) for n, _, _, _ in PHASES
            if n in cache and n not in phases]:
        if not name.endswith(("_train", "_score")) or entry.get("mfu") \
                or not entry.get("ips_per_chip"):
            continue
        remaining = deadline - time.monotonic()
        if remaining <= 60:
            break
        # FLOPs scale linearly in batch, so lower a small batch (cheap CPU
        # compile) and let the child normalize per image.
        cmd = [sys.executable, os.path.abspath(__file__), "--phase", name,
               "--flops-cpu", "--per-chip-batch",
               str(min(32, entry.get("batch_per_chip", 128)))]
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PALLAS_AXON_POOL_IPS="")
        log(f"[parent] {name}: computing FLOPs via CPU lowering")
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=min(600, remaining), env=env)
        except subprocess.SubprocessError as e:
            log(f"[parent] {name}: flops child failed: {e!r}")
            continue
        parsed = _parse_child_json(proc.stdout,
                                   required=("flops_per_image",))
        flops = (parsed or {}).get("flops_per_image")
        if not flops:
            log(f"[parent] {name}: CPU flops lowering gave nothing "
                f"(rc={proc.returncode})")
            continue
        tflops_chip = flops * entry["ips_per_chip"] / 1e12
        entry["gflop_per_image"] = round(flops / 1e9, 2)
        entry["tflops_per_sec_per_chip"] = round(tflops_chip, 1)
        entry["flops_source"] = "cpu-lowering"
        peak = _peak_tflops(entry.get("device_kind", ""))
        if peak:
            entry["mfu"] = round(tflops_chip / peak, 3)
            entry["peak_tflops_per_chip"] = peak
        if name in cache and not entry.get("decode_only") \
                and not entry.get("profiled"):
            # Same rule as the capture loop: profiled timings never
            # clobber a clean cache entry.
            cache[name] = {k: v for k, v in entry.items()
                           if k not in ("cached", "fresh_failure",
                                        "device_unverified")}
            _save_cache(cache)
        _write_partial()


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--phase", default=None)
    parser.add_argument("--iters", type=int, default=50)
    parser.add_argument("--per-chip-batch", type=int, default=128)
    parser.add_argument("--flops-cpu", action="store_true")
    parser.add_argument(
        "--assert_no_regression", action="store_true",
        help="after emitting the compact line, run the perf-regression "
             "gate (scripts/perf_report.py) over BENCH_r*.json + this "
             "run's evidence and exit NONZERO on a pinned regression "
             "(warm al_round seconds or train ips/chip >10%% worse than "
             "best-known; exit 3 when this run produced no usable "
             "evidence to judge).  Opt-in: it deliberately breaks the "
             "always-exit-0 contract so a hardware window produces a "
             "machine-checked verdict")
    args = parser.parse_args()
    if args.phase and args.flops_cpu:
        print(json.dumps(run_flops_cpu(args.phase, args.per_chip_batch)),
              flush=True)
    elif args.phase:
        for result in run_child_phase(args.phase, args.iters,
                                      args.per_chip_batch):
            print(json.dumps(result), flush=True)
    else:
        main()
        if args.assert_no_regression:
            # The gate reads the historical series from the repo root
            # and THIS run's full evidence as the latest point; its
            # table goes to stderr (stdout already carried the one
            # compact line) and its exit code is the verdict.
            import contextlib
            import importlib.util

            spec = importlib.util.spec_from_file_location(
                "perf_report", os.path.join(
                    os.path.dirname(os.path.abspath(__file__)),
                    "scripts", "perf_report.py"))
            perf_report = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(perf_report)
            argv = perf_report.default_series_paths() + [
                "--current", EVIDENCE_PATH]
            with contextlib.redirect_stdout(sys.stderr):
                rc = perf_report.main(argv)
            sys.exit(rc)
