// Native batch JPEG decode + crop + bilinear resize for the data loader.
//
// The reference delegates all native dataloading to torch's C++ DataLoader
// workers + PIL (src/query_strategies/strategy.py:325-328); this is the
// TPU-side equivalent: the 1.28M-image acquisition-scoring passes
// (SURVEY.md hard part (e)) are bottlenecked by host JPEG decode, so the
// decode -> crop -> resize pipeline runs here in C++ with a std::thread
// pool, writing straight into a caller-owned uint8 [N, S, S, 3] buffer
// (zero Python-object overhead per image).
//
// Split of responsibilities: Python computes crop rectangles (the seeded
// RandomResizedCrop / Resize+CenterCrop parameter logic stays in
// data/imagenet.py where it is reproducible per (seed, epoch, index));
// C++ does header parsing, Huffman decode, and the bandwidth-heavy pixel
// work.  C ABI only — loaded via ctypes, no pybind11 dependency.
//
// Build: see native/Makefile (links against the system libjpeg).

#include <cstddef>
#include <cstdio>

#include <jpeglib.h>  // requires <cstdio>/<cstddef> first

#include <algorithm>
#include <atomic>
#include <cmath>
#include <csetjmp>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

struct ErrorMgr {
  jpeg_error_mgr pub;
  jmp_buf setjmp_buffer;
};

void error_exit(j_common_ptr cinfo) {
  ErrorMgr* err = reinterpret_cast<ErrorMgr*>(cinfo->err);
  longjmp(err->setjmp_buffer, 1);
}

// Decode one JPEG file into an RGB buffer.  Returns true on success and
// fills (h, w); the buffer is resized to h*w*3.
bool decode_rgb(const char* path, std::vector<uint8_t>& rgb, int* h,
                int* w) {
  FILE* fh = std::fopen(path, "rb");
  if (!fh) return false;

  jpeg_decompress_struct cinfo;
  ErrorMgr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = error_exit;
  if (setjmp(jerr.setjmp_buffer)) {
    jpeg_destroy_decompress(&cinfo);
    std::fclose(fh);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_stdio_src(&cinfo, fh);
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);

  *h = static_cast<int>(cinfo.output_height);
  *w = static_cast<int>(cinfo.output_width);
  rgb.resize(static_cast<size_t>(*h) * *w * 3);
  while (cinfo.output_scanline < cinfo.output_height) {
    JSAMPROW row = rgb.data() + static_cast<size_t>(cinfo.output_scanline) *
                                    *w * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  std::fclose(fh);
  return true;
}

// Bilinear tap: source index pair + 8.8 fixed-point weight for one output
// coordinate (align-corners=false pixel-center convention, matching
// PIL/torchvision resize geometry).
struct Tap {
  int i0, i1;
  int w1;  // weight of i1 in [0, 256]; i0 gets 256 - w1
};

void make_taps(int in_size, int offset, int in_extent, int out,
               int clamp_max, std::vector<Tap>& taps) {
  taps.resize(out);
  const float scale = static_cast<float>(in_extent) / out;
  for (int o = 0; o < out; ++o) {
    float f = (o + 0.5f) * scale - 0.5f + offset;
    int i0 = static_cast<int>(std::floor(f));
    float frac = f - i0;
    Tap& t = taps[o];
    t.i1 = std::min(std::max(i0 + 1, 0), clamp_max);
    t.i0 = std::min(std::max(i0, 0), clamp_max);
    t.w1 = static_cast<int>(frac * 256.0f + 0.5f);
  }
  (void)in_size;
}

// Crop box [top, left, ch, cw] of src (h x w x 3) -> dst (out x out x 3),
// separable two-pass bilinear with precomputed fixed-point taps: the
// horizontal pass shrinks each needed source row once, the vertical pass
// blends two resampled rows — O(rows_used * out) weight computations
// instead of recomputing 4-tap weights per output pixel.
void crop_resize_bilinear(const uint8_t* src, int h, int w, int top,
                          int left, int ch, int cw, uint8_t* dst, int out) {
  std::vector<Tap> xt, yt;
  make_taps(w, left, cw, out, w - 1, xt);
  make_taps(h, top, ch, out, h - 1, yt);

  // Horizontal pass cache, sized to the row range the vertical taps can
  // touch (the crop box +- 1, not the whole image).
  int row_lo = h - 1, row_hi = 0;
  for (const Tap& t : yt) {
    row_lo = std::min(row_lo, t.i0);
    row_hi = std::max(row_hi, t.i1);
  }
  const int n_rows = row_hi - row_lo + 1;
  std::vector<int16_t> rows(static_cast<size_t>(n_rows) * out * 3);
  std::vector<uint8_t> row_done(n_rows, 0);
  auto hrow = [&](int y_abs) -> const int16_t* {
    const int y = y_abs - row_lo;
    int16_t* r = rows.data() + static_cast<size_t>(y) * out * 3;
    if (!row_done[y]) {
      const uint8_t* s = src + static_cast<size_t>(y_abs) * w * 3;
      for (int o = 0; o < out; ++o) {
        const Tap& t = xt[o];
        const uint8_t* a = s + t.i0 * 3;
        const uint8_t* b = s + t.i1 * 3;
        const int w1 = t.w1, w0 = 256 - t.w1;
        r[o * 3 + 0] = static_cast<int16_t>((a[0] * w0 + b[0] * w1) >> 8);
        r[o * 3 + 1] = static_cast<int16_t>((a[1] * w0 + b[1] * w1) >> 8);
        r[o * 3 + 2] = static_cast<int16_t>((a[2] * w0 + b[2] * w1) >> 8);
      }
      row_done[y] = 1;
    }
    return r;
  };

  for (int oy = 0; oy < out; ++oy) {
    const Tap& t = yt[oy];
    const int16_t* r0 = hrow(t.i0);
    const int16_t* r1 = hrow(t.i1);
    const int w1 = t.w1, w0 = 256 - t.w1;
    uint8_t* o = dst + static_cast<size_t>(oy) * out * 3;
    for (int i = 0; i < out * 3; ++i) {
      o[i] = static_cast<uint8_t>((r0[i] * w0 + r1[i] * w1 + 128) >> 8);
    }
  }
}

template <typename Fn>
void parallel_for(int n, int n_threads, Fn fn) {
  n_threads = std::max(1, std::min(n_threads, n));
  if (n_threads == 1) {
    for (int i = 0; i < n; ++i) fn(i);
    return;
  }
  std::atomic<int> next(0);
  std::vector<std::thread> workers;
  workers.reserve(n_threads);
  for (int t = 0; t < n_threads; ++t) {
    workers.emplace_back([&] {
      int i;
      while ((i = next.fetch_add(1)) < n) fn(i);
    });
  }
  for (auto& th : workers) th.join();
}

}  // namespace

extern "C" {

// Parse JPEG headers only: out_hw[2*i] = height, out_hw[2*i+1] = width.
// Returns the number of files that FAILED (0 == all ok); failures get -1.
int al_jpeg_dims(const char** paths, int n, int32_t* out_hw,
                 int n_threads) {
  std::atomic<int> failures(0);
  parallel_for(n, n_threads, [&](int i) {
    FILE* fh = std::fopen(paths[i], "rb");
    if (!fh) {
      out_hw[2 * i] = out_hw[2 * i + 1] = -1;
      failures.fetch_add(1);
      return;
    }
    jpeg_decompress_struct cinfo;
    ErrorMgr jerr;
    cinfo.err = jpeg_std_error(&jerr.pub);
    jerr.pub.error_exit = error_exit;
    if (setjmp(jerr.setjmp_buffer)) {
      jpeg_destroy_decompress(&cinfo);
      std::fclose(fh);
      out_hw[2 * i] = out_hw[2 * i + 1] = -1;
      failures.fetch_add(1);
      return;
    }
    jpeg_create_decompress(&cinfo);
    jpeg_stdio_src(&cinfo, fh);
    jpeg_read_header(&cinfo, TRUE);
    out_hw[2 * i] = static_cast<int32_t>(cinfo.image_height);
    out_hw[2 * i + 1] = static_cast<int32_t>(cinfo.image_width);
    jpeg_destroy_decompress(&cinfo);
    std::fclose(fh);
  });
  return failures.load();
}

// Decode each JPEG, crop rects[i] = {top, left, ch, cw}, bilinear-resize to
// out_size, write into out[i] (uint8, n * out_size * out_size * 3).
// Per-file failures (e.g. CMYK JPEGs libjpeg can't emit as RGB) set
// failed[i] = 1 and zero the slot so the caller can re-decode just those
// files through its fallback path.  Returns the failure count.
int al_decode_crop_resize(const char** paths, int n, const int32_t* rects,
                          int out_size, uint8_t* out, uint8_t* failed,
                          int n_threads) {
  std::atomic<int> failures(0);
  const size_t stride =
      static_cast<size_t>(out_size) * out_size * 3;
  parallel_for(n, n_threads, [&](int i) {
    std::vector<uint8_t> rgb;
    int h = 0, w = 0;
    if (!decode_rgb(paths[i], rgb, &h, &w)) {
      std::memset(out + i * stride, 0, stride);
      failed[i] = 1;
      failures.fetch_add(1);
      return;
    }
    failed[i] = 0;
    const int32_t* r = rects + 4 * i;
    crop_resize_bilinear(rgb.data(), h, w, r[0], r[1], r[2], r[3],
                         out + i * stride, out_size);
  });
  return failures.load();
}

}  // extern "C"
