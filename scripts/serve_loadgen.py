"""Closed- and open-loop load generator for the scoring service AND the
streaming ingest service.

    python scripts/serve_loadgen.py --url http://127.0.0.1:8000 \\
        [--mode closed|open|both] [--duration 10] [--workers 4] \\
        [--rows 8] [--qps 200] [--endpoint /v1/score]

    # ingest mode (the stream verb's /v1/pool + /v1/label endpoints):
    python scripts/serve_loadgen.py --url http://127.0.0.1:8008 \\
        --ingest_rows 32 --label_frac 0.25 [--mode closed|open|both]

``--ingest_rows`` switches the driver to ingest mode: requests carry
``--ingest_rows`` random rows to ``POST /v1/pool``, acked ids are
collected, and a ``--label_frac`` fraction of requests instead attach
labels to previously-acked ids via ``POST /v1/label`` — so the new
endpoints have a closed- AND open-loop driver exactly like /v1/score
does.  429 backpressure is counted, not retried (offered load is part
of the measurement, same as the scoring loops).

Two loop disciplines, because they answer different questions:

  * **closed** — N workers fire back-to-back requests (a new request
    the moment the previous response lands).  Measures the service's
    throughput ceiling; latency under closed load is a function of the
    worker count, not of the service alone.
  * **open** — requests fire on a fixed schedule at ``--qps``
    regardless of responses (the Poisson-ish arrival pattern real
    traffic has).  Measures latency at a given offered load and how
    the 429 backpressure behaves past saturation; a closed loop can
    never see those, because it slows itself down.

Payloads are random uint8 images shaped from the server's own
``/healthz`` (``image_shape``), sent as ``{"b64", "shape"}`` — the
efficient wire path.  Output: ONE JSON line per mode with achieved
qps/ips, p50/p99 latency (nearest-rank, the server's convention), and
status counts.  Stdlib only; keep-alive via one http.client connection
per worker.
"""

from __future__ import annotations

import argparse
import base64
import concurrent.futures
import http.client
import json
import sys
import threading
import time
import urllib.parse
import urllib.request
from typing import Dict, List, Optional

import numpy as np


def _percentile(sorted_vals: List[float], q: float) -> Optional[float]:
    # Same nearest-rank convention as serve/metrics.py.
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q * (len(sorted_vals) - 1)))))
    return float(sorted_vals[idx])


def fetch_health(url: str, timeout: float = 10.0) -> Dict:
    with urllib.request.urlopen(f"{url}/healthz", timeout=timeout) as r:
        return json.loads(r.read().decode())


def make_payload(image_shape, rows: int, seed: int = 0) -> bytes:
    h, w, c = image_shape
    rng = np.random.default_rng(seed)
    images = rng.integers(0, 256, size=(rows, h, w, c), dtype=np.uint8)
    return json.dumps({
        "b64": base64.b64encode(images.tobytes()).decode(),
        "shape": [rows, h, w, c],
    }).encode()


class _Worker:
    """One keep-alive connection; returns (status, latency_s) per post
    (``want_body=True`` additionally returns the response bytes — the
    ingest loops parse acked ids out of them)."""

    def __init__(self, url: str, timeout: float = 30.0):
        p = urllib.parse.urlparse(url)
        self._host, self._port = p.hostname, p.port or 80
        self._timeout = timeout
        self._conn: Optional[http.client.HTTPConnection] = None

    def post(self, path: str, body: bytes, want_body: bool = False):
        t0 = time.perf_counter()
        for attempt in (0, 1):  # one reconnect on a dropped keep-alive
            if self._conn is None:
                self._conn = http.client.HTTPConnection(
                    self._host, self._port, timeout=self._timeout)
            try:
                self._conn.request(
                    "POST", path, body=body,
                    headers={"Content-Type": "application/json"})
                resp = self._conn.getresponse()
                payload = resp.read()
                if resp.getheader("Connection", "").lower() == "close":
                    self._conn.close()
                    self._conn = None
                dt = time.perf_counter() - t0
                if want_body:
                    return resp.status, dt, payload
                return resp.status, dt
            except (http.client.HTTPException, OSError):
                self._conn = None
                if attempt:
                    raise
        raise AssertionError("unreachable")


def _summarize(mode: str, statuses: List[int], lats: List[float],
               wall: float, rows_per_req: int, offered_qps=None) -> Dict:
    lats = sorted(lats)
    n_ok = sum(1 for s in statuses if s == 200)
    out = {
        "mode": mode,
        "wall_s": round(wall, 2),
        "n_requests": len(statuses),
        "n_ok": n_ok,
        "n_429": sum(1 for s in statuses if s == 429),
        "n_err": sum(1 for s in statuses if s not in (200, 429)),
        "rows_per_request": rows_per_req,
        "qps": round(n_ok / wall, 2) if wall > 0 else 0.0,
        "ips": round(n_ok * rows_per_req / wall, 1) if wall > 0 else 0.0,
        "p50_ms": _ms(_percentile(lats, 0.50)),
        "p99_ms": _ms(_percentile(lats, 0.99)),
    }
    if offered_qps is not None:
        out["offered_qps"] = offered_qps
    return out


def _ms(v: Optional[float]) -> Optional[float]:
    return None if v is None else round(v * 1000.0, 3)


def run_closed(url: str, duration_s: float, workers: int, rows: int,
               image_shape, endpoint: str = "/v1/score",
               warmup_requests: int = 2) -> Dict:
    """Closed loop: ``workers`` threads, back-to-back requests."""
    body = make_payload(image_shape, rows)
    # inf until the window opens: a worker racing past the barrier ahead
    # of the main thread's deadline write must keep looping, not exit.
    stop_at = [float("inf")]
    # Workers warm their connection + the service's first batches OFF
    # the clock, rendezvous at the barrier, and only then does the main
    # thread open the measurement window.
    barrier = threading.Barrier(workers + 1)
    lock = threading.Lock()
    statuses: List[int] = []
    lats: List[float] = []

    def loop(seed: int):
        w = _Worker(url)
        for _ in range(warmup_requests):  # connection + first-batch warm
            w.post(endpoint, body)
        barrier.wait()
        local_s, local_l = [], []
        while time.perf_counter() < stop_at[0]:
            s, dt = w.post(endpoint, body)
            local_s.append(s)
            local_l.append(dt)
        with lock:
            statuses.extend(local_s)
            lats.extend(local_l)

    threads = [threading.Thread(target=loop, args=(i,), daemon=True)
               for i in range(workers)]
    for t in threads:
        t.start()
    barrier.wait()
    t0 = time.perf_counter()
    stop_at[0] = t0 + duration_s
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    out = _summarize("closed", statuses, lats, wall, rows)
    out["workers"] = workers
    return out


def run_open(url: str, duration_s: float, qps: float, rows: int,
             image_shape, endpoint: str = "/v1/score",
             max_inflight: int = 256) -> Dict:
    """Open loop: fire at ``qps`` on schedule, independent of responses.
    Requests the schedule could not launch (pool exhausted) count as
    errors — offered load is part of the measurement."""
    body = make_payload(image_shape, rows)
    lock = threading.Lock()
    statuses: List[int] = []
    lats: List[float] = []
    local = threading.local()

    def one():
        w = getattr(local, "w", None)
        if w is None:
            w = local.w = _Worker(url)
        try:
            s, dt = w.post(endpoint, body)
        except OSError:
            s, dt = -1, None
        with lock:
            statuses.append(s)
            if dt is not None and s == 200:
                lats.append(dt)

    n = max(1, int(duration_s * qps))
    interval = 1.0 / qps
    t0 = time.perf_counter()
    with concurrent.futures.ThreadPoolExecutor(max_inflight) as pool:
        futures = []
        for i in range(n):
            target = t0 + i * interval
            delay = target - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            futures.append(pool.submit(one))
        for f in futures:
            f.result()
    wall = time.perf_counter() - t0
    return _summarize("open", statuses, lats, wall, rows, offered_qps=qps)


# -- ingest mode: /v1/pool + /v1/label ---------------------------------------

class _IngestState:
    """Acked-but-unlabeled pool ids, shared across workers so label
    requests always name ids the service actually promised."""

    def __init__(self):
        self._lock = threading.Lock()
        self._ids: List[int] = []
        self.labels_sent = 0

    def add(self, ids: List[int]) -> None:
        with self._lock:
            self._ids.extend(ids)

    def take(self, n: int) -> List[int]:
        with self._lock:
            batch, self._ids = self._ids[:n], self._ids[n:]
            self.labels_sent += len(batch)
            return batch


def make_pool_payload(image_shape, rows: int, seed: int = 0) -> bytes:
    """A /v1/pool body: random uint8 rows, NO oracle labels — the ids
    come back unlabeled so the /v1/label leg has something to attach
    to."""
    h, w, c = image_shape
    rng = np.random.default_rng(seed)
    images = rng.integers(0, 256, size=(rows, h, w, c), dtype=np.uint8)
    return json.dumps({
        "rows_b64": base64.b64encode(images.tobytes()).decode(),
        "shape": [rows, h, w, c],
    }).encode()


def _ingest_once(w: "_Worker", pool_body: bytes, state: _IngestState,
                 label_frac: float, rows: int, rng):
    """One ingest action: a /v1/label attach when the dice and the id
    pool allow, else a /v1/pool append.  Returns (status, latency,
    rows_appended) — label acks append ZERO rows, so the ingest rate is
    computed from actual appends, never inflated by label traffic."""
    if label_frac > 0 and rng.random() < label_frac:
        ids = state.take(rows)
        if ids:
            body = json.dumps({
                "ids": ids,
                "labels": [int(i) % 10 for i in ids],
            }).encode()
            s, dt = w.post("/v1/label", body)
            return s, dt, 0
    s, dt, payload = w.post("/v1/pool", pool_body, want_body=True)
    appended = 0
    if s == 200:
        try:
            acked = json.loads(payload.decode()).get("ids") or []
            state.add(acked)
            appended = len(acked)
        except (ValueError, AttributeError):
            pass
    return s, dt, appended


def run_ingest_closed(url: str, duration_s: float, workers: int,
                      rows: int, label_frac: float, image_shape) -> Dict:
    """Closed loop over /v1/pool + /v1/label: N workers, back-to-back
    requests — the ingest throughput ceiling (WAL fsync bound)."""
    pool_body = make_pool_payload(image_shape, rows)
    state = _IngestState()
    stop_at = [float("inf")]
    barrier = threading.Barrier(workers + 1)
    lock = threading.Lock()
    statuses: List[int] = []
    lats: List[float] = []
    appended_total = [0]

    def loop(seed: int):
        w = _Worker(url)
        rng = np.random.default_rng(seed)
        w.post("/v1/pool", pool_body, want_body=True)  # warm off-clock
        barrier.wait()
        local_s, local_l, local_rows = [], [], 0
        while time.perf_counter() < stop_at[0]:
            s, dt, appended = _ingest_once(w, pool_body, state,
                                           label_frac, rows, rng)
            local_s.append(s)
            local_l.append(dt)
            local_rows += appended
        with lock:
            statuses.extend(local_s)
            lats.extend(local_l)
            appended_total[0] += local_rows

    threads = [threading.Thread(target=loop, args=(i,), daemon=True)
               for i in range(workers)]
    for t in threads:
        t.start()
    barrier.wait()
    t0 = time.perf_counter()
    stop_at[0] = t0 + duration_s
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    out = _summarize("ingest_closed", statuses, lats, wall, rows)
    # Rows actually appended (label acks append zero): the honest
    # ingest rate, not n_ok * rows_per_request.
    out["rows_appended"] = appended_total[0]
    out["ips"] = (round(appended_total[0] / wall, 1) if wall > 0
                  else 0.0)
    out["workers"] = workers
    out["label_frac"] = label_frac
    out["labels_sent"] = state.labels_sent
    return out


def run_ingest_open(url: str, duration_s: float, qps: float, rows: int,
                    label_frac: float, image_shape,
                    max_inflight: int = 256) -> Dict:
    """Open loop: ingest requests fire on schedule at ``qps`` regardless
    of acks — how the 429 backpressure behaves past the WAL's rate."""
    pool_body = make_pool_payload(image_shape, rows)
    state = _IngestState()
    lock = threading.Lock()
    statuses: List[int] = []
    lats: List[float] = []
    appended_total = [0]
    local = threading.local()

    def one(i: int):
        w = getattr(local, "w", None)
        if w is None:
            w = local.w = _Worker(url)
            local.rng = np.random.default_rng(i)
        try:
            s, dt, appended = _ingest_once(w, pool_body, state,
                                           label_frac, rows, local.rng)
        except OSError:
            s, dt, appended = -1, None, 0
        with lock:
            statuses.append(s)
            appended_total[0] += appended
            if dt is not None and s == 200:
                lats.append(dt)

    n = max(1, int(duration_s * qps))
    interval = 1.0 / qps
    t0 = time.perf_counter()
    with concurrent.futures.ThreadPoolExecutor(max_inflight) as pool:
        futures = []
        for i in range(n):
            target = t0 + i * interval
            delay = target - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            futures.append(pool.submit(one, i))
        for f in futures:
            f.result()
    wall = time.perf_counter() - t0
    out = _summarize("ingest_open", statuses, lats, wall, rows,
                     offered_qps=qps)
    out["rows_appended"] = appended_total[0]
    out["ips"] = (round(appended_total[0] / wall, 1) if wall > 0
                  else 0.0)
    out["label_frac"] = label_frac
    out["labels_sent"] = state.labels_sent
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--url", default="http://127.0.0.1:8000")
    ap.add_argument("--mode", default="both",
                    choices=["closed", "open", "both"])
    ap.add_argument("--duration", type=float, default=10.0)
    ap.add_argument("--workers", type=int, default=4,
                    help="closed-loop concurrency")
    ap.add_argument("--rows", type=int, default=8,
                    help="images per request")
    ap.add_argument("--qps", type=float, default=None,
                    help="open-loop offered load (default: 70%% of the "
                         "closed loop's measured qps)")
    ap.add_argument("--endpoint", default="/v1/score",
                    choices=["/v1/score", "/v1/predict"])
    ap.add_argument("--ingest_rows", type=int, default=None,
                    help="switch to ingest mode: rows per POST /v1/pool "
                         "request against a `stream` service")
    ap.add_argument("--label_frac", type=float, default=0.0,
                    help="ingest mode: fraction of requests that attach "
                         "labels (POST /v1/label) to acked ids")
    args = ap.parse_args(argv)

    health = fetch_health(args.url)
    shape = health["image_shape"]
    results = []
    if args.ingest_rows is not None:
        rows = args.ingest_rows
        if args.mode in ("closed", "both"):
            results.append(run_ingest_closed(
                args.url, args.duration, args.workers, rows,
                args.label_frac, shape))
            print(json.dumps(results[-1]), flush=True)
        if args.mode in ("open", "both"):
            qps = args.qps
            if qps is None:
                base = results[0]["qps"] if results else 20.0
                qps = max(1.0, 0.7 * base)
            results.append(run_ingest_open(
                args.url, max(1.0, args.duration / 2), qps, rows,
                args.label_frac, shape))
            print(json.dumps(results[-1]), flush=True)
        return 0
    if args.mode in ("closed", "both"):
        results.append(run_closed(args.url, args.duration, args.workers,
                                  args.rows, shape, args.endpoint))
        print(json.dumps(results[-1]), flush=True)
    if args.mode in ("open", "both"):
        qps = args.qps
        if qps is None:
            # Probe at 70% of the measured ceiling: open-loop latency is
            # only meaningful below saturation.
            base = results[0]["qps"] if results else 20.0
            qps = max(1.0, 0.7 * base)
        results.append(run_open(args.url, args.duration, qps, args.rows,
                                shape, args.endpoint))
        print(json.dumps(results[-1]), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
